"""``mx.serving.Server`` — continuous-batching model server.

The repo trains fast; this is the piece that *serves* (ROADMAP item 1).
One server wraps one hybridized (optionally int8-quantized) Gluon block
and turns concurrent single-sample requests into bucket-padded batches:

* :meth:`Server.submit` is the thread-safe ingress — any thread hands in
  one sample and gets a ``concurrent.futures.Future`` back;
* a scheduler thread drains the queue into dynamic batches under a
  per-request latency SLO: it keeps filling while the oldest queued
  request is comfortably inside its deadline and dispatches early the
  moment it is not (deadline-aware batch close);
* each batch is padded up to the nearest :class:`~.buckets.BucketGrid`
  entry, so every dispatch lands on one warm ``_CachedGraph`` executable
  (``HybridBlock.warmup`` pre-compiles the whole grid at load time);
* per-request outputs are sliced from the real rows and resolved into
  the futures; padded rows never reach a caller.

Resilience reuses the PR-3 runtime: every dispatch runs under
``fault.retry_call`` at site ``serving.dispatch`` (transient failures
retry with backoff; deterministic ones fail the batch's futures, not the
server), and hot reload (``serving.reload``) swaps a freshly-built,
freshly-WARMED model in behind a lock — the old graph serves every
request that arrives while the new one compiles (see
:mod:`mxnet_tpu.serving.reload`).

Telemetry (``MXNET_TELEMETRY=1`` / ``telemetry.enable()``):
``mxnet_serving_queue_depth``, ``mxnet_serving_batch_occupancy``,
``mxnet_serving_time_in_queue_seconds``, ``mxnet_serving_request_seconds``
(p50/p99 from the fine ``SERVING_BUCKETS``), ``mxnet_serving_requests_total``,
``mxnet_serving_batches_total{reason}``, ``mxnet_serving_reloads_total`` —
all exported via ``telemetry.prom_text()``.
"""
from __future__ import annotations

import contextlib
import threading
import time
import weakref
from concurrent.futures import Future
from typing import Optional, Sequence, Tuple

import numpy as np

from .. import autograd, fault, telemetry, tracing
from ..base import MXNetError
from ..fault import _state as _fault_state
from ..telemetry import _state as _telemetry_state
from ..tracing import _state as _tracing_state
from .buckets import DEFAULT_LEN_BUCKETS, BucketGrid
from .health import Heartbeat
from .kvcache import CacheFull, PagePool

__all__ = ["Server", "GenerateHandle", "live_servers"]

# every running server, for the test-suite leak guard: a test that leaves
# a scheduler (or watcher) thread running would tax every later test
_live_servers = weakref.WeakSet()


def live_servers():
    """Servers whose scheduler thread is currently running."""
    return [s for s in list(_live_servers) if s.is_running]


class _Request:
    __slots__ = ("sample", "shape_key", "future", "t_enqueue", "deadline",
                 "trace", "span", "own_trace")

    def __init__(self, sample, shape_key, deadline_s):
        self.sample = sample
        self.shape_key = shape_key
        self.future = Future()
        self.t_enqueue = time.perf_counter()
        self.deadline = self.t_enqueue + deadline_s
        # tracing (MXNET_TRACING=1): the request's Trace, its live
        # batch.wait span, and whether THIS server minted the trace
        # (a router/worker that handed it in finishes it instead)
        self.trace = None
        self.span = None
        self.own_trace = False


class GenerateHandle:
    """Streaming handle for one autoregressive generate request.

    ``future`` resolves to the full int32 token array when the
    completion finishes (or raises the typed failure — ``CacheFull``,
    ``WorkerCrashed``, ``MXNetError`` — exactly like ``submit``'s
    future: a generate NEVER wedges). Tokens stream as they are
    decoded: ``on_token(index, token)`` fires per token (from the
    scheduler/reader thread — keep it cheap), ``tokens()`` snapshots
    what has arrived, and ``next_token(i)`` blocks until token ``i``
    exists or the stream ends (returns None when it ended first).
    """

    def __init__(self, on_token=None):
        self.future = Future()
        self._on_token = on_token
        self._cond = threading.Condition()
        self._tokens: list = []

    def _push(self, token: int) -> None:
        with self._cond:
            self._tokens.append(int(token))
            i = len(self._tokens) - 1
            self._cond.notify_all()
        cb = self._on_token
        if cb is not None:
            try:
                cb(i, int(token))
            except Exception:   # noqa: BLE001 - user callback stays user's
                pass

    def _seal(self) -> None:
        """Wake every next_token() waiter once the future resolved."""
        with self._cond:
            self._cond.notify_all()

    def tokens(self) -> list:
        with self._cond:
            return list(self._tokens)

    def next_token(self, i: int, timeout: Optional[float] = None):
        """Block until token ``i`` streams in; None when the request
        finished (or failed — check ``future``) before producing it."""
        deadline = (time.perf_counter() + timeout
                    if timeout is not None else None)
        with self._cond:
            while len(self._tokens) <= i:
                if self.future.done():
                    return None
                wait = 0.05 if deadline is None \
                    else min(0.05, deadline - time.perf_counter())
                if wait <= 0:
                    return None
                self._cond.wait(wait)
            return self._tokens[i]

    def result(self, timeout: Optional[float] = None):
        return self.future.result(timeout)


class _GenRequest:
    __slots__ = ("prompt", "max_new", "handle", "pages", "length",
                 "generated", "t_submit", "t_last", "deadline", "trace",
                 "span", "own_trace", "len_bucket", "model_version")

    def __init__(self, prompt, max_new, handle, deadline_s):
        self.prompt = prompt                 # 1-D int32 token array
        self.max_new = int(max_new)
        self.handle = handle
        self.pages = None                    # page list once admitted
        self.length = len(prompt)            # tokens written OR known
        self.generated: list = []
        self.t_submit = time.perf_counter()
        self.t_last = self.t_submit          # last token emit (per-token lat)
        self.deadline = (self.t_submit + deadline_s
                         if deadline_s is not None else None)
        self.trace = None
        self.span = None                     # live gen.queue / phase span
        self.own_trace = False
        self.len_bucket = 0
        self.model_version = -1


class Server:
    """Serve a Gluon block under a latency SLO with bucketed batching.

    ::

        net.hybridize()
        srv = mx.serving.Server(net, batch_buckets=(1, 4, 16, 32),
                                shape_buckets=[(3, 224, 224)], slo_ms=50)
        srv.start()                       # warms every grid bucket
        fut = srv.submit(image)           # any thread; one sample, no
        probs = fut.result()              # batch dim; numpy out
        srv.stop()                        # drains in-flight requests

    ``block``: the model. A ``HybridBlock`` is hybridized (if it is not
    already) and every grid bucket is AOT-warmed at :meth:`start`; a
    plain ``Block`` serves eagerly (no warmup — useful for tests).

    ``slo_ms`` is the per-request latency objective: a request's batch
    closes no later than ``slo_ms - close_margin_ms`` after its submit,
    however empty the batch is; under load batches close early on
    ``full``. ``deadline_ms=`` at submit overrides per request.

    ``batch_timeout_ms`` caps how long the OLDEST queued request waits
    for co-batching before its batch closes anyway (the TF-Serving
    ``batch_timeout`` knob). ``None`` (default) keeps the legacy
    deadline-keyed patience: the scheduler fills toward the biggest
    bucket until ``deadline - close_margin``. That patience is optimal
    when arrivals come in tight waves (an in-process closed loop
    refills atomically), but an arrival stream SPREAD by a pipeline —
    results trickling back over a socket, clients refilling one by one
    — never quite fills the bucket, so every batch closes at the SLO
    edge and p50 ~= SLO however light the load (measured: 100% of
    worker batches ``deadline``-closed through the ingress). A few ms
    here trades a few points of occupancy for an SLO-independent
    latency floor; out-of-process workers default it on
    (``serving.RemoteReplica(batch_timeout_ms=5)``).

    ``dtype``: samples are cast to it on submit. Futures resolve with
    numpy arrays (or the model's output structure with numpy leaves).
    """

    def __init__(self, block, batch_buckets=(1, 2, 4, 8, 16, 32),
                 shape_buckets=None, slo_ms: float = 100.0,
                 close_margin_ms: float = 5.0, max_queue: int = 4096,
                 dtype: str = "float32", ctx=None, warmup: bool = True,
                 name: Optional[str] = None,
                 batch_timeout_ms: Optional[float] = None,
                 decode_pages: Optional[int] = None, page_size: int = 16,
                 len_buckets=None,
                 max_generate_tokens: Optional[int] = None):
        if slo_ms <= 0:
            raise MXNetError(f"slo_ms must be > 0, got {slo_ms}")
        if close_margin_ms < 0 or close_margin_ms >= slo_ms:
            raise MXNetError(
                f"close_margin_ms must be in [0, slo_ms), got "
                f"{close_margin_ms} (slo_ms={slo_ms})")
        if batch_timeout_ms is not None and batch_timeout_ms <= 0:
            raise MXNetError(
                f"batch_timeout_ms must be > 0 (or None for the "
                f"deadline-keyed close), got {batch_timeout_ms}")
        if max_queue < 1:
            raise MXNetError(f"max_queue must be >= 1, got {max_queue}")
        # autoregressive decode: a page pool + a model-provided decode
        # engine turn on submit_generate (see _decode_tick)
        self._decode_pages = decode_pages
        if decode_pages is not None and len_buckets is None:
            len_buckets = DEFAULT_LEN_BUCKETS
        self.grid = BucketGrid(batch_buckets, shape_buckets,
                               len_buckets=len_buckets)
        self._page_size = int(page_size)
        if decode_pages is not None:
            cap = (int(decode_pages) - 1) * self._page_size
            self._max_gen_tokens = int(
                max_generate_tokens if max_generate_tokens is not None
                else min(cap, self.grid.len_buckets[-1] + 256))
            if self._max_gen_tokens > cap:
                raise MXNetError(
                    f"max_generate_tokens={self._max_gen_tokens} exceeds "
                    f"the pool's {cap}-token capacity "
                    f"({decode_pages} pages x {page_size}, scratch "
                    "page excluded)")
        self._pool: Optional[PagePool] = None
        self._engine = None
        self._engine_version = -1
        self._gen_table_w = 0
        self._gen_pending: list = []
        self._gen_active: list = []
        self.n_tokens = 0
        self.slo_s = slo_ms / 1e3
        self.margin_s = close_margin_ms / 1e3
        self.batch_timeout_s = (batch_timeout_ms / 1e3
                                if batch_timeout_ms is not None else None)
        self.max_queue = int(max_queue)
        self.dtype = dtype
        self.ctx = ctx
        self.name = name or f"server_{id(self):x}"
        self._warmup = bool(warmup)
        self._model = block
        self._model_lock = threading.Lock()
        self._cond = threading.Condition()
        self._queue: list = []
        self._drain = True
        self._running = False
        self._thread: Optional[threading.Thread] = None
        self._watcher = None        # reload.ReloadWatcher, when enabled
        # pre-dispatch hook, set by serving.Router on managed replicas:
        # runs INSIDE run() (the retried dispatch body) so an injected
        # replica fault / latency lands exactly where a real replica
        # failure would — in this scheduler thread, per batch
        self._pre_dispatch = None
        # scheduler-loop liveness beacon: touched once per loop
        # iteration (so between two touches at most ONE dispatch runs).
        # A Router reads it to tell a *hung* dispatch from a scheduler
        # patiently filling a batch toward its deadline close.
        self.hb = Heartbeat()
        self.loaded_step: Optional[int] = None
        # monotonic model-version counter: bumps on every swap_model /
        # reload; a rolling-upgrade rollback restores the OLD number so
        # fleet version agreement is observable (Router/controller read
        # it, never write it)
        self.model_version = 0
        # signatures actually compiled/used — the reload warmup manifest
        self._warm_sigs = set()
        # always-on light counters (telemetry covers the full story)
        self.n_requests = 0
        self.n_batches = 0
        self.n_errors = 0
        self.n_reloads = 0

    # -- lifecycle -----------------------------------------------------
    @property
    def is_running(self) -> bool:
        return self._running or (self._thread is not None
                                 and self._thread.is_alive())

    def start(self) -> "Server":
        """Warm the bucket grid and start the scheduler thread."""
        if self.is_running:
            raise MXNetError(f"{self.name}: already running")
        self._warm_block(self._model, prime=True)
        if self._decode_pages is not None:
            if not hasattr(self._model, "decode_engine"):
                raise MXNetError(
                    f"{self.name}: decode_pages set but the model has no "
                    "decode_engine() seam (paged-KV generate needs a "
                    "decode-capable model)")
            self._pool = PagePool(self._decode_pages, self._page_size)
            # the engine dtype is the KV/compute dtype, not the request
            # I/O dtype: token servers run dtype="int32" but the cache
            # must hold floats (bf16/f32 servers keep their precision)
            eng_dt = (self.dtype
                      if np.issubdtype(np.dtype(self.dtype), np.floating)
                      else "float32")
            self._engine = self._model.decode_engine(self._pool,
                                                     dtype=eng_dt)
            self._engine_version = self.model_version
            self._gen_table_w = self._pool.pages_for(self._max_gen_tokens)
        self._running = True
        self._thread = threading.Thread(
            target=self._scheduler_loop, name=self.name, daemon=True)
        self._thread.start()
        _live_servers.add(self)
        return self

    def stop(self, drain: bool = True, timeout: Optional[float] = None
             ) -> None:
        """Stop the server. ``drain=True`` (default) serves every queued
        request first (dispatching immediately, SLO waits skipped);
        ``drain=False`` fails pending futures with :class:`MXNetError`."""
        with self._cond:
            self._running = False
            self._drain = bool(drain)
            if not drain:
                pending, self._queue = self._queue, []
                for r in pending:
                    if not r.future.set_running_or_notify_cancel():
                        continue        # caller already cancelled it
                    r.future.set_exception(
                        MXNetError(f"{self.name}: server stopped before "
                                   "this request was dispatched"))
                    self._count_request(outcome="rejected")
                    self._end_trace_rejected(r)
            self._cond.notify_all()
        if self._watcher is not None:
            self._watcher.stop(timeout)
            self._watcher = None
        if self._thread is not None:
            self._thread.join(timeout)
            if self._thread.is_alive():
                raise MXNetError(
                    f"{self.name}: scheduler thread did not exit within "
                    f"{timeout}s")
            self._thread = None
        _live_servers.discard(self)

    def __enter__(self) -> "Server":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop(drain=not any(exc))

    # -- ingress -------------------------------------------------------
    def submit(self, sample, deadline_ms: Optional[float] = None) -> Future:
        """Enqueue one sample (NO batch dimension); returns a Future that
        resolves to the model output for that sample (numpy leaves).
        Thread-safe. Raises :class:`MXNetError` immediately when the
        server is not running, the queue is full, or no shape bucket
        fits the sample — rejection is synchronous, never a hung future.
        """
        arr = sample.asnumpy() if hasattr(sample, "asnumpy") \
            else np.asarray(sample)
        arr = np.ascontiguousarray(arr, dtype=self.dtype)
        bucket = self.grid.bucket_shape(arr.shape)   # raises if none fits
        arr = self.grid.pad_sample(arr, bucket)
        deadline_s = (deadline_ms / 1e3 if deadline_ms is not None
                      else self.slo_s)
        req = _Request(arr, bucket, deadline_s)
        if _tracing_state.enabled:
            # the span must exist BEFORE the queue append: the scheduler
            # may batch-close this request before submit returns
            amb = tracing.ambient()
            if amb is not None:
                req.trace = amb[0]
                req.span = req.trace.begin(
                    "batch.wait", parent=amb[1], replica=self.name)
            else:
                req.trace = tracing.new_trace("request", replica=self.name)
                req.own_trace = True
                req.span = req.trace.begin("batch.wait", replica=self.name)
        with self._cond:
            if not self._running:
                self._count_request(outcome="rejected")
                self._end_trace_rejected(req)
                raise MXNetError(f"{self.name}: server is not running")
            if len(self._queue) >= self.max_queue:
                self._count_request(outcome="rejected")
                self._end_trace_rejected(req)
                raise MXNetError(
                    f"{self.name}: submission queue full "
                    f"({self.max_queue} requests)")
            self._queue.append(req)
            depth = len(self._queue)
            self._cond.notify_all()
        if _telemetry_state.enabled:
            telemetry.set_serving_queue_depth(depth)
        return req.future

    def submit_generate(self, prompt, max_new_tokens: int,
                        deadline_ms: Optional[float] = None,
                        on_token=None) -> GenerateHandle:
        """Enqueue one autoregressive generate request: ``prompt`` is a
        1-D int32 token array, ``max_new_tokens`` the completion budget
        (greedy decode). Returns a :class:`GenerateHandle` streaming
        tokens as the continuous batcher produces them.

        Rejection is synchronous and typed, like :meth:`submit`:
        :class:`~.kvcache.CacheFull` when the request cannot EVER fit
        the cache budget, :class:`MXNetError` when no len bucket fits
        the prompt or the server is not running. A request admitted but
        later starved (deadline blown waiting for pages) fails its
        future typed — a generate never wedges on an exhausted arena.

        ``deadline_ms`` bounds the WHOLE completion (default: none —
        generates outlive the per-request SLO by design).
        """
        if self._decode_pages is None:
            raise MXNetError(f"{self.name}: decode is not enabled "
                             "(construct the server with decode_pages=)")
        arr = prompt.asnumpy() if hasattr(prompt, "asnumpy") \
            else np.asarray(prompt)
        arr = np.ascontiguousarray(arr, dtype=np.int32).reshape(-1)
        if arr.size < 1:
            raise MXNetError(f"{self.name}: empty prompt")
        if int(max_new_tokens) < 1:
            raise MXNetError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        len_bucket = self.grid.prefill_bucket(arr.size)  # raises: no fit
        total = arr.size + int(max_new_tokens)
        if total > self._max_gen_tokens:
            if _telemetry_state.enabled:
                telemetry.record_serving_shed("kvcache_full")
            raise CacheFull(
                f"{self.name}: prompt {arr.size} + max_new_tokens "
                f"{max_new_tokens} exceeds the {self._max_gen_tokens}-"
                "token per-request cache budget")
        handle = GenerateHandle(on_token)
        req = _GenRequest(arr, max_new_tokens, handle,
                          deadline_ms / 1e3 if deadline_ms is not None
                          else None)
        req.len_bucket = len_bucket
        if _tracing_state.enabled:
            amb = tracing.ambient()
            if amb is not None:
                req.trace = amb[0]
                req.span = req.trace.begin("gen.queue", parent=amb[1],
                                           replica=self.name)
            else:
                req.trace = tracing.new_trace(
                    "generate", replica=self.name,
                    prompt_len=int(arr.size),
                    max_new=int(max_new_tokens))
                req.own_trace = True
                req.span = req.trace.begin("gen.queue", replica=self.name)
        with self._cond:
            if not self._running:
                self._count_request(outcome="rejected")
                self._end_gen_rejected(req)
                raise MXNetError(f"{self.name}: server is not running")
            if len(self._gen_pending) >= self.max_queue:
                self._count_request(outcome="rejected")
                self._end_gen_rejected(req)
                raise MXNetError(
                    f"{self.name}: generate queue full "
                    f"({self.max_queue} requests)")
            self._gen_pending.append(req)
            self._cond.notify_all()
        return handle

    @staticmethod
    def _end_gen_rejected(req: "_GenRequest",
                          status: str = "rejected") -> None:
        if req.trace is None:
            return
        if req.span is not None:
            req.span.end(outcome=status)
            req.span = None
        if req.own_trace:
            req.trace.finish(status)

    # -- decode phase (continuous batching) ----------------------------
    def _decode_tick(self) -> bool:
        """One continuous-batching turn: admit pending generates
        (prefill), then run ONE decode step for every active request.
        Requests join and leave the decode batch at any step boundary.
        Returns False when nothing could move (scheduler backs off)."""
        progressed = False
        now = time.perf_counter()
        with self._cond:
            active = list(self._gen_active)
            pending = list(self._gen_pending)
        # deferred weight swap: a completion runs entirely on ONE model
        # version, so a hot reload only reaches the decode engine
        # between completions — never mid-request
        if not active and self._engine_version != self.model_version:
            self._engine.refresh_params(self._model)
            self._engine_version = self.model_version
        # -- admission: all-or-nothing page allocation per request
        admitted = []
        for g in pending:
            if g.deadline is not None and now > g.deadline:
                self._remove_pending(g)
                self._finalize_gen(g, error=MXNetError(
                    f"{self.name}: generate deadline expired before "
                    "prefill (cache/backlog starvation)"))
                progressed = True
                continue
            if len(admitted) >= self.grid.max_batch:
                break
            try:
                g.pages = self._pool.alloc(g, g.length + g.max_new)
            except CacheFull as e:
                if not active and not admitted:
                    # nothing holds pages and it STILL does not fit:
                    # waiting cannot help — shed typed, never wedge
                    if _telemetry_state.enabled:
                        telemetry.record_serving_shed("kvcache_full")
                    self._remove_pending(g)
                    self._finalize_gen(g, error=e)
                    progressed = True
                    continue
                break       # actives will free pages; retry next tick
            self._remove_pending(g)
            admitted.append(g)
        if admitted:
            groups: dict = {}
            for g in admitted:
                groups.setdefault(g.len_bucket, []).append(g)
            for lb in sorted(groups):
                self._prefill_batch(groups[lb], lb)
            progressed = True
        # -- one decode step per active request (chunked to the grid)
        with self._cond:
            active = list(self._gen_active)
        expired = [g for g in active
                   if g.deadline is not None and now > g.deadline]
        for g in expired:
            self._finalize_gen(g, error=MXNetError(
                f"{self.name}: generate deadline expired at token "
                f"{len(g.generated)}/{g.max_new}"))
        active = [g for g in active if g not in expired]
        cap = self.grid.max_batch
        for i in range(0, len(active), cap):
            self._decode_batch(active[i:i + cap])
        return progressed or bool(active) or bool(expired)

    def _remove_pending(self, g) -> None:
        with self._cond:
            try:
                self._gen_pending.remove(g)
            except ValueError:
                pass

    def _prefill_batch(self, group, len_bucket: int) -> None:
        """Prefill one len-bucket group: write the prompts' K/V into
        their pages and emit each request's FIRST token (the
        time-to-first-token dispatch)."""
        cap = self.grid.batch_bucket(len(group))
        w = self._gen_table_w
        tokens = np.zeros((cap, len_bucket), dtype=np.int32)
        lengths = np.zeros((cap,), dtype=np.int32)
        table = np.zeros((cap, w), dtype=np.int32)
        for i, g in enumerate(group):
            tokens[i, :g.prompt.size] = g.prompt
            lengths[i] = g.prompt.size
            table[i, :len(g.pages)] = g.pages
            g.model_version = self._engine_version
            if g.span is not None:          # gen.queue ends here
                g.span.end(outcome="ok")
            g.span = (g.trace.begin("prefill", replica=self.name,
                                    len_bucket=len_bucket)
                      if g.trace is not None else None)
        sig = (cap, len_bucket)

        def run():
            hook = self._pre_dispatch
            if hook is not None:
                hook(sig)
            if _fault_state.enabled:
                fault.check("serving.dispatch",
                            f"{self.name} prefill={sig}")
            return self._engine.prefill(tokens, lengths, table)

        try:
            logits = fault.retry_call("serving.dispatch", run,
                                      detail=self.name)
        except Exception as e:  # noqa: BLE001 - forwarded to handles
            self.n_errors += 1
            for g in group:
                self._finalize_gen(g, error=e)
            return
        self.n_batches += 1
        if _telemetry_state.enabled:
            telemetry.record_serving_batch(len(group), cap, "prefill")
        with self._cond:
            self._gen_active.extend(group)
        t_now = time.perf_counter()
        for i, g in enumerate(group):
            if g.span is not None:
                g.span.end(outcome="ok")
                g.span = None
            self._emit_token(g, int(np.argmax(logits[i])), t_now)

    def _decode_batch(self, chunk) -> None:
        """ONE decode step for up to max_batch active requests — the
        (batch, 1) executable, whatever depth each request is at."""
        cap = self.grid.batch_bucket(len(chunk))
        w = self._gen_table_w
        tokens = np.zeros((cap,), dtype=np.int32)
        lengths = np.zeros((cap,), dtype=np.int32)
        table = np.zeros((cap, w), dtype=np.int32)
        spans = []
        for i, g in enumerate(chunk):
            tokens[i] = g.generated[-1]
            lengths[i] = g.length
            table[i, :len(g.pages)] = g.pages
            spans.append(g.trace.begin("decode.step", replica=self.name,
                                       token=len(g.generated))
                         if g.trace is not None else None)
        sig = (cap, 1)

        def run():
            hook = self._pre_dispatch
            if hook is not None:
                hook(sig)
            if _fault_state.enabled:
                fault.check("serving.dispatch", f"{self.name} decode={sig}")
            return self._engine.decode_step(tokens, lengths, table)

        try:
            logits = fault.retry_call("serving.dispatch", run,
                                      detail=self.name)
        except Exception as e:  # noqa: BLE001 - forwarded to handles
            self.n_errors += 1
            for g, sp in zip(chunk, spans):
                if sp is not None:
                    sp.end(outcome="error", error=type(e).__name__)
            for g in chunk:
                self._finalize_gen(g, error=e)
            return
        if _telemetry_state.enabled:
            telemetry.record_decode_step(len(chunk))
        t_now = time.perf_counter()
        for i, (g, sp) in enumerate(zip(chunk, spans)):
            if sp is not None:
                sp.end(outcome="ok")
            self._emit_token(g, int(np.argmax(logits[i])), t_now)

    def _emit_token(self, g, token: int, t_now: float) -> None:
        g.generated.append(token)
        g.length += 1
        self.n_tokens += 1
        if _telemetry_state.enabled:
            telemetry.record_token(t_now - g.t_last)
        g.t_last = t_now
        g.handle._push(token)
        if len(g.generated) >= g.max_new:
            self._finalize_gen(g)

    def _finalize_gen(self, g, error: Optional[Exception] = None) -> None:
        """Resolve one generate request: free its pages, leave the
        batch, settle the future (exactly once) and seal the stream."""
        if g.pages is not None:
            self._pool.free(g)
            g.pages = None
        with self._cond:
            try:
                self._gen_active.remove(g)
            except ValueError:
                pass
        fut = g.handle.future
        try:
            if error is None:
                fut.set_result(np.asarray(g.generated, dtype=np.int32))
            else:
                fut.set_exception(error)
        except Exception:   # noqa: BLE001 - already settled (racing stop)
            pass
        g.handle._seal()
        if error is not None:
            self.n_errors += 1
        self._count_request(
            outcome="ok" if error is None else "error",
            t_enqueue=g.t_submit,
            trace_id=g.trace.trace_id if g.trace is not None else None)
        if g.span is not None:
            g.span.end(outcome="ok" if error is None else "error")
            g.span = None
        if g.own_trace and g.trace is not None:
            g.trace.finish("ok" if error is None
                           else type(error).__name__)

    def _fail_generates(self, exc: Exception) -> None:
        with self._cond:
            doomed = self._gen_pending + self._gen_active
            self._gen_pending = []
        for g in doomed:
            self._finalize_gen(g, error=exc)

    # -- scheduler -----------------------------------------------------
    def _scheduler_loop(self) -> None:
        try:
            while True:
                self.hb.touch()
                batch, reason = self._next_batch()
                if batch is None:
                    # non-drain shutdown may leave generates behind
                    self._fail_generates(MXNetError(
                        f"{self.name}: server stopped before this "
                        "generate completed"))
                    return
                if batch:
                    self._dispatch(batch, reason)
                if self._gen_pending or self._gen_active:
                    if not self._decode_tick():
                        # nothing admissible this instant (pool full,
                        # actives still hold pages): breathe, retry
                        with self._cond:
                            self._cond.wait(0.005)
        except BaseException:
            # a scheduler death must be LOUD, not a server that accepts
            # requests into a queue nobody drains: stop accepting and
            # fail everything queued
            with self._cond:
                self._running = False
                pending, self._queue = self._queue, []
            for r in pending:
                if r.future.set_running_or_notify_cancel():
                    r.future.set_exception(MXNetError(
                        f"{self.name}: scheduler thread crashed"))
                    self._end_trace_rejected(r, "error")
            self._fail_generates(MXNetError(
                f"{self.name}: scheduler thread crashed"))
            raise

    def _next_batch(self):
        """Block until a batch should close; returns (requests, reason),
        ``([], "decode")`` when decode work should run NOW (continuous
        batching never parks the scheduler while generates are live),
        or (None, None) on shutdown with nothing left to serve."""
        with self._cond:
            while True:
                self.hb.touch()
                gen_work = bool(self._gen_pending or self._gen_active)
                if not self._queue:
                    if not self._running:
                        if gen_work and self._drain:
                            return [], "decode"
                        return None, None
                    if gen_work:
                        return [], "decode"
                    self._cond.wait(0.1)
                    continue
                head = self._queue[0]
                key = head.shape_key
                cap = self.grid.max_batch
                matching = sum(1 for r in self._queue
                               if r.shape_key == key)
                now = time.perf_counter()
                # close on the TIGHTEST deadline in the queue, not just
                # the head's: a short-deadline request behind a lazy head
                # (same key: it rides this batch; different key: it is
                # served right after) must not wait out the head's SLO
                deadline_at = min(r.deadline for r in self._queue) \
                    - self.margin_s
                # batch timeout: the head is the oldest enqueue (submit
                # order is FIFO even when deadline_ms overrides are not)
                # — cap its co-batching wait independently of the SLO
                timeout_at = (head.t_enqueue + self.batch_timeout_s
                              if self.batch_timeout_s is not None
                              else None)
                close_at = deadline_at if timeout_at is None \
                    else min(deadline_at, timeout_at)
                if matching >= cap:
                    reason = "full"
                elif not self._running:
                    reason = "drain"
                elif now >= close_at:
                    reason = ("timeout" if timeout_at is not None
                              and timeout_at <= close_at + 1e-9
                              and now < deadline_at else "deadline")
                else:
                    if gen_work:
                        # decode steps interleave with the batch fill:
                        # the classic batch keeps its SLO patience, the
                        # scheduler just doesn't SLEEP through it
                        return [], "decode"
                    # fill otherwise: sleep until the head's close time
                    # or the next submit, whichever is first
                    self._cond.wait(min(close_at - now, 0.1))
                    continue
                taken, rest = [], []
                for r in self._queue:
                    if len(taken) < cap and r.shape_key == key:
                        taken.append(r)
                    else:
                        rest.append(r)
                self._queue = rest
                if _telemetry_state.enabled:
                    telemetry.set_serving_queue_depth(len(rest))
                return taken, reason

    def _dispatch(self, batch, reason: str) -> None:
        """Pad, run, slice, resolve — one bucketed inference dispatch."""
        from ..ndarray import array as nd_array

        t_start = time.perf_counter()
        # a caller may have cancelled a still-queued future; drop those
        # rows now — set_result on a cancelled future would raise and
        # kill the scheduler thread
        batch = [r for r in batch
                 if r.future.set_running_or_notify_cancel()]
        if not batch:
            return
        n = len(batch)
        key = batch[0].shape_key
        cap = self.grid.batch_bucket(n)
        payload = np.zeros((cap,) + key, dtype=self.dtype)
        for i, r in enumerate(batch):
            payload[i] = r.sample
        model = self._model          # reload swaps the attribute, not us
        sig = (cap,) + key

        bsp = None
        if _tracing_state.enabled:
            traced = [(r.trace, r.span) for r in batch
                      if r.trace is not None]
            if traced:
                # the N co-batched wait spans end here (flow-linked to
                # the ONE dispatch span that serves them all)
                bsp = tracing.begin_batch(
                    traced, wait_tags={"close_reason": reason},
                    replica=self.name, sig=str(sig), reason=reason)

        def run():
            hook = self._pre_dispatch
            if hook is not None:
                hook(sig)
            if _fault_state.enabled:
                fault.check("serving.dispatch", f"{self.name} batch={sig}")
            x = nd_array(payload, ctx=self.ctx)
            with autograd.pause():
                out = model(x)
            return self._materialize(out)

        # injected faults / retries inside the dispatch annotate the
        # batch span (fault.py calls tracing.note against the ambient)
        amb = (tracing.active(batch[0].trace, bsp) if bsp is not None
               else contextlib.nullcontext())
        try:
            with amb:
                leaves, tree = fault.retry_call(
                    "serving.dispatch", run, detail=self.name)
        except Exception as e:  # noqa: BLE001 - forwarded to the futures
            self.n_errors += 1
            tracing.end_batch(bsp, outcome="error",
                              error=type(e).__name__)
            for r in batch:
                r.future.set_exception(e)
                self._count_request(
                    outcome="error", t_enqueue=r.t_enqueue,
                    trace_id=r.trace.trace_id if r.trace is not None
                    else None)
                if r.own_trace:
                    r.trace.finish(type(e).__name__)
            return
        tracing.end_batch(bsp, outcome="ok")
        self.n_batches += 1
        if self.n_batches == 1:
            from .. import compiler

            # replica cold-start milestone: start() -> first served batch
            compiler.mark_event("first_response")
        if _telemetry_state.enabled:
            telemetry.record_serving_batch(n, cap, reason)
            for r in batch:
                telemetry.record_serving_queue_time(t_start - r.t_enqueue)
        with self._model_lock:      # the reload warmup copies this set
            self._warm_sigs.add(sig)
        from ..gluon.block import nested_unflatten_nd

        try:
            for i, r in enumerate(batch):
                # copy: a row VIEW would pin the whole padded batch
                # array for as long as the caller holds the result
                r.future.set_result(nested_unflatten_nd(
                    tree, [leaf[i].copy() for leaf in leaves]))
                self._count_request(
                    outcome="ok", t_enqueue=r.t_enqueue,
                    trace_id=r.trace.trace_id if r.trace is not None
                    else None)
                if r.own_trace:
                    r.trace.finish("ok")
        except Exception as e:  # noqa: BLE001 - e.g. non-batch-major leaf
            self.n_errors += 1
            for r in batch:
                if not r.future.done():
                    r.future.set_exception(e)
                    self._count_request(outcome="error",
                                        t_enqueue=r.t_enqueue)
                if r.own_trace:
                    r.trace.finish(type(e).__name__)

    @staticmethod
    def _materialize(out):
        """Flatten the model output and pull each leaf to host numpy once
        per batch (futures hand out row slices of these)."""
        from ..gluon.block import nested_flatten_nd

        flat, tree = nested_flatten_nd(out)
        return [leaf.asnumpy() for leaf in flat], tree

    def _count_request(self, outcome: str, t_enqueue: Optional[float] = None,
                       trace_id: Optional[str] = None) -> None:
        self.n_requests += 1
        if _telemetry_state.enabled:
            lat = (time.perf_counter() - t_enqueue
                   if t_enqueue is not None else 0.0)
            telemetry.record_serving_request(lat, outcome,
                                             trace_id=trace_id)

    @staticmethod
    def _end_trace_rejected(req: _Request, status: str = "rejected") -> None:
        """Seal a traced request that never reached a batch."""
        if req.trace is None:
            return
        if req.span is not None:
            req.span.end(outcome=status)
        if req.own_trace:
            req.trace.finish(status)

    # -- model management ----------------------------------------------
    def _warm_block(self, block, prime: bool = False) -> int:
        """AOT-compile ``block`` for every known signature: the full
        grid when it is enumerable (``prime=True`` + shape buckets), and
        always every signature this server has actually served — so a
        hot-reloaded model is warm for live traffic before the swap.

        Warm compiles route through the compilation service: a replica
        (or a reloaded model) whose program another in-process replica
        already compiled is an executable-table hit, not a second XLA
        compile — N replicas of one architecture warm for the price of
        one. When a signature manifest is being recorded, its journal is
        replayed against the block first, so signatures served by a
        PREVIOUS process warm too (the manifest may know more than the
        enumerable grid)."""
        if not self._warmup or not hasattr(block, "warmup"):
            return 0
        from .. import compiler

        man = compiler.recorder()
        if man is not None:
            try:
                compiler.warm_start(man, blocks=[block])
            except Exception:   # noqa: BLE001 - warm is best-effort
                pass
        with self._model_lock:      # the scheduler adds sigs concurrently
            sigs = set(self._warm_sigs)
        if prime and self.grid.shape_buckets is not None:
            sigs.update(self.grid.input_signatures())
        if not sigs:
            return 0
        if getattr(block, "_active", None) is False:
            block.hybridize()
        return block.warmup(sorted(sigs), dtype=self.dtype, ctx=self.ctx)

    def current_model(self):
        """The block currently being served (the rolling-upgrade
        machinery keeps it for rollback)."""
        return self._model

    def swap_model(self, block, version: Optional[int] = None) -> None:
        """Atomically replace the served model with ``block``, warming it
        for every signature in live use first — requests dispatched
        during the warmup keep hitting the old graph. ``version``
        overrides the monotonic bump (a rollback restores the old
        number)."""
        self._warm_block(block, prime=True)
        with self._model_lock:
            self._model = block
            self.model_version = (self.model_version + 1
                                  if version is None else int(version))
        self.n_reloads += 1

    def reload(self, manager, model_factory, step: Optional[int] = None
               ) -> int:
        """Zero-downtime reload from a :class:`CheckpointManager` bundle:
        build a fresh block via ``model_factory(bundle_path)``, warm it,
        swap it in. The old graph serves until the swap. Fault site
        ``serving.reload``; transient failures retry, persistent ones
        raise (the old model keeps serving). Returns the loaded step."""
        t0 = time.perf_counter()
        if step is None:
            step = manager.latest_step()
            if step is None:
                raise MXNetError(
                    f"{self.name}: no checksum-valid checkpoint under "
                    f"{manager.directory!r} to reload from")
        path = manager.path(step)

        def build():
            if _fault_state.enabled:
                fault.check("serving.reload", path)
            return model_factory(path)

        try:
            block = fault.retry_call("serving.reload", build, detail=path)
            self.swap_model(block)
        except Exception:
            if _telemetry_state.enabled:
                telemetry.record_serving_reload(0.0, outcome="error")
            raise
        self.loaded_step = step
        if _telemetry_state.enabled:
            telemetry.record_serving_reload(time.perf_counter() - t0)
        return step

    def enable_hot_reload(self, manager, model_factory,
                          interval_s: float = 0.5,
                          tag: Optional[str] = None):
        """Start a watcher thread that polls ``manager`` (via
        :meth:`CheckpointManager.poll_newest`) and hot-reloads on every
        new valid bundle. See :class:`~.reload.ReloadWatcher`."""
        from .reload import ReloadWatcher

        if self._watcher is not None:
            raise MXNetError(f"{self.name}: hot reload already enabled")
        self._watcher = ReloadWatcher(
            self, manager, model_factory, interval_s=interval_s,
            tag=tag or self.name)
        self._watcher.start()
        return self._watcher

    def stats(self) -> dict:
        """Light always-on counters (telemetry has the full story)."""
        with self._cond:
            depth = len(self._queue)
            gen_pending = len(self._gen_pending)
            gen_active = len(self._gen_active)
        out = {"requests": self.n_requests, "batches": self.n_batches,
               "errors": self.n_errors, "reloads": self.n_reloads,
               "queue_depth": depth, "loaded_step": self.loaded_step,
               "model_version": self.model_version,
               "running": self.is_running}
        if self._decode_pages is not None:
            out.update(tokens=self.n_tokens, generates_pending=gen_pending,
                       generates_active=gen_active,
                       kvcache=self._pool.stats() if self._pool else None)
        return out
