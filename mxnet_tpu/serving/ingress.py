"""``mx.serving.Ingress`` — socket ingress in front of the Router.

The network edge of the serving stack (ROADMAP item 1's "network
ingress in front of Router"): stdlib-only connection handling that
turns :mod:`.wire` ``submit`` frames from remote clients into
:meth:`Router.submit` calls and streams ``result`` frames back. Three
properties it guarantees:

* **Backpressure is synchronous and typed, never a dropped
  connection.** Each connection has a bounded in-flight window
  (``window`` submits outstanding); a submit past it is answered with
  an ``overloaded`` error frame IMMEDIATELY — and the Router's own
  admission control (:class:`~.router.ServerOverloaded` at submit,
  queue-full, predicted-wait, deadline expiry) and failover exhaustion
  (:class:`~.router.FailoverExhausted`) map onto the same typed error
  frames. A client always learns WHY, at submit time, instead of
  timing out against a silently shed request.

* **A bad client costs one connection.** A torn or corrupt frame
  (:class:`~.wire.FrameError`) closes that connection; in-flight
  requests already forwarded keep resolving at the Router (their
  result frames are dropped — the socket is gone, the futures are
  not). The accept loop, the Router, and every other connection are
  untouched.

* **Every accepted request resolves.** The per-request done-callbacks
  ride the Router's zero-lost-future invariant; a result that cannot
  be written back (client went away) is discarded, never blocks the
  replica that produced it.

Fault site ``serving.ingress`` fires per handled frame: an injected
fault resolves THAT request with a typed error frame (counted as
``rejected{reason="fault"}``) — chaos runs exercise the edge without
touching the fleet.

Telemetry: ``mxnet_ingress_connections{state}`` (``open`` = currently
connected, ``busy`` = with >= 1 in-flight request),
``mxnet_ingress_rejected_total{reason}`` (``window_full`` /
``overloaded`` / ``failover_exhausted`` / ``bad_frame`` / ``fault`` /
``error``), ``mxnet_ingress_requests_total{outcome}`` +
``mxnet_ingress_request_seconds``.

:class:`IngressClient` is the matching stdlib client: ``submit() ->
Future`` over one connection, error frames reconstructed into the
SAME typed exceptions the in-process Router raises — code written
against ``Router.submit`` ports to the socket edge unchanged.
"""
from __future__ import annotations

import logging
import socket
import threading
import time
import weakref
from concurrent.futures import Future
from typing import Optional

import numpy as np

from .. import fault, telemetry, tracing
from ..base import MXNetError
from ..fault import _state as _fault_state
from ..telemetry import _state as _telemetry_state
from ..tracing import _state as _tracing_state
from . import wire

__all__ = ["Ingress", "IngressClient", "IngressDisconnected",
           "live_ingresses"]

_log = logging.getLogger(__name__)

# every running ingress, for the test-suite leak guard (a leaked bound
# socket + accept thread would tax every later test)
_live_ingresses = weakref.WeakSet()


def live_ingresses():
    """Ingresses whose accept loop is currently running."""
    return [i for i in list(_live_ingresses) if i.is_running]


class IngressDisconnected(MXNetError):
    """The ingress connection dropped with this request in flight. The
    client-side analogue of :class:`~.remote.WorkerCrashed`: typed and
    immediate, never a hung future."""


class _Conn:
    """One accepted connection: socket, coalescing writer, bounded
    window."""

    __slots__ = ("sock", "addr", "writer", "lock", "inflight",
                 "closed")

    def __init__(self, sock, addr):
        self.sock = sock
        self.addr = addr
        # coalescing write side: result frames stream back-to-back
        # under load, and the router's done-callbacks must never block
        # on a slow client's socket (see wire.FrameWriter)
        self.writer = wire.FrameWriter(sock, name="ingress-conn-writer")
        self.lock = threading.Lock()
        self.inflight = 0
        self.closed = False

    def send(self, frame) -> bool:
        """Best-effort framed send; False once the socket is gone (a
        result for a departed client is discarded, not an error)."""
        if self.closed:
            return False
        try:
            self.writer.send(frame)
            return True
        except (OSError, wire.FrameError):
            self.closed = True
            return False

    def close(self):
        self.closed = True
        self.writer.close(flush=True, timeout=1.0)
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass


class Ingress:
    """Serve a :class:`~.router.Router` (or a single ``Server`` — same
    submit contract) over TCP.

    ::

        router = serving.Router(replicas, slo_ms=50).start()
        ing = serving.Ingress(router, port=0, window=64).start()
        ... serving.IngressClient("127.0.0.1", ing.port) ...
        ing.stop(); router.stop()

    ``window`` bounds per-connection in-flight submits (typed
    ``overloaded`` frame past it — the backpressure contract);
    ``max_connections`` bounds handler threads (excess accepts are
    closed immediately). The ingress OWNS neither the router nor its
    replicas — stopping it closes the edge, the fleet keeps serving.
    """

    def __init__(self, router, host: str = "127.0.0.1", port: int = 0,
                 window: int = 64, max_connections: int = 256,
                 name: Optional[str] = None):
        if window < 1:
            raise MXNetError(f"window must be >= 1, got {window}")
        if max_connections < 1:
            raise MXNetError(
                f"max_connections must be >= 1, got {max_connections}")
        self.router = router
        self.host = host
        self.request_port = int(port)
        self.window = int(window)
        self.max_connections = int(max_connections)
        self.name = name or f"ingress_{id(self):x}"
        self.port: Optional[int] = None
        self._listener: Optional[socket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None
        self._conns: set = set()
        self._conns_lock = threading.Lock()
        self._running = False
        self._gauges_next = 0.0     # next conn-gauge scan (rate limit)
        # light counters (telemetry has the labeled story)
        self.n_accepted = 0
        self.n_requests = 0
        self.n_rejected = 0

    # -- lifecycle -----------------------------------------------------
    @property
    def is_running(self) -> bool:
        t = self._accept_thread
        return self._running and t is not None and t.is_alive()

    def start(self) -> "Ingress":
        if self.is_running:
            raise MXNetError(f"{self.name}: already running")
        listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        listener.bind((self.host, self.request_port))
        listener.listen(128)
        # a blocking accept() does not reliably wake when another
        # thread closes the socket — poll so stop() is bounded
        listener.settimeout(0.25)
        self._listener = listener
        self.port = listener.getsockname()[1]
        self._running = True
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name=self.name, daemon=True)
        self._accept_thread.start()
        _live_ingresses.add(self)
        self._publish_conn_gauges(force=True)
        return self

    def stop(self, timeout: Optional[float] = None) -> None:
        """Close the edge: stop accepting, drop every connection (their
        in-flight requests keep resolving at the router; the result
        frames are discarded). The router keeps serving."""
        self._running = False
        listener, self._listener = self._listener, None
        if listener is not None:
            try:
                listener.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass        # not connected on this platform: the
            try:            # accept poll timeout bounds the exit
                listener.close()
            except OSError:
                pass
        with self._conns_lock:
            conns = list(self._conns)
        for c in conns:
            c.close()
        t = self._accept_thread
        if t is not None:
            t.join(timeout if timeout is not None else 10.0)
            if t.is_alive():
                raise MXNetError(
                    f"{self.name}: accept thread did not exit")
        self._accept_thread = None
        _live_ingresses.discard(self)
        self._publish_conn_gauges(force=True)

    def __enter__(self) -> "Ingress":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- accept / per-connection handling ------------------------------
    def _accept_loop(self) -> None:
        listener = self._listener
        while self._running and listener is not None:
            try:
                sock, addr = listener.accept()
            except socket.timeout:
                continue            # poll tick: re-check _running
            except OSError:
                return              # listener closed by stop()
            with self._conns_lock:
                full = len(self._conns) >= self.max_connections
            if full:
                # the connection cap is load shedding too: refuse with
                # a typed frame, then close — not a silent RST
                try:
                    wire.send_frame(sock, {
                        "kind": "result", "id": None, "ok": False,
                        "etype": "overloaded",
                        "error": f"{self.name}: connection limit "
                                 f"({self.max_connections}) reached"})
                except (OSError, wire.FrameError):
                    pass
                try:
                    sock.close()
                except OSError:
                    pass
                self._count_rejected("connection_limit")
                continue
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            conn = _Conn(sock, addr)
            with self._conns_lock:
                self._conns.add(conn)
            self.n_accepted += 1
            self._publish_conn_gauges(force=True)
            threading.Thread(
                target=self._conn_loop, args=(conn,),
                name=f"{self.name}-conn", daemon=True).start()

    def _conn_loop(self, conn: _Conn) -> None:
        try:
            rf = wire.reader(conn.sock)     # buffered read side
            while self._running:
                try:
                    frame = wire.recv_frame(rf)
                except wire.ConnectionClosed:
                    return          # client went away (clean or torn)
                except (wire.FrameError, OSError):
                    # corrupt stream: this connection is unusable; the
                    # partial frame was discarded, everything else in
                    # the process is untouched
                    self._count_rejected("bad_frame")
                    return
                if frame["kind"] == "submit":
                    self._handle_submit(conn, frame)
                elif frame["kind"] == "generate":
                    self._handle_generate(conn, frame)
                elif frame["kind"] == "ping":
                    conn.send({"kind": "pong", "id": frame.get("id")})
                # unknown kinds ignored (protocol growth)
        finally:
            conn.close()
            with self._conns_lock:
                self._conns.discard(conn)
            self._publish_conn_gauges(force=True)

    def _handle_submit(self, conn: _Conn, frame: dict) -> None:
        req_id = frame.get("id")
        t0 = time.perf_counter()
        if _fault_state.enabled:
            try:
                fault.check("serving.ingress", f"{self.name}")
            except fault.FaultInjected as e:
                self._reject(conn, req_id, "fault", e)
                return
        with conn.lock:
            if conn.inflight >= self.window:
                # THE backpressure frame: synchronous, typed, while the
                # window's requests are still in flight
                self._reject(conn, req_id, "window_full", MXNetError(
                    f"{self.name}: per-connection window "
                    f"({self.window} in flight) is full"),
                    etype="overloaded")
                return
            conn.inflight += 1
        tr = None
        if _tracing_state.enabled:
            # adopt the client's context from the frame header (absent
            # or malformed = mint fresh — a bad peer degrades to a
            # server-side-only trace, never a crash)
            tr = tracing.adopt(frame.get("trace"), ingress=self.name)
            if tr is None:
                tr = tracing.new_trace("request", ingress=self.name)
            # ingress.decode: frame-in to router-admission (codec +
            # fault site + window check) — latency_report's framing leg
            dsp = tr.begin("ingress.decode", ingress=self.name)
            # backdate to frame receipt: t0 was stamped before the
            # fault site and window check this span accounts for
            dsp.ts -= int((time.perf_counter() - t0) * 1e6)
            dsp.end()
        try:
            # absent model/priority fields = default tenant: frames
            # from peers that predate multi-tenancy route unchanged
            if tr is not None:
                with tracing.active(tr, tr.root or tr.remote_parent):
                    fut = self.router.submit(
                        frame["sample"],
                        deadline_ms=frame.get("deadline_ms"),
                        model=frame.get("model"),
                        priority=frame.get("priority"))
            else:
                fut = self.router.submit(
                    frame["sample"], deadline_ms=frame.get("deadline_ms"),
                    model=frame.get("model"),
                    priority=frame.get("priority"))
        except Exception as e:  # noqa: BLE001 - typed onto the wire
            with conn.lock:
                conn.inflight -= 1
            etype, _msg = wire.encode_error(e)
            reason = etype if etype in (
                "overloaded", "failover_exhausted",
                "throttled") else "error"
            if tr is not None:
                tr.finish(reason)
            self._reject(conn, req_id, reason, e, etype=etype)
            return
        self._publish_conn_gauges()
        fut.add_done_callback(
            lambda f, c=conn, i=req_id, t=t0, r=tr:
            self._on_done(c, i, f, t, r))

    def _on_done(self, conn: _Conn, req_id, fut, t0: float,
                 tr=None) -> None:
        with conn.lock:
            conn.inflight -= 1
        rts = tracing.now_us() if tr is not None else 0
        try:
            payload = fut.result()
        except Exception as e:  # noqa: BLE001 - typed onto the wire
            etype, msg = wire.encode_error(e)
            delivered = conn.send({"kind": "result", "id": req_id,
                                   "ok": False, "etype": etype,
                                   "error": msg})
            if tr is not None:
                tr.add_raw("ingress.reply", ts=rts,
                           dur=tracing.now_us() - rts, etype=etype)
                tr.finish(type(e).__name__)
            self._count_request("error", t0, trace_id=(
                tr.trace_id if tr is not None else None))
        else:
            delivered = conn.send({"kind": "result", "id": req_id,
                                   "ok": True, "payload": payload})
            if tr is not None:
                tr.add_raw("ingress.reply", ts=rts,
                           dur=tracing.now_us() - rts)
                tr.finish("ok" if delivered else "undeliverable")
            self._count_request("ok" if delivered else "undeliverable",
                                t0, trace_id=(
                                    tr.trace_id if tr is not None
                                    else None))
        self._publish_conn_gauges()

    def _handle_generate(self, conn: _Conn, frame: dict) -> None:
        """One streaming generate over the edge: tokens go back as
        ``token`` frames as the fleet decodes them, the ``gen_done``
        finale carries the authoritative full array or the typed error
        (``kvcache_full`` stays typed across the socket). A generate
        occupies one slot of the connection's in-flight window for its
        WHOLE completion — long completions are backpressure too."""
        req_id = frame.get("id")
        t0 = time.perf_counter()
        if _fault_state.enabled:
            try:
                fault.check("serving.ingress", f"{self.name}")
            except fault.FaultInjected as e:
                self._reject(conn, req_id, "fault", e,
                             kind="gen_done")
                return
        with conn.lock:
            if conn.inflight >= self.window:
                self._reject(conn, req_id, "window_full", MXNetError(
                    f"{self.name}: per-connection window "
                    f"({self.window} in flight) is full"),
                    etype="overloaded", kind="gen_done")
                return
            conn.inflight += 1
        tr = None
        if _tracing_state.enabled:
            tr = tracing.adopt(frame.get("trace"), ingress=self.name)
            if tr is None:
                tr = tracing.new_trace("generate", ingress=self.name)
            dsp = tr.begin("ingress.decode", ingress=self.name)
            dsp.ts -= int((time.perf_counter() - t0) * 1e6)
            dsp.end()

        def on_token(i, token):
            conn.send({"kind": "token", "id": req_id, "i": int(i),
                       "token": int(token)})

        try:
            if tr is not None:
                with tracing.active(tr, tr.root or tr.remote_parent):
                    handle = self.router.submit_generate(
                        frame["prompt"],
                        int(frame["max_new_tokens"]),
                        deadline_ms=frame.get("deadline_ms"),
                        on_token=on_token,
                        model=frame.get("model"),
                        priority=frame.get("priority"))
            else:
                handle = self.router.submit_generate(
                    frame["prompt"], int(frame["max_new_tokens"]),
                    deadline_ms=frame.get("deadline_ms"),
                    on_token=on_token, model=frame.get("model"),
                    priority=frame.get("priority"))
        except Exception as e:  # noqa: BLE001 - typed onto the wire
            with conn.lock:
                conn.inflight -= 1
            etype, _msg = wire.encode_error(e)
            reason = etype if etype in (
                "overloaded", "failover_exhausted",
                "kvcache_full", "throttled") else "error"
            if tr is not None:
                tr.finish(reason)
            self._reject(conn, req_id, reason, e, etype=etype,
                         kind="gen_done")
            return
        self._publish_conn_gauges()
        handle.future.add_done_callback(
            lambda f, c=conn, i=req_id, t=t0, r=tr:
            self._on_gen_done(c, i, f, t, r))

    def _on_gen_done(self, conn: _Conn, req_id, fut, t0: float,
                     tr=None) -> None:
        with conn.lock:
            conn.inflight -= 1
        rts = tracing.now_us() if tr is not None else 0
        try:
            payload = fut.result()
        except Exception as e:  # noqa: BLE001 - typed onto the wire
            etype, msg = wire.encode_error(e)
            conn.send({"kind": "gen_done", "id": req_id, "ok": False,
                       "etype": etype, "error": msg})
            if tr is not None:
                tr.add_raw("ingress.reply", ts=rts,
                           dur=tracing.now_us() - rts, etype=etype)
                tr.finish(type(e).__name__)
            self._count_request("error", t0, trace_id=(
                tr.trace_id if tr is not None else None))
        else:
            delivered = conn.send({"kind": "gen_done", "id": req_id,
                                   "ok": True, "payload": payload})
            if tr is not None:
                tr.add_raw("ingress.reply", ts=rts,
                           dur=tracing.now_us() - rts)
                tr.finish("ok" if delivered else "undeliverable")
            self._count_request("ok" if delivered else "undeliverable",
                                t0, trace_id=(
                                    tr.trace_id if tr is not None
                                    else None))
        self._publish_conn_gauges()

    # -- counters ------------------------------------------------------
    def _reject(self, conn: _Conn, req_id, reason: str,
                exc: BaseException, etype: Optional[str] = None,
                kind: str = "result") -> None:
        if etype is None:
            etype, _ = wire.encode_error(exc)
        conn.send({"kind": kind, "id": req_id, "ok": False,
                   "etype": etype, "error": str(exc)})
        self._count_rejected(reason)

    def _count_rejected(self, reason: str) -> None:
        self.n_rejected += 1
        if _telemetry_state.enabled:
            telemetry.record_ingress_rejected(reason)

    def _count_request(self, outcome: str, t0: float,
                       trace_id: Optional[str] = None) -> None:
        self.n_requests += 1
        if _telemetry_state.enabled:
            telemetry.record_ingress_request(
                time.perf_counter() - t0, outcome, trace_id=trace_id)

    def _publish_conn_gauges(self, force: bool = False) -> None:
        if not _telemetry_state.enabled:
            return
        # gauges feed ~1 Hz scrapes; recounting every connection under
        # the shared lock on EVERY submit/done would put O(conns) work
        # + lock contention on the hot path this stack optimizes.
        # Rate-limit the scan; accept/close (force) always publish.
        now = time.monotonic()
        if not force and now < self._gauges_next:
            return
        self._gauges_next = now + 0.25
        with self._conns_lock:
            conns = list(self._conns)
        busy = sum(1 for c in conns if c.inflight > 0)
        telemetry.set_ingress_connections("open", len(conns))
        telemetry.set_ingress_connections("busy", busy)

    def stats(self) -> dict:
        with self._conns_lock:
            n_conns = len(self._conns)
            inflight = sum(c.inflight for c in self._conns)
        return {"name": self.name, "port": self.port,
                "running": self.is_running, "connections": n_conns,
                "inflight": inflight, "accepted": self.n_accepted,
                "requests": self.n_requests,
                "rejected": self.n_rejected}


class IngressClient:
    """Stdlib client for one :class:`Ingress` connection.

    ::

        with serving.IngressClient("127.0.0.1", port) as cli:
            out = cli.submit(sample).result(timeout=5)

    ``submit`` returns a Future that resolves with the result payload
    or raises the SAME typed exceptions the in-process Router does
    (``ServerOverloaded`` for backpressure/admission, reconstructed
    from the error frame) — or :class:`IngressDisconnected` the moment
    the connection drops with requests outstanding. Thread-safe
    submits; one reader thread resolves by request id."""

    def __init__(self, host: str, port: int,
                 connect_timeout_s: float = 10.0):
        self.host, self.port = host, int(port)
        self._sock = wire.connect(host, int(port),
                                  timeout=connect_timeout_s)
        self._sock.settimeout(None)
        # coalescing writer: burst submits share syscalls, and a
        # stalled ingress stalls the writer thread, not the submitter
        self._writer = wire.FrameWriter(self._sock,
                                        name="ingress-client-writer")
        self._lock = threading.Lock()
        self._futures: dict = {}
        self._gens: dict = {}       # id -> GenerateHandle (streaming)
        self._next_id = 0
        self._closed = False
        self._reader = threading.Thread(
            target=self._reader_loop, name="ingress-client", daemon=True)
        self._reader.start()

    def submit(self, sample, deadline_ms: Optional[float] = None,
               model: Optional[str] = None,
               priority: Optional[int] = None) -> Future:
        fut = Future()
        with self._lock:
            if self._closed:
                raise IngressDisconnected(
                    "ingress connection is closed")
            self._next_id += 1
            req_id = self._next_id
            self._futures[req_id] = fut
        frame = {"kind": "submit", "id": req_id, "sample": sample}
        if deadline_ms is not None:
            frame["deadline_ms"] = float(deadline_ms)
        # tenant fields only when set: an old ingress ignores unknown
        # header fields, an absent field means the default tenant
        if model is not None:
            frame["model"] = str(model)
        if priority is not None:
            frame["priority"] = int(priority)
        if _tracing_state.enabled:
            # propagate the caller's ambient trace context across the
            # socket (absent field = untraced; old servers ignore it)
            amb = tracing.ambient()
            if amb is not None:
                frame["trace"] = amb[0].wire(amb[1])
        try:
            self._writer.send(frame)
        except (OSError, wire.FrameError) as e:
            self._fail_all(f"send failed: {e}")
            raise IngressDisconnected(
                f"ingress connection lost at submit: {e}") from e
        return fut

    def submit_generate(self, prompt, max_new_tokens: int,
                        deadline_ms: Optional[float] = None,
                        on_token=None, model: Optional[str] = None,
                        priority: Optional[int] = None):
        """Same contract as :meth:`Router.submit_generate`, over the
        socket: a :class:`~.server.GenerateHandle` whose tokens stream
        in as the fleet decodes them (``on_token`` fires on this
        client's reader thread) and whose future resolves from the
        ``gen_done`` finale — result array, the SAME typed errors
        (``CacheFull``, ``ServerOverloaded``), or
        :class:`IngressDisconnected` if the connection drops
        mid-stream."""
        from .server import GenerateHandle

        handle = GenerateHandle(on_token)
        with self._lock:
            if self._closed:
                raise IngressDisconnected(
                    "ingress connection is closed")
            self._next_id += 1
            req_id = self._next_id
            self._gens[req_id] = handle
        arr = np.ascontiguousarray(np.asarray(prompt),
                                   dtype=np.int32).reshape(-1)
        frame = {"kind": "generate", "id": req_id, "prompt": arr,
                 "max_new_tokens": int(max_new_tokens)}
        if deadline_ms is not None:
            frame["deadline_ms"] = float(deadline_ms)
        if model is not None:
            frame["model"] = str(model)
        if priority is not None:
            frame["priority"] = int(priority)
        if _tracing_state.enabled:
            amb = tracing.ambient()
            if amb is not None:
                frame["trace"] = amb[0].wire(amb[1])
        try:
            self._writer.send(frame)
        except (OSError, wire.FrameError) as e:
            self._fail_all(f"send failed: {e}")
            raise IngressDisconnected(
                f"ingress connection lost at submit: {e}") from e
        return handle

    def _reader_loop(self) -> None:
        try:
            rf = wire.reader(self._sock)    # buffered read side
            while True:
                frame = wire.recv_frame(rf)
                kind = frame["kind"]
                if kind == "token":
                    with self._lock:
                        handle = self._gens.get(frame.get("id"))
                    if handle is not None:
                        handle._push(int(frame["token"]))
                    continue
                if kind == "gen_done":
                    self._on_gen_done(frame)
                    continue
                if kind != "result":
                    continue
                with self._lock:
                    fut = self._futures.pop(frame.get("id"), None)
                if fut is None or \
                        not fut.set_running_or_notify_cancel():
                    continue
                if frame.get("ok"):
                    fut.set_result(frame.get("payload"))
                else:
                    fut.set_exception(wire.decode_error(
                        frame.get("etype", "mxnet_error"),
                        frame.get("error", "ingress error")))
        except (wire.FrameError, OSError) as e:
            self._fail_all(f"connection lost: {e}")

    def _on_gen_done(self, frame: dict) -> None:
        with self._lock:
            handle = self._gens.pop(frame.get("id"), None)
        if handle is None:
            return
        if frame.get("ok"):
            payload = np.asarray(frame.get("payload"),
                                 dtype=np.int32)
            # token frames are best-effort; the finale is authoritative
            for i in range(len(handle.tokens()), payload.size):
                handle._push(int(payload[i]))
            try:
                handle.future.set_result(payload)
            except Exception:   # noqa: BLE001 - already resolved
                pass
        else:
            try:
                handle.future.set_exception(wire.decode_error(
                    frame.get("etype", "mxnet_error"),
                    frame.get("error", "ingress error")))
            except Exception:   # noqa: BLE001 - already resolved
                pass
        handle._seal()

    def _fail_all(self, why: str) -> None:
        with self._lock:
            if self._closed:
                pending, gens = {}, {}
            else:
                self._closed = True
                pending, self._futures = self._futures, {}
                gens, self._gens = self._gens, {}
        exc = IngressDisconnected(
            f"ingress client: {why}; "
            f"{len(pending) + len(gens)} request(s) were in flight")
        for fut in pending.values():
            if fut.set_running_or_notify_cancel():
                try:
                    fut.set_exception(exc)
                except Exception:   # noqa: BLE001
                    pass
        for h in gens.values():
            if h.future.set_running_or_notify_cancel():
                try:
                    h.future.set_exception(exc)
                except Exception:   # noqa: BLE001
                    pass
            h._seal()
        self._writer.close(flush=False, timeout=1.0)
        try:
            self._sock.close()
        except OSError:
            pass

    def close(self) -> None:
        self._fail_all("closed by the client")

    def __enter__(self) -> "IngressClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
