"""Replica health primitives for the multi-replica serving router.

Two building blocks, both lock-cheap and dependency-free:

* :class:`CircuitBreaker` — the per-replica health automaton the
  :class:`~.router.Router` consults before every dispatch. Three states,
  the classic cycle::

        CLOSED --(N consecutive failures | hung dispatch)--> OPEN
        OPEN   --(cooldown elapsed)-------------------------> HALF_OPEN
        HALF_OPEN --(probe succeeds)------------------------> CLOSED
        HALF_OPEN --(probe fails)---------------------------> OPEN

  CLOSED admits traffic freely; OPEN admits nothing until its cooldown
  elapses; HALF_OPEN admits exactly ONE in-flight request (the probe) —
  a recovered replica is re-admitted by one cheap canary instead of a
  thundering herd, and a still-broken one costs one retried request,
  not a queue. Repeated trips back off: the cooldown doubles per
  consecutive OPEN (capped at 16x) and resets on a successful close.

* :class:`Heartbeat` — the in-process liveness beacon, the PR-8 elastic
  heartbeat pattern (``parallel/elastic.py``'s per-rank file touches)
  without the filesystem: the watched loop calls :meth:`Heartbeat.touch`
  every iteration, a watchdog thread checks :meth:`Heartbeat.stale`.
  A scheduler thread that is *alive but wedged* (stuck dispatch, lost
  lock) looks exactly like a dead one — the failure PR 8 showed file
  heartbeats catch and ``Thread.is_alive()`` cannot.

Env knobs (read at construction so tests can monkeypatch):
``MXNET_SERVING_BREAKER_FAILURES`` (3) — consecutive dispatch failures
that trip CLOSED -> OPEN; ``MXNET_SERVING_BREAKER_COOLDOWN`` (1.0 s) —
base OPEN -> HALF_OPEN delay; ``MXNET_SERVING_DISPATCH_TIMEOUT``
(30 s) — a replica scheduler heartbeat silent longer than this while
requests are in flight there is a *hung dispatch* and trips the
breaker immediately (read by the router; must exceed the longest
legitimate single model dispatch).
"""
from __future__ import annotations

import os
import threading
import time
from typing import Optional

from ..base import MXNetError

__all__ = ["CircuitBreaker", "Heartbeat",
           "CLOSED", "OPEN", "HALF_OPEN"]

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half-open"

_COOLDOWN_BACKOFF_CAP = 16.0   # cooldown doubles per consecutive trip, to 16x


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        return float(raw)
    except ValueError as e:
        raise MXNetError(f"{name}={raw!r} is not a number") from e


class Heartbeat:
    """In-process liveness beacon (the elastic heartbeat, file-free).

    The watched loop ``touch()``es once per iteration; a monitor asks
    ``stale(timeout)``. ``touch`` is a single float store (atomic under
    the GIL) so it costs nothing on the hot path.
    """

    __slots__ = ("_t",)

    def __init__(self):
        self._t = time.monotonic()

    def touch(self) -> None:
        self._t = time.monotonic()

    def age(self) -> float:
        return time.monotonic() - self._t

    def stale(self, timeout: float) -> bool:
        return self.age() > timeout


class CircuitBreaker:
    """Per-replica dispatch health automaton (thread-safe).

    The router asks :meth:`admit` before routing a request at the
    replica; every finished dispatch reports :meth:`record_success` or
    :meth:`record_failure`; a dispatch the router declares hung reports
    :meth:`record_hang` (trips immediately — a wedged replica must not
    get ``failure_threshold`` more requests to prove itself dead).
    """

    def __init__(self, name: str = "replica",
                 failure_threshold: Optional[int] = None,
                 cooldown_s: Optional[float] = None,
                 time_fn=time.monotonic):
        if failure_threshold is None:
            failure_threshold = int(_env_float(
                "MXNET_SERVING_BREAKER_FAILURES", 3))
        if cooldown_s is None:
            cooldown_s = _env_float("MXNET_SERVING_BREAKER_COOLDOWN", 1.0)
        if failure_threshold < 1:
            raise MXNetError(
                f"breaker failure threshold must be >= 1, got "
                f"{failure_threshold}")
        if cooldown_s <= 0:
            raise MXNetError(
                f"breaker cooldown must be > 0, got {cooldown_s}")
        self.name = name
        self.failure_threshold = int(failure_threshold)
        self.cooldown_s = float(cooldown_s)
        self._time = time_fn
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consecutive_failures = 0
        self._opened_at = 0.0
        self._open_streak = 0        # consecutive OPENs since last close
        self._probe_inflight = False
        self.n_trips = 0             # lifetime CLOSED/HALF_OPEN -> OPEN

    # -- state ---------------------------------------------------------
    @property
    def state(self) -> str:
        with self._lock:
            self._maybe_half_open()
            return self._state

    def _cooldown(self) -> float:
        return self.cooldown_s * min(
            2.0 ** max(self._open_streak - 1, 0), _COOLDOWN_BACKOFF_CAP)

    def _maybe_half_open(self) -> None:
        # caller holds the lock
        if self._state == OPEN and \
                self._time() - self._opened_at >= self._cooldown():
            self._state = HALF_OPEN
            self._probe_inflight = False

    def _trip(self) -> None:
        # caller holds the lock
        self._state = OPEN
        self._opened_at = self._time()
        self._open_streak += 1
        self._probe_inflight = False
        self._consecutive_failures = 0
        self.n_trips += 1

    # -- router-facing protocol ----------------------------------------
    def admit(self) -> bool:
        """May one request be routed at this replica right now?

        CLOSED: always. OPEN: no (flips to HALF_OPEN once the cooldown
        elapsed, then admits). HALF_OPEN: exactly one — the caller that
        gets ``True`` owns the probe; everyone else is refused until
        the probe reports back.
        """
        with self._lock:
            self._maybe_half_open()
            if self._state == CLOSED:
                return True
            if self._state == HALF_OPEN and not self._probe_inflight:
                self._probe_inflight = True
                return True
            return False

    def record_success(self) -> None:
        """A dispatch at this replica resolved OK."""
        with self._lock:
            self._consecutive_failures = 0
            if self._state == HALF_OPEN:
                # the probe came back healthy: full re-admission
                self._state = CLOSED
                self._probe_inflight = False
                self._open_streak = 0

    def record_failure(self) -> None:
        """A dispatch at this replica failed (typed error after the
        replica's own retries). HALF_OPEN: the probe failed — re-open.
        CLOSED: trips after ``failure_threshold`` consecutive ones."""
        with self._lock:
            if self._state == HALF_OPEN:
                self._trip()
                return
            if self._state == OPEN:     # late failure from a pre-trip
                return                  # dispatch: already quarantined
            self._consecutive_failures += 1
            if self._consecutive_failures >= self.failure_threshold:
                self._trip()

    def release_probe(self) -> None:
        """The caller claimed the HALF_OPEN probe slot but never
        dispatched (routing fault, replica refused the submit): free
        the slot so the next request can probe instead of stalling
        recovery until a timeout."""
        with self._lock:
            if self._state == HALF_OPEN:
                self._probe_inflight = False

    def record_hang(self) -> None:
        """A dispatch exceeded the dispatch timeout: trip immediately,
        whatever the consecutive-failure count — a wedged replica gets
        no benefit of the doubt."""
        with self._lock:
            if self._state != OPEN:
                self._trip()
            else:
                # already quarantined; refresh the clock so the cooldown
                # measures from the LATEST evidence of brokenness
                self._opened_at = self._time()

    def describe(self) -> dict:
        with self._lock:
            self._maybe_half_open()
            return {"state": self._state,
                    "consecutive_failures": self._consecutive_failures,
                    "trips": self.n_trips,
                    "cooldown_s": self._cooldown()}
