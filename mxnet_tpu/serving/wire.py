"""Length-prefixed frame protocol for the out-of-process serving stack.

One wire format shared by all three socket seams: router <-> replica
worker process (:mod:`.remote` / :mod:`.worker`), client <-> ingress
(:mod:`.ingress`), and the bench/chaos harnesses that drive them. Two
design constraints shape it:

* **A torn frame must be discarded, never mis-parsed.** Every frame
  starts with a fixed magic + two length words; the reader either
  receives the WHOLE frame or raises :class:`ConnectionClosed` — a
  worker that dies mid-``sendall`` leaves a truncated tail that reads
  as EOF-inside-a-frame, not as a smaller frame with garbage bits. A
  wrong magic or an absurd length raises :class:`FrameError`
  immediately (a desynchronized or hostile peer is cut off, not
  guessed at).

* **No pickled code over the socket.** Payloads are a JSON header plus
  a raw binary section for numpy buffers — nested lists/tuples/dicts
  with ndarray leaves round-trip exactly (dtype, shape, bits), and the
  decoder can never execute anything. The ingress accepts these frames
  from arbitrary network clients; ``pickle.loads`` there would be a
  remote-code-execution hole, so the private router<->worker seam pays
  the same (tiny) encoding cost for one shared, safe codec.

Frame layout::

    MAGIC (4 bytes, b"MXS1") | header_len u32 BE | body_len u32 BE
    | header (UTF-8 JSON)    | body (concatenated ndarray buffers)

The header is a dict with a ``kind`` field (``hello`` / ``submit`` /
``result`` / ``health`` / ``stop`` / ``bye``); ndarrays anywhere in it
are hoisted into the body section and referenced by index. Typed
errors cross the wire as ``{"ok": false, "etype": ..., "error": ...}``
result frames; :func:`encode_error` / :func:`decode_error` map the
serving stack's exception types (:class:`~.router.ServerOverloaded`,
:class:`~.router.FailoverExhausted`, ...) to stable wire names so
backpressure stays TYPED across process boundaries.
"""
from __future__ import annotations

import json
import socket
import struct
from typing import Any, Dict, Optional, Tuple

import numpy as np

from ..base import MXNetError

__all__ = [
    "FrameError", "ConnectionClosed", "send_frame", "recv_frame",
    "reader", "pack_frame", "FrameWriter",
    "encode_payload", "decode_payload", "encode_error",
    "decode_error", "MAGIC", "MAX_FRAME_BYTES",
]

MAGIC = b"MXS1"
_HEADER = struct.Struct("!4sII")
# per-call nonblocking send flag for the FrameWriter inline fast path
# (Linux/BSD; None disables the fast path, everything coalesces through
# the writer thread as before)
_MSG_DONTWAIT = getattr(socket, "MSG_DONTWAIT", None)
# sanity cap: one frame carries one sample or one sliced result row set,
# not a dataset — a length past this is a desynchronized/hostile peer
MAX_FRAME_BYTES = 256 << 20


class FrameError(MXNetError):
    """The byte stream is not a valid frame (bad magic, absurd length,
    malformed header). The connection is unusable — callers close it."""


class ConnectionClosed(FrameError):
    """EOF — cleanly between frames or (a dying peer's half-written
    frame) in the middle of one. Either way the partial bytes are
    discarded, never parsed."""


# ---------------------------------------------------------------------------
# payload codec: JSON header + hoisted ndarray buffers (no pickle)
# ---------------------------------------------------------------------------

def encode_payload(obj) -> Tuple[bytes, bytes]:
    """Encode ``obj`` (JSON-able scalars + list/tuple/dict containers +
    ndarray/np-scalar leaves) into ``(header_json, body)``."""
    blobs = []

    def enc(o):
        if isinstance(o, np.ndarray):
            arr = np.ascontiguousarray(o)
            blobs.append(arr)
            return {"__nd__": [len(blobs) - 1, arr.dtype.str,
                               list(arr.shape)]}
        if isinstance(o, np.generic):
            return {"__np__": [o.dtype.str, o.item()]}
        if isinstance(o, dict):
            return {"__d__": [[enc(k), enc(v)] for k, v in o.items()]}
        if isinstance(o, tuple):
            return {"__t__": [enc(x) for x in o]}
        if isinstance(o, list):
            return {"__l__": [enc(x) for x in o]}
        if o is None or isinstance(o, (bool, int, float, str)):
            return {"__v__": o}
        raise FrameError(
            f"cannot encode {type(o).__name__} for the serving wire "
            "(JSON scalars, list/tuple/dict, numpy only)")

    data = enc(obj)
    header = json.dumps(
        {"data": data,
         "blobs": [[b.dtype.str, list(b.shape)] for b in blobs]},
        separators=(",", ":")).encode("utf-8")
    body = b"".join(b.tobytes() for b in blobs)
    return header, body


def decode_payload(header: bytes, body: bytes):
    """Inverse of :func:`encode_payload`. Raises :class:`FrameError` on
    anything malformed — a bad frame is rejected, not guessed at."""
    try:
        meta = json.loads(header.decode("utf-8"))
        blob_meta = meta["blobs"]
        arrays = []
        off = 0
        for dtype_str, shape in blob_meta:
            dt = np.dtype(dtype_str)
            n = int(np.prod(shape, dtype=np.int64)) if shape else 1
            nbytes = dt.itemsize * n
            chunk = body[off:off + nbytes]
            if len(chunk) != nbytes:
                raise ValueError("body shorter than its blob table")
            arrays.append(np.frombuffer(chunk, dtype=dt).reshape(shape)
                          .copy())
            off += nbytes

        def dec(o):
            if not isinstance(o, dict) or len(o) != 1:
                raise ValueError(f"untagged node {o!r}")
            tag, v = next(iter(o.items()))
            if tag == "__v__":
                return v
            if tag == "__nd__":
                return arrays[v[0]]
            if tag == "__np__":
                return np.dtype(v[0]).type(v[1])
            if tag == "__d__":
                return {dec(k): dec(val) for k, val in v}
            if tag == "__t__":
                return tuple(dec(x) for x in v)
            if tag == "__l__":
                return [dec(x) for x in v]
            raise ValueError(f"unknown tag {tag!r}")

        return dec(meta["data"])
    except FrameError:
        raise
    except Exception as e:  # noqa: BLE001 - any malformation is typed
        raise FrameError(f"malformed wire payload: {e}") from e


# ---------------------------------------------------------------------------
# framing
# ---------------------------------------------------------------------------

def pack_frame(payload: Dict[str, Any]) -> bytes:
    """Serialize ``payload`` into one complete frame's bytes."""
    header, body = encode_payload(payload)
    return _HEADER.pack(MAGIC, len(header), len(body)) + header + body


def send_frame(sock: socket.socket, payload: Dict[str, Any]) -> None:
    """Serialize ``payload`` (a dict with a ``kind`` field; ndarrays
    anywhere inside) and write one frame. Callers serialize concurrent
    senders with their own lock — a frame must hit the stream whole."""
    sock.sendall(pack_frame(payload))


class FrameWriter:
    """Coalescing write side for a long-lived frame stream, with an
    opportunistic inline fast path.

    ``send()`` never blocks on the peer. When the stream is IDLE —
    writer thread asleep, nothing queued, socket buffer has room — the
    caller encodes and writes the frame itself in one GIL hold: no
    writer-thread wakeup, no futex round trip, no handoff. On a
    contended interpreter those two thread hops per frame were the
    dominant per-request cost of the out-of-process serving path (the
    bench's "scheduling" overhead bucket: wall time in ``submit`` ~20x
    its CPU time, all GIL handoffs). When the fast path is NOT clear —
    a send already in progress, queued frames, a full socket buffer,
    or a stalled peer — the payload is enqueued and the dedicated
    writer thread encodes + drains everything queued in one
    ``sendall``. Properties the hot paths rely on:

    * Frames from one caller thread hit the stream in ``send()``
      order: the fast path runs only when nothing is queued ahead,
      and queued frames only ever drain behind the in-progress
      inline write (the io lock serializes actual socket writes).
    * Under streaming load the kernel sees a few large writes instead
      of a syscall per frame (the symmetric half of :func:`reader`).
    * The caller — the router's single dispatch thread, a worker's
      result callbacks — never blocks on the peer's socket: the
      inline path writes only what ``select`` says fits right now
      (the unsent tail is handed to the writer thread); a stalled
      peer stalls the writer thread, not the dispatcher.
      Consequence: ndarrays inside ``payload`` are captured by
      REFERENCE and must not be mutated after ``send()``.

    A send after the connection died raises :class:`ConnectionClosed`
    (the reader side owns *reporting* the death — first signal wins
    there); a payload the codec rejects poisons the stream and closes
    the writer (every later ``send`` raises — the stack only feeds it
    frames built from already-validated parts). ``close(flush=True)``
    drains what is queued, then stops.
    """

    def __init__(self, sock: socket.socket, name: str = "wire-writer"):
        import threading

        self._sock = sock
        self._cond = threading.Condition()
        self._buf: list = []
        self._tail = b""        # unsent remainder of an inline write
        self._io = threading.Lock()     # serializes socket writes
        self._closed = False
        self._poisoned = False  # closed BY a codec failure: later
        #                         sends raise FrameError (a caller can
        #                         tell "peer died" from "this stream
        #                         can never speak again" and die loud)
        self._thread = threading.Thread(target=self._run, name=name,
                                        daemon=True)
        self._thread.start()

    def _raise_closed(self) -> None:
        if self._poisoned:
            # NOT ConnectionClosed: the socket may be perfectly
            # healthy — an earlier payload the codec rejected poisoned
            # the stream, and a worker swallowing this as "peer went
            # away" would zombie (read submits forever, answer none)
            raise FrameError(
                "frame writer was poisoned by an unencodable payload; "
                "this stream can no longer send")
        raise ConnectionClosed(
            "frame writer is closed (connection died or close() was "
            "called)")

    def send(self, payload: Dict[str, Any]) -> None:
        # inline fast path: only when we win the io lock WITHOUT
        # waiting (the caller must not block) and nothing is queued
        # ahead (order preservation)
        if self._io.acquire(blocking=False):
            try:
                with self._cond:
                    if self._closed:
                        self._raise_closed()
                    clear = not self._buf and not self._tail
                if clear and self._send_inline(payload):
                    return
            finally:
                self._io.release()
        # fallback: enqueue for the writer thread (coalesced drain)
        with self._cond:
            if self._closed:
                self._raise_closed()
            self._buf.append(payload)
            self._cond.notify()

    def _send_inline(self, payload: Dict[str, Any]) -> bool:
        """Holding ``_io`` with a clear queue: write what fits without
        blocking. True = fully handled (sent, or tail handed to the
        writer thread); False = socket has no room at all — enqueue."""
        if _MSG_DONTWAIT is None:
            return False            # platform without per-call nonblock
        try:
            data = pack_frame(payload)
        except Exception:   # noqa: BLE001 - unencodable payload
            # caller bug; nothing partial was sent, but poison the
            # writer so later frames cannot silently reorder around
            # the failure (same contract as the writer-thread path)
            with self._cond:
                self._closed = True
                self._poisoned = True
                self._buf = []
                self._cond.notify()
            raise
        try:
            # per-call nonblocking: a blocking send() loops in-kernel
            # until the WHOLE buffer is copied, and fd-level O_NONBLOCK
            # would break the peer-direction reader sharing this fd
            n = self._sock.send(data, _MSG_DONTWAIT)
        except BlockingIOError:
            return False            # no room at all right now
        except (OSError, ValueError):   # ValueError: fd already closed
            with self._cond:
                self._closed = True
                self._buf = []
                self._cond.notify()
            raise ConnectionClosed(
                "frame writer is closed (connection died or close() "
                "was called)")
        if n < len(data):
            with self._cond:
                self._tail = data[n:]
                self._cond.notify()
        return True

    def _run(self) -> None:
        while True:
            with self._cond:
                while not self._buf and not self._tail \
                        and not self._closed:
                    self._cond.wait()
                closed = self._closed
            # take the io lock BEFORE popping: an inline sender who
            # saw the queue empty must not write between our pop and
            # our sendall (frames would reorder around the drain)
            with self._io:
                with self._cond:
                    buf, self._buf = self._buf, []
                    tail, self._tail = self._tail, b""
                if buf or tail:
                    try:
                        data = tail + b"".join(pack_frame(p)
                                               for p in buf)
                    except Exception:   # noqa: BLE001 - unencodable
                        # payload = a caller bug; the stream position
                        # is still clean (nothing partial was sent)
                        # but frames after the bad one would be
                        # silently reordered — poison the writer
                        with self._cond:
                            self._closed = True
                            self._poisoned = True
                            self._buf = []
                        raise
                    try:
                        self._sock.sendall(data)
                    except OSError:
                        with self._cond:
                            self._closed = True
                            self._buf = []
                        return
            if closed:
                with self._cond:
                    if not self._buf and not self._tail:
                        return

    def close(self, flush: bool = True, timeout: float = 5.0) -> None:
        with self._cond:
            if not flush:
                self._buf = []
                self._tail = b""
            self._closed = True
            self._cond.notify()
        self._thread.join(timeout)


def _recv_exact(sock, n: int, started: bool) -> bytes:
    """Read exactly ``n`` bytes from a socket OR a buffered file-like
    (``reader()``). EOF raises :class:`ConnectionClosed`; ``started``
    only flavors the message (mid-frame vs between frames)."""
    read = getattr(sock, "read", None)
    if read is not None:
        # BufferedReader.read(n) blocks until n bytes or EOF — one
        # python call, and back-to-back frames amortize the recv
        # syscalls (the throughput seam: a syscall per header is 3+
        # syscalls per frame; buffered it is a fraction of one)
        try:
            buf = read(n)
        except OSError as e:
            raise ConnectionClosed(f"connection lost mid-read: {e}") \
                from e
        if buf is None or len(buf) < n:
            raise ConnectionClosed(
                "peer closed mid-frame (half-written frame discarded)"
                if started or buf else "peer closed the connection")
        return buf
    chunks = []
    got = 0
    while got < n:
        try:
            chunk = sock.recv(min(n - got, 1 << 20))
        except OSError as e:
            raise ConnectionClosed(f"connection lost mid-read: {e}") \
                from e
        if not chunk:
            raise ConnectionClosed(
                "peer closed mid-frame (half-written frame discarded)"
                if started or got else "peer closed the connection")
        chunks.append(chunk)
        got += len(chunk)
        started = True
    return b"".join(chunks)


def reader(sock: socket.socket, bufsize: int = 1 << 16):
    """A buffered read side for ``recv_frame`` — use in every
    long-lived reader loop: streamed frames then cost a fraction of a
    syscall each instead of 3+. The socket itself stays usable for
    (unbuffered) sends; closing the socket unblocks the reader."""
    return sock.makefile("rb", buffering=bufsize)


def recv_frame(sock) -> Dict[str, Any]:
    """Read one whole frame from a socket or a :func:`reader` stream
    and decode it. Raises :class:`ConnectionClosed` on EOF (clean or
    mid-frame) and :class:`FrameError` on a corrupt stream."""
    raw = _recv_exact(sock, _HEADER.size, started=False)
    magic, hlen, blen = _HEADER.unpack(raw)
    if magic != MAGIC:
        raise FrameError(
            f"bad frame magic {magic!r} (desynchronized or non-protocol "
            "peer)")
    if hlen + blen > MAX_FRAME_BYTES:
        raise FrameError(
            f"frame of {hlen + blen} bytes exceeds the "
            f"{MAX_FRAME_BYTES}-byte cap")
    header = _recv_exact(sock, hlen, started=True)
    body = _recv_exact(sock, blen, started=True) if blen else b""
    payload = decode_payload(header, body)
    if not isinstance(payload, dict) or "kind" not in payload:
        raise FrameError(f"frame payload has no 'kind': {payload!r}")
    return payload


# ---------------------------------------------------------------------------
# typed errors on the wire
# ---------------------------------------------------------------------------

def _error_registry():
    # resolved lazily: wire is imported by worker subprocesses before
    # the full serving package, and router imports server — keep the
    # import graph shallow until an error actually crosses the wire
    from ..fault import FaultInjected
    from .kvcache import CacheFull, Preempted
    from .router import FailoverExhausted, ServerOverloaded
    from .server import TenantThrottled

    return {
        "overloaded": ServerOverloaded,
        "failover_exhausted": FailoverExhausted,
        "fault_injected": FaultInjected,
        "preempted": Preempted,
        "kvcache_full": CacheFull,
        "throttled": TenantThrottled,
        "mxnet_error": MXNetError,
    }


def encode_error(exc: BaseException) -> Tuple[str, str]:
    """``(etype, message)`` wire form of ``exc`` — the most specific
    registered type wins, anything unknown degrades to ``internal``."""
    reg = _error_registry()
    for name in ("overloaded", "failover_exhausted", "fault_injected",
                 "preempted", "kvcache_full", "throttled"):
        if isinstance(exc, reg[name]):
            return name, str(exc)
    if isinstance(exc, MXNetError):
        return "mxnet_error", str(exc)
    return "internal", f"{type(exc).__name__}: {exc}"


def decode_error(etype: str, message: str) -> MXNetError:
    """Reconstruct the typed exception for a wire error. ``FaultInjected``
    carries site/hit structure that does not cross the wire — it comes
    back as a plain :class:`MXNetError` naming the injection."""
    reg = _error_registry()
    cls = reg.get(etype)
    if cls is None or etype == "fault_injected":
        return MXNetError(message)
    return cls(message)


def parse_hostport(addr: str) -> Tuple[str, int]:
    """``host:port`` -> ``(host, port)`` with a typed error on junk."""
    host, _, port = addr.rpartition(":")
    if not host or not port.isdigit():
        raise MXNetError(f"expected host:port, got {addr!r}")
    return host, int(port)


def connect(host: str, port: int,
            timeout: Optional[float] = None) -> socket.socket:
    """TCP connect with TCP_NODELAY (frames are small and latency-bound;
    Nagle would batch a submit behind the previous result's ACK)."""
    sock = socket.create_connection((host, port), timeout=timeout)
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    return sock
