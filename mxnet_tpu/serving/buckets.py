"""Padding-bucket grid for the serving batcher.

The reference answered variable-shape traffic with ``BucketingModule``
(PAPER.md §2.3): one executor per sequence-length bucket, requests padded
up to the nearest bucket so a handful of compiled graphs cover the whole
shape distribution. Here the same idea keys the ``_CachedGraph`` compiled
path instead of executors — two axes:

* **batch buckets** — allowed dispatch batch sizes (e.g. ``1,2,4,...,32``).
  A partially-filled batch is padded with zero rows up to the nearest
  bucket, so every dispatch hits one warm compiled entry instead of a
  retrace per distinct fill level.
* **shape buckets** — allowed per-sample shapes. A request's sample is
  zero-padded up to the smallest bucket that fits (same rank, every dim
  >=), the BucketingModule move. ``None`` = exact-shape mode: no sample
  padding, one compiled entry per distinct sample shape seen.
* **len buckets** — allowed PREFILL lengths for autoregressive
  generate requests. The generate key space is (batch, prefill-len,
  decode-step): prefill dispatches compile per (batch bucket, len
  bucket), while the decode-step axis collapses to the single constant
  ``(batch, 1)`` signature — however deep each co-batched request is in
  its own completion, every decode step lands on ONE warm executable
  per batch bucket (zero steady-state retraces). Requests at different
  decode depths are equal-shaped by construction, which is what lets
  continuous batching re-form the batch every step.

Padding is part of the serving contract exactly as it was for
BucketingModule: the model sees the padded input (a bucketed sequence
model must mask padding itself), and per-request outputs are sliced from
the real rows only — padded rows never reach a caller.

Bit-reproducibility: padding rows are bit-transparent — a request's
output is identical however empty its batch is, *within one bucket*
(same compiled executable). Across buckets, XLA may pick a different
kernel per batch size: batch-1 matmuls lower to a GEMV whose reduction
order differs in the last ulp from the GEMM used for every batch >= 2
(tools/serving_bench.py measures this). Grids that need response bits
independent of co-batched traffic should start at batch bucket 2.
"""
from __future__ import annotations

import threading
import time
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..base import MXNetError

__all__ = ["BucketGrid", "TokenBucket"]

DEFAULT_BATCH_BUCKETS = (1, 2, 4, 8, 16, 32)
DEFAULT_LEN_BUCKETS = (16, 32, 64, 128, 256)


class TokenBucket:
    """Per-tenant admission rate limiter (the weighted-admission half of
    multi-tenant serving): ``rate`` tokens/second refill into a bucket
    of ``burst`` capacity, one token per admitted request. ``take()``
    is non-blocking — an empty bucket is a SYNCHRONOUS, typed shed at
    submit (``TenantThrottled``), never a queued request that starves
    another tenant's deadline. Thread-safe (any submitter thread)."""

    def __init__(self, rate: float, burst: Optional[float] = None):
        rate = float(rate)
        if rate <= 0:
            raise MXNetError(f"token bucket rate must be > 0, got {rate}")
        self.rate = rate
        self.burst = float(burst) if burst is not None \
            else max(1.0, rate)
        if self.burst < 1:
            raise MXNetError(
                f"token bucket burst must be >= 1, got {self.burst}")
        self._tokens = self.burst
        self._t = time.monotonic()
        self._lock = threading.Lock()

    def take(self, n: float = 1.0) -> bool:
        """Take ``n`` tokens if available; False (taking nothing) when
        the bucket cannot cover them right now."""
        now = time.monotonic()
        with self._lock:
            self._tokens = min(self.burst,
                               self._tokens + (now - self._t) * self.rate)
            self._t = now
            if self._tokens < n:
                return False
            self._tokens -= n
            return True

    def level(self) -> float:
        """Current token level (refilled to now) — observability only."""
        now = time.monotonic()
        with self._lock:
            return min(self.burst,
                       self._tokens + (now - self._t) * self.rate)


class BucketGrid:
    """The (batch buckets x shape buckets x len buckets) padding grid.

    ``batch_buckets``: positive ints; dispatches are padded up to the
    smallest bucket >= the drained request count (capped at the largest).
    ``shape_buckets``: sample-shape tuples, or None for exact-shape mode.
    ``len_buckets``: allowed prefill lengths for generate requests, or
    None when the server does no autoregressive decode.
    """

    def __init__(self, batch_buckets: Sequence[int] = DEFAULT_BATCH_BUCKETS,
                 shape_buckets: Optional[Sequence[Tuple[int, ...]]] = None,
                 len_buckets: Optional[Sequence[int]] = None):
        self.len_buckets: Optional[Tuple[int, ...]] = None
        if len_buckets is not None:
            lens = sorted({int(b) for b in len_buckets})
            if not lens or lens[0] < 1:
                raise MXNetError(
                    f"len_buckets must be positive ints, got "
                    f"{len_buckets!r}")
            self.len_buckets = tuple(lens)
        buckets = sorted({int(b) for b in batch_buckets})
        if not buckets or buckets[0] < 1:
            raise MXNetError(
                f"batch_buckets must be positive ints, got {batch_buckets!r}")
        self.batch_buckets: Tuple[int, ...] = tuple(buckets)
        self.shape_buckets: Optional[Tuple[Tuple[int, ...], ...]] = None
        if shape_buckets is not None:
            shapes = []
            for s in shape_buckets:
                s = tuple(int(d) for d in s)
                if not s or any(d < 1 for d in s):
                    raise MXNetError(
                        f"shape bucket {s!r} must be a non-empty tuple of "
                        "positive dims")
                shapes.append(s)
            if not shapes:
                raise MXNetError("shape_buckets must not be empty "
                                 "(use None for exact-shape mode)")
            # smallest-first so bucket_shape picks the tightest fit
            self.shape_buckets = tuple(
                sorted(set(shapes), key=lambda s: (int(np.prod(s)), s)))

    @property
    def max_batch(self) -> int:
        return self.batch_buckets[-1]

    def batch_bucket(self, n: int) -> int:
        """Smallest batch bucket >= ``n`` (callers cap n at max_batch)."""
        for b in self.batch_buckets:
            if b >= n:
                return b
        return self.max_batch

    def prefill_bucket(self, length: int) -> int:
        """Smallest len bucket >= ``length`` — the padded prefill
        length of a generate request. Raises :class:`MXNetError` when
        the grid has no len buckets or the prompt outgrows the largest
        (rejected at submit, not discovered as a retrace mid-serve)."""
        if self.len_buckets is None:
            raise MXNetError("this grid has no len_buckets: the server "
                             "was not configured for generate requests")
        for b in self.len_buckets:
            if b >= length:
                return b
        raise MXNetError(
            f"no len bucket fits prompt length {length}; buckets: "
            f"{list(self.len_buckets)}")

    def generate_signatures(self) -> List[Tuple[int, int]]:
        """Every (batch_bucket, len) input signature of the generate
        key space: the prefill grid plus the single decode-step column
        ``(batch, 1)`` — the warmup manifest for a decode-capable
        server."""
        if self.len_buckets is None:
            return []
        sigs = [(b, l) for l in self.len_buckets
                for b in self.batch_buckets]
        sigs += [(b, 1) for b in self.batch_buckets]
        return sigs

    def bucket_shape(self, shape: Sequence[int]) -> Tuple[int, ...]:
        """The padded sample shape for a request of ``shape``: the
        tightest shape bucket that fits (exact-shape mode: ``shape``
        itself). Raises :class:`MXNetError` when no bucket fits — a
        too-big request must be rejected at submit, not discovered as a
        shape error mid-batch."""
        shape = tuple(int(d) for d in shape)
        if self.shape_buckets is None:
            return shape
        for b in self.shape_buckets:
            if len(b) == len(shape) and all(d <= bd
                                            for d, bd in zip(shape, b)):
                return b
        raise MXNetError(
            f"no shape bucket fits sample shape {shape}; buckets: "
            f"{list(self.shape_buckets)}")

    @staticmethod
    def pad_sample(arr: np.ndarray, bucket: Tuple[int, ...]) -> np.ndarray:
        """Zero-pad one sample up to its bucket shape (no-op when exact)."""
        if tuple(arr.shape) == tuple(bucket):
            return arr
        pad = [(0, b - d) for d, b in zip(arr.shape, bucket)]
        return np.pad(arr, pad)

    def input_signatures(self, sample_shapes: Optional[Sequence[Tuple[int, ...]]]
                         = None) -> List[Tuple[int, ...]]:
        """Every (batch_bucket, *sample_bucket) input shape of the grid —
        the warmup manifest. ``sample_shapes`` overrides/limits the
        sample axis (required in exact-shape mode, where the grid itself
        has no shape inventory)."""
        samples = (tuple(tuple(int(d) for d in s) for s in sample_shapes)
                   if sample_shapes is not None else self.shape_buckets)
        if not samples:
            return []
        return [(b,) + s for s in samples for b in self.batch_buckets]
