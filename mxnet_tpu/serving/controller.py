"""``mx.serving.controller`` — the traffic-driven control plane.

The robustness subsystems exist (elastic training, health-checked
multi-replica routing, warm-started compilation); this module composes
them into *operations* (ROADMAP item 5): the piece that turns "a
server" into "a deployable system".

* **Autoscaling.** :class:`FleetController` watches the Router's own
  admission signals — shed events, the predicted-wait estimate the
  admission controller already computes, fleet utilization — and grows
  or shrinks the replica fleet between ``min_replicas`` and
  ``max_replicas``. Scale-up spawns a replica through the user's
  ``replica_factory`` and admits it via :meth:`Router.add_replica`,
  which warms the full bucket grid BEFORE the replica takes traffic;
  because grid compiles route through the compilation service's
  executable table and disk cache, a scale-up of an architecture the
  process has seen is a cache hit, not an XLA compile — fast enough to
  matter under a traffic surge. Scale-down drains: the victim stops
  receiving new requests, in-flight ones resolve, then it is detached
  and stopped (zero lost futures by construction). Decisions live in
  :class:`ScalePolicy` — a pure function of
  :class:`FleetSignals` + time, unit-testable with a fake clock:
  scale-up on any shedding or a predicted wait beyond
  ``up_wait_factor``·SLO (one replica per ``up_cooldown_s``);
  scale-down only after utilization stays under
  ``down_utilization`` with an empty queue for ``down_hold_s``
  (hysteresis — a quiet second must not tear down capacity a burst
  needs back).

* **Rolling upgrades.** :func:`rolling_upgrade` walks the fleet one
  replica at a time: build the new model via ``model_factory``, warm it
  for every signature in live use (``Server.swap_model`` — the old
  graph serves throughout, zero downtime), swap, then **bake**: watch
  the replica's circuit breaker and dispatch-error delta for
  ``bake_s``. A breaker trip or any new dispatch error during the bake
  rolls the WHOLE rollout back — every already-upgraded replica gets
  its old model (and old version number) restored, newest first — and
  raises :class:`UpgradeRolledBack`. N-1 replicas serve the old
  version while one bakes, so a poisoned model build costs one
  replica's bake window, never the fleet.

* **Preemption tolerance** lives in the training half of the plane:
  ``parallel/elastic.py``'s graceful-leave protocol (checkpoint on the
  preemption signal, fast leave, supervisor respawn outside the restart
  budget — see ``ElasticRunner.install_preemption_handler`` and
  ``tools/launch.py --preempt-rc``).

Fault sites: ``controller.scale`` fires per scale action (an injected
fault is contained — counted, logged, retried on a later tick);
``serving.upgrade`` fires per replica upgrade (an injected fault
aborts the rollout and exercises the rollback path — that is how the
tests drive it).

Telemetry: ``mxnet_controller_fleet_size``,
``mxnet_controller_scale_total{direction,outcome}``,
``mxnet_controller_scale_seconds{direction}``,
``mxnet_serving_upgrade_total{outcome}``.
"""
from __future__ import annotations

import logging
import threading
import time
import weakref
from dataclasses import dataclass
from typing import Callable, List, Optional

from .. import fault, telemetry
from ..base import MXNetError
from ..fault import _state as _fault_state
from ..telemetry import _state as _telemetry_state
from .health import CLOSED, _env_float
from .router import Router
from .server import DEFAULT_MODEL

__all__ = ["FleetController", "FleetSignals", "ScalePolicy",
           "ScrapeFleetSignals", "UpgradeRolledBack", "rolling_upgrade",
           "live_controllers"]

_log = logging.getLogger(__name__)

# running controllers, for the test-suite leak guard (same pattern as
# server._live_servers / router._live_routers)
_live_controllers = weakref.WeakSet()


def live_controllers():
    """Controllers whose tick thread is currently running."""
    return [c for c in list(_live_controllers) if c.is_running]


class UpgradeRolledBack(MXNetError):
    """A rolling upgrade failed its bake (breaker trip / dispatch
    errors / injected ``serving.upgrade`` fault) and every upgraded
    replica was restored to the previous model. The fleet serves the
    OLD version when this raises."""


@dataclass(frozen=True)
class FleetSignals:
    """One tick's worth of router observations — everything
    :class:`ScalePolicy` is allowed to look at. Pure data so policy
    decisions are replayable in tests without a router."""

    n_replicas: int          # non-draining replicas
    queue_depth: int         # router-queued (not yet dispatched)
    inflight: int            # forwarded, unresolved
    shed_delta: int          # sheds since the previous tick
    predicted_wait_s: float  # admission controller's estimate (0 = none)
    slo_s: float             # the fleet's latency objective
    max_batch: int           # one replica's largest batch bucket
    token_rate: float = 0.0  # decoded tokens/s over the last window
    #                          (0.0 when the fleet serves no generates)

    @property
    def utilization(self) -> float:
        """In-flight work over fleet capacity (1.0 = every replica has
        a full largest-bucket batch outstanding)."""
        cap = self.n_replicas * self.max_batch
        return self.inflight / cap if cap > 0 else 0.0


class ScrapeFleetSignals:
    """Build :class:`FleetSignals` from ``/metrics`` scrapes instead of
    in-process router state — the control plane's signal source when
    the fleet it scales is NOT in its address space (out-of-process
    replica workers, or a router host observed by a separate
    controller process).

    ::

        exporter = telemetry.start_exporter()          # router host
        src = ScrapeFleetSignals(exporter.url,
                                 slo_s=router.slo_s,
                                 max_batch=router.grid.max_batch)
        ctl = FleetController(router, factory, signals_source=src)

    Scrapes the router host's exporter for the gauges the Router's
    monitor publishes every tick (``mxnet_serving_router_queue_depth``,
    ``mxnet_serving_router_inflight``,
    ``mxnet_serving_predicted_wait_seconds``,
    ``mxnet_controller_fleet_size``) plus the
    ``mxnet_serving_shed_total`` counter, whose between-scrape delta is
    computed here (counters are cumulative on the wire), and the
    ``mxnet_serving_tokens_total`` counter, rated into decode
    tokens/s over the scrape window (``FleetSignals.token_rate``; 0.0
    on a fleet that serves no generates). ``slo_s`` and
    ``max_batch`` are deploy-time configuration, not scrapable state.

    A failed scrape returns ``None`` — the controller skips that tick
    (no signal is not the same as a quiet fleet; acting on a default
    would tear down capacity every time the exporter hiccups).

    ``router`` selects ONE router's gauge series by its ``{router=}``
    label when the scraped process hosts several Routers (the bench
    does; a deployed host usually has one). Without it the gauges are
    summed across routers — exact for a single-router host, ambiguous
    otherwise. ``mxnet_serving_shed_total`` has no router dimension,
    so the shed delta is always process-wide: point this source at an
    exporter whose process serves one fleet when sheds matter.
    """

    def __init__(self, url: str, slo_s: float, max_batch: int,
                 timeout_s: float = 2.0,
                 router: Optional[str] = None):
        if slo_s <= 0 or max_batch < 1:
            raise MXNetError(
                f"slo_s must be > 0 and max_batch >= 1, got "
                f"{slo_s}/{max_batch}")
        self.url = url
        self.slo_s = float(slo_s)
        self.max_batch = int(max_batch)
        self.timeout_s = float(timeout_s)
        self.router_label = ({"router": router} if router is not None
                             else None)
        self._last_shed: Optional[float] = None
        # per-tenant router queue depths from the latest good scrape
        # ({model: depth}) — a side-channel for multi-tenant dashboards
        # and tests; FleetSignals itself stays tenant-agnostic (the
        # scale policy sizes the fleet, not any one tenant)
        self.last_tenant_depths: dict = {}
        # decode token-rate window: previous tokens_total reading and
        # when it was taken (same reset-clamp rule as the shed counter)
        self._last_tokens: Optional[float] = None
        self._last_tokens_t: float = 0.0
        self.n_scrapes = 0
        self.n_failures = 0

    def __call__(self) -> Optional[FleetSignals]:
        try:
            parsed = telemetry.scrape(self.url, timeout_s=self.timeout_s)
        except Exception as e:  # noqa: BLE001 - a missed scrape skips
            self.n_failures += 1            # the tick, typed+logged
            _log.warning("scrape of %s failed (%s); skipping this "
                         "tick", self.url, e)
            return None
        self.n_scrapes += 1
        shed = telemetry.prom_value(parsed, "mxnet_serving_shed_total")
        if self._last_shed is None:
            delta = 0.0     # first scrape: no window to delta over
        else:
            # counter reset (router restart) reads as delta<0: clamp —
            # stale pressure must not survive a restart
            delta = max(shed - self._last_shed, 0.0)
        self._last_shed = shed
        now = time.monotonic()
        tokens = telemetry.prom_value(
            parsed, "mxnet_serving_tokens_total", default=0.0)
        if self._last_tokens is None or now <= self._last_tokens_t:
            token_rate = 0.0    # first scrape: no window to rate over
        else:
            token_rate = (max(tokens - self._last_tokens, 0.0)
                          / (now - self._last_tokens_t))
        self._last_tokens = tokens
        self._last_tokens_t = now
        n_replicas = telemetry.prom_value(
            parsed, "mxnet_controller_fleet_size",
            labels=self.router_label, default=-1.0)
        # per-tenant queue depths (one gauge series per model); the
        # router= label filter keeps replica-level series (router="")
        # out when this source watches one named router
        depths: dict = {}
        fam = parsed.get("mxnet_serving_tenant_queue_depth")
        if fam is not None:
            want = self.router_label or {}
            for s in fam["samples"]:
                if s["name"] != "mxnet_serving_tenant_queue_depth":
                    continue
                if not all(s["labels"].get(k) == v
                           for k, v in want.items()):
                    continue
                m = s["labels"].get("model", "")
                if m:
                    depths[m] = depths.get(m, 0) + int(s["value"])
        self.last_tenant_depths = depths
        if n_replicas < 1:
            # the router host publishes its gauges from the monitor
            # tick — an exporter that answers before the first tick (or
            # with telemetry disabled) has no fleet view yet; no signal
            # beats a made-up one
            return None
        return FleetSignals(
            n_replicas=int(n_replicas),
            queue_depth=int(telemetry.prom_value(
                parsed, "mxnet_serving_router_queue_depth",
                labels=self.router_label)),
            inflight=int(telemetry.prom_value(
                parsed, "mxnet_serving_router_inflight",
                labels=self.router_label)),
            shed_delta=int(delta),
            predicted_wait_s=telemetry.prom_value(
                parsed, "mxnet_serving_predicted_wait_seconds",
                labels=self.router_label),
            slo_s=self.slo_s, max_batch=self.max_batch,
            token_rate=token_rate)


class ScalePolicy:
    """The autoscaling decision function (pure: signals + clock in,
    desired fleet size out). Injectable ``time_fn`` so tests replay
    traffic traces against a fake clock.

    Scale-up (urgent, acts on one signal): any shedding since the last
    tick, or a predicted queue wait past ``up_wait_factor``·SLO — one
    replica per ``up_cooldown_s``. Scale-down (conservative,
    hysteresis): utilization under ``down_utilization`` AND an empty
    queue AND no shedding, sustained for ``down_hold_s``, at most one
    replica per ``down_cooldown_s``; any pressure resets the hold
    clock. Bounds ``[min_replicas, max_replicas]`` always win.
    """

    def __init__(self, min_replicas: int = 1, max_replicas: int = 4,
                 up_wait_factor: Optional[float] = None,
                 up_cooldown_s: Optional[float] = None,
                 down_utilization: Optional[float] = None,
                 down_hold_s: Optional[float] = None,
                 down_cooldown_s: Optional[float] = None,
                 time_fn=time.monotonic):
        if min_replicas < 1:
            raise MXNetError(
                f"min_replicas must be >= 1, got {min_replicas}")
        if max_replicas < min_replicas:
            raise MXNetError(
                f"max_replicas ({max_replicas}) must be >= min_replicas "
                f"({min_replicas})")
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.up_wait_factor = _env_float(
            "MXNET_CONTROLLER_UP_WAIT_FACTOR", 0.5) \
            if up_wait_factor is None else float(up_wait_factor)
        self.up_cooldown_s = _env_float(
            "MXNET_CONTROLLER_UP_COOLDOWN", 2.0) \
            if up_cooldown_s is None else float(up_cooldown_s)
        self.down_utilization = _env_float(
            "MXNET_CONTROLLER_DOWN_UTILIZATION", 0.25) \
            if down_utilization is None else float(down_utilization)
        self.down_hold_s = _env_float(
            "MXNET_CONTROLLER_DOWN_HOLD", 10.0) \
            if down_hold_s is None else float(down_hold_s)
        self.down_cooldown_s = _env_float(
            "MXNET_CONTROLLER_DOWN_COOLDOWN", 5.0) \
            if down_cooldown_s is None else float(down_cooldown_s)
        if not 0 < self.up_wait_factor:
            raise MXNetError("up_wait_factor must be > 0")
        if self.up_cooldown_s < 0 or self.down_cooldown_s < 0 \
                or self.down_hold_s < 0:
            raise MXNetError("cooldowns/hold must be >= 0")
        self._time = time_fn
        self._last_up = float("-inf")
        self._last_down = float("-inf")
        self._low_since: Optional[float] = None
        self.last_reason = "steady"

    def desired(self, s: FleetSignals) -> int:
        """Desired fleet size for this tick (moves at most one step from
        ``s.n_replicas``). Sets ``last_reason`` for telemetry labels."""
        now = self._time()
        n = s.n_replicas
        pressured = s.shed_delta > 0 or (
            s.predicted_wait_s > self.up_wait_factor * s.slo_s)
        if pressured:
            self._low_since = None      # pressure resets the down hold
            self.last_reason = ("shed" if s.shed_delta > 0
                                else "predicted_wait")
            if n < self.max_replicas and \
                    now - self._last_up >= self.up_cooldown_s:
                self._last_up = now
                return n + 1
            return max(n, self.min_replicas)
        quiet = (s.queue_depth == 0
                 and s.utilization < self.down_utilization)
        if not quiet:
            self._low_since = None
            self.last_reason = "steady"
            return max(n, self.min_replicas)
        if self._low_since is None:
            self._low_since = now
        self.last_reason = "idle"
        if n > self.min_replicas \
                and now - self._low_since >= self.down_hold_s \
                and now - self._last_down >= self.down_cooldown_s:
            self._last_down = now
            # one step down per cooldown; the hold clock keeps running
            # so a long-idle fleet steps down once per cooldown, not
            # once per hold
            return n - 1
        return max(n, self.min_replicas)

    def action_failed(self, direction: str) -> None:
        """The controller reports a scale action that did NOT happen
        (replica factory raised, drain failed): un-stamp that
        direction's cooldown so the next tick can retry immediately —
        the cooldown paces *successful* fleet changes, and a failed
        spawn under sustained shedding must not buy the failure a
        whole cooldown of continued shedding."""
        if direction == "up":
            self._last_up = float("-inf")
        else:
            self._last_down = float("-inf")


class FleetController:
    """Scale a :class:`Router`'s replica fleet from its own traffic
    signals.

    ::

        def factory(i):                    # UNSTARTED replica, same grid
            return serving.Server(build_net(), name=f"rep{i}",
                                  batch_buckets=..., shape_buckets=...,
                                  slo_ms=...)

        ctl = serving.FleetController(router, factory,
                                      policy=ScalePolicy(1, 4))
        ctl.start()                        # ticks in the background
        ...
        ctl.stop()

    ``replica_factory(index)`` builds an **unstarted** Server whose grid
    matches the fleet's; the controller starts it (full grid warmup —
    executable-table/disk-cache hits when the architecture is known)
    and admits it. A factory/start failure is contained: counted
    (``outcome="failed"``), logged, retried on a later tick — the
    controller thread never dies of a bad spawn. Scale-down picks the
    non-draining replica with the fewest in-flight requests (ties: the
    newest) and drains it through :meth:`Router.remove_replica`.

    ``tick()`` is public and synchronous so tests (and hand-rolled
    loops) can drive the controller without the thread.
    """

    def __init__(self, router: Router,
                 replica_factory: Callable[[int], object],
                 policy: Optional[ScalePolicy] = None,
                 interval_s: Optional[float] = None,
                 drain_timeout_s: float = 30.0,
                 signals_source: Optional[Callable[
                     [], Optional[FleetSignals]]] = None,
                 name: Optional[str] = None):
        if interval_s is None:
            interval_s = _env_float("MXNET_CONTROLLER_INTERVAL", 0.5)
        if interval_s <= 0:
            raise MXNetError(
                f"controller interval must be > 0, got {interval_s}")
        self.router = router
        self.replica_factory = replica_factory
        self.policy = policy or ScalePolicy()
        self.signals_source = signals_source
        self.interval_s = float(interval_s)
        self.drain_timeout_s = float(drain_timeout_s)
        self.name = name or f"controller_{id(self):x}"
        self._spawned = 0           # factory indices, never reused
        self._last_shed = router.n_shed
        self._last_tokens = self._fleet_tokens()
        self._last_tokens_t = time.monotonic()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # light counters
        self.n_ticks = 0
        self.n_scale_up = 0
        self.n_scale_down = 0
        self.n_scale_failed = 0
        self.scale_events: List[dict] = []

    # -- lifecycle -----------------------------------------------------
    @property
    def is_running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    def start(self) -> "FleetController":
        if self.is_running:
            raise MXNetError(f"{self.name}: already running")
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name=self.name, daemon=True)
        self._thread.start()
        _live_controllers.add(self)
        if _telemetry_state.enabled:
            telemetry.set_fleet_size(self.router.fleet_size(),
                                     router=self.router.name)
        return self

    def stop(self, timeout: Optional[float] = None) -> None:
        """Stop the tick thread (the router and its replicas keep
        serving — the controller is an overlay, not an owner)."""
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout if timeout is not None
                   else max(5.0, 4 * self.interval_s))
            if t.is_alive():
                raise MXNetError(
                    f"{self.name}: tick thread did not exit (a drain "
                    "in flight?)")
        self._thread = None
        _live_controllers.discard(self)

    def __enter__(self) -> "FleetController":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception:   # noqa: BLE001 - the loop must survive
                _log.exception("%s: tick failed (contained)", self.name)

    # -- one control iteration -----------------------------------------
    def _fleet_tokens(self) -> int:
        """Fleet-wide decoded-token counter (in-process servers only —
        a RemoteReplica's tokens are scrape territory, see
        :class:`ScrapeFleetSignals`)."""
        return sum(getattr(rep.server, "n_tokens", 0)
                   for rep in self.router._replicas)

    def signals(self) -> FleetSignals:
        r = self.router
        shed = r.n_shed
        delta = shed - self._last_shed
        self._last_shed = shed
        now = time.monotonic()
        tokens = self._fleet_tokens()
        dt = now - self._last_tokens_t
        # a removed replica takes its counter with it: clamp, same as
        # the scrape source does on counter reset
        token_rate = (max(tokens - self._last_tokens, 0) / dt
                      if dt > 0 else 0.0)
        self._last_tokens, self._last_tokens_t = tokens, now
        with r._cond:
            depth = len(r._queue)
            inflight = r._n_inflight
        return FleetSignals(
            n_replicas=r.fleet_size(), queue_depth=depth,
            inflight=inflight, shed_delta=delta,
            predicted_wait_s=r.predicted_wait(), slo_s=r.slo_s,
            max_batch=r.grid.max_batch, token_rate=token_rate)

    def tick(self) -> Optional[str]:
        """Observe, decide, act (at most one scale action). Returns
        ``"up"`` / ``"down"`` / ``None`` for what happened. With a
        ``signals_source`` (e.g. :class:`ScrapeFleetSignals`) the
        observation comes from there — a source returning ``None``
        (failed scrape) skips the tick entirely: no decision on no
        data."""
        self.n_ticks += 1
        if not self.router.is_running:
            return None
        s = self.signals_source() if self.signals_source is not None \
            else self.signals()
        if s is None:
            return None
        want = self.policy.desired(s)
        if want > s.n_replicas:
            return "up" if self._scale_up() else None
        if want < s.n_replicas:
            return "down" if self._scale_down() else None
        return None

    def _scale_up(self) -> bool:
        reason = self.policy.last_reason
        t0 = time.perf_counter()
        try:
            if _fault_state.enabled:
                fault.check("controller.scale", f"{self.name} up")
            idx = self._spawned
            self._spawned += 1
            server = self.replica_factory(idx)
            self.router.add_replica(server)   # starts + warms first
        except Exception as e:  # noqa: BLE001 - contained, retried later
            self.n_scale_failed += 1
            self.policy.action_failed("up")    # no cooldown for a no-op
            if _telemetry_state.enabled:
                telemetry.record_fleet_scale("up", "failed")
            _log.warning("%s: scale-up failed (%s); will retry on a "
                         "later tick", self.name, e)
            return False
        dt = time.perf_counter() - t0
        self.n_scale_up += 1
        self.scale_events.append(
            {"dir": "up", "reason": reason, "replica": server.name,
             "seconds": dt})
        if _telemetry_state.enabled:
            telemetry.record_fleet_scale("up")
            telemetry.record_fleet_scale_seconds("up", dt)
        _log.info("%s: scaled up to %d (%s, %.2fs warm)", self.name,
                  self.router.fleet_size(), reason, dt)
        return True

    def _scale_down(self) -> bool:
        # victim: fewest in-flight among non-draining; ties -> newest
        # (highest stable index) so long-lived replicas stay put
        candidates = [r for r in self.router.replicas()
                      if not r["draining"]]
        if len(candidates) <= 1:
            return False
        victim = min(candidates,
                     key=lambda r: (r["inflight"], -r["index"]))
        t0 = time.perf_counter()
        try:
            if _fault_state.enabled:
                fault.check("controller.scale", f"{self.name} down")
            self.router.remove_replica(
                victim["name"], drain=True,
                timeout=self.drain_timeout_s)
        except Exception as e:  # noqa: BLE001 - contained, retried later
            self.n_scale_failed += 1
            self.policy.action_failed("down")
            if _telemetry_state.enabled:
                telemetry.record_fleet_scale("down", "failed")
            _log.warning("%s: scale-down of %s failed (%s)", self.name,
                         victim["name"], e)
            return False
        dt = time.perf_counter() - t0
        self.n_scale_down += 1
        self.scale_events.append(
            {"dir": "down", "reason": self.policy.last_reason,
             "replica": victim["name"], "seconds": dt})
        if _telemetry_state.enabled:
            telemetry.record_fleet_scale("down")
            telemetry.record_fleet_scale_seconds("down", dt)
        _log.info("%s: drained %s, fleet now %d", self.name,
                  victim["name"], self.router.fleet_size())
        return True

    def stats(self) -> dict:
        return {"ticks": self.n_ticks, "scale_up": self.n_scale_up,
                "scale_down": self.n_scale_down,
                "scale_failed": self.n_scale_failed,
                "fleet_size": self.router.fleet_size(),
                "events": list(self.scale_events),
                "running": self.is_running}


# ---------------------------------------------------------------------------
# rolling upgrade
# ---------------------------------------------------------------------------

def _bake(rep: dict, bake_s: float, poll_s: float = 0.05) -> Optional[str]:
    """Watch one freshly-upgraded replica for ``bake_s``: returns None
    when it baked healthy, else the failure description. Signals: the
    replica's breaker leaving CLOSED (the router's own failure/hang
    evidence) or ANY new dispatch error on the server (a batch the new
    model failed — visible even before the breaker's threshold).
    Deliberately conservative: the server dispatches one batch at a
    time, so at most one OLD-model batch can still be in flight when
    the swap lands — if that one errors into the bake window the
    rollout rolls back on ambiguous evidence rather than baking a
    possibly-bad build through it."""
    server, breaker = rep["server"], rep["breaker"]
    err0 = server.n_errors
    deadline = time.monotonic() + max(0.0, bake_s)
    while True:
        if breaker.state != CLOSED:
            return (f"breaker {breaker.state} during bake "
                    f"(trips={breaker.n_trips})")
        if server.n_errors > err0:
            return (f"{server.n_errors - err0} dispatch error(s) "
                    "during bake")
        if time.monotonic() >= deadline:
            return None
        time.sleep(min(poll_s, max(bake_s, 1e-3)))


def rolling_upgrade(router: Router, model_factory: Callable,
                    bake_s: Optional[float] = None,
                    version: Optional[int] = None,
                    model: Optional[str] = None) -> dict:
    """Upgrade every replica of ``router`` to a new model, one at a
    time, with automatic rollback.

    ``model_factory(server)`` builds the NEW block for one replica (load
    new weights, hybridize — the ``ReloadWatcher`` factory contract,
    handed the live ``Server`` instead of a bundle path). Per replica:
    fault-check ``serving.upgrade`` → build → ``swap_model`` (warms
    every live signature first; the old graph serves until the swap) →
    bake for ``bake_s`` (``MXNET_UPGRADE_BAKE``, default 1.0 s)
    watching the breaker and dispatch errors. Any failure rolls back
    every replica touched so far — old model AND old version number,
    newest first — and raises :class:`UpgradeRolledBack` chained to the
    cause. On success every replica reports the same new
    ``model_version`` (``version`` or max(old)+1).

    ``model`` selects WHICH tenant is upgraded on a multi-tenant fleet
    (default: the default tenant). The swap, the bake and a rollback
    touch that tenant's block and version only — upgrading (or rolling
    back) tenant A never rebuilds or rolls back tenant B, even though
    both share the replica's cache pool and executable table.

    Returns ``{"version", "model", "upgraded": [names...],
    "seconds"}``. Serialized against scale actions via the router's
    admin lock — the fleet cannot change shape mid-rollout.
    """
    if bake_s is None:
        bake_s = _env_float("MXNET_UPGRADE_BAKE", 1.0)
    t_start = time.perf_counter()
    with router._admin_lock:
        reps = [r for r in router.replicas() if not r["draining"]]
        if not reps:
            raise MXNetError("rolling_upgrade: no replicas to upgrade")
        # the bake reads each replica's breaker as evidence AGAINST the
        # new model — a breaker already non-CLOSED would fail its bake
        # instantly and blame pre-existing unhealth on the build, so a
        # degraded fleet refuses the rollout up front (typed, nothing
        # swapped) instead of rolling back half an upgrade
        sick = [r["name"] for r in reps if r["state"] != CLOSED]
        if sick:
            raise MXNetError(
                f"rolling_upgrade: fleet not healthy — breaker not "
                f"closed on {sick}; let the fleet recover (half-open "
                "probes re-admit) before upgrading")
        # in-place swap needs the in-process Server surface; an
        # out-of-process RemoteReplica has no swap_model — refuse the
        # whole rollout typed BEFORE anything is swapped (upgrading a
        # worker fleet is respawn-with-a-new-factory, not a live swap)
        remote = [r["name"] for r in reps
                  if not hasattr(r["server"], "swap_model")]
        if remote:
            raise MXNetError(
                f"rolling_upgrade: replicas {remote} are out-of-process"
                " workers without in-place swap_model; upgrade a worker"
                " fleet by respawning workers with the new factory "
                "(remove_replica/add_replica)")
        tenant = DEFAULT_MODEL if model is None else model
        # every replica must serve the tenant BEFORE anything swaps —
        # a mid-rollout unknown-model refusal would strand a partial
        # upgrade (same shape as the remote refusal above)
        missing = [r["name"] for r in reps
                   if tenant not in r["server"].model_versions()]
        if missing:
            raise MXNetError(
                f"rolling_upgrade: replicas {missing} do not serve "
                f"model {tenant!r}; register it on the whole fleet "
                "(Router.register_model) before upgrading it")
        new_version = (
            max(r["server"].model_versions()[tenant] for r in reps) + 1
            if version is None else int(version))
        done: List[tuple] = []      # (rep, old_block, old_version)

        def _rollback(cause: BaseException, failed_at: str):
            for rep, old_block, old_version in reversed(done):
                try:
                    rep["server"].swap_model(old_block,
                                             version=old_version,
                                             model=tenant)
                except Exception:   # noqa: BLE001 - keep restoring
                    _log.exception(
                        "rollback of replica %s failed — it keeps the "
                        "NEW model", rep["name"])
                if _telemetry_state.enabled:
                    telemetry.record_upgrade_replica("rolled_back")
            raise UpgradeRolledBack(
                f"upgrade of model {tenant!r} to version {new_version} "
                f"failed at replica {failed_at} ({cause}); {len(done)} "
                "replica(s) rolled back to the previous model"
                ) from cause

        for rep in reps:
            server = rep["server"]
            old_block = server.current_model(model=tenant)
            old_version = server.model_versions()[tenant]
            try:
                if _fault_state.enabled:
                    fault.check("serving.upgrade", server.name)
                new_block = model_factory(server)
                server.swap_model(new_block, version=new_version,
                                  model=tenant)
            except Exception as e:  # noqa: BLE001 - rollback path
                if _telemetry_state.enabled:
                    telemetry.record_upgrade_replica("aborted")
                _rollback(e, server.name)
            done.append((rep, old_block, old_version))
            failure = _bake(rep, bake_s)
            if failure is not None:
                _rollback(MXNetError(failure), server.name)
            if _telemetry_state.enabled:
                telemetry.record_upgrade_replica("ok")
            _log.info("rolling upgrade: %s model %s now at version %d",
                      server.name, tenant, new_version)
    return {"version": new_version, "model": tenant,
            "upgraded": [r["name"] for r in reps],
            "seconds": time.perf_counter() - t_start}
