"""``python -m mxnet_tpu.serving.worker`` — one replica as an OS process.

The crash-isolation half of the distributed serving story (ROADMAP
item 5, the reference's ps-lite server processes under
``tools/launch.py``): a replica worker runs a full
:class:`~.server.Server` in its OWN process and speaks the
:mod:`.wire` frame protocol back to the router over one TCP
connection. A segfault, OOM kill, or wedged XLA call here costs this
process — the router, its ingress, and every sibling replica live in
other address spaces and route around the corpse
(:class:`~.remote.RemoteReplica` is the parent-side handle).

Protocol (child connects BACK to the parent's listener — the parent
owns the only well-known port, workers are ephemeral)::

    child -> parent   hello  {name, pid, batch_buckets, shape_buckets,
                              slo_ms, metrics_port}
    parent -> child   submit {id, sample, deadline_ms}
    child -> parent   result {id, ok, payload | etype+error}
    child -> parent   health {age, queue_depth, requests, batches,
                              errors}     (every --health-interval s;
                              ``age`` is the server SCHEDULER
                              heartbeat's age, so a wedged dispatch is
                              visible to the router's hung-dispatch
                              sweep across the process boundary)
    parent -> child   stop   {drain}
    child -> parent   bye    {}

Warm start: ``Server.start()`` AOT-warms the bucket grid through the
compilation service, and in a fresh process that routes through the
persistent XLA disk cache + exported-StableHLO blobs
(``MXNET_XLA_CACHE*`` env, inherited from the parent) — a respawned
worker of a known architecture replays executables instead of
re-tracing, which is what makes crash-respawn cheap enough to be the
recovery path.

The model comes from an importable factory (``--factory mod:fn``,
``--path`` entries prepended to ``sys.path``, ``--factory-kwargs``
JSON) — the same spec-not-closure contract ``tools/launch.py`` workers
follow, because a factory cannot be shipped across an exec boundary.

Orphan fencing: EOF on the parent connection stops the server and
exits — a worker never outlives its router. ``--metrics-port`` exposes
this process's own ``/metrics`` + ``/healthz``
(:func:`mxnet_tpu.telemetry.start_exporter`); port 0 picks an
ephemeral one, reported in the hello frame for scrape discovery.
"""
from __future__ import annotations

import argparse
import importlib
import json
import os
import sys
import threading
import time

__all__ = ["main", "load_factory"]


def load_factory(spec: str, paths=()):
    """Resolve ``mod:fn`` to a callable, with ``paths`` prepended to
    ``sys.path`` first (idempotent)."""
    from ..base import MXNetError

    for p in paths:
        p = os.path.abspath(p)
        if p not in sys.path:
            sys.path.insert(0, p)
    mod_name, _, fn_name = spec.partition(":")
    if not mod_name or not fn_name:
        raise MXNetError(f"--factory must be module:function, got {spec!r}")
    mod = importlib.import_module(mod_name)
    fn = getattr(mod, fn_name, None)
    if not callable(fn):
        raise MXNetError(f"{spec!r} does not name a callable")
    return fn


def _parse_args(argv):
    ap = argparse.ArgumentParser(
        prog="mxnet_tpu.serving.worker",
        description="one serving replica as a supervised OS process")
    ap.add_argument("--connect", required=True,
                    help="host:port of the parent's listener")
    ap.add_argument("--factory", required=True,
                    help="model factory as module:function")
    ap.add_argument("--factory-kwargs", default="{}",
                    help="JSON kwargs for the factory")
    ap.add_argument("--path", action="append", default=[],
                    help="prepend to sys.path before importing the "
                         "factory (repeatable)")
    ap.add_argument("--name", required=True)
    ap.add_argument("--batch-buckets", required=True,
                    help="comma-separated batch buckets, e.g. 2,4,8")
    ap.add_argument("--shape-buckets", default="null",
                    help="JSON list of sample-shape lists, or null")
    ap.add_argument("--slo-ms", type=float, default=100.0)
    ap.add_argument("--batch-timeout-ms", type=float, default=None,
                    help="cap the oldest queued request's co-batching "
                         "wait (ms); omit for the deadline-keyed close")
    ap.add_argument("--dtype", default="float32")
    ap.add_argument("--max-queue", type=int, default=4096)
    ap.add_argument("--decode-pages", type=int, default=None,
                    help="enable paged-KV autoregressive generate with "
                         "this many cache pages")
    ap.add_argument("--page-size", type=int, default=16,
                    help="tokens per KV-cache page")
    ap.add_argument("--len-buckets", default=None,
                    help="comma-separated prefill length buckets, e.g. "
                         "16,32,64 (decode mode only)")
    ap.add_argument("--max-generate-tokens", type=int, default=None,
                    help="per-request prompt+completion token cap")
    ap.add_argument("--no-warmup", action="store_true",
                    help="skip the AOT grid warmup (eager/test models)")
    ap.add_argument("--health-interval", type=float, default=0.05)
    ap.add_argument("--metrics-port", type=int, default=None,
                    help="serve /metrics + /healthz on this port "
                         "(0 = ephemeral); omit to disable")
    return ap.parse_args(argv)


def main(argv=None) -> int:
    args = _parse_args(argv if argv is not None else sys.argv[1:])

    # build BEFORE connecting back: the parent's accept timeout bounds
    # model build + grid warmup, and a factory that cannot import must
    # fail this process loudly, not hand the router a dead replica
    from .. import telemetry, tracing
    from ..base import MXNetError
    from ..tracing import _state as _tracing_state
    from . import wire
    from .server import Server

    tracing.set_process_name(args.name)
    try:
        import signal

        def _on_sigterm(signum, frame):
            # the supervisor's polite kill: persist the flight recorder
            # (MXNET_TRACING_OUT, per-pid path) before dying — the
            # dump is this process's last words
            tracing.maybe_dump("sigterm")
            os._exit(143)

        signal.signal(signal.SIGTERM, _on_sigterm)
    except ValueError:
        pass        # not the main thread (in-process test harness)

    factory = load_factory(args.factory, args.path)
    block = factory(**json.loads(args.factory_kwargs))
    shape_buckets = json.loads(args.shape_buckets)
    if shape_buckets is not None:
        shape_buckets = [tuple(s) for s in shape_buckets]
    len_buckets = (tuple(int(b) for b in args.len_buckets.split(","))
                   if args.len_buckets else None)
    server = Server(
        block,
        batch_buckets=tuple(int(b) for b in
                            args.batch_buckets.split(",")),
        shape_buckets=shape_buckets, slo_ms=args.slo_ms,
        batch_timeout_ms=args.batch_timeout_ms,
        dtype=args.dtype, max_queue=args.max_queue,
        warmup=not args.no_warmup, name=args.name,
        decode_pages=args.decode_pages, page_size=args.page_size,
        len_buckets=len_buckets,
        max_generate_tokens=args.max_generate_tokens)
    server.start()

    exporter = None
    if args.metrics_port is not None:
        telemetry.enable()
        exporter = telemetry.start_exporter(
            port=args.metrics_port,
            healthz_fn=lambda: {
                "ok": server.is_running, "name": args.name,
                "pid": os.getpid(), "hb_age": server.hb.age(),
                **server.stats()})

    host, port = wire.parse_hostport(args.connect)
    sock = wire.connect(host, port, timeout=30.0)
    sock.settimeout(None)
    # coalescing writer: result frames from concurrent done-callbacks
    # stream out in batched sendalls, and no callback ever blocks on
    # the router's socket
    writer = wire.FrameWriter(sock, name=f"{args.name}-writer")
    send = writer.send

    send({"kind": "hello", "name": args.name, "pid": os.getpid(),
          "batch_buckets": list(server.grid.batch_buckets),
          "shape_buckets": ([list(s) for s in server.grid.shape_buckets]
                            if server.grid.shape_buckets else None),
          "len_buckets": (list(server.grid.len_buckets)
                          if server.grid.len_buckets else None),
          "slo_ms": args.slo_ms,
          "metrics_port": exporter.port if exporter else None})

    stop_health = threading.Event()

    def health_loop():
        while not stop_health.wait(args.health_interval):
            st = server.stats()
            try:
                send({"kind": "health", "age": server.hb.age(),
                      "queue_depth": st["queue_depth"],
                      "requests": st["requests"],
                      "batches": st["batches"],
                      "errors": st["errors"]})
            except (OSError, wire.FrameError):
                return          # stream unusable (parent gone or
                #                 poisoned); reader/on_done own exit

    threading.Thread(target=health_loop, name=f"{args.name}-health",
                     daemon=True).start()

    def on_done(req_id, fut, tr=None):
        try:
            payload = fut.result()
        except Exception as e:  # noqa: BLE001 - typed onto the wire
            etype, msg = wire.encode_error(e)
            frame = {"kind": "result", "id": req_id, "ok": False,
                     "etype": etype, "error": msg}
        else:
            frame = {"kind": "result", "id": req_id, "ok": True,
                     "payload": payload}
        if tr is not None:
            # piggyback this request's worker-side spans on the result
            # frame; trace_ts stamps the send so the parent can
            # reconstruct the wire.return leg (same-host wall clock)
            tr.finish("ok" if frame["ok"] else frame.get("etype",
                                                         "error"))
            frame["spans"] = tr.export_spans()
            frame["trace_ts"] = tracing.now_us()
        try:
            send(frame)
        except (OSError, wire.ConnectionClosed):
            pass                # parent gone; nothing to report to
        except wire.FrameError:
            # unencodable model output: the writer is poisoned and
            # this process can never answer anything again — dying
            # LOUDLY turns it into the unambiguous crash signal the
            # parent fails over and respawns on, instead of a zombie
            # that reads submits forever and answers none (the
            # hung-dispatch sweep would re-time-out every request)
            sys.stderr.write(
                f"{args.name}: model output not encodable for the "
                "serving wire; exiting\n")
            sys.stderr.flush()
            os._exit(1)

    def on_gen_done(req_id, fut, tr=None):
        """Final frame of one generate stream: the full token array or
        the typed error, after every token frame for this id."""
        try:
            payload = fut.result()
        except Exception as e:  # noqa: BLE001 - typed onto the wire
            etype, msg = wire.encode_error(e)
            frame = {"kind": "gen_done", "id": req_id, "ok": False,
                     "etype": etype, "error": msg}
        else:
            frame = {"kind": "gen_done", "id": req_id, "ok": True,
                     "payload": payload}
        if tr is not None:
            tr.finish("ok" if frame["ok"] else frame.get("etype",
                                                         "error"))
            frame["spans"] = tr.export_spans()
            frame["trace_ts"] = tracing.now_us()
        try:
            send(frame)
        except (OSError, wire.ConnectionClosed):
            pass
        except wire.FrameError:
            sys.stderr.write(
                f"{args.name}: generate result not encodable for the "
                "serving wire; exiting\n")
            sys.stderr.flush()
            os._exit(1)

    def token_sender(req_id):
        # per-token streaming leg: best-effort — a dead parent is the
        # reader loop's signal to handle, and the final gen_done frame
        # carries the authoritative full token array anyway
        def on_token(i, token):
            try:
                send({"kind": "token", "id": req_id, "i": int(i),
                      "token": int(token)})
            except (OSError, wire.FrameError):
                pass
        return on_token

    rc = 0
    rf = wire.reader(sock)      # buffered: streamed submits cost a
    try:                        # fraction of a syscall each
        while True:
            try:
                frame = wire.recv_frame(rf)
            except wire.ConnectionClosed:
                # orphan fencing: the router died — do not serve a
                # queue nobody reads; exit and let supervision decide
                tracing.maybe_dump("orphaned")
                server.stop(drain=False, timeout=10)
                return 0
            kind = frame["kind"]
            if kind == "submit":
                req_id = frame["id"]
                tr = None
                if _tracing_state.enabled:
                    # the frame header's span context: adopt it so the
                    # server's batch.wait/dispatch spans join the
                    # router-side trace (absent/malformed = untraced)
                    tr = tracing.adopt(frame.get("trace"),
                                       worker=args.name)
                # absent model/priority header fields = default tenant
                # (old peers interoperate — the tracing-header contract)
                kw = {"deadline_ms": frame.get("deadline_ms"),
                      "model": frame.get("model"),
                      "priority": frame.get("priority")}
                try:
                    if tr is not None:
                        with tracing.active(tr, tr.remote_parent):
                            fut = server.submit(frame["sample"], **kw)
                    else:
                        fut = server.submit(frame["sample"], **kw)
                except Exception as e:  # noqa: BLE001 - sync refusal
                    etype, msg = wire.encode_error(e)
                    res = {"kind": "result", "id": req_id,
                           "ok": False, "etype": etype, "error": msg}
                    if tr is not None:
                        tr.finish(etype)
                        res["spans"] = tr.export_spans()
                        res["trace_ts"] = tracing.now_us()
                    try:
                        send(res)
                    except (OSError, wire.ConnectionClosed):
                        # parent gone mid-reply: same orphan fencing
                        # as EOF on recv, not a crash
                        tracing.maybe_dump("orphaned")
                        server.stop(drain=False, timeout=10)
                        return 0
                    continue
                fut.add_done_callback(
                    lambda f, i=req_id, t=tr: on_done(i, f, t))
            elif kind == "generate":
                req_id = frame["id"]
                tr = None
                if _tracing_state.enabled:
                    tr = tracing.adopt(frame.get("trace"),
                                       worker=args.name)
                kw = {"deadline_ms": frame.get("deadline_ms"),
                      "on_token": token_sender(req_id),
                      "model": frame.get("model"),
                      "priority": frame.get("priority")}
                try:
                    if tr is not None:
                        with tracing.active(tr, tr.remote_parent):
                            handle = server.submit_generate(
                                frame["prompt"],
                                int(frame["max_new_tokens"]), **kw)
                    else:
                        handle = server.submit_generate(
                            frame["prompt"],
                            int(frame["max_new_tokens"]), **kw)
                except Exception as e:  # noqa: BLE001 - sync refusal
                    etype, msg = wire.encode_error(e)
                    res = {"kind": "gen_done", "id": req_id,
                           "ok": False, "etype": etype, "error": msg}
                    if tr is not None:
                        tr.finish(etype)
                        res["spans"] = tr.export_spans()
                        res["trace_ts"] = tracing.now_us()
                    try:
                        send(res)
                    except (OSError, wire.ConnectionClosed):
                        tracing.maybe_dump("orphaned")
                        server.stop(drain=False, timeout=10)
                        return 0
                    continue
                handle.future.add_done_callback(
                    lambda f, i=req_id, t=tr: on_gen_done(i, f, t))
            elif kind == "register_model":
                # tenant registration across the process boundary: the
                # block arrives as a factory SPEC (mod:fn + kwargs),
                # the same spec-not-closure contract as --factory
                try:
                    tfac = load_factory(frame["factory"],
                                        frame.get("paths", ()))
                    tblock = tfac(**frame.get("factory_kwargs", {}))
                    server.register_model(
                        frame["name"], tblock,
                        slo_class=frame.get("slo_class", "standard"),
                        priority=frame.get("priority", 0),
                        weight=frame.get("weight", 1.0),
                        slo_ms=frame.get("slo_ms"),
                        rate_limit=frame.get("rate_limit"),
                        burst=frame.get("burst"))
                except Exception as e:  # noqa: BLE001 - typed reply
                    etype, msg = wire.encode_error(e)
                    res = {"kind": "registered", "id": frame.get("id"),
                           "name": frame.get("name"), "ok": False,
                           "etype": etype, "error": msg}
                else:
                    res = {"kind": "registered", "id": frame.get("id"),
                           "name": frame["name"], "ok": True}
                try:
                    send(res)
                except (OSError, wire.ConnectionClosed):
                    tracing.maybe_dump("orphaned")
                    server.stop(drain=False, timeout=10)
                    return 0
            elif kind == "stop":
                try:
                    server.stop(drain=bool(frame.get("drain", True)),
                                timeout=frame.get("timeout"))
                except MXNetError:
                    rc = 1      # wedged scheduler: report, still exit
                try:
                    send({"kind": "bye"})
                except (OSError, wire.ConnectionClosed):
                    pass        # stopping anyway; nothing to report to
                return rc
            elif kind == "ping":
                try:
                    send({"kind": "pong", "id": frame.get("id")})
                except (OSError, wire.ConnectionClosed):
                    server.stop(drain=False, timeout=10)
                    return 0
            # unknown kinds are ignored: protocol growth must not kill
            # old workers
    finally:
        stop_health.set()
        if exporter is not None:
            exporter.stop()
        writer.close(flush=True)    # the bye frame must reach the wire
        try:
            sock.close()
        except OSError:
            pass
        if server.is_running:
            try:
                server.stop(drain=False, timeout=10)
            except MXNetError:
                pass


if __name__ == "__main__":
    sys.exit(main())
