"""Paged KV-cache pool for autoregressive decode (ROADMAP item 1).

The reference's ``BucketingModule`` amortized *compilation* across
sequence lengths but re-ran full-sequence compute every step; the
modern answer is a KV cache, and the serving-grade shape of that cache
is **paged** (vLLM's insight): keys/values live in fixed-size pages
inside one preallocated per-replica arena, and each request owns a
*list* of pages rather than a contiguous max-length slab. Continuous
batching then composes freely — requests of wildly different lengths
join and leave the decode batch at every step without copying or
re-packing anybody's cache.

This module is the **accounting** half: :class:`PagePool` hands out
page ids from a free list, tracks per-owner page lists, and raises the
typed :class:`CacheFull` when the arena cannot fit a request —
admission control, wired into the Router's shed machinery exactly like
``ServerOverloaded`` (shed reason ``kvcache_full``). The **storage**
half is a pair of arena arrays (:func:`make_kv_arena`) indexed by flat
slot: token ``i`` of a request whose page table is ``pt`` lives at slot
``pt[i // page_size] * page_size + i % page_size``.

Page 0 is **reserved as scratch**: batch-padding rows and padded tail
positions scatter their (meaningless) K/V there, so a padded dispatch
can write unconditionally without ever corrupting a live request's
pages — the same bit-transparent-padding contract the batcher already
guarantees (see :mod:`.buckets`).

Fixed-size pages cannot fragment in the classical sense (any free page
serves any request), but a long-lived fleet still wants
:meth:`PagePool.defrag`: it computes the permutation that packs live
pages down to the lowest indices (arena locality, and the precondition
for shrinking an arena), and :func:`apply_defrag` replays that
permutation onto the arena arrays.

Telemetry (``MXNET_TELEMETRY=1``): every alloc/free publishes
``mxnet_serving_kvcache_pages{state=free|used|reserved}``.
"""
from __future__ import annotations

import threading
from collections import deque
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..base import MXNetError
from ..telemetry import _state as _telemetry_state

__all__ = ["CacheFull", "Preempted", "PagePool", "make_kv_arena",
           "apply_defrag"]


class CacheFull(MXNetError):
    """Typed admission error: the KV arena cannot hold this request.

    Raised synchronously at admission (never as a wedged future) and
    shipped over :mod:`.wire` under the stable name ``kvcache_full`` so
    a remote caller gets this exact type back. The Router counts it as
    a shed (``mxnet_serving_shed_total{reason="kvcache_full"}``).
    """


class Preempted(MXNetError):
    """This stream's pages were reclaimed for a higher-priority arrival.

    Resolved onto the victim's ``GenerateHandle.future`` at a decode-step
    boundary: every token streamed before the preemption is a clean,
    sealed prefix (the chaos-gate-9 crash contract — never a torn
    token), and the handle never wedges. Crosses :mod:`.wire` under the
    stable name ``preempted``. Counted per tenant as
    ``mxnet_serving_preempted_total{victim,beneficiary}``.
    """


class PagePool:
    """Free-list allocator over ``n_pages`` fixed-size cache pages.

    ``page_size`` is in tokens. Page 0 is reserved as the padding
    scratch page and is never handed out. Thread-safe: the serving
    scheduler allocates while ``stats()``/telemetry readers observe.
    """

    def __init__(self, n_pages: int, page_size: int = 16):
        if n_pages < 2:
            raise MXNetError(
                f"PagePool needs >= 2 pages (page 0 is the reserved "
                f"scratch page), got {n_pages}")
        if page_size < 1:
            raise MXNetError(f"page_size must be >= 1, got {page_size}")
        self.n_pages = int(n_pages)
        self.page_size = int(page_size)
        self._lock = threading.Lock()
        self._free: deque = deque(range(1, self.n_pages))
        self._owned: Dict[object, List[int]] = {}
        self._publish()

    # -- capacity ------------------------------------------------------
    @property
    def slots(self) -> int:
        """Total arena slots (tokens), scratch page included."""
        return self.n_pages * self.page_size

    @property
    def capacity_tokens(self) -> int:
        """Tokens the pool can hold for real requests (scratch excluded)."""
        return (self.n_pages - 1) * self.page_size

    def pages_for(self, n_tokens: int) -> int:
        return -(-max(int(n_tokens), 1) // self.page_size)

    # -- allocation ----------------------------------------------------
    def alloc(self, owner, n_tokens: int) -> List[int]:
        """Allocate pages covering ``n_tokens`` for ``owner``. Raises
        :class:`CacheFull` (allocating nothing) when the free list is
        short — admission is all-or-nothing, so a request can never
        wedge half-allocated."""
        need = self.pages_for(n_tokens)
        with self._lock:
            if owner in self._owned:
                raise MXNetError(f"PagePool: owner {owner!r} already holds "
                                 f"{len(self._owned[owner])} page(s)")
            if need > len(self._free):
                raise CacheFull(
                    f"kv cache full: need {need} page(s) for {n_tokens} "
                    f"token(s), {len(self._free)} of "
                    f"{self.n_pages - 1} free")
            pages = [self._free.popleft() for _ in range(need)]
            self._owned[owner] = pages
        self._publish()
        return list(pages)

    def extend(self, owner, n_tokens: int) -> List[int]:
        """Grow ``owner``'s allocation to cover ``n_tokens`` total.
        Raises :class:`CacheFull` without changing the allocation when
        the free list cannot cover the growth."""
        need = self.pages_for(n_tokens)
        with self._lock:
            held = self._owned.get(owner)
            if held is None:
                raise MXNetError(f"PagePool: unknown owner {owner!r}")
            grow = need - len(held)
            if grow <= 0:
                return list(held)
            if grow > len(self._free):
                raise CacheFull(
                    f"kv cache full: owner {owner!r} needs {grow} more "
                    f"page(s), {len(self._free)} free")
            held.extend(self._free.popleft() for _ in range(grow))
            pages = list(held)
        self._publish()
        return pages

    def free(self, owner) -> int:
        """Return ``owner``'s pages to the free list (idempotent);
        returns the number of pages released."""
        with self._lock:
            pages = self._owned.pop(owner, None)
            if pages:
                self._free.extend(pages)
        self._publish()
        return len(pages) if pages else 0

    def page_table(self, owner, width: Optional[int] = None) -> np.ndarray:
        """``owner``'s page list as an int32 vector padded with the
        scratch page (0) up to ``width`` — the dense per-row page table
        a batched dispatch gathers through."""
        with self._lock:
            pages = list(self._owned.get(owner, ()))
        if width is None:
            width = len(pages)
        if len(pages) > width:
            raise MXNetError(
                f"PagePool: owner {owner!r} holds {len(pages)} page(s), "
                f"page_table width {width} too small")
        out = np.zeros((width,), dtype=np.int32)
        out[:len(pages)] = pages
        return out

    def owned(self, owner) -> List[int]:
        """``owner``'s current page list (a copy). Needed after
        :meth:`defrag`, which renumbers pages in place — any snapshot a
        caller took at :meth:`alloc` time is stale the moment a defrag
        runs."""
        with self._lock:
            return list(self._owned.get(owner, ()))

    # -- observability -------------------------------------------------
    def frag_info(self) -> Tuple[int, int]:
        """``(n_live, span)``: live page count and the highest live page
        index (0 when empty). ``span - n_live`` is the number of free
        holes below the high-water mark — the fragmentation measure the
        serving scheduler's automatic :meth:`defrag` trigger thresholds
        on (a packed pool has ``span == n_live``)."""
        with self._lock:
            live = [p for pages in self._owned.values() for p in pages]
            return len(live), (max(live) if live else 0)

    def stats(self) -> dict:
        with self._lock:
            used = sum(len(p) for p in self._owned.values())
            return {"free": len(self._free), "used": used, "reserved": 1,
                    "owners": len(self._owned),
                    "page_size": self.page_size,
                    "n_pages": self.n_pages}

    def _publish(self) -> None:
        if not _telemetry_state.enabled:
            return
        from .. import telemetry

        s = self.stats()
        telemetry.set_kvcache_pages(s["free"], s["used"], s["reserved"])

    # -- defrag --------------------------------------------------------
    def defrag(self) -> List[Tuple[int, int]]:
        """Pack live pages down to the lowest page indices. Returns the
        ``(src, dst)`` page moves performed (empty when already packed);
        the caller replays them onto the arena with
        :func:`apply_defrag` *before* the next dispatch reads it.
        Accounting (page lists, free list) is updated here atomically.
        """
        with self._lock:
            live = sorted(p for pages in self._owned.values()
                          for p in pages)
            # target: live pages occupy 1..len(live) in order
            target = {src: dst for dst, src in
                      enumerate(live, start=1) if src != dst}
            if not target:
                return []
            moves = sorted(target.items(), key=lambda m: m[1])
            for pages in self._owned.values():
                for i, p in enumerate(pages):
                    pages[i] = target.get(p, p)
            n_live = len(live)
            self._free = deque(range(n_live + 1, self.n_pages))
            return moves


def make_kv_arena(n_layers: int, pool: PagePool, n_kv_heads: int,
                  head_dim: int, dtype="float32"):
    """Preallocate the per-replica K and V arenas:
    ``(n_layers, pool.slots, n_kv_heads, head_dim)`` zeros each.

    The arenas are committed to a device (``device_put``) so their
    sharding matches what jit outputs carry — an uncommitted zeros
    array keys the first executable differently and forces a silent
    one-time recompile on the second forward."""
    import jax
    import jax.numpy as jnp

    shape = (int(n_layers), pool.slots, int(n_kv_heads), int(head_dim))
    dev = jax.local_devices()[0]
    return (jax.device_put(jnp.zeros(shape, dtype=dtype), dev),
            jax.device_put(jnp.zeros(shape, dtype=dtype), dev))


def apply_defrag(arena, moves, page_size: int):
    """Replay :meth:`PagePool.defrag` page moves onto one arena array
    (``(..., slots, heads, dim)`` with slots on axis 1). Moves are
    applied from one snapshot, so overlapping src/dst chains are safe.
    """
    if not moves:
        return arena
    import jax.numpy as jnp

    src = np.concatenate([np.arange(s * page_size, (s + 1) * page_size)
                          for s, _ in moves])
    dst = np.concatenate([np.arange(d * page_size, (d + 1) * page_size)
                          for _, d in moves])
    rows = jnp.take(arena, jnp.asarray(src), axis=1)
    return arena.at[:, jnp.asarray(dst)].set(rows)
