"""``mx.serving`` — the inference serving stack (ROADMAP item 1).

A model server over the ``_CachedGraph`` compiled path: concurrent
requests enter through ``Server.submit`` (thread-safe, Future out), a
scheduler drains them into dynamic batches padded onto a
``BucketGrid`` — the ``BucketingModule`` idea (PAPER.md §2.3) re-keyed
to compiled-graph cache entries — and dispatches each batch as one warm
XLA executable under a per-request latency SLO. ``Router`` fronts N
``Server`` replicas behind the same ``submit() -> Future`` contract
with least-loaded dispatch, per-replica circuit breakers, bounded
failover (no future is ever lost) and deadline-aware admission control
(synchronous typed ``ServerOverloaded`` shedding). The fleet is
elastic: ``Router.add_replica``/``remove_replica`` grow and drain it
live, ``FleetController`` drives them from the router's own traffic
signals, and ``rolling_upgrade`` walks a new model through the fleet
with breaker-gated automatic rollback (see :mod:`.controller`).

The stack also serves **autoregressive decode** with continuous
batching: ``Server.submit_generate() -> GenerateHandle`` streams
tokens as they are produced, per-request KV state lives in a paged
``PagePool`` (:mod:`.kvcache`), prefill lands on the ``BucketGrid``'s
length buckets, and every decode step for every in-flight request
rejoins one warm ``(batch, 1)`` executable — zero steady-state
retraces. Capacity exhaustion is a synchronous typed ``CacheFull``.
The same contract crosses the process boundary: ``RemoteReplica``,
``Router`` and ``IngressClient`` all expose ``submit_generate`` with
token streaming over the wire.

The fleet is also **crash-isolated**: a replica may be an
out-of-process worker (``RemoteReplica`` over
``python -m mxnet_tpu.serving.worker``, one supervised OS process per
replica speaking the :mod:`.wire` frame protocol) — a segfault or
SIGKILL there is an unambiguous, typed failure the router routes
around and the supervisor respawns with backoff. ``Ingress`` puts a
socket edge in front of the Router (bounded per-connection windows,
backpressure as typed error frames; ``IngressClient`` is the matching
client), and ``ScrapeFleetSignals`` feeds the autoscaler from
``/metrics`` scrapes so the control plane works across address
spaces. Hot reload, fault injection/retry and Prometheus telemetry
ride the PR-1/PR-3 infrastructure; see :mod:`.server`,
:mod:`.buckets`, :mod:`.reload`, :mod:`.router`, :mod:`.health`,
:mod:`.wire`, :mod:`.worker`, :mod:`.remote`, :mod:`.ingress`.

The stack is **multi-tenant**: ``Server.register_model`` /
``Router.register_model`` put several hybridized blocks behind one
replica fleet (each tenant carries an SLO class, a priority, a
weighted-fair share and an optional ``TokenBucket`` rate limit), the
scheduler interleaves tenants per decode step under weighted
admission, and when the shared KV-cache pool fills a higher-priority
arrival preempts the lowest-priority active stream BETWEEN decode
steps — the victim resolves typed (``Preempted``) with a sealed
clean-prefix token stream, never a torn token. ``model=`` /
``priority=`` ride every seam (wire frames, worker, ``RemoteReplica``,
``Ingress``); an absent field means the default tenant, so old peers
interoperate.
"""
from .buckets import DEFAULT_LEN_BUCKETS, BucketGrid, TokenBucket
from .controller import (
    FleetController,
    FleetSignals,
    ScalePolicy,
    ScrapeFleetSignals,
    UpgradeRolledBack,
    live_controllers,
    rolling_upgrade,
)
from .health import CircuitBreaker, Heartbeat
from .ingress import (
    Ingress,
    IngressClient,
    IngressDisconnected,
    live_ingresses,
)
from .kvcache import CacheFull, PagePool, Preempted
from .reload import ReloadWatcher
from .remote import RemoteReplica, WorkerCrashed, live_workers
from .router import (
    FailoverExhausted,
    ReplicaFault,
    Router,
    ServerOverloaded,
    live_routers,
)
from .server import (
    DEFAULT_MODEL,
    GenerateHandle,
    Server,
    TenantThrottled,
    live_servers,
)

__all__ = [
    "Server", "BucketGrid", "ReloadWatcher", "live_servers",
    "GenerateHandle", "PagePool", "CacheFull", "DEFAULT_LEN_BUCKETS",
    "DEFAULT_MODEL", "TenantThrottled", "Preempted", "TokenBucket",
    "Router", "ServerOverloaded", "FailoverExhausted", "ReplicaFault",
    "CircuitBreaker", "Heartbeat", "live_routers",
    "FleetController", "FleetSignals", "ScalePolicy",
    "ScrapeFleetSignals",
    "UpgradeRolledBack", "rolling_upgrade", "live_controllers",
    "RemoteReplica", "WorkerCrashed", "live_workers",
    "Ingress", "IngressClient", "IngressDisconnected", "live_ingresses",
]
