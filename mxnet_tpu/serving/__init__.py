"""``mx.serving`` — the inference serving stack (ROADMAP item 1).

A model server over the ``_CachedGraph`` compiled path: concurrent
requests enter through ``Server.submit`` (thread-safe, Future out), a
scheduler drains them into dynamic batches padded onto a
``BucketGrid`` — the ``BucketingModule`` idea (PAPER.md §2.3) re-keyed
to compiled-graph cache entries — and dispatches each batch as one warm
XLA executable under a per-request latency SLO. Hot reload, fault
injection/retry and Prometheus telemetry ride the PR-1/PR-3
infrastructure; see :mod:`.server`, :mod:`.buckets`, :mod:`.reload`.
"""
from .buckets import BucketGrid
from .reload import ReloadWatcher
from .server import Server, live_servers

__all__ = ["Server", "BucketGrid", "ReloadWatcher", "live_servers"]
