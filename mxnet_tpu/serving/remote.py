"""``mx.serving.RemoteReplica`` — a crash-isolated replica worker handle.

The parent-side half of the out-of-process serving stack: a
``RemoteReplica`` satisfies the same dispatch contract the Router's
in-process ``_Replica.server`` does (``name`` / ``grid`` / ``slo_s`` /
``hb`` / ``is_running`` / ``submit() -> Future`` / ``start`` /
``stop``), but the model lives in a SEPARATE supervised OS process
(:mod:`.worker`) reached over one :mod:`.wire` connection. The router's
breakers, hung-dispatch watchdog, failover, drain and zero-lost-future
invariant apply unchanged — what changes is the *failure signal*:

* **Connection drop and ``waitpid`` are unambiguous.** An in-process
  replica can only look "slow" until a timeout says otherwise; a
  worker whose socket EOFs or whose process is reaped CRASHED, full
  stop. Every in-flight future resolves immediately with the typed
  :class:`WorkerCrashed` (never a hang on a dead process), and
  ``crash_count`` bumps — the Router's monitor reads it and trips the
  breaker at once (crash != slow: no failure-threshold grace for a
  corpse).

* **Hung is still hung.** The worker streams health frames carrying
  its server SCHEDULER heartbeat's age; this handle's ``hb`` replays
  them, so the router's existing hung-dispatch sweep sees a wedged
  remote dispatch exactly as it saw an in-process one — and a wedged
  worker whose health frames keep flowing is distinguished from a
  dead one.

* **Supervision with exponential backoff.** A crashed worker is
  respawned (``respawn=True``) after ``MXNET_WORKER_RESPAWN_BACKOFF``
  seconds, doubling per consecutive crash (capped), up to
  ``MXNET_WORKER_MAX_RESPAWNS`` times; the respawned process warms its
  grid through the compilation service's persistent disk cache +
  exported StableHLO (the executable table does not span processes,
  the disk tier does), and the router re-admits it through the
  breaker's half-open probe. ``mxnet_worker_restarts_total{replica}``
  counts re-spawns.

Fault sites: ``worker.spawn`` fires on every process launch (spawn and
respawn), with the indexed ``worker.spawn.<i>`` sub-site (PR-9 form)
targeting one worker's spawn path — ``i`` is the replica's stable
``worker_index``, assigned at construction, process-wide monotonic.

The model is specified as an importable factory (``module:function`` +
kwargs + extra ``sys.path`` entries), not a closure — it must be
reconstructable across an exec boundary, the ``tools/launch.py``
contract. ``_pre_dispatch`` (the Router's in-process fault hook) is
accepted and ignored: injected dispatch faults cannot reach another
address space — chaos runs target workers with ``worker.spawn`` sites
and real signals (``tools/chaos_check.py`` gate 8 SIGKILLs one).
"""
from __future__ import annotations

import itertools
import json
import logging
import os
import socket
import subprocess
import sys
import threading
import time
import weakref
from concurrent.futures import Future
from typing import Optional, Sequence

import numpy as np

from .. import fault, telemetry, tracing
from ..base import MXNetError
from ..fault import _state as _fault_state
from ..telemetry import _state as _telemetry_state
from ..tracing import _state as _tracing_state
from . import wire
from .buckets import BucketGrid
from .health import Heartbeat, _env_float

__all__ = ["RemoteReplica", "WorkerCrashed", "live_workers"]

_log = logging.getLogger(__name__)

_REPO_ROOT = os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", ".."))

# every constructed handle with a possibly-live child, for the
# test-suite leak guard (mirrors server._live_servers)
_live_workers = weakref.WeakSet()

# stable worker indices for the worker.spawn.<i> fault sub-site —
# process-wide monotonic, never reused (the serving.replica.<i> rule)
_WORKER_INDEX = itertools.count()

_RESPAWN_BACKOFF_CAP_S = 30.0


def live_workers():
    """Handles whose worker process is currently alive (leak guard)."""
    out = []
    for w in list(_live_workers):
        p = w.proc
        if p is not None and p.poll() is None:
            out.append(w)
    return out


class WorkerCrashed(MXNetError):
    """The worker process died (connection drop / waitpid) with this
    request in flight. Unambiguous and typed — the router fails over;
    nothing waits on a corpse."""


class RemoteReplica:
    """One supervised out-of-process replica behind the Server contract.

    ::

        rep = serving.RemoteReplica(
            "my_models:build_resnet", name="w0",
            batch_buckets=(2, 4, 8), shape_buckets=[(3, 224, 224)],
            slo_ms=50, python_paths=["/path/to/models"])
        router = serving.Router([rep, ...], slo_ms=50).start()

    ``factory`` is ``module:function`` importable IN THE CHILD (use
    ``python_paths`` for directories outside the environment);
    ``factory_kwargs`` must be JSON-able. The grid arguments must match
    what the worker builds — the hello frame cross-checks and start()
    fails typed on drift. ``batch_timeout_ms`` (default 5) caps the
    oldest queued request's co-batching wait in the worker's server —
    out-of-process arrival streams are SPREAD by the socket pipeline,
    so the deadline-keyed close alone pins p50 at the SLO edge; pass
    ``None`` to restore it. ``metrics_port`` exposes the worker's own
    ``/metrics``+``/healthz`` (0 = ephemeral, discovered via
    :attr:`metrics_port` after start).
    """

    def __init__(self, factory: str, name: str,
                 batch_buckets: Sequence[int] = (1, 2, 4, 8, 16, 32),
                 shape_buckets=None, slo_ms: float = 100.0,
                 batch_timeout_ms: Optional[float] = 5.0,
                 dtype: str = "float32", factory_kwargs: Optional[dict] = None,
                 python_paths: Sequence[str] = (),
                 env: Optional[dict] = None, warmup: bool = True,
                 max_queue: int = 4096,
                 respawn: bool = True,
                 max_respawns: Optional[int] = None,
                 respawn_backoff_s: Optional[float] = None,
                 spawn_timeout_s: float = 180.0,
                 health_interval_s: float = 0.05,
                 metrics_port: Optional[int] = None,
                 decode_pages: Optional[int] = None,
                 page_size: int = 16,
                 len_buckets: Optional[Sequence[int]] = None,
                 max_generate_tokens: Optional[int] = None):
        if slo_ms <= 0:
            raise MXNetError(f"slo_ms must be > 0, got {slo_ms}")
        self.factory = factory
        self.factory_kwargs = dict(factory_kwargs or {})
        json.dumps(self.factory_kwargs)   # fail at construction, typed
        self.name = name
        self.decode_pages = decode_pages
        self.page_size = int(page_size)
        self.max_generate_tokens = max_generate_tokens
        if decode_pages is not None and len_buckets is None:
            from .buckets import DEFAULT_LEN_BUCKETS
            len_buckets = DEFAULT_LEN_BUCKETS
        self.grid = BucketGrid(batch_buckets, shape_buckets,
                               len_buckets=len_buckets)
        self.slo_s = slo_ms / 1e3
        if batch_timeout_ms is not None and batch_timeout_ms <= 0:
            raise MXNetError(
                f"batch_timeout_ms must be > 0 (or None for the "
                f"deadline-keyed close), got {batch_timeout_ms}")
        self.batch_timeout_ms = batch_timeout_ms
        self.dtype = dtype
        self.python_paths = [os.path.abspath(p) for p in python_paths]
        self.extra_env = dict(env or {})
        self.warmup = bool(warmup)
        self.max_queue = int(max_queue)
        self.respawn = bool(respawn)
        if max_respawns is None:
            max_respawns = int(_env_float("MXNET_WORKER_MAX_RESPAWNS", 8))
        if respawn_backoff_s is None:
            respawn_backoff_s = _env_float(
                "MXNET_WORKER_RESPAWN_BACKOFF", 0.5)
        if respawn_backoff_s <= 0:
            raise MXNetError(
                f"respawn backoff must be > 0, got {respawn_backoff_s}")
        self.max_respawns = int(max_respawns)
        self.respawn_backoff_s = float(respawn_backoff_s)
        self.spawn_timeout_s = float(spawn_timeout_s)
        self.health_interval_s = float(health_interval_s)
        self.request_metrics_port = metrics_port
        self.metrics_port: Optional[int] = None
        self.worker_index = next(_WORKER_INDEX)

        self.hb = Heartbeat()
        self._pre_dispatch = None     # Router compat; ignored (see doc)
        self.model_version = 0        # Router/controller read-only compat
        self.proc: Optional[subprocess.Popen] = None
        self._sock: Optional[socket.socket] = None
        self._writer: Optional[wire.FrameWriter] = None
        self._lock = threading.Lock()
        self._futures: dict = {}      # id -> Future
        self._gens: dict = {}         # id -> GenerateHandle (streaming)
        self._traces: dict = {}       # id -> Trace (tracing on only)
        self._registered: list = []   # tenant specs, replayed on respawn
        self._next_id = 0
        self._incarnation = 0         # bumps per successful spawn
        self._down_handled = -1       # last incarnation whose death ran
        self._running = False
        self._stopping = False
        self._respawner: Optional[threading.Thread] = None
        self.crash_count = 0          # unexpected downs (the router's
        #                               crash-trip signal)
        self.n_restarts = 0           # successful respawns
        self.n_requests = 0
        self.n_ok = 0
        self.n_errors = 0

    # -- lifecycle -----------------------------------------------------
    @property
    def is_running(self) -> bool:
        # the cheap flag, NOT proc.poll(): the router's picker reads
        # this per routed request, and a waitpid syscall per replica
        # per submit is real cost on the inline fast path. The
        # dedicated waitpid/reader threads flip _running within the
        # same signal latency; a submit racing the flip fails its
        # writer send and resolves typed through _on_down anyway.
        return self._running and self.proc is not None

    def start(self) -> "RemoteReplica":
        if self.is_running:
            raise MXNetError(f"{self.name}: worker already running")
        self._stopping = False
        self._spawn_once()
        _live_workers.add(self)
        return self

    def _spawn_cmd(self, port: int):
        cmd = [sys.executable, "-m", "mxnet_tpu.serving.worker",
               "--connect", f"127.0.0.1:{port}",
               "--factory", self.factory,
               "--factory-kwargs", json.dumps(self.factory_kwargs),
               "--name", self.name,
               "--batch-buckets",
               ",".join(str(b) for b in self.grid.batch_buckets),
               "--shape-buckets",
               json.dumps([list(s) for s in self.grid.shape_buckets]
                          if self.grid.shape_buckets else None),
               "--slo-ms", str(self.slo_s * 1e3),
               "--dtype", self.dtype,
               "--max-queue", str(self.max_queue),
               "--health-interval", str(self.health_interval_s)]
        if self.batch_timeout_ms is not None:
            cmd += ["--batch-timeout-ms", str(self.batch_timeout_ms)]
        if self.decode_pages is not None:
            cmd += ["--decode-pages", str(self.decode_pages),
                    "--page-size", str(self.page_size),
                    "--len-buckets",
                    ",".join(str(b) for b in self.grid.len_buckets)]
            if self.max_generate_tokens is not None:
                cmd += ["--max-generate-tokens",
                        str(self.max_generate_tokens)]
        for p in self.python_paths:
            cmd += ["--path", p]
        if not self.warmup:
            cmd.append("--no-warmup")
        if self.request_metrics_port is not None:
            cmd += ["--metrics-port", str(self.request_metrics_port)]
        return cmd

    def _spawn_env(self):
        env = dict(os.environ, **self.extra_env)
        paths = [_REPO_ROOT] + self.python_paths
        prev = env.get("PYTHONPATH")
        if prev:
            paths.append(prev)
        env["PYTHONPATH"] = os.pathsep.join(paths)
        # the child must not clobber the parent's exit-hook snapshot
        env.pop("MXNET_TELEMETRY_OUT", None)
        return env

    def _spawn(self, port: int) -> subprocess.Popen:
        """The exec seam — tests monkeypatch this to run a protocol-
        speaking fake in-thread; production launches the real child."""
        return subprocess.Popen(self._spawn_cmd(port),
                                env=self._spawn_env())

    def _spawn_once(self) -> None:
        """One supervised process launch: fault site, listener, exec,
        hello handshake, grid cross-check, reader+waitpid threads.
        Raises typed on any failure (the caller owns retry/backoff)."""
        if self._stopping:
            raise MXNetError(f"{self.name}: handle is stopping")
        if _fault_state.enabled:
            sub = f"worker.spawn.{self.worker_index}"
            fault.check("worker.spawn", self.name)
            if fault.has_policy(sub):    # no double-count under '*'
                fault.check(sub, self.name)
        listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        conn = None
        try:
            listener.bind(("127.0.0.1", 0))
            listener.listen(1)
            listener.settimeout(self.spawn_timeout_s)
            port = listener.getsockname()[1]
            self.proc = self._spawn(port)
            try:
                conn, _addr = listener.accept()
            except socket.timeout:
                raise MXNetError(
                    f"{self.name}: worker did not connect back within "
                    f"{self.spawn_timeout_s:g}s (build/warmup hung or "
                    "crashed; see its stderr)") from None
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            conn.settimeout(self.spawn_timeout_s)
            hello = wire.recv_frame(conn)
            if hello.get("kind") != "hello":
                raise MXNetError(
                    f"{self.name}: expected hello, got "
                    f"{hello.get('kind')!r}")
            got_batch = tuple(hello["batch_buckets"])
            got_shapes = (tuple(tuple(s) for s in hello["shape_buckets"])
                          if hello["shape_buckets"] else None)
            if got_batch != self.grid.batch_buckets or \
                    got_shapes != self.grid.shape_buckets:
                raise MXNetError(
                    f"{self.name}: worker grid {got_batch}/{got_shapes} "
                    f"does not match the handle's "
                    f"{self.grid.batch_buckets}/{self.grid.shape_buckets}"
                    " — matched-bucket bit-identity would not hold")
            if self.decode_pages is not None:
                got_lens = (tuple(hello.get("len_buckets"))
                            if hello.get("len_buckets") else None)
                if got_lens != self.grid.len_buckets:
                    raise MXNetError(
                        f"{self.name}: worker len buckets {got_lens} do "
                        f"not match the handle's {self.grid.len_buckets}"
                        " — generate bit-identity would not hold")
            conn.settimeout(None)
            self.metrics_port = hello.get("metrics_port")
            writer = wire.FrameWriter(conn, name=f"{self.name}-writer")
            with self._lock:
                self._sock = conn
                self._writer = writer
                self._incarnation += 1
                inc = self._incarnation
                self._running = True
            self.hb.touch()
            threading.Thread(
                target=self._reader_loop, args=(conn, inc),
                name=f"{self.name}-reader", daemon=True).start()
            threading.Thread(
                target=self._waitpid_loop, args=(self.proc, inc),
                name=f"{self.name}-waitpid", daemon=True).start()
            # replay tenant registrations into the fresh process: the
            # frame loop is sequential, so any later submit carrying
            # model= lands after its tenant exists (no ack wait needed)
            with self._lock:
                specs = list(self._registered)
            for spec in specs:
                writer.send(dict(spec, kind="register_model"))
        except BaseException:
            if conn is not None:
                try:
                    conn.close()
                except OSError:
                    pass
            p = self.proc
            if p is not None and p.poll() is None:
                p.kill()
                p.wait()
            self._running = False
            raise
        finally:
            listener.close()

    # -- the two unambiguous failure signals ---------------------------
    def _reader_loop(self, conn: socket.socket, inc: int) -> None:
        try:
            rf = wire.reader(conn)      # buffered: result/health frames
            while True:                 # stream back-to-back under load
                frame = wire.recv_frame(rf)
                kind = frame["kind"]
                if kind == "result":
                    self._on_result(frame)
                elif kind == "token":
                    with self._lock:
                        handle = self._gens.get(frame["id"])
                    if handle is not None:
                        handle._push(int(frame["token"]))
                elif kind == "gen_done":
                    self._on_gen_done(frame)
                elif kind == "registered":
                    self._on_registered(frame)
                elif kind == "health":
                    # replay the worker scheduler's heartbeat age into
                    # this handle's beacon: the router's hung-dispatch
                    # sweep reads it across the process boundary
                    self.hb._t = time.monotonic() - max(
                        float(frame.get("age", 0.0)), 0.0)
        except wire.FrameError as e:
            # EOF (clean or half-written frame) or corrupt stream —
            # either way the connection is dead and partial bytes were
            # DISCARDED, not parsed
            self._on_down(inc, f"connection lost: {e}")
        except OSError as e:
            self._on_down(inc, f"connection error: {e}")

    def _waitpid_loop(self, proc: subprocess.Popen, inc: int) -> None:
        rc = proc.wait()
        if rc < 0:
            why = f"worker process killed by signal {-rc}"
        else:
            why = f"worker process exited rc={rc}"
        self._on_down(inc, why)

    def _close_and_fail(self, sock, writer, pending,
                        exc: MXNetError) -> None:
        """Shared teardown tail for _on_down/stop: close the connection
        halves, fail every pending future typed (first resolution
        wins — a future the worker already resolved is left alone)."""
        if writer is not None:
            writer.close(flush=False, timeout=1.0)
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
        for fut in pending.values():
            if fut.set_running_or_notify_cancel():
                try:
                    fut.set_exception(exc)
                except Exception:   # noqa: BLE001 - already resolved
                    pass

    def _on_down(self, inc: int, why: str) -> None:
        """Connection drop / waitpid for incarnation ``inc``: fail every
        in-flight future typed, bump ``crash_count`` (unless this is a
        requested stop), schedule the respawner. First signal wins."""
        with self._lock:
            if inc != self._incarnation or self._down_handled >= inc:
                return                  # stale or already handled
            self._down_handled = inc
            self._running = False
            pending, self._futures = self._futures, {}
            gens, self._gens = self._gens, {}
            ptraces, self._traces = self._traces, {}
            sock, self._sock = self._sock, None
            writer, self._writer = self._writer, None
            stopping = self._stopping
        for tr in ptraces.values():
            # annotate BEFORE the futures fail: the finish-callbacks
            # seal these traces, and the crash is the explanation
            tr.note(f"worker {self.name} crashed: {why}")
        crashed = WorkerCrashed(
            f"worker {self.name}: {why}; "
            f"{len(pending) + len(gens)} request(s) were in flight")
        self._close_and_fail(sock, writer, pending, crashed)
        # streaming generates fail typed too — and are NEVER replayed
        # (the caller may have consumed half the completion already)
        self._fail_gens(gens, crashed)
        if stopping:
            return
        self.crash_count += 1
        self.n_errors += len(pending) + len(gens)
        if _tracing_state.enabled:
            tracing.record_event("crash", replica=self.name, why=why,
                                 inflight=len(pending))
        if self.respawn and self.n_restarts < self.max_respawns:
            t = threading.Thread(target=self._respawn_loop,
                                 name=f"{self.name}-respawn",
                                 daemon=True)
            self._respawner = t
            t.start()

    def _respawn_loop(self) -> None:
        """Exponential-backoff respawn until one spawn succeeds or the
        budget is spent — ``max_respawns`` bounds FAILED attempts too
        (a permanently-broken spawn path — deleted factory module, disk
        full — must reach a terminal state, not churn a failed exec
        every 30 s forever). Each failed attempt doubles the delay
        (capped); warm start through the persistent compile cache keeps
        the success path cheap."""
        attempt = 0
        while not self._stopping and \
                self.n_restarts < self.max_respawns and \
                attempt < self.max_respawns:
            delay = min(self.respawn_backoff_s * (2.0 ** attempt),
                        _RESPAWN_BACKOFF_CAP_S)
            time.sleep(delay)
            if self._stopping:
                return
            try:
                self._spawn_once()
            except Exception as e:  # noqa: BLE001 - retried w/ backoff
                attempt += 1
                _log.warning("%s: respawn attempt %d failed (%s); "
                             "backing off", self.name, attempt, e)
                if _telemetry_state.enabled:
                    telemetry.record_worker_restart(self.name,
                                                    outcome="failed")
                if _tracing_state.enabled:
                    tracing.record_event("respawn", replica=self.name,
                                         outcome="failed",
                                         attempt=attempt)
                continue
            if self._stopping:
                # stop() ran while we were spawning: this fresh child
                # must not outlive the handle (stop()'s sweep may have
                # already passed it by)
                p = self.proc
                if p is not None and p.poll() is None:
                    p.kill()
                    p.wait()
                self._running = False
                return
            self.n_restarts += 1
            _log.info("%s: worker respawned (pid %s, restart %d)",
                      self.name, self.proc.pid, self.n_restarts)
            if _telemetry_state.enabled:
                telemetry.record_worker_restart(self.name)
            if _tracing_state.enabled:
                tracing.record_event("respawn", replica=self.name,
                                     outcome="ok",
                                     restarts=self.n_restarts)
            return
        if not self._stopping and attempt >= self.max_respawns:
            _log.error("%s: respawn budget spent (%d failed attempts); "
                       "giving up — the replica stays down",
                       self.name, attempt)

    # -- tenants -------------------------------------------------------
    def register_model(self, name: str, factory,
                       slo_class: str = "standard", priority: int = 0,
                       weight: float = 1.0,
                       slo_ms: Optional[float] = None,
                       rate_limit: Optional[float] = None,
                       burst: Optional[float] = None,
                       factory_kwargs: Optional[dict] = None,
                       timeout: float = 60.0) -> None:
        """Register tenant ``name`` on the worker process. ``factory``
        must be an importable ``module:function`` spec string — the
        same spec-not-closure contract as this handle's own
        ``--factory``, because a live block cannot cross an exec
        boundary (a callable raises typed). Blocks until the worker
        acks (its warmup/engine build is inside that wait); the spec is
        replayed automatically into every respawned incarnation."""
        if callable(factory):
            raise MXNetError(
                f"{self.name}: register_model on an out-of-process "
                "worker needs a 'module:function' factory spec, not a "
                "callable (a live block cannot cross the exec boundary)")
        spec = {"name": str(name), "factory": str(factory),
                "factory_kwargs": dict(factory_kwargs or {}),
                "paths": list(self.python_paths),
                "slo_class": str(slo_class), "priority": int(priority),
                "weight": float(weight)}
        if slo_ms is not None:
            spec["slo_ms"] = float(slo_ms)
        if rate_limit is not None:
            spec["rate_limit"] = float(rate_limit)
        if burst is not None:
            spec["burst"] = float(burst)
        json.dumps(spec)                # fail at call time, typed
        fut = Future()
        with self._lock:
            if not self._running or self._writer is None:
                raise MXNetError(
                    f"{self.name}: worker process is not running")
            self._next_id += 1
            req_id = self._next_id
            self._futures[req_id] = fut
            writer = self._writer
            inc = self._incarnation
        try:
            writer.send(dict(spec, kind="register_model", id=req_id))
        except (OSError, wire.FrameError) as e:
            self._on_down(inc, f"send failed: {e}")
            raise MXNetError(
                f"{self.name}: worker connection lost at register: {e}"
            ) from e
        from concurrent.futures import TimeoutError as _FutTimeout

        try:
            fut.result(timeout)
        except _FutTimeout:
            with self._lock:
                self._futures.pop(req_id, None)
            raise MXNetError(
                f"{self.name}: register_model({name!r}) did not ack "
                f"within {timeout:g}s") from None
        with self._lock:
            self._registered.append(spec)

    def _on_registered(self, frame: dict) -> None:
        with self._lock:
            fut = self._futures.pop(frame.get("id"), None)
        if fut is None or not fut.set_running_or_notify_cancel():
            return          # respawn replay ack (no waiter) or late
        if frame.get("ok"):
            fut.set_result(frame.get("name"))
        else:
            fut.set_exception(wire.decode_error(
                frame.get("etype", "mxnet_error"),
                frame.get("error", "register_model failed")))

    # -- dispatch ------------------------------------------------------
    def submit(self, sample, deadline_ms: Optional[float] = None,
               model: Optional[str] = None,
               priority: Optional[int] = None) -> Future:
        """Same contract as :meth:`Server.submit`, across the process
        boundary. Synchronous typed raise when the worker is down (the
        router reads that + ``is_running`` as replica death and fails
        over); otherwise a Future that ALWAYS resolves — with the
        worker's result/typed error, or :class:`WorkerCrashed` the
        instant the process dies."""
        arr = sample.asnumpy() if hasattr(sample, "asnumpy") \
            else np.asarray(sample)
        arr = np.ascontiguousarray(arr, dtype=self.dtype)
        self.grid.bucket_shape(arr.shape)   # typed sync, not mid-batch
        fut = Future()
        with self._lock:
            if not self._running or self._writer is None:
                self.n_requests += 1
                raise MXNetError(
                    f"{self.name}: worker process is not running")
            self._next_id += 1
            req_id = self._next_id
            self._futures[req_id] = fut
            writer = self._writer
            inc = self._incarnation
        self.n_requests += 1
        frame = {"kind": "submit", "id": req_id, "sample": arr}
        if deadline_ms is not None:
            frame["deadline_ms"] = float(deadline_ms)
        if model is not None:       # absent field = default tenant
            frame["model"] = str(model)
        if priority is not None:
            frame["priority"] = int(priority)
        if _tracing_state.enabled:
            # ship the ambient span context in the frame header — the
            # worker adopts it, and its spans ride the result frame back
            amb = tracing.ambient()
            if amb is not None:
                frame["trace"] = amb[0].wire(amb[1])
                with self._lock:
                    self._traces[req_id] = amb[0]
        try:
            # coalescing writer: the caller (the router's single
            # dispatch thread) enqueues and returns — it never blocks
            # on this worker's socket
            writer.send(frame)
        except (OSError, wire.FrameError) as e:
            self._on_down(inc, f"send failed: {e}")
            raise MXNetError(
                f"{self.name}: worker connection lost at submit: {e}"
            ) from e
        return fut

    def _on_result(self, frame: dict) -> None:
        with self._lock:
            fut = self._futures.pop(frame["id"], None)
            tr = self._traces.pop(frame["id"], None)
        if fut is None:
            return          # late result for a crashed-and-failed id
        if tr is not None:
            # adopt the worker's piggybacked spans BEFORE resolving the
            # future: finish-callbacks seal the trace at resolution.
            # trace_ts = the worker's send timestamp (same-host wall
            # clock) -> the wire.return span is the socket leg home.
            tr.merge(frame.get("spans"))
            sent = frame.get("trace_ts")
            if isinstance(sent, (int, float)):
                tr.add_raw("wire.return", ts=int(sent),
                           dur=tracing.now_us() - int(sent),
                           replica=self.name)
        if not fut.set_running_or_notify_cancel():
            return
        if frame.get("ok"):
            self.n_ok += 1
            fut.set_result(frame.get("payload"))
        else:
            self.n_errors += 1
            fut.set_exception(wire.decode_error(
                frame.get("etype", "mxnet_error"),
                frame.get("error", "worker error")))

    # -- generate (paged-KV streaming) ---------------------------------
    def submit_generate(self, prompt, max_new_tokens: int,
                        deadline_ms: Optional[float] = None,
                        on_token=None, model: Optional[str] = None,
                        priority: Optional[int] = None):
        """Same contract as :meth:`Server.submit_generate`, across the
        process boundary: a :class:`~.server.GenerateHandle` whose
        tokens stream back as ``token`` frames (``on_token`` fires on
        this handle's reader thread) and whose future resolves from the
        final ``gen_done`` frame — with the worker's full token array
        or typed error, or :class:`WorkerCrashed` the instant the
        process dies mid-stream (never replayed: the caller may have
        consumed half the completion)."""
        from .server import GenerateHandle

        if self.grid.len_buckets is None:
            raise MXNetError(
                f"{self.name}: worker was not configured for generate "
                "(construct with decode_pages=)")
        arr = prompt.asnumpy() if hasattr(prompt, "asnumpy") \
            else np.asarray(prompt)
        arr = np.ascontiguousarray(arr, dtype=np.int32).reshape(-1)
        self.grid.prefill_bucket(arr.size)   # typed sync, not mid-serve
        handle = GenerateHandle(on_token)
        with self._lock:
            if not self._running or self._writer is None:
                self.n_requests += 1
                raise MXNetError(
                    f"{self.name}: worker process is not running")
            self._next_id += 1
            req_id = self._next_id
            self._gens[req_id] = handle
            writer = self._writer
            inc = self._incarnation
        self.n_requests += 1
        frame = {"kind": "generate", "id": req_id, "prompt": arr,
                 "max_new_tokens": int(max_new_tokens)}
        if deadline_ms is not None:
            frame["deadline_ms"] = float(deadline_ms)
        if model is not None:       # absent field = default tenant
            frame["model"] = str(model)
        if priority is not None:
            frame["priority"] = int(priority)
        if _tracing_state.enabled:
            amb = tracing.ambient()
            if amb is not None:
                frame["trace"] = amb[0].wire(amb[1])
                with self._lock:
                    self._traces[req_id] = amb[0]
        try:
            writer.send(frame)
        except (OSError, wire.FrameError) as e:
            self._on_down(inc, f"send failed: {e}")
            raise MXNetError(
                f"{self.name}: worker connection lost at submit: {e}"
            ) from e
        return handle

    def _on_gen_done(self, frame: dict) -> None:
        with self._lock:
            handle = self._gens.pop(frame["id"], None)
            tr = self._traces.pop(frame["id"], None)
        if handle is None:
            return          # late finale for a crashed-and-failed id
        if tr is not None:
            tr.merge(frame.get("spans"))
            sent = frame.get("trace_ts")
            if isinstance(sent, (int, float)):
                tr.add_raw("wire.return", ts=int(sent),
                           dur=tracing.now_us() - int(sent),
                           replica=self.name)
        if frame.get("ok"):
            payload = np.asarray(frame.get("payload"), dtype=np.int32)
            # token frames are best-effort; the finale is authoritative
            # — push any tail the stream missed before resolving
            for i in range(len(handle.tokens()), payload.size):
                handle._push(int(payload[i]))
            self.n_ok += 1
            try:
                handle.future.set_result(payload)
            except Exception:   # noqa: BLE001 - already resolved
                pass
        else:
            self.n_errors += 1
            try:
                handle.future.set_exception(wire.decode_error(
                    frame.get("etype", "mxnet_error"),
                    frame.get("error", "worker error")))
            except Exception:   # noqa: BLE001 - already resolved
                pass
        handle._seal()

    @staticmethod
    def _fail_gens(gens: dict, exc: MXNetError) -> None:
        """Crash/stop tail for streaming handles: resolve typed (first
        resolution wins) and wake every next_token waiter."""
        for h in gens.values():
            if h.future.set_running_or_notify_cancel():
                try:
                    h.future.set_exception(exc)
                except Exception:   # noqa: BLE001 - already resolved
                    pass
            h._seal()

    # -- stop ----------------------------------------------------------
    def stop(self, drain: bool = True,
             timeout: Optional[float] = None) -> None:
        """Stop the worker process: polite stop frame (honoring
        ``drain``), bounded wait, SIGTERM -> SIGKILL escalation. Also
        disarms the respawner — a stop is not a crash. Safe to call
        twice and after a crash."""
        self._stopping = True
        budget = 30.0 if timeout is None else max(float(timeout), 0.1)
        deadline = time.monotonic() + budget
        with self._lock:
            writer = self._writer
        proc = self.proc
        if writer is not None and self._running:
            try:
                writer.send({"kind": "stop", "drain": bool(drain),
                             "timeout": budget})
            except (OSError, wire.FrameError):
                pass
        if proc is not None:
            try:
                proc.wait(max(deadline - time.monotonic(), 0.1))
            except subprocess.TimeoutExpired:
                proc.terminate()
                try:
                    proc.wait(5)
                except subprocess.TimeoutExpired:
                    proc.kill()
                    proc.wait()
        # waitpid/reader threads observe the death and fail leftovers;
        # do it inline too in case they already exited (double-stop)
        with self._lock:
            pending, self._futures = self._futures, {}
            gens, self._gens = self._gens, {}
            self._running = False
            s2, self._sock = self._sock, None
            w2, self._writer = self._writer, None
        stopped = MXNetError(
            f"{self.name}: worker stopped before this request "
            "resolved")
        self._close_and_fail(s2, w2, pending, stopped)
        self._fail_gens(gens, stopped)
        # a respawn racing this stop either aborts at its _stopping
        # checks or kills its own fresh child; join it briefly, then
        # sweep any process that slipped through the window — a stop()
        # must never leak a live worker past the leak guard
        t = self._respawner
        if t is not None and t is not threading.current_thread() \
                and t.is_alive():
            t.join(5.0)
        p = self.proc
        if p is not None and p.poll() is None:
            p.kill()
            p.wait()
        _live_workers.discard(self)

    def __enter__(self) -> "RemoteReplica":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop(drain=not any(exc))

    # -- introspection -------------------------------------------------
    @property
    def metrics_url(self) -> Optional[str]:
        if self.metrics_port is None:
            return None
        return f"http://127.0.0.1:{self.metrics_port}/metrics"

    def stats(self) -> dict:
        with self._lock:
            inflight = len(self._futures) + len(self._gens)
        p = self.proc
        return {"name": self.name, "pid": p.pid if p else None,
                "running": self.is_running, "inflight": inflight,
                "requests": self.n_requests, "ok": self.n_ok,
                "errors": self.n_errors, "crashes": self.crash_count,
                "restarts": self.n_restarts,
                "worker_index": self.worker_index,
                "metrics_port": self.metrics_port}
