"""``mx.serving.Router`` — overload-safe multi-replica dispatch.

One :class:`~.server.Server` replica batches well (PR 6) but has no
failure story: a wedged or crashing replica takes its queue down with
it, and under overload it queues until every deadline blows. The router
is the serving analogue of the elastic training runtime (PR 8): scale
*as* a robustness layer. It fronts N ``Server`` replicas (one per
device or device group) behind the same ``submit() -> Future`` contract
and owns four concerns the single server cannot:

* **Least-loaded dispatch.** Each request is forwarded to the healthy
  replica with the fewest outstanding router-forwarded requests, so a
  slow replica sheds load to its siblings instead of growing a queue.

* **Health tracking.** A :class:`~.health.CircuitBreaker` per replica:
  ``MXNET_SERVING_BREAKER_FAILURES`` consecutive dispatch failures trip
  it OPEN, and so does a *hung dispatch* — the replica scheduler's
  heartbeat (touched once per loop iteration) going silent past
  ``MXNET_SERVING_DISPATCH_TIMEOUT`` while router requests are in
  flight there (a scheduler patiently filling a batch keeps touching;
  a wedged model dispatch does not). After a cooldown it goes HALF_OPEN
  and exactly one live request is routed through it as a probe —
  success re-admits the replica, failure re-opens it with a doubled
  cooldown. Probes take priority over least-loaded choice so recovery
  is detected under any traffic level.

* **Failover — no future is ever lost.** A failed or hung replica's
  in-flight requests are re-submitted to healthy replicas under a
  bounded retry budget (``MXNET_SERVING_RETRY_BUDGET`` extra
  dispatches, default 2). Every future submitted to the router
  resolves: with a result, or with a typed error
  (:class:`ServerOverloaded` at admission / queued past deadline,
  :class:`FailoverExhausted` when the budget is spent,
  :class:`MXNetError` on stop without drain). The first resolution
  wins; a late result from a replica already declared hung is dropped.

* **Admission control.** The router queue is bounded (``max_queue``)
  and sheds by *predicted deadline miss*: completion timestamps give a
  service-rate estimate, and a request whose predicted queue wait
  exceeds its own deadline is rejected **synchronously** with
  :class:`ServerOverloaded` — at 2x sustainable load the router keeps
  serving at capacity with bounded latency instead of queueing every
  request into a blown deadline (``tools/serving_bench.py`` overload
  stage gates goodput >= 90% of measured capacity).

A scheduler-liveness watchdog (the PR-8 heartbeat pattern, in-process
via :class:`~.health.Heartbeat`) covers the router's own dispatcher
thread: if the loop goes silent past ``MXNET_SERVING_WATCHDOG_TIMEOUT``
the monitor fails every queued future loudly and stops admission — a
wedged dispatcher must not turn into a queue nobody drains.

Fault sites: ``serving.route`` fires on every routing decision (a
transient routing fault costs one unit of the request's retry budget,
not replica health); ``serving.replica`` (and the per-instance
``serving.replica.<index>`` sub-sites) fire inside a replica's dispatch
— an injected fault there is a replica failure, a ``latency:S`` policy
past the dispatch timeout is a hang. ``tools/chaos_check.py``'s serving
gate kills one replica mid-traffic this way and asserts zero lost
futures, survivor bit-identity, and half-open re-admission.

Telemetry: ``mxnet_serving_replica_healthy{replica}`` (1 closed /
0.5 half-open / 0 open), ``mxnet_serving_breaker_transitions_total``,
``mxnet_serving_shed_total{reason}``,
``mxnet_serving_failover_total{replica}``,
``mxnet_serving_route_retry_total{reason}``,
``mxnet_serving_router_queue_depth``,
``mxnet_serving_router_queue_wait_seconds``.
"""
from __future__ import annotations

import logging
import threading
import time
import weakref
from collections import deque
from concurrent.futures import Future
from typing import List, Optional, Sequence

import numpy as np

from .. import fault, telemetry, tracing
from ..base import MXNetError
from ..fault import _state as _fault_state
from ..telemetry import _state as _telemetry_state
from ..tracing import _state as _tracing_state
from .health import (
    CLOSED,
    HALF_OPEN,
    OPEN,
    CircuitBreaker,
    Heartbeat,
    _env_float,
)
from .kvcache import CacheFull
from .server import DEFAULT_MODEL, Server, TenantThrottled

__all__ = ["Router", "ServerOverloaded", "FailoverExhausted",
           "ReplicaFault", "live_routers"]

_log = logging.getLogger(__name__)

# every running router, for the test-suite leak guard (mirrors
# server._live_servers)
_live_routers = weakref.WeakSet()


def live_routers():
    """Routers whose dispatcher thread is currently running."""
    return [r for r in list(_live_routers) if r.is_running]


class ServerOverloaded(MXNetError):
    """Typed admission-control rejection: the router queue is full, the
    predicted queue wait exceeds the request's deadline, or the request's
    deadline expired while it was still queued. Synchronous at
    ``submit`` whenever the overload is knowable there — never a hung
    future."""


class FailoverExhausted(MXNetError):
    """A request failed on every replica it was routed to and its retry
    budget (``MXNET_SERVING_RETRY_BUDGET``) is spent. Chained to the
    last underlying replica error."""


class ReplicaFault(MXNetError):
    """An injected ``serving.replica`` fault: the replica 'crashed' on
    this dispatch. Deliberately NOT retry-transient — a killed replica
    must fail over at the router, not retry locally inside the corpse."""


_HEALTH_VALUE = {CLOSED: 1.0, HALF_OPEN: 0.5, OPEN: 0.0}


class _RouteReq:
    """One routed request: the router-facing future plus retry state.
    ``resolve_*`` are first-wins (a failover copy and a late replica
    result may race) and always leave the future resolved."""

    __slots__ = ("sample", "future", "t_enqueue", "deadline", "attempts",
                 "started", "_lock", "trace", "span", "own_trace",
                 "model", "priority")

    def __init__(self, sample, deadline_s: float, model=None,
                 priority=None):
        # tenant fields ride the request through requeues and
        # failovers: a retried dispatch must land in the SAME tenant's
        # queue on the next replica
        self.model = model
        self.priority = priority
        self.sample = sample
        self.future = Future()
        self.t_enqueue = time.perf_counter()
        self.deadline = self.t_enqueue + deadline_s
        self.attempts = 0          # dispatch attempts so far
        self.started = False       # set_running_or_notify_cancel done
        self._lock = threading.Lock()
        # tracing (MXNET_TRACING=1): the request's Trace, its currently
        # open router.queue span, and whether this router minted the
        # trace (an ingress that handed it in finishes it instead)
        self.trace = None
        self.span = None
        self.own_trace = False

    def begin(self) -> bool:
        """First dispatch: flip the future to RUNNING; False if the
        caller already cancelled it."""
        if self.started:
            return True
        if not self.future.set_running_or_notify_cancel():
            return False
        self.started = True
        return True

    def resolve_result(self, result) -> bool:
        with self._lock:
            if self.future.done():
                return False
            if not self.started:
                if not self.future.set_running_or_notify_cancel():
                    return False
                self.started = True
            self.future.set_result(result)
            return True

    def resolve_exc(self, exc: BaseException) -> bool:
        with self._lock:
            if self.future.done():
                return False
            if not self.started:
                if not self.future.set_running_or_notify_cancel():
                    return False
                self.started = True
            self.future.set_exception(exc)
            return True


class _Flight:
    """One request currently forwarded to one replica. Holds the
    :class:`_Replica` OBJECT, not a position in the replica list — the
    list is mutable now (``add_replica``/``remove_replica``) and a
    positional index would dangle the moment the fleet changes under an
    outstanding dispatch."""

    __slots__ = ("req", "rep", "t_sent", "rfut", "probe", "span")

    def __init__(self, req, rep, t_sent, probe):
        self.req = req
        self.rep = rep
        self.t_sent = t_sent
        self.rfut = None
        self.probe = probe
        self.span = None      # router.attempt span (tracing on)


class _Replica:
    """Router-side state for one managed Server replica. ``index`` is a
    stable id assigned at admission (monotonic, never reused), not a
    list position."""

    __slots__ = ("server", "index", "breaker", "inflight", "n_ok",
                 "n_failed", "last_state", "draining", "crashes_seen")

    def __init__(self, server: Server, index: int,
                 failure_threshold, cooldown_s):
        self.server = server
        self.index = index
        self.breaker = CircuitBreaker(
            server.name, failure_threshold=failure_threshold,
            cooldown_s=cooldown_s)
        self.inflight = 0          # router-forwarded, not yet resolved
        self.n_ok = 0
        self.n_failed = 0
        self.last_state = CLOSED   # for transition counting
        self.draining = False      # remove_replica in progress: no new
        #                            dispatches, in-flight ones finish
        # last RemoteReplica.crash_count this router turned into a
        # breaker trip — seeded from the server's CURRENT count, not 0:
        # a worker with prior crash history re-admitted via add_replica
        # (or fronted by a new Router) must not trip its fresh breaker
        # for crashes that predate this membership
        self.crashes_seen = getattr(server, "crash_count", 0)


class Router:
    """Front N ``Server`` replicas behind one ``submit() -> Future``.

    ::

        reps = [serving.Server(build_net(), name=f"r{i}", ...)
                for i in range(n)]
        router = serving.Router(reps, slo_ms=50).start()
        fut = router.submit(sample)          # same contract as Server
        out = fut.result()                   # result or typed error
        router.stop()

    Replicas must share one bucket grid (same batch and shape buckets):
    responses must be bit-identical whichever replica serves them, and
    that only holds at matched buckets. ``start()`` starts replicas
    that are not already running; ``stop()`` stops every replica
    (pass ``stop_replicas=False`` to leave them serving).

    A replica may be an in-process :class:`Server` or an out-of-process
    :class:`~.remote.RemoteReplica` (same dispatch contract) — breakers,
    hung-dispatch detection, failover and drain apply identically, and
    a remote replica's ``crash_count`` (connection drop / ``waitpid``)
    trips its breaker immediately: process death is unambiguous,
    unlike a slow dispatch.
    """

    def __init__(self, replicas: Sequence[Server],
                 slo_ms: Optional[float] = None,
                 max_queue: int = 4096,
                 retry_budget: Optional[int] = None,
                 dispatch_timeout_s: Optional[float] = None,
                 watchdog_timeout_s: Optional[float] = None,
                 name: Optional[str] = None):
        replicas = list(replicas)
        if not replicas:
            raise MXNetError("Router needs at least one Server replica")
        g0 = replicas[0].grid
        for s in replicas[1:]:
            if s.grid.batch_buckets != g0.batch_buckets or \
                    s.grid.shape_buckets != g0.shape_buckets:
                raise MXNetError(
                    f"replica {s.name} has a different bucket grid than "
                    f"{replicas[0].name} — replicas must share one grid "
                    "(matched-bucket bit-identity)")
        names = [s.name for s in replicas]
        if len(set(names)) != len(names):
            raise MXNetError(f"replica names must be unique, got {names}")
        self._next_index = len(replicas)   # stable replica ids, never reused
        if max_queue < 1:
            raise MXNetError(f"max_queue must be >= 1, got {max_queue}")
        if retry_budget is None:
            retry_budget = int(_env_float("MXNET_SERVING_RETRY_BUDGET", 2))
        if retry_budget < 0:
            raise MXNetError(
                f"retry_budget must be >= 0, got {retry_budget}")
        if dispatch_timeout_s is None:
            dispatch_timeout_s = _env_float(
                "MXNET_SERVING_DISPATCH_TIMEOUT", 30.0)
        if dispatch_timeout_s < 0.2:
            # an idle replica scheduler touches its heartbeat every
            # <=0.1 s wait tick; a timeout inside that granularity
            # would declare healthy replicas hung
            raise MXNetError(
                "dispatch timeout must be >= 0.2 s (scheduler "
                f"heartbeat granularity), got {dispatch_timeout_s}")
        if watchdog_timeout_s is None:
            watchdog_timeout_s = _env_float(
                "MXNET_SERVING_WATCHDOG_TIMEOUT", 5.0)
        if watchdog_timeout_s <= 0:
            raise MXNetError(
                f"watchdog timeout must be > 0, got {watchdog_timeout_s}")
        self.name = name or f"router_{id(self):x}"
        self.grid = g0
        self.slo_s = (slo_ms / 1e3 if slo_ms is not None
                      else replicas[0].slo_s)
        if self.slo_s <= 0:
            raise MXNetError(f"slo_ms must be > 0, got {slo_ms}")
        self.max_queue = int(max_queue)
        self.retry_budget = int(retry_budget)
        self.dispatch_timeout_s = float(dispatch_timeout_s)
        self.watchdog_timeout_s = float(watchdog_timeout_s)
        # copy-on-write: fleet changes REPLACE the list (atomic store
        # under the GIL), so dispatcher/monitor threads iterating a
        # captured snapshot never see a half-mutated fleet
        self._replicas: List[_Replica] = [
            _Replica(s, i, None, None) for i, s in enumerate(replicas)]
        # serializes fleet admin (add/remove/rolling upgrade) — the
        # dispatch path never takes it
        self._admin_lock = threading.Lock()
        # tenant registry: name -> registration spec, so every replica
        # (including ones admitted later) serves the same model set and
        # submit() can reject an unknown tenant synchronously instead
        # of refuse-spinning it against the fleet
        self._models: dict = {}

        self._cond = threading.Condition()
        self._queue: deque = deque()
        self._flights: dict = {}            # id(flight) -> _Flight
        self._n_inflight = 0
        self._done_ts: deque = deque(maxlen=64)   # completion timestamps
        self._accepting = False
        self._running = False
        self._wedged = False
        self._routing: Optional[_RouteReq] = None   # popped, in _route
        self._thread: Optional[threading.Thread] = None
        self._monitor: Optional[threading.Thread] = None
        self._monitor_stop = threading.Event()
        self.hb = Heartbeat()
        # always-on light counters (telemetry has the full story)
        self.n_requests = 0
        self.n_shed = 0
        self.n_failovers = 0
        self.n_ok = 0
        self.n_errors = 0

    @property
    def _shed_arm_pending(self) -> int:
        # predicted-wait shedding arms only past this backlog (queued +
        # in flight): below a couple of full fleet batches the observed
        # completion rate measures demand, not capacity, and a burst
        # into an idle fleet would shed against a spuriously low
        # estimate. Backlog counts IN-FLIGHT too — under overload the
        # requests pile up in the replica queues, not the router's.
        # A property because the fleet is elastic now: the threshold
        # tracks the CURRENT replica count.
        return max(32, 2 * self.grid.max_batch * len(self._replicas))

    # -- replica fault plumbing ----------------------------------------
    def _replica_fault_hook(self, r: _Replica):
        """The ``serving.replica`` injection point, run INSIDE the
        replica's scheduler thread per dispatched batch. An injected
        fault is wrapped :class:`ReplicaFault` (non-transient: the
        replica's own ``serving.dispatch`` retry must NOT resurrect a
        killed replica — failover at the router is the recovery path);
        a ``latency:S`` policy sleeps here, which is exactly a hung
        dispatch."""
        name, idx = r.server.name, r.index

        def hook(sig):
            if not _fault_state.enabled:
                return
            sub = f"serving.replica.{idx}"
            try:
                fault.check("serving.replica", f"{name} batch={sig}")
                if fault.has_policy(sub):   # no double-count under '*'
                    fault.check(sub, f"{name} batch={sig}")
            except fault.FaultInjected as e:
                raise ReplicaFault(
                    f"replica {name} (index {idx}) failed: {e}") from e
        return hook

    # -- lifecycle -----------------------------------------------------
    @property
    def is_running(self) -> bool:
        return self._running or (self._thread is not None
                                 and self._thread.is_alive())

    def start(self) -> "Router":
        if self.is_running:
            raise MXNetError(f"{self.name}: already running")
        to_start = []
        for r in self._replicas:
            # hooks live only while the router does: an orphaned hook on
            # a server kept serving standalone would raise ReplicaFault
            # (deliberately non-transient) with no failover layer left
            r.server._pre_dispatch = self._replica_fault_hook(r)
            if not r.server.is_running:
                to_start.append(r.server)
        if len(to_start) == 1:
            to_start[0].start()
        elif to_start:
            # warm replicas CONCURRENTLY: Server.start() AOT-compiles the
            # whole bucket grid, and N replicas of one architecture used
            # to pay that serially, N times over. Grid compiles now route
            # through the compilation service's in-process executable
            # table (single-flight per lowered program), so the first
            # replica to lower a bucket compiles it and the other N-1
            # warm threads block briefly and share the executable —
            # replica fleet warmup costs one compile set + (N-1) cheap
            # traces, wall-clocked across a thread pool
            from concurrent.futures import ThreadPoolExecutor

            try:
                with ThreadPoolExecutor(
                        max_workers=min(8, len(to_start)),
                        thread_name_prefix=f"{self.name}-warm") as pool:
                    # list() re-raises the first failed replica start
                    list(pool.map(lambda s: s.start(), to_start))
            except BaseException:
                # one replica failed mid-fleet-start: the pool already
                # launched the others — stop every server THIS call
                # started and drop the hooks, or they would keep serving
                # standalone with a ReplicaFault hook and no failover
                # layer above it
                for r in self._replicas:
                    r.server._pre_dispatch = None
                for s in to_start:
                    if s.is_running:
                        try:
                            s.stop(drain=False, timeout=5)
                        except Exception:   # noqa: BLE001 - best effort
                            pass
                raise
        self._accepting = True
        self._running = True
        self._wedged = False
        self.hb.touch()
        self._thread = threading.Thread(
            target=self._dispatch_loop, name=self.name, daemon=True)
        self._thread.start()
        self._monitor_stop.clear()
        self._monitor = threading.Thread(
            target=self._monitor_loop, name=f"{self.name}-monitor",
            daemon=True)
        self._monitor.start()
        _live_routers.add(self)
        return self

    def stop(self, drain: bool = True, timeout: Optional[float] = None,
             stop_replicas: bool = True) -> None:
        """Stop the router. ``drain=True`` (default) routes every queued
        request and waits (bounded by ``timeout``) for in-flight ones;
        ``drain=False`` fails queued futures with :class:`MXNetError`
        (in-flight ones still resolve through their replicas)."""
        deadline = (time.monotonic() + timeout) if timeout is not None \
            else None
        with self._cond:
            self._accepting = False
            if not drain:
                pending, self._queue = list(self._queue), deque()
            else:
                pending = []
            self._cond.notify_all()
        self._fail_queued(pending)
        if drain:
            with self._cond:
                while self._queue or self._n_inflight:
                    if deadline is not None and \
                            time.monotonic() >= deadline:
                        break
                    self._cond.wait(0.05)
        with self._cond:
            self._running = False
            leftovers, self._queue = list(self._queue), deque()
            self._cond.notify_all()
        self._fail_queued(leftovers)    # drain timed out, queue wedged
        self._monitor_stop.set()

        def _remaining():
            # ONE budget for the whole stop: joins and replica stops
            # spend the same deadline (floored so a spent budget still
            # makes each join/stop attempt briefly rather than hanging)
            if deadline is None:
                return None
            return max(deadline - time.monotonic(), 0.1)

        errors = []
        for t in (self._thread, self._monitor):
            if t is not None:
                t.join(_remaining())
                if t.is_alive():
                    errors.append(MXNetError(
                        f"{self.name}: thread {t.name} did not exit "
                        f"within {timeout}s"))
        self._thread = None
        self._monitor = None
        # belt for the stop-vs-failover race: anything that slipped
        # into the queue after the leftovers sweep (a callback that won
        # the requeue race an instant before _running flipped) has no
        # consumer now — resolve it typed rather than strand it
        with self._cond:
            tail, self._queue = list(self._queue), deque()
        self._fail_queued(tail)
        for r in self._replicas:      # hooks die with the router, even
            r.server._pre_dispatch = None   # when replicas keep serving
        if stop_replicas:
            for r in self._replicas:
                srv = r.server
                if not srv.is_running:
                    continue
                try:
                    srv.stop(drain=drain, timeout=_remaining())
                except MXNetError as e:   # a wedged replica must not
                    errors.append(e)      # leak the rest un-stopped
        _live_routers.discard(self)
        if errors:
            raise errors[0]

    def _fail_queued(self, reqs) -> None:
        """Resolve de-queued requests with the typed stopped error."""
        for req in reqs:
            if req.resolve_exc(MXNetError(
                    f"{self.name}: router stopped before this request "
                    "was dispatched")):
                if req.span is not None:
                    req.span.end(outcome="stopped")
                self._count_request("rejected")

    def __enter__(self) -> "Router":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop(drain=not any(exc))

    # -- fleet management (the control plane's seam) -------------------
    def _check_compatible(self, server: Server) -> None:
        g0 = self.grid
        if server.grid.batch_buckets != g0.batch_buckets or \
                server.grid.shape_buckets != g0.shape_buckets:
            raise MXNetError(
                f"replica {server.name} has a different bucket grid "
                "than the fleet — replicas must share one grid "
                "(matched-bucket bit-identity)")
        if any(r.server.name == server.name for r in self._replicas):
            raise MXNetError(
                f"replica name {server.name!r} already in the fleet")

    def register_model(self, name: str, factory, *,
                       slo_class: str = "standard", priority: int = 0,
                       weight: float = 1.0,
                       slo_ms: Optional[float] = None,
                       rate_limit: Optional[float] = None,
                       burst: Optional[float] = None,
                       factory_kwargs: Optional[dict] = None) -> None:
        """Register tenant ``name`` on EVERY replica in the fleet.

        ``factory`` builds the tenant's block: a zero-(or kw-)arg
        callable for in-process fleets (called once PER replica — each
        replica owns its parameters), or a ``"module:function"`` spec
        string, which is REQUIRED when any replica is out-of-process
        (a callable cannot cross the exec boundary; the refusal is
        typed, not a pickle crash). Replicas share one bucket grid, so
        the tenant's executables land in the compilation service's
        signature-keyed table once and every replica's warmup after the
        first is a table hit. Serialized with fleet admin; replicas
        admitted later via :meth:`add_replica` get the same model set
        replayed before they take traffic."""
        with self._admin_lock:
            if name in self._models:
                raise MXNetError(
                    f"{self.name}: model {name!r} is already registered")
            reps = list(self._replicas)
            remote = [r for r in reps
                      if not isinstance(r.server, Server)]
            if remote and callable(factory):
                raise MXNetError(
                    f"{self.name}: model {name!r} uses a callable "
                    "factory but the fleet includes out-of-process "
                    f"replica {remote[0].server.name!r} — a callable "
                    "cannot cross the process boundary; pass a "
                    "'module:function' spec string instead")
            kwargs = dict(factory_kwargs or {})
            done: List[str] = []
            try:
                for r in reps:
                    self._register_on(r.server, name, factory, kwargs,
                                      slo_class, priority, weight,
                                      slo_ms, rate_limit, burst)
                    done.append(r.server.name)
            except MXNetError as e:
                # partial registration is worse than none — a request
                # routed at an unregistered replica would refuse-spin.
                # There is no unregister seam, so surface exactly which
                # replicas took it and refuse the registry entry.
                raise MXNetError(
                    f"{self.name}: registering model {name!r} failed "
                    f"after replicas {done} accepted it: {e}") from e
            self._models[name] = {
                "factory": factory, "factory_kwargs": kwargs,
                "slo_class": slo_class, "priority": priority,
                "weight": weight, "slo_ms": slo_ms,
                "rate_limit": rate_limit, "burst": burst}

    @staticmethod
    def _register_on(server, name, factory, kwargs, slo_class,
                     priority, weight, slo_ms, rate_limit, burst):
        """Register one tenant on one replica, in-process or remote."""
        if isinstance(server, Server):
            if callable(factory):
                block = factory(**kwargs)
            else:
                from .worker import load_factory
                block = load_factory(factory)(**kwargs)
            server.register_model(
                name, block, slo_class=slo_class, priority=priority,
                weight=weight, slo_ms=slo_ms, rate_limit=rate_limit,
                burst=burst)
        else:
            server.register_model(
                name, factory, slo_class=slo_class, priority=priority,
                weight=weight, slo_ms=slo_ms, rate_limit=rate_limit,
                burst=burst, factory_kwargs=kwargs)

    def models(self) -> list:
        """Registered tenant names (router registry; the default
        tenant every replica carries is not listed)."""
        return sorted(self._models)

    def add_replica(self, server: Server) -> None:
        """Admit one more ``Server`` replica into the fleet, live.

        The server's grid must match the fleet's (bit-identity at
        matched buckets) and its name must be unique. On a running
        router the server is started first when it is not already —
        ``Server.start()`` AOT-warms the whole bucket grid through the
        compilation service, so a scale-up of an architecture any
        in-process replica already compiled is an executable-table hit,
        not a fresh XLA compile — and only then joins the dispatch set:
        no request is ever routed at a cold replica. Thread-safe
        (serialized with ``remove_replica``/rolling upgrades)."""
        with self._admin_lock:      # serializes fleet admin: the name /
            self._check_compatible(server)   # grid check cannot race
            # replay the tenant registry BEFORE the replica takes
            # traffic: a submit(model=X) routed at a replica without X
            # would refuse-spin against the fleet
            have = getattr(server, "models", None)
            have = set(have()) if have is not None else set()
            for mname, spec in self._models.items():
                if mname in have:
                    continue
                self._register_on(
                    server, mname, spec["factory"],
                    spec["factory_kwargs"], spec["slo_class"],
                    spec["priority"], spec["weight"], spec["slo_ms"],
                    spec["rate_limit"], spec["burst"])
            if self.is_running:
                server._pre_dispatch = self._replica_fault_hook_for(server)
                if not server.is_running:
                    try:
                        server.start()      # warm BEFORE taking traffic
                    except BaseException:
                        server._pre_dispatch = None
                        raise
            with self._cond:
                rep = _Replica(server, self._next_index, None, None)
                self._next_index += 1
                # the start-window hook had no stable index; swap in
                # the real one (sub-site ``serving.replica.<index>``)
                if self.is_running:
                    server._pre_dispatch = self._replica_fault_hook(rep)
                self._replicas = self._replicas + [rep]
                self._cond.notify_all()
        if _telemetry_state.enabled:
            telemetry.set_fleet_size(len(self._replicas),
                                     router=self.name)

    def _replica_fault_hook_for(self, server: Server):
        """Placeholder hook for the start window of an admitted-but-not-
        yet-committed replica: family site only (it has no stable index
        yet). Replaced by the indexed hook at commit."""
        name = server.name

        def hook(sig):
            if not _fault_state.enabled:
                return
            try:
                fault.check("serving.replica", f"{name} batch={sig}")
            except fault.FaultInjected as e:
                raise ReplicaFault(
                    f"replica {name} (joining) failed: {e}") from e
        return hook

    def remove_replica(self, name: str, drain: bool = True,
                       timeout: Optional[float] = None,
                       stop_server: bool = True) -> Server:
        """Retire the replica called ``name`` from the fleet.

        ``drain=True`` (default) first stops routing NEW requests at it
        (the picker skips draining replicas) and waits — bounded by
        ``timeout`` — for its router-forwarded in-flight requests to
        resolve; anything still outstanding at the deadline is failed
        over to the rest of the fleet (zero lost futures). The replica
        is then detached and, with ``stop_server=True``, stopped.
        Removing the LAST replica is refused — scale to zero is
        ``Router.stop()``, not a drain. Returns the detached
        ``Server``."""
        with self._admin_lock:
            # deadline starts AFTER the admin lock is ours: time spent
            # queued behind a rolling upgrade's bakes or a scale-up
            # warm must not consume the caller's drain budget
            deadline = (time.monotonic() + timeout) \
                if timeout is not None else None
            with self._cond:
                target = next((r for r in self._replicas
                               if r.server.name == name), None)
                if target is None:
                    raise MXNetError(
                        f"{self.name}: no replica named {name!r}")
                if len(self._replicas) <= 1:
                    raise MXNetError(
                        f"{self.name}: refusing to remove the last "
                        f"replica {name!r} — stop the router instead")
                target.draining = True
                self._cond.notify_all()
            if drain and self.is_running:
                with self._cond:
                    while target.inflight > 0:
                        if deadline is not None and \
                                time.monotonic() >= deadline:
                            break
                        self._cond.wait(0.02)
            # anything still in flight (drain=False, or the timeout
            # expired): evict and fail over — the fleet it drains into
            # is healthy, the replica is leaving either way
            evicted = self._take_flights_of(target)
            for f in evicted:
                self._retry_or_fail(
                    f.req,
                    MXNetError(f"replica {name} drained out of the "
                               "fleet with this request in flight"),
                    reason="drained", replica=target)
            with self._cond:
                self._replicas = [r for r in self._replicas
                                  if r is not target]
                self._cond.notify_all()
            target.server._pre_dispatch = None
        if _telemetry_state.enabled:
            telemetry.set_fleet_size(len(self._replicas),
                                     router=self.name)
        if stop_server and target.server.is_running:
            remaining = (max(deadline - time.monotonic(), 0.1)
                         if deadline is not None else None)
            try:
                target.server.stop(drain=drain, timeout=remaining)
            except MXNetError:
                # a scheduler wedged in dispatch can outlive the drain
                # deadline — the REMOVAL already succeeded (replica
                # detached, flights failed over), so don't fail it;
                # the daemon thread exits when the dispatch returns
                _log.warning(
                    "%s: removed replica %s did not stop within its "
                    "drain deadline (scheduler wedged in dispatch?); "
                    "its thread will exit when the dispatch returns",
                    self.name, name)
        return target.server

    def replicas(self) -> list:
        """Fleet snapshot for the control plane: one dict per replica
        (name, stable index, breaker state, inflight, draining)."""
        return [{"name": r.server.name, "index": r.index,
                 "state": r.breaker.state, "inflight": r.inflight,
                 "draining": r.draining, "server": r.server,
                 "breaker": r.breaker}
                for r in self._replicas]

    def fleet_size(self, include_draining: bool = False) -> int:
        reps = self._replicas
        if include_draining:
            return len(reps)
        return sum(1 for r in reps if not r.draining)

    def predicted_wait(self) -> float:
        """The admission controller's current completion-time estimate
        for a request submitted now (0.0 when there is no estimate) —
        the autoscaler's primary scale-up signal. Armed by the same
        backlog threshold as predicted-wait shedding: an idle fleet
        that JUST finished a burst still has a nonzero raw estimate
        (a fresh request would ride a full fleet batch), and reporting
        it would scale up a fleet with nothing queued."""
        with self._cond:
            pending = len(self._queue) + self._n_inflight
            if pending <= self._shed_arm_pending:
                return 0.0
            return self._predicted_wait_locked(pending)

    # -- admission -----------------------------------------------------
    # completions older than the window do not inform the service-rate
    # estimate, and gaps between completions are capped: idle time
    # between traffic bursts is not service time, and counting it would
    # make the router look slower than it is and shed spuriously
    _PRED_WINDOW_S = 2.0
    _PRED_GAP_CAP_S = 0.05

    def _predicted_wait_locked(self, pending: int) -> float:
        """Predicted time-to-completion for a request admitted now:
        (pending work + two full fleet batches — the request waits out
        the dispatch already RUNNING and then rides its OWN) over the
        measured service rate (last <=64 completions inside a recent
        window, busy time only). With fewer than 8 recent completions
        there is no estimate — admit (the bounded queue still caps the
        damage)."""
        now = time.perf_counter()
        ts = self._done_ts
        while ts and now - ts[0] > self._PRED_WINDOW_S:
            ts.popleft()
        if len(ts) < 8:
            return 0.0
        busy = 0.0
        prev = None
        for t in ts:
            if prev is not None:
                busy += min(t - prev, self._PRED_GAP_CAP_S)
            prev = t
        busy += min(now - prev, self._PRED_GAP_CAP_S)
        if busy <= 1e-6:
            return 0.0
        fleet_batch = self.grid.max_batch * len(self._replicas)
        return (pending + 2 * fleet_batch) * busy / len(ts)

    def _check_model(self, model: Optional[str]) -> None:
        """Reject an unknown tenant SYNCHRONOUSLY at admission: letting
        it through would refuse-spin the request against every replica
        until its deadline expired, reading as overload instead of a
        caller bug. Tenants registered directly on an in-process Server
        (bypassing the router registry) still pass."""
        if model is None or model == DEFAULT_MODEL \
                or model in self._models:
            return
        for r in self._replicas:
            ms = getattr(r.server, "models", None)
            if ms is not None:
                if model in ms():
                    return
                break
        self._count_request("rejected")
        raise MXNetError(
            f"{self.name}: unknown model {model!r} — register it with "
            "Router.register_model first")

    def submit(self, sample, deadline_ms: Optional[float] = None,
               model: Optional[str] = None,
               priority: Optional[int] = None) -> Future:
        """Enqueue one sample (no batch dimension) for the replica
        fleet; same contract as :meth:`Server.submit`. Raises
        synchronously — :class:`ServerOverloaded` on queue-full or a
        predicted deadline miss, :class:`MXNetError` when stopped, no
        shape bucket fits, or ``model`` names an unregistered tenant.
        Thread-safe. When the queue is empty the dispatch itself runs
        on this thread (never blocking on it — replica submits are
        enqueue-and-return); a backlog is drained in FIFO order by the
        dispatcher thread."""
        self._check_model(model)
        shape = getattr(sample, "shape", None)
        if shape is None:
            shape = np.asarray(sample).shape
        self.grid.bucket_shape(shape)       # raises if no bucket fits
        deadline_s = (deadline_ms / 1e3 if deadline_ms is not None
                      else self.slo_s)
        with self._cond:
            if not self._accepting:
                self._count_request("rejected")
                raise MXNetError(f"{self.name}: router is not running")
            pending = len(self._queue) + self._n_inflight
            if pending >= self.max_queue:
                self._shed_locked("queue_full", model=model)
                raise ServerOverloaded(
                    f"{self.name}: router queue full ({self.max_queue} "
                    "requests queued or in flight)")
            wait = (self._predicted_wait_locked(pending)
                    if pending > self._shed_arm_pending else 0.0)
            if wait > deadline_s:
                self._shed_locked("predicted_wait", model=model)
                raise ServerOverloaded(
                    f"{self.name}: predicted queue wait {wait * 1e3:.1f}"
                    f" ms exceeds the request deadline "
                    f"{deadline_s * 1e3:.1f} ms ({pending} pending)")
            req = _RouteReq(sample, deadline_s, model=model,
                            priority=priority)
            if _tracing_state.enabled:
                # the span must exist BEFORE the queue append: the
                # dispatcher thread may route this request before
                # submit returns
                amb = tracing.ambient()
                if amb is not None:
                    req.trace = amb[0]
                    req.span = req.trace.begin(
                        "router.queue", parent=amb[1], router=self.name)
                else:
                    req.trace = tracing.new_trace(
                        "request", router=self.name)
                    req.own_trace = True
                    req.span = req.trace.begin(
                        "router.queue", router=self.name)
                    req.future.add_done_callback(
                        req.trace.finish_from_future)
            # fast path: with nothing queued ahead (FIFO preserved),
            # route on the SUBMITTING thread — decode-to-dispatch is
            # one GIL hold with no queue hand-off and no dispatcher
            # wake-up. On a contended interpreter the hand-off is not
            # free: a wave of submits used to sit in the queue burning
            # deadline while the dispatcher thread waited for its next
            # slice (measured as head-of-line expiry through the socket
            # ingress). The dispatcher thread still owns the backlog:
            # anything the fast path cannot place immediately falls
            # back to the queue it drains. Under FAULT INJECTION the
            # fast path stands down entirely: chaos targets the
            # dispatcher's routing loop (``serving.route`` hits burn
            # budget there, latency faults wedge the dispatcher where
            # the watchdog contains them) — routing on a caller thread
            # would move the blast radius onto the client. With faults
            # off, every surface the fast path touches
            # (``_pick_replica``, a replica ``submit``) is
            # enqueue-and-return by construction, so ``submit`` stays
            # non-blocking.
            inline = not self._queue and not _fault_state.enabled
            if not inline:
                self._queue.append(req)
                depth = len(self._queue)
                self._cond.notify_all()
            else:
                depth = 0
        if inline:
            self._route(req, inline=True)
        if _telemetry_state.enabled:
            telemetry.set_router_queue_depth(depth, router=self.name)
        return req.future

    def submit_generate(self, prompt, max_new_tokens: int,
                        deadline_ms: Optional[float] = None,
                        on_token=None, model: Optional[str] = None,
                        priority: Optional[int] = None):
        """Route one autoregressive generate to a decode-capable
        replica (least-loaded CLOSED breaker). Returns the replica's
        :class:`~.server.GenerateHandle` directly — tokens stream
        straight from the serving replica; the router stays out of the
        per-token path.

        Unlike :meth:`submit`, a generate does NOT fail over
        mid-stream: by the time a replica dies the caller may have
        consumed half the completion, and replaying it elsewhere would
        duplicate streamed tokens. A crash resolves the handle's
        future with the typed replica error and counts as breaker
        evidence — the CALLER decides whether to resubmit.
        :class:`~.kvcache.CacheFull` (the request can never fit the
        replica's cache budget) sheds synchronously and typed
        (``mxnet_serving_shed_total{reason="kvcache_full"}``) —
        replicas share one cache geometry, so another replica would
        refuse it identically. So does :class:`TenantThrottled`
        (``reason="throttled"``) — retrying a tenant's rate-limit
        refusal on a sibling would multiply the tenant's configured
        rate by the fleet size."""
        self._check_model(model)
        with self._cond:
            if not self._accepting:
                self._count_request("rejected")
                raise MXNetError(f"{self.name}: router is not running")
        last_err: Optional[MXNetError] = None
        # half-open probes excluded: one multi-second generate is a
        # bad canary — recovery detection stays on short requests
        live = [r for r in self._replicas
                if r.server.is_running and not r.draining
                and r.breaker.state == CLOSED]
        for r in sorted(live, key=lambda r: r.inflight):
            if not r.breaker.admit():
                continue
            trace = span = None
            own = False
            if _tracing_state.enabled:
                amb = tracing.ambient()
                if amb is not None:
                    trace = amb[0]
                    span = trace.begin("router.generate", parent=amb[1],
                                       replica=r.server.name,
                                       model=model or DEFAULT_MODEL)
                else:
                    trace = tracing.new_trace("generate",
                                              router=self.name)
                    own = True
                    span = trace.begin("router.generate",
                                       replica=r.server.name,
                                       model=model or DEFAULT_MODEL)
            try:
                if span is not None:
                    with tracing.active(trace, span):
                        handle = r.server.submit_generate(
                            prompt, max_new_tokens,
                            deadline_ms=deadline_ms, on_token=on_token,
                            model=model, priority=priority)
                else:
                    handle = r.server.submit_generate(
                        prompt, max_new_tokens, deadline_ms=deadline_ms,
                        on_token=on_token, model=model,
                        priority=priority)
            except CacheFull:
                if span is not None:
                    span.end(outcome="shed")
                if own:
                    trace.finish("kvcache_full")
                with self._cond:
                    self._shed_locked("kvcache_full", model=model)
                raise
            except TenantThrottled:
                if span is not None:
                    span.end(outcome="shed")
                if own:
                    trace.finish("throttled")
                with self._cond:
                    self._shed_locked("throttled", model=model)
                raise
            except MXNetError as e:
                # this replica refuses (decode off / queue full): not
                # terminal for the request — try the next one
                if span is not None:
                    span.end(outcome="refused", error=type(e).__name__)
                if own:
                    trace.finish("refused")
                last_err = e
                continue
            with self._cond:
                r.inflight += 1
                self._n_inflight += 1
            t_enq = time.perf_counter()

            def _done(f, rep=r, sp=span, tr=trace, own_tr=own,
                      t0=t_enq):
                with self._cond:
                    rep.inflight -= 1
                    self._n_inflight -= 1
                    self._cond.notify_all()
                try:
                    exc = f.exception()
                except BaseException as e:  # noqa: BLE001 - cancelled
                    exc = e
                if exc is None:
                    rep.breaker.record_success()
                    rep.n_ok += 1
                elif not isinstance(exc, CacheFull):
                    # CacheFull is capacity, not health; anything else
                    # (crash, fault, wedge) is breaker evidence
                    rep.breaker.record_failure()
                    rep.n_failed += 1
                if sp is not None:
                    sp.end(outcome="ok" if exc is None else "error")
                self._count_request(
                    "ok" if exc is None else "error", t_enqueue=t0,
                    trace_id=tr.trace_id if tr is not None else None)
                if own_tr:
                    tr.finish("ok" if exc is None
                              else type(exc).__name__)

            handle.future.add_done_callback(_done)
            return handle
        if last_err is not None:
            raise last_err
        with self._cond:
            self._shed_locked("queue_full", model=model)
        raise ServerOverloaded(
            f"{self.name}: no decode-capable healthy replica admits "
            "generate requests right now")

    def _shed_locked(self, reason: str,
                     model: Optional[str] = None) -> None:
        self.n_shed += 1
        self.n_requests += 1
        if _telemetry_state.enabled:
            telemetry.record_serving_shed(reason, model=model)
        if _tracing_state.enabled:
            tracing.record_event("shed", reason=reason, router=self.name,
                                 model=model or DEFAULT_MODEL)

    def _count_request(self, outcome: str,
                       t_enqueue: Optional[float] = None,
                       trace_id: Optional[str] = None) -> None:
        self.n_requests += 1
        if outcome == "ok":
            self.n_ok += 1
        elif outcome == "error":
            self.n_errors += 1
        if _telemetry_state.enabled:
            lat = (time.perf_counter() - t_enqueue
                   if t_enqueue is not None else 0.0)
            telemetry.record_router_request(lat, outcome,
                                            trace_id=trace_id)

    # -- dispatcher ----------------------------------------------------
    def _dispatch_loop(self) -> None:
        try:
            while True:
                self.hb.touch()
                with self._cond:
                    while not self._queue and self._running:
                        self._cond.wait(0.05)
                        self.hb.touch()
                    if not self._queue:
                        return          # stopped, queue empty
                    req = self._queue.popleft()
                    # track the popped request IMMEDIATELY (same locked
                    # section): if this thread wedges or dies anywhere
                    # after the pop, the watchdog/containment must fail
                    # THIS future too, not just the still-queued ones
                    self._routing = req
                    if _telemetry_state.enabled:
                        telemetry.set_router_queue_depth(
                            len(self._queue), router=self.name)
                self._route(req)
                self._routing = None
        except BaseException:
            # loud containment, same contract as Server: a dead
            # dispatcher must not leave a queue nobody drains
            self._fail_all_queued("dispatcher thread crashed")
            raise

    def _fail_all_queued(self, why: str) -> None:
        with self._cond:
            self._accepting = False
            pending, self._queue = list(self._queue), deque()
            routing = self._routing
            self._cond.notify_all()
        if routing is not None:
            pending = [routing] + pending   # first-wins guards the race
        for req in pending:                 # with a later un-wedge
            if req.resolve_exc(MXNetError(f"{self.name}: {why}")):
                if req.span is not None:
                    req.span.end(outcome="error")
                self._count_request("error", t_enqueue=req.t_enqueue)
        if _tracing_state.enabled:
            tracing.record_event("router_wedged", router=self.name,
                                 why=why)
            tracing.maybe_dump("router_wedged")

    def _route(self, req: _RouteReq, inline: bool = False) -> None:
        """Forward one request to the best replica, retrying admission
        refusals briefly; requeues / resolves on terminal conditions.
        ``inline=True`` = running on the SUBMITTING thread (the fast
        path): transient can't-place-right-now conditions hand the
        request to the dispatcher's queue instead of backing off in
        place — a client/ingress thread must not sleep inside
        ``submit``."""
        if req.future.done():
            return      # already resolved (watchdog / late failover)
        if not req.begin():
            return                              # caller cancelled it
        now = time.perf_counter()
        if now >= req.deadline:
            # shed-in-queue safety net: dispatching it would burn a
            # replica slot on an already-dead request
            if req.resolve_exc(ServerOverloaded(
                    f"{self.name}: request deadline expired after "
                    f"{(now - req.t_enqueue) * 1e3:.1f} ms in the router "
                    f"queue ({req.attempts} dispatch attempt(s))")):
                if req.span is not None:
                    req.span.end(outcome="expired")
                with self._cond:
                    self._shed_locked("expired")
            return
        if _fault_state.enabled:
            try:
                fault.check("serving.route", f"{self.name}")
            except fault.FaultInjected as e:
                # a routing fault burns one unit of the request's
                # budget (else every:1 would requeue forever) but is
                # NOT replica health evidence
                req.attempts += 1
                if req.trace is not None:
                    req.trace.note(f"injected route fault: {e}")
                self._retry_or_fail(req, e, reason="route_fault")
                return
        target = self._pick_replica()
        if target is None:
            # nothing healthy admits right now: put it back and let the
            # dispatcher breathe (a breaker cooldown or an in-flight
            # completion will move things)
            self._hand_to_dispatcher(req, inline, wait_s=0.005)
            return
        r, probe = target
        flight = _Flight(req, r, time.perf_counter(), probe)
        remaining_ms = max((req.deadline - time.perf_counter()) * 1e3,
                           1.0)
        with self._cond:
            self._flights[id(flight)] = flight
            r.inflight += 1
            self._n_inflight += 1
        if req.trace is not None:
            # queue time ends the moment a replica is chosen; each
            # dispatch attempt gets its own span so a failover reads as
            # attempt-on-victim -> attempt-on-survivor under one trace
            if req.span is not None:
                req.span.end()
                req.span = None
            flight.span = req.trace.begin(
                "router.attempt", replica=r.server.name,
                attempt=req.attempts + 1)
        try:
            if flight.span is not None:
                # ambient context so the replica's submit (local Server
                # or RemoteReplica wire frame) joins this trace
                with tracing.active(req.trace, flight.span):
                    rfut = r.server.submit(req.sample,
                                           deadline_ms=remaining_ms,
                                           model=req.model,
                                           priority=req.priority)
            else:
                rfut = r.server.submit(req.sample,
                                       deadline_ms=remaining_ms,
                                       model=req.model,
                                       priority=req.priority)
        except Exception as e:  # noqa: BLE001 - sync admission refusal
            with self._cond:
                # guard like _on_replica_done: the hung-dispatch sweep
                # may have removed this flight (and decremented for it)
                # between registration and the submit raising — an
                # unconditional decrement would drive the counts
                # negative and double-queue the request
                live = self._flights.pop(id(flight), None) is not None
                if live:
                    r.inflight -= 1
                    self._n_inflight -= 1
                    self._cond.notify_all()
            if not live:
                return      # the sweep owns this request's fate now
            if isinstance(e, TenantThrottled):
                # per-tenant rate-limit refusal: typed and TERMINAL —
                # retrying on a sibling replica would multiply the
                # tenant's configured rate by the fleet size
                if flight.span is not None:
                    flight.span.end(outcome="shed",
                                    error=type(e).__name__)
                if probe:
                    r.breaker.release_probe()
                if req.resolve_exc(e):
                    with self._cond:
                        self._shed_locked("throttled", model=req.model)
                return
            if flight.span is not None:
                flight.span.end(outcome="refused",
                                error=type(e).__name__)
                # back to queued state: reopen a queue span so the
                # re-route attempt is attributed to scheduling time
                req.span = req.trace.begin("router.queue",
                                           router=self.name,
                                           requeue="refused")
            if probe:
                r.breaker.release_probe()
            if isinstance(e, MXNetError) and not r.server.is_running:
                # replica died between health check and submit
                r.breaker.record_failure()
                self._retry_or_fail(req, e, reason="replica_down",
                                    replica=r)
            else:
                # queue-full style refusal: not a health event; retry
                # the route (does not burn the retry budget — the
                # request was never dispatched)
                if _telemetry_state.enabled:
                    telemetry.record_serving_route_retry("refused")
                self._hand_to_dispatcher(req, inline, wait_s=0.002)
            return
        req.attempts += 1
        flight.rfut = rfut
        if _telemetry_state.enabled:
            telemetry.record_router_queue_wait(
                flight.t_sent - req.t_enqueue)
        rfut.add_done_callback(
            lambda f, fl=flight: self._on_replica_done(fl, f))

    def _hand_to_dispatcher(self, req: _RouteReq, inline: bool,
                            wait_s: float) -> None:
        """A route attempt could not place ``req`` right now (no
        admitting replica / transient refusal). Dispatcher thread:
        head-requeue and breathe — it owns the backoff loop. Inline
        fast path: tail-enqueue for the dispatcher and return (the
        submitting thread must not sleep here); if the router stopped
        while we were routing, resolve typed instead of stranding the
        request in a queue nobody will drain."""
        with self._cond:
            if not inline:
                self._queue.appendleft(req)
                self._cond.wait(wait_s)
                return
            if self._accepting:
                self._queue.append(req)
                self._cond.notify_all()
                return
        if req.resolve_exc(MXNetError(
                f"{self.name}: router stopped before this request "
                "was dispatched")):
            if req.span is not None:
                req.span.end(outcome="stopped")
            self._count_request("rejected")

    def _pick_replica(self):
        """(replica, is_probe) — HALF_OPEN probes first (recovery must
        be detected under any traffic), then least-loaded CLOSED.
        Draining replicas (a ``remove_replica`` in progress) take no new
        work — their in-flight dispatches finish through the normal
        resolution path."""
        live = [r for r in self._replicas
                if r.server.is_running and not r.draining]
        for r in live:
            if r.breaker.state == HALF_OPEN and r.breaker.admit():
                return r, True
        closed = [r for r in live if r.breaker.state == CLOSED]
        for r in sorted(closed, key=lambda r: r.inflight):
            if r.breaker.admit():
                return r, False
        return None

    def _on_replica_done(self, flight: _Flight, rfut) -> None:
        """Replica future resolved (runs on the replica's scheduler
        thread — keep it quick). ``late`` = the hung-dispatch sweep
        already removed this flight and failed it over; its breaker
        verdict stands, but a late SUCCESS is still a usable result
        (first resolution wins)."""
        with self._cond:
            late = self._flights.pop(id(flight), None) is None
            if not late:
                flight.rep.inflight -= 1
                self._n_inflight -= 1
                self._cond.notify_all()
        r = flight.rep
        try:
            exc = rfut.exception()
        except BaseException as e:  # noqa: BLE001 - cancelled etc.
            exc = e
        if exc is None:
            if not late:
                r.breaker.record_success()
                r.n_ok += 1
                with self._cond:
                    self._done_ts.append(time.perf_counter())
            if flight.span is not None:
                flight.span.end(outcome="ok")
            if flight.req.resolve_result(rfut.result()):
                self._count_request(
                    "ok", t_enqueue=flight.req.t_enqueue,
                    trace_id=(flight.req.trace.trace_id
                              if flight.req.trace is not None else None))
            return
        if flight.span is not None:
            flight.span.end(outcome="error", error=type(exc).__name__)
        if late:
            return                  # hung flight already failed over
        r.breaker.record_failure()
        r.n_failed += 1
        if r.breaker.state == OPEN:
            # the trip's collateral: every OTHER flight at this replica
            # is sitting in its batch queue and would ride the same
            # sick dispatch — or worse, wait out the deadline-close
            # window first and fail over with no deadline left. Evict
            # them through the failover path NOW, while their budgets
            # still buy a healthy replica (their late resolutions, if
            # the replica gets to them anyway, drop first-wins).
            for f in self._take_flights_of(r):
                if f.rfut is not None:
                    f.rfut.cancel()     # spare the sick replica's queue
                r.n_failed += 1
                self._retry_or_fail(
                    f.req,
                    MXNetError(
                        f"replica {r.server.name} circuit breaker "
                        "opened with this request in flight"),
                    reason="breaker_open", replica=r)
        self._retry_or_fail(flight.req, exc, reason="replica_error",
                            replica=r)

    def _retry_or_fail(self, req: _RouteReq, exc: BaseException,
                       reason: str, replica: Optional[_Replica] = None
                       ) -> None:
        """Failover: requeue at the FRONT (it has waited longest) under
        the retry budget, else resolve with a typed error. Never leaves
        the future unresolved."""
        if req.future.done():
            return
        if _telemetry_state.enabled:
            telemetry.record_serving_route_retry(reason)
        budget = 1 + self.retry_budget           # total dispatches
        requeued = False
        if req.attempts < budget:
            # re-check _running in the SAME critical section as the
            # requeue: a stop() racing between a stale check and the
            # appendleft would strand the request in a queue with no
            # consumer — a lost future
            with self._cond:
                if self._running:
                    self._queue.appendleft(req)
                    self._cond.notify_all()
                    requeued = True
        if requeued:
            self.n_failovers += 1
            if _telemetry_state.enabled and replica is not None:
                telemetry.record_serving_failover(replica.server.name)
            if req.trace is not None:
                victim = (replica.server.name if replica is not None
                          else "?")
                req.trace.note(
                    f"failover: {reason} on {victim} "
                    f"({type(exc).__name__}: {exc}); requeued "
                    f"(attempt {req.attempts} of {budget})")
                if req.span is None or req.span._done:
                    req.span = req.trace.begin(
                        "router.queue", router=self.name, requeue=reason)
                tracing.record_event(
                    "failover", router=self.name, reason=reason,
                    replica=victim, trace_id=req.trace.trace_id)
            return
        detail = (f" (last replica: {replica.server.name})"
                  if replica is not None else "")
        if req.resolve_exc(FailoverExhausted(
                f"{self.name}: request failed after {req.attempts} "
                f"dispatch attempt(s), retry budget "
                f"{self.retry_budget} spent{detail}: {exc}")):
            if req.span is not None:
                req.span.end(outcome="exhausted")
            if req.trace is not None:
                tracing.record_event(
                    "failover_exhausted", router=self.name,
                    reason=reason, trace_id=req.trace.trace_id)
            self._count_request(
                "error", t_enqueue=req.t_enqueue,
                trace_id=(req.trace.trace_id
                          if req.trace is not None else None))

    # -- monitor: hung dispatches, breaker gauges, watchdog ------------
    def _monitor_loop(self) -> None:
        interval = min(0.05, self.dispatch_timeout_s / 4)
        while not self._monitor_stop.wait(interval):
            self._sweep_hung()
            self._publish_health()
            self._check_dispatcher()

    def _take_flights_of(self, r: _Replica) -> list:
        """Remove and return every flight currently at replica ``r``
        (their late resolutions, if any, are dropped first-wins)."""
        with self._cond:
            mine = [f for f in self._flights.values()
                    if f.rep is r]
            for f in mine:
                self._flights.pop(id(f), None)
                r.inflight -= 1
                self._n_inflight -= 1
            if mine:
                self._cond.notify_all()
        return mine

    def _sweep_hung(self) -> None:
        """Hung-dispatch detection. Primary signal: a replica's
        scheduler heartbeat (touched once per loop iteration, so
        between touches at most ONE dispatch runs) stale past the
        dispatch timeout while it has router flights outstanding — a
        scheduler patiently filling a batch keeps touching, a wedged
        dispatch does not. Trip the breaker and fail over EVERY flight
        at that replica at once. Backstop: any single flight
        outstanding a full timeout past its own deadline (a live
        replica resolves by the deadline — its batch closes at
        deadline - margin) fails over too, so a silently dropped
        callback can never strand a future."""
        now = time.perf_counter()
        hung: List = []
        for r in self._replicas:
            srv = r.server
            if not srv.is_running:
                continue        # crash containment fails its futures
            with self._cond:
                busy = r.inflight > 0
            if busy and srv.hb.stale(self.dispatch_timeout_s):
                r.breaker.record_hang()
                taken = self._take_flights_of(r)
                r.n_failed += len(taken)
                age = srv.hb.age()
                for f in taken:
                    hung.append((f, r, MXNetError(
                        f"replica {srv.name} scheduler silent for "
                        f"{age:.2f}s > MXNET_SERVING_DISPATCH_TIMEOUT="
                        f"{self.dispatch_timeout_s:g}s with this "
                        "request in flight (hung dispatch)")))
        with self._cond:
            overdue = [f for f in self._flights.values()
                       if now > max(f.req.deadline, f.t_sent)
                       + self.dispatch_timeout_s]
            for f in overdue:
                self._flights.pop(id(f), None)
                f.rep.inflight -= 1
                self._n_inflight -= 1
            if overdue:
                self._cond.notify_all()
        for f in overdue:
            r = f.rep
            r.breaker.record_hang()
            r.n_failed += 1
            hung.append((f, r, MXNetError(
                f"dispatch at replica {r.server.name} still "
                f"outstanding {self.dispatch_timeout_s:g}s past the "
                "request deadline (unresponsive replica)")))
        for f, r, err in hung:
            if f.span is not None:
                f.span.end(outcome="hung")
            self._retry_or_fail(f.req, err, reason="hung", replica=r)

    def _publish_health(self) -> None:
        for r in self._replicas:
            # out-of-process replicas report crashes explicitly
            # (connection drop / waitpid — see serving/remote.py): an
            # UNAMBIGUOUS death trips the breaker immediately instead
            # of burning a failure threshold against a corpse (crash !=
            # slow); the respawned worker re-enters through the
            # half-open probe like any recovered replica
            cc = getattr(r.server, "crash_count", 0)
            if cc > r.crashes_seen:
                r.crashes_seen = cc
                r.breaker.record_hang()
                if _tracing_state.enabled:
                    tracing.record_event(
                        "worker_crash", replica=r.server.name,
                        crash_count=cc, router=self.name)
            state = r.breaker.state
            if state != r.last_state:
                if _telemetry_state.enabled:
                    telemetry.record_breaker_transition(
                        r.server.name, state)
                if _tracing_state.enabled:
                    tracing.record_event(
                        "breaker", replica=r.server.name,
                        from_state=r.last_state, to_state=state,
                        router=self.name)
                    if state == OPEN:
                        # a breaker trip is exactly the moment the
                        # flight recorder exists for: persist the ring
                        # so the trip can be explained post-mortem
                        tracing.maybe_dump("breaker_open")
                r.last_state = state
            if _telemetry_state.enabled:
                telemetry.set_replica_health(
                    r.server.name, _HEALTH_VALUE[state])
        if _telemetry_state.enabled:
            # the scrape-fed control plane's signal set: every gauge a
            # remote FleetController needs rides /metrics from here
            with self._cond:
                depth = len(self._queue)
                inflight = self._n_inflight
                by_model: dict = {}
                for q in self._queue:
                    m = q.model or DEFAULT_MODEL
                    by_model[m] = by_model.get(m, 0) + 1
            telemetry.set_router_queue_depth(depth, router=self.name)
            telemetry.set_router_inflight(inflight, router=self.name)
            telemetry.set_predicted_wait(self.predicted_wait(),
                                         router=self.name)
            telemetry.set_fleet_size(self.fleet_size(),
                                     router=self.name)
            # per-tenant depth: every registered tenant gets a sample
            # (zero included) so a drained queue reads as 0, not stale
            for m in ({DEFAULT_MODEL} | set(self._models)
                      | set(by_model)):
                telemetry.set_tenant_queue_depth(
                    by_model.get(m, 0), m, router=self.name)

    def _check_dispatcher(self) -> None:
        if self._wedged or not self._running:
            return
        t = self._thread
        dead = t is not None and not t.is_alive()
        stale = self.hb.stale(self.watchdog_timeout_s)
        if not (dead or stale):
            return
        # the dispatcher is gone or wedged: requests already forwarded
        # will still resolve through their replicas, but the queue has
        # no consumer — fail it loudly NOW (zero hung futures), and
        # stop admitting
        self._wedged = True
        why = ("dispatcher thread died" if dead else
               f"dispatcher silent for {self.hb.age():.1f}s > "
               f"MXNET_SERVING_WATCHDOG_TIMEOUT="
               f"{self.watchdog_timeout_s:g}s (wedged)")
        self._fail_all_queued(f"scheduler-liveness watchdog: {why}")

    # -- introspection -------------------------------------------------
    def stats(self) -> dict:
        with self._cond:
            depth = len(self._queue)
            inflight = self._n_inflight
        return {
            "requests": self.n_requests, "ok": self.n_ok,
            "errors": self.n_errors, "shed": self.n_shed,
            "failovers": self.n_failovers, "queue_depth": depth,
            "inflight": inflight, "running": self.is_running,
            "wedged": self._wedged,
            "fleet_size": self.fleet_size(),
            "models": sorted(self._models),
            "replicas": [
                {"name": r.server.name, "index": r.index,
                 "state": r.breaker.state, "inflight": r.inflight,
                 "ok": r.n_ok, "failed": r.n_failed,
                 "draining": r.draining,
                 "trips": r.breaker.n_trips}
                for r in self._replicas],
        }
