"""``mx.amp`` — automatic mixed precision (reference:
``python/mxnet/contrib/amp/amp.py`` + ``loss_scaler.py``).

The reference's AMP rewrites the op namespace so whitelisted (MXU-friendly)
ops run fp16 and blacklisted (range-sensitive) ops stay fp32, and wraps the
Trainer with a dynamic loss scaler. The TPU-native counterpart is the same
three pieces with bf16 as the default target:

* ``init()`` — patch the op registry: TARGET_DTYPE_OPS run in bf16 (their
  float inputs are cast at the boundary; XLA fuses the converts), FP32_OPS
  get f32 inputs. Under jit these casts trace into the one compiled step.
* ``init_trainer()`` / ``scale_loss()`` — dynamic loss scaling. bf16 has
  f32's exponent range so the scaler is a no-op there by default; for
  ``float16`` (and for API parity) the full grow/backoff scaler runs.
* ``convert_model`` / ``convert_hybrid_block`` — cast a trained model's
  params to the target dtype.

Reference parity notes: list names follow ``amp/lists/symbol_fp16.py``'s
roles (TARGET/FP32/WIDEST); unlisted ops run in their input dtype.
"""
from __future__ import annotations

import contextlib
import logging
import warnings

from ..base import MXNetError

__all__ = ["init", "init_trainer", "scale_loss", "unscale",
           "convert_model", "convert_hybrid_block", "DynamicLossScaler",
           "TARGET_DTYPE_OPS", "FP32_OPS"]

# MXU-bound ops: run in the target dtype (reference: FP16_FUNCS)
TARGET_DTYPE_OPS = [
    "FullyConnected", "Convolution", "Deconvolution", "batch_dot", "dot",
    "RNN",
]
# range/precision-sensitive ops: force f32 inputs (reference: FP32_FUNCS)
FP32_OPS = [
    "softmax", "log_softmax", "SoftmaxOutput", "softmax_cross_entropy",
    "smooth_l1", "exp", "log", "log2", "log10", "norm", "mean", "sum",
    "L2Normalization", "InstanceNorm", "LayerNorm", "BatchNorm", "erfinv",
]

_state = {"initialized": False, "target_dtype": None, "orig_fns": {}}


def _cast_tensors(args, dtype):
    import jax.numpy as jnp

    def cast(a):
        if hasattr(a, "dtype") and jnp.issubdtype(
                jnp.asarray(a).dtype, jnp.floating):
            return jnp.asarray(a).astype(dtype)
        return a

    return [cast(a) for a in args]


def init(target_dtype="bfloat16", target_precision_ops=None,
         conditional_fp32_ops=None, fp32_ops=None):
    """Enable AMP op-level autocasting (reference: amp.init).

    Idempotent; patches the op registry in place so every frontend
    (nd/np/gluon/symbol/TrainStep — they all dispatch through the
    registry) autocasts identically, eagerly and under jit.
    """
    import jax.numpy as jnp

    from ..ops import registry as reg

    if _state["initialized"]:
        if str(target_dtype) != _state["target_dtype"]:
            raise MXNetError(
                f"amp.init already active with target_dtype="
                f"{_state['target_dtype']!r}; it cannot be re-initialized "
                f"with {target_dtype!r}")
        return
    if str(target_dtype) not in ("bfloat16", "float16"):
        raise MXNetError(
            f"amp.init: target_dtype must be bfloat16 or float16, got "
            f"{target_dtype!r} (bfloat16 is the TPU-native choice)")
    target = jnp.bfloat16 if str(target_dtype) == "bfloat16" else jnp.float16
    logging.info("AMP init: target dtype %s", target_dtype)

    def wrap(opdef, dtype):
        orig = opdef.fn

        def autocast_fn(*tensors, **attrs):
            return orig(*_cast_tensors(tensors, dtype), **attrs)

        # OpDef is an immutable NamedTuple: swap every registry alias that
        # points at this op for a _replace'd copy
        new = opdef._replace(fn=autocast_fn)
        for key, val in list(reg._REGISTRY.items()):
            if val is opdef:
                reg._REGISTRY[key] = new
        _state["orig_fns"][opdef.name] = opdef

    for name in (target_precision_ops or TARGET_DTYPE_OPS):
        try:
            wrap(reg.get_op(name), target)
        except Exception:
            pass  # op families differ per build; mirror reference leniency
    for name in (fp32_ops or FP32_OPS):
        try:
            wrap(reg.get_op(name), jnp.float32)
        except Exception:
            pass
    # invalidate the per-op executable cache: it closed over original fns
    try:
        reg._cached_call.cache_clear()
    except Exception:
        pass
    _state.update(initialized=True, target_dtype=str(target_dtype))


def _deinit_for_tests():
    """Undo init() — test isolation helper (not in the reference API)."""
    from ..ops import registry as reg

    for name, orig_opdef in _state["orig_fns"].items():
        patched = reg._REGISTRY.get(orig_opdef.name)
        for key, val in list(reg._REGISTRY.items()):
            if val is patched:
                reg._REGISTRY[key] = orig_opdef
    _state["orig_fns"].clear()
    _state.update(initialized=False, target_dtype=None)
    try:
        reg._cached_call.cache_clear()
    except Exception:
        pass


class DynamicLossScaler:
    """Grow/backoff loss scaler (reference: amp/loss_scaler.py::LossScaler).

    Scale doubles after ``scale_window`` consecutive finite-gradient steps
    and halves on overflow (the update that overflowed is skipped by
    Trainer.step)."""

    def __init__(self, init_scale=2.0 ** 16, scale_factor=2.0,
                 scale_window=2000, tolerance=0.0):
        self.loss_scale = float(init_scale)
        self._scale_factor = float(scale_factor)
        self._scale_window = int(scale_window)
        self._unskipped = 0

    def has_overflow(self, params):
        """True if any gradient is non-finite (post-unscale check input)."""
        import jax.numpy as jnp

        for p in params:
            if p.grad_req == "null":
                continue
            for g in p.list_grad():
                if not bool(jnp.isfinite(g.data).all()):
                    return True
        return False

    def update_scale(self, overflow):
        if overflow:
            self.loss_scale = max(1.0, self.loss_scale / self._scale_factor)
            self._unskipped = 0
        else:
            self._unskipped += 1
            if self._unskipped >= self._scale_window:
                self.loss_scale *= self._scale_factor
                self._unskipped = 0


def init_trainer(trainer):
    """Attach a dynamic loss scaler to a Trainer (reference:
    amp.init_trainer). bf16 targets get scale 1 (bf16 keeps f32's exponent
    range — scaling exists for f16's narrow range)."""
    if getattr(trainer, "_amp_loss_scaler", None) is not None:
        return
    if not _state["initialized"]:
        raise MXNetError("call amp.init() before amp.init_trainer()")
    if _state["target_dtype"] == "bfloat16":
        scaler = DynamicLossScaler(init_scale=1.0, scale_window=10 ** 9)
    else:
        scaler = DynamicLossScaler()
    trainer._amp_loss_scaler = scaler
    _patch_trainer_step(trainer)


def _patch_trainer_step(trainer):
    orig_step = trainer.step

    def amp_step(batch_size, ignore_stale_grad=False):
        scaler = trainer._amp_loss_scaler
        # fold the loss scale into rescale_grad so the unscale happens
        # inside the (compiled) updater — unless amp.unscale() already
        # divided the gradients this iteration
        already = getattr(trainer, "_amp_grads_unscaled", False)
        trainer._amp_grads_unscaled = False
        prev_scale = trainer._scale
        if not already:
            trainer._scale = prev_scale / scaler.loss_scale
        try:
            overflow = scaler.has_overflow(trainer._params)
            if overflow:
                logging.warning(
                    "AMP: gradient overflow, skipping step "
                    "(loss scale %.1f -> %.1f)", scaler.loss_scale,
                    scaler.loss_scale / scaler._scale_factor)
                # the scaler owns overflow handling (skip + scale
                # backoff); the Trainer's nonfinite guard defers to it,
                # so account the skip here under the shared counter
                from .. import telemetry

                trainer.steps_skipped = getattr(
                    trainer, "steps_skipped", 0) + 1
                telemetry.record_step_skipped("amp_overflow")
            else:
                orig_step(batch_size, ignore_stale_grad)
            scaler.update_scale(overflow)
        finally:
            trainer._scale = prev_scale
    trainer._amp_orig_step = orig_step
    trainer.step = amp_step


@contextlib.contextmanager
def scale_loss(loss, trainer):
    """Scale the loss before backward (reference: amp.scale_loss)."""
    if getattr(trainer, "_amp_loss_scaler", None) is None:
        init_trainer(trainer)
    scale = trainer._amp_loss_scaler.loss_scale
    if isinstance(loss, (list, tuple)):
        yield [l * scale for l in loss]
    else:
        yield loss * scale


def unscale(trainer):
    """Divide current gradients by the loss scale in place (reference:
    amp.unscale) — for clipping between backward and step."""
    scaler = getattr(trainer, "_amp_loss_scaler", None)
    if scaler is None or scaler.loss_scale == 1.0:
        return
    inv = 1.0 / scaler.loss_scale
    for p in trainer._params:
        if p.grad_req == "null":
            continue
        for g in p.list_grad():
            g._set_data((g.data * g.data.dtype.type(inv))
                        if hasattr(g.data.dtype, "type") else g.data * inv)
    # tell the patched step not to divide again this iteration (the scale
    # itself is untouched — next scale_loss uses it as usual)
    trainer._amp_grads_unscaled = True


def convert_model(sym, arg_params, aux_params, target_dtype="bfloat16",
                  fp32_params=None):
    """Cast a symbolic checkpoint's params (reference: amp.convert_model)."""
    fp32 = set(fp32_params or ())
    import jax.numpy as jnp

    def conv(d):
        out = {}
        for k, v in d.items():
            if k in fp32 or not jnp.issubdtype(
                    jnp.asarray(v.data).dtype, jnp.floating):
                out[k] = v
            else:
                out[k] = v.astype(target_dtype)
        return out

    return sym, conv(arg_params), conv(aux_params)


def convert_hybrid_block(block, target_dtype="bfloat16"):
    """Cast a Gluon block in place (reference: amp.convert_hybrid_block)."""
    block.cast(target_dtype)
    return block
