"""Weight initializers.

Reference: ``python/mxnet/initializer.py`` — the registry of `Initializer`
subclasses (`Xavier`, `MSRAPrelu`, `Normal`, `Uniform`, `Zero`, `One`,
`Constant`, `Orthogonal`, `Bilinear`, `LSTMBias`, `Mixed`) plus the
name-pattern dispatch in ``Initializer.__call__`` (weights vs bias vs
gamma/beta/moving stats).
"""
from __future__ import annotations

import json
import math
import re
from typing import Optional

import numpy as _np

from .base import MXNetError

__all__ = ["Initializer", "Uniform", "Normal", "Zero", "One", "Constant",
           "Xavier", "MSRAPrelu", "Orthogonal", "Bilinear", "LSTMBias",
           "Mixed", "Load", "register", "create", "InitDesc"]

_REGISTRY = {}


def register(klass):
    _REGISTRY[klass.__name__.lower()] = klass
    return klass


def create(name, **kwargs):
    if isinstance(name, Initializer):
        return name
    if name is None:
        return Uniform()
    key = str(name).lower()
    key = {"zeros": "zero", "ones": "one"}.get(key, key)
    if key not in _REGISTRY:
        raise MXNetError(f"unknown initializer {name!r}")
    return _REGISTRY[key](**kwargs)


class InitDesc(str):
    """Parameter name + attrs hint passed to initializers
    (reference: initializer.py::InitDesc)."""

    def __new__(cls, name, attrs=None, global_init=None):
        obj = super().__new__(cls, name)
        obj.attrs = attrs or {}
        obj.global_init = global_init
        return obj


class Initializer:
    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __call__(self, desc, arr) -> None:
        """Initialize ``arr`` (an NDArray) according to the name pattern."""
        if not isinstance(desc, str):
            raise TypeError("desc must be a string/InitDesc")
        init_name = getattr(desc, "attrs", {}).get("__init__", "")
        if init_name:
            create(json.loads(init_name)[0], **json.loads(init_name)[1])._init_weight(desc, arr)
            return
        name = desc.lower()
        if name.endswith("weight"):
            self._init_weight(desc, arr)
        elif name.endswith("bias"):
            self._init_bias(desc, arr)
        elif name.endswith("gamma"):
            self._init_gamma(desc, arr)
        elif name.endswith("beta"):
            self._init_beta(desc, arr)
        elif name.endswith("running_mean") or name.endswith("moving_mean"):
            self._init_zero(desc, arr)
        elif name.endswith("running_var") or name.endswith("moving_var"):
            self._init_one(desc, arr)
        elif name.endswith("moving_inv_var") or name.endswith("moving_avg"):
            self._init_zero(desc, arr)
        else:
            self._init_default(desc, arr)

    # -- leaf initializers --------------------------------------------
    def _init_weight(self, name, arr):
        raise NotImplementedError

    def _init_bias(self, name, arr):
        arr[:] = 0.0

    def _init_gamma(self, name, arr):
        arr[:] = 1.0

    def _init_beta(self, name, arr):
        arr[:] = 0.0

    def _init_zero(self, name, arr):
        arr[:] = 0.0

    def _init_one(self, name, arr):
        arr[:] = 1.0

    def _init_default(self, name, arr):
        self._init_weight(name, arr)

    def __repr__(self):
        return f"{self.__class__.__name__}({self._kwargs})"

    def _rand(self):
        # initializer randomness flows from the global mx.random seed
        from .random_state import host_rng

        return host_rng()


@register
class Uniform(Initializer):
    def __init__(self, scale=0.07):
        super().__init__(scale=scale)
        self.scale = scale

    def _init_weight(self, name, arr):
        arr[:] = self._rand().uniform(-self.scale, self.scale, arr.shape).astype("float32")


@register
class Normal(Initializer):
    def __init__(self, sigma=0.01):
        super().__init__(sigma=sigma)
        self.sigma = sigma

    def _init_weight(self, name, arr):
        arr[:] = self._rand().normal(0, self.sigma, arr.shape).astype("float32")


@register
class Zero(Initializer):
    def _init_weight(self, name, arr):
        arr[:] = 0.0


@register
class One(Initializer):
    def _init_weight(self, name, arr):
        arr[:] = 1.0


@register
class Constant(Initializer):
    def __init__(self, value=0.0):
        super().__init__(value=value)
        self.value = value

    def _init_weight(self, name, arr):
        if hasattr(self.value, "asnumpy"):
            arr[:] = self.value.asnumpy()
        else:
            arr[:] = self.value


@register
class Xavier(Initializer):
    """reference: initializer.py::Xavier — fan-based scaling with
    rnd_type ∈ {uniform, gaussian}, factor_type ∈ {avg, in, out}."""

    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        super().__init__(rnd_type=rnd_type, factor_type=factor_type, magnitude=magnitude)
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = float(magnitude)

    def _init_weight(self, name, arr):
        shape = arr.shape
        hw_scale = 1.0
        if len(shape) < 2:
            raise MXNetError(f"Xavier requires ndim>=2, got shape {shape} for {name}")
        if len(shape) > 2:
            hw_scale = _np.prod(shape[2:])
        fan_in, fan_out = shape[1] * hw_scale, shape[0] * hw_scale
        factor = {"avg": (fan_in + fan_out) / 2.0, "in": fan_in, "out": fan_out}[self.factor_type]
        scale = math.sqrt(self.magnitude / factor)
        if self.rnd_type == "uniform":
            arr[:] = self._rand().uniform(-scale, scale, shape).astype("float32")
        elif self.rnd_type == "gaussian":
            arr[:] = self._rand().normal(0, scale, shape).astype("float32")
        else:
            raise MXNetError(f"unknown rnd_type {self.rnd_type}")


@register
class MSRAPrelu(Xavier):
    def __init__(self, factor_type="avg", slope=0.25):
        magnitude = 2.0 / (1 + slope ** 2)
        super().__init__("gaussian", factor_type, magnitude)
        self._kwargs = {"factor_type": factor_type, "slope": slope}


@register
class Orthogonal(Initializer):
    def __init__(self, scale=1.414, rand_type="uniform"):
        super().__init__(scale=scale, rand_type=rand_type)
        self.scale = scale
        self.rand_type = rand_type

    def _init_weight(self, name, arr):
        nout = arr.shape[0]
        nin = int(_np.prod(arr.shape[1:]))
        if self.rand_type == "uniform":
            tmp = self._rand().uniform(-1.0, 1.0, (nout, nin))
        else:
            tmp = self._rand().normal(0.0, 1.0, (nout, nin))
        u, _, v = _np.linalg.svd(tmp, full_matrices=False)
        q = u if u.shape == tmp.shape else v
        arr[:] = (self.scale * q).reshape(arr.shape).astype("float32")


@register
class Bilinear(Initializer):
    """Bilinear upsampling kernel (reference: initializer.py::Bilinear,
    used by UpSampling deconvolution)."""

    def _init_weight(self, name, arr):
        weight = _np.zeros(arr.shape, dtype="float32")
        shape = arr.shape
        f = _np.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(int(_np.prod(shape))):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight.flat[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        arr[:] = weight


@register
class LSTMBias(Initializer):
    """Forget-gate bias set to a constant (reference: initializer.py::LSTMBias)."""

    def __init__(self, forget_bias=1.0):
        super().__init__(forget_bias=forget_bias)
        self.forget_bias = forget_bias

    def _init_weight(self, name, arr):
        b = _np.zeros(arr.shape, dtype="float32")
        num_hidden = arr.shape[0] // 4
        b[num_hidden : 2 * num_hidden] = self.forget_bias
        arr[:] = b

    _init_default = _init_weight
    _init_bias = _init_weight


@register
class Mixed(Initializer):
    def __init__(self, patterns, initializers):
        super().__init__()
        assert len(patterns) == len(initializers)
        self.map = [(re.compile(p), i) for p, i in zip(patterns, initializers)]

    def __call__(self, name, arr):
        for pat, init in self.map:
            if pat.match(str(name)):
                init(name, arr)
                return
        raise MXNetError(f"parameter {name} did not match any Mixed pattern")


class Load:
    """Initialize parameters from a saved dict (reference:
    initializer.py::Load): names found in ``param`` take their stored
    value; anything else falls through to ``default_init`` (or raises
    when none is given)."""

    def __init__(self, param, default_init=None, verbose=False):
        if isinstance(param, str):
            from .ndarray import serialization

            param = serialization.load(param)
        if not isinstance(param, dict):
            raise TypeError(
                "Load: expected a dict of name -> NDArray (a .params file "
                "saved with names), got " + type(param).__name__)
        self.param = {
            (k[4:] if k.startswith(("arg:", "aux:")) else k): v
            for k, v in param.items()}
        self.default_init = default_init
        self.verbose = verbose

    def __call__(self, desc, arr):
        name = str(desc)
        if name in self.param:
            src = self.param[name]
            if tuple(src.shape) != tuple(arr.shape):
                raise ValueError(
                    f"Load: parameter {name!r} has shape {src.shape} in the "
                    f"file but {arr.shape} is requested")
            arr[:] = src
            if self.verbose:
                print(f"Initialized {name} by loading")
        else:
            if self.default_init is None:
                raise ValueError(
                    f"Load: cannot initialize {name!r} — not found in the "
                    "loaded file and no default_init is given")
            self.default_init(desc, arr)
