"""``mx.rtc`` — user runtime kernels (reference: ``src/common/rtc.cc`` ::
``CudaModule``/``CudaKernel``, exposed as ``mx.rtc.CudaModule``).

The reference compiles user CUDA source with NVRTC at runtime. The
TPU-native counterpart compiles user **Pallas** kernels: a ``PallasModule``
holds Python kernel functions (the Pallas analogue of a .cu source blob)
and ``get_kernel`` binds one with block/grid metadata into a callable that
launches on NDArrays — same two-level API shape as CudaModule, with Mosaic
as the runtime compiler and VMEM refs instead of raw pointers.

    mod = mx.rtc.PallasModule(dict(
        axpy=lambda x_ref, y_ref, o_ref, *, alpha: o_ref.__setitem__(
            ..., alpha * x_ref[...] + y_ref[...])))
    k = mod.get_kernel("axpy", out_shapes=[("o", "float32", (128, 128))],
                       alpha=2.0)
    out, = k.launch([x, y])

``mx.rtc.CudaModule`` raises with guidance (CUDA source cannot target
the MXU); the name is kept so ported code fails loudly, not with
AttributeError.
"""
from __future__ import annotations

import functools

from .base import MXNetError
from .context import current_context
from .ndarray import NDArray

__all__ = ["PallasModule", "PallasKernel", "CudaModule"]


class PallasKernel:
    """A bound user kernel (reference: rtc.cc::CudaKernel).

    ``launch(args)`` maps NDArray inputs to VMEM refs positionally, then
    the declared outputs; compiled once per input-signature by Mosaic and
    cached (the reference caches PTX per device the same way).
    """

    def __init__(self, name, fn, out_shapes, grid=None, interpret=False,
                 **attrs):
        self.name = name
        self._fn = fn
        self._outs = list(out_shapes)
        self._grid = grid
        self._interpret = bool(interpret)
        self._attrs = dict(attrs)
        self._cache = {}

    def _build(self, interpret):
        import jax
        from jax.experimental import pallas as pl

        out_shape = [jax.ShapeDtypeStruct(tuple(shape), dtype)
                     for (_n, dtype, shape) in self._outs]
        kern = functools.partial(self._fn, **self._attrs) if self._attrs \
            else self._fn
        kwargs = {}
        if self._grid is not None:
            kwargs["grid"] = self._grid
        return pl.pallas_call(kern, out_shape=out_shape,
                              interpret=interpret, **kwargs)

    def launch(self, args, ctx=None):
        """Run on NDArray inputs; returns a list of output NDArrays."""
        from .base import current_execution_platform

        if ctx is None:
            ctx = next((a.context for a in args
                        if isinstance(a, NDArray)), current_context())
        vals = [a.data if isinstance(a, NDArray) else a for a in args]
        platform = current_execution_platform(vals[0] if vals else None)
        interpret = self._interpret or platform != "tpu"
        # the platform is part of the key: the same shapes may launch both
        # a Mosaic build (TPU) and an interpreted build (CPU oracle)
        import numpy as _np

        # np.shape/np.result_type so raw scalars/lists are legal operands
        sig = (interpret,) + tuple(
            (tuple(_np.shape(v)), _np.result_type(v).name) for v in vals)
        call = self._cache.get(sig)
        if call is None:
            call = self._build(interpret)
            self._cache[sig] = call
        outs = call(*vals)
        outs = outs if isinstance(outs, (list, tuple)) else [outs]
        return [NDArray(data=o, ctx=ctx) for o in outs]

    __call__ = launch


class PallasModule:
    """A bag of user kernels (reference: rtc.cc::CudaModule).

    ``kernels``: mapping name -> Pallas kernel function (refs first, then
    keyword attrs). ``get_kernel(name, out_shapes, grid=None, **attrs)``
    binds launch metadata, mirroring CudaModule.get_kernel's signature
    declaration step.
    """

    def __init__(self, kernels, exports=None):
        if callable(kernels):
            kernels = {getattr(kernels, "__name__", "kernel"): kernels}
        self._kernels = dict(kernels)
        self.exports = list(exports or self._kernels)

    def get_kernel(self, name, out_shapes, grid=None, interpret=False,
                   **attrs):
        if name not in self._kernels:
            raise MXNetError(
                f"kernel {name!r} not in module (have {self.exports})")
        if not out_shapes:
            raise MXNetError("out_shapes is required: [(name, dtype, shape)]")
        return PallasKernel(name, self._kernels[name], out_shapes,
                            grid=grid, interpret=interpret, **attrs)


class CudaModule:
    def __init__(self, *a, **k):
        raise MXNetError(
            "mx.rtc.CudaModule compiles CUDA source, which cannot target "
            "the TPU MXU; port the kernel to mx.rtc.PallasModule "
            "(jax.experimental.pallas) — see SURVEY.md §2.1 RTC row")
