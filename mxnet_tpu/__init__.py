"""mxnet_tpu: a TPU-native deep-learning framework with MXNet's capabilities.

Built new on JAX/XLA/Pallas — NOT a port. See SURVEY.md for the reference
analysis (`532416645/mxnet`, an Apache MXNet 1.x fork) and the layer-by-layer
mapping. Import as::

    import mxnet_tpu as mx
    x = mx.nd.ones((2, 3), ctx=mx.tpu())

Layer map (reference → here):
  Engine/Storage/NDArray (C++)  → JAX async dispatch + mxnet_tpu.ndarray
  CachedOp / GraphExecutor      → jax.jit via HybridBlock.hybridize / Symbol
  KVStore nccl/dist_sync        → kvstore 'tpu_sync' (XLA collectives, ICI)
  Gluon / Module / optimizers   → mxnet_tpu.gluon / module / optimizer
"""
from __future__ import annotations

__version__ = "0.1.0"

import jax as _jax

# MXNet treats int64/float64 as first-class dtypes; JAX defaults to 32-bit.
# Enable x64 so explicit 64-bit dtypes round-trip (TPU compute stays in the
# dtype the user asked for; bf16/f32 remain the perf path).
_jax.config.update("jax_enable_x64", True)

# Counter-based RBG PRNG instead of threefry: dropout over transformer-sized
# activations generates hundreds of millions of random bits per step, and
# threefry does it in ALU ops while rbg uses the hardware generator (~2x
# cheaper measured on BERT-base). Trade-off: rbg streams are deterministic
# per seed only for a fixed compiler/sharding (XLA RngBitGenerator makes no
# cross-version/cross-mesh guarantee); the reference's CUDA cuRAND path has
# the same property. Set JAX_DEFAULT_PRNG_IMPL=threefry2x32 to get
# bit-stable streams back at a perf cost.
import os as _os

if not _os.environ.get("JAX_DEFAULT_PRNG_IMPL"):
    _jax.config.update("jax_default_prng_impl", "rbg")

# Persistent XLA compilation cache (reference counterpart: MXNet's op-level
# autotune caches / CUDA kernel cache). Training-step executables for
# transformer-sized models take minutes to build; caching them on disk makes
# the second process start in seconds. MXNET_XLA_CACHE_DIR overrides the
# base location; MXNET_XLA_CACHE=0 disables.
#
# The cache is namespaced per host-CPU feature set: jax's cache key does not
# include host ISA features, so an XLA:CPU AOT executable compiled on an
# AVX-512/AMX host replays on a host without them ("could lead to execution
# errors such as SIGILL" — cpu_aot_loader). A host with a different
# /proc/cpuinfo flag set gets its own subdirectory and recompiles.


# ISA-extension prefixes (x86 `flags` / ARM `Features`) that codegen can
# actually depend on; kernel-mitigation and power-management flags (md_clear,
# ibrs, retbleed, ...) churn with microcode/kernel updates and must not key
# the cache — they'd force full recompiles on identical hardware.
_ISA_PREFIXES = (
    "sse", "avx", "amx", "fma", "bmi", "aes", "sha", "mmx", "f16c",
    "pclmul", "vpclmul", "gfni", "vaes", "adx", "lzcnt", "popcnt", "abm",
    "movbe", "movdir", "xsave", "rtm", "rdrnd", "rdseed", "rdpid",
    "fsgsbase", "invpcid", "clflush", "clwb", "cldemote", "wbnoinvd",
    "serialize", "cmov", "cx8", "cx16", "fxsr", "crc32",
    "lahf", "kl", "widekl", "waitpkg", "enqcmd", "uintr", "hreset", "lm",
    "neon", "asimd", "sve", "fp", "fphp", "crypto", "atomics", "lse",
)
# deliberately absent: rtm/hle/tsxldtrk — TSX is routinely disabled by
# microcode mitigations (flag churn on identical hardware) and XLA codegen
# never emits it.


def _host_cpu_tag() -> str:
    import hashlib
    import platform

    feats = ""
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith(("flags", "Features")):
                    toks = line.split(":", 1)[1].split()
                    feats = " ".join(
                        sorted(t for t in toks if t.startswith(_ISA_PREFIXES)))
                    break
    except OSError:
        pass
    if not feats:
        # degraded path (no readable /proc/cpuinfo — non-Linux or /proc
        # unmounted): only the coarse arch is known, so hosts of the same
        # arch but different ISA extensions share a namespace and the
        # cross-host AOT protection is WEAK here; the distinct prefix
        # keeps these entries out of any verified-feature namespace.
        feats = "weak:" + (platform.processor() or platform.machine()
                           or "unknown")
    return hashlib.sha1(feats.encode()).hexdigest()[:12]


def _cache_default() -> str:
    # Pure-CPU processes (tests, the driver's virtual-mesh dryrun) default
    # to NO persistent cache: their compiles are cheap, and XLA:CPU AOT
    # entries are what trigger the cpu_aot_loader feature-probe warning on
    # every later load (the probe doesn't know the +prefer-no-scatter/
    # +prefer-no-gather tuning pseudo-features this XLA version compiles
    # with — benign same-host noise, but it pollutes driver artifacts and
    # reads like SIGILL risk). TPU-capable processes keep the cache (the
    # minutes-long transformer TrainStep compiles are the whole point);
    # their host-side CPU jits stay under the 1 s min-compile-time bar, so
    # no CPU AOT entries get written and the warning cannot fire.
    plats = _os.environ.get("JAX_PLATFORMS", "")
    toks = [t.strip() for t in plats.split(",") if t.strip()]
    if toks and all(t == "cpu" for t in toks):
        return "0"
    return "1"


if _os.environ.get("MXNET_XLA_CACHE", _cache_default()) != "0":
    _cache_dir = _os.path.join(
        _os.environ.get(
            "MXNET_XLA_CACHE_DIR",
            _os.path.join(_os.path.expanduser("~"), ".cache",
                          "mxnet_tpu_xla")),
        "host-" + _host_cpu_tag())
    try:
        _os.makedirs(_cache_dir, exist_ok=True)
        # one-time cleanup: flat entries written by versions before the
        # host namespacing have unknown host provenance (they're the
        # SIGILL-risk entries this scheme exists to quarantine) — delete
        # rather than migrate; they recompile once into the new subdir.
        # Match ONLY the exact filenames the jax compilation cache
        # writes (<fn>-<sha256 hex>-cache plus its -atime sidecar):
        # MXNET_XLA_CACHE_DIR may point at a shared directory, and a
        # broad *-cache sweep would unlink foreign files there.
        import re as _re

        _jax_cache_entry = _re.compile(
            r".+-[0-9a-f]{64}-(cache|atime)$").fullmatch
        _base = _os.path.dirname(_cache_dir)
        for _f in _os.listdir(_base):
            if _jax_cache_entry(_f) and _os.path.isfile(
                    _os.path.join(_base, _f)):
                try:
                    _os.unlink(_os.path.join(_base, _f))
                except OSError:
                    pass
        _jax.config.update("jax_compilation_cache_dir", _cache_dir)
        _jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        _jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    except Exception:  # pragma: no cover - cache is best-effort
        pass

from . import base
from .base import MXNetError
from .context import (
    Context,
    cpu,
    cpu_pinned,
    cpu_shared,
    gpu,
    tpu,
    current_context,
    num_gpus,
    num_tpus,
    num_devices,
)
from . import engine
from . import ndarray
from . import ndarray as nd
from . import autograd
from . import random
from . import random_state

from . import initializer
from . import init  # noqa: F401  (mx.init alias namespace)
from . import optimizer
from . import lr_scheduler
from . import metric
from . import gluon
from . import kvstore
from . import kvstore as kv
from . import io
from . import module
from . import module as mod
from . import parallel
from . import symbol
from . import symbol as sym
from . import tracing
from . import telemetry
from . import fault
from . import checkpoint
from . import serving
from . import profiler
from . import callback
from . import monitor
from . import numpy as np
from . import numpy_extension as npx
from . import contrib
from . import recordio
from . import image
from . import test_utils
from . import operator
from . import runtime
from . import rtc
from . import amp
from . import library
from . import subgraph
from . import storage
from . import visualization
from . import visualization as viz

from .ndarray import NDArray
from .optimizer import Optimizer
