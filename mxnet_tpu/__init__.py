"""mxnet_tpu: a TPU-native deep-learning framework with MXNet's capabilities.

Built new on JAX/XLA/Pallas — NOT a port. See SURVEY.md for the reference
analysis (`532416645/mxnet`, an Apache MXNet 1.x fork) and the layer-by-layer
mapping. Import as::

    import mxnet_tpu as mx
    x = mx.nd.ones((2, 3), ctx=mx.tpu())

Layer map (reference → here):
  Engine/Storage/NDArray (C++)  → JAX async dispatch + mxnet_tpu.ndarray
  CachedOp / GraphExecutor      → jax.jit via HybridBlock.hybridize / Symbol
  KVStore nccl/dist_sync        → kvstore 'tpu_sync' (XLA collectives, ICI)
  Gluon / Module / optimizers   → mxnet_tpu.gluon / module / optimizer
"""
from __future__ import annotations

__version__ = "0.1.0"

import jax as _jax

# MXNet treats int64/float64 as first-class dtypes; JAX defaults to 32-bit.
# Enable x64 so explicit 64-bit dtypes round-trip (TPU compute stays in the
# dtype the user asked for; bf16/f32 remain the perf path).
_jax.config.update("jax_enable_x64", True)

# Counter-based RBG PRNG instead of threefry: dropout over transformer-sized
# activations generates hundreds of millions of random bits per step, and
# threefry does it in ALU ops while rbg uses the hardware generator (~2x
# cheaper measured on BERT-base). Trade-off: rbg streams are deterministic
# per seed only for a fixed compiler/sharding (XLA RngBitGenerator makes no
# cross-version/cross-mesh guarantee); the reference's CUDA cuRAND path has
# the same property. Set JAX_DEFAULT_PRNG_IMPL=threefry2x32 to get
# bit-stable streams back at a perf cost.
import os as _os

if not _os.environ.get("JAX_DEFAULT_PRNG_IMPL"):
    _jax.config.update("jax_default_prng_impl", "rbg")

# Persistent XLA compilation cache — the compilation service's disk tier
# (reference counterpart: MXNet's op-level autotune caches / CUDA kernel
# cache). Training-step executables for transformer-sized models take
# minutes to build; caching them on disk makes the second process start in
# seconds. ISA-namespacing, size-capped GC and the knobs
# (MXNET_XLA_CACHE[_DIR|_MIN_COMPILE_S|_MAX_BYTES]) live in
# compiler/persistent.py; the signature manifest + AOT warm-start that
# replay INTO this cache live in the sibling compiler modules.
from .compiler import persistent as _persistent

_persistent.setup()

from . import base
from .base import MXNetError
from .context import (
    Context,
    cpu,
    cpu_pinned,
    cpu_shared,
    gpu,
    tpu,
    current_context,
    num_gpus,
    num_tpus,
    num_devices,
)
from . import engine
from . import ndarray
from . import ndarray as nd
from . import autograd
from . import random
from . import random_state

from . import initializer
from . import init  # noqa: F401  (mx.init alias namespace)
from . import optimizer
from . import lr_scheduler
from . import metric
from . import gluon
from . import kvstore
from . import kvstore as kv
from . import io
from . import module
from . import module as mod
from . import parallel
from . import symbol
from . import symbol as sym
from . import mutation
from . import tracing
from . import telemetry
from . import compiler
from . import fault
from . import checkpoint
from . import serving
from . import profiler
from . import callback
from . import monitor
from . import numpy as np
from . import numpy_extension as npx
from . import contrib
from . import recordio
from . import image
from . import test_utils
from . import operator
from . import runtime
from . import rtc
from . import amp
from . import library
from . import subgraph
from . import storage
from . import visualization
from . import visualization as viz

from .ndarray import NDArray
from .optimizer import Optimizer
