"""``mx.telemetry`` — always-available runtime metrics.

The reference pairs its dependency engine with a first-class profiler
(``src/profiler/profiler.cc``); profiling answers "where did this one run
spend its time", but a serving-scale system also needs cheap *structured
counters* that are always on in production: op mix, comms volume, compile
-cache behaviour, step throughput. This module is that spine: a thread-safe
registry of counters, gauges and fixed-bucket histograms (no unbounded
state) with three exporters:

* ``dumps()``       — structured JSON snapshot;
* ``prom_text()``   — Prometheus text exposition format (no dependency);
* ``chrome_counter_events()`` — chrome-trace ``ph:"C"`` counter events,
  merged into ``profiler.dumps(format="chrome_trace")``'s timeline.

Recording is **default-off**: every instrumented hot path guards on one
module-level flag (``_state.enabled`` — a single attribute load + branch)
so the disabled fast path costs one branch and allocates nothing. Enable
with ``MXNET_TELEMETRY=1`` in the environment or ``telemetry.enable()``.

Instrumented layers (each records through the ``record_*`` helpers below,
which also no-op when disabled, so call sites may skip the outer guard off
the hot path):

* op dispatch    — ``ops/registry.py::eager_call`` +
  ``ndarray.imperative_invoke`` (per-op counts, host dispatch latency);
* engine         — live-array gauge, ``wait_for_all`` block time,
  live-ref eviction counter (``engine.track`` overflow);
* kvstore        — push/pull/allreduce call counts, bytes moved, latency;
* jit caches     — hit/miss per cache (eager per-op executables, CachedOp,
  TrainStep, symbol Executor);
* training loop  — ``TrainingTelemetry`` step hook: step time,
  examples/sec, MFU (FLOP accounting shared with ``tools/cost_check.py``
  via :func:`xla_cost_analysis`).
"""
from __future__ import annotations

import json
import math
import os
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = [
    "enable", "disable", "enabled", "reset",
    "counter", "gauge", "histogram",
    "dumps", "prom_text", "chrome_counter_events", "snapshot",
    "start_exporter", "MetricsExporter",
    "parse_prom_text", "emit_prom_text", "scrape", "prom_value",
    "record_op_dispatch", "record_cache", "record_cache_eviction",
    "record_cold_start", "record_warm_start", "record_elastic_warm",
    "record_kv",
    "record_kv_collective", "record_kv_bucket", "record_kv_compression",
    "record_optimizer_dispatch", "record_optimizer_bucket",
    "record_engine_wait", "set_live_arrays", "record_live_evictions",
    "record_training_step", "record_xla_dispatch", "record_bulk_flush",
    "record_fault_injected", "record_retry", "record_checkpoint_write",
    "record_step_skipped",
    "record_data_wait", "set_data_queue_depth", "record_images_decoded",
    "record_serving_request", "record_serving_batch",
    "record_serving_queue_time", "set_serving_queue_depth",
    "record_serving_reload",
    "record_serving_shed", "record_serving_failover",
    "record_decode_step", "record_token", "set_kvcache_pages",
    "record_serving_route_retry", "record_router_queue_wait",
    "set_router_queue_depth", "set_replica_health",
    "record_breaker_transition", "record_router_request",
    "record_worker_restart", "record_ingress_rejected",
    "record_ingress_request", "set_ingress_connections",
    "set_router_inflight", "set_predicted_wait",
    "TrainingTelemetry", "xla_cost_analysis",
    "pop_telemetry_out_flag", "write_snapshot",
    "LATENCY_BUCKETS", "STEP_BUCKETS", "SEGMENT_BUCKETS",
    "BYTES_BUCKETS", "SERVING_BUCKETS", "OCCUPANCY_BUCKETS",
]


class _State:
    __slots__ = ("enabled",)

    def __init__(self, enabled: bool):
        self.enabled = enabled


# THE fast-path guard: instrumented modules read `_state.enabled` directly
# (one attribute load + branch; never swap the _State instance, callers
# cache a reference to it).
_state = _State(os.environ.get("MXNET_TELEMETRY", "0") == "1")


def enabled() -> bool:
    return _state.enabled


def enable() -> None:
    _state.enabled = True


def disable() -> None:
    _state.enabled = False


# ---------------------------------------------------------------------------
# Metric registry
# ---------------------------------------------------------------------------

_lock = threading.Lock()
_registry: Dict[str, "_Family"] = {}

# Per-family label-child cap: label values come from bounded sets (op names,
# cache names) but a bug upstream must degrade to a catch-all child, never
# to unbounded registry growth.
_MAX_CHILDREN = 4096
_OVERFLOW_LABEL = "_overflow"

# host-side dispatch/comms latencies: 10 µs .. 30 s, ~x3 geometric
LATENCY_BUCKETS: Tuple[float, ...] = (
    10e-6, 30e-6, 100e-6, 300e-6, 1e-3, 3e-3, 10e-3, 30e-3,
    100e-3, 300e-3, 1.0, 3.0, 10.0, 30.0)
# training steps: 1 ms .. 100 s
STEP_BUCKETS: Tuple[float, ...] = (
    1e-3, 3e-3, 10e-3, 30e-3, 100e-3, 300e-3, 1.0, 3.0, 10.0, 30.0, 100.0)
# bulk-segment lengths (op counts): powers of two up to the practical cap
SEGMENT_BUCKETS: Tuple[float, ...] = (
    1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024)
# payload sizes (gradient buckets): 4 KB .. 1 GB, x4 geometric
BYTES_BUCKETS: Tuple[float, ...] = (
    4096, 16384, 65536, 262144, 1 << 20, 4 << 20, 16 << 20, 64 << 20,
    256 << 20, 1 << 30)
# inference request latencies: LATENCY_BUCKETS bottoms out too coarse for
# serving p50s (a batched CPU dense dispatch answers in tens of µs) —
# 20 µs .. 10 s, ~x2–2.5 geometric, dense through the sub-millisecond range
SERVING_BUCKETS: Tuple[float, ...] = (
    20e-6, 50e-6, 100e-6, 200e-6, 500e-6, 1e-3, 2e-3, 5e-3, 10e-3,
    20e-3, 50e-3, 100e-3, 200e-3, 500e-3, 1.0, 2.0, 5.0, 10.0)
# batch occupancy (real rows / padded bucket capacity): eighths of a batch
OCCUPANCY_BUCKETS: Tuple[float, ...] = (
    0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0)


class _Counter:
    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError("counters only go up; use a gauge")
        with _lock:
            self.value += amount


class _Gauge:
    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0

    def set(self, value: float) -> None:
        with _lock:
            self.value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with _lock:
            self.value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)


class _Histogram:
    __slots__ = ("edges", "counts", "sum", "count", "exemplars")

    def __init__(self, edges: Tuple[float, ...]):
        self.edges = edges
        self.counts = [0] * (len(edges) + 1)   # last slot = +Inf
        self.sum = 0.0
        self.count = 0
        # OpenMetrics exemplars: bucket index -> (labels, value, ts).
        # None until the first exemplar so plain observes stay
        # allocation-free; kept as last-write-wins per bucket.
        self.exemplars = None

    def observe(self, value: float,
                exemplar: Optional[Dict[str, str]] = None) -> None:
        i = 0
        edges = self.edges
        n = len(edges)
        # linear scan: bucket lists are ~a dozen entries, and bisect on a
        # tuple of floats is not faster at this size
        while i < n and value > edges[i]:
            i += 1
        with _lock:
            self.counts[i] += 1
            self.sum += value
            self.count += 1
            if exemplar is not None:
                if self.exemplars is None:
                    self.exemplars = {}
                self.exemplars[i] = (dict(exemplar), value, time.time())


_KINDS = {"counter": _Counter, "gauge": _Gauge, "histogram": _Histogram}


class _Family:
    """One named metric with a fixed label schema and per-labelset children."""

    __slots__ = ("name", "kind", "help", "labelnames", "buckets", "children")

    def __init__(self, name, kind, help="", labelnames=(), buckets=None):
        self.name = name
        self.kind = kind
        self.help = help
        self.labelnames = tuple(labelnames)
        self.buckets = tuple(buckets) if buckets is not None else None
        self.children: Dict[Tuple[str, ...], object] = {}

    def labels(self, *values) -> object:
        if len(values) != len(self.labelnames):
            raise ValueError(
                f"{self.name}: expected labels {self.labelnames}, "
                f"got {values!r}")
        key = tuple(str(v) for v in values)
        child = self.children.get(key)
        if child is None:
            with _lock:
                child = self.children.get(key)
                if child is None:
                    if len(self.children) >= _MAX_CHILDREN:
                        key = (_OVERFLOW_LABEL,) * len(self.labelnames)
                        child = self.children.get(key)
                        if child is not None:
                            return child
                    child = (_Histogram(self.buckets)
                             if self.kind == "histogram"
                             else _KINDS[self.kind]())
                    self.children[key] = child
        return child

    # label-less convenience: family with no labelnames acts as its child
    def _solo(self):
        return self.labels()

    def inc(self, amount: float = 1.0):
        self._solo().inc(amount)

    def set(self, value: float):
        self._solo().set(value)

    def dec(self, amount: float = 1.0):
        self._solo().dec(amount)

    def observe(self, value: float,
                exemplar: Optional[Dict[str, str]] = None):
        self._solo().observe(value, exemplar=exemplar)


def _get_or_create(name, kind, help, labelnames, buckets=None) -> _Family:
    fam = _registry.get(name)
    if fam is not None:
        if (fam.kind != kind or fam.labelnames != tuple(labelnames)
                or (buckets is not None and fam.buckets != tuple(buckets))):
            raise ValueError(
                f"metric {name!r} already registered as {fam.kind} with "
                f"labels {fam.labelnames} and buckets {fam.buckets}")
        return fam
    with _lock:
        fam = _registry.get(name)
        if fam is None:
            fam = _Family(name, kind, help, labelnames, buckets)
            _registry[name] = fam
    return fam


def counter(name: str, help: str = "",
            labelnames: Sequence[str] = ()) -> _Family:
    """Get or create a monotonically-increasing counter family."""
    return _get_or_create(name, "counter", help, labelnames)


def gauge(name: str, help: str = "",
          labelnames: Sequence[str] = ()) -> _Family:
    """Get or create a gauge (set/inc/dec) family."""
    return _get_or_create(name, "gauge", help, labelnames)


def histogram(name: str, help: str = "", labelnames: Sequence[str] = (),
              buckets: Sequence[float] = LATENCY_BUCKETS) -> _Family:
    """Get or create a fixed-bucket histogram family."""
    edges = tuple(sorted(float(b) for b in buckets))
    if not edges:
        raise ValueError("histogram needs at least one bucket edge")
    return _get_or_create(name, "histogram", help, labelnames, edges)


def reset() -> None:
    """Drop all registered metrics (values AND families).

    Instrumentation re-creates families lazily through the ``record_*``
    helpers, so a full clear is safe; tests use this for isolation.
    """
    with _lock:
        _registry.clear()


# ---------------------------------------------------------------------------
# Exporters
# ---------------------------------------------------------------------------

def snapshot() -> Dict:
    """Point-in-time dict of every metric (the JSON exporter's payload)."""
    out: Dict = {"enabled": _state.enabled, "metrics": {}}
    with _lock:
        families = list(_registry.values())
    for fam in families:
        samples: List[Dict] = []
        with _lock:
            children = list(fam.children.items())
        for key, child in children:
            labels = dict(zip(fam.labelnames, key))
            if fam.kind == "histogram":
                with _lock:
                    counts = list(child.counts)
                    hsum, hcount = child.sum, child.count
                    exemplars = (dict(child.exemplars)
                                 if child.exemplars else None)
                cum = 0
                buckets = {}
                edges = list(fam.buckets) + [math.inf]
                ex_out = {}
                for i, (edge, c) in enumerate(zip(edges, counts)):
                    cum += c
                    le = _fmt_float(edge)
                    buckets[le] = cum
                    if exemplars is not None and i in exemplars:
                        xlabels, xval, xts = exemplars[i]
                        ex_out[le] = {"labels": xlabels, "value": xval,
                                      "ts": xts}
                buckets["+Inf"] = hcount
                sample = {"labels": labels, "sum": hsum,
                          "count": hcount, "buckets": buckets}
                if ex_out:
                    sample["exemplars"] = ex_out
                samples.append(sample)
            else:
                samples.append({"labels": labels, "value": child.value})
        out["metrics"][fam.name] = {
            "type": fam.kind, "help": fam.help, "samples": samples}
    return out


def dumps(indent: Optional[int] = None) -> str:
    """Structured JSON snapshot of all metrics."""
    return json.dumps(snapshot(), indent=indent, sort_keys=True)


def _fmt_float(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    s = repr(float(v))
    return s[:-2] if s.endswith(".0") else s


def _esc_label(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _prom_labels(labels: Dict[str, str], extra: Tuple[str, str] = None) -> str:
    items = list(labels.items())
    if extra is not None:
        items.append(extra)
    if not items:
        return ""
    body = ",".join(f'{k}="{_esc_label(str(v))}"' for k, v in items)
    return "{" + body + "}"


def prom_text() -> str:
    """Prometheus text exposition format (version 0.0.4) of all metrics."""
    snap = snapshot()
    lines: List[str] = []
    for name in sorted(snap["metrics"]):
        fam = snap["metrics"][name]
        if fam["help"]:
            lines.append(f"# HELP {name} {fam['help']}")
        lines.append(f"# TYPE {name} {fam['type']}")
        for s in fam["samples"]:
            if fam["type"] == "histogram":
                exemplars = s.get("exemplars") or {}
                for le, cum in s["buckets"].items():
                    line = (f"{name}_bucket"
                            f"{_prom_labels(s['labels'], ('le', le))} {cum}")
                    ex = exemplars.get(le)
                    if ex is not None:
                        # OpenMetrics exemplar suffix:
                        #   ... 5 # {trace_id="deadbeef"} 0.053 1690000000.0
                        line += (f" # {_prom_labels(ex['labels'])} "
                                 f"{_fmt_float(ex['value'])}"
                                 + (f" {_fmt_float(ex['ts'])}"
                                    if ex.get("ts") is not None else ""))
                    lines.append(line)
                lines.append(
                    f"{name}_sum{_prom_labels(s['labels'])} "
                    f"{_fmt_float(s['sum'])}")
                lines.append(
                    f"{name}_count{_prom_labels(s['labels'])} {s['count']}")
            else:
                lines.append(
                    f"{name}{_prom_labels(s['labels'])} "
                    f"{_fmt_float(s['value'])}")
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# HTTP exporter + scrape parser: the cross-process half of telemetry.
# A process (serving worker, router host) exposes /metrics + /healthz via
# stdlib http.server; a scraper (FleetController's ScrapeFleetSignals,
# Prometheus itself) pulls the text format back and parses it — the only
# signal channel that works when the observed fleet is not in the
# observer's address space.
# ---------------------------------------------------------------------------

class MetricsExporter:
    """Serve ``/metrics`` (Prometheus text 0.0.4 via :func:`prom_text`)
    and ``/healthz`` (JSON; ``healthz_fn`` supplies the body) from a
    daemon thread. ``port=0`` binds an ephemeral port — read
    :attr:`port` after construction."""

    def __init__(self, port: int = 0, host: str = "127.0.0.1",
                 healthz_fn=None):
        import http.server

        exporter = self

        class _Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):          # noqa: N802 - stdlib contract
                path = self.path.split("?", 1)[0]
                if path == "/metrics":
                    body = prom_text().encode("utf-8")
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                elif path == "/varz":
                    # the /metrics payload without the prometheus
                    # lossiness: full JSON snapshot, exemplars included
                    body = dumps(indent=2).encode("utf-8")
                    ctype = "application/json"
                elif path == "/traces":
                    # flight-recorder ring as JSONL (one event or
                    # completed trace per line); empty when tracing off
                    from . import tracing
                    body = tracing.dump_jsonl().encode("utf-8")
                    ctype = "application/jsonl"
                elif path == "/healthz":
                    try:
                        payload = (exporter.healthz_fn()
                                   if exporter.healthz_fn else
                                   {"ok": True, "pid": os.getpid()})
                    except Exception as e:  # noqa: BLE001 - report it
                        payload = {"ok": False, "error": str(e)}
                    body = json.dumps(payload).encode("utf-8")
                    ctype = "application/json"
                else:
                    self.send_error(404)
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # scrapes are high-rate; silence
                pass

        self.healthz_fn = healthz_fn
        self._server = http.server.ThreadingHTTPServer(
            (host, port), _Handler)
        self._server.daemon_threads = True
        self.host = host
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name=f"telemetry-exporter-{self.port}", daemon=True)
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}/metrics"

    def stop(self, timeout: Optional[float] = 5.0) -> None:
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout)


def start_exporter(port: int = 0, host: str = "127.0.0.1",
                   healthz_fn=None) -> MetricsExporter:
    """Start a :class:`MetricsExporter`; returns it (``.port``/``.url``/
    ``.stop()``)."""
    return MetricsExporter(port=port, host=host, healthz_fn=healthz_fn)


def _unquote_label(s: str, i: int) -> Tuple[str, int]:
    """Parse one double-quoted prometheus label value starting at the
    opening quote ``s[i]``; returns (value, index past closing quote).
    Inverse of :func:`_esc_label`: ``\\\\``, ``\\"`` and ``\\n``."""
    if s[i] != '"':
        raise ValueError(f"expected '\"' at col {i} of {s!r}")
    i += 1
    buf: List[str] = []
    while True:
        if i >= len(s):
            raise ValueError(f"unterminated label value in {s!r}")
        c = s[i]
        if c == "\\":
            nxt = s[i + 1] if i + 1 < len(s) else ""
            buf.append({"\\": "\\", '"': '"', "n": "\n"}.get(nxt, nxt))
            i += 2
        elif c == '"':
            return "".join(buf), i + 1
        else:
            buf.append(c)
            i += 1


def _parse_label_set(s: str, i: int) -> Tuple[Dict[str, str], int]:
    """Parse ``{k="v",...}`` starting at the opening brace ``s[i]``;
    returns (labels, index past the closing brace)."""
    labels: Dict[str, str] = {}
    i += 1
    while i < len(s) and s[i] != "}":
        eq = s.index("=", i)
        key = s[i:eq].strip().lstrip(",").strip()
        value, i = _unquote_label(s, eq + 1)
        labels[key] = value
        if i < len(s) and s[i] == ",":
            i += 1
    if i >= len(s) or s[i] != "}":
        raise ValueError(f"unterminated label set in {s!r}")
    return labels, i + 1


def _parse_exemplar(text: str) -> Dict:
    """OpenMetrics exemplar tail ``{labels} value [ts]`` -> dict."""
    text = text.strip()
    labels: Dict[str, str] = {}
    i = 0
    if text.startswith("{"):
        labels, i = _parse_label_set(text, 0)
    rest = text[i:].split()
    if not rest:
        raise ValueError(f"exemplar with no value in {text!r}")
    ex: Dict = {"labels": labels, "value": float(rest[0])}
    if len(rest) > 1:
        ex["ts"] = float(rest[1])
    return ex


def _parse_sample_line(line: str
                       ) -> Tuple[str, Dict[str, str], float,
                                  Optional[Dict]]:
    """One exposition sample line -> (sample_name, labels, value,
    exemplar-or-None). The `` # {...} v [ts]`` OpenMetrics exemplar
    suffix is preserved structurally, never folded into the value."""
    brace = line.find("{")
    if brace == -1:
        main, _, ex_text = line.partition(" # ")
        name, _, val = main.partition(" ")
        return (name, {}, float(val),
                _parse_exemplar(ex_text) if ex_text else None)
    name = line[:brace]
    # the main label set may contain a quoted '#': parse it first, then
    # look for the exemplar separator in the remainder only
    labels, i = _parse_label_set(line, brace)
    main, _, ex_text = line[i:].partition(" # ")
    return (name, labels, float(main.strip()),
            _parse_exemplar(ex_text) if ex_text else None)


def parse_prom_text(text: str) -> Dict[str, Dict]:
    """Parse Prometheus text exposition (the :func:`prom_text` format)
    into ``{family: {"type", "help", "samples": [{"name", "labels",
    "value"}]}}``. Histogram ``_bucket``/``_sum``/``_count`` samples are
    attributed to their family; label-value escaping is fully reversed
    (``\\\\`` / ``\\"`` / ``\\n``). Malformed lines raise ``ValueError``
    — a scrape that half-parses is worse than one that fails."""
    out: Dict[str, Dict] = {}

    def family(name: str) -> Dict:
        fam = out.get(name)
        if fam is None:
            fam = out[name] = {"type": None, "help": "", "samples": []}
        return fam

    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("# HELP "):
            name, _, help_text = line[len("# HELP "):].partition(" ")
            family(name)["help"] = help_text
        elif line.startswith("# TYPE "):
            name, _, kind = line[len("# TYPE "):].partition(" ")
            family(name)["type"] = kind.strip()
        elif line.startswith("#"):
            continue
        else:
            sname, labels, value, exemplar = _parse_sample_line(line)
            fam_name = sname
            if fam_name not in out:
                for suffix in ("_bucket", "_sum", "_count"):
                    if sname.endswith(suffix) and \
                            sname[: -len(suffix)] in out:
                        fam_name = sname[: -len(suffix)]
                        break
            sample = {"name": sname, "labels": labels, "value": value}
            if exemplar is not None:
                sample["exemplar"] = exemplar
            family(fam_name)["samples"].append(sample)
    return out


def emit_prom_text(parsed: Dict[str, Dict]) -> str:
    """Re-emit a :func:`parse_prom_text` structure as exposition text
    (label values re-escaped) — ``parse -> emit -> parse`` is the
    identity, which is what makes the scrape channel trustworthy."""
    lines: List[str] = []
    for name in sorted(parsed):
        fam = parsed[name]
        if fam.get("help"):
            lines.append(f"# HELP {name} {fam['help']}")
        if fam.get("type"):
            lines.append(f"# TYPE {name} {fam['type']}")
        for s in fam["samples"]:
            line = (f"{s['name']}{_prom_labels(s['labels'])} "
                    f"{_fmt_float(s['value'])}")
            ex = s.get("exemplar")
            if ex is not None:
                line += (f" # {_prom_labels(ex['labels'])} "
                         f"{_fmt_float(ex['value'])}"
                         + (f" {_fmt_float(ex['ts'])}"
                            if ex.get("ts") is not None else ""))
            lines.append(line)
    return "\n".join(lines) + "\n"


def scrape(url: str, timeout_s: float = 2.0) -> Dict[str, Dict]:
    """HTTP GET ``url`` (a ``/metrics`` endpoint) and parse it. Stdlib
    urllib; raises on HTTP/socket errors (the caller decides whether a
    failed scrape is fatal — the autoscaler skips the tick)."""
    import urllib.request

    with urllib.request.urlopen(url, timeout=timeout_s) as resp:
        return parse_prom_text(resp.read().decode("utf-8"))


def prom_value(parsed: Dict[str, Dict], name: str,
               labels: Optional[Dict[str, str]] = None,
               default: float = 0.0) -> float:
    """Sum of the samples named exactly ``name`` whose labels are a
    superset of ``labels`` (counters with label dimensions scrape back
    as one series per labelset; the controller wants the total)."""
    fam = parsed.get(name)
    if fam is None:
        return default
    want = labels or {}
    total, hit = 0.0, False
    for s in fam["samples"]:
        if s["name"] != name:
            continue
        if all(s["labels"].get(k) == v for k, v in want.items()):
            total += s["value"]
            hit = True
    return total if hit else default


def chrome_counter_events(ts_us: Optional[float] = None) -> List[Dict]:
    """Current counter/gauge values as chrome-trace ``ph:"C"`` events.

    ``profiler.dumps(format="chrome_trace")`` merges these onto its
    timeline so about:tracing shows telemetry counters next to the spans.
    Histograms contribute their ``_count`` and ``_sum`` series.
    """
    if ts_us is None:
        ts_us = time.perf_counter() * 1e6
    snap = snapshot()
    events: List[Dict] = []
    for name, fam in sorted(snap["metrics"].items()):
        for s in fam["samples"]:
            series = "/".join(v for v in s["labels"].values()) or "value"
            if fam["type"] == "histogram":
                args = {series + "_count": s["count"],
                        series + "_sum": s["sum"]}
            else:
                args = {series: s["value"]}
            events.append({"name": name, "ph": "C", "pid": 0, "tid": 0,
                           "ts": ts_us, "args": args})
    return events


# ---------------------------------------------------------------------------
# Tool plumbing: the shared `--telemetry-out PATH` contract (bench.py,
# tools/trace_ops.py) lives here so the flag cannot drift between tools.
# ---------------------------------------------------------------------------

def pop_telemetry_out_flag(argv: Sequence[str]
                           ) -> Tuple[List[str], Optional[str]]:
    """Strip ``--telemetry-out PATH`` / ``--telemetry-out=PATH`` from argv.

    Returns ``(argv_without_flag, path_or_None)`` — positionals keep their
    slots. A flag with no PATH is a hard error (SystemExit) rather than a
    silent no-snapshot run discovered only after an expensive trace.
    """
    out: List[str] = []
    path: Optional[str] = None
    it = iter(argv)
    for a in it:
        if a == "--telemetry-out":
            path = next(it, None)
        elif a.startswith("--telemetry-out="):
            path = a.split("=", 1)[1]
        else:
            out.append(a)
            continue
        if not path or path.startswith("-"):
            # a following option is NOT a path — erroring beats silently
            # consuming the flag and snapshotting into "--some-flag"
            raise SystemExit("--telemetry-out requires a PATH argument")
    return out, path


def write_snapshot(path: str) -> None:
    """Write an indented JSON snapshot to ``path`` (tool exit hook).

    Atomic (temp + fsync + rename via :func:`checkpoint.atomic_write`):
    a scraper or post-mortem reader never sees a half-written snapshot,
    and a crash mid-dump leaves the previous one intact."""
    from . import checkpoint   # lazy: avoid import cycle at module load

    checkpoint.atomic_write(path, dumps(indent=2).encode("utf-8"))


# MXNET_TELEMETRY_OUT=PATH: enable recording and write a snapshot at
# interpreter exit — how driver-spawned subprocesses (bench.py's BERT/
# Llama stages) report telemetry without any CLI plumbing of their own.
_env_out = os.environ.get("MXNET_TELEMETRY_OUT")
if _env_out:
    import atexit

    _state.enabled = True
    atexit.register(write_snapshot, _env_out)


# ---------------------------------------------------------------------------
# Recording helpers — the one place metric names/schemas are defined.
# All no-op when telemetry is disabled.
# ---------------------------------------------------------------------------

def record_op_dispatch(op: str, seconds: float) -> None:
    """One imperative op dispatch: per-op count + host latency."""
    if not _state.enabled:
        return
    counter("mxnet_op_dispatch_total",
            "Imperative op dispatches by op name.",
            ("op",)).labels(op).inc()
    histogram("mxnet_op_dispatch_seconds",
              "Host-side dispatch latency per op (async: excludes device "
              "execution).", ("op",)).labels(op).observe(seconds)


def record_cache(cache: str, hit: bool) -> None:
    """One lookup in a jit/CachedOp compile cache."""
    if not _state.enabled:
        return
    counter("mxnet_jit_cache_total",
            "Compile-cache lookups by cache and result.",
            ("cache", "result")).labels(
                cache, "hit" if hit else "miss").inc()


def record_cache_eviction(cache: str, n: int = 1) -> None:
    """LRU eviction(s) from a compile cache (or the persistent XLA disk
    tier). Previously silent — a thrashing cache recompiled forever with
    nothing on the dashboard; now the rate is a first-class signal."""
    if not _state.enabled:
        return
    counter("mxnet_jit_cache_evictions_total",
            "Compile-cache LRU evictions by cache.",
            ("cache",)).labels(cache).inc(n)


def record_cold_start(event: str, seconds: float) -> None:
    """A cold-start milestone (``compiler.mark_event``): seconds from
    package import to the first ``warm_start_done`` / ``first_train_step``
    / ``first_response``. Set once per event per process."""
    if not _state.enabled:
        return
    gauge("mxnet_coldstart_seconds",
          "Seconds from package import to each first-time lifecycle "
          "event.", ("event",)).labels(event).set(seconds)


def record_elastic_warm(seconds: float) -> None:
    """Duration of one elastic warm_start hook (fires per membership
    epoch — a DURATION histogram, distinct from the since-import
    ``mxnet_coldstart_seconds`` milestones)."""
    if not _state.enabled:
        return
    histogram("mxnet_elastic_warm_seconds",
              "Elastic warm_start hook duration per (re-)bootstrap.",
              buckets=STEP_BUCKETS).observe(seconds)


def record_warm_start(outcome: str, n: int = 1) -> None:
    """Manifest warm-start replay outcomes (``replayed``: compiled AOT,
    ``deduped``: already in the in-process executable table, ``skipped``:
    no provider for the entry, ``failed``)."""
    if not _state.enabled:
        return
    counter("mxnet_compile_warm_total",
            "Signature-manifest warm-start entries by outcome.",
            ("outcome",)).labels(outcome).inc(n)


def record_kv(op: str, nbytes: float, seconds: float) -> None:
    """One kvstore operation (push/pull/allreduce/row_sparse_pull)."""
    if not _state.enabled:
        return
    counter("mxnet_kvstore_calls_total",
            "KVStore operations by kind.", ("op",)).labels(op).inc()
    counter("mxnet_kvstore_bytes_total",
            "Payload bytes moved through the kvstore by kind.",
            ("op",)).labels(op).inc(float(nbytes))
    histogram("mxnet_kvstore_seconds",
              "Host-side kvstore call latency by kind.",
              ("op",)).labels(op).observe(seconds)


def record_kv_collective(path: str, n: int = 1) -> None:
    """One gradient-reduction dispatch on the comms path. ``path``:
    ``per_key`` (one reduce/psum per parameter — the reference shape),
    ``bucketed`` (one collective per fused gradient bucket),
    ``hierarchical`` (one topology-aware bucket collective — intra-host
    ICI + inter-host DCN factored through the 2-D device mesh; the count
    IS the inter-host dispatch count, exactly one per bucket), or
    ``zero`` (one fused reduce-scatter + shard-update + allgather
    program per ZeRO bucket). The per-step dispatch-reduction ratio in
    BENCH/PERF rounds is computed from this."""
    if not _state.enabled:
        return
    counter("mxnet_kvstore_collective_dispatch_total",
            "Gradient-reduction collective dispatches by path "
            "(per_key/bucketed/hierarchical/zero).", ("path",)).labels(path).inc(n)


def record_kv_bucket(nbytes: float, nkeys: int) -> None:
    """One fused gradient bucket exchanged by batched pushpull."""
    if not _state.enabled:
        return
    histogram("mxnet_kvstore_bucket_bytes",
              "Payload bytes per fused gradient bucket.",
              buckets=BYTES_BUCKETS).observe(float(nbytes))
    counter("mxnet_kvstore_bucketed_keys_total",
            "Parameter keys coalesced through bucketed pushpull."
            ).inc(nkeys)


def record_kv_bucket_fallback(reason: str, nkeys: int = 1) -> None:
    """Keys that fell OFF the fused bucketed-pushpull path back to the
    per-key exchange. ``reason``: ``row_sparse`` (non-default storage —
    PR 5's documented gap), ``zero_family`` (optimizer family the ZeRO
    shard sweep cannot reproduce bit-exactly, e.g. LAMB's cross-member
    trust-ratio norms), ``zero_multi_precision``, ``zero_sparse``.
    Observability for coverage gaps that used to be silent."""
    if not _state.enabled:
        return
    counter("mxnet_kvstore_bucket_fallback_total",
            "Keys excluded from fused bucketed pushpull by reason.",
            ("reason",)).labels(reason).inc(nkeys)


def record_optimizer_state_bytes(mode: str, nbytes: float) -> None:
    """Persistent optimizer-state bytes held by THIS rank, by layout
    ``mode``: ``replicated`` (every rank holds the full state — the
    reference KVStore shape), ``zero1`` / ``zero2`` (this rank's shard
    under ZeRO partitioning). The ZeRO engine publishes BOTH its actual
    per-rank bytes and the replicated-equivalent total, so the ~1/world
    memory drop is read directly off the gauge pair."""
    if not _state.enabled:
        return
    gauge("mxnet_optimizer_state_bytes",
          "Per-rank persistent optimizer-state bytes by layout mode.",
          ("mode",)).labels(mode).set(float(nbytes))


def record_kv_compression(ratio: float, elements: int) -> None:
    """One compressed bucket. ``ratio``: logical wire compression
    (uncompressed payload bits / 2-bit payload, e.g. 16x for fp32)."""
    if not _state.enabled:
        return
    gauge("mxnet_kvstore_compression_ratio",
          "Logical wire compression of the most recent compressed "
          "bucket (uncompressed bits / 2-bit quantized bits).").set(ratio)
    counter("mxnet_kvstore_compressed_elements_total",
            "Gradient elements through the 2-bit quantizer.").inc(elements)


def record_pallas_dispatch(kernel: str, n: int = 1) -> None:
    """A Pallas kernel routed into a trace. ``kernel``: flash_attention /
    fused_layer_norm / fused_rms_norm / fused_bias_gelu / ... Counts
    ROUTING decisions (the Python dispatch site runs once per trace, not
    per executed step), so this is the kernel ADOPTION observable: zero
    while MXNET_PALLAS_FUSED / shape gates keep a model on the eager
    path, one per kernel site per compiled executable otherwise."""
    if not _state.enabled:
        return
    counter("mxnet_pallas_dispatch_total",
            "Pallas-kernel routings into compiled traces by kernel "
            "(adoption counter: one per kernel site per trace).",
            ("kernel",)).labels(kernel).inc(n)


def record_optimizer_dispatch(path: str, n: int = 1) -> None:
    """One optimizer-phase update dispatch on the eager Trainer path.
    ``path``: ``per_param`` (one updater call per parameter — the
    reference shape) or ``fused_sweep`` (one packed multi-tensor sweep
    per dtype bucket). The O(params) -> O(buckets) collapse the fused
    engine exists for is read directly off this counter."""
    if not _state.enabled:
        return
    counter("mxnet_optimizer_dispatch_total",
            "Optimizer-phase update dispatches by path "
            "(per_param/fused_sweep).", ("path",)).labels(path).inc(n)


def record_optimizer_bucket(nbytes: float, nparams: int) -> None:
    """One fused optimizer bucket swept (packed multi-tensor update)."""
    if not _state.enabled:
        return
    histogram("mxnet_optimizer_bucket_bytes",
              "Parameter bytes per fused optimizer sweep bucket.",
              buckets=BYTES_BUCKETS).observe(float(nbytes))
    counter("mxnet_optimizer_bucketed_params_total",
            "Parameters updated through fused multi-tensor sweeps."
            ).inc(nparams)


def record_kv_overlap(when: str, n: int = 1) -> None:
    """One gradient-bucket pushpull dispatched by the overlapped-comms
    trainer. ``when``: ``backward`` (issued from the grad-ready hook
    while autograd's reverse sweep was still running — the overlap win)
    or ``step`` (flushed by Trainer.step for buckets whose members never
    became ready in the backward)."""
    if not _state.enabled:
        return
    counter("mxnet_kvstore_overlap_dispatch_total",
            "Overlapped-comms bucket dispatches by phase "
            "(backward/step).", ("when",)).labels(when).inc(n)


def record_engine_wait(seconds: float) -> None:
    if not _state.enabled:
        return
    histogram("mxnet_engine_wait_all_seconds",
              "Time blocked in engine.wait_for_all.").observe(seconds)


def set_live_arrays(n: int) -> None:
    if not _state.enabled:
        return
    gauge("mxnet_engine_live_arrays",
          "Arrays tracked by the engine whose async work may be in "
          "flight.").set(n)


def record_live_evictions(n: int) -> None:
    """Still-live refs evicted by engine.track overflow compaction —
    a nonzero rate means wait_for_all coverage is leaking."""
    if not _state.enabled or n <= 0:
        return
    counter("mxnet_engine_live_evictions_total",
            "Still-live refs evicted from the engine registry by "
            "overflow compaction.").inc(n)


def record_xla_dispatch(kind: str) -> None:
    """One host→XLA dispatch (a compiled-callable invocation). ``kind``:
    ``eager_op`` (cached per-op executable), ``eager_uncached`` (tracer/
    fallback path), ``fused_segment`` (one bulked segment). The eager-vs-
    bulk dispatch-reduction ratio in BENCH rounds is computed from this."""
    if not _state.enabled:
        return
    counter("mxnet_xla_dispatch_total",
            "Host-side XLA dispatches by kind (a fused bulk segment "
            "counts once however many ops it contains).",
            ("kind",)).labels(kind).inc()


def record_bulk_flush(reason: str, n_ops: int, seconds: float) -> None:
    """One bulk-segment flush: why it flushed, how many ops it fused,
    and host-side flush latency (cache lookup + dispatch)."""
    if not _state.enabled:
        return
    counter("mxnet_bulk_flush_total",
            "Bulk segment flushes by trigger (sync/size/unrecordable/"
            "scope_exit/nested_scope).", ("reason",)).labels(reason).inc()
    counter("mxnet_bulk_ops_total",
            "Imperative ops executed via fused bulk segments.").inc(n_ops)
    histogram("mxnet_bulk_segment_ops",
              "Ops fused per flushed bulk segment.",
              buckets=SEGMENT_BUCKETS).observe(n_ops)
    histogram("mxnet_bulk_flush_seconds",
              "Host-side bulk flush latency (fused-cache lookup + "
              "dispatch).").observe(seconds)


def record_fault_injected(site: str) -> None:
    """One fault fired by the injector (mxnet_tpu/fault.py)."""
    if not _state.enabled:
        return
    counter("mxnet_fault_injected_total",
            "Faults fired by the fault injector by site.",
            ("site",)).labels(site).inc()


def record_retry(site: str, outcome: str) -> None:
    """One retry event at a comms/IO site. ``outcome``: ``retry`` (one
    failed attempt), ``recovered`` (call succeeded after >=1 retry),
    ``exhausted`` (attempts used up, error surfaced)."""
    if not _state.enabled:
        return
    counter("mxnet_retry_total",
            "Retry events by site and outcome (retry/recovered/"
            "exhausted).", ("site", "outcome")).labels(site, outcome).inc()


def record_checkpoint_write(seconds: float) -> None:
    """One committed checkpoint bundle write (manifest valid on disk)."""
    if not _state.enabled:
        return
    histogram("mxnet_checkpoint_write_seconds",
              "Wall time to write + commit one checkpoint bundle.",
              buckets=STEP_BUCKETS).observe(seconds)


def record_step_skipped(reason: str) -> None:
    """One training step skipped by an anomaly guard. ``reason``:
    ``nonfinite_grad`` (Trainer guard) or ``amp_overflow`` (loss-scaler
    backoff)."""
    if not _state.enabled:
        return
    counter("mxnet_steps_skipped_total",
            "Training steps skipped by anomaly guards, by reason.",
            ("reason",)).labels(reason).inc()


def set_elastic_epoch(epoch: int) -> None:
    """Current elastic membership epoch (parallel/elastic.py) — bumps
    on every worker join/leave re-bootstrap."""
    if not _state.enabled:
        return
    gauge("mxnet_elastic_membership_epoch",
          "Elastic membership epoch (monotonic; one bump per worker "
          "join/leave re-bootstrap).").set(int(epoch))


def record_elastic_restart(n: int = 1) -> None:
    """Worker restarts observed by the elastic runtime: a rank's own
    rejoin-restore from a bundle, plus each sibling rejoin it
    witnesses."""
    if not _state.enabled or n <= 0:
        return
    counter("mxnet_elastic_worker_restarts_total",
            "Worker restarts observed by the elastic runtime "
            "(self rejoin-restores + witnessed sibling rejoins).").inc(n)


def record_elastic_heartbeat_miss(rank) -> None:
    """One rank declared dead by heartbeat expiry
    (MXNET_ELASTIC_HEARTBEAT_TIMEOUT exceeded)."""
    if not _state.enabled:
        return
    counter("mxnet_elastic_heartbeat_miss_total",
            "Heartbeat expiries (rank declared dead) by missed rank.",
            ("rank",)).labels(str(rank)).inc()


def record_elastic_preemption() -> None:
    """One graceful preemption leave: the runner checkpointed at the
    step boundary and exited for the supervisor to respawn (spot /
    preemptible capacity reclaim — the control plane's common case,
    not a failure)."""
    if not _state.enabled:
        return
    counter("mxnet_elastic_preemptions_total",
            "Graceful preemption leaves (checkpoint-then-exit on the "
            "preemption signal).").inc()


def set_fleet_size(n: int, router: str = "") -> None:
    """Current serving replica count behind the Router (non-draining) —
    the autoscaler's actuator state. Labeled by ``router``: a process
    may host several Routers (the bench does), and a scrape-fed
    controller must be able to tell whose fleet it is reading."""
    if not _state.enabled:
        return
    gauge("mxnet_controller_fleet_size",
          "Serving replicas currently in the Router fleet "
          "(draining replicas excluded).",
          ("router",)).labels(router).set(int(n))


def record_fleet_scale(direction: str, outcome: str = "ok") -> None:
    """One autoscaler action: ``direction`` up/down, ``outcome`` ok /
    failed (replica factory or start raised — the controller contains
    it and retries on a later tick)."""
    if not _state.enabled:
        return
    counter("mxnet_controller_scale_total",
            "Autoscaler scale actions by direction and outcome.",
            ("direction", "outcome")).labels(direction, outcome).inc()


def record_fleet_scale_seconds(direction: str, seconds: float) -> None:
    """Wall seconds for one completed scale action — scale-up includes
    the replica's full grid warmup (the number that must stay small for
    autoscaling to matter; warm-started spawn via the compilation
    service is what keeps it small)."""
    if not _state.enabled:
        return
    histogram("mxnet_controller_scale_seconds",
              "Scale-action duration (up includes replica warmup).",
              ("direction",), buckets=STEP_BUCKETS
              ).labels(direction).observe(seconds)


def record_upgrade_replica(outcome: str) -> None:
    """Rolling-upgrade per-replica outcomes: ``ok`` (swapped and baked
    healthy), ``rolled_back`` (this replica's old model was restored),
    ``aborted`` (rollout stopped before touching this replica)."""
    if not _state.enabled:
        return
    counter("mxnet_serving_upgrade_total",
            "Rolling-upgrade replica outcomes.",
            ("outcome",)).labels(outcome).inc()


def record_data_wait(seconds: float, stage: str = "device_feed") -> None:
    """Time the consumer blocked waiting on an input-pipeline stage.

    The host-vs-device starvation discriminator: a real-data step whose
    ``mxnet_data_wait_seconds`` sum approaches wall time is host-starved
    (feed the device more); one near zero is device-bound (the pipeline
    keeps up)."""
    if not _state.enabled:
        return
    histogram("mxnet_data_wait_seconds",
              "Time the training loop blocked waiting for the input "
              "pipeline, by stage.", ("stage",)).labels(stage).observe(seconds)


def set_data_queue_depth(stage: str, depth: int) -> None:
    """Prefetched batches currently ready in a pipeline stage's queue."""
    if not _state.enabled:
        return
    gauge("mxnet_data_queue_depth",
          "Prefetched batches ready per input-pipeline stage.",
          ("stage",)).labels(stage).set(depth)


def record_images_decoded(n: int) -> None:
    """Images decoded+augmented by the host input pipeline."""
    if not _state.enabled or n <= 0:
        return
    counter("mxnet_data_decoded_images_total",
            "Images decoded and augmented by the input pipeline.").inc(n)


def record_serving_request(seconds: float, outcome: str = "ok",
                           trace_id: Optional[str] = None,
                           model: Optional[str] = None) -> None:
    """One served request, end-to-end (submit -> future resolved).
    ``outcome``: ``ok``, ``error`` (dispatch failed after retries) or
    ``rejected`` (queue full / server stopped — no latency recorded).
    p50/p99 come from the histogram quantiles. ``trace_id`` (when the
    request was traced) becomes an OpenMetrics exemplar on the latency
    bucket it lands in — the jump from "p99 is slow" to THE trace that
    explains it. ``model`` (multi-tenant serving) additionally counts
    the request into the per-tenant family
    ``mxnet_serving_tenant_requests_total{model,outcome}`` — the
    unlabeled family stays the fleet total, so existing dashboards and
    label sets are untouched."""
    if not _state.enabled:
        return
    counter("mxnet_serving_requests_total",
            "Serving requests by outcome (ok/error/rejected).",
            ("outcome",)).labels(outcome).inc()
    if model is not None:
        counter("mxnet_serving_tenant_requests_total",
                "Serving requests per tenant model, by outcome.",
                ("model", "outcome")).labels(model, outcome).inc()
    if outcome != "rejected":
        histogram("mxnet_serving_request_seconds",
                  "End-to-end request latency (submit to future "
                  "resolution).", buckets=SERVING_BUCKETS).observe(
            seconds,
            exemplar=({"trace_id": trace_id}
                      if trace_id is not None else None))


def record_serving_batch(n_real: int, capacity: int, reason: str) -> None:
    """One dispatched inference batch. ``reason``: what closed it —
    ``full`` (bucket capacity reached), ``deadline`` (oldest request
    neared its SLO), ``drain`` (server stopping)."""
    if not _state.enabled:
        return
    counter("mxnet_serving_batches_total",
            "Inference batches dispatched, by close reason "
            "(full/deadline/drain).", ("reason",)).labels(reason).inc()
    if capacity > 0:
        histogram("mxnet_serving_batch_occupancy",
                  "Real requests / padded bucket capacity per dispatched "
                  "batch.", buckets=OCCUPANCY_BUCKETS).observe(
                      n_real / capacity)
    pad = capacity - n_real
    if pad > 0:
        counter("mxnet_serving_padded_slots_total",
                "Padding rows dispatched to round batches up to their "
                "bucket.").inc(pad)


def record_serving_queue_time(seconds: float) -> None:
    """Time one request spent queued before its batch dispatched."""
    if not _state.enabled:
        return
    histogram("mxnet_serving_time_in_queue_seconds",
              "Time a request waited in the submission queue before "
              "batch dispatch.", buckets=SERVING_BUCKETS).observe(seconds)


def set_serving_queue_depth(depth: int) -> None:
    """Requests currently waiting in the server's submission queue."""
    if not _state.enabled:
        return
    gauge("mxnet_serving_queue_depth",
          "Requests waiting in the serving submission queue.").set(depth)


def record_serving_reload(seconds: float, outcome: str = "ok") -> None:
    """One hot-reload attempt (build + restore + warmup + swap)."""
    if not _state.enabled:
        return
    counter("mxnet_serving_reloads_total",
            "Model hot-reload attempts by outcome (ok/error).",
            ("outcome",)).labels(outcome).inc()
    if outcome == "ok":
        histogram("mxnet_serving_reload_seconds",
                  "Wall time to build, warm and swap in a reloaded "
                  "model.", buckets=STEP_BUCKETS).observe(seconds)


def record_router_request(seconds: float, outcome: str = "ok",
                          trace_id: Optional[str] = None) -> None:
    """One Router-level request resolution. A SEPARATE family from
    ``mxnet_serving_requests_total``: every routed request is also
    counted by the replica Server that served it, and after a failover
    the layers legitimately disagree (replica error, router ok) — one
    shared counter would double-count RPS and mix the two stories.
    ``trace_id`` rides along as an exemplar (see
    :func:`record_serving_request`)."""
    if not _state.enabled:
        return
    counter("mxnet_serving_router_requests_total",
            "Router requests by final outcome (ok/error/rejected).",
            ("outcome",)).labels(outcome).inc()
    if outcome != "rejected":
        histogram("mxnet_serving_router_request_seconds",
                  "End-to-end router request latency (submit to future "
                  "resolution).", buckets=SERVING_BUCKETS).observe(
            seconds,
            exemplar=({"trace_id": trace_id}
                      if trace_id is not None else None))


def record_serving_shed(reason: str, model: Optional[str] = None) -> None:
    """One request shed by admission control. ``reason``:
    ``queue_full`` (bounded queue at capacity), ``predicted_wait``
    (predicted queue wait exceeds the request's deadline), ``expired``
    (deadline blew while queued — the in-queue safety net),
    ``kvcache_full`` (a generate request that cannot fit the paged
    KV-cache budget) or ``throttled`` (a tenant's admission token
    bucket is empty). ``model`` additionally counts into
    ``mxnet_serving_tenant_shed_total{model,reason}`` — the isolation
    witness: under one tenant's overload, shed increments stay
    confined to that tenant's label."""
    if not _state.enabled:
        return
    counter("mxnet_serving_shed_total",
            "Requests shed by router admission control, by reason "
            "(queue_full/predicted_wait/expired/kvcache_full/"
            "throttled).",
            ("reason",)).labels(reason).inc()
    if model is not None:
        counter("mxnet_serving_tenant_shed_total",
                "Requests shed per tenant model, by reason.",
                ("model", "reason")).labels(model, reason).inc()


def record_decode_step(n_requests: int,
                       model: Optional[str] = None) -> None:
    """One continuous-batching decode step: a single (batch, 1)
    executable advancing ``n_requests`` co-batched completions by one
    token each. ``model`` counts the step into the per-tenant family
    ``mxnet_serving_tenant_decode_steps_total{model}``."""
    if not _state.enabled:
        return
    counter("mxnet_serving_decode_steps_total",
            "Autoregressive decode steps dispatched (one fused "
            "(batch, 1) executable per step).").inc()
    histogram("mxnet_serving_decode_batch_width",
              "Active completions co-batched per decode step.",
              buckets=(1, 2, 4, 8, 16, 32, 64)).observe(n_requests)
    if model is not None:
        counter("mxnet_serving_tenant_decode_steps_total",
                "Decode steps dispatched per tenant model.",
                ("model",)).labels(model).inc()


def record_token(seconds: float, model: Optional[str] = None) -> None:
    """One emitted token's inter-token latency (prefill first token:
    submit -> first token, i.e. TTFT). ``model`` counts the token into
    ``mxnet_serving_tenant_tokens_total{model}`` — per-tenant token
    share is the weighted-fairness witness."""
    if not _state.enabled:
        return
    counter("mxnet_serving_tokens_total",
            "Tokens emitted by autoregressive decode (prefill first "
            "tokens included).").inc()
    histogram("mxnet_serving_token_seconds",
              "Per-token latency: time since the previous token of the "
              "same completion (first token: since submit — TTFT).",
              buckets=SERVING_BUCKETS).observe(seconds)
    if model is not None:
        counter("mxnet_serving_tenant_tokens_total",
                "Tokens emitted per tenant model.",
                ("model",)).labels(model).inc()


def set_tenant_queue_depth(depth: int, model: str,
                           router: str = "") -> None:
    """Requests currently queued for ONE tenant model (replica level
    when ``router`` is empty, router level otherwise). Scraped into
    :class:`~.serving.controller.ScrapeFleetSignals` so the autoscaler
    sees per-tenant backlog, not just the fleet total."""
    if not _state.enabled:
        return
    gauge("mxnet_serving_tenant_queue_depth",
          "Requests waiting per tenant model (replica queues when "
          "router label is empty, router queue otherwise).",
          ("model", "router")).labels(model, router).set(depth)


def record_preemption(victim: str, beneficiary: str) -> None:
    """One priority preemption: ``victim``'s stream had its KV-cache
    pages reclaimed (between decode steps) for a higher-priority
    ``beneficiary`` arrival. Both are tenant model names — the counter
    answers "who preempted whom"."""
    if not _state.enabled:
        return
    counter("mxnet_serving_preempted_total",
            "Generate streams preempted, by victim and beneficiary "
            "tenant model.",
            ("victim", "beneficiary")).labels(victim, beneficiary).inc()


def record_kvcache_defrag(n_moves: int) -> None:
    """One automatic KV-cache defrag pass (pages packed between decode
    steps when fragmentation crossed the server's threshold)."""
    if not _state.enabled:
        return
    counter("mxnet_serving_kvcache_defrag_total",
            "Automatic KV-cache defrag passes.").inc()
    if n_moves > 0:
        counter("mxnet_serving_kvcache_defrag_moves_total",
                "Pages moved by automatic KV-cache defrag passes."
                ).inc(n_moves)


def set_kvcache_pages(free: int, used: int, reserved: int = 0) -> None:
    """Paged KV-cache arena occupancy, by page state."""
    if not _state.enabled:
        return
    g = gauge("mxnet_serving_kvcache_pages",
              "KV-cache arena pages by state (free/used/reserved).",
              ("state",))
    g.labels("free").set(free)
    g.labels("used").set(used)
    g.labels("reserved").set(reserved)


def record_serving_failover(replica: str) -> None:
    """One request re-submitted away from a failed/hung replica."""
    if not _state.enabled:
        return
    counter("mxnet_serving_failover_total",
            "Requests failed over from a replica to a healthy sibling.",
            ("replica",)).labels(replica).inc()


def record_serving_route_retry(reason: str) -> None:
    """One routing retry event at the Router. ``reason``:
    ``route_fault`` (injected/transient routing failure),
    ``replica_error`` (dispatch failed at the replica),
    ``replica_down`` (replica stopped between health check and submit),
    ``hung`` (dispatch exceeded the dispatch timeout), ``refused``
    (replica queue refused the submit — retried, no budget burned)."""
    if not _state.enabled:
        return
    counter("mxnet_serving_route_retry_total",
            "Router routing retries, by reason (route_fault/"
            "replica_error/replica_down/hung/refused).",
            ("reason",)).labels(reason).inc()


def record_router_queue_wait(seconds: float) -> None:
    """Time one request spent in the ROUTER queue before being
    forwarded to a replica (replica queue time is
    ``mxnet_serving_time_in_queue_seconds``)."""
    if not _state.enabled:
        return
    histogram("mxnet_serving_router_queue_wait_seconds",
              "Time a request waited in the router queue before being "
              "forwarded to a replica.",
              buckets=SERVING_BUCKETS).observe(seconds)


def set_router_queue_depth(depth: int, router: str = "") -> None:
    """Requests currently waiting in the Router's global queue
    (labeled per router — see :func:`set_fleet_size`)."""
    if not _state.enabled:
        return
    gauge("mxnet_serving_router_queue_depth",
          "Requests waiting in the serving router's global queue.",
          ("router",)).labels(router).set(depth)


def set_replica_health(replica: str, value: float) -> None:
    """Per-replica health gauge: 1 = closed (healthy), 0.5 = half-open
    (probing), 0 = open (quarantined)."""
    if not _state.enabled:
        return
    gauge("mxnet_serving_replica_healthy",
          "Replica circuit-breaker health (1 closed / 0.5 half-open / "
          "0 open).", ("replica",)).labels(replica).set(value)


def record_breaker_transition(replica: str, to_state: str) -> None:
    """One circuit-breaker state transition observed by the router."""
    if not _state.enabled:
        return
    counter("mxnet_serving_breaker_transitions_total",
            "Replica circuit-breaker state transitions, by target "
            "state.", ("replica", "to")).labels(replica, to_state).inc()


def record_worker_restart(replica: str, outcome: str = "ok") -> None:
    """One worker-process respawn by the :class:`RemoteReplica`
    supervisor. ``outcome="ok"`` counts a successful restart
    (``mxnet_worker_restarts_total{replica}``); ``"failed"`` counts a
    spawn attempt that raised and re-entered backoff (a separate
    family — a flapping spawn path must not read as recoveries)."""
    if not _state.enabled:
        return
    if outcome == "ok":
        counter("mxnet_worker_restarts_total",
                "Successful worker-process respawns by replica.",
                ("replica",)).labels(replica).inc()
    else:
        counter("mxnet_worker_respawn_failures_total",
                "Failed worker respawn attempts by replica (retried "
                "with exponential backoff).", ("replica",)
                ).labels(replica).inc()


def set_ingress_connections(state: str, n: int) -> None:
    """Current ingress connection gauge. ``state``: ``open`` (accepted,
    connected) or ``busy`` (with >= 1 request in flight)."""
    if not _state.enabled:
        return
    gauge("mxnet_ingress_connections",
          "Ingress connections by state (open/busy).",
          ("state",)).labels(state).set(n)


def record_ingress_rejected(reason: str) -> None:
    """One request rejected at the ingress with a typed error frame.
    ``reason``: ``window_full`` (per-connection backpressure),
    ``overloaded`` (router admission shed), ``failover_exhausted``,
    ``connection_limit``, ``bad_frame`` (corrupt/torn stream),
    ``fault`` (injected ``serving.ingress`` fault), ``error``."""
    if not _state.enabled:
        return
    counter("mxnet_ingress_rejected_total",
            "Requests rejected at the ingress by reason.",
            ("reason",)).labels(reason).inc()


def record_ingress_request(seconds: float, outcome: str = "ok",
                           trace_id: Optional[str] = None) -> None:
    """One ingress request resolved end-to-end (frame in -> result
    frame out). ``outcome``: ``ok``, ``error`` (typed error frame), or
    ``undeliverable`` (resolved after the client disconnected).
    ``trace_id`` rides along as an exemplar (see
    :func:`record_serving_request`)."""
    if not _state.enabled:
        return
    counter("mxnet_ingress_requests_total",
            "Ingress requests by outcome (ok/error/undeliverable).",
            ("outcome",)).labels(outcome).inc()
    histogram("mxnet_ingress_request_seconds",
              "Ingress request latency (submit frame received to "
              "result frame written).",
              buckets=SERVING_BUCKETS).observe(
        seconds,
        exemplar=({"trace_id": trace_id}
                  if trace_id is not None else None))


def set_router_inflight(n: int, router: str = "") -> None:
    """Requests the Router has forwarded to replicas and not yet
    resolved — the scrape-fed utilization numerator (labeled per
    router — see :func:`set_fleet_size`)."""
    if not _state.enabled:
        return
    gauge("mxnet_serving_router_inflight",
          "Router requests forwarded to replicas, unresolved.",
          ("router",)).labels(router).set(n)


def set_predicted_wait(seconds: float, router: str = "") -> None:
    """The Router admission controller's current predicted queue wait
    (0 when unarmed) — the scrape-fed autoscaler's scale-up signal
    (labeled per router — see :func:`set_fleet_size`)."""
    if not _state.enabled:
        return
    gauge("mxnet_serving_predicted_wait_seconds",
          "Admission controller's predicted completion wait for a "
          "request submitted now (0 = no estimate/unarmed).",
          ("router",)).labels(router).set(seconds)


def record_training_step(seconds: float, examples: float,
                         mfu_pct: Optional[float] = None) -> None:
    if not _state.enabled:
        return
    counter("mxnet_training_steps_total", "Completed training steps.").inc()
    counter("mxnet_training_examples_total",
            "Examples consumed by training steps.").inc(examples)
    histogram("mxnet_training_step_seconds", "Training step wall time.",
              buckets=STEP_BUCKETS).observe(seconds)
    if seconds > 0:
        gauge("mxnet_training_examples_per_sec",
              "Throughput of the most recent training step.").set(
                  examples / seconds)
    if mfu_pct is not None:
        gauge("mxnet_training_mfu_pct",
              "Model-FLOP utilization of the most recent step (percent)."
              ).set(mfu_pct)


# ---------------------------------------------------------------------------
# Training-step observability
# ---------------------------------------------------------------------------

def xla_cost_analysis(step, batch) -> Dict[str, float]:
    """Static cost analysis of a TrainStep's compiled executable.

    The FLOP accounting behind ``tools/cost_check.py`` (which imports this):
    mirror ``TrainStep.__call__``'s argument assembly, lower the cached
    executable, and return XLA's ``compiled.cost_analysis()`` dict —
    ``'flops'`` is the compiler's own static per-step FLOP count.

    .. warning:: This EXECUTES one real training step on ``batch`` to
       populate the step's executable cache: parameters, optimizer state,
       ``optimizer.num_update`` and the RNG stream all advance by one
       update. Call it before training starts (a warmup batch), not
       mid-run.
    """
    import numpy as np

    import jax
    from . import random_state
    from .base import execution_platform
    from .parallel.mesh import use_mesh
    from .parallel.step import _as_tuple

    loss, _ = step(*batch)
    loss.asnumpy()
    data_tuple = _as_tuple(batch[0])
    label_tuple = _as_tuple(batch[1]) if len(batch) > 1 else ()
    entry = next(iter(step._cache.values()))
    jitted = entry["jitted"]
    optimizer = step.optimizer
    t = np.int32(optimizer.num_update)
    lr = np.float32(optimizer.learning_rate)
    rng = random_state.get_state_key()
    param_vals = tuple(p.data().data for p in step._params)
    state_vals = tuple(s.data for s in step._state_leaf_nds)
    batch_vals = [jax.device_put(v.data, sh)
                  for v, sh in zip(tuple(data_tuple) + tuple(label_tuple),
                                   entry["batch_sh"])]
    with execution_platform(step.mesh.devices.flat[0].platform), \
            use_mesh(step.mesh):
        lowered = jitted.lower(param_vals, state_vals, t, lr, rng,
                               *batch_vals)
        compiled = lowered.compile()
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0]
    return ca


class TrainingTelemetry:
    """Per-step observability hook for Gluon/Module training loops.

    Records step wall time, examples/sec and an MFU estimate into the
    telemetry registry (when enabled) and keeps the latest values as
    attributes (always), so it is usable standalone::

        tt = telemetry.TrainingTelemetry(batch_size=256,
                                         flops_per_step=fl, peak_flops=pk)
        for x, y in loader:
            with tt.step():
                loss, _ = train_step(x, y)
        print(tt.last_examples_per_sec, tt.last_mfu_pct)

    ``Module.fit``-style loops attach it as a batch-end callback
    (``batch_end_callback=tt.batch_end`` — step time is measured between
    consecutive calls, reference ``BatchEndParam`` contract).

    FLOP accounting: pass ``flops_per_step`` (e.g. from
    :func:`xla_cost_analysis`'s ``'flops'`` — the same number
    ``tools/cost_check.py`` reports) or ``flops_per_sample`` (6ND-style);
    :meth:`for_step` derives it from a TrainStep via the compiler. The MFU
    denominator is ``peak_flops`` or ``callback.device_peak_flops() x
    num_devices`` (None on hosts with no known peak — MFU is skipped then).
    """

    def __init__(self, batch_size: int, flops_per_step: Optional[float] = None,
                 flops_per_sample: Optional[float] = None,
                 num_devices: Optional[int] = None,
                 peak_flops: Optional[float] = None):
        self.batch_size = batch_size
        self.flops_per_step = flops_per_step
        if flops_per_step is None and flops_per_sample is not None:
            self.flops_per_step = flops_per_sample * batch_size
        self._num_devices = num_devices
        self._peak = peak_flops
        self._peak_resolved = peak_flops is not None
        self._t0: Optional[float] = None
        self._last_batch_end: Optional[float] = None
        self.steps = 0
        self.last_step_seconds: Optional[float] = None
        self.last_examples_per_sec: Optional[float] = None
        self.last_mfu_pct: Optional[float] = None

    @classmethod
    def for_step(cls, step, batch, batch_size: int, **kwargs
                 ) -> "TrainingTelemetry":
        """Build with ``flops_per_step`` read from XLA's cost analysis of
        ``step``'s compiled executable. Note this runs one REAL optimizer
        update on ``batch`` (see :func:`xla_cost_analysis`) — use it
        during setup, counting ``batch`` as a consumed warmup step."""
        ca = xla_cost_analysis(step, batch)
        flops = float(ca.get("flops", 0.0)) or None
        return cls(batch_size, flops_per_step=flops, **kwargs)

    # -- explicit step timing -----------------------------------------
    def step_begin(self) -> None:
        self._t0 = time.perf_counter()

    def step_end(self) -> None:
        if self._t0 is None:
            return
        self._observe(time.perf_counter() - self._t0)
        self._t0 = None

    class _StepScope:
        __slots__ = ("tt",)

        def __init__(self, tt):
            self.tt = tt

        def __enter__(self):
            self.tt.step_begin()
            return self.tt

        def __exit__(self, *exc):
            self.tt.step_end()
            return False

    def step(self) -> "_StepScope":
        """Context manager timing one training step."""
        return self._StepScope(self)

    # -- Module.fit / BatchEndParam adapter ---------------------------
    def batch_end(self, param=None) -> None:
        """Batch-end callback: step time = time since the previous call
        (the first call only arms the clock)."""
        now = time.perf_counter()
        if getattr(param, "nbatch", None) == 0:
            # first batch of an epoch (reference BatchEndParam: nbatch
            # resets per epoch): the gap since the previous call spans
            # validation/checkpointing, not a training step — re-arm
            self._last_batch_end = now
            return
        if self._last_batch_end is not None:
            self._observe(now - self._last_batch_end)
        self._last_batch_end = now

    __call__ = batch_end

    # -- internals ----------------------------------------------------
    def _resolve_peak(self) -> Optional[float]:
        if not self._peak_resolved:
            from .callback import device_peak_flops

            per_chip = device_peak_flops()
            if per_chip:
                if self._num_devices is None:
                    import jax

                    self._num_devices = jax.device_count()
                self._peak = per_chip * self._num_devices
            self._peak_resolved = True
        return self._peak

    def _observe(self, dt: float) -> None:
        self.steps += 1
        self.last_step_seconds = dt
        self.last_examples_per_sec = self.batch_size / dt if dt > 0 else None
        mfu = None
        if self.flops_per_step and dt > 0:
            peak = self._resolve_peak()
            if peak:
                mfu = 100.0 * self.flops_per_step / (dt * peak)
        self.last_mfu_pct = mfu
        record_training_step(dt, self.batch_size, mfu)
