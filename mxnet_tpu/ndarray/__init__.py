"""The ``mx.nd`` namespace: NDArray + generated op wrappers.

Reference: ``python/mxnet/ndarray/register.py`` — at import time MXNet
enumerates C-registered operators and code-generates Python wrappers into
``mx.nd.*``. Here the registry is the pure-JAX op table
(``mxnet_tpu/ops/registry.py``) and wrappers are generated the same way, so
``dir(mx.nd)`` shows the operator surface and each wrapper accepts tensors
positionally or by name, attrs as keywords, plus ``out=`` / ``ctx=``.
"""
from __future__ import annotations

import sys
import types
from typing import Optional

import numpy as _np

from ..base import numeric_types
from ..context import Context, current_context, cpu, gpu, tpu
from ..ops import registry as _registry
from ..ops.registry import get_op, list_ops
# import op implementation modules to populate the registry
from ..ops import elemwise as _elemwise  # noqa: F401
from ..ops import tensor as _tensor  # noqa: F401
from ..ops import nn as _nn  # noqa: F401
from ..ops import random as _random_ops  # noqa: F401
from ..ops import optimizer_op as _optimizer_op  # noqa: F401
from ..ops import contrib as _contrib_ops  # noqa: F401
from ..ops import rnn as _rnn_ops  # noqa: F401
from ..ops import attention as _attention_ops  # noqa: F401
from ..ops import fused_loss as _fused_loss_ops  # noqa: F401
from ..ops import spatial as _spatial_ops  # noqa: F401
from ..ops import multibox as _multibox_ops  # noqa: F401
from ..ops import deformable as _deformable_ops  # noqa: F401
from ..ops import custom as _custom_ops  # noqa: F401

from .ndarray import NDArray, array, empty, imperative_invoke, waitall, _wrap_jax
from .serialization import save, load, loads

__all__ = ["NDArray", "array", "empty", "save", "load", "waitall", "zeros",
           "ones", "full", "arange", "concat", "random", "contrib", "linalg"]


def _make_wrapper(opname: str):
    opdef = get_op(opname)

    def wrapper(*args, **kwargs):
        out = kwargs.pop("out", None)
        kwargs.pop("name", None)
        ctx = kwargs.pop("ctx", None)
        if isinstance(ctx, str):
            ctx = Context(ctx)
        tensors = []
        attrs = {}
        if opdef.variadic:
            tensors = [a for a in args]
            for k, v in kwargs.items():
                attrs[k] = v
        elif opdef.tensor_params:
            named = {}
            pos = list(args)
            # positional args fill tensor slots first
            tensors = [None] * len(opdef.tensor_params)
            for i, a in enumerate(pos):
                if i < len(tensors):
                    tensors[i] = a
                else:
                    # overflow positionals map onto attr params in order
                    # (MXNet parity: e.g. nd.clip(x, 0, 6))
                    j = i - len(tensors)
                    if j < len(opdef.attr_params):
                        attrs[opdef.attr_params[j]] = a
                    else:
                        raise TypeError(
                            f"{opname}: too many positional arguments")
            for k, v in kwargs.items():
                if k in opdef.tensor_params:
                    tensors[opdef.tensor_params.index(k)] = v
                elif k in attrs:
                    raise TypeError(
                        f"{opname}() got multiple values for argument "
                        f"{k!r}")
                else:
                    attrs[k] = v
            # trim trailing unset optional tensors
            while tensors and tensors[-1] is None:
                tensors.pop()
        else:
            # creation-style op: positional args map onto attrs in order
            for i, a in enumerate(args):
                if i < len(opdef.attr_params):
                    attrs[opdef.attr_params[i]] = a
            attrs.update(kwargs)
        tensors = [
            t if (t is None or isinstance(t, NDArray) or isinstance(t, numeric_types))
            else array(t, ctx=ctx)
            for t in tensors
        ]
        return imperative_invoke(opdef, tensors, attrs, out=out, ctx=ctx)

    wrapper.__name__ = opname
    wrapper.__qualname__ = f"nd.{opname}"
    from ..ops.registry import render_attr_docs

    wrapper.__doc__ = (opdef.fn.__doc__ or f"{opname} operator.") \
        + render_attr_docs(opdef)
    return wrapper


_this = sys.modules[__name__]
random = types.ModuleType(__name__ + ".random")
contrib = types.ModuleType(__name__ + ".contrib")
linalg = types.ModuleType(__name__ + ".linalg")
image = types.ModuleType(__name__ + ".image")
sys.modules[random.__name__] = random
sys.modules[contrib.__name__] = contrib
sys.modules[linalg.__name__] = linalg
sys.modules[image.__name__] = image

def _refresh_ops():
    """(Re)generate op wrappers from the registry — called at import and
    again by mx.library.load after native ops register."""
    for _name in list_ops():
        if hasattr(_this, _name):
            continue
        _w = _make_wrapper(_name)
        setattr(_this, _name, _w)
        if _name.startswith("_contrib_"):
            setattr(contrib, _name[len("_contrib_"):], _w)
        if _name.startswith("_linalg_"):
            setattr(linalg, _name[len("_linalg_"):], _w)
        if _name.startswith("_image_"):
            setattr(image, _name[len("_image_"):], _w)
        if _name.startswith("_random_"):
            setattr(random, _name[len("_random_"):], _w)
        elif _name.startswith("_sample_"):
            # NDArray-parameterized forms live as random.sample_* (the
            # scalar forms keep the short names, matching mx.nd.random)
            setattr(random, _name[1:], _w)


_refresh_ops()

from . import sparse  # noqa: E402  (mx.nd.sparse)

# higher-order control flow (python-function arguments — not registry ops)
from ..ops import control_flow as _control_flow  # noqa: E402

contrib.foreach = _control_flow.foreach
contrib.while_loop = _control_flow.while_loop
contrib.cond = _control_flow.cond

# mx.nd.random has MXNet names: uniform/normal/... already set above;
# add the multisample aliases whose broadcast-parameter form differs.
random.seed = None  # patched by mxnet_tpu.random module import


def zeros(shape, ctx: Optional[Context] = None, dtype=None, **kwargs) -> NDArray:
    if isinstance(shape, int):
        shape = (shape,)
    return imperative_invoke(get_op("_zeros"), [],
                             {"shape": tuple(shape), "dtype": str(_np.dtype(dtype or "float32")) if dtype != "bfloat16" else "bfloat16"},
                             ctx=ctx)


def ones(shape, ctx: Optional[Context] = None, dtype=None, **kwargs) -> NDArray:
    if isinstance(shape, int):
        shape = (shape,)
    return imperative_invoke(get_op("_ones"), [],
                             {"shape": tuple(shape), "dtype": str(_np.dtype(dtype or "float32")) if dtype != "bfloat16" else "bfloat16"},
                             ctx=ctx)


def full(shape, val, ctx: Optional[Context] = None, dtype=None) -> NDArray:
    if isinstance(shape, int):
        shape = (shape,)
    return imperative_invoke(get_op("_full"), [],
                             {"shape": tuple(shape), "value": float(val),
                              "dtype": str(_np.dtype(dtype or "float32")) if dtype != "bfloat16" else "bfloat16"},
                             ctx=ctx)


def arange(start, stop=None, step=1.0, repeat=1, ctx: Optional[Context] = None,
           dtype=None) -> NDArray:
    return imperative_invoke(get_op("_arange"), [],
                             {"start": start, "stop": stop, "step": step,
                              "repeat": repeat,
                              "dtype": str(_np.dtype(dtype or "float32"))},
                             ctx=ctx)


def zeros_like(a, **kw):
    return imperative_invoke(get_op("zeros_like"), [a], {})


def ones_like(a, **kw):
    return imperative_invoke(get_op("ones_like"), [a], {})


def moveaxis(a, source, destination):
    axes = list(range(a.ndim))
    axes.remove(source)
    axes.insert(destination if destination >= 0 else destination + a.ndim, source)
    return a.transpose(axes)


def maximum(lhs, rhs):
    if isinstance(rhs, numeric_types):
        return imperative_invoke(get_op("_maximum_scalar"), [lhs], {"scalar": float(rhs)})
    if isinstance(lhs, numeric_types):
        return imperative_invoke(get_op("_maximum_scalar"), [rhs], {"scalar": float(lhs)})
    return imperative_invoke(get_op("broadcast_maximum"), [lhs, rhs], {})


def minimum(lhs, rhs):
    if isinstance(rhs, numeric_types):
        return imperative_invoke(get_op("_minimum_scalar"), [lhs], {"scalar": float(rhs)})
    if isinstance(lhs, numeric_types):
        return imperative_invoke(get_op("_minimum_scalar"), [rhs], {"scalar": float(lhs)})
    return imperative_invoke(get_op("broadcast_minimum"), [lhs, rhs], {})


def power(lhs, rhs):
    if isinstance(rhs, numeric_types):
        return imperative_invoke(get_op("_power_scalar"), [lhs], {"scalar": float(rhs)})
    if isinstance(lhs, numeric_types):
        return imperative_invoke(get_op("_rpower_scalar"), [rhs], {"scalar": float(lhs)})
    return imperative_invoke(get_op("broadcast_power"), [lhs, rhs], {})


def equal(l, r):
    return l == r


def not_equal(l, r):
    return l != r


def greater(l, r):
    return l > r


def lesser(l, r):
    return l < r


def cast_storage(arr, stype="default"):
    """reference: src/operator/tensor/cast_storage.cc — convert between
    dense/'csr'/'row_sparse' storage. Sparse storage is a Python-level
    facade here (SURVEY.md §7.3.5), so this delegates to ``tostype``."""
    return arr.tostype(stype)
