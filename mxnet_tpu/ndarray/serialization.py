"""NDArray binary serialization — the ``.params`` format.

Reference: ``src/ndarray/ndarray.cc :: NDArray::Save`` / ``::Load``
(magic-numbered, versioned ``NDARRAY_V1/V2/V3``) and
``src/c_api/c_api.cc :: MXNDArraySave`` / ``MXNDArrayLoad`` (the
dict-of-arrays list format used by ``Block.save_parameters`` and the model
zoos). Layout follows upstream MXNet 1.x defaults (dense storage,
32-bit dim_t):

list file   : u64 kMXAPINDListMagic(0x112) | u64 reserved(0)
              | u64 n | n × NDArray | u64 m | m × (u64 len, bytes) names
NDArray (V2): u32 0xF993FAC9 | i32 stype(0=dense) | i32 ndim | i32×ndim
              | i32 dev_type | i32 dev_id | i32 dtype_id | raw data (LE)

The loader also accepts the V1/legacy layouts and, as a pragmatic escape
hatch, NumPy ``.npz`` archives (so fixtures can be produced anywhere).
"""
from __future__ import annotations

import struct
from typing import Dict, List, Sequence, Tuple, Union

import numpy as _np

from .. import fault as _fault
from ..base import MXNetError, dtype_id_to_np, dtype_np_to_id
from ..context import Context, cpu, current_context
from ..fault import _state as _fault_state

_LIST_MAGIC = 0x112
_V1_MAGIC = 0xF993FAC8
_V2_MAGIC = 0xF993FAC9
_V3_MAGIC = 0xF993FACA


def _save_one(buf: bytearray, arr_np: _np.ndarray) -> None:
    dtype_id = dtype_np_to_id(arr_np.dtype)
    magic = _V3_MAGIC if dtype_id == 12 else _V2_MAGIC
    buf += struct.pack("<I", magic)
    buf += struct.pack("<i", 0)  # kDefaultStorage
    buf += struct.pack("<i", arr_np.ndim)
    for d in arr_np.shape:
        buf += struct.pack("<i", d)
    buf += struct.pack("<ii", 1, 0)  # Context: kCPU, dev_id 0
    buf += struct.pack("<i", dtype_id)
    buf += arr_np.tobytes(order="C")


def _load_one(data: bytes, off: int) -> Tuple[_np.ndarray, int]:
    (magic,) = struct.unpack_from("<I", data, off)
    off += 4
    if magic in (_V2_MAGIC, _V3_MAGIC):
        (stype,) = struct.unpack_from("<i", data, off)
        off += 4
        if stype != 0:
            raise MXNetError(
                "sparse NDArray storage in .params files is not supported "
                "(dense fallback framework; SURVEY.md §7.3.5)")
        (ndim,) = struct.unpack_from("<i", data, off)
        off += 4
        shape = struct.unpack_from(f"<{ndim}i", data, off) if ndim else ()
        off += 4 * ndim
    elif magic == _V1_MAGIC:
        (ndim,) = struct.unpack_from("<I", data, off)
        off += 4
        shape = struct.unpack_from(f"<{ndim}I", data, off) if ndim else ()
        off += 4 * ndim
    else:
        # oldest layout: the magic word itself is ndim
        ndim = magic
        if ndim > 32:
            raise MXNetError("unrecognized NDArray file magic")
        shape = struct.unpack_from(f"<{ndim}I", data, off) if ndim else ()
        off += 4 * ndim
    dev_type, dev_id = struct.unpack_from("<ii", data, off)
    off += 8
    (dtype_id,) = struct.unpack_from("<i", data, off)
    off += 4
    dt = _np.dtype(dtype_id_to_np(dtype_id))
    n = 1
    for d in shape:
        n *= d
    nbytes = dt.itemsize * n
    arr = _np.frombuffer(data, dtype=dt, count=n, offset=off).reshape(shape)
    off += nbytes
    return arr.copy(), off


def save(fname: str, data) -> None:
    """Save NDArray(s) (reference: mx.nd.save / MXNDArraySave)."""
    from .ndarray import NDArray

    if isinstance(data, NDArray):
        arrays, names = [data], []
    elif isinstance(data, (list, tuple)):
        arrays, names = list(data), []
    elif isinstance(data, dict):
        names = list(data.keys())
        arrays = [data[k] for k in names]
    else:
        raise TypeError("save requires NDArray, list of NDArray, or dict")

    buf = bytearray()
    buf += struct.pack("<QQ", _LIST_MAGIC, 0)
    buf += struct.pack("<Q", len(arrays))
    for a in arrays:
        _save_one(buf, a.asnumpy())
    buf += struct.pack("<Q", len(names))
    for n in names:
        nb = n.encode("utf-8")
        buf += struct.pack("<Q", len(nb))
        buf += nb
    # crash-safe commit (temp + fsync + rename): a .params file either
    # has its old content or its new content, never a torn write
    from ..checkpoint import atomic_write

    atomic_write(fname, bytes(buf))


def save_indexed(fname: str, data: Dict) -> Dict:
    """``save`` for a dict, additionally returning a byte index:
    ``{name: [data_offset, nbytes, shape, dtype_str]}`` so a reader can
    fetch one array's raw payload with a seek instead of parsing the
    whole container (the sharded-checkpoint restore path)."""
    names = list(data.keys())
    arrays = [data[k] for k in names]
    buf = bytearray()
    buf += struct.pack("<QQ", _LIST_MAGIC, 0)
    buf += struct.pack("<Q", len(arrays))
    index: Dict = {}
    for name, a in zip(names, arrays):
        arr_np = a.asnumpy() if hasattr(a, "asnumpy") else _np.asarray(a)
        before = len(buf)
        _save_one(buf, arr_np)
        nbytes = arr_np.dtype.itemsize * arr_np.size
        index[name] = [len(buf) - nbytes, nbytes,
                       list(arr_np.shape), str(arr_np.dtype)]
        assert len(buf) - before >= nbytes
    buf += struct.pack("<Q", len(names))
    for n in names:
        nb = n.encode("utf-8")
        buf += struct.pack("<Q", len(nb))
        buf += nb
    from ..checkpoint import atomic_write

    atomic_write(fname, bytes(buf))
    return index


def read_indexed(fname: str, entry) -> _np.ndarray:
    """Fetch one array's payload via its ``save_indexed`` index entry."""
    off, nbytes, shape, dtype = entry
    with open(fname, "rb") as f:
        f.seek(off)
        raw = f.read(nbytes)
    return _np.frombuffer(raw, dtype=_np.dtype(dtype)).reshape(shape).copy()


def load(fname: str, ctx: Context = None):
    """Load NDArray(s) (reference: mx.nd.load / MXNDArrayLoad)."""
    from .ndarray import array

    if _fault_state.enabled:
        _fault.check("checkpoint.read", fname)
    ctx = ctx or cpu(0)
    try:
        with open(fname, "rb") as f:
            data = f.read()
    except OSError as e:
        raise MXNetError(
            f"cannot read NDArray file {fname!r}: {e}") from e
    if data[:6] == b"PK\x03\x04" + b"\x14\x00" or data[:2] == b"PK":
        # NumPy .npz escape hatch for externally produced fixtures
        npz = _np.load(fname)
        return {k: array(npz[k], ctx=ctx) for k in npz.files}
    try:
        return loads(data, ctx=ctx)
    except MXNetError as e:
        # re-raise with the filename: "invalid magic" without a path is
        # undebuggable from a training-loop traceback
        raise MXNetError(f"{fname!r}: {e}") from e


def loads(data: bytes, ctx: Context = None):
    from .ndarray import array

    ctx = ctx or cpu(0)
    try:
        magic, _reserved = struct.unpack_from("<QQ", data, 0)
        if magic != _LIST_MAGIC:
            raise MXNetError("invalid NDArray list file magic")
        off = 16
        (n,) = struct.unpack_from("<Q", data, off)
        off += 8
        arrays: List = []
        for _ in range(n):
            arr, off = _load_one(data, off)
            arrays.append(array(arr, ctx=ctx, dtype=arr.dtype))
        (m,) = struct.unpack_from("<Q", data, off)
        off += 8
        names: List[str] = []
        for _ in range(m):
            (ln,) = struct.unpack_from("<Q", data, off)
            off += 8
            names.append(data[off : off + ln].decode("utf-8"))
            off += ln
    except (struct.error, ValueError, UnicodeDecodeError, KeyError) as e:
        # truncated payload / garbage bytes must surface as a framework
        # error, not a struct traceback from the middle of the parser
        # (KeyError: a corrupted dtype-id field failing the id->np map)
        raise MXNetError(
            f"corrupt or truncated NDArray payload: {e!r}") from e
    if m == 0:
        return arrays
    return dict(zip(names, arrays))
