"""``mx.nd.sparse`` — sparse storage types (reference:
``python/mxnet/ndarray/sparse.py`` :: CSRNDArray / RowSparseNDArray).

Dense-backed by design (SURVEY.md §7.3.5): XLA/TPU has no general sparse
kernel library, and the reference's dominant sparse uses — embedding
gradients (row_sparse) and bag-of-words batches (csr) — compile to
efficient dense/gather-scatter XLA today. These classes keep the full
reference API (indices/indptr/data views, tostype conversions, retain,
sparse.dot) over a dense payload, so ported code runs unchanged; the
`aux_data` views are materialized lazily from the payload.
"""
from __future__ import annotations

import numpy as _np

from ..base import MXNetError
from .ndarray import NDArray, _wrap_jax

__all__ = ["BaseSparseNDArray", "CSRNDArray", "RowSparseNDArray",
           "csr_matrix", "row_sparse_array", "array", "zeros", "empty",
           "dot", "retain", "add", "elemwise_add"]


def _jnp():
    import jax.numpy as jnp

    return jnp


class BaseSparseNDArray(NDArray):
    """Common sparse behavior; payload is dense, views are lazy."""

    _stype = "base_sparse"

    @property
    def stype(self):
        return self._stype

    def asnumpy(self):
        return super().asnumpy()

    def tostype(self, stype):
        return _convert(self, stype)

    def as_nd_ndarray(self):
        return NDArray(data=self.data, ctx=self._ctx)

    def __repr__(self):
        return (f"\n<{type(self).__name__} {self.shape} "
                f"@{self.context}>")


class CSRNDArray(BaseSparseNDArray):
    """Compressed sparse row matrix (reference: sparse.py::CSRNDArray).

    Two storage modes (mirroring RowSparseNDArray):

    * dense-backed — full payload; ``indices``/``indptr``/``values``
      views computed lazily from it;
    * FACTORED — ``set_csr(values, indices, indptr, full_shape)`` keeps
      only the aux arrays (what ``csr_matrix((data, indices, indptr))``
      and ``LibSVMIter`` produce). The dense payload materializes lazily
      only if something reads ``.data``; :func:`dot` consumes the
      factored parts directly via a gather + ``segment_sum`` formulation
      that never builds the (M, K) dense matrix on device.
    """

    _stype = "csr"
    _vals = None
    _cols = None
    _iptr = None
    _full_shape = None
    _row_ids_cache = None

    def set_csr(self, values, indices, indptr, full_shape):
        """Install a factored (values, col indices, indptr) payload."""
        jnp = _jnp()
        self._vals = jnp.asarray(values)
        self._cols = jnp.asarray(indices, dtype="int32")
        self._iptr = jnp.asarray(indptr, dtype="int32")
        self._full_shape = tuple(full_shape)
        self._shape = tuple(full_shape)
        self._row_ids_cache = None
        self._data = None
        self._version += 1

    def _set_data(self, new_jax):
        # a dense rewrite invalidates the factored views
        self._vals = self._cols = self._iptr = None
        self._row_ids_cache = None
        super()._set_data(new_jax)

    def _row_ids(self):
        """Per-nnz row ids (host-computed once from indptr) — the
        segment ids of the segment-sum matmul."""
        if self._row_ids_cache is None:
            iptr = _np.asarray(self._iptr)
            counts = _np.diff(iptr)
            self._row_ids_cache = _jnp().asarray(
                _np.repeat(_np.arange(len(counts)), counts), dtype="int32")
        return self._row_ids_cache

    @property
    def data(self):
        if self._data is None and self._vals is not None:
            jnp = _jnp()
            self._data = jnp.zeros(
                self._full_shape, self._vals.dtype).at[
                self._row_ids(), self._cols].add(self._vals)
        return NDArray.data.fget(self)

    @property
    def shape(self):
        if self._data is None and self._full_shape is not None:
            return self._full_shape
        return NDArray.shape.fget(self)

    @property
    def indices(self):
        """Column indices aux array (per-row concatenated)."""
        if self._vals is not None:
            return NDArray(data=self._cols.astype("int64"), ctx=self._ctx)
        dense = self.asnumpy()
        cols = [_np.nonzero(row)[0] for row in dense]
        return NDArray(data=_jnp().asarray(
            _np.concatenate(cols) if cols else _np.zeros(0),
            dtype="int64"), ctx=self._ctx)

    @property
    def indptr(self):
        if self._vals is not None:
            return NDArray(data=self._iptr.astype("int64"), ctx=self._ctx)
        dense = self.asnumpy()
        counts = [0] + [int((row != 0).sum()) for row in dense]
        return NDArray(data=_jnp().asarray(_np.cumsum(counts),
                                           dtype="int64"), ctx=self._ctx)

    @property
    def values(self):
        if self._vals is not None:
            return NDArray(data=self._vals, ctx=self._ctx)
        dense = self.asnumpy()
        return NDArray(data=_jnp().asarray(dense[dense != 0]),
                       ctx=self._ctx)

    # MXNet calls the values view `.data` on sparse arrays, but `.data`
    # is this framework's payload accessor; `values` is the sparse view.


class RowSparseNDArray(BaseSparseNDArray):
    """Row-sparse array (reference: sparse.py::RowSparseNDArray).

    Two storage modes:

    * dense-backed (default, SURVEY.md §7.3.5) — full payload, views
      computed lazily;
    * FACTORED — ``set_rows(rows, vals, full_shape)`` stores only the
      touched rows (what ``kvstore.row_sparse_pull`` returns); the dense
      payload materializes lazily only if something reads ``.data``,
      while ``indices``/``values``/``retain`` work on the factored parts
      directly at O(rows) cost.
    """

    _stype = "row_sparse"
    _rows = None
    _vals = None
    _full_shape = None

    def set_rows(self, rows, vals, full_shape):
        """Install a factored (indices, values) payload."""
        self._rows = rows
        self._vals = vals
        self._full_shape = tuple(full_shape)
        self._shape = tuple(full_shape)
        self._data = None
        self._version += 1

    def _set_data(self, new_jax):
        # a dense rewrite invalidates the factored views — they must
        # never disagree with .data
        self._rows = self._vals = None
        super()._set_data(new_jax)

    @property
    def data(self):
        if self._data is None and self._rows is not None:
            jnp = _jnp()
            self._data = jnp.zeros(
                self._full_shape, self._vals.dtype).at[self._rows].set(
                self._vals, mode="drop")
        return NDArray.data.fget(self)

    @property
    def shape(self):
        if self._data is None and self._full_shape is not None:
            return self._full_shape
        return NDArray.shape.fget(self)

    def _live_factored(self):
        """(sorted rows, values) with sentinel/padding slots compressed
        out — the MXNet aux-array contract (sorted, in-range, exact nnz).
        Host-side (eager) by nature: these getters are the user API."""
        rows = _np.asarray(self._rows)
        vals = _np.asarray(self._vals)
        live = rows < self._full_shape[0]
        rows, vals = rows[live], vals[live]
        order = _np.argsort(rows)
        return rows[order], vals[order]

    @property
    def indices(self):
        if self._rows is not None:
            rows, _ = self._live_factored()
            return NDArray(data=_jnp().asarray(rows, dtype="int64"),
                           ctx=self._ctx)
        dense = self.asnumpy()
        rows = _np.nonzero(dense.reshape(dense.shape[0], -1).any(axis=1))[0]
        return NDArray(data=_jnp().asarray(rows, dtype="int64"),
                       ctx=self._ctx)

    @property
    def values(self):
        if self._rows is not None:
            _, vals = self._live_factored()
            return NDArray(data=_jnp().asarray(vals), ctx=self._ctx)
        dense = self.asnumpy()
        rows = _np.nonzero(dense.reshape(dense.shape[0], -1).any(axis=1))[0]
        return NDArray(data=_jnp().asarray(dense[rows]), ctx=self._ctx)

    def retain(self, rows):
        """Keep only ``rows`` (reference: sparse.retain)."""
        jnp = _jnp()
        rows = rows.data.astype("int32") if isinstance(rows, NDArray) \
            else jnp.asarray(rows, dtype="int32")
        if self._rows is not None and self._data is None:
            keep = jnp.isin(self._rows, rows)
            out = RowSparseNDArray(
                data=jnp.zeros((0,)), ctx=self._ctx)
            out.set_rows(
                jnp.where(keep, self._rows, self._full_shape[0]),
                jnp.where(keep.reshape((-1,) + (1,) * (self._vals.ndim - 1)),
                          self._vals, 0),
                self._full_shape)
            return out
        mask = jnp.zeros((self.shape[0],), bool).at[rows].set(True)
        kept = jnp.where(mask.reshape((-1,) + (1,) * (len(self.shape) - 1)),
                         self.data, 0)
        return RowSparseNDArray(data=kept, ctx=self._ctx)


def _convert(arr, stype):
    cls = {"default": NDArray, "csr": CSRNDArray,
           "row_sparse": RowSparseNDArray}.get(stype)
    if cls is None:
        raise MXNetError(f"unknown storage type {stype!r}")
    if type(arr) is cls:
        return arr
    if stype == "csr" and len(arr.shape) != 2:
        raise MXNetError("csr storage requires a 2-D array")
    return cls(data=arr.data, ctx=arr.context)


def csr_matrix(arg1, shape=None, ctx=None, dtype=None):
    """Build a CSRNDArray from (data, indices, indptr) or a dense source
    (reference: sparse.csr_matrix). The aux-triple form stays FACTORED —
    no dense (M, K) payload is built unless something reads ``.data``."""
    from . import array as nd_array
    from ..context import current_context

    if isinstance(arg1, tuple) and len(arg1) == 3:
        data, indices, indptr = (a.asnumpy() if isinstance(a, NDArray)
                                 else _np.asarray(a) for a in arg1)
        if shape is None:
            raise MXNetError("csr_matrix from aux arrays requires shape")
        if dtype is not None:
            data = data.astype(dtype)
        out = CSRNDArray(data=_jnp().zeros((0,), data.dtype),
                         ctx=ctx or current_context())
        out.set_csr(data, indices, indptr, shape)
        return out
    src = arg1 if isinstance(arg1, NDArray) else nd_array(
        _np.asarray(arg1, dtype=dtype), ctx=ctx)
    return _convert(src, "csr")


def row_sparse_array(arg1, shape=None, ctx=None, dtype=None):
    """Build a RowSparseNDArray from (values, row indices) or a dense
    source (reference: sparse.row_sparse_array)."""
    from . import array as nd_array

    if isinstance(arg1, tuple) and len(arg1) == 2:
        values, indices = (a.asnumpy() if isinstance(a, NDArray)
                           else _np.asarray(a) for a in arg1)
        if shape is None:
            shape = (int(indices.max()) + 1,) + values.shape[1:]
        dense = _np.zeros(shape, dtype=dtype or values.dtype)
        dense[indices.astype(int)] = values
        src = nd_array(dense, ctx=ctx)
    else:
        src = arg1 if isinstance(arg1, NDArray) else nd_array(
            _np.asarray(arg1, dtype=dtype), ctx=ctx)
    return _convert(src, "row_sparse")


def array(source_array, ctx=None, dtype=None, stype=None):
    """Build a sparse array from a sparse source (reference signature:
    ``sparse.array(source_array, ctx=None, dtype=None)``). The source's
    storage type is kept; scipy.sparse inputs become csr; dense inputs
    need an explicit ``stype=`` (the reference directs them to
    ``mx.nd.array``)."""
    from . import array as nd_array

    if isinstance(source_array, BaseSparseNDArray) and stype is None:
        stype = source_array.stype
    elif hasattr(source_array, "tocsr") and hasattr(source_array, "toarray"):
        # scipy.sparse-style object
        source_array = source_array.toarray()
        stype = stype or "csr"
    if stype is None:
        raise MXNetError(
            "sparse.array requires a sparse source (or pass stype=); use "
            "mx.nd.array for dense sources")
    src = source_array if isinstance(source_array, NDArray) else nd_array(
        _np.asarray(source_array, dtype=dtype), ctx=ctx)
    return _convert(src, stype)


def zeros(stype, shape, ctx=None, dtype="float32"):
    from . import zeros as nd_zeros

    return _convert(nd_zeros(shape, ctx=ctx, dtype=dtype), stype)


def empty(stype, shape, ctx=None, dtype="float32"):
    return zeros(stype, shape, ctx=ctx, dtype=dtype)


def csr_matmul(values, col_idx, row_ids, n_rows, n_cols, rhs,
               transpose_a=False):
    """Pure-JAX CSR×dense matmul over factored parts — gather rows of
    ``rhs`` per nonzero, scale, ``segment_sum`` by destination row. The
    (n_rows, n_cols) dense lhs never exists on device; FLOPs and memory
    are O(nnz·N). TPU-shaped: the gather/segment-sum lower to efficient
    one-hot-free scatter-adds, and XLA fuses the scale into the gather.

    ``transpose_a=True`` computes ``lhs.T @ rhs`` ((n_cols, N)) by
    swapping the gather/segment roles — the same trick upstream's
    ``dot(csr, dense, transpose_a=True)`` kernel uses
    (src/operator/tensor/dot-inl.h).
    """
    import jax

    if transpose_a:
        gather_ids, seg_ids, n_seg = row_ids, col_idx, n_cols
    else:
        gather_ids, seg_ids, n_seg = col_idx, row_ids, n_rows
    contrib = values[:, None] * rhs[gather_ids]
    return jax.ops.segment_sum(contrib, seg_ids, num_segments=n_seg)


def dot(lhs, rhs, transpose_a=False, transpose_b=False):
    """sparse.dot (reference: mx.nd.sparse.dot / dot-inl.h).

    Factored CSR lhs × dense rhs runs the O(nnz) segment-sum kernel;
    everything else falls back to the dense matmul (XLA fuses the zero
    structure)."""
    from .ndarray import NDArray as _ND, imperative_invoke
    from ..ops.registry import get_op

    if (isinstance(lhs, CSRNDArray) and lhs._vals is not None
            and not transpose_b and getattr(rhs, "ndim", 2) == 2):
        m, k = lhs._full_shape
        inner = m if transpose_a else k
        if rhs.shape[0] != inner:
            # the gather would silently clamp out-of-range indices —
            # validate like the dense path does
            raise MXNetError(
                f"dot: csr lhs {'T' if transpose_a else ''}{(m, k)} is "
                f"incompatible with rhs {tuple(rhs.shape)}")
        out = csr_matmul(lhs._vals, lhs._cols, lhs._row_ids(), m, k,
                         rhs.data, transpose_a=transpose_a)
        return _ND(data=out, ctx=lhs.context)
    return imperative_invoke(get_op("dot"), [lhs, rhs],
                             {"transpose_a": transpose_a,
                              "transpose_b": transpose_b})


def retain(data, indices):
    if not isinstance(data, RowSparseNDArray):
        raise MXNetError("retain expects a RowSparseNDArray")
    return data.retain(indices)


def add(lhs, rhs):
    """Sparse-aware add (reference: ndarray/sparse.py::add).

    Operands with sparse stypes participate through their dense views;
    the result keeps the OPERANDS' common sparse storage type (csr+csr ->
    csr, row_sparse+row_sparse -> row_sparse) and is dense otherwise —
    matching the reference's storage-type inference."""
    out = lhs + rhs
    ls = getattr(lhs, "stype", "default")
    rs = getattr(rhs, "stype", "default")
    if ls == rs and ls in ("csr", "row_sparse"):
        return out.tostype(ls)
    return out


elemwise_add = add
