"""``mx.nd.sparse`` — sparse storage types (reference:
``python/mxnet/ndarray/sparse.py`` :: CSRNDArray / RowSparseNDArray).

Dense-backed by design (SURVEY.md §7.3.5): XLA/TPU has no general sparse
kernel library, and the reference's dominant sparse uses — embedding
gradients (row_sparse) and bag-of-words batches (csr) — compile to
efficient dense/gather-scatter XLA today. These classes keep the full
reference API (indices/indptr/data views, tostype conversions, retain,
sparse.dot) over a dense payload, so ported code runs unchanged; the
`aux_data` views are materialized lazily from the payload.
"""
from __future__ import annotations

import numpy as _np

from ..base import MXNetError
from .ndarray import NDArray, _wrap_jax

__all__ = ["BaseSparseNDArray", "CSRNDArray", "RowSparseNDArray",
           "csr_matrix", "row_sparse_array", "array", "zeros", "empty",
           "dot", "retain"]


def _jnp():
    import jax.numpy as jnp

    return jnp


class BaseSparseNDArray(NDArray):
    """Common sparse behavior; payload is dense, views are lazy."""

    _stype = "base_sparse"

    @property
    def stype(self):
        return self._stype

    def asnumpy(self):
        return super().asnumpy()

    def tostype(self, stype):
        return _convert(self, stype)

    def as_nd_ndarray(self):
        return NDArray(data=self.data, ctx=self._ctx)

    def __repr__(self):
        return (f"\n<{type(self).__name__} {self.shape} "
                f"@{self.context}>")


class CSRNDArray(BaseSparseNDArray):
    """Compressed sparse row matrix (reference: sparse.py::CSRNDArray)."""

    _stype = "csr"

    @property
    def indices(self):
        """Column indices aux array (per-row concatenated)."""
        dense = self.asnumpy()
        cols = [_np.nonzero(row)[0] for row in dense]
        return NDArray(data=_jnp().asarray(
            _np.concatenate(cols) if cols else _np.zeros(0),
            dtype="int64"), ctx=self._ctx)

    @property
    def indptr(self):
        dense = self.asnumpy()
        counts = [0] + [int((row != 0).sum()) for row in dense]
        return NDArray(data=_jnp().asarray(_np.cumsum(counts),
                                           dtype="int64"), ctx=self._ctx)

    @property
    def values(self):
        dense = self.asnumpy()
        return NDArray(data=_jnp().asarray(dense[dense != 0]),
                       ctx=self._ctx)

    # MXNet calls the values view `.data` on sparse arrays, but `.data`
    # is this framework's payload accessor; `values` is the sparse view.


class RowSparseNDArray(BaseSparseNDArray):
    """Row-sparse array (reference: sparse.py::RowSparseNDArray).

    Two storage modes:

    * dense-backed (default, SURVEY.md §7.3.5) — full payload, views
      computed lazily;
    * FACTORED — ``set_rows(rows, vals, full_shape)`` stores only the
      touched rows (what ``kvstore.row_sparse_pull`` returns); the dense
      payload materializes lazily only if something reads ``.data``,
      while ``indices``/``values``/``retain`` work on the factored parts
      directly at O(rows) cost.
    """

    _stype = "row_sparse"
    _rows = None
    _vals = None
    _full_shape = None

    def set_rows(self, rows, vals, full_shape):
        """Install a factored (indices, values) payload."""
        self._rows = rows
        self._vals = vals
        self._full_shape = tuple(full_shape)
        self._shape = tuple(full_shape)
        self._data = None
        self._version += 1

    def _set_data(self, new_jax):
        # a dense rewrite invalidates the factored views — they must
        # never disagree with .data
        self._rows = self._vals = None
        super()._set_data(new_jax)

    @property
    def data(self):
        if self._data is None and self._rows is not None:
            jnp = _jnp()
            self._data = jnp.zeros(
                self._full_shape, self._vals.dtype).at[self._rows].set(
                self._vals, mode="drop")
        return NDArray.data.fget(self)

    @property
    def shape(self):
        if self._data is None and self._full_shape is not None:
            return self._full_shape
        return NDArray.shape.fget(self)

    def _live_factored(self):
        """(sorted rows, values) with sentinel/padding slots compressed
        out — the MXNet aux-array contract (sorted, in-range, exact nnz).
        Host-side (eager) by nature: these getters are the user API."""
        rows = _np.asarray(self._rows)
        vals = _np.asarray(self._vals)
        live = rows < self._full_shape[0]
        rows, vals = rows[live], vals[live]
        order = _np.argsort(rows)
        return rows[order], vals[order]

    @property
    def indices(self):
        if self._rows is not None:
            rows, _ = self._live_factored()
            return NDArray(data=_jnp().asarray(rows, dtype="int64"),
                           ctx=self._ctx)
        dense = self.asnumpy()
        rows = _np.nonzero(dense.reshape(dense.shape[0], -1).any(axis=1))[0]
        return NDArray(data=_jnp().asarray(rows, dtype="int64"),
                       ctx=self._ctx)

    @property
    def values(self):
        if self._rows is not None:
            _, vals = self._live_factored()
            return NDArray(data=_jnp().asarray(vals), ctx=self._ctx)
        dense = self.asnumpy()
        rows = _np.nonzero(dense.reshape(dense.shape[0], -1).any(axis=1))[0]
        return NDArray(data=_jnp().asarray(dense[rows]), ctx=self._ctx)

    def retain(self, rows):
        """Keep only ``rows`` (reference: sparse.retain)."""
        jnp = _jnp()
        rows = rows.data.astype("int32") if isinstance(rows, NDArray) \
            else jnp.asarray(rows, dtype="int32")
        if self._rows is not None and self._data is None:
            keep = jnp.isin(self._rows, rows)
            out = RowSparseNDArray(
                data=jnp.zeros((0,)), ctx=self._ctx)
            out.set_rows(
                jnp.where(keep, self._rows, self._full_shape[0]),
                jnp.where(keep.reshape((-1,) + (1,) * (self._vals.ndim - 1)),
                          self._vals, 0),
                self._full_shape)
            return out
        mask = jnp.zeros((self.shape[0],), bool).at[rows].set(True)
        kept = jnp.where(mask.reshape((-1,) + (1,) * (len(self.shape) - 1)),
                         self.data, 0)
        return RowSparseNDArray(data=kept, ctx=self._ctx)


def _convert(arr, stype):
    cls = {"default": NDArray, "csr": CSRNDArray,
           "row_sparse": RowSparseNDArray}.get(stype)
    if cls is None:
        raise MXNetError(f"unknown storage type {stype!r}")
    if stype == "csr" and len(arr.shape) != 2:
        raise MXNetError("csr storage requires a 2-D array")
    return cls(data=arr.data, ctx=arr.context)


def csr_matrix(arg1, shape=None, ctx=None, dtype=None):
    """Build a CSRNDArray from (data, indices, indptr) or a dense source
    (reference: sparse.csr_matrix)."""
    from . import array as nd_array

    if isinstance(arg1, tuple) and len(arg1) == 3:
        data, indices, indptr = (a.asnumpy() if isinstance(a, NDArray)
                                 else _np.asarray(a) for a in arg1)
        if shape is None:
            raise MXNetError("csr_matrix from aux arrays requires shape")
        dense = _np.zeros(shape, dtype=dtype or data.dtype)
        for r in range(shape[0]):
            lo, hi = int(indptr[r]), int(indptr[r + 1])
            dense[r, indices[lo:hi].astype(int)] = data[lo:hi]
        src = nd_array(dense, ctx=ctx)
    else:
        src = arg1 if isinstance(arg1, NDArray) else nd_array(
            _np.asarray(arg1, dtype=dtype), ctx=ctx)
    return _convert(src, "csr")


def row_sparse_array(arg1, shape=None, ctx=None, dtype=None):
    """Build a RowSparseNDArray from (values, row indices) or a dense
    source (reference: sparse.row_sparse_array)."""
    from . import array as nd_array

    if isinstance(arg1, tuple) and len(arg1) == 2:
        values, indices = (a.asnumpy() if isinstance(a, NDArray)
                           else _np.asarray(a) for a in arg1)
        if shape is None:
            shape = (int(indices.max()) + 1,) + values.shape[1:]
        dense = _np.zeros(shape, dtype=dtype or values.dtype)
        dense[indices.astype(int)] = values
        src = nd_array(dense, ctx=ctx)
    else:
        src = arg1 if isinstance(arg1, NDArray) else nd_array(
            _np.asarray(arg1, dtype=dtype), ctx=ctx)
    return _convert(src, "row_sparse")


def array(source_array, ctx=None, dtype=None, stype=None):
    """Build a sparse array from a sparse source (reference signature:
    ``sparse.array(source_array, ctx=None, dtype=None)``). The source's
    storage type is kept; scipy.sparse inputs become csr; dense inputs
    need an explicit ``stype=`` (the reference directs them to
    ``mx.nd.array``)."""
    from . import array as nd_array

    if isinstance(source_array, BaseSparseNDArray) and stype is None:
        stype = source_array.stype
    elif hasattr(source_array, "tocsr") and hasattr(source_array, "toarray"):
        # scipy.sparse-style object
        source_array = source_array.toarray()
        stype = stype or "csr"
    if stype is None:
        raise MXNetError(
            "sparse.array requires a sparse source (or pass stype=); use "
            "mx.nd.array for dense sources")
    src = source_array if isinstance(source_array, NDArray) else nd_array(
        _np.asarray(source_array, dtype=dtype), ctx=ctx)
    return _convert(src, stype)


def zeros(stype, shape, ctx=None, dtype="float32"):
    from . import zeros as nd_zeros

    return _convert(nd_zeros(shape, ctx=ctx, dtype=dtype), stype)


def empty(stype, shape, ctx=None, dtype="float32"):
    return zeros(stype, shape, ctx=ctx, dtype=dtype)


def dot(lhs, rhs, transpose_a=False, transpose_b=False):
    """sparse.dot — dense-backed matmul; XLA fuses the zero structure."""
    from .ndarray import imperative_invoke
    from ..ops.registry import get_op

    return imperative_invoke(get_op("dot"), [lhs, rhs],
                             {"transpose_a": transpose_a,
                              "transpose_b": transpose_b})


def retain(data, indices):
    if not isinstance(data, RowSparseNDArray):
        raise MXNetError("retain expects a RowSparseNDArray")
    return data.retain(indices)
