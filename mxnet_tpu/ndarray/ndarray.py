"""NDArray: the imperative, asynchronous tensor.

Reference: ``include/mxnet/ndarray.h :: NDArray`` and
``src/ndarray/ndarray.cc`` — a ref-counted async tensor with in-place
mutation, view/slice aliasing, deferred allocation and engine-ordered
execution.

TPU-native design (SURVEY.md §7.3.1 — the riskiest seam):

* the payload is an immutable ``jax.Array``; *mutation* is a functional
  swap of the payload plus a **version counter** bump;
* *views* (``x[1:3]``, ``reshape``) hold a read/write lens onto their base
  array — reads recompute lazily when the base version moved, writes go
  through ``base.at[...]`` (copy-on-write, XLA fuses the scatter);
* *async*: JAX dispatch is async-by-default, so every op returns
  immediately and ``wait_to_read`` / ``asnumpy`` are the sync points where
  captured exceptions surface (reference: ThreadedVar ExceptionRef);
* under ``autograd.record()``, view-producing methods route through real
  ops so the tape sees pure functions.
"""
from __future__ import annotations

import time
from typing import Optional

import numpy as _np

from .. import autograd, engine, random_state, telemetry
from ..base import MXNetError, numeric_types, integer_types
from ..context import Context, current_context
from ..ops.registry import OpDef, eager_call, get_op
from ..telemetry import _state as _telemetry_state

__all__ = ["NDArray", "array", "empty", "_wrap_jax", "imperative_invoke", "waitall"]


def _jnp():
    import jax.numpy as jnp

    return jnp


def _resolve_dtype(dtype):
    import ml_dtypes

    if dtype is None:
        return _np.float32
    if dtype == "bfloat16" or dtype is ml_dtypes.bfloat16:
        return ml_dtypes.bfloat16
    return _np.dtype(dtype)


class NDArray:
    """Multi-dimensional array on a device context."""

    __array_priority__ = 100.0

    def __init__(self, data=None, ctx: Optional[Context] = None, base=None,
                 view_read=None, view_write=None, shape=None, dtype=None):
        self._ctx = ctx or current_context()
        self._base = base
        self._view_read = view_read
        self._view_write = view_write
        self._cached_version = -1
        self._version = 0
        self._data = data
        if base is not None:
            self._shape = shape
            self._dtype = dtype
        elif data is not None:
            self._shape = tuple(data.shape)
            self._dtype = _np.dtype(data.dtype) if data.dtype != "bfloat16" else data.dtype
        else:
            self._shape, self._dtype = shape, dtype
        # autograd state
        self._grad = None
        self._grad_req = "null"
        self._ag_node = None
        self._ag_index = 0

    # ------------------------------------------------------------------
    # payload access / mutation
    # ------------------------------------------------------------------
    @property
    def data(self):
        """The underlying jax.Array (recomputed for stale views).

        A sync point for bulked execution: when the payload is a pending
        bulk-segment output, reading it flushes the owning segment
        (reference: ThreadedVar WaitToRead), so ``asnumpy``/
        ``wait_to_read``/``item``/printing all materialize for free.
        """
        if self._base is not None:
            if self._cached_version != self._base._version or self._data is None:
                self._data = self._view_read(self._base.data)
                self._cached_version = self._base._version
        d = self._data
        if type(d) is engine.PendingValue:
            d = engine.concretize(d)
            self._data = d
        if d is None:
            raise MXNetError("NDArray payload not yet materialized")
        return d

    def _payload(self):
        """Payload for op dispatch: the raw ``engine.PendingValue`` while
        this array is an unflushed bulk-segment output — keeping chains
        deferred — else the concrete jax.Array (``.data``)."""
        d = self._data
        if self._base is None and type(d) is engine.PendingValue:
            c = d._concrete
            if c is None:
                return d
            self._data = c
            return c
        return self.data

    def _set_data(self, new_jax) -> None:
        """Functionally replace the payload (an in-place write in API terms)."""
        from .. import mutation

        log = mutation.active_log()
        if log is not None:
            import jax as _jax

            if isinstance(new_jax, _jax.core.Tracer) or isinstance(self._data, _jax.core.Tracer):
                # traced (hybridized) execution: record so the compiled graph
                # returns this as an extra output (see mutation.py). Views
                # write through to their base so base reads stay coherent
                # within the trace; the BASE is what gets logged/returned.
                if self._base is not None:
                    self._base._set_data(self._view_write(self._base.data, new_jax))
                    self._data = new_jax
                    self._cached_version = self._base._version
                    return
                log.log(self)
                self._data = new_jax
                self._version += 1
                return
        if self._base is not None:
            self._base._set_data(self._view_write(self._base.data, new_jax))
            self._data = new_jax
            self._cached_version = self._base._version
        else:
            self._data = new_jax
            self._version += 1
        engine.track(new_jax)

    # ------------------------------------------------------------------
    # basic properties
    # ------------------------------------------------------------------
    @property
    def shape(self):
        if self._shape is None:
            self._shape = tuple(self.data.shape)
        return self._shape

    @property
    def dtype(self):
        if self._dtype is None:
            d = self.data.dtype
            self._dtype = d if str(d) == "bfloat16" else _np.dtype(d)
        return self._dtype

    @property
    def size(self):
        n = 1
        for d in self.shape:
            n *= d
        return n

    @property
    def ndim(self):
        return len(self.shape)

    @property
    def context(self) -> Context:
        return self._ctx

    ctx = context

    @property
    def stype(self):
        return "default"

    @property
    def T(self):
        return imperative_invoke(get_op("transpose"), [self], {})

    @property
    def grad(self):
        return self._grad

    @property
    def handle(self):
        # reference: NDArrayHandle — opaque identity for C-API parity
        return id(self)

    # ------------------------------------------------------------------
    # sync / host transfer
    # ------------------------------------------------------------------
    def wait_to_read(self) -> None:
        import jax

        jax.block_until_ready(self.data)

    def asnumpy(self) -> _np.ndarray:
        return _np.asarray(self.data)

    def asscalar(self):
        if self.size != 1:
            raise ValueError("The current array is not a scalar")
        return self.asnumpy().reshape(())[()]

    def item(self):
        return self.asscalar()

    def tolist(self):
        return self.asnumpy().tolist()

    # ------------------------------------------------------------------
    # context / dtype movement
    # ------------------------------------------------------------------
    def as_in_context(self, ctx: Context) -> "NDArray":
        if ctx == self._ctx:
            return self
        return self.copyto(ctx)

    as_in_ctx = as_in_context

    def copyto(self, other) -> "NDArray":
        import jax

        if isinstance(other, NDArray):
            other._set_data(jax.device_put(self.data, other._ctx.jax_device()))
            return other
        if isinstance(other, Context):
            val = jax.device_put(self.data, other.jax_device())
            return _wrap_jax(val, other)
        raise TypeError(f"copyto does not support type {type(other)}")

    def copy(self) -> "NDArray":
        # stays on device and non-blocking (async copy via XLA)
        return _wrap_jax(_jnp().array(self.data, copy=True), self._ctx)

    def astype(self, dtype, copy: bool = True) -> "NDArray":
        dt = _resolve_dtype(dtype)
        if not copy and self.dtype == dt:
            return self
        name = "bfloat16" if str(dt) == "bfloat16" or dt is not None and \
            getattr(dt, "__name__", "") == "bfloat16" else str(_np.dtype(dt))
        return imperative_invoke(get_op("Cast"), [self], {"dtype": name})

    def as_np_ndarray(self):
        from ..numpy import ndarray as np_ndarray

        return np_ndarray(data=self.data, ctx=self._ctx)

    def as_nd_ndarray(self):
        return self

    # ------------------------------------------------------------------
    # autograd
    # ------------------------------------------------------------------
    def attach_grad(self, grad_req: str = "write", stype=None) -> None:
        jnp = _jnp()
        self._grad = _wrap_jax(jnp.zeros(self.shape, self.data.dtype), self._ctx)
        self._grad_req = grad_req

    def drop_grad(self) -> None:
        self._grad = None
        self._grad_req = "null"

    def detach(self) -> "NDArray":
        out = _wrap_jax(self.data, self._ctx)
        return out

    def backward(self, out_grad=None, retain_graph=False, train_mode=True) -> None:
        autograd.backward([self], [out_grad] if out_grad is not None else None,
                          retain_graph=retain_graph, train_mode=train_mode)

    # ------------------------------------------------------------------
    # shape ops (views outside autograd; real ops when recording)
    # ------------------------------------------------------------------
    def reshape(self, *shape, **kwargs):
        if len(shape) == 1 and isinstance(shape[0], (list, tuple)):
            shape = tuple(shape[0])
        if kwargs.get("shape"):
            shape = tuple(kwargs["shape"])
        if autograd.is_recording():
            return imperative_invoke(get_op("Reshape"), [self], {"shape": shape})
        from ..ops.tensor import _reshape_with_magic

        new_shape = _reshape_with_magic(self.shape, tuple(shape))
        new_shape = _np.empty(self.shape, dtype=_np.int8).reshape(new_shape).shape
        return NDArray(
            base=self._root_base(),
            view_read=_compose_read(self, lambda d: d.reshape(new_shape)),
            view_write=_compose_write(self, lambda d, v: v.reshape(d.shape)),
            ctx=self._ctx, shape=tuple(new_shape), dtype=self.dtype,
        )

    def reshape_like(self, other):
        return self.reshape(other.shape)

    def _root_base(self):
        return self if self._base is None else self._base

    def expand_dims(self, axis):
        return imperative_invoke(get_op("expand_dims"), [self], {"axis": axis})

    def squeeze(self, axis=None):
        return imperative_invoke(get_op("squeeze"), [self], {"axis": axis})

    def flatten(self):
        return imperative_invoke(get_op("Flatten"), [self], {})

    def transpose(self, *axes):
        if len(axes) == 1 and isinstance(axes[0], (list, tuple)):
            axes = tuple(axes[0])
        return imperative_invoke(get_op("transpose"), [self], {"axes": axes})

    def swapaxes(self, dim1, dim2):
        return imperative_invoke(get_op("swapaxes"), [self], {"dim1": dim1, "dim2": dim2})

    def flip(self, axis):
        return imperative_invoke(get_op("flip"), [self], {"axis": axis})

    def tile(self, reps):
        return imperative_invoke(get_op("tile"), [self], {"reps": reps})

    def slice(self, begin, end, step=None):
        return imperative_invoke(get_op("slice"), [self],
                                 {"begin": begin, "end": end, "step": step or ()})

    def slice_axis(self, axis, begin, end):
        return imperative_invoke(get_op("slice_axis"), [self],
                                 {"axis": axis, "begin": begin, "end": end})

    def take(self, indices, axis=0, mode="clip"):
        return imperative_invoke(get_op("take"), [self, indices], {"axis": axis, "mode": mode})

    def one_hot(self, depth, **kw):
        return imperative_invoke(get_op("one_hot"), [self], {"depth": depth, **kw})

    def clip(self, a_min, a_max):
        return imperative_invoke(get_op("clip"), [self], {"a_min": a_min, "a_max": a_max})

    def abs(self):
        return imperative_invoke(get_op("abs"), [self], {})

    def sign(self):
        return imperative_invoke(get_op("sign"), [self], {})

    def sqrt(self):
        return imperative_invoke(get_op("sqrt"), [self], {})

    def square(self):
        return imperative_invoke(get_op("square"), [self], {})

    def exp(self):
        return imperative_invoke(get_op("exp"), [self], {})

    def log(self):
        return imperative_invoke(get_op("log"), [self], {})

    def sum(self, axis=None, keepdims=False):
        return imperative_invoke(get_op("sum"), [self], {"axis": axis, "keepdims": keepdims})

    def mean(self, axis=None, keepdims=False):
        return imperative_invoke(get_op("mean"), [self], {"axis": axis, "keepdims": keepdims})

    def max(self, axis=None, keepdims=False):
        return imperative_invoke(get_op("max"), [self], {"axis": axis, "keepdims": keepdims})

    def min(self, axis=None, keepdims=False):
        return imperative_invoke(get_op("min"), [self], {"axis": axis, "keepdims": keepdims})

    def prod(self, axis=None, keepdims=False):
        return imperative_invoke(get_op("prod"), [self], {"axis": axis, "keepdims": keepdims})

    def norm(self, ord=2, axis=None, keepdims=False):
        return imperative_invoke(get_op("norm"), [self],
                                 {"ord": ord, "axis": axis, "keepdims": keepdims})

    def argmax(self, axis=None, keepdims=False):
        return imperative_invoke(get_op("argmax"), [self], {"axis": axis, "keepdims": keepdims})

    def argmin(self, axis=None, keepdims=False):
        return imperative_invoke(get_op("argmin"), [self], {"axis": axis, "keepdims": keepdims})

    def argsort(self, axis=-1, is_ascend=True):
        return imperative_invoke(get_op("argsort"), [self], {"axis": axis, "is_ascend": is_ascend})

    def topk(self, axis=-1, k=1, ret_typ="indices", is_ascend=False):
        return imperative_invoke(get_op("topk"), [self],
                                 {"axis": axis, "k": k, "ret_typ": ret_typ,
                                  "is_ascend": is_ascend})

    def softmax(self, axis=-1):
        return imperative_invoke(get_op("softmax"), [self], {"axis": axis})

    def log_softmax(self, axis=-1):
        return imperative_invoke(get_op("log_softmax"), [self], {"axis": axis})

    def dot(self, other, transpose_a=False, transpose_b=False):
        return imperative_invoke(get_op("dot"), [self, other],
                                 {"transpose_a": transpose_a, "transpose_b": transpose_b})

    def tostype(self, stype):
        if stype == "default":
            return self
        from .sparse import _convert

        return _convert(self, stype)

    # ------------------------------------------------------------------
    # indexing
    # ------------------------------------------------------------------
    def __getitem__(self, key):
        jnp = _jnp()
        key = _clean_index(key)
        if autograd.is_recording():
            data = self.data

            def pure(d):
                return d[key] if not isinstance(key, NDArray) else d[key.data]

            return imperative_invoke(_lambda_op(pure, "getitem"), [self], {})
        if isinstance(key, NDArray):
            return _wrap_jax(jnp.take(self.data, key.data.astype("int32"), axis=0), self._ctx)
        idx = key
        sub = self.data[idx]
        return NDArray(
            base=self._root_base(),
            view_read=_compose_read(self, lambda d: d[idx]),
            view_write=_compose_write(self, lambda d, v: d.at[idx].set(v)),
            ctx=self._ctx, shape=tuple(sub.shape), dtype=self.dtype,
        )

    def __setitem__(self, key, value):
        jnp = _jnp()
        self._check_inplace_during_record()
        key = _clean_index(key)
        if isinstance(value, NDArray):
            v = value.data
        elif isinstance(value, numeric_types):
            v = value
        else:
            v = jnp.asarray(_np.asarray(value), dtype=self.data.dtype)
        if isinstance(key, NDArray):
            key = key.data.astype("int32")
        if key is Ellipsis or (isinstance(key, slice) and key == slice(None)):
            if isinstance(v, numeric_types):
                self._set_data(jnp.full(self.shape, v, dtype=self.data.dtype))
            else:
                self._set_data(jnp.broadcast_to(v, self.shape).astype(self.data.dtype))
            return
        self._set_data(self.data.at[key].set(v))

    # ------------------------------------------------------------------
    # python protocol
    # ------------------------------------------------------------------
    def __len__(self):
        if not self.shape:
            raise TypeError("len() of unsized object")
        return self.shape[0]

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __bool__(self):
        if self.size == 1:
            return bool(self.asscalar())
        raise ValueError("The truth value of an NDArray with multiple elements is ambiguous")

    def __float__(self):
        return float(self.asscalar())

    def __int__(self):
        return int(self.asscalar())

    def __index__(self):
        return int(self.asscalar())

    def __repr__(self):
        try:
            arr = self.asnumpy()
            return f"\n{arr}\n<NDArray {'x'.join(map(str, self.shape))} @{self._ctx}>"
        except Exception as e:  # async error surfaces here (sync point)
            raise

    def __array__(self, dtype=None):
        a = self.asnumpy()
        return a.astype(dtype) if dtype is not None else a

    # DLPack interchange (reference: NDArray::ToDLPack / FromDLPack)
    def __dlpack__(self, stream=None):
        return self.data.__dlpack__()

    def __dlpack_device__(self):
        return self.data.__dlpack_device__()

    # ------------------------------------------------------------------
    # arithmetic
    # ------------------------------------------------------------------
    def _binop(self, other, opname, scalar_opname, reverse=False):
        if isinstance(other, NDArray):
            args = [other, self] if reverse else [self, other]
            return imperative_invoke(get_op(opname), args, {})
        if isinstance(other, numeric_types):
            return imperative_invoke(get_op(scalar_opname), [self], {"scalar": float(other)})
        if isinstance(other, _np.ndarray):
            return self._binop(array(other, ctx=self._ctx), opname, scalar_opname, reverse)
        return NotImplemented

    def __add__(self, o):
        return self._binop(o, "broadcast_add", "_plus_scalar")

    __radd__ = __add__

    def __sub__(self, o):
        return self._binop(o, "broadcast_sub", "_minus_scalar")

    def __rsub__(self, o):
        if isinstance(o, numeric_types):
            return imperative_invoke(get_op("_rminus_scalar"), [self], {"scalar": float(o)})
        return self._binop(o, "broadcast_sub", "_minus_scalar", reverse=True)

    def __mul__(self, o):
        return self._binop(o, "broadcast_mul", "_mul_scalar")

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._binop(o, "broadcast_div", "_div_scalar")

    def __rtruediv__(self, o):
        if isinstance(o, numeric_types):
            return imperative_invoke(get_op("_rdiv_scalar"), [self], {"scalar": float(o)})
        return self._binop(o, "broadcast_div", "_div_scalar", reverse=True)

    def __mod__(self, o):
        return self._binop(o, "broadcast_mod", "_mod_scalar")

    def __rmod__(self, o):
        if isinstance(o, numeric_types):
            return imperative_invoke(get_op("_rmod_scalar"), [self], {"scalar": float(o)})
        return self._binop(o, "broadcast_mod", "_mod_scalar", reverse=True)

    def __pow__(self, o):
        return self._binop(o, "broadcast_power", "_power_scalar")

    def __rpow__(self, o):
        if isinstance(o, numeric_types):
            return imperative_invoke(get_op("_rpower_scalar"), [self], {"scalar": float(o)})
        return self._binop(o, "broadcast_power", "_power_scalar", reverse=True)

    def __neg__(self):
        return imperative_invoke(get_op("negative"), [self], {})

    def __abs__(self):
        return imperative_invoke(get_op("abs"), [self], {})

    def __eq__(self, o):
        if o is None:
            return False
        return self._binop(o, "broadcast_equal", "_equal_scalar")

    def __ne__(self, o):
        if o is None:
            return True
        return self._binop(o, "broadcast_not_equal", "_not_equal_scalar")

    def __gt__(self, o):
        return self._binop(o, "broadcast_greater", "_greater_scalar")

    def __ge__(self, o):
        return self._binop(o, "broadcast_greater_equal", "_greater_equal_scalar")

    def __lt__(self, o):
        return self._binop(o, "broadcast_lesser", "_lesser_scalar")

    def __le__(self, o):
        return self._binop(o, "broadcast_lesser_equal", "_lesser_equal_scalar")

    def __hash__(self):
        return id(self)

    # in-place variants mutate the payload (engine-ordered like MXNet's +=)
    def _ibinop(self, other, opname, scalar_opname):
        self._check_inplace_during_record()
        with autograd.pause():
            out = self._binop(other, opname, scalar_opname)
        if out is NotImplemented:
            return out
        if self._base is None and self.dtype == out.dtype:
            # same-dtype in-place update on a non-view: adopt the (possibly
            # still pending) payload so `x += y` loops stay bulked
            self._set_data(out._payload())
        else:
            self._set_data(out.data.astype(self.data.dtype))
        return self

    def _check_inplace_during_record(self):
        # reference parity: MXNet forbids in-place writes to arrays that
        # participate in the autograd graph while recording — a silent
        # stale-tape gradient otherwise (the tape keeps the pre-mutation
        # producer node).
        if autograd.is_recording() and autograd.is_on_tape(self):
            raise MXNetError(
                "in-place operation on an array held by the autograd tape "
                "inside autograd.record() is not allowed; use out-of-place "
                "ops or move the mutation outside the record scope")

    def __iadd__(self, o):
        return self._ibinop(o, "broadcast_add", "_plus_scalar")

    def __isub__(self, o):
        return self._ibinop(o, "broadcast_sub", "_minus_scalar")

    def __imul__(self, o):
        return self._ibinop(o, "broadcast_mul", "_mul_scalar")

    def __itruediv__(self, o):
        return self._ibinop(o, "broadcast_div", "_div_scalar")

    # ------------------------------------------------------------------
    # serialization hooks (full format lives in ndarray/utils.py)
    # ------------------------------------------------------------------
    def save(self, fname):
        from .serialization import save

        save(fname, self)

    def __getstate__(self):
        return {"data": self.asnumpy(), "ctx": str(self._ctx.device_type), "id": self._ctx.device_id}

    def __setstate__(self, state):
        import jax

        self.__init__()
        self._ctx = Context(state["ctx"], state["id"])
        try:
            dev = self._ctx.jax_device()
        except Exception:
            self._ctx = Context("cpu", 0)
            dev = self._ctx.jax_device()
        self._data = jax.device_put(state["data"], dev)
        self._shape = tuple(self._data.shape)
        self._dtype = state["data"].dtype


def _clean_index(key):
    if isinstance(key, tuple):
        return tuple(k.data.astype("int32") if isinstance(k, NDArray) else k for k in key)
    return key


def _compose_read(view_or_base, read):
    if view_or_base._base is None:
        return read
    outer = view_or_base._view_read
    return lambda d: read(outer(d))


def _compose_write(view_or_base, write):
    if view_or_base._base is None:
        return write
    outer_read = view_or_base._view_read
    outer_write = view_or_base._view_write

    def composed(d, v):
        inner = outer_read(d)
        return outer_write(d, write(inner, v))

    return composed


class _LambdaOp:
    """Ad-hoc OpDef-alike for closures (getitem under autograd)."""

    def __init__(self, fn, name):
        self.fn = fn
        self.name = name
        self.tensor_params = ("data",)
        self.optional_tensor_params = frozenset()
        self.attr_params = ()
        self.needs_rng = False
        self.num_outputs = None
        self.pass_training_flag = False
        self.variadic = False
        self.eager_only = False


def _lambda_op(fn, name):
    return _LambdaOp(fn, name)


# ---------------------------------------------------------------------------
# the imperative invoke path (reference: SURVEY.md §3.1 call stack)
# ---------------------------------------------------------------------------


def imperative_invoke(opdef, tensor_args, attrs, out=None, ctx=None,
                      force_record=False):
    """Execute a registered op on NDArrays.

    This is the TPU equivalent of ``MXImperativeInvokeEx →
    Imperative::Invoke → Engine::PushAsync``: resolve inputs, execute
    asynchronously via the cached per-op executable, record on the autograd
    tape if needed, and wrap outputs. Returns immediately; JAX's async
    dispatch provides the engine's non-blocking contract.
    """
    import jax

    if ctx is None:
        for a in tensor_args:
            if isinstance(a, NDArray):
                ctx = a.context
                break
    if ctx is None:
        ctx = current_context()

    recording = autograd.is_recording() and (force_record or any(
        isinstance(a, NDArray) and autograd.is_on_tape(a) for a in tensor_args
    ))
    if recording:
        # autograd recording is non-recordable for bulking (flush trigger
        # c): the vjp trace below must see concrete arrays, and tape
        # ordering must match execution order
        scope = engine.current_bulk_scope()
        if scope is not None:
            scope.flush("unrecordable")
    # the eager OpDef path forwards raw pending payloads so op chains stay
    # deferred inside a bulk scope; the vjp/lambda paths call opdef.fn
    # directly and need concrete jax.Arrays
    defer_ok = not recording and isinstance(opdef, OpDef)

    vals = []
    for a in tensor_args:
        if a is None:
            vals.append(None)
        elif isinstance(a, NDArray):
            vals.append(a._payload() if defer_ok else a.data)
        elif isinstance(a, numeric_types):
            vals.append(a)
        else:
            vals.append(jax.device_put(_np.asarray(a), ctx.jax_device()))

    attrs = {k: _canon_attr(v) for k, v in attrs.items() if v is not None or k in ("axis",)}
    if opdef.pass_training_flag:
        attrs["_training"] = autograd.is_training()
    wants_rng = opdef.needs_rng and (
        opdef.rng_gate is None or opdef.rng_gate(attrs))
    rng = random_state.next_key() if wants_rng else None

    if recording:
        fixed_attrs = dict(attrs)
        fn = opdef.fn
        if rng is not None:
            def pure(*tensors):
                return fn(rng, *tensors, **fixed_attrs)
        elif opdef.needs_rng:  # rng draw gated off: fn still has the slot
            def pure(*tensors):
                return fn(None, *tensors, **fixed_attrs)
        else:
            def pure(*tensors):
                return fn(*tensors, **fixed_attrs)
        from ..base import current_execution_platform, execution_platform

        # telemetry: the recording path bypasses eager_call (jax.vjp over
        # the raw fn), so per-op dispatch is counted here; the eager OpDef
        # branch below counts inside eager_call — no double count. The
        # flag is captured once so a mid-call enable() can't pair an
        # unset t0 with a recording exit
        _tel = _telemetry_state.enabled
        _tel_t0 = time.perf_counter() if _tel else 0.0
        sample = next((v for v in vals if hasattr(v, "devices")), None)
        with execution_platform(current_execution_platform(sample)):
            result, vjp_fn = jax.vjp(pure, *vals)
        if _tel:
            telemetry.record_op_dispatch(
                getattr(opdef, "name", "op"), time.perf_counter() - _tel_t0)
    elif isinstance(opdef, OpDef):
        result = eager_call(opdef, vals, attrs, rng=rng)
        vjp_fn = None
    else:
        _tel = _telemetry_state.enabled
        _tel_t0 = time.perf_counter() if _tel else 0.0
        result = opdef.fn(*vals, **{k: v for k, v in attrs.items()})
        if _tel:
            telemetry.record_op_dispatch(
                getattr(opdef, "name", "op"), time.perf_counter() - _tel_t0)
        vjp_fn = None

    multi = isinstance(result, (tuple, list))
    results = list(result) if multi else [result]
    if not any(isinstance(a, NDArray) for a in tensor_args):
        # creation-style op: commit outputs to the requested context
        dev = ctx.jax_device()
        results = [jax.device_put(r, dev) for r in results]
    outputs = [_wrap_jax(r, ctx) for r in results]

    if recording:
        nd_inputs = [a for a in tensor_args]

        def tape_vjp(cotangents):
            grads = vjp_fn(cotangents)
            return grads

        # tape inputs must align with vjp's positional grads
        autograd.record_node(_TapeVjp(vjp_fn, multi),
                             [a if isinstance(a, NDArray) else _DUMMY for a in nd_inputs],
                             outputs, name=getattr(opdef, "name", "op"),
                             primal_fn=pure, primal_vals=list(vals))

    if engine.is_naive():
        for o in outputs:
            o.wait_to_read()

    if out is not None:
        outs = out if isinstance(out, (list, tuple)) else [out]
        for o_dst, o_src in zip(outs, outputs):
            if o_dst._base is None and o_dst.dtype == o_src.dtype:
                # same-dtype write into a non-view: hand over the payload
                # as-is (possibly still pending) so `out=` chains — the
                # optimizer-update pattern — stay bulked
                o_dst._set_data(o_src._payload())
                continue
            o_dst._set_data(o_src.data.astype(o_dst.data.dtype)
                            if o_dst.data.dtype != o_src.data.dtype else o_src.data)
        return out
    if multi:
        return outputs
    return outputs[0]


class _TapeVjp:
    """Adapter: autograd hands cotangents as (tuple if >1 else bare); the
    jax.vjp function requires the exact pytree of the primal output."""

    __slots__ = ("vjp_fn", "out_was_tuple")

    def __init__(self, vjp_fn, out_was_tuple):
        self.vjp_fn = vjp_fn
        self.out_was_tuple = out_was_tuple

    def __call__(self, cotangents):
        if self.out_was_tuple and not isinstance(cotangents, tuple):
            cotangents = (cotangents,)
        elif not self.out_was_tuple and isinstance(cotangents, tuple):
            cotangents = cotangents[0]
        return self.vjp_fn(cotangents)


class _Dummy:
    """Placeholder tape input for non-NDArray args (never accumulates)."""
    _ag_node = None
    _grad_req = "null"


_DUMMY = _Dummy()


def _canon_attr(v):
    if isinstance(v, list):
        return tuple(v)
    if isinstance(v, _np.integer):
        return int(v)
    if isinstance(v, _np.floating):
        return float(v)
    return v


def _wrap_jax(value, ctx: Context, copy: bool = False) -> NDArray:
    import jax

    if not hasattr(value, "shape"):
        value = _jnp().asarray(value)
    if copy:
        value = jax.device_put(_np.asarray(value), ctx.jax_device())
    engine.track(value)
    return NDArray(data=value, ctx=ctx)


# ---------------------------------------------------------------------------
# creation
# ---------------------------------------------------------------------------


def array(source_array, ctx: Optional[Context] = None, dtype=None) -> NDArray:
    """Create an NDArray from any array-like (reference: mx.nd.array)."""
    import jax

    ctx = ctx or current_context()
    if isinstance(source_array, NDArray):
        src = source_array.asnumpy()
    else:
        src = _np.asarray(source_array)
    if dtype is None:
        dtype = _np.float32 if src.dtype == _np.float64 else src.dtype
    dt = _resolve_dtype(dtype)
    src = src.astype(dt) if src.dtype != dt else src
    val = jax.device_put(src, ctx.jax_device())
    return NDArray(data=val, ctx=ctx)


def empty(shape, ctx: Optional[Context] = None, dtype=None) -> NDArray:
    import jax

    ctx = ctx or current_context()
    dt = _resolve_dtype(dtype)
    val = jax.device_put(_np.empty(shape, dtype=dt), ctx.jax_device())
    return NDArray(data=val, ctx=ctx)


def waitall() -> None:
    engine.wait_for_all()


# ---------------------------------------------------------------------------
# fluent method surface (reference: ndarray.py — every registered unary /
# attr-only op is callable as a METHOD, e.g. x.sin(), x.broadcast_to(...)).
# Attached here so one list covers the tail instead of 40 hand-written
# forwarders; two-tensor fluent ops get explicit wrappers below.
# ---------------------------------------------------------------------------

def _attach_fluent(name, opname=None):
    op = opname or name

    def method(self, *args, **kw):
        # forward to the module-level wrapper: it owns the positional ->
        # attr mapping (opdef.attr_params order) and the overflow errors,
        # so the fluent surface can never drift from the op signature
        import mxnet_tpu.ndarray as _pkg

        return getattr(_pkg, op)(self, *args, **kw)

    method.__name__ = name
    method.__doc__ = f"Fluent form of ``mx.nd.{op}`` (reference ndarray.py)."
    if not hasattr(NDArray, name):
        setattr(NDArray, name, method)

for _n in ["sort", "round", "rint", "floor", "ceil", "trunc", "fix",
           "log2", "log10", "rsqrt", "cbrt", "sin", "cos", "tan",
           "arcsin", "arccos", "arctan", "sinh", "cosh", "tanh",
           "arcsinh", "arccosh", "arctanh", "degrees", "radians",
           "sigmoid", "relu", "zeros_like", "ones_like", "shape_array",
           "size_array", "diag", "pad", "broadcast_to", "split"]:
    _attach_fluent(_n)


def _nd_pick(self, index, axis=-1, mode="clip", keepdims=False):
    return imperative_invoke(get_op("pick"), [self, index],
                             {"axis": axis, "keepdims": keepdims,
                              "mode": mode})


def _nd_broadcast_like(self, rhs, **kw):
    return imperative_invoke(get_op("broadcast_like"), [self, rhs], kw)


def _nd_slice_like(self, shape_like, axes=()):
    return imperative_invoke(get_op("slice_like"), [self, shape_like],
                             {"axes": axes})


NDArray.pick = _nd_pick
NDArray.broadcast_like = _nd_broadcast_like
NDArray.slice_like = _nd_slice_like
