"""Subgraph backends / custom graph passes (reference:
``src/operator/subgraph/subgraph_property.h`` :: ``SubgraphProperty``,
``build_subgraph.cc``, python ``Symbol.optimize_for`` /
``HybridBlock.optimize_for``).

XLA already performs operator fusion natively, so the reference's main
subgraph use case (oneDNN conv+bn+relu fusion) is mostly subsumed — what
remains valuable is the PLUGGABLE pass hook: users register graph→graph
passes (plus built-ins like inference conv+BN weight folding, which XLA
cannot do because it changes the *parameters*, not the compute graph).

    @subgraph.register_pass("my_pass")
    def my_pass(sym, arg_params, aux_params, **kwargs):
        ...mutate/rebuild...
        return sym, arg_params, aux_params

    subgraph.register_backend("MY_BACKEND", ["fuse_conv_bn", "my_pass"])
    qsym = sym.optimize_for("MY_BACKEND", arg_dict, aux_dict)
"""
from __future__ import annotations

from typing import Callable, Dict, List

import numpy as _np

from .base import MXNetError

__all__ = ["register_pass", "register_backend", "list_backends",
           "apply_backend"]

_PASSES: Dict[str, Callable] = {}
_BACKENDS: Dict[str, List[str]] = {}


def register_pass(name):
    """Decorator: register ``fn(sym, arg_params, aux_params, **kw) ->
    (sym, arg_params, aux_params)`` under ``name``."""
    def deco(fn):
        _PASSES[name] = fn
        return fn
    return deco


def register_backend(name, passes):
    """Register an ordered pass list as a backend (the reference's
    SubgraphProperty registration, e.g. MXNET_SUBGRAPH_BACKEND=MKLDNN)."""
    missing = [p for p in passes if p not in _PASSES]
    if missing:
        raise MXNetError(f"unknown passes {missing}; registered: "
                         f"{sorted(_PASSES)}")
    _BACKENDS[name.upper()] = list(passes)


def list_backends():
    return sorted(_BACKENDS)


def apply_backend(backend, sym, arg_params=None, aux_params=None, **kwargs):
    """Run a backend's passes; params dicts (if given) are updated in
    place. Returns the transformed Symbol."""
    key = str(backend).upper()
    if key not in _BACKENDS:
        raise MXNetError(f"unknown backend {backend!r}; registered: "
                         f"{list_backends()}")
    arg_params = arg_params if arg_params is not None else {}
    aux_params = aux_params if aux_params is not None else {}
    for pname in _BACKENDS[key]:
        sym, arg_params, aux_params = _PASSES[pname](
            sym, arg_params, aux_params, **kwargs)
    return sym


# ---------------------------------------------------------------- passes
def _consumers(sym):
    """Map id(node) -> list of (consumer_node, input_slot)."""
    cons: Dict[int, list] = {}
    for node in sym._topo():
        for slot, (parent, _oi) in enumerate(node.inputs):
            cons.setdefault(id(parent), []).append((node, slot))
    return cons


@register_pass("fuse_conv_bn")
def fuse_conv_bn(sym, arg_params, aux_params, **kwargs):
    """Fold inference BatchNorm into the preceding Convolution's weights
    (the oneDNN subgraph fusion the reference ships):
    ``w' = w * g/sqrt(v+eps)``, ``b' = (b - m) * g/sqrt(v+eps) + beta``.
    Only applies when the conv output feeds ONLY the BN and all five BN
    stats/params are known. INFERENCE-ONLY: training would need the batch
    stats back."""
    from .symbol import symbol as sym_mod

    graph = sym_mod.load_json(sym.tojson())
    cons = _consumers(graph)
    fused = 0
    for node in graph._topo():
        if node.op != "BatchNorm":
            continue
        conv, _ = node.inputs[0]
        if conv.op != "Convolution":
            continue
        if len(cons.get(id(conv), [])) != 1:
            continue                      # conv output used elsewhere
        names = [p.name for p, _ in node.inputs[1:]]
        if len(names) < 4 or not all(
                (n in arg_params) or (n in aux_params) for n in names):
            continue
        gname, bname, mname, vname = names[:4]

        def take(name):
            # checkpoints are often one flat dict — fetch (and later drop)
            # from whichever dict holds the param
            src = arg_params if name in arg_params else aux_params
            return src[name].asnumpy(), src

        gamma, gsrc = take(gname)
        beta, bsrc = take(bname)
        mean, _ = take(mname)
        var, _ = take(vname)
        if int(node.attrs.get("axis", 1)) != 1:
            continue                  # channels-last BN: fold axis differs
        # defaults must match the OP's defaults (ops/nn.py batch_norm)
        eps = float(node.attrs.get("eps", 1e-3))
        # default must match the OP's default (ops/nn.py batch_norm:
        # fix_gamma=True), not False
        if str(node.attrs.get("fix_gamma", True)).lower() in ("true", "1"):
            gamma = _np.ones_like(gamma)
        scale = gamma / _np.sqrt(var + eps)

        wname = conv.inputs[1][0].name
        from .ndarray import array as nd_array

        w = arg_params[wname].asnumpy()
        arg_params[wname] = nd_array(
            (w * scale.reshape((-1,) + (1,) * (w.ndim - 1)))
            .astype(w.dtype))
        no_bias = str(conv.attrs.get("no_bias", False)).lower() in (
            "true", "1")
        if no_bias:
            b = _np.zeros_like(beta)
            bias_name = conv.name + "_fused_bias"
            bias_var = sym_mod.var(bias_name)._entries[0]
            conv.inputs = list(conv.inputs) + [bias_var]
            conv.attrs = dict(conv.attrs)
            conv.attrs["no_bias"] = False
        else:
            bias_name = conv.inputs[2][0].name
            b = arg_params[bias_name].asnumpy()
        arg_params[bias_name] = nd_array(
            ((b - mean) * scale + beta).astype(b.dtype))

        # rewire BN consumers to the conv output and drop the BN params
        for user, slot in cons.get(id(node), []):
            user.inputs[slot] = (conv, 0)
        graph._entries = [(conv, 0) if n is node else (n, i)
                          for n, i in graph._entries]
        for name in (gname, bname, mname, vname):
            arg_params.pop(name, None)
            aux_params.pop(name, None)
        fused += 1
    if fused:
        # rebuild through JSON so dropped nodes disappear from the graph
        graph = sym_mod.load_json(graph.tojson())
    return graph, arg_params, aux_params


register_backend("TPU", ["fuse_conv_bn"])
# reference script compat: ported `optimize_for('MKLDNN'/'ONEDNN')` calls
# get the equivalent inference fusion here
register_backend("MKLDNN", ["fuse_conv_bn"])
register_backend("ONEDNN", ["fuse_conv_bn"])
