"""``mx.contrib.onnx`` — ONNX interop (reference:
``python/mxnet/contrib/onnx/{mx2onnx,onnx2mx}``).

Self-contained: speaks the ONNX protobuf wire format directly (the
``onnx`` pip package is not required — see ``onnx_pb``). Files written
here are stock ONNX; files from other exporters import here as long as
their ops fall in the supported table.

    from mxnet_tpu.contrib import onnx as onnx_mxnet
    onnx_mxnet.export_model(sym, params, [(1, 3, 224, 224)],
                            onnx_file_path="resnet.onnx")
    sym, arg, aux = onnx_mxnet.import_model("resnet.onnx")
"""
from .mx2onnx import export_model
from .onnx2mx import import_model, import_to_gluon, get_model_metadata

__all__ = ["export_model", "import_model", "import_to_gluon",
           "get_model_metadata"]
