"""ONNX → Symbol import (reference surface:
``python/mxnet/contrib/onnx/onnx2mx/import_model.py :: import_model``,
``import_to_gluon.py``, ``import_model.py::get_model_metadata``).

Parses an ONNX file with the self-contained codec (``onnx_pb``) and
rebuilds the graph through ``mx.sym`` operators; initializers become
arg/aux params (aux = whatever the rebuilt symbol lists as auxiliary,
e.g. BatchNorm running stats — same split upstream's importer makes).
"""
from __future__ import annotations

import numpy as _np

from ...base import MXNetError
from . import onnx_pb as pb

__all__ = ["import_model", "import_to_gluon", "get_model_metadata"]

_IMPORTERS = {}


def _imports(*names):
    def deco(fn):
        for n in names:
            _IMPORTERS[n] = fn
        return fn
    return deco


def _first_half_pads(pads):
    if not pads:
        return None
    n = len(pads) // 2
    begins, ends = tuple(pads[:n]), tuple(pads[n:])
    if begins != ends:
        raise MXNetError(
            f"ONNX import: asymmetric pads {pads} need an explicit Pad op")
    return begins


@_imports("Conv")
def _conv(sym, ins, attrs, ctx):
    w = ctx.param_array(1)
    group = int(attrs.get("group", 1))
    return sym.Convolution(
        *ins, kernel=tuple(attrs["kernel_shape"]),
        stride=tuple(attrs.get("strides", ())) or None,
        dilate=tuple(attrs.get("dilations", ())) or None,
        pad=_first_half_pads(attrs.get("pads")),
        num_filter=int(w.shape[0]), num_group=group,
        no_bias=(len(ins) == 2))


@_imports("ConvTranspose")
def _deconv(sym, ins, attrs, ctx):
    w = ctx.param_array(1)
    group = int(attrs.get("group", 1))
    return sym.Deconvolution(
        *ins, kernel=tuple(attrs["kernel_shape"]),
        stride=tuple(attrs.get("strides", ())) or None,
        dilate=tuple(attrs.get("dilations", ())) or None,
        pad=_first_half_pads(attrs.get("pads")),
        num_filter=int(w.shape[1]) * group, num_group=group,
        no_bias=(len(ins) == 2))


@_imports("Gemm")
def _gemm(sym, ins, attrs, ctx):
    alpha = float(attrs.get("alpha", 1.0))
    beta = float(attrs.get("beta", 1.0))
    if alpha != 1.0 or beta != 1.0 or int(attrs.get("transA", 0)):
        raise MXNetError("ONNX import: Gemm with alpha/beta/transA != "
                         "defaults is not supported")
    w = ctx.param_array(1)
    if not int(attrs.get("transB", 0)):
        ctx.set_param(1, _np.ascontiguousarray(w.T))
        w = ctx.param_array(1)
    return sym.FullyConnected(*ins, num_hidden=int(w.shape[0]),
                              no_bias=(len(ins) == 2), flatten=False)


@_imports("BatchNormalization")
def _bn(sym, ins, attrs, ctx):
    return sym.BatchNorm(*ins, eps=float(attrs.get("epsilon", 1e-5)),
                         momentum=float(attrs.get("momentum", 0.9)),
                         fix_gamma=False)


@_imports("LayerNormalization")
def _ln(sym, ins, attrs, ctx):
    return sym.LayerNorm(*ins, axis=int(attrs.get("axis", -1)),
                         eps=float(attrs.get("epsilon", 1e-5)))


@_imports("MaxPool", "AveragePool")
def _pool(sym, ins, attrs, ctx):
    ptype = "max" if ctx.op_type == "MaxPool" else "avg"
    return sym.Pooling(
        ins[0], kernel=tuple(attrs["kernel_shape"]),
        stride=tuple(attrs.get("strides", ())) or None,
        pad=_first_half_pads(attrs.get("pads")), pool_type=ptype,
        count_include_pad=bool(attrs.get("count_include_pad", 0)))


@_imports("GlobalMaxPool", "GlobalAveragePool")
def _gpool(sym, ins, attrs, ctx):
    ptype = "max" if "Max" in ctx.op_type else "avg"
    return sym.Pooling(ins[0], kernel=(1, 1), pool_type=ptype,
                       global_pool=True)


@_imports("Reshape")
def _reshape(sym, ins, attrs, ctx):
    if "shape" in attrs:          # opset < 5 form
        shape = tuple(attrs["shape"])
    else:
        shape = tuple(int(x) for x in ctx.take_constant(1))
    return sym.Reshape(ins[0], shape=shape)


@_imports("Clip")
def _clip(sym, ins, attrs, ctx):
    if len(ins) > 1:
        lo = float(ctx.take_constant(1)) if ins[1] is not None else -_np.inf
        hi = float(ctx.take_constant(2)) if len(ins) > 2 and \
            ins[2] is not None else _np.inf
    else:
        lo = float(attrs.get("min", -_np.inf))
        hi = float(attrs.get("max", _np.inf))
    return sym.clip(ins[0], a_min=lo, a_max=hi)


@_imports("Pad")
def _pad(sym, ins, attrs, ctx):
    if "pads" in attrs:
        pads = list(attrs["pads"])
    else:
        pads = [int(x) for x in ctx.take_constant(1)]
    n = len(pads) // 2
    width = []
    for b, e in zip(pads[:n], pads[n:]):
        width += [b, e]
    return sym.Pad(ins[0], mode=attrs.get("mode", "constant"),
                   pad_width=tuple(width))


@_imports("Gather")
def _gather(sym, ins, attrs, ctx):
    axis = int(attrs.get("axis", 0))
    w = ctx.maybe_param_array(0)
    if axis == 0 and w is not None and w.ndim == 2:
        return sym.Embedding(ins[1], ins[0], input_dim=int(w.shape[0]),
                             output_dim=int(w.shape[1]))
    return sym.take(ins[0], ins[1], axis=axis)


@_imports("Cast")
def _cast(sym, ins, attrs, ctx):
    return sym.Cast(ins[0], dtype=pb.ONNX_TO_NP[int(attrs["to"])])


@_imports("Transpose")
def _transpose(sym, ins, attrs, ctx):
    perm = attrs.get("perm")
    return sym.transpose(ins[0], axes=tuple(perm) if perm else None)


@_imports("Concat")
def _concat(sym, ins, attrs, ctx):
    return sym.Concat(*ins, dim=int(attrs.get("axis", 1)))


@_imports("Softmax", "LogSoftmax")
def _softmax(sym, ins, attrs, ctx):
    fn = sym.log_softmax if ctx.op_type == "LogSoftmax" else sym.softmax
    return fn(ins[0], axis=int(attrs.get("axis", -1)))


@_imports("Dropout")
def _dropout(sym, ins, attrs, ctx):
    return sym.identity(ins[0])


@_imports("ReduceMean", "ReduceSum", "ReduceMax", "ReduceMin")
def _reduce(sym, ins, attrs, ctx):
    fn = {"ReduceMean": sym.mean, "ReduceSum": sym.sum,
          "ReduceMax": sym.max, "ReduceMin": sym.min}[ctx.op_type]
    axes = attrs.get("axes")
    if axes is None and len(ins) > 1:
        axes = [int(x) for x in ctx.take_constant(1)]
    return fn(ins[0], axis=tuple(axes) if axes else None,
              keepdims=bool(attrs.get("keepdims", 1)))


@_imports("Flatten")
def _flatten(sym, ins, attrs, ctx):
    if int(attrs.get("axis", 1)) != 1:
        raise MXNetError("ONNX import: Flatten axis != 1")
    return sym.Flatten(ins[0])


def _simple(op):
    def imp(sym, ins, attrs, ctx):
        return getattr(sym, op)(*ins)
    return imp


for _ox, _mx in [
        ("Add", "broadcast_add"), ("Sub", "broadcast_sub"),
        ("Mul", "broadcast_mul"), ("Div", "broadcast_div"),
        ("Pow", "broadcast_power"), ("Max", "broadcast_maximum"),
        ("Min", "broadcast_minimum"), ("MatMul", "dot"),
        ("Relu", "relu"), ("Sigmoid", "sigmoid"), ("Tanh", "tanh"),
        ("Exp", "exp"), ("Log", "log"), ("Sqrt", "sqrt"), ("Abs", "abs"),
        ("Neg", "negative"), ("Floor", "floor"), ("Ceil", "ceil"),
        ("Erf", "erf"), ("Identity", "identity"), ("Sum", "add_n")]:
    _IMPORTERS[_ox] = _simple(_mx)


@_imports("LeakyRelu")
def _leaky(sym, ins, attrs, ctx):
    return sym.LeakyReLU(ins[0], act_type="leaky",
                         slope=float(attrs.get("alpha", 0.01)))


@_imports("Elu")
def _elu(sym, ins, attrs, ctx):
    return sym.LeakyReLU(ins[0], act_type="elu",
                         slope=float(attrs.get("alpha", 1.0)))


@_imports("PRelu")
def _prelu(sym, ins, attrs, ctx):
    return sym.LeakyReLU(*ins, act_type="prelu")


@_imports("Softplus")
def _softplus(sym, ins, attrs, ctx):
    return sym.Activation(ins[0], act_type="softrelu")


@_imports("Constant")
def _constant(sym, ins, attrs, ctx):
    t = attrs.get("value")
    ctx.add_initializer(ctx.node_name, t.to_array())
    return sym.var(ctx.node_name)


class _ImportCtx:
    def __init__(self, params):
        self.params = params           # name -> np array
        self.consumed = set()
        self.op_type = ""
        self.node_name = ""
        self.in_names = []             # current node's ONNX input names

    def param_array(self, i):
        name = self.in_names[i]
        if name not in self.params:
            raise MXNetError(f"ONNX import: {name!r} is not an initializer")
        return self.params[name]

    def maybe_param_array(self, i):
        return self.params.get(self.in_names[i])

    def set_param(self, i, arr):
        self.params[self.in_names[i]] = arr

    def add_initializer(self, name, arr):
        self.params[name] = _np.asarray(arr)

    def take_constant(self, i):
        """Consume an initializer used as graph metadata (Reshape shape,
        Clip bounds …) — it must NOT surface as a learnable param."""
        arr = self.param_array(i)
        self.consumed.add(self.in_names[i])
        return arr


def _parse(filename):
    with open(filename, "rb") as f:
        model = pb.dec_model(f.read())
    if model.graph is None:
        raise MXNetError(f"{filename}: no graph in ONNX model")
    return model


def import_model(model_file):
    """Returns ``(sym, arg_params, aux_params)`` — the reference
    ``onnx2mx.import_model`` contract."""
    from ... import ndarray as nd_mod
    from ... import symbol as sym_ns

    model = _parse(model_file)
    g = model.graph
    params = {t.name: t.to_array() for t in g.initializer}
    ctx = _ImportCtx(params)

    outputs_of = {}
    for vi in g.input:
        if vi.name not in params:
            outputs_of[vi.name] = sym_ns.var(vi.name)
    for name in params:
        outputs_of[name] = sym_ns.var(name)

    for node in g.node:
        imp = _IMPORTERS.get(node.op_type)
        if imp is None:
            raise MXNetError(
                f"ONNX import: no importer for op {node.op_type!r} "
                f"(node {node.name or node.output[0]}); see "
                "mxnet_tpu/contrib/onnx/onnx2mx.py")
        ctx.op_type = node.op_type
        ctx.node_name = node.name or node.output[0]
        # trailing empty names = omitted optional inputs (drop); interior
        # empties keep their POSITION as None so later inputs don't shift
        # (e.g. Clip with min omitted: ['x', '', 'max'])
        names = list(node.input)
        while names and names[-1] == "":
            names.pop()
        ctx.in_names = names
        ins = []
        for i in names:
            if i == "":
                ins.append(None)
                continue
            if i not in outputs_of:      # late initializer (Constant etc.)
                outputs_of[i] = sym_ns.var(i)
            ins.append(outputs_of[i])
        out = imp(sym_ns, ins, node.attribute, ctx)
        outs = out if isinstance(out, (list, tuple)) else [out]
        for name, s in zip(node.output, outs):
            # graph edges are keyed by ONNX value names; rebind the symbol
            outputs_of[name] = s

    heads = [outputs_of[vi.name] for vi in g.output]
    sym = heads[0] if len(heads) == 1 else sym_ns.Group(heads)

    aux_names = set(sym.list_auxiliary_states())
    arg_names = set(sym.list_arguments())
    arg_params, aux_params = {}, {}
    for name, arr in ctx.params.items():
        if name in ctx.consumed:
            continue
        nd = nd_mod.array(arr)
        if name in aux_names:
            aux_params[name] = nd
        elif name in arg_names:
            arg_params[name] = nd
    return sym, arg_params, aux_params


def import_to_gluon(model_file, ctx=None):
    """Import an ONNX model as a :class:`gluon.SymbolBlock`."""
    from ...gluon.block import SymbolBlock

    sym, arg_params, aux_params = import_model(model_file)
    data_names = [n for n in sym.list_arguments()
                  if n not in arg_params and n not in aux_params]
    from ... import symbol as sym_ns

    inputs = [sym_ns.var(n) for n in data_names]
    net = SymbolBlock(sym, inputs)
    net_params = net.collect_params()
    for name, arr in list(arg_params.items()) + list(aux_params.items()):
        p = net_params[name]
        p.shape = tuple(arr.shape)
        p.initialize(ctx=ctx, force_reinit=True)
        p.set_data(arr)
    return net


def get_model_metadata(model_file):
    """Input/output names+shapes without building the graph (reference:
    onnx2mx.import_model.get_model_metadata)."""
    model = _parse(model_file)
    g = model.graph
    init = {t.name for t in g.initializer}
    return {
        "input_tensor_data": [(vi.name, tuple(vi.shape))
                              for vi in g.input if vi.name not in init],
        "output_tensor_data": [(vi.name, tuple(vi.shape))
                               for vi in g.output],
    }
