"""Self-contained ONNX protobuf wire codec.

Reference surface: ``python/mxnet/contrib/onnx`` (mx2onnx/onnx2mx) sits on
the ``onnx`` pip package. That package is not in this image, so this
module speaks the protobuf WIRE FORMAT for the subset of ``onnx.proto``
the converters need (Model/Graph/Node/Attribute/Tensor/ValueInfo). Files
written here load in stock onnxruntime/netron, and files produced by real
``onnx`` load here — the format is the contract, not the library.

Wire format recap: each field is ``(field_number << 3 | wire_type)`` as a
varint, then the payload; wire types 0 = varint, 1 = fixed64,
2 = length-delimited (strings, bytes, sub-messages, packed scalars),
5 = fixed32.
"""
from __future__ import annotations

import struct

import numpy as _np

# onnx.proto TensorProto.DataType
FLOAT, UINT8, INT8, UINT16, INT16, INT32, INT64 = 1, 2, 3, 4, 5, 6, 7
STRING, BOOL, FLOAT16, DOUBLE, UINT32, UINT64 = 8, 9, 10, 11, 12, 13
BFLOAT16 = 16

NP_TO_ONNX = {
    "float32": FLOAT, "uint8": UINT8, "int8": INT8, "uint16": UINT16,
    "int16": INT16, "int32": INT32, "int64": INT64, "bool": BOOL,
    "float16": FLOAT16, "float64": DOUBLE, "uint32": UINT32,
    "uint64": UINT64, "bfloat16": BFLOAT16,
}
ONNX_TO_NP = {v: k for k, v in NP_TO_ONNX.items()}

# AttributeProto.AttributeType
AT_FLOAT, AT_INT, AT_STRING, AT_TENSOR, AT_GRAPH = 1, 2, 3, 4, 5
AT_FLOATS, AT_INTS, AT_STRINGS = 6, 7, 8


# ---------------------------------------------------------------- encode
def _varint(n: int) -> bytes:
    n &= (1 << 64) - 1
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _tag(field: int, wire: int) -> bytes:
    return _varint((field << 3) | wire)


def _f_varint(field: int, value: int) -> bytes:
    return _tag(field, 0) + _varint(int(value))


def _f_bytes(field: int, payload: bytes) -> bytes:
    return _tag(field, 2) + _varint(len(payload)) + payload


def _f_str(field: int, s: str) -> bytes:
    return _f_bytes(field, s.encode("utf-8"))


def _f_float(field: int, v: float) -> bytes:
    return _tag(field, 5) + struct.pack("<f", float(v))


class _Msg:
    """Base: encodes to bytes via ``encode``; fields set in __init__."""

    def encode(self) -> bytes:  # pragma: no cover - abstract
        raise NotImplementedError


class TensorProto(_Msg):
    def __init__(self, name="", dims=(), data_type=FLOAT, raw_data=b""):
        self.name = name
        self.dims = list(dims)
        self.data_type = data_type
        self.raw_data = raw_data

    @classmethod
    def from_array(cls, arr, name=""):
        arr = _np.ascontiguousarray(arr)
        dt = NP_TO_ONNX.get(str(arr.dtype))
        if dt is None:
            raise ValueError(f"no ONNX dtype for {arr.dtype}")
        little = arr.astype(arr.dtype.newbyteorder("<"), copy=False)
        return cls(name=name, dims=arr.shape, data_type=dt,
                   raw_data=little.tobytes())

    def to_array(self):
        dtype = _np.dtype(ONNX_TO_NP[self.data_type]).newbyteorder("<")
        if self.raw_data:
            a = _np.frombuffer(self.raw_data, dtype=dtype)
        else:
            a = _np.asarray(self.typed_data, dtype=dtype)
        return a.reshape(self.dims).astype(dtype.newbyteorder("="))

    def encode(self) -> bytes:
        out = b"".join(_f_varint(1, d) for d in self.dims)
        out += _f_varint(2, self.data_type)
        out += _f_str(8, self.name)
        out += _f_bytes(9, self.raw_data)
        return out


class ValueInfoProto(_Msg):
    def __init__(self, name="", elem_type=FLOAT, shape=()):
        self.name = name
        self.elem_type = elem_type
        self.shape = list(shape)  # ints or strings (symbolic dims)

    def encode(self) -> bytes:
        dims = b""
        for d in self.shape:
            if isinstance(d, str):
                dims += _f_bytes(1, _f_str(2, d))
            else:
                dims += _f_bytes(1, _f_varint(1, int(d)))
        tensor = _f_varint(1, self.elem_type) + _f_bytes(2, dims)
        return _f_str(1, self.name) + _f_bytes(2, _f_bytes(1, tensor))


class AttributeProto(_Msg):
    def __init__(self, name, value):
        self.name = name
        self.value = value

    def encode(self) -> bytes:
        out = _f_str(1, self.name)
        v = self.value
        if isinstance(v, bool):
            out += _f_varint(3, int(v)) + _f_varint(20, AT_INT)
        elif isinstance(v, int):
            out += _f_varint(3, v) + _f_varint(20, AT_INT)
        elif isinstance(v, float):
            out += _f_float(2, v) + _f_varint(20, AT_FLOAT)
        elif isinstance(v, str):
            out += _f_bytes(4, v.encode()) + _f_varint(20, AT_STRING)
        elif isinstance(v, bytes):
            out += _f_bytes(4, v) + _f_varint(20, AT_STRING)
        elif isinstance(v, TensorProto):
            out += _f_bytes(5, v.encode()) + _f_varint(20, AT_TENSOR)
        elif isinstance(v, (list, tuple)):
            if all(isinstance(x, (int, bool)) for x in v):
                out += b"".join(_f_varint(8, int(x)) for x in v)
                out += _f_varint(20, AT_INTS)
            elif all(isinstance(x, float) for x in v):
                out += b"".join(_tag(7, 5) + struct.pack("<f", x) for x in v)
                out += _f_varint(20, AT_FLOATS)
            elif all(isinstance(x, (str, bytes)) for x in v):
                out += b"".join(
                    _f_bytes(9, x.encode() if isinstance(x, str) else x)
                    for x in v)
                out += _f_varint(20, AT_STRINGS)
            else:
                raise TypeError(f"mixed attribute list: {v!r}")
        else:
            raise TypeError(f"unsupported attribute value: {v!r}")
        return out


class NodeProto(_Msg):
    def __init__(self, op_type, inputs, outputs, name="", attrs=None,
                 domain=""):
        self.op_type = op_type
        self.input = list(inputs)
        self.output = list(outputs)
        self.name = name
        self.domain = domain
        self.attribute = [AttributeProto(k, v)
                          for k, v in (attrs or {}).items()
                          if v is not None]

    def encode(self) -> bytes:
        out = b"".join(_f_str(1, s) for s in self.input)
        out += b"".join(_f_str(2, s) for s in self.output)
        out += _f_str(3, self.name)
        out += _f_str(4, self.op_type)
        out += b"".join(_f_bytes(5, a.encode()) for a in self.attribute)
        if self.domain:
            out += _f_str(7, self.domain)
        return out


class GraphProto(_Msg):
    def __init__(self, name="mxnet_tpu", nodes=(), inputs=(), outputs=(),
                 initializers=()):
        self.node = list(nodes)
        self.name = name
        self.input = list(inputs)
        self.output = list(outputs)
        self.initializer = list(initializers)

    def encode(self) -> bytes:
        out = b"".join(_f_bytes(1, n.encode()) for n in self.node)
        out += _f_str(2, self.name)
        out += b"".join(_f_bytes(5, t.encode()) for t in self.initializer)
        out += b"".join(_f_bytes(11, v.encode()) for v in self.input)
        out += b"".join(_f_bytes(12, v.encode()) for v in self.output)
        return out


class ModelProto(_Msg):
    # opset 17: ReduceSum takes axes as input (>=13) and
    # LayerNormalization exists (==17); ReduceMean/Max/Min still take the
    # axes attribute (they switch at 18)
    def __init__(self, graph, ir_version=8, opset=17,
                 producer_name="mxnet_tpu", producer_version="2.0"):
        self.ir_version = ir_version
        self.opset = opset
        self.producer_name = producer_name
        self.producer_version = producer_version
        self.graph = graph

    def encode(self) -> bytes:
        out = _f_varint(1, self.ir_version)
        out += _f_str(2, self.producer_name)
        out += _f_str(3, self.producer_version)
        out += _f_bytes(7, self.graph.encode())
        out += _f_bytes(8, _f_varint(2, self.opset))  # opset_import{version}
        return out


# ---------------------------------------------------------------- decode
def _read_varint(buf, i):
    shift = 0
    val = 0
    while True:
        b = buf[i]
        i += 1
        val |= (b & 0x7F) << shift
        if not b & 0x80:
            return val, i
        shift += 7


def _fields(buf):
    """Yield (field_number, wire_type, value) triples."""
    i = 0
    n = len(buf)
    while i < n:
        tag, i = _read_varint(buf, i)
        field, wire = tag >> 3, tag & 7
        if wire == 0:
            val, i = _read_varint(buf, i)
        elif wire == 2:
            ln, i = _read_varint(buf, i)
            val = buf[i:i + ln]
            i += ln
        elif wire == 5:
            val = buf[i:i + 4]
            i += 4
        elif wire == 1:
            val = buf[i:i + 8]
            i += 8
        else:
            raise ValueError(f"unsupported wire type {wire}")
        yield field, wire, val


def _signed64(v):
    return v - (1 << 64) if v >= (1 << 63) else v


def _ints(wire, val, width="q"):
    """A repeated-int field entry: packed (wire 2) or single varint."""
    if wire == 0:
        return [_signed64(val)]
    out = []
    i = 0
    while i < len(val):
        v, i = _read_varint(val, i)
        out.append(_signed64(v))
    return out


class _D:  # decoded-message namespace
    def __repr__(self):
        return f"{self.__class__.__name__}({self.__dict__})"


def dec_tensor(buf) -> TensorProto:
    t = TensorProto()
    t.typed_data = []
    for f, w, v in _fields(buf):
        if f == 1:
            t.dims += _ints(w, v)
        elif f == 2:
            t.data_type = v
        elif f == 4:
            if w == 5:
                t.typed_data.append(struct.unpack("<f", v)[0])
            else:
                t.typed_data += [x[0] for x in struct.iter_unpack("<f", v)]
        elif f in (5, 7, 11):
            t.typed_data += _ints(w, v)
        elif f == 8:
            t.name = v.decode()
        elif f == 9:
            t.raw_data = v
        elif f == 10:
            if w == 1:
                t.typed_data.append(struct.unpack("<d", v)[0])
            else:
                t.typed_data += [x[0] for x in struct.iter_unpack("<d", v)]
    return t


def dec_attribute(buf):
    a = _D()
    a.name = ""
    a.f = None
    a.i = None
    a.s = None
    a.t = None
    a.floats = []
    a.ints = []
    a.strings = []
    a.type = 0
    for f, w, v in _fields(buf):
        if f == 1:
            a.name = v.decode()
        elif f == 2:
            a.f = struct.unpack("<f", v)[0]
        elif f == 3:
            a.i = _signed64(v)
        elif f == 4:
            a.s = v
        elif f == 5:
            a.t = dec_tensor(v)
        elif f == 7:
            if w == 5:
                a.floats.append(struct.unpack("<f", v)[0])
            else:
                a.floats += [x[0] for x in struct.iter_unpack("<f", v)]
        elif f == 8:
            a.ints += _ints(w, v)
        elif f == 9:
            a.strings.append(v)
        elif f == 20:
            a.type = v
    return a


def attr_value(a):
    """Collapse a decoded AttributeProto to its python value."""
    if a.type == AT_FLOAT:
        return a.f
    if a.type == AT_INT:
        return a.i
    if a.type == AT_STRING:
        return a.s.decode()
    if a.type == AT_TENSOR:
        return a.t
    if a.type == AT_FLOATS:
        return list(a.floats)
    if a.type == AT_INTS:
        return list(a.ints)
    if a.type == AT_STRINGS:
        return [s.decode() for s in a.strings]
    # untyped (some writers omit field 20): first non-empty wins
    for v in (a.i, a.f, a.s):
        if v is not None:
            return v.decode() if isinstance(v, bytes) else v
    return a.ints or a.floats or a.t


def dec_node(buf):
    n = _D()
    n.input, n.output, n.attribute = [], [], {}
    n.name = n.op_type = n.domain = ""
    for f, w, v in _fields(buf):
        if f == 1:
            n.input.append(v.decode())
        elif f == 2:
            n.output.append(v.decode())
        elif f == 3:
            n.name = v.decode()
        elif f == 4:
            n.op_type = v.decode()
        elif f == 5:
            a = dec_attribute(v)
            n.attribute[a.name] = attr_value(a)
        elif f == 7:
            n.domain = v.decode()
    return n


def dec_value_info(buf):
    vi = _D()
    vi.name = ""
    vi.elem_type = FLOAT
    vi.shape = []
    for f, w, v in _fields(buf):
        if f == 1:
            vi.name = v.decode()
        elif f == 2:
            for f2, _w2, v2 in _fields(v):
                if f2 != 1:    # tensor_type
                    continue
                for f3, _w3, v3 in _fields(v2):
                    if f3 == 1:
                        vi.elem_type = v3
                    elif f3 == 2:
                        for f4, _w4, v4 in _fields(v3):
                            if f4 != 1:
                                continue
                            dim = None
                            for f5, w5, v5 in _fields(v4):
                                if f5 == 1:
                                    dim = _signed64(v5)
                                elif f5 == 2:
                                    dim = v5.decode()
                            vi.shape.append(dim if dim is not None else 0)
    return vi


def dec_graph(buf):
    g = _D()
    g.node, g.initializer, g.input, g.output = [], [], [], []
    g.name = ""
    for f, w, v in _fields(buf):
        if f == 1:
            g.node.append(dec_node(v))
        elif f == 2:
            g.name = v.decode()
        elif f == 5:
            g.initializer.append(dec_tensor(v))
        elif f == 11:
            g.input.append(dec_value_info(v))
        elif f == 12:
            g.output.append(dec_value_info(v))
    return g


def dec_model(buf):
    m = _D()
    m.ir_version = 0
    m.producer_name = ""
    m.graph = None
    m.opset = 0
    for f, w, v in _fields(buf):
        if f == 1:
            m.ir_version = v
        elif f == 2:
            m.producer_name = v.decode()
        elif f == 7:
            m.graph = dec_graph(v)
        elif f == 8:
            for f2, _w2, v2 in _fields(v):
                if f2 == 2:
                    m.opset = max(m.opset, _signed64(v2))
    return m
