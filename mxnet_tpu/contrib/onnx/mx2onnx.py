"""Symbol graph → ONNX export (reference surface:
``python/mxnet/contrib/onnx/mx2onnx/export_model.py :: export_model``).

Walks the Symbol's JSON graph (the same artifact ``HybridBlock.export``
writes) and emits an ONNX ModelProto through the self-contained codec in
``onnx_pb``. Converters cover the op families the model zoos lower to;
unknown ops raise with the op name so gaps fail loudly.
"""
from __future__ import annotations

import ast
import json
import logging

import numpy as _np

from ...base import MXNetError
from . import onnx_pb as pb

__all__ = ["export_model"]


def _tuple_attr(attrs, key, default=None):
    v = attrs.get(key)
    if v is None:
        return default
    if isinstance(v, (tuple, list)):
        return tuple(int(x) for x in v)
    v = str(v).strip()
    try:
        parsed = ast.literal_eval(v)
    except (ValueError, SyntaxError):
        return default
    if isinstance(parsed, (tuple, list)):
        return tuple(int(x) for x in parsed)
    return (int(parsed),)


def _bool_attr(attrs, key, default=False):
    v = attrs.get(key)
    if v is None:
        return default
    return str(v).lower() in ("true", "1")


def _pads(pad):
    # mxnet pad is per-dim begin==end; ONNX wants begins then ends
    return list(pad) + list(pad)


_CONVERTERS = {}


def _converts(*names):
    def deco(fn):
        for n in names:
            _CONVERTERS[n] = fn
        return fn
    return deco


class _Ctx:
    """Per-export state: name maps, initializers, emitted nodes."""

    def __init__(self, params, dtype):
        self.params = params
        self.dtype = dtype
        self.nodes = []
        self.initializers = []
        self.init_names = set()

    def emit(self, op_type, inputs, outputs, name=None, **attrs):
        self.nodes.append(pb.NodeProto(op_type, inputs, outputs,
                                       name=name or outputs[0],
                                       attrs=attrs))
        return outputs[0]

    def constant(self, name, arr):
        if name not in self.init_names:
            self.initializers.append(
                pb.TensorProto.from_array(_np.asarray(arr), name=name))
            self.init_names.add(name)
        return name


# -- converters ---------------------------------------------------------
@_converts("FullyConnected")
def _fc(ctx, name, ins, attrs):
    no_bias = _bool_attr(attrs, "no_bias")
    flatten = _bool_attr(attrs, "flatten", True)
    x, w = ins[0], ins[1]
    if flatten:
        x = ctx.emit("Flatten", [x], [name + "_flat"], axis=1)
        if no_bias:
            zero = ctx.constant(
                name + "_zero_bias",
                _np.zeros((int(attrs["num_hidden"]),), ctx.dtype))
            return ctx.emit("Gemm", [x, w, zero], [name], alpha=1.0,
                            beta=1.0, transA=0, transB=1)
        return ctx.emit("Gemm", [x, w, ins[2]], [name], alpha=1.0,
                        beta=1.0, transA=0, transB=1)
    # N-D input: MatMul against W^T, then Add bias
    wt = ctx.emit("Transpose", [w], [name + "_wT"], perm=[1, 0])
    y = ctx.emit("MatMul", [x, wt],
                 [name if no_bias else name + "_mm"])
    if not no_bias:
        y = ctx.emit("Add", [y, ins[2]], [name])
    return y


@_converts("Convolution")
def _conv(ctx, name, ins, attrs):
    kernel = _tuple_attr(attrs, "kernel")
    nd = len(kernel)
    conv_attrs = dict(
        kernel_shape=list(kernel),
        strides=list(_tuple_attr(attrs, "stride", (1,) * nd)),
        dilations=list(_tuple_attr(attrs, "dilate", (1,) * nd)),
        pads=_pads(_tuple_attr(attrs, "pad", (0,) * nd)),
        group=int(attrs.get("num_group", 1)),
    )
    return ctx.emit("Conv", list(ins), [name], **conv_attrs)


@_converts("Deconvolution")
def _deconv(ctx, name, ins, attrs):
    kernel = _tuple_attr(attrs, "kernel")
    nd = len(kernel)
    return ctx.emit(
        "ConvTranspose", list(ins), [name],
        kernel_shape=list(kernel),
        strides=list(_tuple_attr(attrs, "stride", (1,) * nd)),
        dilations=list(_tuple_attr(attrs, "dilate", (1,) * nd)),
        pads=_pads(_tuple_attr(attrs, "pad", (0,) * nd)),
        group=int(attrs.get("num_group", 1)))


@_converts("Activation")
def _act(ctx, name, ins, attrs):
    act = attrs.get("act_type", "relu")
    table = {"relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh",
             "softsign": "Softsign", "silu": None, "softrelu": None}
    if act not in table:
        raise MXNetError(f"ONNX export: unsupported act_type {act!r}")
    if act == "silu":
        s = ctx.emit("Sigmoid", [ins[0]], [name + "_sig"])
        return ctx.emit("Mul", [ins[0], s], [name])
    if act == "softrelu":
        return ctx.emit("Softplus", [ins[0]], [name])
    return ctx.emit(table[act], [ins[0]], [name])


@_converts("LeakyReLU")
def _leaky(ctx, name, ins, attrs):
    act = attrs.get("act_type", "leaky")
    if act == "leaky":
        return ctx.emit("LeakyRelu", [ins[0]], [name],
                        alpha=float(attrs.get("slope", 0.25)))
    if act == "elu":
        return ctx.emit("Elu", [ins[0]], [name],
                        alpha=float(attrs.get("slope", 0.25)))
    if act == "prelu":
        return ctx.emit("PRelu", list(ins), [name])
    if act == "gelu":
        # erf formulation: x * 0.5 * (1 + erf(x / sqrt(2)))
        c = ctx.constant(name + "_rsqrt2",
                         _np.asarray(1.0 / _np.sqrt(2.0), ctx.dtype))
        h = ctx.emit("Mul", [ins[0], c], [name + "_h"])
        e = ctx.emit("Erf", [h], [name + "_erf"])
        one = ctx.constant(name + "_one", _np.asarray(1.0, ctx.dtype))
        half = ctx.constant(name + "_half", _np.asarray(0.5, ctx.dtype))
        e1 = ctx.emit("Add", [e, one], [name + "_e1"])
        xh = ctx.emit("Mul", [ins[0], half], [name + "_xh"])
        return ctx.emit("Mul", [xh, e1], [name])
    raise MXNetError(f"ONNX export: unsupported LeakyReLU {act!r}")


@_converts("BatchNorm")
def _bn(ctx, name, ins, attrs):
    return ctx.emit("BatchNormalization", list(ins[:5]), [name],
                    epsilon=float(attrs.get("eps", 1e-5)),
                    momentum=float(attrs.get("momentum", 0.9)))


@_converts("LayerNorm")
def _ln(ctx, name, ins, attrs):
    return ctx.emit("LayerNormalization", list(ins[:3]), [name],
                    axis=int(attrs.get("axis", -1)),
                    epsilon=float(attrs.get("eps", 1e-5)))


@_converts("Pooling")
def _pool(ctx, name, ins, attrs):
    ptype = attrs.get("pool_type", "max")
    if _bool_attr(attrs, "global_pool"):
        op = {"max": "GlobalMaxPool", "avg": "GlobalAveragePool"}.get(ptype)
        if op is None:
            raise MXNetError(f"ONNX export: global {ptype} pooling")
        return ctx.emit(op, [ins[0]], [name])
    kernel = _tuple_attr(attrs, "kernel")
    nd = len(kernel)
    kw = dict(kernel_shape=list(kernel),
              strides=list(_tuple_attr(attrs, "stride", (1,) * nd)),
              pads=_pads(_tuple_attr(attrs, "pad", (0,) * nd)))
    if ptype == "max":
        return ctx.emit("MaxPool", [ins[0]], [name], **kw)
    if ptype == "avg":
        kw["count_include_pad"] = 0 if _bool_attr(
            attrs, "count_include_pad", True) is False else 1
        return ctx.emit("AveragePool", [ins[0]], [name], **kw)
    raise MXNetError(f"ONNX export: unsupported pool_type {ptype!r}")


@_converts("Flatten")
def _flatten(ctx, name, ins, attrs):
    return ctx.emit("Flatten", [ins[0]], [name], axis=1)


@_converts("Reshape")
def _reshape(ctx, name, ins, attrs):
    shape = _tuple_attr(attrs, "shape")
    c = ctx.constant(name + "_shape", _np.asarray(shape, _np.int64))
    return ctx.emit("Reshape", [ins[0], c], [name])


@_converts("transpose")
def _transpose(ctx, name, ins, attrs):
    axes = _tuple_attr(attrs, "axes")
    kw = {"perm": list(axes)} if axes else {}
    return ctx.emit("Transpose", [ins[0]], [name], **kw)


@_converts("softmax", "log_softmax")
def _softmax(ctx, name, ins, attrs):
    axis = int(attrs.get("axis", -1))
    op = "LogSoftmax" if attrs.get("__op__") == "log_softmax" else "Softmax"
    return ctx.emit(op, [ins[0]], [name], axis=axis)


@_converts("SoftmaxOutput")
def _softmax_out(ctx, name, ins, attrs):
    # inference surface: plain softmax over the last axis
    return ctx.emit("Softmax", [ins[0]], [name], axis=-1)


@_converts("Concat")
def _concat(ctx, name, ins, attrs):
    return ctx.emit("Concat", list(ins), [name],
                    axis=int(attrs.get("dim", 1)))


@_converts("Dropout")
def _dropout(ctx, name, ins, attrs):
    # inference graph: identity
    return ctx.emit("Identity", [ins[0]], [name])


@_converts("Embedding")
def _embedding(ctx, name, ins, attrs):
    idx = ctx.emit("Cast", [ins[0]], [name + "_i64"], to=pb.INT64)
    return ctx.emit("Gather", [ins[1], idx], [name], axis=0)


@_converts("Cast")
def _cast(ctx, name, ins, attrs):
    dt = pb.NP_TO_ONNX[str(_np.dtype(attrs.get("dtype", "float32")))]
    return ctx.emit("Cast", [ins[0]], [name], to=dt)


@_converts("clip")
def _clip(ctx, name, ins, attrs):
    lo = ctx.constant(name + "_min",
                      _np.asarray(float(attrs["a_min"]), ctx.dtype))
    hi = ctx.constant(name + "_max",
                      _np.asarray(float(attrs["a_max"]), ctx.dtype))
    return ctx.emit("Clip", [ins[0], lo, hi], [name])


@_converts("Pad")
def _pad(ctx, name, ins, attrs):
    width = _tuple_attr(attrs, "pad_width")
    # mxnet interleaves (before, after) per dim; ONNX: all befores, afters
    befores, afters = list(width[0::2]), list(width[1::2])
    c = ctx.constant(name + "_pads",
                     _np.asarray(befores + afters, _np.int64))
    mode = attrs.get("mode", "constant")
    return ctx.emit("Pad", [ins[0], c], [name], mode=mode)


def _binary(onnx_op):
    def conv(ctx, name, ins, attrs):
        return ctx.emit(onnx_op, list(ins), [name])
    return conv


for _mx, _ox in [
        ("elemwise_add", "Add"), ("_plus", "Add"), ("broadcast_add", "Add"),
        ("_Plus", "Add"),
        ("elemwise_sub", "Sub"), ("broadcast_sub", "Sub"),
        ("elemwise_mul", "Mul"), ("broadcast_mul", "Mul"),
        ("elemwise_div", "Div"), ("broadcast_div", "Div"),
        ("dot", "MatMul"), ("batch_dot", "MatMul"),
        ("broadcast_maximum", "Max"), ("broadcast_minimum", "Min"),
        ("broadcast_power", "Pow")]:
    _CONVERTERS[_mx] = _binary(_ox)

for _mx, _ox in [
        ("relu", "Relu"), ("sigmoid", "Sigmoid"), ("tanh", "Tanh"),
        ("exp", "Exp"), ("log", "Log"), ("sqrt", "Sqrt"), ("abs", "Abs"),
        ("negative", "Neg"), ("floor", "Floor"), ("ceil", "Ceil"),
        ("erf", "Erf"), ("identity", "Identity"), ("BlockGrad", "Identity"),
        ("add_n", "Sum")]:
    def _mk(_op):
        def conv(ctx, name, ins, attrs):
            return ctx.emit(_op, list(ins), [name])
        return conv
    _CONVERTERS[_mx] = _mk(_ox)


@_converts("mean", "sum", "max", "min")
def _reduce(ctx, name, ins, attrs):
    op = {"mean": "ReduceMean", "sum": "ReduceSum", "max": "ReduceMax",
          "min": "ReduceMin"}[attrs["__op__"]]
    axes = _tuple_attr(attrs, "axis")
    kw = dict(keepdims=1 if _bool_attr(attrs, "keepdims") else 0)
    inputs = [ins[0]]
    if axes is not None:
        if op == "ReduceSum":
            # axes moved from attribute to input at opset 13
            inputs.append(ctx.constant(
                name + "_axes", _np.asarray(axes, _np.int64)))
        else:
            kw["axes"] = list(axes)
    return ctx.emit(op, inputs, [name], **kw)


# -- driver -------------------------------------------------------------
def export_model(sym, params, input_shapes=None, input_dtype="float32",
                 onnx_file_path="model.onnx", verbose=False,
                 in_shapes=None, in_types=None):
    """Export a Symbol (or symbol-file path) + params to an ONNX file.

    ``sym``: Symbol or path to ``*-symbol.json``; ``params``: dict of
    NDArray/ndarray (``arg:``/``aux:`` prefixes accepted — the ``.params``
    artifact of ``HybridBlock.export``) or a path to such a file.
    Returns ``onnx_file_path``.
    """
    from ... import ndarray as nd_mod
    from ...symbol import symbol as sym_mod

    if isinstance(sym, str):
        sym = sym_mod.load(sym)
    if isinstance(params, str):
        params = nd_mod.load(params)
    if input_shapes is None:
        input_shapes = in_shapes
    if in_types is not None:
        input_dtype = in_types if isinstance(in_types, str) else in_types[0]
    dtype = _np.dtype(input_dtype)

    clean = {}
    for k, v in (params or {}).items():
        k = k.split(":", 1)[1] if k.startswith(("arg:", "aux:")) else k
        clean[k] = _np.asarray(v.asnumpy() if hasattr(v, "asnumpy") else v)

    graph = json.loads(sym.tojson())
    nodes = graph["nodes"]
    heads = graph["heads"]

    ctx = _Ctx(clean, dtype)
    out_of = {}          # node index -> onnx value name
    graph_inputs = []
    data_idx = 0
    for i, node in enumerate(nodes):
        name = node["name"]
        if node["op"] == "null":
            out_of[i] = name
            if name in clean:
                ctx.constant(name, clean[name])
            else:
                shape = None
                if isinstance(input_shapes, dict):
                    shape = input_shapes.get(name)
                elif input_shapes is not None:
                    if data_idx < len(input_shapes):
                        shape = input_shapes[data_idx]
                    data_idx += 1
                graph_inputs.append(pb.ValueInfoProto(
                    name, pb.NP_TO_ONNX[str(dtype)],
                    shape if shape is not None else ()))
            continue
        op = node["op"]
        conv = _CONVERTERS.get(op)
        if conv is None:
            raise MXNetError(
                f"ONNX export: no converter for op {op!r} (node {name}); "
                "see mxnet_tpu/contrib/onnx/mx2onnx.py")
        ins = [out_of[a[0]] if a[1] == 0 else f"{out_of[a[0]]}__{a[1]}"
               for a in node["inputs"]]
        attrs = dict(node.get("attrs", {}))
        attrs["__op__"] = op
        out_of[i] = conv(ctx, name, ins, attrs)
        if verbose:
            logging.info("converted %s (%s)", name, op)

    outputs = [pb.ValueInfoProto(out_of[h[0]] if h[1] == 0
                                 else f"{out_of[h[0]]}__{h[1]}",
                                 pb.NP_TO_ONNX[str(dtype)], ())
               for h in heads]
    g = pb.GraphProto(nodes=ctx.nodes, inputs=graph_inputs,
                      outputs=outputs, initializers=ctx.initializers)
    model = pb.ModelProto(g)
    with open(onnx_file_path, "wb") as f:
        f.write(model.encode())
    return onnx_file_path
