"""SVRG optimization (reference:
``python/mxnet/contrib/svrg_optimization/{svrg_module,svrg_optimizer}.py``
:: ``SVRGModule``) — Johnson & Zhang (2013) stochastic variance-reduced
gradient.

Every ``update_freq`` epochs the module snapshots the weights ``w~`` and
accumulates the FULL dataset gradient ``mu = mean_i grad_i(w~)``; each
minibatch then steps with the variance-reduced direction
``g_i(w) - g_i(w~) + mu``. The special-cased SGD the reference implements
as ``_SVRGOptimizer`` is here a gradient rewrite in ``update()``, so ANY
registered optimizer drives the corrected gradient."""
from __future__ import annotations

import logging
from typing import Dict, Optional

from ..base import MXNetError
from ..module.module import Module

__all__ = ["SVRGModule"]


class SVRGModule(Module):
    """Module with SVRG gradient correction (reference: SVRGModule).

    Extra parameter: ``update_freq`` — snapshot + full-gradient refresh
    period, in epochs.
    """

    def __init__(self, symbol, data_names=("data",),
                 label_names=("softmax_label",), update_freq=2, **kwargs):
        super().__init__(symbol, data_names=data_names,
                         label_names=label_names, **kwargs)
        if int(update_freq) < 1:
            raise MXNetError("update_freq must be >= 1 (epochs)")
        self.update_freq = int(update_freq)
        # snapshot weights w~ and full gradient mu, by param name
        self._snapshot: Dict[str, object] = {}
        self._full_grads: Dict[str, object] = {}
        # batch gradients at w~ for the CURRENT batch
        self._snap_batch_grads: Dict[str, object] = {}

    # -- SVRG machinery -------------------------------------------------
    def take_snapshot(self):
        """w~ <- w (reference: SVRGModule._update_svrg_weights)."""
        self._snapshot = {name: self._exec.arg_dict[name].copy()
                          for name in self._param_names
                          if name in self._exec.arg_dict}

    def update_full_grads(self, train_data):
        """mu <- mean over ``train_data`` of grad(w~) (reference:
        SVRGModule.update_full_grads). Call after take_snapshot()."""
        if not self._snapshot:
            self.take_snapshot()
        # .copy(): arg_dict holds the LIVE NDArrays; saving the objects
        # and then _set_data'ing them would alias away the live weights
        saved = {n: self._exec.arg_dict[n].copy() for n in self._snapshot}
        totals = {n: None for n in self._snapshot}
        nbatch = 0
        try:
            for n, w in self._snapshot.items():
                self._exec.arg_dict[n]._set_data(w.data)
            train_data.reset()
            for batch in train_data:
                self.forward_backward(batch)
                nbatch += 1
                for n in totals:
                    g = self._exec.grad_dict.get(n)
                    if g is None:
                        continue
                    totals[n] = g.copy() if totals[n] is None \
                        else totals[n] + g
        finally:
            for n, w in saved.items():
                self._exec.arg_dict[n]._set_data(w.data)
            train_data.reset()
        if nbatch == 0:
            raise MXNetError("update_full_grads: empty train_data")
        self._full_grads = {n: t / float(nbatch)
                            for n, t in totals.items() if t is not None}

    def _compute_snapshot_batch_grads(self, data_batch):
        """grad_i(w~) for one batch, leaving live weights untouched."""
        saved = {n: self._exec.arg_dict[n].copy() for n in self._snapshot}
        try:
            for n, w in self._snapshot.items():
                self._exec.arg_dict[n]._set_data(w.data)
            self.forward_backward(data_batch)
            self._snap_batch_grads = {
                n: self._exec.grad_dict[n].copy()
                for n in self._snapshot
                if self._exec.grad_dict.get(n) is not None}
        finally:
            for n, w in saved.items():
                self._exec.arg_dict[n]._set_data(w.data)

    def forward_backward(self, data_batch):
        super().forward_backward(data_batch)

    def svrg_forward_backward(self, data_batch):
        """One SVRG step's gradients: runs the snapshot pass FIRST (it
        clobbers grad buffers), then the live pass, so ``update()`` sees
        live ``g_i(w)`` plus the stored correction terms."""
        if self._full_grads:
            self._compute_snapshot_batch_grads(data_batch)
        self.forward_backward(data_batch)

    def update(self):
        """Apply w -= lr * (g_i(w) - g_i(w~) + mu) via the bound
        optimizer (reference: _SVRGOptimizer's corrected update)."""
        if not self._full_grads:
            return super().update()
        for i, name in enumerate(self._param_names):
            grad = self._exec.grad_dict.get(name)
            if grad is None:
                continue
            g_snap = self._snap_batch_grads.get(name)
            mu = self._full_grads.get(name)
            if g_snap is not None and mu is not None:
                grad = grad - g_snap + mu
            if self._compression is not None:
                grad = self._compression.compress(name, 0, grad)
            self._updater(i, grad, self._exec.arg_dict[name])

    # -- training loop --------------------------------------------------
    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None,
            kvstore="local", optimizer="sgd",
            optimizer_params=(("learning_rate", 0.01),),
            initializer=None, num_epoch=1, force_init=False,
            validation_metric=None, **kwargs):
        """SVRG training loop (reference: SVRGModule.fit): every
        ``update_freq`` epochs, refresh w~ and mu over the whole data."""
        from .. import metric as metric_mod

        if not self.binded:
            self.bind(data_shapes=train_data.provide_data,
                      label_shapes=train_data.provide_label,
                      for_training=True)
        if not self.params_initialized or force_init:
            self.init_params(initializer=initializer, force_init=force_init)
        if not self.optimizer_initialized or force_init:
            self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                                optimizer_params=optimizer_params)
        eval_metric = metric_mod.create(eval_metric) \
            if not hasattr(eval_metric, "update") else eval_metric
        for epoch in range(num_epoch):
            if epoch % self.update_freq == 0:
                self.take_snapshot()
                self.update_full_grads(train_data)
            eval_metric.reset()
            train_data.reset()
            for nbatch, batch in enumerate(train_data):
                self.svrg_forward_backward(batch)
                self.update()
                self.update_metric(eval_metric, batch.label)
                if batch_end_callback is not None:
                    batch_end_callback(type("P", (), {
                        "epoch": epoch, "nbatch": nbatch,
                        "eval_metric": eval_metric})())
            if epoch_end_callback is not None:
                epoch_end_callback(epoch, self.symbol, None, None)
            logging.info("SVRG epoch %d: %s", epoch,
                         dict([eval_metric.get()]
                              if not isinstance(eval_metric.get()[0], list)
                              else zip(*eval_metric.get())))
        return self
