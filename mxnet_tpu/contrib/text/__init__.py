"""``mx.contrib.text`` — vocabulary + token embeddings (reference:
``python/mxnet/contrib/text/{vocab,embedding,utils}.py``).

Offline-first: pretrained-embedding downloads are unavailable in this
environment, so ``CustomEmbedding`` loads any GloVe/fastText-format text
file and ``get_pretrained_file_names`` documents the gap instead of
silently failing.
"""
from . import utils
from .vocab import Vocabulary
from .embedding import (TokenEmbedding, CustomEmbedding, CompositeEmbedding,
                        register, create, get_pretrained_file_names)

__all__ = ["Vocabulary", "TokenEmbedding", "CustomEmbedding",
           "CompositeEmbedding", "register", "create",
           "get_pretrained_file_names", "utils"]
