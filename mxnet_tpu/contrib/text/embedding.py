"""Token embeddings (reference: ``python/mxnet/contrib/text/embedding.py``
:: ``_TokenEmbedding``/``GloVe``/``FastText``/``CustomEmbedding``/
``CompositeEmbedding`` + the ``register``/``create`` registry).

Pretrained weight DOWNLOADS are impossible in this offline environment;
``CustomEmbedding`` loads the same on-disk text format (one token per
line followed by its vector), which is what GloVe/fastText files contain
once fetched — point it at a local copy and the API matches upstream."""
from __future__ import annotations

import io
import logging
from typing import Dict, List, Optional

import numpy as _np

from ...base import MXNetError

__all__ = ["TokenEmbedding", "CustomEmbedding", "CompositeEmbedding",
           "register", "create", "get_pretrained_file_names"]

_REGISTRY: Dict[str, type] = {}


def register(cls):
    """Register an embedding class (reference: embedding.py::register)."""
    _REGISTRY[cls.__name__.lower()] = cls
    return cls


def create(embedding_name, **kwargs):
    key = str(embedding_name).lower()
    if key not in _REGISTRY:
        raise MXNetError(
            f"unknown embedding {embedding_name!r}; registered: "
            f"{sorted(_REGISTRY)}. Pretrained GloVe/fastText downloads "
            "are unavailable offline — load a local vector file with "
            "CustomEmbedding(pretrained_file_path=...)")
    return _REGISTRY[key](**kwargs)


def get_pretrained_file_names(embedding_name=None):
    """Upstream lists downloadable archives; offline there are none."""
    return {} if embedding_name is None else []


class TokenEmbedding:
    """Base: idx<->token plus an (N, dim) vector table; index 0 is the
    unknown token whose vector comes from ``init_unknown_vec``."""

    def __init__(self, unknown_token="<unk>"):
        self._unknown_token = unknown_token
        self._idx_to_token: List[str] = [unknown_token]
        self._token_to_idx: Dict[str, int] = {unknown_token: 0}
        self._idx_to_vec = None     # numpy (N, dim)

    # -- loading --------------------------------------------------------
    def _load_embedding_txt(self, path, elem_delim=" ",
                            encoding="utf8", init_unknown_vec=_np.zeros):
        vecs = []
        dim = None
        with io.open(path, "r", encoding=encoding) as f:
            for lineno, line in enumerate(f):
                parts = line.rstrip().split(elem_delim)
                if lineno == 0 and len(parts) == 2 and \
                        parts[0].isdigit() and parts[1].isdigit():
                    continue            # fastText header "N dim"
                token, elems = parts[0], parts[1:]
                if dim is None:
                    dim = len(elems)
                    if dim < 2:
                        raise MXNetError(
                            f"{path}:{lineno}: vector dim {dim} < 2 — "
                            "wrong elem_delim?")
                if len(elems) != dim:
                    logging.warning("%s:%d: dim %d != %d, skipped",
                                    path, lineno, len(elems), dim)
                    continue
                if token in self._token_to_idx:
                    logging.warning("%s:%d: duplicate token %r, skipped",
                                    path, lineno, token)
                    continue
                self._token_to_idx[token] = len(self._idx_to_token)
                self._idx_to_token.append(token)
                vecs.append(_np.asarray(elems, _np.float32))
        if dim is None:
            raise MXNetError(f"{path}: no vectors found")
        table = _np.vstack([init_unknown_vec((1, dim)).reshape(1, dim)]
                           + [v[None] for v in vecs]).astype(_np.float32)
        self._idx_to_vec = table

    # -- surface --------------------------------------------------------
    def __len__(self):
        return len(self._idx_to_token)

    def __contains__(self, token):
        return token in self._token_to_idx

    @property
    def vec_len(self):
        return 0 if self._idx_to_vec is None else self._idx_to_vec.shape[1]

    @property
    def unknown_token(self):
        return self._unknown_token

    @property
    def token_to_idx(self):
        return self._token_to_idx

    @property
    def idx_to_token(self):
        return self._idx_to_token

    @property
    def idx_to_vec(self):
        from ...ndarray import array as nd_array

        return nd_array(self._idx_to_vec)

    def get_vecs_by_tokens(self, tokens, lower_case_backup=False):
        from ...ndarray import array as nd_array

        single = isinstance(tokens, str)
        toks = [tokens] if single else list(tokens)
        idx = []
        for t in toks:
            i = self._token_to_idx.get(t)
            if i is None and lower_case_backup:
                i = self._token_to_idx.get(t.lower())
            idx.append(0 if i is None else i)
        out = self._idx_to_vec[idx]
        return nd_array(out[0] if single else out)

    def update_token_vectors(self, tokens, new_vectors):
        vecs = _np.asarray(
            new_vectors.asnumpy() if hasattr(new_vectors, "asnumpy")
            else new_vectors, _np.float32)
        toks = [tokens] if isinstance(tokens, str) else list(tokens)
        vecs = vecs.reshape(len(toks), -1)
        for t, v in zip(toks, vecs):
            if t not in self._token_to_idx:
                raise MXNetError(f"token {t!r} is not in the embedding")
            self._idx_to_vec[self._token_to_idx[t]] = v


@register
class CustomEmbedding(TokenEmbedding):
    """Load any GloVe/fastText-format text file of vectors (reference:
    embedding.py::CustomEmbedding)."""

    def __init__(self, pretrained_file_path, elem_delim=" ",
                 encoding="utf8", init_unknown_vec=_np.zeros,
                 vocabulary=None, unknown_token="<unk>"):
        super().__init__(unknown_token=unknown_token)
        self._load_embedding_txt(pretrained_file_path, elem_delim,
                                 encoding, init_unknown_vec)
        if vocabulary is not None:
            self._restrict_to_vocab(vocabulary)

    def _restrict_to_vocab(self, vocab):
        table = _np.zeros((len(vocab), self.vec_len), _np.float32)
        for i, tok in enumerate(vocab.idx_to_token):
            j = self._token_to_idx.get(tok)
            if j is not None:
                table[i] = self._idx_to_vec[j]
        self._idx_to_token = list(vocab.idx_to_token)
        self._token_to_idx = dict(vocab.token_to_idx)
        self._idx_to_vec = table


class CompositeEmbedding(TokenEmbedding):
    """Concatenate several embeddings over one vocabulary (reference:
    embedding.py::CompositeEmbedding)."""

    def __init__(self, vocabulary, token_embeddings):
        if not isinstance(token_embeddings, (list, tuple)):
            token_embeddings = [token_embeddings]
        super().__init__(unknown_token=vocabulary.unknown_token)
        self._idx_to_token = list(vocabulary.idx_to_token)
        self._token_to_idx = dict(vocabulary.token_to_idx)
        parts = []
        for emb in token_embeddings:
            sub = _np.zeros((len(vocabulary), emb.vec_len), _np.float32)
            for i, tok in enumerate(vocabulary.idx_to_token):
                j = emb.token_to_idx.get(tok)
                if j is not None:
                    sub[i] = emb._idx_to_vec[j]
            parts.append(sub)
        self._idx_to_vec = _np.concatenate(parts, axis=1)
