"""Vocabulary (reference: ``python/mxnet/contrib/text/vocab.py`` ::
``Vocabulary``) — token/index mapping built from a frequency counter."""
from __future__ import annotations

from collections import Counter
from typing import Dict, List, Optional

__all__ = ["Vocabulary"]


class Vocabulary:
    """Indexes tokens by frequency (ties broken alphabetically), with an
    unknown token at index 0 and optional reserved tokens after it —
    the reference's ordering contract."""

    def __init__(self, counter: Optional[Counter] = None, most_freq_count=None,
                 min_freq=1, unknown_token="<unk>", reserved_tokens=None):
        if min_freq < 1:
            raise ValueError("min_freq must be >= 1")
        reserved_tokens = list(reserved_tokens or [])
        if unknown_token in reserved_tokens:
            raise ValueError("unknown_token must not be in reserved_tokens")
        if len(set(reserved_tokens)) != len(reserved_tokens):
            raise ValueError("reserved_tokens must be unique")
        self._unknown_token = unknown_token
        self._reserved_tokens = reserved_tokens or None
        self._idx_to_token: List[str] = [unknown_token] + reserved_tokens
        self._token_to_idx: Dict[str, int] = {
            t: i for i, t in enumerate(self._idx_to_token)}
        if counter is not None:
            special = set(self._idx_to_token)
            pairs = sorted(counter.items(), key=lambda kv: (-kv[1], kv[0]))
            kept = 0
            for token, freq in pairs:
                if freq < min_freq:
                    break
                if most_freq_count is not None and kept >= most_freq_count:
                    break
                if token in special:
                    continue
                self._token_to_idx[token] = len(self._idx_to_token)
                self._idx_to_token.append(token)
                kept += 1

    def __len__(self):
        return len(self._idx_to_token)

    def __contains__(self, token):
        return token in self._token_to_idx

    @property
    def token_to_idx(self):
        return self._token_to_idx

    @property
    def idx_to_token(self):
        return self._idx_to_token

    @property
    def unknown_token(self):
        return self._unknown_token

    @property
    def reserved_tokens(self):
        return self._reserved_tokens

    def to_indices(self, tokens):
        """Token(s) -> index/indices; unknown tokens map to index 0."""
        single = isinstance(tokens, str)
        toks = [tokens] if single else list(tokens)
        idx = [self._token_to_idx.get(t, 0) for t in toks]
        return idx[0] if single else idx

    def to_tokens(self, indices):
        single = isinstance(indices, int)
        idxs = [indices] if single else list(indices)
        for i in idxs:
            if not 0 <= i < len(self):
                raise ValueError(f"token index {i} out of range [0, "
                                 f"{len(self)})")
        toks = [self._idx_to_token[i] for i in idxs]
        return toks[0] if single else toks
