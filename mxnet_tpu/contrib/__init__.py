"""``mx.contrib`` namespace (reference: ``python/mxnet/contrib/``).

The pieces with TPU-native equivalents live at top level and are
re-exported here under their reference import paths:
``mx.contrib.amp`` -> mxnet_tpu.amp. Gluon-side contribs (SyncBatchNorm,
Estimator) are under ``mxnet_tpu.gluon.contrib``.
"""
from .. import amp  # noqa: F401  (reference path: mxnet.contrib.amp)
