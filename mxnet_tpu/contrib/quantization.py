"""Post-training int8 quantization (reference:
``python/mxnet/contrib/quantization.py`` :: ``quantize_model``,
``quantize_net``, ``_LayerOutputMinMaxCollector``,
``_LayerHistogramCollector`` / KL-entropy calibration).

TPU-native design: weights are stored int8 with per-output-channel
symmetric scales; activations are fake-quantized onto the int8 grid with
calibrated (naive min/max or KL-entropy) or dynamic ranges, so the f32
MXU matmul reproduces the integer arithmetic exactly while parameter
memory drops 4x. Both surfaces are provided: ``quantize_net`` rewrites a
Gluon net's Dense/Conv children in place; ``quantize_model`` rewrites a
Symbol graph + params (the Module-era API).
"""
from __future__ import annotations

import logging
from typing import Dict, List, Optional

import numpy as _np

from ..base import MXNetError

__all__ = ["quantize_model", "quantize_net", "quantize_weight",
           "LayerOutputMinMaxCollector", "LayerHistogramCollector",
           "optimal_threshold_kl"]

_QUANTIZABLE = {"FullyConnected": "_contrib_quantized_dense",
                "Convolution": "_contrib_quantized_conv"}


# ---------------------------------------------------------------- weights
def quantize_weight(w: _np.ndarray):
    """Symmetric per-output-channel int8: returns (wq int8, scale f32[out])
    with ``w ≈ wq * scale[:, None, ...]``."""
    w = _np.asarray(w, _np.float32)
    flat = _np.abs(w.reshape(w.shape[0], -1))
    t = _np.maximum(flat.max(axis=1), 1e-12)
    scale = (t / 127.0).astype(_np.float32)
    wq = _np.clip(_np.round(w / scale.reshape((-1,) + (1,) * (w.ndim - 1))),
                  -127, 127).astype(_np.int8)
    return wq, scale


# ---------------------------------------------------------------- calib
class LayerOutputMinMaxCollector:
    """Naive calibration: running min/max per collected name."""

    def __init__(self):
        self.min_max: Dict[str, tuple] = {}

    def collect(self, name, arr):
        arr = _np.asarray(arr)
        lo, hi = float(arr.min()), float(arr.max())
        if name in self.min_max:
            plo, phi = self.min_max[name]
            lo, hi = min(lo, plo), max(hi, phi)
        self.min_max[name] = (lo, hi)

    def ranges(self):
        return dict(self.min_max)


class LayerHistogramCollector:
    """Entropy calibration: symmetric histograms, thresholds by KL."""

    def __init__(self, num_bins=2048):
        self.num_bins = num_bins
        self.hist: Dict[str, _np.ndarray] = {}
        self.edges: Dict[str, _np.ndarray] = {}

    def collect(self, name, arr):
        arr = _np.abs(_np.asarray(arr, _np.float32)).ravel()
        t = float(arr.max()) if arr.size else 0.0
        if name not in self.hist:
            t = max(t, 1e-12)
            self.edges[name] = _np.linspace(0.0, t, self.num_bins + 1)
            self.hist[name] = _np.histogram(arr, bins=self.edges[name])[0] \
                .astype(_np.float64)
        else:
            edges = self.edges[name]
            if t > edges[-1]:
                # grow the range: re-bin the old histogram into new edges
                new_edges = _np.linspace(0.0, t, self.num_bins + 1)
                centers = (edges[:-1] + edges[1:]) / 2
                idx = _np.clip(_np.searchsorted(new_edges, centers) - 1,
                               0, self.num_bins - 1)
                re_binned = _np.zeros(self.num_bins)
                _np.add.at(re_binned, idx, self.hist[name])
                self.hist[name] = re_binned
                self.edges[name] = new_edges
            self.hist[name] += _np.histogram(
                arr, bins=self.edges[name])[0].astype(_np.float64)

    def ranges(self):
        out = {}
        for name, hist in self.hist.items():
            t = optimal_threshold_kl(hist, self.edges[name])
            out[name] = (-t, t)
        return out


def _kl_divergence(p, q):
    p = p / max(p.sum(), 1e-12)
    q = q / max(q.sum(), 1e-12)
    mask = p > 0
    return float(_np.sum(p[mask] * _np.log(
        p[mask] / _np.maximum(q[mask], 1e-12))))


def optimal_threshold_kl(hist, edges, num_quantized_bins=255):
    """The TensorRT-style KL sweep (reference:
    quantization.py::_get_optimal_threshold): pick the clip threshold
    whose 255-bin quantized distribution diverges least from the
    reference distribution."""
    hist = _np.asarray(hist, _np.float64).copy()
    # TensorRT's rule: bin 0 (zeros — e.g. half a relu's mass) is not part
    # of the distribution being matched; keeping it biases the sweep
    # toward clipping the real positive tail
    hist[0] = 0
    n = len(hist)
    if hist.sum() == 0:
        return float(edges[-1])
    best_t, best_kl = float(edges[-1]), _np.inf
    start = max(num_quantized_bins // 2, num_quantized_bins)
    for i in range(start, n + 1, max(1, n // 128)):
        ref = hist[:i].copy()
        ref[i - 1] += hist[i:].sum()        # clip outliers into last bin
        # quantize first i bins down to num_quantized_bins, then expand
        factor = i / num_quantized_bins
        q = _np.zeros(i)
        for j in range(num_quantized_bins):
            lo, hi = int(_np.floor(j * factor)), int(_np.ceil((j + 1) * factor))
            hi = min(hi, i)
            chunk = hist[lo:hi]
            nz = (chunk > 0).sum()
            if nz:
                q[lo:hi] = _np.where(chunk > 0, chunk.sum() / nz, 0.0)
        kl = _kl_divergence(ref, q)
        if kl < best_kl:
            best_kl, best_t = kl, float(edges[i])
    return best_t


def _make_collector(calib_mode):
    if calib_mode == "naive":
        return LayerOutputMinMaxCollector()
    if calib_mode == "entropy":
        return LayerHistogramCollector()
    raise MXNetError(f"unknown calib_mode {calib_mode!r} "
                     "(expected 'naive', 'entropy' or 'none')")


def _iter_batches(calib_data, num_calib_batches):
    from ..ndarray import NDArray

    if isinstance(calib_data, NDArray):
        yield calib_data
        return
    count = 0
    for batch in calib_data:
        # io.DataBatch carries a LIST of arrays; NDArray.data is its jax
        # payload — the duck test must not confuse the two
        if hasattr(batch, "data") and isinstance(batch.data, (list, tuple)) \
                and not isinstance(batch, NDArray):
            batch = batch.data[0]
        if isinstance(batch, (list, tuple)):
            batch = batch[0]
        yield batch
        count += 1
        if num_calib_batches is not None and count >= num_calib_batches:
            return


# ---------------------------------------------------------------- gluon
class _QuantizedLayer:
    """Mixin: holds int8 weight + scale (+bias) as frozen Parameters."""

    def _setup_qparams(self, w, bias):
        wq, scale = quantize_weight(w.asnumpy())
        from ..ndarray import array as nd_array

        with self.name_scope():
            self.weight_q = self.params.get(
                "weight_quant", shape=wq.shape, dtype="int8",
                grad_req="null", init="zeros", differentiable=False)
            self.w_scale = self.params.get(
                "weight_scale", shape=scale.shape, grad_req="null",
                init="ones", differentiable=False)
            self.bias = None
            if bias is not None:
                self.bias = self.params.get(
                    "bias", shape=bias.shape, grad_req="null",
                    init="zeros", differentiable=False)
        self.weight_q.initialize()
        self.weight_q.set_data(nd_array(wq, dtype="int8"))
        self.w_scale.initialize()
        self.w_scale.set_data(nd_array(scale))
        if bias is not None:
            self.bias.initialize()
            self.bias.set_data(bias.data())


def _quantized_dense_cls():
    from ..gluon.block import HybridBlock

    class QuantizedDense(HybridBlock, _QuantizedLayer):
        def __init__(self, src, calib_range, prefix=None, params=None):
            super().__init__(prefix=prefix, params=params)
            self._units = src._units
            self._flatten = src._flatten
            self._range = calib_range      # (min, max) or None = dynamic
            self.act = src.act
            self._setup_qparams(src.weight.data(), src.bias)

        def hybrid_forward(self, F, x, weight_q, w_scale, bias=None):
            lo, hi = self._range or (None, None)
            out = F._contrib_quantized_dense(
                x, weight_q, w_scale, bias, num_hidden=self._units,
                no_bias=bias is None, flatten=self._flatten,
                min_calib_range=lo, max_calib_range=hi)
            return self.act(out) if self.act is not None else out

    return QuantizedDense


def _quantized_conv_cls():
    from ..gluon.block import HybridBlock

    class QuantizedConv(HybridBlock, _QuantizedLayer):
        def __init__(self, src, calib_range, prefix=None, params=None):
            super().__init__(prefix=prefix, params=params)
            self._kwargs = dict(src._kwargs)
            self._range = calib_range
            self.act = src.act
            # int8-trunk chaining knobs (set by _fuse_int8_trunks):
            # _out_grid=(lo,hi) -> emit (int8 codes, min, max) on that
            # grid; _in_codes=(lo,hi) -> input is codes on that grid
            self._out_grid = None
            self._in_codes = None
            self._setup_qparams(src.weight.data(), src.bias)

        def hybrid_forward(self, F, x, weight_q, w_scale, bias=None):
            lo, hi = self._in_codes or self._range or (None, None)
            kw = dict(self._kwargs)
            if self._out_grid is not None:
                kw.update(out_type="int8",
                          out_min_calib=self._out_grid[0],
                          out_max_calib=self._out_grid[1])
            out = F._contrib_quantized_conv(
                x, weight_q, w_scale, bias, no_bias=bias is None,
                min_calib_range=lo, max_calib_range=hi, **kw)
            if self._out_grid is not None:
                return out          # (codes, min, max); act runs on codes
            return self.act(out) if self.act is not None else out

    return QuantizedConv


def _find_targets(block, exclude, path=""):
    """Yield (parent, child_key, attr_name, block) for quantizable layers."""
    from ..gluon import nn

    for key, child in list(block._children.items()):
        name = child.name
        quantizable = isinstance(child, nn.Dense) or (
            isinstance(child, nn.Conv2D))
        if quantizable and name not in exclude:
            attr = next((a for a, v in vars(block).items() if v is child),
                        None)
            yield block, key, attr, child
        else:
            yield from _find_targets(child, exclude, path + key + ".")


def quantize_net(network, calib_data=None, calib_mode="naive",
                 exclude_layers=None, num_calib_batches=None,
                 quantized_dtype="int8", logger=None, int8_trunk=False):
    """Quantize a Gluon net's Dense/Conv2D layers in place (reference:
    quantization.py::quantize_net). ``calib_mode='none'`` → dynamic
    per-batch activation ranges (no calib_data needed). Returns the net.

    ``int8_trunk=True`` (requires calibration) additionally fuses
    HybridSequential runs of conv/relu/max-pool/flatten into Int8Run
    blocks that pass int8 CODES between layers — no f32 activation
    tensors inside the run (see _fuse_int8_trunks).
    """
    from .. import autograd

    if quantized_dtype != "int8":
        raise MXNetError("only int8 quantization is supported")
    if int8_trunk and calib_mode == "none":
        raise MXNetError(
            "int8_trunk=True requires calibration (the inter-layer "
            "code grids are the calibrated ranges)")
    exclude = set(exclude_layers or ())
    targets = list(_find_targets(network, exclude))
    if not targets:
        raise MXNetError("quantize_net: no quantizable (Dense/Conv2D) "
                         "layers found")

    ranges: Dict[str, tuple] = {}
    if calib_mode != "none":
        if calib_data is None:
            raise MXNetError(
                f"calib_mode={calib_mode!r} needs calib_data "
                "(use calib_mode='none' for dynamic quantization)")
        collector = _make_collector(calib_mode)
        handles = []
        for _parent, _key, _attr, child in targets:
            def hook(blk, inputs, _name=child.name):
                collector.collect(_name, inputs[0].asnumpy())
            handles.append(child.register_forward_pre_hook(hook))

            def out_hook(blk, inputs, outputs, _name=child.name):
                out = outputs[0] if isinstance(outputs, (list, tuple)) \
                    else outputs
                # the int8-trunk requantize grid (post-act output range)
                collector.collect(_name + "__out", out.asnumpy())
            handles.append(child.register_forward_hook(out_hook))
        # calibration must run EAGERLY: a hybridized net dispatches
        # through the compiled CachedOp, bypassing children's __call__
        # (hooks never fire) — temporarily drop to the eager path
        saved_active = [(b, b._active) for b in _walk(network)
                        if hasattr(b, "_active")]
        for b, _ in saved_active:
            b._active = False
        try:
            with autograd.pause():
                for batch in _iter_batches(calib_data, num_calib_batches):
                    network(batch)
        finally:
            for b, was in saved_active:
                b._active = was
        for h in handles:
            h.detach()
        ranges = collector.ranges()
        if not ranges:
            raise MXNetError(
                "quantize_net: calibration collected no activations — "
                "calib_data produced no batches?")

    dense_cls, conv_cls = _quantized_dense_cls(), _quantized_conv_cls()
    from ..gluon import nn

    for parent, key, attr, child in targets:
        calib = ranges.get(child.name)
        cls = dense_cls if isinstance(child, nn.Dense) else conv_cls
        q = cls(child, calib, prefix=child.prefix + "quant_")
        q._src_name = child.name
        parent._children[key] = q
        if attr is not None:
            object.__setattr__(parent, attr, q)
        if logger:
            logger.info("quantized %s (calib=%s)", child.name, calib)
    if int8_trunk:
        _fuse_int8_trunks(network, ranges, logger=logger)
    # any compiled CachedOp graphs are stale now
    for blk in _walk(network):
        if getattr(blk, "_cached_graph", None) is not None:
            blk._cached_graph = None
    # a hybridized net must STAY hybridized: the swapped-in Quantized*/
    # Int8Run children are fresh blocks constructed inactive, so without
    # re-propagation a child served standalone (or a later warmup() over
    # the serving bucket grid) would silently run eager
    if getattr(network, "_active", False):
        network.hybridize(True, **getattr(network, "_flags", {}))
    return network




def _int8_run_cls():
    from ..gluon.block import HybridBlock

    class Int8Run(HybridBlock):
        """A fused run of quantized blocks that passes INT8 CODES between
        layers (VERDICT r4 #5 "int8 end-to-end"): the leading
        QuantizedConv requantizes onto its successor's calibrated input
        grid (``out_type='int8'``), relu/max-pool/flatten operate on the
        codes exactly (monotonic), inner convs consume codes directly,
        and the tail dequantizes once. No f32 activation tensor exists
        between the member layers.

        ``steps``: list of ("conv", block) / ("relu", None) /
        ("pool", kwargs) / ("flatten", None) / ("dequant", t)."""

        def __init__(self, steps, prefix=None, params=None):
            super().__init__(prefix=prefix, params=params)
            self._steps = []
            with self.name_scope():
                for i, (kind, payload) in enumerate(steps):
                    if kind in ("conv", "conv_f32"):
                        self.register_child(payload, f"conv{i}")
                    self._steps.append((kind, payload))

        def hybrid_forward(self, F, x):
            mn = mx_ = None
            for kind, payload in self._steps:
                if kind == "conv":
                    x, mn, mx_ = payload(x)
                elif kind == "conv_f32":
                    x = payload(x)          # consumes codes, emits f32
                elif kind == "relu":
                    # relu on symmetric-grid codes is exact: max(c, 0)
                    x = F.relu(x)
                elif kind == "pool":
                    x, mn, mx_ = F._contrib_quantized_pooling(
                        x, mn, mx_, **payload)
                elif kind == "flatten":
                    x, mn, mx_ = F._contrib_quantized_flatten(x, mn, mx_)
                elif kind == "dequant":
                    x = x.astype("float32") * (payload / 127.0)
            return x

        def __repr__(self):
            kinds = [k for k, _ in self._steps]
            return f"Int8Run({'->'.join(kinds)})"

    return Int8Run


def _grid_t(rng):
    return max(abs(float(rng[0])), abs(float(rng[1]))) + 1e-12


def _fuse_int8_trunks(network, ranges, logger=None):
    """Rewrite HybridSequential runs of quantized conv / relu / max-pool
    / flatten children into Int8Run blocks (codes between layers).

    Grid assignment: a code-emitting conv requantizes onto the grid of
    its own CALIBRATED OUTPUT range (``ranges[name + "__out"]`` — the
    post-activation output the collector recorded); the consuming conv
    dequantizes with the same constant, so producer and consumer agree
    by construction. relu/max-pool/flatten are exact on codes
    (monotonic, symmetric grid). A conv with no recorded output range
    ends the run: it consumes codes but emits f32 ("conv_f32"); runs
    whose last step leaves codes get one tail dequantize."""
    from ..gluon import nn

    Int8Run = _int8_run_cls()

    def chain_kind(child):
        if type(child).__name__ == "QuantizedConv":
            act = getattr(child, "act", None)
            if act is None or getattr(act, "_act_type", None) == "relu":
                return "conv"
            return None
        if isinstance(child, nn.Activation) \
                and child._act_type == "relu":
            return "relu"
        if isinstance(child, nn.MaxPool2D):
            return "pool"
        if isinstance(child, nn.Flatten):
            return "flatten"
        return None

    def out_grid_t(conv):
        rng = ranges.get(getattr(conv, "_src_name", "") + "__out")
        return None if rng is None else _grid_t(rng)

    for block in list(_walk(network)):
        if not isinstance(block, nn.HybridSequential):
            continue
        kids = [block._children[k] for k in list(block._children.keys())]
        kinds = [chain_kind(c) for c in kids]
        new_children = []
        i = 0
        while i < len(kids):
            startable = (kinds[i] == "conv"
                         and getattr(kids[i], "_range", None) is not None
                         and out_grid_t(kids[i]) is not None)
            if not startable:
                new_children.append(kids[i])
                i += 1
                continue
            # maximal chainable run [i, j)
            j = i + 1
            while j < len(kids) and kinds[j] is not None:
                if kinds[j] == "conv" \
                        and getattr(kids[j], "_range", None) is None:
                    break
                j += 1
            n_convs = sum(1 for k in range(i, j) if kinds[k] == "conv")
            if n_convs < 2 and not any(kinds[k] in ("pool", "flatten")
                                       for k in range(i + 1, j)):
                new_children.append(kids[i])
                i += 1
                continue
            steps = []
            cur_t = None
            for k in range(i, j):
                c, kind = kids[k], kinds[k]
                if kind == "conv":
                    if k > i:
                        c._in_codes = (-cur_t, cur_t)
                    t = out_grid_t(c)
                    is_last_step = (k == j - 1)
                    if t is None or (is_last_step and cur_t is None):
                        # no grid, or a lone tail conv: emit f32, end run
                        steps.append(("conv_f32", c))
                        j = k + 1
                        break
                    if is_last_step:
                        # tail conv: codes would only need a dequant —
                        # emit f32 directly instead
                        steps.append(("conv_f32", c))
                        break
                    c._out_grid = (-t, t)
                    cur_t = t
                    steps.append(("conv", c))
                    if c.act is not None:
                        steps.append(("relu", None))
                elif kind == "relu":
                    steps.append(("relu", None))
                elif kind == "pool":
                    steps.append(("pool", dict(c._kwargs)))
                elif kind == "flatten":
                    steps.append(("flatten", None))
            if steps and steps[-1][0] != "conv_f32":
                steps.append(("dequant", cur_t))
            run = Int8Run(steps, prefix=block.prefix + f"int8run{i}_")
            new_children.append(run)
            if logger:
                logger.info("int8 trunk: fused %s", run)
            i = j
        block._children.clear()
        for idx, c in enumerate(new_children):
            block._children[str(idx)] = c
    return network


def _walk(block):
    yield block
    for child in block._children.values():
        yield from _walk(child)


# ---------------------------------------------------------------- symbol
def quantize_model(sym, arg_params, aux_params, data_names=("data",),
                   excluded_sym_names=None, calib_mode="naive",
                   calib_data=None, num_calib_batches=None,
                   quantized_dtype="int8", logger=None):
    """Quantize a Symbol graph + params (reference:
    quantization.py::quantize_model). Returns (qsym, qarg_params,
    aux_params); FullyConnected/Convolution nodes whose weights live in
    ``arg_params`` become ``_contrib_quantized_*`` nodes with int8
    weights + per-channel scales."""
    from ..symbol import symbol as sym_mod
    from ..ndarray import array as nd_array

    if quantized_dtype != "int8":
        raise MXNetError("only int8 quantization is supported")
    excluded = set(excluded_sym_names or ())
    qsym = sym_mod.load_json(sym.tojson())

    targets = []
    for node in qsym._topo():
        if node.op in _QUANTIZABLE and node.name not in excluded:
            wnode = node.inputs[1][0]
            if wnode.op is None and wnode.name in arg_params:
                targets.append(node)
    if not targets:
        raise MXNetError("quantize_model: no quantizable nodes found")

    # calibration: evaluate every target's data input over calib batches
    ranges: Dict[int, tuple] = {}
    if calib_mode != "none":
        if calib_data is None:
            raise MXNetError(
                f"calib_mode={calib_mode!r} needs calib_data "
                "(use calib_mode='none' for dynamic quantization)")
        from ..symbol.executor import eval_symbol

        probe = sym_mod.Symbol([node.inputs[0] for node in targets])
        collector = _make_collector(calib_mode)
        base_feed = {k: v for k, v in arg_params.items()}
        base_feed.update(aux_params or {})
        for batch in _iter_batches(calib_data, num_calib_batches):
            feed = dict(base_feed)
            feed[data_names[0]] = batch
            outs = eval_symbol(probe, feed)
            outs = outs if isinstance(outs, (list, tuple)) else [outs]
            for node, out in zip(targets, outs):
                collector.collect(node.name, out.asnumpy())
        named = collector.ranges()
        ranges = {id(node): named[node.name] for node in targets}

    qarg = {k: v for k, v in arg_params.items()}
    # a weight var may feed several nodes (tied weights) or non-quantized
    # consumers: drop the f32 original only once every consumer is a
    # rewritten target, and quantize each weight once
    target_ids = {id(n) for n in targets}
    uses: Dict[str, int] = {}
    target_uses: Dict[str, int] = {}
    for node in qsym._topo():
        for slot, (parent, _) in enumerate(node.inputs):
            if parent.op is None and parent.name in qarg:
                uses[parent.name] = uses.get(parent.name, 0) + 1
                if id(node) in target_ids and slot == 1:
                    target_uses[parent.name] = \
                        target_uses.get(parent.name, 0) + 1
    for node in targets:
        wname = node.inputs[1][0].name
        if wname + "_quant" not in qarg:
            wq, scale = quantize_weight(qarg[wname].asnumpy())
            qarg[wname + "_quant"] = nd_array(wq, dtype="int8")
            qarg[wname + "_scale"] = nd_array(scale)
        if uses.get(wname, 0) == target_uses.get(wname, 0):
            qarg.pop(wname, None)
        wq_var = sym_mod.var(wname + "_quant")._entries[0]
        ws_var = sym_mod.var(wname + "_scale")._entries[0]
        new_inputs = [node.inputs[0], wq_var, ws_var] + list(node.inputs[2:])
        attrs = dict(node.attrs)
        if node.op == "FullyConnected":
            attrs.pop("num_group", None)
        attrs.pop("no_bias", None)
        lo, hi = ranges.get(id(node), (None, None))
        attrs["min_calib_range"] = lo
        attrs["max_calib_range"] = hi
        node.op = _QUANTIZABLE[node.op]
        node.inputs = new_inputs
        node.attrs = attrs
        if logger:
            logger.info("quantized %s -> %s", node.name, node.op)
    return qsym, qarg, dict(aux_params or {})
