"""Autograd: imperative tape + reverse-mode differentiation.

Reference: ``src/imperative/imperative.cc :: Imperative::RecordOp`` /
``::Backward`` and ``python/mxnet/autograd.py``. MXNet records an nnvm graph
on a tape and composes per-op ``FGradient`` attrs into a backward graph that
is executed imperatively.

TPU-native design: every recorded op is a **pure JAX function**; at record
time we obtain the op's VJP via ``jax.vjp`` (XLA derives the backward — no
per-op hand-written gradients), and ``backward()`` walks the tape in reverse
accumulating cotangents. Because the VJP closes over the *captured* primal
values, later in-place mutation of an input NDArray cannot corrupt the
gradient — stronger than the reference's aliasing rules.

The tape is thread-local, like MXNet's `Imperative::AGInfo` state.
"""
from __future__ import annotations

import contextlib
import threading
from typing import List, Optional, Sequence

import numpy as _np

from .base import MXNetError

__all__ = [
    "record",
    "pause",
    "train_mode",
    "predict_mode",
    "is_recording",
    "is_training",
    "set_recording",
    "set_training",
    "mark_variables",
    "backward",
    "grad",
    "get_symbol",
    "Function",
    "watch_grad_ready",
    "unwatch_grad_ready",
]

_state = threading.local()


def _st():
    if not hasattr(_state, "recording"):
        _state.recording = False
        _state.training = False
    return _state


def is_recording() -> bool:
    return _st().recording


def is_training() -> bool:
    return _st().training


def set_recording(is_record: bool) -> bool:
    st = _st()
    prev = st.recording
    st.recording = bool(is_record)
    return prev


def set_training(train: bool) -> bool:
    st = _st()
    prev = st.training
    st.training = bool(train)
    return prev


class _RecordingStateScope:
    def __init__(self, is_record: Optional[bool], train: Optional[bool]):
        self._enter_record = is_record
        self._enter_train = train
        self._prev_record = None
        self._prev_train = None

    def __enter__(self):
        if self._enter_record is not None:
            self._prev_record = set_recording(self._enter_record)
        if self._enter_train is not None:
            self._prev_train = set_training(self._enter_train)
        return self

    def __exit__(self, *exc):
        if self._enter_record is not None:
            set_recording(self._prev_record)
        if self._enter_train is not None:
            set_training(self._prev_train)


def record(train_mode: bool = True):
    """Scope enabling tape recording (reference: autograd.record)."""
    return _RecordingStateScope(True, train_mode)


def pause(train_mode: bool = False):
    return _RecordingStateScope(False, train_mode)


def train_mode():
    return _RecordingStateScope(None, True)


def predict_mode():
    return _RecordingStateScope(None, False)


# ---------------------------------------------------------------------------
# tape
# ---------------------------------------------------------------------------


class TapeNode:
    """One recorded op (reference: nnvm::Node on the autograd tape)."""

    __slots__ = ("vjp_fn", "inputs", "outputs", "out_avals", "name",
                 "primal_fn", "primal_vals", "in_versions")

    def __init__(self, vjp_fn, inputs, outputs, out_avals, name="",
                 primal_fn=None, primal_vals=None):
        self.vjp_fn = vjp_fn  # cotangents(tuple matching outputs) -> input cotangents
        self.inputs = inputs  # list[NDArray] — all tensor inputs
        self.outputs = outputs  # list[NDArray] — produced arrays
        self.out_avals = out_avals  # list[(shape, dtype)]
        self.name = name
        # create_graph support: the pure primal fn + its positional raw
        # values (aligned with `inputs`), so the sweep can RE-linearize
        # with the primals as live tape inputs (the stored pullback holds
        # them as closure constants, invisible to a second differentiation)
        self.primal_fn = primal_fn
        self.primal_vals = primal_vals
        # input version counters at record time: create_graph re-reads
        # the inputs' LIVE data, so in-place mutation after the forward
        # must be detected (the stored-closure first-order path is immune)
        self.in_versions = [getattr(a, "_version", None) for a in inputs]


def _mark_output(arr, node: TapeNode, index: int) -> None:
    arr._ag_node = node
    arr._ag_index = index


def is_on_tape(arr) -> bool:
    return getattr(arr, "_ag_node", None) is not None or getattr(arr, "_grad_req", "null") != "null"


def record_node(vjp_fn, inputs, outputs, name="", primal_fn=None,
                primal_vals=None) -> None:
    avals = [(o.shape, o.dtype) for o in outputs]
    node = TapeNode(vjp_fn, list(inputs), list(outputs), avals, name,
                    primal_fn=primal_fn, primal_vals=primal_vals)
    for i, o in enumerate(outputs):
        _mark_output(o, node, i)


def mark_variables(variables, gradients, grad_reqs="write") -> None:
    """Attach grad buffers (reference: autograd.mark_variables /
    MXAutogradMarkVariables)."""
    if isinstance(grad_reqs, str):
        grad_reqs = [grad_reqs] * len(variables)
    for v, g, req in zip(variables, gradients, grad_reqs):
        v._grad = g
        v._grad_req = req


# ---------------------------------------------------------------------------
# grad-ready watch (backward-overlapped comms)
# ---------------------------------------------------------------------------

# id(array) -> (weakref(array), weak-callable(callback)). When a watched
# array's attached .grad is FINALIZED during a backward sweep (its last
# contributing tape node has been processed — no later node can add to
# it), the grad buffer is written immediately and the callback fires,
# with the rest of the reverse sweep still to run. This is the seam the
# overlapped-comms Trainer uses to issue a gradient bucket's allreduce
# *inside* the backward (the reference engine's DependencyEngine push
# scheduling, re-created on the tape): via JAX async dispatch the
# collective's device work overlaps the remaining backward.
# The array reference is weak, and a bound-method callback holds only a
# weak reference to its owner (a plain-function callback is kept
# strongly — it IS the registration); dead entries are pruned at the
# start of every watched sweep. An id() can be reused by a new object —
# the identity check on fire protects against aliasing.
_GRAD_READY_WATCH = {}

# Monotone id of the currently-running (or last) watched backward sweep.
# Consumers with per-sweep state (the overlapped-comms Trainer) compare
# it inside their ready callback: a backward that raised mid-sweep (so
# the consumer's end-of-step reset never ran) is detected as a NEW
# sweep id and the stale state self-heals.
_BACKWARD_SEQ = 0


def backward_sweep_seq() -> int:
    """The current watched-backward sweep id (see _BACKWARD_SEQ)."""
    return _BACKWARD_SEQ


def watch_grad_ready(arrays, callback) -> None:
    """Register ``callback(array)`` to fire when ``array``'s attached
    gradient is finalized during ``backward()`` — while the reverse
    sweep is still running. A bound-method callback keeps only a weak
    reference to its owner (a plain function is referenced strongly);
    dead registrations are pruned at the next watched sweep. No effect
    on ``grad(..., create_graph=True)`` sweeps (grads are tape nodes
    there, not buffer writes)."""
    import weakref

    try:
        cb_ref = weakref.WeakMethod(callback)
    except TypeError:
        cb_ref = lambda _cb=callback: _cb
    for a in arrays:
        _GRAD_READY_WATCH[id(a)] = (weakref.ref(a), cb_ref)


def unwatch_grad_ready(arrays) -> None:
    for a in arrays:
        _GRAD_READY_WATCH.pop(id(a), None)


def _finalize_attached(arr, acc) -> bool:
    """Write ``arr``'s accumulated cotangent into its attached grad
    buffer per grad_req; True if a write happened."""
    req = getattr(arr, "_grad_req", "null")
    if req == "null" or getattr(arr, "_grad", None) is None:
        return False
    g = acc.get(id(arr))
    if g is None:
        return False
    gbuf = arr._grad
    if req == "add":
        gbuf._set_data(gbuf.data + g.astype(gbuf.dtype))
    else:
        gbuf._set_data(g.astype(gbuf.dtype))
    return True


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------


def _zeros_cotangent(shape, dtype):
    import jax
    import jax.numpy as jnp

    if jnp.issubdtype(dtype, jnp.floating) or jnp.issubdtype(dtype, jnp.complexfloating):
        return jnp.zeros(shape, dtype)
    return _np.zeros(shape, jax.dtypes.float0)


def backward(heads, head_grads=None, retain_graph: bool = False,
             train_mode: bool = True) -> None:
    """Run reverse accumulation from ``heads`` into attached ``.grad``
    buffers (reference: Imperative::Backward)."""
    from .ndarray.ndarray import NDArray

    if isinstance(heads, NDArray):
        heads = [heads]
        if head_grads is not None and not isinstance(head_grads, (list, tuple)):
            head_grads = [head_grads]
    grads = _run_backward(heads, head_grads)
    # _run_backward already wrote into attached .grad buffers
    del grads


def grad(heads, variables, head_grads=None, retain_graph=None,
         create_graph: bool = False, train_mode: bool = True):
    """Return gradients of heads w.r.t. variables (reference: autograd.grad)."""
    from .ndarray.ndarray import NDArray, _wrap_jax

    single = isinstance(variables, NDArray)
    if isinstance(heads, NDArray):
        heads = [heads]
        if head_grads is not None and not isinstance(head_grads, (list, tuple)):
            head_grads = [head_grads]
    if single:
        variables = [variables]
    acc = _run_backward(heads, head_grads, collect=variables,
                        write_attached=False, create_graph=create_graph)
    out = []
    for v in variables:
        g = acc.get(id(v))
        if g is None:
            raise MXNetError(
                "cannot differentiate: one of the requested variables is not "
                "part of the recorded graph")
        out.append(g if create_graph and isinstance(g, NDArray)
                   else _wrap_jax(g, v.context))
    return out[0] if single else out


def _sweep_node_recorded(node, acc, add_grad):
    """One reverse-sweep step with the vjp routed through the imperative
    invoke path (create_graph=True).

    The node's stored pullback closes over its primal inputs as CONSTANTS
    — a second differentiation would see zero sensitivity to them. So the
    grad op re-linearizes the node's stored pure primal function with the
    float primal inputs as live tape inputs alongside the cotangents:
    jax.vjp inside the recorded op gives second-order terms through both.
    Nodes recorded without a primal (custom autograd.Function backwards)
    fall back to the closure pullback: gradients flow through their
    cotangent chain only, matching the reference's contract that a custom
    Function is only twice-differentiable if written so.
    """
    import jax.numpy as jnp

    from .ndarray.ndarray import NDArray, _LambdaOp, imperative_invoke

    tensor_cts = []
    slots = []
    const_ct = []
    any_grad = False
    for j, (o, (shape, dtype)) in enumerate(zip(node.outputs,
                                                node.out_avals)):
        g = acc.get(id(o))
        if g is None:
            const_ct.append(_zeros_cotangent(shape, dtype))
        else:
            any_grad = True
            if isinstance(g, NDArray) and g.dtype != dtype:
                g = g.astype(dtype)
            slots.append(j)
            tensor_cts.append(g)
            const_ct.append(None)
    if not any_grad:
        return
    float_in = [i for i, inp in enumerate(node.inputs)
                if getattr(inp, "dtype", None) is not None
                and jnp.issubdtype(jnp.dtype(inp.dtype), jnp.floating)]
    if not float_in:
        return
    n_ct = len(tensor_cts)

    if node.primal_fn is None:
        # documented fallback (custom autograd.Function backward): no pure
        # primal stored, so re-linearization through the primal inputs is
        # impossible — route the stored closure pullback through the
        # imperative invoke path instead. Gradients flow through the
        # cotangent chain only, matching the reference's contract that a
        # custom Function is twice-differentiable only if its backward is
        # written with differentiable ops. That contract is easy to
        # violate silently (saved primals enter the backward as closure
        # CONSTANTS — zero second-order sensitivity through them), so be
        # loud about taking this path.
        import warnings

        warnings.warn(
            f"create_graph=True through custom Function {node.name!r}: "
            "no pure primal is recorded, so second-order terms flow "
            "through the custom backward's OPS only — sensitivity "
            "through values the forward saved (saved primals) is "
            "silently ZERO unless the backward recomputes from its "
            "cotangent inputs. Write the backward with differentiable "
            "ops over its inputs, or use built-in ops for "
            "twice-differentiated paths (see README, 'higher-order "
            "autograd').",
            RuntimeWarning, stacklevel=2)
        vjp_fn = node.vjp_fn
        in_avals = [(node.inputs[i].shape, node.inputs[i].dtype)
                    for i in float_in]

        def closure_fn(*cts):
            full_ct = list(const_ct)
            for s, c in zip(slots, cts):
                full_ct[s] = c
            ct = tuple(full_ct) if len(full_ct) > 1 else full_ct[0]
            gs = vjp_fn(ct)
            out = []
            for i, (shape, dtype) in zip(float_in, in_avals):
                g = gs[i]
                if g is None or (getattr(g, "dtype", None) is not None
                                 and str(g.dtype) == "float0"):
                    g = jnp.zeros(shape, dtype)
                out.append(g)
            return tuple(out) if len(out) > 1 else out[0]

        outs = imperative_invoke(_LambdaOp(closure_fn, f"grad[{node.name}]"),
                                 tensor_cts, {}, force_record=True)
        outs = list(outs) if isinstance(outs, (list, tuple)) else [outs]
        for i, g in zip(float_in, outs):
            add_grad(node.inputs[i], g)
        return
    # the grad op re-reads the inputs' LIVE data; an input mutated in
    # place since the forward would silently change even the first-order
    # result — refuse loudly (the stored-closure path is immune)
    for i in float_in:
        if getattr(node.inputs[i], "_version", None) != node.in_versions[i]:
            raise MXNetError(
                "create_graph=True: an input of recorded op "
                f"{node.name!r} was mutated in place after the forward "
                "pass; gradients would be computed at the mutated value")
    primal_fn, primal_vals = node.primal_fn, node.primal_vals

    def fn(*args):
        import jax

        cts, prims = args[:n_ct], args[n_ct:]
        full_ct = list(const_ct)
        for s, c in zip(slots, cts):
            full_ct[s] = c
        ct = tuple(full_ct) if len(full_ct) > 1 else full_ct[0]

        def primal_of(*sel):
            vals = list(primal_vals)
            for i, v in zip(float_in, sel):
                vals[i] = v
            return primal_fn(*vals)

        _, pull = jax.vjp(primal_of, *prims)
        gs = pull(ct)
        return gs if len(gs) > 1 else gs[0]

    op_inputs = tensor_cts + [node.inputs[i] for i in float_in]

    # force_record: the seed cotangent (a fresh ones-constant) is not on
    # the tape, but the produced gradients must be
    outs = imperative_invoke(_LambdaOp(fn, f"grad[{node.name}]"),
                             op_inputs, {}, force_record=True)
    outs = list(outs) if isinstance(outs, (list, tuple)) else [outs]
    for i, g in zip(float_in, outs):
        add_grad(node.inputs[i], g)


def _run_backward(heads, head_grads, collect=None, write_attached=True,
                  create_graph=False):
    """Reverse accumulation over the tape.

    ``create_graph=True`` (reference: autograd.grad(create_graph=True),
    higher-order gradients): every vjp call of the sweep runs THROUGH the
    imperative invoke path on live NDArrays, so if recording is active the
    returned gradients are themselves on the tape and differentiable —
    jax pullback closures are pure traced functions, so jax can transpose
    them again.
    """
    import jax.numpy as jnp

    from .ndarray.ndarray import NDArray, _wrap_jax

    # grad accumulator keyed by array object identity
    acc = {}
    keep = {}  # keep NDArray objects alive so ids stay unique

    def add_grad(arr, g):
        from jax.dtypes import float0 as _float0

        # float0 = jax's "no cotangent" marker (int/bool inputs)
        if g is None or (hasattr(g, "dtype") and g.dtype == _float0):
            return
        k = id(arr)
        if k in acc:
            acc[k] = acc[k] + g
        else:
            acc[k] = g
            keep[k] = arr

    # seed heads
    for i, h in enumerate(heads):
        if getattr(h, "_ag_node", None) is None and getattr(h, "_grad_req", "null") == "null":
            raise MXNetError(
                "cannot differentiate a head that is not on the tape; "
                "call .attach_grad() and compute inside autograd.record()")
        if head_grads is None or head_grads[i] is None:
            hg = jnp.ones(h.shape, dtype=h.dtype)
            if create_graph:
                hg = _wrap_jax(hg, h.context)
        else:
            hg = head_grads[i] if create_graph else head_grads[i].data
        add_grad(h, hg)

    # collect reachable nodes (reverse topological via iterative DFS
    # postorder — deep tapes, e.g. long unrolled RNNs, must not hit the
    # Python recursion limit)
    visited = set()
    order: List[TapeNode] = []
    stack = []
    for h in heads:
        n = getattr(h, "_ag_node", None)
        if n is not None:
            stack.append((n, False))
    while stack:
        node, expanded = stack.pop()
        if expanded:
            order.append(node)
            continue
        if id(node) in visited:
            continue
        visited.add(id(node))
        stack.append((node, True))
        for inp in node.inputs:
            child = getattr(inp, "_ag_node", None)
            if child is not None and id(child) not in visited:
                stack.append((child, False))

    # watched-array early finalization: a watched array's grad is FINAL
    # once the last tape node listing it as an input has been swept — no
    # later node can add_grad into it. Precompute that last-use index so
    # the sweep can write the grad buffer and fire the ready callback
    # in-flight (backward-overlapped comms; see watch_grad_ready).
    sweep = list(reversed(order))
    ready_at = {}
    if write_attached and not create_graph and _GRAD_READY_WATCH:
        # prune dead entries first — a process churning watchers must
        # not pay the last-use scan for registrations that can never
        # fire (and their ids may alias new objects)
        for k, (aref, cref) in list(_GRAD_READY_WATCH.items()):
            if aref() is None or cref() is None:
                _GRAD_READY_WATCH.pop(k, None)
    if write_attached and not create_graph and _GRAD_READY_WATCH:
        global _BACKWARD_SEQ
        _BACKWARD_SEQ += 1
        last_use = {}
        for idx, node in enumerate(sweep):
            for inp in node.inputs:
                k = id(inp)
                if k in _GRAD_READY_WATCH:
                    last_use[k] = idx
        for k, idx in last_use.items():
            ready_at.setdefault(idx, []).append(k)
    finalized = set()

    # reverse sweep
    for idx, node in enumerate(sweep):
        if create_graph:
            _sweep_node_recorded(node, acc, add_grad)
            continue
        cotangents = []
        any_grad = False
        for o, (shape, dtype) in zip(node.outputs, node.out_avals):
            g = acc.get(id(o))
            if g is None:
                cotangents.append(_zeros_cotangent(shape, dtype))
            else:
                any_grad = True
                cotangents.append(g.astype(dtype) if hasattr(g, "astype") and g.dtype != dtype else g)
        if any_grad:
            ct = tuple(cotangents) if len(cotangents) > 1 else cotangents[0]
            in_grads = node.vjp_fn(ct)
            for inp, g in zip(node.inputs, in_grads):
                if g is None:
                    continue
                dt = getattr(g, "dtype", None)
                if dt is not None and str(dt) == "float0":
                    continue
                add_grad(inp, g)
        for k in ready_at.get(idx, ()):
            entry = _GRAD_READY_WATCH.get(k)
            if entry is None:
                continue
            arr = entry[0]()
            cb = entry[1]()
            if arr is None or cb is None:
                # array or callback owner died — prune the stale entry
                # (its id may alias a new object)
                _GRAD_READY_WATCH.pop(k, None)
                continue
            if _finalize_attached(arr, acc):
                finalized.add(k)
                cb(arr)

    # write attached grads (reference: grads written per grad_req write/add)
    if write_attached:
        for k, arr in keep.items():
            if k not in finalized:
                _finalize_attached(arr, acc)
    return acc


def get_symbol(x):
    raise NotImplementedError(
        "autograd.get_symbol: tape-to-Symbol export is not supported; "
        "use HybridBlock.export for deployable graphs")


# ---------------------------------------------------------------------------
# custom Function (reference: python/mxnet/autograd.py :: Function +
# src/c_api/c_api_function.cc)
# ---------------------------------------------------------------------------


class Function:
    """User-defined differentiable function with explicit forward/backward."""

    def __init__(self):
        self._saved = ()

    def save_for_backward(self, *args):
        self._saved = args

    @property
    def saved_tensors(self):
        return self._saved

    def forward(self, *inputs):
        raise NotImplementedError

    def backward(self, *output_grads):
        raise NotImplementedError

    def __call__(self, *inputs):
        from .ndarray.ndarray import NDArray, _wrap_jax

        with pause():
            outputs = self.forward(*inputs)
        single = not isinstance(outputs, (list, tuple))
        outs = [outputs] if single else list(outputs)
        if is_recording():
            fn_self = self

            def vjp_fn(cotangents):
                cts = cotangents if isinstance(cotangents, tuple) else (cotangents,)
                ndcts = [_wrap_jax(c, outs[0].context) for c in cts]
                with pause():
                    in_grads = fn_self.backward(*ndcts)
                if not isinstance(in_grads, (list, tuple)):
                    in_grads = [in_grads]
                return tuple(g.data if g is not None else None for g in in_grads)

            record_node(vjp_fn, list(inputs), outs, name=type(self).__name__)
        return outs[0] if single else outs
