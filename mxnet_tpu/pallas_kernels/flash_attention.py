"""Flash attention: Pallas TPU kernel + blockwise-scan fallback.

Two implementations of the same O(L) -memory online-softmax algorithm:

* ``flash_attention`` — Pallas kernels both directions. Forward: grid
  (batch*heads, q_blocks, k_blocks), K/V streamed HBM->VMEM one block per
  grid step, f32 accumulators in VMEM scratch, bf16 matmuls on the MXU;
  emits the per-row logsumexp as a residual. Backward (``jax.custom_vjp``):
  a dK/dV kernel (K block resident, Q streams; scores computed transposed
  so row stats broadcast from lane vectors) and a dQ kernel (Q resident,
  K streams), both recomputing probabilities from the saved logsumexp —
  the FlashAttention-2 recompute trade, all matmuls on the MXU.
* ``flash_attention_scan`` — pure-XLA `lax.scan` over K blocks; runs
  anywhere (the CPU-oracle path for check_consistency tests) and is the
  long-sequence fallback when the kernel's shape constraints aren't met.

Shapes: q (B, H, Lq, D), k/v (B, H, Lk, D) -> (B, H, Lq, D).
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as _np

BLOCK_Q = 128
BLOCK_K = 128

_NEG_INF = -1e30
# np.float32 constants: under global jax_enable_x64 a Python float would be
# promoted to f64 inside the kernel trace, which Mosaic cannot legalize
_NEG_INF32 = _np.float32(-1e30)
_ONE32 = _np.float32(1.0)
_ZERO32 = _np.float32(0.0)


def _x32_mode():
    # Mosaic cannot legalize the i64/f64 constants that jax_enable_x64
    # (on globally for MXNet dtype parity) injects into kernel traces and
    # BlockSpec index maps; trace kernels in 32-bit mode.
    return jax.enable_x64(False)


def _prec_for(dtype):
    # f32 inputs get multi-pass MXU matmuls (f32-faithful); bf16 inputs run
    # the native single-pass — the training fast path
    if jnp.dtype(dtype) == jnp.float32:
        return jax.lax.Precision.HIGHEST
    return jax.lax.Precision.DEFAULT


def flash_shape_supported(q, k, v, causal=False) -> bool:
    """Platform-independent kernel shape eligibility.

    Causal with lq > lk is rejected: bottom-right alignment would leave the
    top query rows with no visible keys (a fully-masked, degenerate row the
    dense reference only "answers" with a uniform softmax over masked-out
    scores — not a shape any model in the zoo produces)."""
    lq, lk = q.shape[-2], k.shape[-2]
    if causal and lq > lk:
        return False
    return (lq % BLOCK_Q == 0 and lk % BLOCK_K == 0
            and q.shape[-1] <= 256 and q.shape[-1] % 8 == 0)


def flash_supported(q, k, v, causal=False) -> bool:
    """Kernel eligibility: TPU execution + block-aligned sequence lengths.

    Platform comes from ``base.current_execution_platform`` — set by the
    framework's jit entry points — so a CPU-context op never takes the
    kernel path just because a TPU exists in the process.
    """
    from ..base import current_execution_platform

    if current_execution_platform(q) != "tpu":
        return False
    return flash_shape_supported(q, k, v, causal=causal)


# ---------------------------------------------------------------------------
# scan fallback (runs anywhere; also the VJP recompute path)
# ---------------------------------------------------------------------------


def flash_attention_scan(q, k, v, scale=None, causal=False,
                         block_k=BLOCK_K):
    """Online-softmax attention via lax.scan over K blocks. O(Lk/block)
    scan steps, never materialises the (Lq, Lk) score matrix."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    dtype = q.dtype
    b, h, lq, d = q.shape
    lk = k.shape[2]
    block_k = min(block_k, lk)
    nk = -(-lk // block_k)
    pad = nk * block_k - lk
    if pad:
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pad), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pad), (0, 0)))
    qf = q.astype(jnp.float32) * scale
    kf = k.astype(jnp.float32).reshape(b, h, nk, block_k, d)
    vf = v.astype(jnp.float32).reshape(b, h, nk, block_k, d)
    # bottom-right causal alignment (matches _sdpa_reference's tril
    # k=lk-lq): the LAST query row sees all lk keys
    q_pos = jnp.arange(lq)[:, None] + (lk - lq)

    def body(carry, blk):
        acc, m, l = carry
        kb, vb, kidx = blk
        s = jnp.einsum("bhqd,bhkd->bhqk", qf, kb)
        k_pos = kidx * block_k + jnp.arange(block_k)[None, :]
        valid = k_pos < lk
        if causal:
            valid = valid & (k_pos <= q_pos)
        s = jnp.where(valid[None, None], s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        # zero masked probabilities explicitly: for a FULLY-masked row
        # m_new == _NEG_INF and exp(s - m_new) would be 1 for every
        # masked/padded key, silently averaging them in
        p = jnp.where(valid[None, None], jnp.exp(s - m_new), 0.0)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jnp.einsum("bhqk,bhkd->bhqd", p, vb)
        return (acc_new, m_new, l_new), None

    acc0 = jnp.zeros((b, h, lq, d), jnp.float32)
    m0 = jnp.full((b, h, lq, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, lq, 1), jnp.float32)
    (acc, m, l), _ = jax.lax.scan(
        body, (acc0, m0, l0),
        (kf.transpose(2, 0, 1, 3, 4), vf.transpose(2, 0, 1, 3, 4),
         jnp.arange(nk)))
    # fully-masked rows (l == 0) emit zeros rather than 0/0 NaN
    return (acc / jnp.where(l == 0.0, 1.0, l)).astype(dtype)


# ---------------------------------------------------------------------------
# pallas kernel
# ---------------------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref,
                *, scale, causal, nk, causal_offset, prec):
    from jax.experimental import pallas as pl

    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF32)
        l_ref[:] = jnp.zeros_like(l_ref)

    qi = pl.program_id(1)

    def compute():
        q = q_ref[0].astype(jnp.float32) * scale          # (BQ, D)
        k = k_ref[0].astype(jnp.float32)                   # (BK, D)
        v = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=prec)  # (BQ, BK)
        if causal:
            # bottom-right alignment: offset = lk - lq
            q_pos = causal_offset + qi * BLOCK_Q + jax.lax.broadcasted_iota(
                jnp.int32, (BLOCK_Q, BLOCK_K), 0)
            k_pos = ki * BLOCK_K + jax.lax.broadcasted_iota(
                jnp.int32, (BLOCK_Q, BLOCK_K), 1)
            s = jnp.where(k_pos <= q_pos, s, _NEG_INF32)
        m_prev = m_ref[:, 0:1]                             # (BQ, 1)
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_ref[:] = l_ref[:] * alpha + jnp.broadcast_to(
            jnp.sum(p, axis=-1, keepdims=True), l_ref.shape)
        acc_ref[:] = acc_ref[:] * alpha + jnp.dot(
            p, v, preferred_element_type=jnp.float32, precision=prec)
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)

    if causal:
        # blocks entirely above the diagonal contribute nothing — skip
        @pl.when(ki * BLOCK_K <= causal_offset + qi * BLOCK_Q + BLOCK_Q - 1)
        def _():
            compute()
    else:
        compute()

    @pl.when(ki == nk - 1)
    def _final():
        # fully-masked rows (every K block skipped: l == 0) emit zeros
        l = l_ref[:, 0:1]
        o_ref[0] = (acc_ref[:] / jnp.where(l == _ZERO32, _ONE32, l)).astype(
            o_ref.dtype)
        # per-row logsumexp residual for the backward kernels, stored as a
        # lane vector broadcast over 8 sublanes — (8, BQ) is the smallest
        # f32 tile, so the (BQ,) column transposes into it legally
        m_col = m_ref[:, 0:1]
        l_safe = jnp.where(l == _ZERO32, _ONE32, l)
        lse_col = jnp.where(l == _ZERO32, _NEG_INF32, m_col + jnp.log(l_safe))
        lse_ref[0, 0] = jnp.broadcast_to(
            lse_col.reshape(1, BLOCK_Q), (8, BLOCK_Q))


def _flash_fwd_pallas(q, k, v, scale, causal, interpret=False):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, lq, d = q.shape
    lk = k.shape[2]
    bh = b * h
    q3 = q.reshape(bh, lq, d)
    k3 = k.reshape(bh, lk, d)
    v3 = v.reshape(bh, lk, d)
    nq, nk = lq // BLOCK_Q, lk // BLOCK_K
    prec = _prec_for(q.dtype)
    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               nk=nk, causal_offset=lk - lq, prec=prec)
    with _x32_mode():
        out, lse = _call_fwd(kernel, q3, k3, v3, bh, nq, nk, lq, d,
                             q.dtype, interpret)
    return out.reshape(b, h, lq, d), lse


def _call_fwd(kernel, q3, k3, v3, bh, nq, nk, lq, d, dtype, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    out, lse = pl.pallas_call(
        kernel,
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, BLOCK_Q, d), lambda bh_, qi, ki: (bh_, qi, 0)),
            pl.BlockSpec((1, BLOCK_K, d), lambda bh_, qi, ki: (bh_, ki, 0)),
            pl.BlockSpec((1, BLOCK_K, d), lambda bh_, qi, ki: (bh_, ki, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, BLOCK_Q, d), lambda bh_, qi, ki: (bh_, qi, 0)),
            pl.BlockSpec((1, 1, 8, BLOCK_Q),
                         lambda bh_, qi, ki: (bh_, qi, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, lq, d), dtype),
            jax.ShapeDtypeStruct((bh, nq, 8, BLOCK_Q), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((BLOCK_Q, d), jnp.float32),
            pltpu.VMEM((BLOCK_Q, 128), jnp.float32),
            pltpu.VMEM((BLOCK_Q, 128), jnp.float32),
        ],
        interpret=interpret,
    )(q3, k3, v3)
    return out, lse


def _bwd_dkdv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                     dk_ref, dv_ref, dk_acc, dv_acc, *,
                     scale, causal, nq, causal_offset, prec):
    """dK/dV for one K block; Q blocks stream on the innermost grid dim.

    All score math is done TRANSPOSED — s_T = (BK, BQ) — so the per-row
    stats (lse, delta) broadcast from lane vectors (1, BQ) without any
    relayout, and dV/dK contractions take p_T/ds_T directly.
    """
    from jax.experimental import pallas as pl

    qi = pl.program_id(2)
    ki = pl.program_id(1)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    def compute():
        q = q_ref[0].astype(jnp.float32)                   # (BQ, D)
        k = k_ref[0].astype(jnp.float32)                   # (BK, D)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)                 # (BQ, D)
        lse = lse_ref[0, 0][0:1, :]                         # (1, BQ)
        delta = delta_ref[0, 0][0:1, :]                     # (1, BQ)
        s_t = jax.lax.dot_general(
            k, q, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=prec) * scale
        if causal:
            q_pos = causal_offset + qi * BLOCK_Q + jax.lax.broadcasted_iota(
                jnp.int32, (BLOCK_K, BLOCK_Q), 1)
            k_pos = ki * BLOCK_K + jax.lax.broadcasted_iota(
                jnp.int32, (BLOCK_K, BLOCK_Q), 0)
            s_t = jnp.where(k_pos <= q_pos, s_t, _NEG_INF32)
        p_t = jnp.exp(s_t - lse)                            # (BK, BQ)
        dv_acc[:] += jnp.dot(p_t, do, preferred_element_type=jnp.float32,
                             precision=prec)
        dp_t = jax.lax.dot_general(
            v, do, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=prec)  # (BK, BQ)
        ds_t = p_t * (dp_t - delta) * scale
        dk_acc[:] += jnp.dot(ds_t, q, preferred_element_type=jnp.float32,
                             precision=prec)

    if causal:
        @pl.when(ki * BLOCK_K <= causal_offset + qi * BLOCK_Q + BLOCK_Q - 1)
        def _():
            compute()
    else:
        compute()

    @pl.when(qi == nq - 1)
    def _final():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, dq_acc, *, scale, causal, nk, causal_offset, prec):
    """dQ for one Q block; K blocks stream on the innermost grid dim."""
    from jax.experimental import pallas as pl

    ki = pl.program_id(2)
    qi = pl.program_id(1)

    @pl.when(ki == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    def compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0, 0][0:1, :]                         # (1, BQ)
        delta = delta_ref[0, 0][0:1, :]                     # (1, BQ)
        s_t = jax.lax.dot_general(
            k, q, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=prec) * scale
        if causal:
            q_pos = causal_offset + qi * BLOCK_Q + jax.lax.broadcasted_iota(
                jnp.int32, (BLOCK_K, BLOCK_Q), 1)
            k_pos = ki * BLOCK_K + jax.lax.broadcasted_iota(
                jnp.int32, (BLOCK_K, BLOCK_Q), 0)
            s_t = jnp.where(k_pos <= q_pos, s_t, _NEG_INF32)
        p_t = jnp.exp(s_t - lse)
        dp_t = jax.lax.dot_general(
            v, do, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=prec)
        ds_t = p_t * (dp_t - delta) * scale                 # (BK, BQ)
        # dq = ds @ k = ds_t^T @ k : contract the BK dim of both
        dq_acc[:] += jax.lax.dot_general(
            ds_t, k, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=prec)  # (BQ, D)

    if causal:
        @pl.when(ki * BLOCK_K <= causal_offset + qi * BLOCK_Q + BLOCK_Q - 1)
        def _():
            compute()
    else:
        compute()

    @pl.when(ki == nk - 1)
    def _final():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _flash_bwd_pallas(q, k, v, o, lse, g, scale, causal, interpret=False):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, lq, d = q.shape
    lk = k.shape[2]
    bh = b * h
    q3 = q.reshape(bh, lq, d)
    k3 = k.reshape(bh, lk, d)
    v3 = v.reshape(bh, lk, d)
    do3 = g.reshape(bh, lq, d)
    nq, nk = lq // BLOCK_Q, lk // BLOCK_K
    # delta_i = rowsum(dO_i * O_i) — cheap, fused by XLA outside the
    # kernel; stored in the same sublane-padded layout as lse
    delta = jnp.sum(do3.astype(jnp.float32)
                    * o.reshape(bh, lq, d).astype(jnp.float32), axis=-1)
    delta = jnp.broadcast_to(delta.reshape(bh, nq, 1, BLOCK_Q),
                             (bh, nq, 8, BLOCK_Q))
    offset = lk - lq

    q_spec = pl.BlockSpec((1, BLOCK_Q, d), lambda bh_, i, j: (bh_, j, 0))
    row_spec = pl.BlockSpec((1, 1, 8, BLOCK_Q),
                            lambda bh_, i, j: (bh_, j, 0, 0))
    with _x32_mode():
        dkdv = pl.pallas_call(
            functools.partial(_bwd_dkdv_kernel, scale=scale, causal=causal,
                              nq=nq, causal_offset=offset,
                              prec=_prec_for(q.dtype)),
            grid=(bh, nk, nq),
            in_specs=[
                q_spec,                                          # q by qi=j
                pl.BlockSpec((1, BLOCK_K, d), lambda bh_, i, j: (bh_, i, 0)),
                pl.BlockSpec((1, BLOCK_K, d), lambda bh_, i, j: (bh_, i, 0)),
                q_spec,                                          # do by qi=j
                row_spec,                                        # lse
                row_spec,                                        # delta
            ],
            out_specs=[
                pl.BlockSpec((1, BLOCK_K, d), lambda bh_, i, j: (bh_, i, 0)),
                pl.BlockSpec((1, BLOCK_K, d), lambda bh_, i, j: (bh_, i, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((bh, lk, d), k.dtype),
                jax.ShapeDtypeStruct((bh, lk, d), v.dtype),
            ],
            scratch_shapes=[
                pltpu.VMEM((BLOCK_K, d), jnp.float32),
                pltpu.VMEM((BLOCK_K, d), jnp.float32),
            ],
            interpret=interpret,
        )
        dk3, dv3 = dkdv(q3, k3, v3, do3, lse, delta)

        dq = pl.pallas_call(
            functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                              nk=nk, causal_offset=offset,
                              prec=_prec_for(q.dtype)),
            grid=(bh, nq, nk),
            in_specs=[
                pl.BlockSpec((1, BLOCK_Q, d), lambda bh_, i, j: (bh_, i, 0)),
                pl.BlockSpec((1, BLOCK_K, d), lambda bh_, i, j: (bh_, j, 0)),
                pl.BlockSpec((1, BLOCK_K, d), lambda bh_, i, j: (bh_, j, 0)),
                pl.BlockSpec((1, BLOCK_Q, d), lambda bh_, i, j: (bh_, i, 0)),
                pl.BlockSpec((1, 1, 8, BLOCK_Q),
                             lambda bh_, i, j: (bh_, i, 0, 0)),
                pl.BlockSpec((1, 1, 8, BLOCK_Q),
                             lambda bh_, i, j: (bh_, i, 0, 0)),
            ],
            out_specs=pl.BlockSpec((1, BLOCK_Q, d),
                                   lambda bh_, i, j: (bh_, i, 0)),
            out_shape=jax.ShapeDtypeStruct((bh, lq, d), q.dtype),
            scratch_shapes=[pltpu.VMEM((BLOCK_Q, d), jnp.float32)],
            interpret=interpret,
        )(q3, k3, v3, do3, lse, delta)
    return (dq.reshape(b, h, lq, d), dk3.reshape(b, h, lk, d),
            dv3.reshape(b, h, lk, d))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash(q, k, v, scale, causal, interpret):
    return _flash_fwd_pallas(q, k, v, scale, causal, interpret)[0]


def _flash_fwd(q, k, v, scale, causal, interpret):
    o, lse = _flash_fwd_pallas(q, k, v, scale, causal, interpret)
    return o, (q, k, v, o, lse)


def _flash_bwd(scale, causal, interpret, res, g):
    # Pallas dq/dk/dv kernels recomputing p from the saved logsumexp —
    # training-mode attention runs on the MXU in BOTH directions (round-1
    # weakness #5: the old bwd re-differentiated the XLA scan).
    q, k, v, o, lse = res
    return _flash_bwd_pallas(q, k, v, o, lse, g, scale, causal, interpret)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, scale=None, causal=False, interpret=False):
    """Pallas flash attention (differentiable)."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    return _flash(q, k, v, float(scale), bool(causal), bool(interpret))
