"""Flash attention: Pallas TPU kernel + blockwise-scan fallback.

Two implementations of the same O(L) -memory online-softmax algorithm:

* ``flash_attention`` — Pallas kernel. Grid (batch*heads, q_blocks,
  k_blocks), K/V streamed HBM->VMEM one block per grid step, f32
  accumulators in VMEM scratch, bf16 matmuls on the MXU. Backward via
  ``jax.custom_vjp`` differentiating the scan fallback (recompute — trades
  FLOPs for the O(L^2) score matrix, the flash trade).
* ``flash_attention_scan`` — pure-XLA `lax.scan` over K blocks; runs
  anywhere (the CPU-oracle path for check_consistency tests) and is the
  long-sequence fallback when the kernel's shape constraints aren't met.

Shapes: q (B, H, Lq, D), k/v (B, H, Lk, D) -> (B, H, Lq, D).
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

BLOCK_Q = 128
BLOCK_K = 128
_NEG_INF = -1e30


def flash_shape_supported(q, k, v, causal=False) -> bool:
    """Platform-independent kernel shape eligibility.

    Causal with lq > lk is rejected: bottom-right alignment would leave the
    top query rows with no visible keys (a fully-masked, degenerate row the
    dense reference only "answers" with a uniform softmax over masked-out
    scores — not a shape any model in the zoo produces)."""
    lq, lk = q.shape[-2], k.shape[-2]
    if causal and lq > lk:
        return False
    return (lq % BLOCK_Q == 0 and lk % BLOCK_K == 0
            and q.shape[-1] <= 256 and q.shape[-1] % 8 == 0)


def flash_supported(q, k, v, causal=False) -> bool:
    """Kernel eligibility: TPU platform + block-aligned sequence lengths."""
    try:
        platform = jax.devices()[0].platform
    except Exception:
        return False
    if platform != "tpu":
        return False
    return flash_shape_supported(q, k, v, causal=causal)


# ---------------------------------------------------------------------------
# scan fallback (runs anywhere; also the VJP recompute path)
# ---------------------------------------------------------------------------


def flash_attention_scan(q, k, v, scale=None, causal=False,
                         block_k=BLOCK_K):
    """Online-softmax attention via lax.scan over K blocks. O(Lk/block)
    scan steps, never materialises the (Lq, Lk) score matrix."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    dtype = q.dtype
    b, h, lq, d = q.shape
    lk = k.shape[2]
    block_k = min(block_k, lk)
    nk = -(-lk // block_k)
    pad = nk * block_k - lk
    if pad:
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pad), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pad), (0, 0)))
    qf = q.astype(jnp.float32) * scale
    kf = k.astype(jnp.float32).reshape(b, h, nk, block_k, d)
    vf = v.astype(jnp.float32).reshape(b, h, nk, block_k, d)
    # bottom-right causal alignment (matches _sdpa_reference's tril
    # k=lk-lq): the LAST query row sees all lk keys
    q_pos = jnp.arange(lq)[:, None] + (lk - lq)

    def body(carry, blk):
        acc, m, l = carry
        kb, vb, kidx = blk
        s = jnp.einsum("bhqd,bhkd->bhqk", qf, kb)
        k_pos = kidx * block_k + jnp.arange(block_k)[None, :]
        valid = k_pos < lk
        if causal:
            valid = valid & (k_pos <= q_pos)
        s = jnp.where(valid[None, None], s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        # zero masked probabilities explicitly: for a FULLY-masked row
        # m_new == _NEG_INF and exp(s - m_new) would be 1 for every
        # masked/padded key, silently averaging them in
        p = jnp.where(valid[None, None], jnp.exp(s - m_new), 0.0)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jnp.einsum("bhqk,bhkd->bhqd", p, vb)
        return (acc_new, m_new, l_new), None

    acc0 = jnp.zeros((b, h, lq, d), jnp.float32)
    m0 = jnp.full((b, h, lq, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, lq, 1), jnp.float32)
    (acc, m, l), _ = jax.lax.scan(
        body, (acc0, m0, l0),
        (kf.transpose(2, 0, 1, 3, 4), vf.transpose(2, 0, 1, 3, 4),
         jnp.arange(nk)))
    # fully-masked rows (l == 0) emit zeros rather than 0/0 NaN
    return (acc / jnp.where(l == 0.0, 1.0, l)).astype(dtype)


# ---------------------------------------------------------------------------
# pallas kernel
# ---------------------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref, *,
                scale, causal, nk, causal_offset):
    from jax.experimental import pallas as pl

    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    qi = pl.program_id(1)

    def compute():
        q = q_ref[0].astype(jnp.float32) * scale          # (BQ, D)
        k = k_ref[0].astype(jnp.float32)                   # (BK, D)
        v = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)            # (BQ, BK)
        if causal:
            # bottom-right alignment: offset = lk - lq
            q_pos = causal_offset + qi * BLOCK_Q + jax.lax.broadcasted_iota(
                jnp.int32, (BLOCK_Q, BLOCK_K), 0)
            k_pos = ki * BLOCK_K + jax.lax.broadcasted_iota(
                jnp.int32, (BLOCK_Q, BLOCK_K), 1)
            s = jnp.where(k_pos <= q_pos, s, _NEG_INF)
        m_prev = m_ref[:, 0:1]                             # (BQ, 1)
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_ref[:] = l_ref[:] * alpha + jnp.broadcast_to(
            jnp.sum(p, axis=-1, keepdims=True), l_ref.shape)
        acc_ref[:] = acc_ref[:] * alpha + jnp.dot(
            p, v, preferred_element_type=jnp.float32)
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)

    if causal:
        # blocks entirely above the diagonal contribute nothing — skip
        @pl.when(ki * BLOCK_K <= causal_offset + qi * BLOCK_Q + BLOCK_Q - 1)
        def _():
            compute()
    else:
        compute()

    @pl.when(ki == nk - 1)
    def _final():
        # fully-masked rows (every K block skipped: l == 0) emit zeros
        l = l_ref[:, 0:1]
        o_ref[0] = (acc_ref[:] / jnp.where(l == 0.0, 1.0, l)).astype(
            o_ref.dtype)


def _flash_fwd_pallas(q, k, v, scale, causal, interpret=False):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, lq, d = q.shape
    lk = k.shape[2]
    bh = b * h
    q3 = q.reshape(bh, lq, d)
    k3 = k.reshape(bh, lk, d)
    v3 = v.reshape(bh, lk, d)
    nq, nk = lq // BLOCK_Q, lk // BLOCK_K
    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               nk=nk, causal_offset=lk - lq)
    out = pl.pallas_call(
        kernel,
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, BLOCK_Q, d), lambda bh_, qi, ki: (bh_, qi, 0)),
            pl.BlockSpec((1, BLOCK_K, d), lambda bh_, qi, ki: (bh_, ki, 0)),
            pl.BlockSpec((1, BLOCK_K, d), lambda bh_, qi, ki: (bh_, ki, 0)),
        ],
        out_specs=pl.BlockSpec((1, BLOCK_Q, d),
                               lambda bh_, qi, ki: (bh_, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, lq, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((BLOCK_Q, d), jnp.float32),
            pltpu.VMEM((BLOCK_Q, 128), jnp.float32),
            pltpu.VMEM((BLOCK_Q, 128), jnp.float32),
        ],
        interpret=interpret,
    )(q3, k3, v3)
    return out.reshape(b, h, lq, d)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash(q, k, v, scale, causal, interpret):
    return _flash_fwd_pallas(q, k, v, scale, causal, interpret)


def _flash_fwd(q, k, v, scale, causal, interpret):
    return _flash_fwd_pallas(q, k, v, scale, causal, interpret), (q, k, v)


def _flash_bwd(scale, causal, interpret, res, g):
    q, k, v = res
    # recompute-based backward through the O(L)-memory scan path
    _, vjp = jax.vjp(
        lambda q_, k_, v_: flash_attention_scan(q_, k_, v_, scale=scale,
                                                causal=causal), q, k, v)
    return vjp(g)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, scale=None, causal=False, interpret=False):
    """Pallas flash attention (differentiable)."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    return _flash(q, k, v, float(scale), bool(causal), bool(interpret))
