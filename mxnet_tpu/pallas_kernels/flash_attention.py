"""Flash attention: Pallas TPU kernel + blockwise-scan fallback.

Two implementations of the same O(L) -memory online-softmax algorithm:

* ``flash_attention`` — Pallas kernels both directions. Forward: grid
  (batch*heads, q_blocks, k_blocks), K/V streamed HBM->VMEM one block per
  grid step, f32 accumulators in VMEM scratch, bf16 matmuls on the MXU;
  emits the per-row logsumexp as a residual. Backward (``jax.custom_vjp``):
  a dK/dV kernel (K block resident, Q streams; scores computed transposed
  so row stats broadcast from lane vectors) and a dQ kernel (Q resident,
  K streams), both recomputing probabilities from the saved logsumexp —
  the FlashAttention-2 recompute trade, all matmuls on the MXU.
* ``flash_attention_scan`` — pure-XLA `lax.scan` over K blocks; runs
  anywhere (the CPU-oracle path for check_consistency tests) and is the
  long-sequence fallback when the kernel's shape constraints aren't met.

Shapes: q (B, H, Lq, D), k/v (B, H, Lk, D) -> (B, H, Lq, D).
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as _np

BLOCK_Q = 128
BLOCK_K = 128
MAX_BLOCK = 512


def _block_sizes(lq, lk):
    """Largest power-of-two blocks (<= MAX_BLOCK) dividing the seq lengths.

    Bigger blocks mean fewer grid steps and larger MXU matmuls — at seq 512
    a single (512, 512) block turns the whole head into one VMEM-resident
    fused attention, which is what beats XLA's HBM-bound softmax path. 512
    is the VMEM comfort cap: the f32 score tile is bq*bk*4 = 1 MB.
    """
    try:
        # NOTE: an isolated-attention microbench prefers bq=256 at seq 512
        # (~20% on the kernel alone), but the END-TO-END BERT step is
        # consistently FASTER with 512x512 (197-199 vs 182-191 samples/s)
        # — in-context VMEM pressure and step pipelining differ; trust the
        # end-to-end number
        bq = next(b for b in (MAX_BLOCK, 256, 128) if lq % b == 0)
        bk = next(b for b in (MAX_BLOCK, 256, 128) if lk % b == 0)
    except StopIteration:
        raise ValueError(
            f"flash_attention requires sequence lengths that are multiples "
            f"of {BLOCK_Q}; got lq={lq}, lk={lk} (use flash_attention_scan "
            f"or sdp_attention, which fall back automatically)") from None
    return bq, bk

_NEG_INF = -1e30
# np.float32 constants: under global jax_enable_x64 a Python float would be
# promoted to f64 inside the kernel trace, which Mosaic cannot legalize
_NEG_INF32 = _np.float32(-1e30)
_ONE32 = _np.float32(1.0)
_ZERO32 = _np.float32(0.0)
# All kernels run softmax in BASE-2: log2(e) folds into the score scale
# (one multiply that was already there) and exp2 is the VPU's native
# transcendental — exp lowers to exp2 plus a scale per element, so at
# attention sizes (50M+ exps/layer/step, the kernels' dominant VPU cost)
# base-2 removes a full multiply sweep. The saved lse residual is
# therefore in the base-2 domain: p == exp2(s2 - lse2) exactly equals
# exp(s - lse); gradient math (ds = p*(dp-delta)*scale) is unchanged
# because only the representation of p's computation moves, not p.
_LOG2E = _np.float32(1.4426950408889634)

# --- stateless dropout hash (shared by kernels, fallbacks, and oracles) ---
# splitmix/murmur3-finalizer on the element's absolute (head, q, k) id:
# pure elementwise integer code, so the SAME mask is reproducible in any
# kernel orientation/grouping (fwd (BQ, BK) vs transposed bwd (BK, BQ))
# and in the pure-jnp reference path — no PRNG state to thread, no
# fwd-to-bwd mask tensor in HBM. 16 low hash bits vs a u16 threshold =
# the dropout op's keep-rate granularity (ops/nn.py::dropout_op).
_GOLD = _np.uint32(0x9E3779B9)
_MUR1 = _np.uint32(0x85EBCA6B)
_MUR2 = _np.uint32(0xC2B2AE35)
_U16 = _np.uint32(0xFFFF)


def _hash_u32(idx, seed):
    """Murmur3-finalize uint32 ``idx`` (+seed); full 32-bit result."""
    z = idx * _GOLD + seed
    z = z ^ (z >> 16)
    z = z * _MUR1
    z = z ^ (z >> 13)
    z = z * _MUR2
    z = z ^ (z >> 16)
    return z


def _hash_u16(idx, seed):
    """Low 16 bits of the murmur3 finalizer (dropout threshold compare)."""
    return _hash_u32(idx, seed) & _U16


def dropout_thresh(p):
    """u16 keep threshold for drop probability ``p``."""
    return _np.uint32(min(0xFFFF, int(round((1.0 - p) * 65536.0))))


def fold_key_seed(rng):
    """Fold a jax PRNG key's words into one u32 dropout seed — shared by
    every stateless-hash dropout site so all dispatch paths derive the
    identical stream from the same op key."""
    kd = jax.random.key_data(rng).astype(jnp.uint32).reshape(-1)
    seed = kd[0]
    for i in range(1, kd.shape[0]):
        seed = seed ^ (kd[i] * _np.uint32(0x9E3779B9 + i))
    return seed


def _drop_mask(head_idx, q_pos, k_pos, lq, lk, seed, thresh):
    """Keep-mask for absolute (head, q, k) positions (any orientation).

    Two-level hash: the (batch*head) index folds into a per-head seed
    first, then the in-head (q*lk + k) id is hashed under it — a single
    flat (head*lq + q)*lk + k id would wrap uint32 at b*h*lq*lk > 2^32
    (e.g. 32x16 heads at seq 4096) and silently give distinct elements
    identical masks. Per-head ids wrap only at lq*lk > 2^32, i.e. seq
    ~65k even before the head split.
    """
    head_seed = _hash_u32(head_idx.astype(jnp.uint32), seed)
    idx = (q_pos.astype(jnp.uint32) * _np.uint32(lk)
           + k_pos.astype(jnp.uint32))
    return _hash_u16(idx, head_seed) < thresh


def _x32_mode():
    # Mosaic cannot legalize the i64/f64 constants that jax_enable_x64
    # (on globally for MXNet dtype parity) injects into kernel traces and
    # BlockSpec index maps; trace kernels in 32-bit mode. The context
    # manager moved from jax.experimental to the jax root namespace
    # across versions — accept either home.
    if hasattr(jax, "enable_x64"):
        return jax.enable_x64(False)
    from jax.experimental import enable_x64

    return enable_x64(False)


def _prec_for(dtype):
    # f32 inputs get multi-pass MXU matmuls (f32-faithful); bf16 inputs run
    # the native single-pass — the training fast path
    if jnp.dtype(dtype) == jnp.float32:
        return jax.lax.Precision.HIGHEST
    return jax.lax.Precision.DEFAULT


def flash_shape_supported(q, k, v, causal=False, layout="bhld") -> bool:
    """Platform-independent kernel shape eligibility.

    Causal with lq > lk is rejected: bottom-right alignment would leave the
    top query rows with no visible keys (a fully-masked, degenerate row the
    dense reference only "answers" with a uniform softmax over masked-out
    scores — not a shape any model in the zoo produces)."""
    if layout == "blhd":
        # Mosaic requires the last two block dims be (8k, 128k)-aligned or
        # span the full array dim; a per-head (bq, d) tile of (B, L, H, D)
        # puts a squeezed H in sublane position, which it rejects. The
        # kernel therefore only takes the bhld layout; blhd callers get the
        # einsum path (whose head transposes fold into the contractions).
        return False
    lq, lk = q.shape[-2], k.shape[-2]
    if causal and lq > lk:
        return False
    return (lq % BLOCK_Q == 0 and lk % BLOCK_K == 0
            and q.shape[-1] <= 256 and q.shape[-1] % 8 == 0)


def flash_supported(q, k, v, causal=False, layout="bhld") -> bool:
    """Kernel eligibility: TPU execution + block-aligned sequence lengths.

    Platform comes from ``base.current_execution_platform`` — set by the
    framework's jit entry points — so a CPU-context op never takes the
    kernel path just because a TPU exists in the process.
    """
    from ..base import current_execution_platform

    if current_execution_platform(q) != "tpu":
        return False
    return flash_shape_supported(q, k, v, causal=causal, layout=layout)


# ---------------------------------------------------------------------------
# scan fallback (runs anywhere; also the VJP recompute path)
# ---------------------------------------------------------------------------


def flash_attention_scan(q, k, v, scale=None, causal=False,
                         block_k=BLOCK_K, dropout=0.0, seed=None):
    """Online-softmax attention via lax.scan over K blocks. O(Lk/block)
    scan steps, never materialises the (Lq, Lk) score matrix.

    ``dropout``/``seed``: same stateless position-hash mask as the Pallas
    kernels (bitwise identical given the same seed) — this path doubles
    as the kernels' CPU oracle."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    dropout = float(dropout)
    if dropout > 0.0 and seed is None:
        raise ValueError("flash_attention_scan: dropout > 0 requires seed")
    dtype = q.dtype
    b, h, lq, d = q.shape
    lk = k.shape[2]
    block_k = min(block_k, lk)
    nk = -(-lk // block_k)
    pad = nk * block_k - lk
    if pad:
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pad), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pad), (0, 0)))
    qf = q.astype(jnp.float32) * scale
    kf = k.astype(jnp.float32).reshape(b, h, nk, block_k, d)
    vf = v.astype(jnp.float32).reshape(b, h, nk, block_k, d)
    # bottom-right causal alignment (matches _sdpa_reference's tril
    # k=lk-lq): the LAST query row sees all lk keys
    q_pos = jnp.arange(lq)[:, None] + (lk - lq)

    def body(carry, blk):
        acc, m, l = carry
        kb, vb, kidx = blk
        s = jnp.einsum("bhqd,bhkd->bhqk", qf, kb)
        k_pos = kidx * block_k + jnp.arange(block_k)[None, :]
        valid = k_pos < lk
        if causal:
            valid = valid & (k_pos <= q_pos)
        s = jnp.where(valid[None, None], s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        # zero masked probabilities explicitly: for a FULLY-masked row
        # m_new == _NEG_INF and exp(s - m_new) would be 1 for every
        # masked/padded key, silently averaging them in
        p = jnp.where(valid[None, None], jnp.exp(s - m_new), 0.0)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        if dropout > 0.0:
            shp = (b, h, lq, block_k)
            head = (jax.lax.broadcasted_iota(jnp.int32, shp, 0) * h
                    + jax.lax.broadcasted_iota(jnp.int32, shp, 1))
            qp = jax.lax.broadcasted_iota(jnp.int32, shp, 2)
            kp = kidx * block_k + jax.lax.broadcasted_iota(
                jnp.int32, shp, 3)
            # true lk (not the padded extent): padded columns have p == 0
            # regardless, and the kernel oracle hashes with true lk
            keep = _drop_mask(head, qp, kp, lq, lk,
                              jnp.asarray(seed, jnp.uint32).reshape(-1)[0],
                              dropout_thresh(dropout))
            p_acc = jnp.where(keep, p, 0.0)
        else:
            p_acc = p
        acc_new = acc * alpha + jnp.einsum("bhqk,bhkd->bhqd", p_acc, vb)
        return (acc_new, m_new, l_new), None

    acc0 = jnp.zeros((b, h, lq, d), jnp.float32)
    m0 = jnp.full((b, h, lq, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, lq, 1), jnp.float32)
    (acc, m, l), _ = jax.lax.scan(
        body, (acc0, m0, l0),
        (kf.transpose(2, 0, 1, 3, 4), vf.transpose(2, 0, 1, 3, 4),
         jnp.arange(nk)))
    # fully-masked rows (l == 0) emit zeros rather than 0/0 NaN
    if dropout > 0.0:
        acc = acc * _np.float32(1.0 / (1.0 - dropout))
    return (acc / jnp.where(l == 0.0, 1.0, l)).astype(dtype)


# ---------------------------------------------------------------------------
# pallas kernel
# ---------------------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, seed_ref, o_ref, lse_ref, acc_ref,
                m_ref, l_ref, *, scale2, causal, nk, causal_offset, prec,
                bq, bk, dropout, lq, lk):
    from jax.experimental import pallas as pl

    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF32)
        l_ref[:] = jnp.zeros_like(l_ref)

    qi = pl.program_id(1)

    def compute():
        # operands stay in the INPUT dtype: casting bf16 to f32 before
        # the dot forces multi-pass f32 MXU matmuls — the bf16 native
        # single-pass with f32 accumulate is the whole fast path. The
        # base-2 scale moves onto the f32 scores (exact there).
        q = q_ref[...]                                     # (BQ, D)
        k = k_ref[...]                                     # (BK, D)
        v = v_ref[...]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=prec) * scale2                       # (BQ, BK) f32
        if causal:
            # bottom-right alignment: offset = lk - lq
            q_pos = causal_offset + qi * bq + jax.lax.broadcasted_iota(
                jnp.int32, (bq, bk), 0)
            k_pos = ki * bk + jax.lax.broadcasted_iota(
                jnp.int32, (bq, bk), 1)
            s = jnp.where(k_pos <= q_pos, s, _NEG_INF32)
        m_prev = m_ref[:, 0:1]                             # (BQ, 1)
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp2(s - m_new)
        alpha = jnp.exp2(m_prev - m_new)
        l_ref[:] = l_ref[:] * alpha + jnp.broadcast_to(
            jnp.sum(p, axis=-1, keepdims=True), l_ref.shape)
        if dropout > 0.0:
            # drop in the PV accumulation only: the online (m, l) stats
            # stay pre-dropout; inv_keep folds into the final normalize
            keep = _drop_mask_2d(seed_ref, bq, bk, qi, ki, lq, lk, dropout)
            pd = jnp.where(keep, p, _ZERO32)
        else:
            pd = p
        acc_ref[:] = acc_ref[:] * alpha + jnp.dot(
            pd.astype(v.dtype), v, preferred_element_type=jnp.float32,
            precision=prec)
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)

    if causal:
        # blocks entirely above the diagonal contribute nothing — skip
        @pl.when(ki * bk <= causal_offset + qi * bq + bq - 1)
        def _():
            compute()
    else:
        compute()

    @pl.when(ki == nk - 1)
    def _final():
        # fully-masked rows (every K block skipped: l == 0) emit zeros
        l = l_ref[:, 0:1]
        div = jnp.where(l == _ZERO32, _ONE32, l)
        if dropout > 0.0:
            div = div * _np.float32(1.0 - dropout)
        o_ref[...] = (acc_ref[:] / div).astype(o_ref.dtype)
        # per-row base-2 logsumexp residual for the backward kernels,
        # stored as a lane vector broadcast over 8 sublanes — (8, BQ) is
        # the smallest f32 tile, so the (BQ,) column transposes in legally
        m_col = m_ref[:, 0:1]
        l_safe = jnp.where(l == _ZERO32, _ONE32, l)
        lse_col = jnp.where(l == _ZERO32, _NEG_INF32,
                            m_col + jnp.log2(l_safe))
        lse_ref[...] = jnp.broadcast_to(
            lse_col.reshape(1, bq), (8, bq))


def _drop_mask_g(seed_ref, g, bq, bk, qi, ki, lq, lk, dropout):
    """(G, bq, bk) keep-mask for the g-heads-per-step kernels; head ids
    are absolute (program_id(0) * g + local)."""
    from jax.experimental import pallas as pl

    head = (pl.program_id(0) * g + jax.lax.broadcasted_iota(
        jnp.int32, (g, bq, bk), 0))
    q_pos = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (g, bq, bk), 1)
    k_pos = ki * bk + jax.lax.broadcasted_iota(jnp.int32, (g, bq, bk), 2)
    return _drop_mask(head, q_pos, k_pos, lq, lk, seed_ref[0],
                      dropout_thresh(dropout))


def _drop_mask_2d(seed_ref, bq, bk, qi, ki, lq, lk, dropout,
                  transposed=False):
    """(bq, bk) keep-mask — or its exact (bk, bq) transpose for the
    score-transposed backward kernels (same absolute ids, so the bits
    match the forward elementwise)."""
    from jax.experimental import pallas as pl

    head = pl.program_id(0)
    if transposed:
        q_pos = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bk, bq), 1)
        k_pos = ki * bk + jax.lax.broadcasted_iota(jnp.int32, (bk, bq), 0)
    else:
        q_pos = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        k_pos = ki * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    return _drop_mask(head, q_pos, k_pos, lq, lk, seed_ref[0],
                      dropout_thresh(dropout))


def _fwd_kernel_single_g(q_ref, k_ref, v_ref, seed_ref, o_ref, lse_ref, *,
                         scale2, causal, causal_offset, prec, bq, bk,
                         dropout, lq, lk):
    """g heads per grid step (refs (G, BQ/BK, D)): amortizes the
    per-grid-step overhead that dominates once the softmax runs in
    base-2 — the dots batch over the leading head dim on the MXU."""
    q = q_ref[...]                                         # (G, BQ, D)
    k = k_ref[...]
    v = v_ref[...]
    s = jax.lax.dot_general(
        q, k, (((2,), (2,)), ((0,), (0,))),
        preferred_element_type=jnp.float32, precision=prec) * scale2
    if causal:
        g = q.shape[0]
        q_pos = causal_offset + jax.lax.broadcasted_iota(
            jnp.int32, (g, bq, bk), 1)
        k_pos = jax.lax.broadcasted_iota(jnp.int32, (g, bq, bk), 2)
        s = jnp.where(k_pos <= q_pos, s, _NEG_INF32)
    m = jnp.max(s, axis=-1, keepdims=True)                 # (G, BQ, 1)
    p = jnp.exp2(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    l_safe = jnp.where(l == _ZERO32, _ONE32, l)
    if dropout > 0.0:
        # mask applied to the PV accumulation only: l (and the lse
        # residual) stay pre-dropout softmax statistics; inv_keep folds
        # into the final normalize
        g = q.shape[0]
        keep = _drop_mask_g(seed_ref, g, bq, bk, 0, 0, lq, lk, dropout)
        pd = jnp.where(keep, p, _ZERO32)
        l_safe = l_safe * _np.float32(1.0 - dropout)
    else:
        pd = p
    o = jax.lax.dot_general(
        pd.astype(v.dtype), v, (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32, precision=prec)
    o_ref[...] = (o / l_safe).astype(o_ref.dtype)
    g = q.shape[0]
    l_norm = jnp.where(l == _ZERO32, _ONE32, l)
    lse_col = jnp.where(l == _ZERO32, _NEG_INF32, m + jnp.log2(l_norm))
    lse_ref[...] = jnp.broadcast_to(
        lse_col.reshape(g, 1, bq), (g, 8, bq))


def _fwd_kernel_single(q_ref, k_ref, v_ref, seed_ref, o_ref, lse_ref, *,
                       scale2, causal, causal_offset, prec, bq, bk,
                       dropout, lq, lk):
    """Whole-head-in-one-block forward (nq == nk == 1, e.g. BERT seq 512).

    No streaming means no running statistics: the scratch carries and the
    alpha-rescale sweeps of the online-softmax kernel disappear — at these
    shapes the kernel is VPU-bound, so fewer elementwise passes is the
    win, not matmul shape.
    """
    q = q_ref[...]                                         # (BQ, D)
    k = k_ref[...]
    v = v_ref[...]
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32, precision=prec) * scale2
    if causal:
        q_pos = causal_offset + jax.lax.broadcasted_iota(
            jnp.int32, (bq, bk), 0)
        k_pos = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        s = jnp.where(k_pos <= q_pos, s, _NEG_INF32)
    m = jnp.max(s, axis=-1, keepdims=True)                 # (BQ, 1)
    p = jnp.exp2(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    l_safe = jnp.where(l == _ZERO32, _ONE32, l)
    if dropout > 0.0:
        keep = _drop_mask_2d(seed_ref, bq, bk, 0, 0, lq, lk, dropout)
        pd = jnp.where(keep, p, _ZERO32)
        div = l_safe * _np.float32(1.0 - dropout)
    else:
        pd = p
        div = l_safe
    o_ref[...] = (jnp.dot(pd.astype(v.dtype), v,
                          preferred_element_type=jnp.float32,
                          precision=prec) / div).astype(o_ref.dtype)
    lse_col = jnp.where(l == _ZERO32, _NEG_INF32, m + jnp.log2(l_safe))
    lse_ref[...] = jnp.broadcast_to(lse_col.reshape(1, bq), (8, bq))


def _dims(x, layout, is_q=True):
    if layout == "blhd":
        b, l, h, d = x.shape
    else:
        b, h, l, d = x.shape
    return b, h, l, d


def _tile_spec(layout, h, blk, d, seq_index):
    """BlockSpec for one (blk, d) Q/K/V/O tile of a head.

    bhld: array is pre-reshaped (B*H, L, D); blhd: array stays native
    (B, L, H, D) and the batch/head grid dim splits in the index map —
    no relayout of the activations at all (None entries squeeze the unit
    dims out of the kernel block).
    """
    from jax.experimental import pallas as pl

    if layout == "blhd":
        return pl.BlockSpec(
            (None, blk, None, d),
            lambda bh_, qi, ki, _h=h, _s=seq_index: (
                bh_ // _h, (qi, ki)[_s], bh_ % _h, 0))
    return pl.BlockSpec(
        (None, blk, d),
        lambda bh_, qi, ki, _s=seq_index: (bh_, (qi, ki)[_s], 0))


def _seed_arr(seed):
    """Normalize the dropout seed to the (1,) u32 SMEM operand the
    kernels read (zeros when dropout is off — the mask code isn't
    traced then, the operand just keeps signatures uniform)."""
    if seed is None:
        return jnp.zeros((1,), jnp.uint32)
    return jnp.asarray(seed, jnp.uint32).reshape((1,))


def _flash_fwd_pallas(q, k, v, scale, causal, interpret=False,
                      layout="bhld", dropout=0.0, seed=None):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, lq, d = _dims(q, layout)
    lk = _dims(k, layout)[2]
    bh = b * h
    if layout == "bhld":
        q = q.reshape(bh, lq, d)
        k = k.reshape(bh, lk, d)
        v = v.reshape(bh, lk, d)
        o_shape = jax.ShapeDtypeStruct((bh, lq, d), q.dtype)
    else:
        o_shape = jax.ShapeDtypeStruct((b, lq, h, d), q.dtype)
    bq, bk = _block_sizes(lq, lk)
    nq, nk = lq // bq, lk // bk
    prec = _prec_for(q.dtype)
    scale2 = _np.float32(scale) * _LOG2E
    smem_spec = pl.BlockSpec(memory_space=pltpu.SMEM)
    in_specs = [
        _tile_spec(layout, h, bq, d, 0),
        _tile_spec(layout, h, bk, d, 1),
        _tile_spec(layout, h, bk, d, 1),
        smem_spec,
    ]
    out_specs = [
        _tile_spec(layout, h, bq, d, 0),
        pl.BlockSpec((None, None, 8, bq),
                     lambda bh_, qi, ki: (bh_, qi, 0, 0)),
    ]
    out_shape = [
        o_shape,
        jax.ShapeDtypeStruct((bh, nq, 8, bq), jnp.float32),
    ]
    if nq == 1 and nk == 1 and layout == "bhld":
        # g heads per grid step; f32 score tile g*bq*bk*4 caps VMEM
        # f32 score tile gg*bq*bk*4 plus double-buffered operands must
        # fit the 16 MB VMEM scoped limit: g=8 at 512-blocks OOMs (18 MB)
        # and g=6 measures ~1% SLOWER than g=4 end-to-end (BERT-base,
        # PERF.md round 3) — pipelining beats raw occupancy here
        g = next(gg for gg in (4, 3, 2, 1)
                 if bh % gg == 0 and gg * bq * bk * 4 <= 4 << 20)
        kernel = functools.partial(
            _fwd_kernel_single_g, scale2=scale2, causal=causal,
            causal_offset=lk - lq, prec=prec, bq=bq, bk=bk,
            dropout=dropout, lq=lq, lk=lk)
        with _x32_mode():
            out, lse = pl.pallas_call(
                kernel,
                grid=(bh // g, 1, 1),
                in_specs=[
                    pl.BlockSpec((g, bq, d), lambda b, qi, ki: (b, qi, 0)),
                    pl.BlockSpec((g, bk, d), lambda b, qi, ki: (b, ki, 0)),
                    pl.BlockSpec((g, bk, d), lambda b, qi, ki: (b, ki, 0)),
                    smem_spec,
                ],
                out_specs=[
                    pl.BlockSpec((g, bq, d), lambda b, qi, ki: (b, qi, 0)),
                    pl.BlockSpec((g, None, 8, bq),
                                 lambda b, qi, ki: (b, qi, 0, 0)),
                ],
                out_shape=[
                    jax.ShapeDtypeStruct((bh, lq, d), q.dtype),
                    jax.ShapeDtypeStruct((bh, nq, 8, bq), jnp.float32),
                ],
                interpret=interpret,
            )(q, k, v, _seed_arr(seed))
        return out.reshape(b, h, lq, d), lse
    if nq == 1 and nk == 1:
        kernel = functools.partial(
            _fwd_kernel_single, scale2=scale2, causal=causal,
            causal_offset=lk - lq, prec=prec, bq=bq, bk=bk,
            dropout=dropout, lq=lq, lk=lk)
        scratch = []
    else:
        kernel = functools.partial(
            _fwd_kernel, scale2=scale2, causal=causal, nk=nk,
            causal_offset=lk - lq, prec=prec, bq=bq, bk=bk,
            dropout=dropout, lq=lq, lk=lk)
        scratch = [
            pltpu.VMEM((bq, d), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
        ]
    with _x32_mode():
        out, lse = pl.pallas_call(
            kernel,
            grid=(bh, nq, nk),
            in_specs=in_specs,
            out_specs=out_specs,
            out_shape=out_shape,
            scratch_shapes=scratch,
            interpret=interpret,
        )(q, k, v, _seed_arr(seed))
    if layout == "bhld":
        out = out.reshape(b, h, lq, d)
    return out, lse


def _bwd_dkdv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                     seed_ref, dk_ref, dv_ref, dk_acc, dv_acc, *,
                     scale, scale2, causal, nq, causal_offset, prec, bq, bk,
                     dropout, lq, lk):
    """dK/dV for one K block; Q blocks stream on the innermost grid dim.

    All score math is done TRANSPOSED — s_T = (BK, BQ) — so the per-row
    stats (lse, delta) broadcast from lane vectors (1, BQ) without any
    relayout, and dV/dK contractions take p_T/ds_T directly.
    """
    from jax.experimental import pallas as pl

    qi = pl.program_id(2)
    ki = pl.program_id(1)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    def compute():
        # native-dtype MXU operands (see _fwd_kernel note); f32
        # intermediates (p, ds) cast down before their dots
        q = q_ref[...]                                     # (BQ, D)
        k = k_ref[...]                                     # (BK, D)
        v = v_ref[...]
        do = do_ref[...]                                   # (BQ, D)
        lse = lse_ref[0:1, :]                               # (1, BQ)
        delta = delta_ref[0:1, :]                           # (1, BQ)
        s_t = jax.lax.dot_general(
            k, q, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=prec) * scale2
        if causal:
            q_pos = causal_offset + qi * bq + jax.lax.broadcasted_iota(
                jnp.int32, (bk, bq), 1)
            k_pos = ki * bk + jax.lax.broadcasted_iota(
                jnp.int32, (bk, bq), 0)
            s_t = jnp.where(k_pos <= q_pos, s_t, _NEG_INF32)
        p_t = jnp.exp2(s_t - lse)                            # (BK, BQ)
        if dropout > 0.0:
            # regenerate the forward's exact mask (same absolute ids,
            # transposed orientation); dV sees P_drop, dP gets the mask
            # before the softmax backward (dS = P ⊙ (dP - delta) — the
            # delta trick survives dropout unchanged, PERF.md round 5)
            keep_t = _drop_mask_2d(seed_ref, bq, bk, qi, ki, lq, lk,
                                   dropout, transposed=True)
            inv_keep = _np.float32(1.0 / (1.0 - dropout))
            pd_t = jnp.where(keep_t, p_t * inv_keep, _ZERO32)
        else:
            pd_t = p_t
        dv_acc[:] += jnp.dot(pd_t.astype(do.dtype), do,
                             preferred_element_type=jnp.float32,
                             precision=prec)
        dp_t = jax.lax.dot_general(
            v, do, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=prec)  # (BK, BQ)
        if dropout > 0.0:
            dp_t = jnp.where(keep_t, dp_t * inv_keep, _ZERO32)
        ds_t = p_t * (dp_t - delta) * scale
        dk_acc[:] += jnp.dot(ds_t.astype(q.dtype), q,
                             preferred_element_type=jnp.float32,
                             precision=prec)

    if causal:
        @pl.when(ki * bk <= causal_offset + qi * bq + bq - 1)
        def _():
            compute()
    else:
        compute()

    @pl.when(qi == nq - 1)
    def _final():
        dk_ref[...] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[...] = dv_acc[:].astype(dv_ref.dtype)


def _drop_mask_g_t(seed_ref, g, bq, bk, lq, lk, dropout):
    """(G, bk, bq) transposed keep-mask for the g-heads fused backward —
    bitwise identical to _drop_mask_g's forward mask."""
    from jax.experimental import pallas as pl

    head = (pl.program_id(0) * g + jax.lax.broadcasted_iota(
        jnp.int32, (g, bk, bq), 0))
    q_pos = jax.lax.broadcasted_iota(jnp.int32, (g, bk, bq), 2)
    k_pos = jax.lax.broadcasted_iota(jnp.int32, (g, bk, bq), 1)
    return _drop_mask(head, q_pos, k_pos, lq, lk, seed_ref[0],
                      dropout_thresh(dropout))


def _bwd_fused_kernel_g(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                        seed_ref, dq_ref, dk_ref, dv_ref, *, scale, scale2,
                        causal, causal_offset, prec, bq, bk, dropout,
                        lq, lk):
    """g-heads-per-step fused backward (refs (G, ., .)); see
    _bwd_fused_kernel for the math, _fwd_kernel_single_g for why."""
    q = q_ref[...]                                     # (G, BQ, D)
    k = k_ref[...]
    v = v_ref[...]
    do = do_ref[...]
    lse = lse_ref[:, 0:1, :]                           # (G, 1, BQ)
    delta = delta_ref[:, 0:1, :]
    s_t = jax.lax.dot_general(
        k, q, (((2,), (2,)), ((0,), (0,))),
        preferred_element_type=jnp.float32, precision=prec) * scale2
    if causal:
        g = q.shape[0]
        q_pos = causal_offset + jax.lax.broadcasted_iota(
            jnp.int32, (g, bk, bq), 2)
        k_pos = jax.lax.broadcasted_iota(jnp.int32, (g, bk, bq), 1)
        s_t = jnp.where(k_pos <= q_pos, s_t, _NEG_INF32)
    p_t = jnp.exp2(s_t - lse)                          # (G, BK, BQ)
    if dropout > 0.0:
        keep_t = _drop_mask_g_t(seed_ref, q.shape[0], bq, bk, lq, lk,
                                dropout)
        inv_keep = _np.float32(1.0 / (1.0 - dropout))
        pd_t = jnp.where(keep_t, p_t * inv_keep, _ZERO32)
    else:
        pd_t = p_t
    p_cast = pd_t.astype(do.dtype)
    dv_ref[...] = jax.lax.dot_general(
        p_cast, do, (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
        precision=prec).astype(dv_ref.dtype)
    dp_t = jax.lax.dot_general(
        v, do, (((2,), (2,)), ((0,), (0,))),
        preferred_element_type=jnp.float32, precision=prec)
    if dropout > 0.0:
        dp_t = jnp.where(keep_t, dp_t * inv_keep, _ZERO32)
    ds_t = (p_t * (dp_t - delta) * scale).astype(q.dtype)
    dk_ref[...] = jax.lax.dot_general(
        ds_t, q, (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
        precision=prec).astype(dk_ref.dtype)
    dq_ref[...] = jax.lax.dot_general(
        ds_t, k, (((1,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
        precision=prec).astype(dq_ref.dtype)


def _bwd_fused_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                      seed_ref, dq_ref, dk_ref, dv_ref, *, scale, scale2,
                      causal, causal_offset, prec, bq, bk, dropout, lq, lk):
    """Fused dQ/dK/dV for the single-block case (nq == nk == 1).

    The split dK/dV + dQ kernels each recompute the probability matrix —
    7 MXU matmuls and 2 VPU exp sweeps per head per step. When the whole
    head fits one (bq, bk) block there is nothing to stream, so one kernel
    can share the recompute: 5 matmuls and 1 exp. At BERT shapes the
    attention kernels are VPU(exp)-bound, so the saved exp sweep is the
    dominant win (measured: see PERF.md round-3 attention table).

    Score math transposed (s_t: (BK, BQ)) as in _bwd_dkdv_kernel so the
    per-row stats broadcast from lane vectors.
    """
    q = q_ref[...]                                     # (BQ, D)
    k = k_ref[...]                                     # (BK, D)
    v = v_ref[...]
    do = do_ref[...]                                   # (BQ, D)
    lse = lse_ref[0:1, :]                              # (1, BQ)
    delta = delta_ref[0:1, :]                          # (1, BQ)
    s_t = jax.lax.dot_general(
        k, q, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32, precision=prec) * scale2
    if causal:
        q_pos = causal_offset + jax.lax.broadcasted_iota(
            jnp.int32, (bk, bq), 1)
        k_pos = jax.lax.broadcasted_iota(jnp.int32, (bk, bq), 0)
        s_t = jnp.where(k_pos <= q_pos, s_t, _NEG_INF32)
    p_t = jnp.exp2(s_t - lse)                           # (BK, BQ) f32
    if dropout > 0.0:
        keep_t = _drop_mask_2d(seed_ref, bq, bk, 0, 0, lq, lk, dropout,
                               transposed=True)
        inv_keep = _np.float32(1.0 / (1.0 - dropout))
        pd_t = jnp.where(keep_t, p_t * inv_keep, _ZERO32)
    else:
        pd_t = p_t
    p_cast = pd_t.astype(do.dtype)
    dv_ref[...] = jnp.dot(p_cast, do,
                          preferred_element_type=jnp.float32,
                          precision=prec).astype(dv_ref.dtype)
    dp_t = jax.lax.dot_general(
        v, do, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32, precision=prec)  # (BK, BQ)
    if dropout > 0.0:
        dp_t = jnp.where(keep_t, dp_t * inv_keep, _ZERO32)
    ds_t = (p_t * (dp_t - delta) * scale).astype(q.dtype)
    dk_ref[...] = jnp.dot(ds_t, q,
                          preferred_element_type=jnp.float32,
                          precision=prec).astype(dk_ref.dtype)
    # dq = ds @ k = ds_t^T @ k : contract the BK dim of both
    dq_ref[...] = jax.lax.dot_general(
        ds_t, k, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=prec).astype(dq_ref.dtype)           # (BQ, D)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   seed_ref, dq_ref, dq_acc, *, scale, scale2, causal, nk,
                   causal_offset, prec, bq, bk, dropout, lq, lk):
    """dQ for one Q block; K blocks stream on the innermost grid dim."""
    from jax.experimental import pallas as pl

    ki = pl.program_id(2)
    qi = pl.program_id(1)

    @pl.when(ki == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    def compute():
        q = q_ref[...]
        k = k_ref[...]
        v = v_ref[...]
        do = do_ref[...]
        lse = lse_ref[0:1, :]                               # (1, BQ)
        delta = delta_ref[0:1, :]                           # (1, BQ)
        s_t = jax.lax.dot_general(
            k, q, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=prec) * scale2
        if causal:
            q_pos = causal_offset + qi * bq + jax.lax.broadcasted_iota(
                jnp.int32, (bk, bq), 1)
            k_pos = ki * bk + jax.lax.broadcasted_iota(
                jnp.int32, (bk, bq), 0)
            s_t = jnp.where(k_pos <= q_pos, s_t, _NEG_INF32)
        p_t = jnp.exp2(s_t - lse)
        dp_t = jax.lax.dot_general(
            v, do, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=prec)
        if dropout > 0.0:
            keep_t = _drop_mask_2d(seed_ref, bq, bk, qi, ki, lq, lk,
                                   dropout, transposed=True)
            dp_t = jnp.where(keep_t,
                             dp_t * _np.float32(1.0 / (1.0 - dropout)),
                             _ZERO32)
        ds_t = (p_t * (dp_t - delta) * scale)               # (BK, BQ)
        # dq = ds @ k = ds_t^T @ k : contract the BK dim of both
        dq_acc[:] += jax.lax.dot_general(
            ds_t.astype(k.dtype), k, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=prec)  # (BQ, D)

    if causal:
        @pl.when(ki * bk <= causal_offset + qi * bq + bq - 1)
        def _():
            compute()
    else:
        compute()

    @pl.when(ki == nk - 1)
    def _final():
        dq_ref[...] = dq_acc[:].astype(dq_ref.dtype)


def _flash_bwd_pallas(q, k, v, o, lse, g, scale, causal, interpret=False,
                      layout="bhld", delta=None, dropout=0.0, seed=None):
    """``delta``: optional precomputed rowsum(dO*O) of shape (B*H, Lq)
    f32 — ring attention passes the GLOBAL delta so per-pair calls don't
    recompute it; ``o`` may then be None."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, lq, d = _dims(q, layout)
    lk = _dims(k, layout)[2]
    bh = b * h
    if layout == "bhld":
        q = q.reshape(bh, lq, d)
        k = k.reshape(bh, lk, d)
        v = v.reshape(bh, lk, d)
        do = g.reshape(bh, lq, d)
        if delta is None:
            do_f32 = do.astype(jnp.float32)
            o_f32 = o.reshape(bh, lq, d).astype(jnp.float32)
        dq_shape = jax.ShapeDtypeStruct((bh, lq, d), q.dtype)
        dk_shape = jax.ShapeDtypeStruct((bh, lk, d), k.dtype)
        dv_shape = jax.ShapeDtypeStruct((bh, lk, d), v.dtype)
    else:
        do = g
        # (B, L, H, D) -> (BH, L) rowsums for delta
        if delta is None:
            do_f32 = g.astype(jnp.float32).transpose(0, 2, 1, 3).reshape(
                bh, lq, d)
            o_f32 = o.astype(jnp.float32).transpose(0, 2, 1, 3).reshape(
                bh, lq, d)
        dq_shape = jax.ShapeDtypeStruct((b, lq, h, d), q.dtype)
        dk_shape = jax.ShapeDtypeStruct((b, lk, h, d), k.dtype)
        dv_shape = jax.ShapeDtypeStruct((b, lk, h, d), v.dtype)
    bq, bk = _block_sizes(lq, lk)
    nq, nk = lq // bq, lk // bk
    # delta_i = rowsum(dO_i * O_i) — cheap, fused by XLA outside the
    # kernel; stored in the same sublane-padded layout as lse
    if delta is None:
        delta = jnp.sum(do_f32 * o_f32, axis=-1)
    delta = jnp.broadcast_to(delta.reshape(bh, nq, 1, bq),
                             (bh, nq, 8, bq))
    offset = lk - lq
    prec = _prec_for(q.dtype)
    smem_spec = pl.BlockSpec(memory_space=pltpu.SMEM)

    if nq == 1 and nk == 1 and layout == "bhld":
        # fused dq/dk/dv kernel, g heads per grid step (f32 score tiles
        # are the VMEM cap: ~3 live (G, BK, BQ) intermediates)
        grp = next(gg for gg in (2, 1)
                   if bh % gg == 0 and 3 * gg * bq * bk * 4 <= 7 << 20)
        gq_spec = pl.BlockSpec((grp, bq, d),
                               lambda b_, qi, ki: (b_, qi, 0))
        gk_spec = pl.BlockSpec((grp, bk, d),
                               lambda b_, qi, ki: (b_, ki, 0))
        grow_spec = pl.BlockSpec((grp, None, 8, bq),
                                 lambda b_, qi, ki: (b_, qi, 0, 0))
        with _x32_mode():
            dq, dk3, dv3 = pl.pallas_call(
                functools.partial(_bwd_fused_kernel_g, scale=scale,
                                  scale2=_np.float32(scale) * _LOG2E,
                                  causal=causal, causal_offset=offset,
                                  prec=prec, bq=bq, bk=bk,
                                  dropout=dropout, lq=lq, lk=lk),
                grid=(bh // grp, 1, 1),
                in_specs=[gq_spec, gk_spec, gk_spec, gq_spec,
                          grow_spec, grow_spec, smem_spec],
                out_specs=[gq_spec, gk_spec, gk_spec],
                out_shape=[dq_shape, dk_shape, dv_shape],
                interpret=interpret,
            )(q, k, v, do, lse, delta, _seed_arr(seed))
        return (dq.reshape(b, h, lq, d), dk3.reshape(b, h, lk, d),
                dv3.reshape(b, h, lk, d))
    if nq == 1 and nk == 1:
        # whole head in one block: fused dq/dk/dv kernel shares the p
        # recompute (5 matmuls + 1 exp instead of 7 + 2)
        q_spec = _tile_spec(layout, h, bq, d, 0)
        k_spec = _tile_spec(layout, h, bk, d, 1)
        row_spec = pl.BlockSpec((None, None, 8, bq),
                                lambda bh_, qi, ki: (bh_, qi, 0, 0))
        with _x32_mode():
            dq, dk3, dv3 = pl.pallas_call(
                functools.partial(_bwd_fused_kernel, scale=scale,
                                  scale2=_np.float32(scale) * _LOG2E,
                                  causal=causal, causal_offset=offset,
                                  prec=prec, bq=bq, bk=bk,
                                  dropout=dropout, lq=lq, lk=lk),
                grid=(bh, 1, 1),
                in_specs=[q_spec, k_spec, k_spec, q_spec,
                          row_spec, row_spec, smem_spec],
                out_specs=[q_spec, k_spec, k_spec],
                out_shape=[dq_shape, dk_shape, dv_shape],
                interpret=interpret,
            )(q, k, v, do, lse, delta, _seed_arr(seed))
        return dq, dk3, dv3

    # grid (bh, nk, nq): q/do/lse/delta stream on the inner (j) dim, so
    # their tiles index by grid dim 2 (seq_index=1) and K/V by dim 1
    q_spec_j = _tile_spec(layout, h, bq, d, 1)
    k_spec_i = _tile_spec(layout, h, bk, d, 0)
    row_spec_j = pl.BlockSpec((None, None, 8, bq),
                              lambda bh_, i, j: (bh_, j, 0, 0))
    with _x32_mode():
        dk3, dv3 = pl.pallas_call(
            functools.partial(_bwd_dkdv_kernel, scale=scale,
                              scale2=_np.float32(scale) * _LOG2E,
                              causal=causal, nq=nq, causal_offset=offset,
                              prec=prec, bq=bq, bk=bk,
                              dropout=dropout, lq=lq, lk=lk),
            grid=(bh, nk, nq),
            in_specs=[q_spec_j, k_spec_i, k_spec_i, q_spec_j,
                      row_spec_j, row_spec_j, smem_spec],
            out_specs=[k_spec_i, k_spec_i],
            out_shape=[dk_shape, dv_shape],
            scratch_shapes=[
                pltpu.VMEM((bk, d), jnp.float32),
                pltpu.VMEM((bk, d), jnp.float32),
            ],
            interpret=interpret,
        )(q, k, v, do, lse, delta, _seed_arr(seed))

        dq = pl.pallas_call(
            functools.partial(_bwd_dq_kernel, scale=scale,
                              scale2=_np.float32(scale) * _LOG2E,
                              causal=causal, nk=nk, causal_offset=offset,
                              prec=prec, bq=bq, bk=bk,
                              dropout=dropout, lq=lq, lk=lk),
            grid=(bh, nq, nk),
            in_specs=[
                _tile_spec(layout, h, bq, d, 0),
                _tile_spec(layout, h, bk, d, 1),
                _tile_spec(layout, h, bk, d, 1),
                _tile_spec(layout, h, bq, d, 0),
                pl.BlockSpec((None, None, 8, bq),
                             lambda bh_, i, j: (bh_, i, 0, 0)),
                pl.BlockSpec((None, None, 8, bq),
                             lambda bh_, i, j: (bh_, i, 0, 0)),
                smem_spec,
            ],
            out_specs=_tile_spec(layout, h, bq, d, 0),
            out_shape=dq_shape,
            scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
            interpret=interpret,
        )(q, k, v, do, lse, delta, _seed_arr(seed))
    if layout == "bhld":
        return (dq.reshape(b, h, lq, d), dk3.reshape(b, h, lk, d),
                dv3.reshape(b, h, lk, d))
    return dq, dk3, dv3


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _flash(q, k, v, seed, scale, causal, interpret, layout, dropout):
    return _flash_fwd_pallas(q, k, v, scale, causal, interpret, layout,
                             dropout, seed)[0]


def _flash_fwd(q, k, v, seed, scale, causal, interpret, layout, dropout):
    o, lse = _flash_fwd_pallas(q, k, v, scale, causal, interpret, layout,
                               dropout, seed)
    return o, (q, k, v, o, lse, seed)


def _flash_bwd(scale, causal, interpret, layout, dropout, res, g):
    # Pallas dq/dk/dv kernels recomputing p from the saved logsumexp —
    # training-mode attention runs on the MXU in BOTH directions (round-1
    # weakness #5: the old bwd re-differentiated the XLA scan). The
    # dropout mask is REGENERATED from (seed, positions) — nothing beyond
    # the (1,) seed crosses fwd->bwd.
    q, k, v, o, lse, seed = res
    dq, dk, dv = _flash_bwd_pallas(q, k, v, o, lse, g, scale, causal,
                                   interpret, layout, dropout=dropout,
                                   seed=seed)
    return dq, dk, dv, _np.zeros((1,), jax.dtypes.float0)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, scale=None, causal=False, interpret=False,
                    layout="bhld", dropout=0.0, seed=None):
    """Pallas flash attention (differentiable).

    ``layout``: "bhld" (B, H, L, D) — the classic attention layout — or
    "blhd" (B, L, H, D), the projection-native layout. blhd currently
    lowers only in interpret mode (tests / CPU oracle): Mosaic rejects the
    squeezed-H sublane tile — groundwork for a (B, L, H*D) 128-aligned
    view once a head_dim % 128 model needs it. On-hardware callers go
    through ``sdp_attention``, which gates on ``flash_supported``.

    ``dropout``: attention-probability drop rate (reference capability:
    GluonNLP MultiHeadAttentionCell's dropout on the attention weights).
    The keep-mask is a stateless position hash (see _drop_mask) applied
    to the post-softmax P inside the kernels, pre-PV-matmul; ``seed``
    (uint32 scalar/(1,) array, may be traced) selects the stream and
    MUST be supplied when dropout > 0.
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    dropout = float(dropout)
    if dropout > 0.0 and seed is None:
        raise ValueError("flash_attention: dropout > 0 requires a seed")
    return _flash(q, k, v, _seed_arr(seed), float(scale), bool(causal),
                  bool(interpret), str(layout), dropout)
