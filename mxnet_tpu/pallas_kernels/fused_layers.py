"""Fused transformer layer kernels: LayerNorm/RMSNorm + residual +
dropout, and the bias+GELU matmul epilogue.

Reference counterpart: MXNet's hand-fused transformer ops
(``src/operator/contrib/transformer.cc``) and the NVRTC runtime fusion
that welded bias/activation/residual epilogues onto the GEMMs. On TPU,
XLA fuses elementwise chains on its own but the BENCH r04/r05 batch-32
trace (PERF.md) shows the residue it leaves on the transformer step:
fusion epilogues re-reading the residual stream, RNG + bool mask traffic
for dropout, and bandwidth-bound LayerNorm sweeps. These kernels close
that gap the same way flash attention did for softmax:

* ``fused_layer_norm`` — ONE VMEM pass computing
  ``LN(dropout(x) + residual)``. The dropout keep-mask is the stateless
  position hash shared with the flash kernels (no RNG state, no mask
  tensor in HBM — regenerated bit-identically in the backward), and the
  ``jax.custom_vjp`` backward recomputes ``xhat`` from the saved per-row
  ``(mean, rstd)`` statistics — the same residual trick
  ``flash_attention.py`` uses for the logsumexp. Nothing but two f32
  row-vectors crosses forward->backward beyond the step's own inputs.
* ``fused_rms_norm`` — the same kernel family in RMS mode (no mean, no
  beta): the Llama-path norm, routed from ``ops/attention.py::rms_norm``.
* ``fused_bias_gelu`` — the Dense epilogue ``gelu(x + bias)`` (exact erf
  form, matching ``Activation(act_type='gelu')``); the backward
  recomputes the activation derivative from the (already-live) matmul
  output instead of saving erf/cdf intermediates.

Routing contract (mirrors ``flash_supported``): kernels engage only when
``MXNET_PALLAS_FUSED=1`` AND the executing platform is TPU AND the shape
gate passes; every caller falls back to the eager jnp composition
otherwise, and the *reference* implementations here double as the CPU
oracles for the bit-/tolerance-identity tests
(``tests/test_pallas_fused_layers.py``).
"""
from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as _np

from .flash_attention import (_hash_u16, _x32_mode, dropout_thresh,
                              fold_key_seed)

__all__ = [
    "fused_layer_norm", "fused_rms_norm", "fused_bias_gelu",
    "fused_layer_norm_reference", "fused_rms_norm_reference",
    "fused_bias_gelu_reference", "fused_layers_enabled",
    "fused_ln_shape_supported", "fused_ln_supported",
]

# VMEM comfort cap for one (rows, D) f32 tile; with ~4 live f32
# intermediates per row-block the backward stays well under the 16 MB
# scoped limit at 2 MB per operand tile
_TILE_BYTES = 2 << 20
_MAX_D = 8192
_INV_SQRT2 = _np.float32(0.7071067811865476)
_INV_SQRT2PI = _np.float32(0.3989422804014327)
_ONE32 = _np.float32(1.0)
_HALF32 = _np.float32(0.5)


def fused_layers_enabled() -> bool:
    """The routing knob: ``MXNET_PALLAS_FUSED=1`` opts the ops/nn.py and
    model-zoo seams into the fused-kernel dispatch (shape/platform gates
    still apply per call). Read per call so tests can toggle it."""
    return os.environ.get("MXNET_PALLAS_FUSED", "0") == "1"


def fused_ln_shape_supported(x) -> bool:
    """Platform-independent shape eligibility for the row kernels.

    Rows (product of leading dims) must tile into 8-sublane f32 blocks
    and the feature dim must be lane-aligned and VMEM-resident; anything
    else takes the eager path (which XLA handles fine at those sizes).
    """
    if x.ndim < 2:
        return False
    d = x.shape[-1]
    rows = 1
    for s in x.shape[:-1]:
        rows *= s
    return (d % 128 == 0 and d <= _MAX_D and rows % 8 == 0 and rows > 0)


def fused_ln_supported(x) -> bool:
    """Kernel eligibility: TPU execution platform + shape gate (the
    ``flash_supported`` twin for the layer kernels)."""
    from ..base import current_execution_platform

    if current_execution_platform(x) != "tpu":
        return False
    return fused_ln_shape_supported(x)


def _block_rows(rows: int, d: int) -> int:
    """Largest 8-multiple row-block whose f32 tile fits the VMEM cap."""
    cap = max(8, _TILE_BYTES // (d * 4))
    for br in (1024, 512, 256, 128, 64, 32, 16, 8):
        if br <= cap and rows % br == 0:
            return br
    return 8


def _seed_arr(seed):
    if seed is None:
        return jnp.zeros((1,), jnp.uint32)
    return jnp.asarray(seed, jnp.uint32).reshape((1,))


def _row_keep_mask(seed_ref, block_idx, br, d, dropout):
    """(br, d) keep-mask for a row block: the flash kernels' murmur
    finalizer over the element's absolute flat (row, col) id, so the
    backward regenerates the forward's exact bits from the (1,) seed."""
    base = (block_idx * br).astype(jnp.uint32)
    row = base + jax.lax.broadcasted_iota(jnp.uint32, (br, d), 0)
    col = jax.lax.broadcasted_iota(jnp.uint32, (br, d), 1)
    flat = row * _np.uint32(d) + col
    return _hash_u16(flat, seed_ref[0]) < dropout_thresh(dropout)


def _ref_keep_mask(shape2d, seed, dropout):
    """The oracle's mask over a flattened (rows, d) view — bitwise
    identical to the in-kernel mask."""
    rows, d = shape2d
    row = jax.lax.broadcasted_iota(jnp.uint32, (rows, d), 0)
    col = jax.lax.broadcasted_iota(jnp.uint32, (rows, d), 1)
    flat = row * _np.uint32(d) + col
    seed_u = jnp.asarray(seed, jnp.uint32).reshape(-1)[0]
    return _hash_u16(flat, seed_u) < dropout_thresh(dropout)


# ---------------------------------------------------------------------------
# reference implementations (eager fallback path AND the CPU oracle)
# ---------------------------------------------------------------------------


def _apply_ref_dropout(x, dropout, seed):
    if not dropout:
        return x
    rows = 1
    for s in x.shape[:-1]:
        rows *= s
    keep = _ref_keep_mask((rows, x.shape[-1]), seed, dropout).reshape(
        x.shape)
    inv_keep = jnp.asarray(1.0 / (1.0 - dropout), x.dtype)
    return jnp.where(keep, x * inv_keep, jnp.zeros_like(x))


def fused_layer_norm_reference(x, gamma, beta, residual=None, *, eps=1e-5,
                               dropout=0.0, seed=None):
    """Eager composition of ``LN(dropout(x) + residual)`` — the same
    math as ``ops/nn.py::layer_norm`` over the summed input, with the
    kernels' stateless-hash dropout so both paths draw identical masks
    for a given seed."""
    h = _apply_ref_dropout(x, float(dropout), seed)
    if residual is not None:
        h = h + residual
    h32 = h.astype(jnp.float32)
    mean = jnp.mean(h32, axis=-1, keepdims=True)
    var = jnp.var(h32, axis=-1, keepdims=True)
    inv = jax.lax.rsqrt(var + eps)
    out = (h32 - mean) * inv
    bshape = (1,) * (x.ndim - 1) + (x.shape[-1],)
    out = out * gamma.astype(jnp.float32).reshape(bshape) \
        + beta.astype(jnp.float32).reshape(bshape)
    return out.astype(x.dtype)


def fused_rms_norm_reference(x, weight, *, eps=1e-6):
    """Identical math to ``ops/attention.py::rms_norm``."""
    x32 = x.astype(jnp.float32)
    inv = jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps)
    return (x32 * inv).astype(x.dtype) * weight


def fused_bias_gelu_reference(x, bias):
    """Identical math to the eager Dense path: ``out + bias`` in the
    matmul dtype, then exact-erf GELU."""
    return jax.nn.gelu(x + bias.astype(x.dtype), approximate=False)


# ---------------------------------------------------------------------------
# pallas kernels
# ---------------------------------------------------------------------------


def _norm_fwd_kernel(*refs, eps, dropout, d, br, rms, has_res):
    """One row-block: h = dropout(x) + residual; out = norm(h).

    Writes the per-row statistics (mean, rstd — rstd only in RMS mode)
    as (8, br) sublane-broadcast f32 tiles, the backward's residuals.
    """
    from jax.experimental import pallas as pl

    it = iter(refs)
    x_ref = next(it)
    res_ref = next(it) if has_res else None
    g_ref = next(it)
    b_ref = None if rms else next(it)
    seed_ref = next(it)
    o_ref = next(it)
    mean_ref = None if rms else next(it)
    rstd_ref = next(it)

    h = x_ref[...].astype(jnp.float32)                    # (br, d)
    if dropout > 0.0:
        keep = _row_keep_mask(seed_ref, pl.program_id(0), br, d, dropout)
        h = jnp.where(keep, h * _np.float32(1.0 / (1.0 - dropout)),
                      _np.float32(0.0))
    if has_res:
        h = h + res_ref[...].astype(jnp.float32)
    if rms:
        var = jnp.mean(h * h, axis=-1, keepdims=True)
        rstd = jax.lax.rsqrt(var + _np.float32(eps))
        # eager parity (ops/attention.py::rms_norm): the normalized
        # value is rounded to the INPUT dtype before the weight multiply
        # — with f32 norm weights over bf16 activations the output
        # promotes to f32, and the rounding is observable
        xhat = (h * rstd).astype(x_ref.dtype).astype(jnp.float32)
        out = xhat * g_ref[...].astype(jnp.float32)
    else:
        mean = jnp.mean(h, axis=-1, keepdims=True)
        hc = h - mean
        var = jnp.mean(hc * hc, axis=-1, keepdims=True)
        rstd = jax.lax.rsqrt(var + _np.float32(eps))
        xhat = hc * rstd
        out = xhat * g_ref[...].astype(jnp.float32) \
            + b_ref[...].astype(jnp.float32)
        mean_ref[...] = jnp.broadcast_to(mean.reshape(1, br), (8, br))
    o_ref[...] = out.astype(o_ref.dtype)
    rstd_ref[...] = jnp.broadcast_to(rstd.reshape(1, br), (8, br))


def _norm_bwd_kernel(*refs, eps, dropout, d, br, rms, has_res):
    """Backward for one row-block, recomputing ``xhat`` from the saved
    (mean, rstd) row statistics — no activation tensor was saved.

    dgamma/dbeta contributions are emitted as per-block partial rows
    ((nb, d) outputs) and summed outside the kernel: the grid is
    embarrassingly row-parallel, and the (nb, d) partials are tiny next
    to the activations.
    """
    from jax.experimental import pallas as pl

    it = iter(refs)
    x_ref = next(it)
    res_ref = next(it) if has_res else None
    g_ref = next(it)
    mean_ref = None if rms else next(it)
    rstd_ref = next(it)
    dy_ref = next(it)
    seed_ref = next(it)
    dx_ref = next(it)
    dres_ref = next(it) if (has_res and dropout > 0.0) else None
    dg_ref = next(it)
    db_ref = None if rms else next(it)

    h = x_ref[...].astype(jnp.float32)
    if dropout > 0.0:
        keep = _row_keep_mask(seed_ref, pl.program_id(0), br, d, dropout)
        inv_keep = _np.float32(1.0 / (1.0 - dropout))
        h = jnp.where(keep, h * inv_keep, _np.float32(0.0))
    if has_res:
        h = h + res_ref[...].astype(jnp.float32)
    rstd = rstd_ref[0:1, :].reshape(br, 1)                # (br, 1)
    if rms:
        xhat = h * rstd
    else:
        mean = mean_ref[0:1, :].reshape(br, 1)
        xhat = (h - mean) * rstd
    dy = dy_ref[...].astype(jnp.float32)
    g32 = g_ref[...].astype(jnp.float32)                  # (1, d)
    wdy = dy * g32
    m2 = jnp.mean(wdy * xhat, axis=-1, keepdims=True)
    if rms:
        dh = rstd * (wdy - xhat * m2)
    else:
        m1 = jnp.mean(wdy, axis=-1, keepdims=True)
        dh = rstd * (wdy - m1 - xhat * m2)
        db_ref[...] = jnp.sum(dy, axis=0).reshape(1, d)
    dg_ref[...] = jnp.sum(dy * xhat, axis=0).reshape(1, d)
    if dropout > 0.0:
        dx = jnp.where(keep, dh * inv_keep, _np.float32(0.0))
    else:
        dx = dh
    dx_ref[...] = dx.astype(dx_ref.dtype)
    if dres_ref is not None:
        dres_ref[...] = dh.astype(dres_ref.dtype)


def _norm_fwd_pallas(x2, res2, gamma, beta, seed, eps, dropout, rms,
                     interpret):
    """x2/res2: (rows, d); gamma/beta: (1, d). Returns (out, mean, rstd)
    with stats shaped (nb, 8, br) f32 (mean is None in RMS mode)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    rows, d = x2.shape
    br = _block_rows(rows, d)
    nb = rows // br
    has_res = res2 is not None
    row_spec = pl.BlockSpec((br, d), lambda i: (i, 0))
    vec_spec = pl.BlockSpec((1, d), lambda i: (0, 0))
    stat_spec = pl.BlockSpec((None, 8, br), lambda i: (i, 0, 0))
    smem_spec = pl.BlockSpec(memory_space=pltpu.SMEM)
    in_specs = [row_spec] + ([row_spec] if has_res else []) + [vec_spec] \
        + ([] if rms else [vec_spec]) + [smem_spec]
    out_specs = [row_spec] + ([] if rms else [stat_spec]) + [stat_spec]
    # RMS mode promotes by the weight dtype, like the eager
    # `.astype(x.dtype) * weight` (f32 norm weights -> f32 output)
    out_dtype = jnp.result_type(x2.dtype, gamma.dtype) if rms else x2.dtype
    out_shape = [jax.ShapeDtypeStruct((rows, d), out_dtype)] \
        + ([] if rms else [jax.ShapeDtypeStruct((nb, 8, br), jnp.float32)]) \
        + [jax.ShapeDtypeStruct((nb, 8, br), jnp.float32)]
    args = [x2] + ([res2] if has_res else []) + [gamma] \
        + ([] if rms else [beta]) + [_seed_arr(seed)]
    kernel = functools.partial(_norm_fwd_kernel, eps=eps, dropout=dropout,
                               d=d, br=br, rms=rms, has_res=has_res)
    with _x32_mode():
        outs = pl.pallas_call(kernel, grid=(nb,), in_specs=in_specs,
                              out_specs=out_specs, out_shape=out_shape,
                              interpret=interpret)(*args)
    if rms:
        out, rstd = outs
        return out, None, rstd
    return outs


def _norm_bwd_pallas(x2, res2, gamma, mean, rstd, dy2, seed, eps, dropout,
                     rms, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    rows, d = x2.shape
    br = _block_rows(rows, d)
    nb = rows // br
    has_res = res2 is not None
    emit_dres = has_res and dropout > 0.0
    row_spec = pl.BlockSpec((br, d), lambda i: (i, 0))
    vec_spec = pl.BlockSpec((1, d), lambda i: (0, 0))
    stat_spec = pl.BlockSpec((None, 8, br), lambda i: (i, 0, 0))
    part_spec = pl.BlockSpec((1, d), lambda i: (i, 0))
    smem_spec = pl.BlockSpec(memory_space=pltpu.SMEM)
    in_specs = [row_spec] + ([row_spec] if has_res else []) + [vec_spec] \
        + ([] if rms else [stat_spec]) + [stat_spec, row_spec, smem_spec]
    out_specs = [row_spec] + ([row_spec] if emit_dres else []) \
        + [part_spec] + ([] if rms else [part_spec])
    out_shape = [jax.ShapeDtypeStruct((rows, d), x2.dtype)] \
        + ([jax.ShapeDtypeStruct((rows, d), x2.dtype)] if emit_dres
           else []) \
        + [jax.ShapeDtypeStruct((nb, d), jnp.float32)] \
        + ([] if rms else [jax.ShapeDtypeStruct((nb, d), jnp.float32)])
    args = [x2] + ([res2] if has_res else []) + [gamma] \
        + ([] if rms else [mean]) + [rstd, dy2, _seed_arr(seed)]
    kernel = functools.partial(_norm_bwd_kernel, eps=eps, dropout=dropout,
                               d=d, br=br, rms=rms, has_res=has_res)
    with _x32_mode():
        outs = pl.pallas_call(kernel, grid=(nb,), in_specs=in_specs,
                              out_specs=out_specs, out_shape=out_shape,
                              interpret=interpret)(*args)
    outs = list(outs)
    dx = outs.pop(0)
    dres = outs.pop(0) if emit_dres else (dx if has_res else None)
    dg_part = outs.pop(0)
    db_part = None if rms else outs.pop(0)
    dgamma = jnp.sum(dg_part, axis=0)
    dbeta = None if rms else jnp.sum(db_part, axis=0)
    return dx, dres, dgamma, dbeta


# -- layer norm with residual ------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7))
def _ln_res(x2, res2, gamma, beta, seed, eps, dropout, interpret):
    out, _, _ = _norm_fwd_pallas(x2, res2, gamma, beta, seed, eps,
                                 dropout, False, interpret)
    return out


def _ln_res_fwd(x2, res2, gamma, beta, seed, eps, dropout, interpret):
    out, mean, rstd = _norm_fwd_pallas(x2, res2, gamma, beta, seed, eps,
                                       dropout, False, interpret)
    return out, (x2, res2, gamma, mean, rstd, seed)


def _ln_res_bwd(eps, dropout, interpret, resids, dy):
    x2, res2, gamma, mean, rstd, seed = resids
    dx, dres, dgamma, dbeta = _norm_bwd_pallas(
        x2, res2, gamma, mean, rstd, dy, seed, eps, dropout, False,
        interpret)
    return (dx, dres.astype(res2.dtype),
            dgamma.reshape(gamma.shape).astype(gamma.dtype),
            dbeta.reshape(gamma.shape).astype(gamma.dtype),
            _np.zeros((1,), jax.dtypes.float0))


_ln_res.defvjp(_ln_res_fwd, _ln_res_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _ln_plain(x2, gamma, beta, seed, eps, dropout, interpret):
    out, _, _ = _norm_fwd_pallas(x2, None, gamma, beta, seed, eps,
                                 dropout, False, interpret)
    return out


def _ln_plain_fwd(x2, gamma, beta, seed, eps, dropout, interpret):
    out, mean, rstd = _norm_fwd_pallas(x2, None, gamma, beta, seed, eps,
                                       dropout, False, interpret)
    return out, (x2, gamma, mean, rstd, seed)


def _ln_plain_bwd(eps, dropout, interpret, resids, dy):
    x2, gamma, mean, rstd, seed = resids
    dx, _, dgamma, dbeta = _norm_bwd_pallas(
        x2, None, gamma, mean, rstd, dy, seed, eps, dropout, False,
        interpret)
    return (dx, dgamma.reshape(gamma.shape).astype(gamma.dtype),
            dbeta.reshape(gamma.shape).astype(gamma.dtype),
            _np.zeros((1,), jax.dtypes.float0))


_ln_plain.defvjp(_ln_plain_fwd, _ln_plain_bwd)


def fused_layer_norm(x, gamma, beta, residual=None, *, eps=1e-5,
                     dropout=0.0, seed=None, interpret=False):
    """Fused ``LayerNorm(dropout(x) + residual)`` over the last axis.

    ``gamma``/``beta``: (D,). ``residual``: same shape as ``x`` or None.
    ``dropout`` applies to ``x`` only (the post-LN transformer pattern:
    the block output is dropped, the skip connection is not); the mask
    is the stateless position hash seeded by ``seed`` (uint32, required
    when dropout > 0). Differentiable via ``jax.custom_vjp``: the
    backward kernel recomputes ``xhat`` from the saved per-row
    (mean, rstd) statistics.
    """
    dropout = float(dropout)
    if dropout > 0.0 and seed is None:
        raise ValueError("fused_layer_norm: dropout > 0 requires a seed")
    shape = x.shape
    d = shape[-1]
    rows = 1
    for s in shape[:-1]:
        rows *= s
    x2 = x.reshape(rows, d)
    g2 = gamma.reshape(1, d)
    b2 = beta.reshape(1, d)
    if residual is not None:
        out = _ln_res(x2, residual.reshape(rows, d), g2, b2,
                      _seed_arr(seed), float(eps), dropout,
                      bool(interpret))
    else:
        out = _ln_plain(x2, g2, b2, _seed_arr(seed), float(eps), dropout,
                        bool(interpret))
    return out.reshape(shape)


# -- rms norm ----------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _rms(x2, weight, eps, interpret):
    out, _, _ = _norm_fwd_pallas(x2, None, weight, None, None, eps, 0.0,
                                 True, interpret)
    return out


def _rms_fwd(x2, weight, eps, interpret):
    out, _, rstd = _norm_fwd_pallas(x2, None, weight, None, None, eps,
                                    0.0, True, interpret)
    return out, (x2, weight, rstd)


def _rms_bwd(eps, interpret, resids, dy):
    x2, weight, rstd = resids
    dx, _, dw, _ = _norm_bwd_pallas(x2, None, weight, None, rstd, dy,
                                    None, eps, 0.0, True, interpret)
    return dx, dw.reshape(weight.shape).astype(weight.dtype)


_rms.defvjp(_rms_fwd, _rms_bwd)


def fused_rms_norm(x, weight, *, eps=1e-6, interpret=False):
    """Fused RMSNorm over the last axis (the Llama-path norm); stats in
    f32, backward recomputes ``xhat`` from the saved rstd row-vector."""
    shape = x.shape
    d = shape[-1]
    rows = 1
    for s in shape[:-1]:
        rows *= s
    out = _rms(x.reshape(rows, d), weight.reshape(1, d), float(eps),
               bool(interpret))
    return out.reshape(shape)


# -- bias + gelu epilogue ----------------------------------------------------


def _bias_gelu_fwd_kernel(x_ref, b_ref, o_ref, *, d, br):
    u = x_ref[...].astype(jnp.float32) + b_ref[...].astype(jnp.float32)
    cdf = _HALF32 * (_ONE32 + jax.lax.erf(u * _INV_SQRT2))
    o_ref[...] = (u * cdf).astype(o_ref.dtype)


def _bias_gelu_bwd_kernel(x_ref, b_ref, dy_ref, dx_ref, db_ref, *, d, br):
    u = x_ref[...].astype(jnp.float32) + b_ref[...].astype(jnp.float32)
    cdf = _HALF32 * (_ONE32 + jax.lax.erf(u * _INV_SQRT2))
    pdf = jnp.exp(-_HALF32 * u * u) * _INV_SQRT2PI
    deriv = cdf + u * pdf
    dy = dy_ref[...].astype(jnp.float32)
    dx = dy * deriv
    dx_ref[...] = dx.astype(dx_ref.dtype)
    db_ref[...] = jnp.sum(dx, axis=0).reshape(1, d)


def _bias_gelu_pallas(x2, b2, interpret, backward_dy=None):
    from jax.experimental import pallas as pl

    rows, d = x2.shape
    br = _block_rows(rows, d)
    nb = rows // br
    row_spec = pl.BlockSpec((br, d), lambda i: (i, 0))
    vec_spec = pl.BlockSpec((1, d), lambda i: (0, 0))
    part_spec = pl.BlockSpec((1, d), lambda i: (i, 0))
    with _x32_mode():
        if backward_dy is None:
            return pl.pallas_call(
                functools.partial(_bias_gelu_fwd_kernel, d=d, br=br),
                grid=(nb,), in_specs=[row_spec, vec_spec],
                out_specs=row_spec,
                out_shape=jax.ShapeDtypeStruct((rows, d), x2.dtype),
                interpret=interpret)(x2, b2)
        dx, db_part = pl.pallas_call(
            functools.partial(_bias_gelu_bwd_kernel, d=d, br=br),
            grid=(nb,), in_specs=[row_spec, vec_spec, row_spec],
            out_specs=[row_spec, part_spec],
            out_shape=[jax.ShapeDtypeStruct((rows, d), x2.dtype),
                       jax.ShapeDtypeStruct((nb, d), jnp.float32)],
            interpret=interpret)(x2, b2, backward_dy)
    return dx, jnp.sum(db_part, axis=0)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _bias_gelu(x2, b2, interpret):
    return _bias_gelu_pallas(x2, b2, interpret)


def _bias_gelu_fwd(x2, b2, interpret):
    return _bias_gelu_pallas(x2, b2, interpret), (x2, b2)


def _bias_gelu_bwd(interpret, resids, dy):
    x2, b2 = resids
    dx, db = _bias_gelu_pallas(x2, b2, interpret, backward_dy=dy)
    return dx, db.reshape(b2.shape).astype(b2.dtype)


_bias_gelu.defvjp(_bias_gelu_fwd, _bias_gelu_bwd)


def fused_bias_gelu(x, bias, *, interpret=False):
    """Fused ``gelu(x + bias)`` (exact erf form) — the Dense matmul
    epilogue. ``bias``: (D,). The backward recomputes the activation
    derivative from (x, bias); no erf/cdf intermediate is saved."""
    shape = x.shape
    d = shape[-1]
    rows = 1
    for s in shape[:-1]:
        rows *= s
    out = _bias_gelu(x.reshape(rows, d), bias.reshape(1, d),
                     bool(interpret))
    return out.reshape(shape)
