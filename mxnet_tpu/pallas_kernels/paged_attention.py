"""Paged single-query (decode) attention kernel.

The decode step of an autoregressive request attends one query row
against every cached K/V token of that request, where the cache lives
in fixed-size pages of a shared arena (:mod:`mxnet_tpu.serving.kvcache`)
addressed through a per-request page table. The kernel is the
vLLM-style shape of that read: grid ``(batch, n_pages)``, the page
table scalar-prefetched so the BlockSpec index map steers each grid
step's DMA straight at the right arena page — no gather materializes,
no (batch, max_len) K/V copy exists, and VMEM holds one page of K and V
per step. Online softmax accumulates across the page axis exactly like
the flash kernels (f32 statistics, rescale-by-alpha per block).

Eligibility mirrors flash_attention: ``paged_supported`` gates on TPU
execution (``base.current_execution_platform``) plus Mosaic-friendly
shapes — head_dim a multiple of 128 and page_size a multiple of 8 (the
(sublane, lane) tile of an f32 page block). The eager gather in
``ops/attention.py`` (``_contrib_paged_attention``'s reference path) is
the bit-oracle; CPU tests run this kernel in ``interpret=True`` mode
against it.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

__all__ = ["paged_attention_kernel", "paged_supported",
           "paged_shape_supported"]


def paged_shape_supported(q, k_arena, page_size: int) -> bool:
    """Platform-independent shape eligibility: one query row per batch
    element, f32-tileable page blocks, and a head grouping the MXU can
    contract without relayout."""
    if q.ndim != 4 or q.shape[2] != 1:
        return False            # decode kernel: exactly one query row
    d = q.shape[-1]
    h = q.shape[1]
    kv = k_arena.shape[-2]
    if d % 128 or d != k_arena.shape[-1]:
        return False
    if page_size % 8 or k_arena.shape[0] % page_size:
        return False
    return h % kv == 0


def paged_supported(q, k_arena, page_size: int) -> bool:
    """TPU execution + shape eligibility (same contract as
    ``flash_supported``: platform comes from the framework's jit entry
    points, so a CPU-context op never takes the kernel path)."""
    from ..base import current_execution_platform

    if current_execution_platform(q) != "tpu":
        return False
    return paged_shape_supported(q, k_arena, page_size)


def _decode_kernel(pt_ref, len_ref, q_ref, k_ref, v_ref, o_ref,
                   acc_ref, stat_ref, *, scale, page_size, n_pages_req,
                   h, kv, d):
    """One (batch row, page) grid step: score the query heads against
    this page's keys, fold into the online-softmax accumulator, emit on
    the last page."""
    from jax.experimental import pallas as pl

    b = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        stat_ref[0, :] = jnp.full((h,), -jnp.inf, jnp.float32)
        stat_ref[1, :] = jnp.zeros((h,), jnp.float32)

    q = q_ref[0].astype(jnp.float32) * scale        # (H, D)
    k = k_ref[...].astype(jnp.float32)              # (ps, KV, D)
    v = v_ref[...].astype(jnp.float32)
    rep = h // kv
    # GQA without materializing repeated keys: group q rows per kv head
    qg = q.reshape(kv, rep, d)
    s = jax.lax.dot_general(qg, k,
                            (((2,), (2,)), ((0,), (1,))))  # (KV, rep, ps)
    s = s.reshape(h, page_size)
    pos = j * page_size + jax.lax.broadcasted_iota(
        jnp.int32, (h, page_size), 1)
    valid = pos < len_ref[b]
    s = jnp.where(valid, s, -jnp.inf)

    m_prev = stat_ref[0, :]
    l_prev = stat_ref[1, :]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=1))
    # a fully-masked page (tail pages of a short request) keeps m at
    # -inf; exp(-inf - -inf) would be NaN — pin the rescale to 0/1
    alpha = jnp.where(jnp.isfinite(m_prev), jnp.exp(m_prev - m_new), 0.0)
    alpha = jnp.where(jnp.isfinite(m_new), alpha, 1.0)
    p = jnp.where(valid, jnp.exp(s - m_new[:, None]), 0.0)  # (H, ps)
    stat_ref[0, :] = m_new
    stat_ref[1, :] = l_prev * alpha + jnp.sum(p, axis=1)
    pv = jax.lax.dot_general(p.reshape(kv, rep, page_size), v,
                             (((2,), (0,)), ((0,), (1,))))  # (KV, rep, D)
    acc_ref[...] = acc_ref[...] * alpha[:, None] + pv.reshape(h, d)

    @pl.when(j == n_pages_req - 1)
    def _emit():
        l = stat_ref[1, :]
        l = jnp.where(l == 0.0, 1.0, l)     # padding row: all-masked
        o_ref[0] = (acc_ref[...] / l[:, None]).astype(o_ref.dtype)


def paged_attention_kernel(q, k_arena, v_arena, page_table, lengths, *,
                           page_size: int, scale: float,
                           interpret: bool = False):
    """Decode attention over paged K/V.

    ``q``: (B, H, 1, D); ``k_arena``/``v_arena``: (slots, KV, D) — ONE
    layer's arena; ``page_table``: (B, P) int32 page ids (scratch page 0
    pads the tail); ``lengths``: (B,) int32 valid tokens per row.
    Returns (B, H, 1, D) in q's dtype.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, _, d = q.shape
    kv = k_arena.shape[-2]
    n_pages_req = page_table.shape[1]
    q3 = q.reshape(b, h, d)
    kernel = functools.partial(
        _decode_kernel, scale=float(scale), page_size=int(page_size),
        n_pages_req=int(n_pages_req), h=h, kv=kv, d=d)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, n_pages_req),
        in_specs=[
            pl.BlockSpec((1, h, d), lambda bi, j, pt, ln: (bi, 0, 0)),
            # the scalar-prefetched page table steers each step's DMA:
            # block index IS the page id (block size = one page)
            pl.BlockSpec((page_size, kv, d),
                         lambda bi, j, pt, ln: (pt[bi, j], 0, 0)),
            pl.BlockSpec((page_size, kv, d),
                         lambda bi, j, pt, ln: (pt[bi, j], 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, h, d), lambda bi, j, pt, ln: (bi, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((h, d), jnp.float32),
            pltpu.VMEM((2, h), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, d), q.dtype),
        interpret=interpret,
    )(page_table.astype(jnp.int32), lengths.astype(jnp.int32),
      q3, k_arena, v_arena)
    return out.reshape(b, h, 1, d)
