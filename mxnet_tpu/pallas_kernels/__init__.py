"""Pallas TPU kernels — the hand-written hot path.

Reference counterpart: MXNet's fused CUDA kernels
(`src/operator/contrib/transformer.cc`, `src/operator/fusion/`) and NVRTC
runtime fusion. On TPU, XLA already fuses elementwise chains; what pays here
is flash attention (O(L) memory softmax-attention streaming K/V blocks
through VMEM) — the enabler for long sequences — plus the `mx.pallas`
user-kernel surface (the `mx.rtc.CudaModule` capability re-imagined,
see mxnet_tpu.pallas_api).
"""
from .flash_attention import (flash_attention, flash_attention_scan,
                              flash_supported, flash_shape_supported)
from .fused_layers import (fused_bias_gelu, fused_layer_norm,
                           fused_layers_enabled, fused_ln_shape_supported,
                           fused_ln_supported, fused_rms_norm)
from .fused_optimizer import (fused_opt_enabled, fused_opt_supported,
                              sweep_pallas)
from .paged_attention import (paged_attention_kernel,
                              paged_shape_supported, paged_supported)

__all__ = ["flash_attention", "flash_attention_scan", "flash_supported",
           "flash_shape_supported", "fused_layer_norm", "fused_rms_norm",
           "fused_bias_gelu", "fused_layers_enabled",
           "fused_ln_shape_supported", "fused_ln_supported",
           "fused_opt_enabled", "fused_opt_supported", "sweep_pallas",
           "paged_attention_kernel", "paged_shape_supported",
           "paged_supported"]
