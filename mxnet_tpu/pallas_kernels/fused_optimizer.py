"""Fused multi-tensor optimizer sweep kernel.

Reference counterpart: MXNet's horizontally-fused ``multi_sgd_update`` /
``multi_mp_sgd_mom_update`` kernels (``src/operator/optimizer_op.cc``) —
one launch updating a whole parameter list. Here the bucket's
(param, grad, state) leaves arrive PRE-PACKED into flat buffers
(``optimizer/multi_tensor.py``) and the kernel is a single VMEM
elementwise pass over them: each (block, 128) tile of every operand is
read once, the family formula runs on the VPU in f32, and each output
tile is written once — no per-parameter kernel launches, no HBM
round-trips between the Adam moments.

The kernel body CALLS the same formula function as the pure-``lax``
fallback (``multi_tensor._adam_elem`` et al.), so the two paths are
bit-identical by construction; what the kernel adds on TPU is explicit
tiling (one fused loop regardless of how XLA would have scheduled the
unpacked update) — the same contract as ``fused_layers.py``.

Routing (mirrors ``fused_ln_supported``): ``MXNET_PALLAS_FUSED=1`` AND
the execution platform is TPU; every caller falls back to the identical
jnp composition otherwise. Non-elementwise residue (LAMB's trust-ratio
norms, AdamW's per-param overflow scan) is reduced OUTSIDE the kernel on
the packed buffer and re-enters as a per-element vector.
"""
from __future__ import annotations

import os

import numpy as _np

from .flash_attention import _x32_mode

__all__ = ["fused_opt_enabled", "fused_opt_supported", "sweep_pallas"]

# flat buffers are padded to a whole number of (sublane, 128) tiles; 32
# sublanes covers the f32/bf16/int8 minimum-tile table in one granule
_GRANULE = 32 * 128
# VMEM comfort cap per operand tile (same budget as fused_layers)
_TILE_BYTES = 2 << 20


def fused_opt_enabled() -> bool:
    """Same knob family as the layer kernels: ``MXNET_PALLAS_FUSED=1``
    opts the packed optimizer sweep into the Pallas kernel (platform
    gate still applies per call). Read per call so tests can toggle."""
    return os.environ.get("MXNET_PALLAS_FUSED", "0") == "1"


def fused_opt_supported(platform) -> bool:
    """Kernel eligibility for a sweep lowered for ``platform``. The
    packed layout is padded inside :func:`sweep_pallas`, so unlike the
    row kernels there is no shape gate — any bucket size qualifies."""
    return fused_opt_enabled() and platform == "tpu"


def _block_rows(rows: int, width_bytes: int) -> int:
    """Largest 32-multiple row block whose widest operand tile fits the
    VMEM cap (32 keeps every dtype's sublane minimum satisfied)."""
    cap = max(32, _TILE_BYTES // max(width_bytes, 1))
    for br in (1024, 512, 256, 128, 64, 32):
        if br <= cap and rows % br == 0:
            return br
    return 32


def sweep_pallas(fn, static, flats, vec_el, scalars, out_specs,
                 interpret=False):
    """Run one elementwise sweep stage as a Pallas kernel.

    ``fn(env, static)``: the shared formula — sees each flat operand and
    per-element vector as a (block, 128) f32-or-original-dtype tile and
    each scalar as a 0-d value; returns a dict of output arrays.
    ``flats`` / ``vec_el``: name -> (L,) arrays (equal lengths);
    ``scalars``: name -> 0-d values; ``out_specs``: ordered
    ``(name, dtype)`` outputs. Returns name -> (L,) arrays.
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    flat_names = sorted(flats)
    vec_names = sorted(vec_el)
    scalar_names = sorted(scalars)
    L = int(flats[flat_names[0]].shape[0])
    Lp = ((L + _GRANULE - 1) // _GRANULE) * _GRANULE
    rows = Lp // 128
    width = max(_np.dtype(flats[n].dtype).itemsize * 128
                for n in flat_names)
    br = _block_rows(rows, width)
    nb = rows // br

    def to2d(a):
        if Lp != L:
            # zero padding is formula-safe: every family's math maps the
            # all-zeros element to a finite value (eps guards the
            # divisions), and the pad region is sliced off below
            a = jnp.pad(a, (0, Lp - L))
        return a.reshape(rows, 128)

    args = [to2d(flats[n]) for n in flat_names]
    args += [to2d(vec_el[n]) for n in vec_names]
    args += [jnp.asarray(scalars[n], jnp.float32).reshape((1,))
             for n in scalar_names]

    row_spec = pl.BlockSpec((br, 128), lambda i: (i, 0))
    smem_spec = pl.BlockSpec(memory_space=pltpu.SMEM)
    in_specs = [row_spec] * (len(flat_names) + len(vec_names)) \
        + [smem_spec] * len(scalar_names)
    out_shape = [jax.ShapeDtypeStruct((rows, 128), dtype)
                 for _, dtype in out_specs]

    def kernel(*refs):
        it = iter(refs)
        env = {}
        for name in flat_names:
            env[name] = next(it)[...]
        for name in vec_names:
            env[name] = next(it)[...]
        for name in scalar_names:
            env[name] = next(it)[0]
        outs = fn(env, static)
        for name, _ in out_specs:
            o_ref = next(it)
            o_ref[...] = outs[name].astype(o_ref.dtype)

    with _x32_mode():
        results = pl.pallas_call(
            kernel, grid=(nb,), in_specs=in_specs,
            out_specs=[row_spec] * len(out_specs), out_shape=out_shape,
            interpret=interpret)(*args)
    if not isinstance(results, (list, tuple)):
        results = (results,)
    return {name: r.reshape(-1)[:L]
            for (name, _), r in zip(out_specs, results)}
