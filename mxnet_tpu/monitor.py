"""``mx.monitor.Monitor`` — debugging stat collection (reference:
``python/mxnet/monitor.py``).

The reference installs a per-op output hook on every executor
(``MXExecutorSetMonitorCallback``) and prints ``stat_func`` of each
intermediate array every ``interval`` batches. Under XLA the graph is one
fused executable, so per-internal-op outputs don't exist to hook; the
TPU-native Monitor instead snapshots everything that IS materialized at the
step boundary — arguments (weights), gradients, auxiliary states, and
outputs of each installed executor — which covers the dominant uses
(exploding/vanishing grad & weight norms). Name filtering (``pattern``),
``interval``, ``tic/toc/toc_print`` and the ``(step, name, stat)`` result
triples match the reference API.
"""
from __future__ import annotations

import logging
import re

import numpy as np

__all__ = ["Monitor"]


class Monitor:
    def __init__(self, interval, stat_func=None, pattern=".*", sort=False):
        if stat_func is None:
            def stat_func(x):
                # reference default: mean(abs(x))
                return np.abs(x).mean()
        self.stat_func = stat_func
        self.interval = interval
        self.activated = False
        self.queue = []
        self.step = 0
        self.exes = []
        self.re_pattern = re.compile(pattern)
        self.sort = sort

    def install(self, exe):
        """Register an executor whose arrays are snapshotted at toc()."""
        if exe not in self.exes:
            self.exes.append(exe)
        return exe

    def tic(self):
        """Start collecting for this batch if the interval hits."""
        if self.step % self.interval == 0:
            self.queue = []
            self.activated = True
        self.step += 1

    def _collect(self, name, arr):
        if arr is None or not self.re_pattern.match(name):
            return
        try:
            val = self.stat_func(arr.asnumpy())
        except Exception as e:  # stat on a weird dtype/shape — keep going
            val = f"<stat failed: {e}>"
        self.queue.append((self.step, name, val))

    def toc(self):
        """Collect stats from installed executors; returns result triples."""
        if not self.activated:
            return []
        for exe in self.exes:
            for name, arr in getattr(exe, "arg_dict", {}).items():
                self._collect(name, arr)
            for name, arr in getattr(exe, "aux_dict", {}).items():
                self._collect(name, arr)
            grad_dict = getattr(exe, "grad_dict", {}) or {}
            for name, arr in grad_dict.items():
                self._collect(name + "_grad", arr)
            for i, arr in enumerate(getattr(exe, "outputs", []) or []):
                self._collect(f"output{i}", arr)
        self.activated = False
        res = list(self.queue)
        if self.sort:
            res.sort(key=lambda t: t[1])
        self.queue = []
        return res

    def toc_print(self):
        """Collect and log (reference: Monitor.toc_print)."""
        for step, name, value in self.toc():
            logging.info("Batch: %7d %30s %s", step, name, value)
