"""``mx.checkpoint`` — crash-safe checkpointing.

The reference's ``model.py::save_checkpoint`` artifacts are the resume
contract for every production training run, but the reference (and our
seed) writes them with a bare ``open(...).write`` — a process killed
mid-write leaves a truncated file that *looks* like a checkpoint until
resume explodes hours later. This module makes every checkpoint write
crash-safe and every resume verifiable:

* :func:`atomic_write` — the one file-commit primitive: temp file in the
  destination directory, flush + ``fsync``, ``os.replace`` (atomic on
  POSIX), then a best-effort directory fsync. Either the old bytes or
  the new bytes exist — never a torn file. ``Block.save_parameters``,
  ``Trainer.save_states``, ``KVStore.save_optimizer_states``,
  ``Module.save_checkpoint`` and the ``.params`` serializer all commit
  through it (fault site ``checkpoint.write``).

* :class:`CheckpointManager` — manifest-tracked bundles. One checkpoint
  is a directory ``{prefix}-{step:08d}/`` holding ``params.params``
  (standard ``.params`` serialization — loadable by
  ``Block.load_parameters`` directly), ``trainer.states`` (the
  ``Trainer.save_states`` pickle), ``rng.pkl``
  (``random_state.checkpoint_state()`` — bit-exact resume needs the RNG
  stream, not just weights), ``meta.json`` (step/epoch/user extras) and
  a ``MANIFEST.json`` written **last** with the sha256 of every payload
  file. The bundle is staged in a temp directory and committed with one
  ``os.replace`` — a checkpoint without a checksum-valid manifest never
  existed. Resume discovers the **newest valid** bundle, skipping
  corrupt/partial ones, and retention keeps the last K.

Telemetry: ``mxnet_checkpoint_write_seconds``. Fault sites:
``checkpoint.write`` (every atomic commit), ``checkpoint.read`` (every
manifest/payload read) — see ``mxnet_tpu/fault.py``.
"""
from __future__ import annotations

import hashlib
import json
import os
import pickle
import re
import shutil
import tempfile
import time
from typing import Dict, List, Optional

from . import fault, telemetry
from .base import MXNetError
from .fault import _state as _fault_state

__all__ = ["atomic_write", "read_state_bytes", "apply_state_bytes",
           "CheckpointManager", "MANIFEST_NAME", "FORMAT_VERSION"]

MANIFEST_NAME = "MANIFEST.json"
FORMAT_VERSION = 1

_PARAMS_FILE = "params.params"
_STATES_FILE = "trainer.states"
_RNG_FILE = "rng.pkl"
_META_FILE = "meta.json"
# partition-plan manifest of a ZeRO-sharded trainer.states: names the
# mode / world size / rank / bucket layout so rejoin tooling can decide
# which rank bundles to gather BEFORE unpickling any tensor payload
_ZERO_FILE = "zero.json"


def _fsync_dir(path: str) -> None:
    """fsync a directory so a rename into it survives power loss.
    Best-effort: not all filesystems allow opening directories."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def atomic_write(path: str, data: bytes) -> None:
    """Commit ``data`` to ``path`` atomically: temp file in the same
    directory + flush + fsync + ``os.replace`` + directory fsync.
    Readers see the old content or the new content, never a torn file.
    Fault site ``checkpoint.write`` fires before any byte is written, so
    an injected crash leaves the previous content untouched."""
    if _fault_state.enabled:
        fault.check("checkpoint.write", path)
    path = os.fspath(path)
    d = os.path.dirname(os.path.abspath(path))
    fd, tmp = tempfile.mkstemp(dir=d, prefix=os.path.basename(path) + ".",
                               suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    _fsync_dir(d)


def read_state_bytes(fname: str, context: str) -> bytes:
    """Read an optimizer-state file, surfacing failures as
    :class:`MXNetError` naming the file (the shared error contract of
    ``Trainer.load_states``, ``KVStore.load_optimizer_states`` and
    ``Module.load`` — one implementation, not three copies)."""
    try:
        with open(fname, "rb") as f:
            return f.read()
    except OSError as e:
        raise MXNetError(
            f"{context}: cannot read optimizer state file {fname!r}: "
            f"{e}") from e


def apply_state_bytes(states: bytes, apply, fname: str,
                      context: str) -> None:
    """Run ``apply(states)`` (an ``Updater.set_states``-like consumer),
    wrapping corrupt-payload failures in :class:`MXNetError` naming the
    file instead of leaking a pickle traceback. An ``MXNetError`` raised
    by the consumer is already a first-class, contextualized diagnosis
    (e.g. a compression-config mismatch on a well-formed file) and
    passes through unwrapped — re-labelling it 'corrupt' would bury the
    real cause."""
    try:
        apply(states)
    except MXNetError:
        raise
    except Exception as e:
        raise MXNetError(
            f"{context}: {fname!r} is not a valid optimizer state file "
            f"(corrupt or wrong format): {e}") from e


def _sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


class CheckpointManager:
    """Crash-safe, manifest-tracked, last-K checkpoint bundles.

    ::

        mgr = mx.checkpoint.CheckpointManager("ckpts", keep_last=3)
        for step, batch in enumerate(loader):
            ...
            if step % 100 == 0:
                mgr.save(step, params=net, trainer=trainer, epoch=epoch)

        # after a crash, in a fresh process:
        meta = mgr.restore(block=net, trainer=trainer)   # newest valid
        start = meta["step"] + 1      # params + optimizer + RNG restored

    ``save`` stages the bundle in a temp directory and commits it with
    one ``os.replace``; a SIGKILL at ANY point leaves the previous
    checkpoint the newest valid one. Re-saving an existing step replaces
    it. Retention removes all but the newest ``keep_last`` valid bundles
    (and invalid debris older than the newest valid).
    """

    def __init__(self, directory: str, prefix: str = "ckpt",
                 keep_last: int = 3):
        if keep_last < 1:
            raise MXNetError(
                f"keep_last must be >= 1, got {keep_last}")
        if not re.fullmatch(r"[A-Za-z0-9._-]+", prefix):
            raise MXNetError(
                f"checkpoint prefix {prefix!r} must be filename-safe "
                "([A-Za-z0-9._-])")
        self.directory = os.fspath(directory)
        self.prefix = prefix
        self.keep_last = int(keep_last)
        self._pat = re.compile(re.escape(prefix) + r"-(\d{8})$")
        # poll_newest change-detection state, keyed by caller tag
        self._poll_state: Dict[str, Dict] = {}
        os.makedirs(self.directory, exist_ok=True)

    # -- naming --------------------------------------------------------
    def _name(self, step: int) -> str:
        return f"{self.prefix}-{int(step):08d}"

    def path(self, step: int) -> str:
        """Bundle directory for ``step`` (whether or not it exists)."""
        return os.path.join(self.directory, self._name(step))

    def _scan(self) -> List[int]:
        """All steps with a bundle directory present (validity unchecked),
        newest first."""
        steps = []
        try:
            entries = os.listdir(self.directory)
        except OSError:
            return []
        for e in entries:
            m = self._pat.fullmatch(e)
            if m and os.path.isdir(os.path.join(self.directory, e)):
                steps.append(int(m.group(1)))
        return sorted(steps, reverse=True)

    # -- validation ----------------------------------------------------
    def _read_manifest(self, step: int) -> Optional[Dict]:
        p = os.path.join(self.path(step), MANIFEST_NAME)
        if _fault_state.enabled:
            fault.check("checkpoint.read", p)
        try:
            with open(p, "rb") as f:
                return json.loads(f.read().decode("utf-8"))
        except (OSError, ValueError, UnicodeDecodeError):
            return None

    def is_valid(self, step: int) -> bool:
        """True iff the bundle's manifest exists and every payload file
        matches its recorded sha256 and size."""
        man = self._read_manifest(step)
        if not isinstance(man, dict) or "files" not in man:
            return False
        root = self.path(step)
        for fname, rec in man["files"].items():
            fp = os.path.join(root, fname)
            try:
                if os.path.getsize(fp) != rec["bytes"]:
                    return False
                if _sha256_file(fp) != rec["sha256"]:
                    return False
            except (OSError, KeyError, TypeError):
                return False
        return True

    def steps(self) -> List[int]:
        """Checksum-valid checkpoint steps, newest first."""
        return [s for s in self._scan() if self.is_valid(s)]

    def latest_step(self) -> Optional[int]:
        """Newest checksum-valid step, or None. Corrupt/partial bundles
        are skipped, not fatal — that is the whole point."""
        for s in self._scan():
            if self.is_valid(s):
                return s
        return None

    def _manifest_sig(self, step: int) -> Optional[tuple]:
        """Cheap identity of a bundle's commit record: one stat() of its
        manifest. The manifest is always written last and atomically, so
        (step, mtime_ns, size) changing is necessary AND sufficient for
        the bundle's content having changed."""
        try:
            st = os.stat(os.path.join(self.path(step), MANIFEST_NAME))
        except OSError:
            return None
        return (step, st.st_mtime_ns, st.st_size)

    def poll_newest(self, tag: str = "default") -> Optional[int]:
        """Return the newest valid step IFF it changed since the last
        poll with this ``tag``; None when nothing new (including "still
        no checkpoint"). The hot-reload watcher's tick primitive: the
        no-change path is one ``listdir`` + one ``stat`` — full manifest
        re-hashing (:meth:`is_valid` over every payload file) only runs
        when a bundle's commit record actually moved. Each ``tag`` keeps
        independent state, so several watchers can share one manager.
        The first poll with a tag reports an existing checkpoint as a
        change; prime the tag with one discarded poll to watch for
        *subsequent* checkpoints only."""
        committed = [s for s in self._scan() if self._has_manifest(s)]
        commit_sig = self._manifest_sig(committed[0]) if committed else None
        prev = self._poll_state.get(tag)
        if prev is not None and prev["commit_sig"] == commit_sig:
            return None
        # the newest committed bundle moved (or first poll): pay one full
        # validation pass to find the newest VALID step
        step = self.latest_step()
        valid_sig = self._manifest_sig(step) if step is not None else None
        changed = (prev is None or step != prev["valid_step"]
                   or valid_sig != prev["valid_sig"])
        self._poll_state[tag] = {"commit_sig": commit_sig,
                                 "valid_step": step,
                                 "valid_sig": valid_sig}
        return step if (changed and step is not None) else None

    def poll_reset(self, tag: str = "default") -> None:
        """Forget ``tag``'s poll state: the next :meth:`poll_newest`
        reports the newest valid bundle again. A consumer that FAILED to
        act on a reported change calls this so the change is re-offered
        next tick instead of being lost until a newer bundle lands."""
        self._poll_state.pop(tag, None)

    # -- write ---------------------------------------------------------
    def _param_payload(self, params) -> Dict:
        """Normalize ``params`` (Block | dict of Parameter/NDArray) into
        a name->NDArray dict on cpu(0) for serialization."""
        from .context import cpu
        from .gluon.parameter import Parameter

        if hasattr(params, "_collect_params_with_prefix"):
            params = params._collect_params_with_prefix()
        if not isinstance(params, dict):
            raise MXNetError(
                "CheckpointManager.save params must be a Block or a dict "
                f"of Parameter/NDArray, got {type(params)}")
        out = {}
        for name, v in params.items():
            if isinstance(v, Parameter):
                v = v.data()
            out[name] = v.as_in_context(cpu(0))
        return out

    # staging dirs younger than this are presumed to belong to a LIVE
    # writer sharing the directory and are left alone (the same guard
    # _gc applies to committed debris); older ones are crash leftovers
    _STAGING_SWEEP_AGE_S = 3600.0

    def _clean_tmp(self) -> None:
        """Remove staging leftovers from crashed writers (best-effort).
        Age-gated: a fresh staging dir may be another writer's in-flight
        bundle — sweeping it would make that writer's save fail
        spuriously mid-write."""
        try:
            entries = os.listdir(self.directory)
        except OSError:
            return
        now = time.time()
        for e in entries:
            if e.startswith("." + self.prefix + "-") and ".staging-" in e:
                p = os.path.join(self.directory, e)
                try:
                    age = now - os.path.getmtime(p)
                except OSError:
                    continue
                if age > self._STAGING_SWEEP_AGE_S:
                    shutil.rmtree(p, ignore_errors=True)

    def save(self, step: int, params=None, trainer=None, epoch=None,
             extra=None) -> str:
        """Write + commit one bundle; returns the committed path.

        ``params``: Block or name->NDArray/Parameter dict.
        ``trainer``: a Gluon Trainer whose updater states go into
        ``trainer.states`` (via ``Trainer.save_states``). The RNG stream
        (``random_state.checkpoint_state()``) is always captured.
        ``extra`` must be JSON-serializable.
        """
        t0 = time.perf_counter()
        step = int(step)
        if step < 0:
            raise MXNetError(f"checkpoint step must be >= 0, got {step}")
        self._clean_tmp()
        final = self.path(step)
        tmp = tempfile.mkdtemp(
            dir=self.directory,
            prefix=f".{self._name(step)}.staging-")
        try:
            written: List[str] = []
            if params is not None:
                from .ndarray import serialization

                serialization.save(os.path.join(tmp, _PARAMS_FILE),
                                   self._param_payload(params))
                written.append(_PARAMS_FILE)
            if trainer is not None:
                trainer.save_states(os.path.join(tmp, _STATES_FILE))
                written.append(_STATES_FILE)
                zman = trainer.partition_manifest() \
                    if hasattr(trainer, "partition_manifest") else None
                if zman is not None:
                    atomic_write(
                        os.path.join(tmp, _ZERO_FILE),
                        json.dumps(zman, indent=1).encode("utf-8"))
                    written.append(_ZERO_FILE)
            from . import random_state

            atomic_write(os.path.join(tmp, _RNG_FILE),
                         pickle.dumps(random_state.checkpoint_state()))
            written.append(_RNG_FILE)
            meta = {"format": FORMAT_VERSION, "step": step,
                    "epoch": epoch, "extra": extra,
                    "created_unix": time.time()}
            atomic_write(os.path.join(tmp, _META_FILE),
                         json.dumps(meta, indent=1).encode("utf-8"))
            written.append(_META_FILE)
            manifest = {
                "format": FORMAT_VERSION, "step": step,
                "files": {
                    f: {"sha256": _sha256_file(os.path.join(tmp, f)),
                        "bytes": os.path.getsize(os.path.join(tmp, f))}
                    for f in written}}
            # the commit record — written LAST: a bundle without it (or
            # with stale checksums) is invisible to discovery
            atomic_write(os.path.join(tmp, MANIFEST_NAME),
                         json.dumps(manifest, indent=1).encode("utf-8"))
            _fsync_dir(tmp)
            if os.path.isdir(final):
                # re-save of an existing step: replace the old bundle.
                # (os.replace cannot overwrite a non-empty dir; the gap
                # between rmtree and rename is the one non-atomic window,
                # and only for same-step re-saves.)
                shutil.rmtree(final)
            os.replace(tmp, final)
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        _fsync_dir(self.directory)
        telemetry.record_checkpoint_write(time.perf_counter() - t0)
        self._gc()
        return final

    def _has_manifest(self, step: int) -> bool:
        return os.path.isfile(os.path.join(self.path(step), MANIFEST_NAME))

    def _gc(self) -> None:
        """Retention: keep the newest ``keep_last`` committed bundles
        (manifest present — the cheap commit marker; full checksum
        validation is the RESUME path's job, re-hashing every retained
        gigabyte-scale bundle on every save would make checkpointing an
        I/O hotspot); drop older committed ones and any manifest-less
        debris older than the newest committed bundle (never newer — it
        may be another writer's in-flight work)."""
        committed = [s for s in self._scan() if self._has_manifest(s)]
        keep = set(committed[:self.keep_last])
        newest = committed[0] if committed else None
        for s in self._scan():
            if s in keep:
                continue
            if s in committed or (newest is not None and s < newest):
                shutil.rmtree(self.path(s), ignore_errors=True)

    # -- read ----------------------------------------------------------
    def _resolve_valid(self, step: Optional[int]):
        """Pick the target step (newest valid when None), checksum-check
        it once, and return ``(step, manifest)``."""
        if step is None:
            step = self.latest_step()
            if step is None:
                raise MXNetError(
                    f"no checksum-valid checkpoint found under "
                    f"{self.directory!r} (prefix {self.prefix!r})")
        elif not self.is_valid(step):
            raise MXNetError(
                f"checkpoint step {step} under {self.directory!r} is "
                f"missing or fails checksum validation")
        return step, self._read_manifest(step)

    def load(self, step: Optional[int] = None) -> Dict:
        """Load a bundle's payloads (newest valid when ``step`` is None).

        Returns ``{"step", "epoch", "extra", "path", "params" (dict of
        NDArray or None), "trainer_states" (bytes or None), "rng"
        (random_state snapshot or None)}``. Raises :class:`MXNetError`
        when no valid checkpoint exists or ``step`` is invalid/corrupt.
        """
        step, man = self._resolve_valid(step)
        root = self.path(step)
        out: Dict = {"step": step, "path": root, "params": None,
                     "trainer_states": None, "rng": None,
                     "epoch": None, "extra": None}
        files = man["files"]
        if _META_FILE in files:
            with open(os.path.join(root, _META_FILE), "rb") as f:
                meta = json.loads(f.read().decode("utf-8"))
            out["epoch"] = meta.get("epoch")
            out["extra"] = meta.get("extra")
        if _PARAMS_FILE in files:
            from .ndarray import serialization

            out["params"] = serialization.load(
                os.path.join(root, _PARAMS_FILE))
        if _STATES_FILE in files:
            with open(os.path.join(root, _STATES_FILE), "rb") as f:
                out["trainer_states"] = f.read()
        if _RNG_FILE in files:
            if _fault_state.enabled:
                fault.check("checkpoint.read",
                            os.path.join(root, _RNG_FILE))
            with open(os.path.join(root, _RNG_FILE), "rb") as f:
                out["rng"] = pickle.loads(f.read())
        out["zero"] = self.partition_manifest(step)
        return out

    def partition_manifest(self, step: int) -> Optional[Dict]:
        """The bundle's ZeRO partition-plan manifest (``zero.json``), or
        None for a replicated (unpartitioned) bundle. Step must name an
        existing bundle; no checksum pass is run here — callers on the
        restore path already validated."""
        p = os.path.join(self.path(step), _ZERO_FILE)
        try:
            with open(p, "rb") as f:
                return json.loads(f.read().decode("utf-8"))
        except (OSError, ValueError, UnicodeDecodeError):
            return None

    def states_path(self, step: int) -> str:
        """Path of the bundle's ``trainer.states`` payload (the per-rank
        sharded state file under ZeRO) — the unit
        ``Trainer.load_states_resharded`` gathers across rank bundles."""
        return os.path.join(self.path(step), _STATES_FILE)

    def restore(self, block=None, trainer=None, restore_rng: bool = True,
                step: Optional[int] = None) -> Dict:
        """One-call resume: pick the newest valid bundle (or ``step``)
        and apply it — params into ``block``
        (``Block.load_parameters``), optimizer states into ``trainer``
        (``Trainer.load_states``), and the RNG stream back into
        ``mx.random``. Each payload is read exactly once, straight into
        its consumer (no intermediate materialization via :meth:`load` —
        that would double a large model's resume time and peak memory).
        Returns the bundle's meta dict (``step``, ``epoch``, ``extra``,
        ``path``)."""
        step, man = self._resolve_valid(step)
        root = self.path(step)
        files = man["files"]
        if block is not None:
            if _PARAMS_FILE not in files:
                raise MXNetError(
                    f"checkpoint {root!r} holds no params.params to "
                    "restore the block from")
            block.load_parameters(os.path.join(root, _PARAMS_FILE))
        if trainer is not None:
            if _STATES_FILE not in files:
                raise MXNetError(
                    f"checkpoint {root!r} holds no trainer.states to "
                    "restore the trainer from")
            trainer.load_states(os.path.join(root, _STATES_FILE))
        if restore_rng and _RNG_FILE in files:
            if _fault_state.enabled:
                fault.check("checkpoint.read",
                            os.path.join(root, _RNG_FILE))
            from . import random_state

            with open(os.path.join(root, _RNG_FILE), "rb") as f:
                random_state.restore_checkpoint_state(pickle.loads(f.read()))
        out = {"step": step, "epoch": None, "extra": None, "path": root}
        if _META_FILE in files:
            with open(os.path.join(root, _META_FILE), "rb") as f:
                meta = json.loads(f.read().decode("utf-8"))
            out["epoch"] = meta.get("epoch")
            out["extra"] = meta.get("extra")
        return out
