"""mx.io — DataIter protocol and iterators.

Reference: ``python/mxnet/io/io.py`` (`DataIter`, `DataBatch`, `DataDesc`,
`NDArrayIter`, `PrefetchingIter`, `ResizeIter`) and the C++-backed iters
(`MXDataIter` wrapping `src/io/` — MNISTIter/ImageRecordIter/CSVIter).
TPU note: iterators produce host-side batches; device placement happens at
bind/step time (per-host sharded `device_put` on pods).
"""
from .io import (DataDesc, DataBatch, DataIter, NDArrayIter, ResizeIter,
                 PrefetchingIter, CSVIter, LibSVMIter, MNISTIter,
                 ImageRecordIter)
from .device_feed import (DeviceFeedIter, make_normalize_transform,
                          stage_on_device)

__all__ = ["DataDesc", "DataBatch", "DataIter", "NDArrayIter", "ResizeIter",
           "PrefetchingIter", "CSVIter", "LibSVMIter", "MNISTIter",
           "ImageRecordIter", "DeviceFeedIter", "stage_on_device",
           "make_normalize_transform"]
