"""``DeviceFeedIter`` — async host→device input staging.

The host-side pipeline (``PrefetchingIter``/``DataLoader``) overlaps
*decode* with compute, but the batch still crossed to the device inside
the training step — an H2D transfer serialized with every step, which on
a relay-attached TPU dominates real-data throughput (PERF.md round 7:
the 25× device-idle gap). The reference's C++ ``iter_prefetcher.h``
double-buffers into engine-managed staging memory; the TPU-native
equivalent (tf.data ``prefetch_to_device`` / DALI-style) is this
iterator: a producer thread ``jax.device_put``s the next ``depth``
batches *with the consuming step's input sharding* while the device
crunches the current one, so by the time the step runs, its inputs are
already sharded device buffers and the per-step transfer is a no-op
(``TrainStep`` detects the matching sharding and skips its own put).

    step = par.TrainStep(net, loss, "sgd", mesh=mesh, donate_inputs=True)
    feed = mxio.DeviceFeedIter(train_iter, step=step, depth=2)
    for batch in feed:
        loss, _ = step(batch.data[0], batch.label[0])

``device_transform`` runs a jitted function over the staged arrays ON
DEVICE — e.g. cast a uint8 batch to bf16 and normalize, so the wire
carries quarter-size pixels and the VPU does the float math (the DALI
"GPU-side augmentation tail" move).

Telemetry (``MXNET_TELEMETRY=1``): ``mxnet_data_wait_seconds{stage}``
(consumer block time — the host-starved vs device-starved
discriminator), ``mxnet_data_queue_depth{stage}``. Fault site
``datafeed.put`` fires inside the producer; any producer failure
surfaces at ``next()`` as an ``MXNetError`` naming the stage — never a
hang on an empty queue. Producer/lifecycle machinery is shared with
``PrefetchingIter`` (``io.io._AsyncStage``).
"""
from __future__ import annotations

from .. import fault
from ..base import MXNetError
from ..context import cpu_pinned, current_context
from ..ndarray import NDArray
from .io import DataBatch, _AsyncStage

__all__ = ["DeviceFeedIter", "stage_on_device", "make_normalize_transform"]


def make_normalize_transform(mean, std, dtype="bfloat16"):
    """The canonical uint8-wire ``device_transform``: per-channel
    ``(x - mean) / std`` in float32 on device, cast to ``dtype``. Labels
    pass through. ``mean``/``std`` are per-channel sequences (NCHW dim 1)
    — e.g. the ImageNet constants the C++ iterator took as
    ``mean_r/g/b`` + ``std_r/g/b``."""
    import numpy as _np

    mean = _np.asarray(mean, _np.float32).reshape(1, -1, 1, 1)
    std = _np.asarray(std, _np.float32).reshape(1, -1, 1, 1)

    def transform(x, *labels):
        import jax.numpy as jnp

        xb = ((x.astype(jnp.float32) - mean) / std).astype(dtype)
        return (xb,) + labels

    return transform


def stage_on_device(batch, device_id=0, device=None):
    """Stage a host batch (NDArray / nested list) onto one device with an
    async ``device_put`` — the ``DataLoader(pin_memory=True)`` path. The
    returned NDArrays carry the ``cpu_pinned`` context (reference
    semantics: pinned staging buffers owned by the host)."""
    import jax

    if device is None:
        devs = jax.devices()
        device = devs[min(int(device_id), len(devs) - 1)]

    def go(b):
        if isinstance(b, (list, tuple)):
            return [go(x) for x in b]
        if isinstance(b, NDArray):
            return NDArray(data=jax.device_put(b.data, device),
                           ctx=cpu_pinned())
        return b

    return go(batch)


class DeviceFeedIter(_AsyncStage):
    """Asynchronously stage batches from ``data_iter`` onto the device.

    Parameters
    ----------
    data_iter : DataIter, DataLoader or any iterable of batches. A batch
        may be a ``DataBatch`` (data+label lists) or a flat list/tuple of
        NDArrays (DataLoader's shape); the staged batch keeps the form.
    step : TrainStep, optional — placement comes from
        ``step.input_shardings`` so the step's per-call ``device_put``
        becomes a no-op. Exactly one of ``step``/``shardings`` required.
    shardings : explicit placement instead of a step: a sequence (one
        entry per batch array, anything ``jax.device_put`` accepts) or a
        callable ``(arrays) -> sequence``.
    depth : producer queue depth (batches staged ahead), default 2 —
        the classic double buffer.
    device_transform : optional function over the staged jax arrays,
        jitted on first use and run on device (same arity in and out);
        e.g. uint8→bf16 normalize.
    name : stage label for telemetry/fault/error messages.
    """

    def __init__(self, data_iter, step=None, shardings=None, depth=2,
                 device_transform=None, name="device_feed"):
        self._source = data_iter
        if (step is None) == (shardings is None):
            raise MXNetError(
                "DeviceFeedIter needs exactly one of step= (a TrainStep "
                "whose input sharding to feed) or shardings=")
        self._step = step
        self._shardings = shardings
        self._device_transform = device_transform
        self._jit_transform = None
        self._sh_cache = {}
        self.name = name
        self._stage_name = name
        super().__init__(getattr(data_iter, "batch_size", 0), depth=depth,
                         thread_name=f"mxnet-{name}")
        self._start()

    # -- provide_* proxy (post-transform dtypes may differ; descriptors
    # describe the HOST side, same caveat as the reference prefetcher)
    @property
    def provide_data(self):
        return getattr(self._source, "provide_data", None)

    @property
    def provide_label(self):
        return getattr(self._source, "provide_label", None)

    # -- _AsyncStage surface -------------------------------------------
    def _source_obj(self):
        return self._source

    def _on_start(self):
        self._iter = iter(self._source)

    def _produce(self):
        return self._stage(next(self._iter))

    def _raise_failure(self):
        raise MXNetError(
            f"input pipeline stage '{self.name}' failed at datafeed.put "
            f"(producer thread died): {self._failure!r}") \
            from self._failure

    # -- staging -------------------------------------------------------
    def _resolve_shardings(self, vals):
        key = tuple((tuple(v.shape), str(v.dtype)) for v in vals)
        shs = self._sh_cache.get(key)
        if shs is None:
            if self._step is not None:
                shs = self._step.input_shardings(vals)
            elif callable(self._shardings):
                shs = tuple(self._shardings(vals))
            else:
                shs = tuple(self._shardings)
            if len(shs) != len(vals):
                raise MXNetError(
                    f"DeviceFeedIter({self.name}): {len(shs)} shardings "
                    f"for {len(vals)} batch arrays")
            self._sh_cache[key] = shs
        return shs

    def _stage(self, batch):
        """device_put every array of one batch with its target sharding
        (async — transfer overlaps downstream compute), then apply the
        on-device transform. Runs on the producer thread."""
        import jax

        if fault._state.enabled:
            fault.check("datafeed.put", detail=self.name)
        if isinstance(batch, DataBatch):
            data = list(batch.data or [])
            label = list(batch.label or [])
        elif isinstance(batch, (list, tuple)):
            data, label = list(batch), []
        else:
            data, label = [batch], []
        arrs = data + label
        ctxs = [a.context if isinstance(a, NDArray) else current_context()
                for a in arrs]
        vals = [a.data if isinstance(a, NDArray) else a for a in arrs]
        shs = self._resolve_shardings(vals)
        put = [jax.device_put(v, sh) for v, sh in zip(vals, shs)]
        if self._device_transform is not None:
            if self._jit_transform is None:
                self._jit_transform = jax.jit(self._device_transform)
            out = self._jit_transform(*put)
            if not isinstance(out, (list, tuple)):
                out = [out]
            if len(out) != len(put):
                raise MXNetError(
                    f"DeviceFeedIter({self.name}): device_transform must "
                    f"keep arity ({len(put)} in, {len(out)} out)")
            put = list(out)
        nds = [NDArray(data=v, ctx=ctx) for v, ctx in zip(put, ctxs)]
        if isinstance(batch, DataBatch):
            return DataBatch(data=nds[:len(data)], label=nds[len(data):],
                             pad=batch.pad, index=batch.index,
                             provide_data=batch.provide_data,
                             provide_label=batch.provide_label)
        if isinstance(batch, (list, tuple)):
            return nds
        return nds[0]

    # -- batch accessors -----------------------------------------------
    def getdata(self):
        b = self._current
        return b.data if isinstance(b, DataBatch) else b

    def getlabel(self):
        b = self._current
        return b.label if isinstance(b, DataBatch) else None

    def getpad(self):
        b = self._current
        return (b.pad or 0) if isinstance(b, DataBatch) else 0
