"""DataIter implementations (reference: python/mxnet/io/io.py)."""
from __future__ import annotations

import threading
import time
import queue as _queue
from collections import namedtuple
from typing import Dict, List, Optional

import numpy as _np

from .. import telemetry
from ..base import MXNetError
from ..context import cpu
from ..ndarray import NDArray, array as nd_array
from ..telemetry import _state as _telemetry_state

__all__ = ["ImageRecordIter",
           "DataDesc", "DataBatch", "DataIter", "NDArrayIter", "ResizeIter",
           "PrefetchingIter", "CSVIter", "MNISTIter"]


class DataDesc(namedtuple("DataDesc", ["name", "shape", "dtype", "layout"])):
    """reference: io.py::DataDesc."""

    def __new__(cls, name, shape, dtype="float32", layout="NCHW"):
        return super().__new__(cls, name, tuple(shape), _np.dtype(dtype),
                               layout)

    @staticmethod
    def get_batch_axis(layout):
        if layout is None:
            return 0
        return layout.find("N")


class DataBatch:
    """reference: io.py::DataBatch."""

    def __init__(self, data, label=None, pad=None, index=None,
                 bucket_key=None, provide_data=None, provide_label=None):
        if data is not None and not isinstance(data, (list, tuple)):
            data = [data]
        if label is not None and not isinstance(label, (list, tuple)):
            label = [label]
        self.data = data
        self.label = label
        self.pad = pad
        self.index = index
        self.bucket_key = bucket_key
        self.provide_data = provide_data
        self.provide_label = provide_label

    def __str__(self):
        shapes = [d.shape for d in (self.data or [])]
        return f"DataBatch: data shapes: {shapes}"


class DataIter:
    """reference: io.py::DataIter — the iterator protocol Module.fit
    consumes (reset/next/iter_next/getdata/getlabel/getpad)."""

    def __init__(self, batch_size=0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    def next(self) -> DataBatch:
        if self.iter_next():
            return DataBatch(data=self.getdata(), label=self.getlabel(),
                             pad=self.getpad(), index=self.getindex())
        raise StopIteration

    def __next__(self):
        return self.next()

    def iter_next(self) -> bool:
        raise NotImplementedError

    def getdata(self):
        raise NotImplementedError

    def getlabel(self):
        raise NotImplementedError

    def getindex(self):
        return None

    def getpad(self):
        return 0


def _init_data(data, allow_empty, default_name):
    if data is None:
        if not allow_empty:
            raise MXNetError("data must be provided")
        return []
    if isinstance(data, (_np.ndarray, NDArray)):
        data = [data]
    if isinstance(data, (list, tuple)):
        out = [(f"{default_name}" if i == 0 else f"_{i}_{default_name}", d)
               for i, d in enumerate(data)]
    elif isinstance(data, dict):
        out = list(data.items())
    else:
        raise MXNetError(f"unsupported data type {type(data)}")
    return [(k, v if isinstance(v, _np.ndarray) else v.asnumpy())
            for k, v in out]


class NDArrayIter(DataIter):
    """reference: io.py::NDArrayIter — in-memory batch iterator with
    shuffle + last-batch padding/rollover."""

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle="pad", data_name="data",
                 label_name="softmax_label"):
        super().__init__(batch_size)
        self.data = _init_data(data, False, data_name)
        self.label = _init_data(label, True, label_name)
        self.num_data = self.data[0][1].shape[0]
        self.shuffle = shuffle
        self.last_batch_handle = last_batch_handle
        self.cursor = -batch_size
        self._order = _np.arange(self.num_data)
        if shuffle:
            _np.random.shuffle(self._order)
        if last_batch_handle == "discard":
            self.num_batches = self.num_data // batch_size
        else:
            self.num_batches = -(-self.num_data // batch_size)

    @property
    def provide_data(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:],
                         v.dtype) for k, v in self.data]

    @property
    def provide_label(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:],
                         v.dtype) for k, v in self.label]

    def reset(self):
        self.cursor = -self.batch_size
        if self.shuffle:
            _np.random.shuffle(self._order)

    def iter_next(self):
        self.cursor += self.batch_size
        if self.last_batch_handle == "discard":
            return self.cursor + self.batch_size <= self.num_data
        return self.cursor < self.num_data

    def _slice(self, arrays):
        out = []
        for _, v in arrays:
            idx = self._order[self.cursor:self.cursor + self.batch_size]
            chunk = v[idx]
            if chunk.shape[0] < self.batch_size:
                # pad by wrapping (reference: last_batch_handle='pad')
                extra = self._order[: self.batch_size - chunk.shape[0]]
                chunk = _np.concatenate([chunk, v[extra]], axis=0)
            out.append(nd_array(chunk))
        return out

    def getdata(self):
        return self._slice(self.data)

    def getlabel(self):
        return self._slice(self.label)

    def getpad(self):
        if self.last_batch_handle == "pad" and \
                self.cursor + self.batch_size > self.num_data:
            return self.cursor + self.batch_size - self.num_data
        return 0

    def getindex(self):
        return self._order[self.cursor:self.cursor + self.batch_size]


class ResizeIter(DataIter):
    """Resize an iterator to a fixed number of batches
    (reference: io.py::ResizeIter)."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__(data_iter.batch_size)
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0
        self.current_batch = None

    @property
    def provide_data(self):
        return self.data_iter.provide_data

    @property
    def provide_label(self):
        return self.data_iter.provide_label

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def iter_next(self):
        if self.cur == self.size:
            return False
        try:
            self.current_batch = self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            self.current_batch = self.data_iter.next()
        self.cur += 1
        return True

    def next(self):
        if self.iter_next():
            return self.current_batch
        raise StopIteration

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getpad(self):
        return self.current_batch.pad or 0


class _WorkerFailure:
    """Queue sentinel: the producer thread died on ``exc``."""

    __slots__ = ("exc",)

    def __init__(self, exc):
        self.exc = exc


class _AsyncStage(DataIter):
    """Producer-thread machinery shared by the async pipeline stages
    (``PrefetchingIter``, ``io.DeviceFeedIter``): a daemon thread fills
    a bounded queue from :meth:`_produce`; the consumer pops.

    The lifecycle contract, implemented once here:

    * post-exhaustion ``next()`` raises ``StopIteration`` immediately
      (the worker is gone — blocking on its queue would hang forever);
    * a producer crash surfaces at ``next()`` as ``MXNetError``, never a
      hang, and stays sticky;
    * ``reset()`` restarts; ``close()`` is idempotent, joins the worker,
      closes the wrapped source, and makes further ``next()`` an error;
    * every worker generation binds its own ``(queue, stop)`` pair,
      and ``_shutdown_worker`` replaces BOTH unconditionally — an
      in-flight put that slipped past the drain, or a join-timeout
      zombie, writes into the orphaned queue, never the successor's.

    Subclasses implement ``_produce()`` (one item or StopIteration),
    ``_source_obj()`` (the wrapped iterator, for reset/close chaining),
    optionally ``_on_start()`` (rebind the source iterator) and set
    ``_stage_name`` (telemetry label).
    """

    _stage_name = "async_stage"

    def __init__(self, batch_size=0, depth=2, thread_name="mxnet-stage"):
        super().__init__(batch_size)
        self._depth = max(1, int(depth))
        self._thread_name = thread_name
        self._queue: _queue.Queue = _queue.Queue(maxsize=self._depth)
        self._stop = threading.Event()
        self._thread = None
        self._current = None
        self._exhausted = False
        self._failure = None
        self._closed = False

    # -- subclass surface ----------------------------------------------
    def _produce(self):
        """Produce one staged item; raise StopIteration when drained."""
        raise NotImplementedError

    def _source_obj(self):
        """The wrapped iterator (reset()/close() chain to it)."""
        raise NotImplementedError

    def _on_start(self):
        """Hook run before each worker generation starts."""

    def _raise_failure(self):
        raise MXNetError(
            f"{type(self).__name__} worker thread died: "
            f"{self._failure!r}") from self._failure

    # -- producer ------------------------------------------------------
    @staticmethod
    def _stop_aware_put(q, stop, item) -> bool:
        """Bounded put that never blocks forever on a full queue whose
        consumer has gone away (close/reset drains concurrently)."""
        while not stop.is_set():
            try:
                q.put(item, timeout=0.05)
                return True
            except _queue.Full:
                continue
        return False

    def _worker(self, q, stop):
        try:
            while not stop.is_set():
                try:
                    item = self._produce()
                except StopIteration:
                    self._stop_aware_put(q, stop, None)
                    return
                if not self._stop_aware_put(q, stop, item):
                    return
                if _telemetry_state.enabled:
                    telemetry.set_data_queue_depth(self._stage_name,
                                                   q.qsize())
        except BaseException as e:  # noqa: BLE001 - delivered to consumer
            # a dead producer must surface as an error at the consumer,
            # not as a next() that blocks on an empty queue forever
            self._stop_aware_put(q, stop, _WorkerFailure(e))

    def _start(self):
        self._on_start()
        self._thread = threading.Thread(
            target=self._worker, args=(self._queue, self._stop),
            daemon=True, name=self._thread_name)
        self._thread.start()

    def _shutdown_worker(self):
        self._stop.set()
        try:
            while True:
                self._queue.get_nowait()
        except _queue.Empty:
            pass
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        # fresh generation objects UNCONDITIONALLY: a put in flight
        # during the drain (or a zombie that outlived the join timeout)
        # lands in the orphaned queue, so no stale batch or None
        # sentinel can leak into the successor epoch
        self._queue = _queue.Queue(maxsize=self._depth)
        self._stop = threading.Event()

    # -- consumer / lifecycle ------------------------------------------
    def reset(self):
        if self._closed:
            raise MXNetError(f"{type(self).__name__} is closed")
        self._shutdown_worker()
        inner_reset = getattr(self._source_obj(), "reset", None)
        if inner_reset is not None:
            inner_reset()
        self._exhausted = False
        self._failure = None
        self._start()

    def close(self):
        """Stop + join the worker and close the wrapped source
        (idempotent; also runs on GC)."""
        if self._closed:
            return
        self._closed = True
        self._shutdown_worker()
        inner_close = getattr(self._source_obj(), "close", None)
        if inner_close is not None:
            inner_close()

    def __del__(self):  # pragma: no cover - GC timing
        try:
            self.close()
        except Exception:
            pass

    def iter_next(self):
        if self._closed:
            raise MXNetError(
                f"{type(self).__name__} is closed; next() after close() "
                "would block on the dead worker's queue")
        if self._failure is not None:
            self._raise_failure()
        if self._exhausted:
            return False
        t0 = time.perf_counter()
        item = self._queue.get()
        if _telemetry_state.enabled:
            telemetry.record_data_wait(time.perf_counter() - t0,
                                       self._stage_name)
            telemetry.set_data_queue_depth(self._stage_name,
                                           self._queue.qsize())
        if item is None:
            self._exhausted = True
            return False
        if isinstance(item, _WorkerFailure):
            self._failure = item.exc
            self._raise_failure()
        self._current = item
        return True

    def next(self):
        if self.iter_next():
            return self._current
        raise StopIteration


class PrefetchingIter(_AsyncStage):
    """Threaded prefetch over one or more iters
    (reference: io.py::PrefetchingIter; the C++ analogue is
    src/io/iter_prefetcher.h). Host-side pipelining: the next batch is
    prepared while the device crunches the current one. Lifecycle per
    :class:`_AsyncStage` (shared with ``io.DeviceFeedIter``)."""

    _stage_name = "prefetch"

    def __init__(self, iters, rename_data=None, rename_label=None,
                 prefetch_depth=2):
        if not isinstance(iters, (list, tuple)):
            iters = [iters]
        if len(iters) != 1:
            raise MXNetError("PrefetchingIter: composite mode not supported; "
                             "pass one iterator")
        self.iter = iters[0]
        super().__init__(self.iter.batch_size, depth=prefetch_depth,
                         thread_name="mxnet-prefetch")
        self._start()

    @property
    def provide_data(self):
        return self.iter.provide_data

    @property
    def provide_label(self):
        return self.iter.provide_label

    def _source_obj(self):
        return self.iter

    def _produce(self):
        return self.iter.next()

    def getdata(self):
        return self._current.data

    def getlabel(self):
        return self._current.label

    def getpad(self):
        return self._current.pad or 0


class CSVIter(NDArrayIter):
    """reference: src/io/iter_csv.cc (C++ CSVIter) — host CSV reader."""

    def __init__(self, data_csv, data_shape, label_csv=None, label_shape=(1,),
                 batch_size=1, **kwargs):
        data = _np.loadtxt(data_csv, delimiter=",", dtype="float32")
        data = data.reshape((-1,) + tuple(data_shape))
        label = None
        if label_csv is not None:
            label = _np.loadtxt(label_csv, delimiter=",", dtype="float32")
            label = label.reshape((-1,) + tuple(label_shape))
            if label_shape == (1,):
                label = label.reshape(-1)
        super().__init__(data, label, batch_size=batch_size, **kwargs)


class LibSVMIter(DataIter):
    """reference: ``src/io/iter_libsvm.cc`` — sparse LibSVM-format reader.

    Batches carry a FACTORED ``CSRNDArray`` (values/indices/indptr built
    straight from the text — the dense (batch, dim) matrix is never
    formed; ``sparse.dot`` consumes the factored parts on device). Lines
    are ``label idx:val idx:val ...`` with 0-based indices, matching the
    upstream iterator's contract (its docs call out that it deviates from
    the 1-based libsvm convention).
    """

    def __init__(self, data_libsvm, data_shape, batch_size,
                 label_libsvm=None, label_shape=None, round_batch=True,
                 dtype="float32", **kwargs):
        super().__init__(batch_size)
        self._dim = int(data_shape[0] if isinstance(
            data_shape, (tuple, list)) else data_shape)
        vals, cols, lens, labels = [], [], [], []
        with open(data_libsvm) as f:
            for line in f:
                toks = line.split()
                if not toks:
                    continue
                labels.append(float(toks[0]))
                n = 0
                for t in toks[1:]:
                    i, v = t.split(":")
                    cols.append(int(i))
                    vals.append(float(v))
                    n += 1
                lens.append(n)
        self._vals = _np.asarray(vals, dtype=dtype)
        self._cols = _np.asarray(cols, dtype="int64")
        self._ends = _np.concatenate([[0], _np.cumsum(lens)]).astype("int64")
        self._labels = _np.asarray(labels, dtype="float32")
        if label_libsvm is not None:
            # separate label file: whitespace-separated floats per line
            # (possibly multi-label); shape honored via label_shape
            lab_rows = []
            with open(label_libsvm) as f:
                for line in f:
                    toks = line.split()
                    if toks:
                        lab_rows.append([float(t) for t in toks])
            self._labels = _np.asarray(lab_rows, dtype="float32")
            if label_shape is not None:
                self._labels = self._labels.reshape(
                    (-1,) + tuple(label_shape))
            elif self._labels.shape[-1] == 1:
                self._labels = self._labels.reshape(-1)
            if len(self._labels) != len(lens):
                raise MXNetError(
                    f"LibSVMIter: {len(self._labels)} labels != "
                    f"{len(lens)} data rows")
        self._n = len(self._labels)
        if self._n < batch_size:
            raise MXNetError(
                f"LibSVMIter: {self._n} rows < batch_size {batch_size}")
        self._round = bool(round_batch)
        self._cursor = 0
        self.provide_data = [DataDesc("data", (batch_size, self._dim),
                                      dtype)]
        self.provide_label = [DataDesc("softmax_label", (batch_size,),
                                       "float32")]

    def reset(self):
        self._cursor = 0

    def iter_next(self):
        return self._cursor < self._n

    def _rows(self):
        idx = _np.arange(self._cursor, self._cursor + self.batch_size)
        pad = int((idx >= self._n).sum())
        idx = idx % self._n if self._round else idx[idx < self._n]
        return idx, pad

    def getdata(self):
        from ..ndarray import sparse as _sparse

        idx, _ = self._rows()
        lens = (self._ends[idx + 1] - self._ends[idx])
        data = _np.concatenate(
            [self._vals[self._ends[r]:self._ends[r + 1]] for r in idx]) \
            if len(idx) else self._vals[:0]
        cols = _np.concatenate(
            [self._cols[self._ends[r]:self._ends[r + 1]] for r in idx]) \
            if len(idx) else self._cols[:0]
        indptr = _np.concatenate([[0], _np.cumsum(lens)])
        return [_sparse.csr_matrix((data, cols, indptr),
                                   shape=(len(idx), self._dim))]

    def getlabel(self):
        from ..ndarray import array as nd_array

        idx, _ = self._rows()
        return [nd_array(self._labels[idx])]

    def getpad(self):
        return self._rows()[1]

    def next(self):
        if not self.iter_next():
            raise StopIteration
        batch = DataBatch(data=self.getdata(), label=self.getlabel(),
                          pad=self.getpad(),
                          provide_data=self.provide_data,
                          provide_label=self.provide_label)
        self._cursor += self.batch_size
        return batch


class MNISTIter(NDArrayIter):
    """reference: src/io/iter_mnist.cc — reads the IDX-format MNIST files."""

    def __init__(self, image, label, batch_size=128, shuffle=True,
                 flat=False, **kwargs):
        import gzip
        import struct

        def read_idx(path):
            opener = gzip.open if path.endswith(".gz") else open
            with opener(path, "rb") as f:
                magic = struct.unpack(">I", f.read(4))[0]
                ndim = magic & 0xFF
                dims = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
                return _np.frombuffer(f.read(), dtype=_np.uint8).reshape(dims)

        images = read_idx(image).astype("float32") / 255.0
        labels = read_idx(label).astype("float32")
        if flat:
            images = images.reshape(images.shape[0], -1)
        else:
            images = images.reshape(images.shape[0], 1,
                                    *images.shape[1:])
        super().__init__(images, labels, batch_size=batch_size,
                         shuffle=shuffle, **kwargs)


def ImageRecordIter(path_imgrec=None, path_imgidx=None, data_shape=None,
                    batch_size=128, shuffle=False, rand_crop=False,
                    rand_mirror=False, mean_r=0.0, mean_g=0.0, mean_b=0.0,
                    std_r=1.0, std_g=1.0, std_b=1.0, resize=0,
                    label_width=1, **kwargs):
    """Record-file image iterator (reference: the C++ ImageRecordIter of
    ``iter_image_recordio_2.cc``, exposed via io.py). Thin factory over
    ``mx.image.ImageIter`` with the classic flat-kwargs interface."""
    import numpy as _np

    from ..image import CreateAugmenter, ImageIter

    if data_shape is None:
        raise MXNetError("ImageRecordIter requires data_shape")
    mean = None
    std = None
    if any(v != 1.0 for v in (std_r, std_g, std_b)):
        std = _np.array([std_r, std_g, std_b], _np.float32)
    if any(v != 0.0 for v in (mean_r, mean_g, mean_b)) or std is not None:
        # std-only normalization still needs the ColorNormalizeAug (a
        # zero mean), matching the C++ iterator's independent std divide
        mean = _np.array([mean_r, mean_g, mean_b], _np.float32)
    dtype = kwargs.get("dtype", "float32")
    if mean is not None and _np.issubdtype(_np.dtype(dtype), _np.integer):
        raise MXNetError(
            f"ImageRecordIter: mean/std normalization produces floats — "
            f"incompatible with dtype={dtype!r} (an integer cast would "
            "wrap). Ship integer pixels and normalize on device via "
            "io.DeviceFeedIter(device_transform=io.make_normalize_"
            "transform(mean, std)), or use a float dtype")
    aug = CreateAugmenter(data_shape, resize=resize, rand_crop=rand_crop,
                          rand_mirror=rand_mirror, mean=mean, std=std,
                          dtype=dtype)
    return ImageIter(batch_size=batch_size, data_shape=data_shape,
                     path_imgrec=path_imgrec, path_imgidx=path_imgidx,
                     shuffle=shuffle, aug_list=aug, label_width=label_width,
                     **kwargs)
