"""Persistent XLA executable cache — the compilation service's disk tier.

Moved out of the package ``__init__`` when the compilation service landed:
the on-disk executable cache, the signature manifest (:mod:`.manifest`)
and AOT warm-start (:mod:`.service`) are one subsystem sharing the
``MXNET_XLA_CACHE_DIR`` layout::

    <MXNET_XLA_CACHE_DIR>/
        host-<isa-tag>/         jax persistent compilation cache entries
        manifests/*.jsonl       signature manifests (replayable journals)

Reference counterpart: MXNet's op-level autotune caches / CUDA kernel
cache. Training-step executables for transformer-sized models take
minutes to build; caching them on disk makes the second process start in
seconds — and the manifest replays the *set of signatures* so the disk
hits happen before first traffic, not during it.

Knobs:
* ``MXNET_XLA_CACHE``            — 0 disables (default: on for
  TPU-capable processes, off for pure-CPU ones, see ``_cache_default``);
* ``MXNET_XLA_CACHE_DIR``        — base directory override;
* ``MXNET_XLA_CACHE_MIN_COMPILE_S`` — only persist executables whose
  compile took at least this long (default 1.0; benches set 0 so CPU
  compiles persist too);
* ``MXNET_XLA_CACHE_MAX_BYTES``  — size cap for this host's namespace;
  oldest-used entries are GC'd past it at setup (default 4 GiB, 0 = no GC).

The cache is namespaced per host-CPU feature set: jax's cache key does
not include host ISA features, so an XLA:CPU AOT executable compiled on
an AVX-512/AMX host replays on a host without them ("could lead to
execution errors such as SIGILL" — cpu_aot_loader). A host with a
different /proc/cpuinfo flag set gets its own subdirectory and
recompiles.
"""
from __future__ import annotations

import logging
import os
import re
from typing import Optional

_log = logging.getLogger(__name__)

__all__ = ["setup", "cache_dir", "gc_cache", "stats"]

# ISA-extension prefixes (x86 `flags` / ARM `Features`) that codegen can
# actually depend on; kernel-mitigation and power-management flags
# (md_clear, ibrs, retbleed, ...) churn with microcode/kernel updates and
# must not key the cache — they'd force full recompiles on identical
# hardware.
_ISA_PREFIXES = (
    "sse", "avx", "amx", "fma", "bmi", "aes", "sha", "mmx", "f16c",
    "pclmul", "vpclmul", "gfni", "vaes", "adx", "lzcnt", "popcnt", "abm",
    "movbe", "movdir", "xsave", "rtm", "rdrnd", "rdseed", "rdpid",
    "fsgsbase", "invpcid", "clflush", "clwb", "cldemote", "wbnoinvd",
    "serialize", "cmov", "cx8", "cx16", "fxsr", "crc32",
    "lahf", "kl", "widekl", "waitpkg", "enqcmd", "uintr", "hreset", "lm",
    "neon", "asimd", "sve", "fp", "fphp", "crypto", "atomics", "lse",
)
# deliberately absent: rtm/hle/tsxldtrk — TSX is routinely disabled by
# microcode mitigations (flag churn on identical hardware) and XLA codegen
# never emits it.

# exact filenames the jax compilation cache writes
# (<fn>-<sha256 hex>-cache plus its -atime sidecar)
_jax_cache_entry = re.compile(r".+-[0-9a-f]{64}-(cache|atime)$").fullmatch

_cache_dir: Optional[str] = None


def _host_cpu_tag() -> str:
    import hashlib
    import platform

    feats = ""
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith(("flags", "Features")):
                    toks = line.split(":", 1)[1].split()
                    feats = " ".join(
                        sorted(t for t in toks if t.startswith(_ISA_PREFIXES)))
                    break
    except OSError:
        pass
    if not feats:
        # degraded path (no readable /proc/cpuinfo — non-Linux or /proc
        # unmounted): only the coarse arch is known, so hosts of the same
        # arch but different ISA extensions share a namespace and the
        # cross-host AOT protection is WEAK here; the distinct prefix
        # keeps these entries out of any verified-feature namespace.
        feats = "weak:" + (platform.processor() or platform.machine()
                           or "unknown")
    return hashlib.sha1(feats.encode()).hexdigest()[:12]


def _cache_default() -> str:
    # Pure-CPU processes (tests, the driver's virtual-mesh dryrun) default
    # to NO persistent cache: their compiles are cheap, and XLA:CPU AOT
    # entries are what trigger the cpu_aot_loader feature-probe warning on
    # every later load (the probe doesn't know the +prefer-no-scatter/
    # +prefer-no-gather tuning pseudo-features this XLA version compiles
    # with — benign same-host noise, but it pollutes driver artifacts and
    # reads like SIGILL risk). TPU-capable processes keep the cache (the
    # minutes-long transformer TrainStep compiles are the whole point);
    # their host-side CPU jits stay under the min-compile-time bar, so
    # no CPU AOT entries get written and the warning cannot fire.
    plats = os.environ.get("JAX_PLATFORMS", "")
    toks = [t.strip() for t in plats.split(",") if t.strip()]
    if toks and all(t == "cpu" for t in toks):
        return "0"
    return "1"


def cache_dir() -> Optional[str]:
    """This process's persistent-cache namespace, or None when the disk
    tier is disabled."""
    return _cache_dir


def setup() -> Optional[str]:
    """Configure jax's persistent compilation cache under the namespaced
    layout; run once at package import. Returns the active cache dir (or
    None when disabled). Best-effort: an unwritable directory degrades to
    in-memory-only compilation, never an import error."""
    global _cache_dir

    if os.environ.get("MXNET_XLA_CACHE", _cache_default()) == "0":
        return None
    import jax

    base = os.environ.get(
        "MXNET_XLA_CACHE_DIR",
        os.path.join(os.path.expanduser("~"), ".cache", "mxnet_tpu_xla"))
    target = os.path.join(base, "host-" + _host_cpu_tag())
    try:
        os.makedirs(target, exist_ok=True)
        # one-time cleanup: flat entries written by versions before the
        # host namespacing have unknown host provenance (they're the
        # SIGILL-risk entries this scheme exists to quarantine) — delete
        # rather than migrate; they recompile once into the new subdir.
        # Match ONLY the exact filenames the jax compilation cache
        # writes: MXNET_XLA_CACHE_DIR may point at a shared directory,
        # and a broad *-cache sweep would unlink foreign files there.
        for f in os.listdir(base):
            if _jax_cache_entry(f) and os.path.isfile(
                    os.path.join(base, f)):
                try:
                    os.unlink(os.path.join(base, f))
                except OSError:
                    pass
        try:
            min_s = float(os.environ.get(
                "MXNET_XLA_CACHE_MIN_COMPILE_S", "1.0"))
        except ValueError:
            min_s = 1.0
        jax.config.update("jax_compilation_cache_dir", target)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          min_s)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        _cache_dir = target
        gc_cache()
    except Exception:  # pragma: no cover - cache is best-effort
        _cache_dir = None
    return _cache_dir


def _gc_exported(exported_dir: str, max_bytes: int) -> int:
    """LRU sweep of the exported-StableHLO blob store (the trace-skip
    tier lives beside the host namespaces and must honor the same size
    cap, or blobs accumulate per signature forever)."""
    try:
        names = [f for f in os.listdir(exported_dir)
                 if f.endswith(".shlo")]
    except OSError:
        return 0
    blobs = []
    total = 0
    for f in names:
        p = os.path.join(exported_dir, f)
        try:
            st = os.stat(p)
        except OSError:
            continue
        blobs.append((st.st_mtime, st.st_size, p))
        total += st.st_size
    removed = 0
    for _, size, p in sorted(blobs):
        if total <= max_bytes:
            break
        try:
            os.unlink(p)
        except OSError:
            continue
        total -= size
        removed += 1
        _log.debug("exported blob gc: evicted %s (%d bytes)", p, size)
    return removed


def stats(directory: Optional[str] = None) -> dict:
    """Entry count + total bytes of one cache namespace."""
    d = directory or _cache_dir
    n = size = 0
    if d:
        try:
            for f in os.listdir(d):
                p = os.path.join(d, f)
                if _jax_cache_entry(f) and os.path.isfile(p):
                    n += 1
                    size += os.path.getsize(p)
        except OSError:
            pass
    return {"dir": d, "entries": n, "bytes": size}


def gc_cache(max_bytes: Optional[int] = None,
             directory: Optional[str] = None) -> int:
    """Size-capped GC of the persistent executable tier: delete
    least-recently-used entries (jax maintains an ``-atime`` sidecar per
    entry; its mtime is the entry's last use) until the namespace fits
    ``max_bytes``. Returns the number of entries removed."""
    d = directory or _cache_dir
    if not d:
        return 0
    if max_bytes is None:
        try:
            max_bytes = int(os.environ.get(
                "MXNET_XLA_CACHE_MAX_BYTES", str(4 << 30)))
        except ValueError:
            max_bytes = 4 << 30
    if max_bytes <= 0:
        return 0
    entries = {}   # stem -> {"bytes", "atime", "mtime", "files"}
    try:
        names = os.listdir(d)
    except OSError:
        return 0
    for f in names:
        p = os.path.join(d, f)
        if not (_jax_cache_entry(f) and os.path.isfile(p)):
            continue
        stem = f.rsplit("-", 1)[0]
        e = entries.setdefault(stem, {"bytes": 0, "atime": None,
                                      "mtime": 0.0, "files": []})
        try:
            st = os.stat(p)
        except OSError:
            continue
        e["bytes"] += st.st_size
        e["files"].append(p)
        # the -atime sidecar's mtime is jax's last-use record and WINS;
        # the entry file's own mtime is the fallback when it is absent
        if f.endswith("-atime"):
            e["atime"] = st.st_mtime
        else:
            e["mtime"] = max(e["mtime"], st.st_mtime)
    for e in entries.values():
        e["used"] = e["atime"] if e["atime"] is not None else e["mtime"]
    total = sum(e["bytes"] for e in entries.values())
    removed = 0
    for stem in sorted(entries, key=lambda s: entries[s]["used"]):
        if total <= max_bytes:
            break
        e = entries[stem]
        for p in e["files"]:
            try:
                os.unlink(p)
            except OSError:
                pass
        total -= e["bytes"]
        removed += 1
        _log.debug("xla cache gc: evicted %s (%d bytes)", stem, e["bytes"])
    # the exported-blob tier SHARES the cap (one budget for the whole
    # layout, not one per tier): blobs get whatever the jax-cache
    # namespace left unspent
    removed += _gc_exported(os.path.join(os.path.dirname(d), "exported"),
                            max(0, max_bytes - total))
    if removed:
        try:
            from .. import telemetry
            from ..telemetry import _state as _tstate

            if _tstate.enabled:
                telemetry.record_cache_eviction("xla_persistent", removed)
        except Exception:
            pass
    return removed
