"""The compilation service: one cache spine for every jit site.

Three pieces:

* :class:`SiteCache` — the shared LRU policy every compile cache routes
  through (eager per-op, fused segments, CachedOp graphs, TrainStep,
  symbol Executor). One keying scheme (:mod:`.keys`), per-site capacity,
  hit/miss telemetry (``mxnet_jit_cache_total{cache,result}``) and —
  new — observable eviction (``mxnet_jit_cache_evictions_total{cache}``
  plus a debug log of the evicted signature), so cache thrash is a
  metric, not a mystery regression.

* :class:`ExecutableTable` — the in-process executable store, keyed by
  lowered-HLO fingerprint with single-flight builds: when N serving
  replicas (or N warm-start threads) race to compile the same program,
  exactly one XLA compile runs; everyone else blocks briefly and shares
  the executable. This is what lets ``Router`` warm replicas
  concurrently without N× compile work.

* :func:`warm_start` — replay a signature manifest (:mod:`.manifest`)
  through ``jax.jit(...).lower().compile()`` BEFORE first traffic, on a
  small thread pool. Generalizes ``HybridBlock.warmup()``: one call
  warms eager-op executables, fused segments, CachedOp graphs (for the
  blocks you pass) and TrainSteps (for the steps you pass), so a serving
  replica, a hot-reload swap, or an elastic rejoiner starts hot.

Cold-start accounting: ``mark_event(name)`` records the first occurrence
of lifecycle milestones (``first_train_step``, ``first_response``,
``warm_start_done``) as seconds since package import, surfaced through
``events()`` and the ``mxnet_coldstart_seconds{event}`` gauge.
"""
from __future__ import annotations

import collections
import logging
import os
import threading
import time
import weakref
from typing import Callable, Dict, List, Optional, Sequence

from . import keys, manifest as manifest_mod

__all__ = ["SiteCache", "ExecutableTable", "GuardedExec", "exec_table",
           "warm_start", "mark_event", "events", "seconds_since_import",
           "site_caches"]

_log = logging.getLogger(__name__)

_T0 = time.monotonic()          # package-import timestamp: cold-start zero
_events: Dict[str, float] = {}
_events_lock = threading.Lock()


def seconds_since_import() -> float:
    return time.monotonic() - _T0


def mark_event(name: str) -> Optional[float]:
    """Record a cold-start milestone (first occurrence only). Returns the
    seconds-since-import it was recorded at, or None if already marked."""
    with _events_lock:
        if name in _events:
            return None
        t = seconds_since_import()
        _events[name] = t
    try:
        from .. import telemetry
        from ..telemetry import _state as _tstate

        if _tstate.enabled:
            telemetry.record_cold_start(name, t)
    except Exception:
        pass
    return t


def events() -> Dict[str, float]:
    """Cold-start milestones recorded so far: name -> seconds since
    package import."""
    with _events_lock:
        return dict(_events)


# ---------------------------------------------------------------------------
# SiteCache
# ---------------------------------------------------------------------------

_MISS = object()


class SiteCache:
    """Thread-safe LRU over canonical signature keys for one cache site.

    ``maxsize=None`` = unbounded (the CachedOp / TrainStep / Executor
    policy — entries live as long as their owner). Lookups record
    hit/miss telemetry under the site name; evictions are counted and
    the evicted signature logged at debug, so thrash at any of the five
    sites shows up in ``mxnet_jit_cache_evictions_total{cache}``.
    """

    def __init__(self, site: str, maxsize: Optional[int] = None):
        self.site = site
        self.maxsize = maxsize
        self._od: "collections.OrderedDict" = collections.OrderedDict()
        self._lock = threading.Lock()

    def lookup(self, key, record: bool = True):
        """Value for ``key`` (LRU-touched) or the ``MISS`` sentinel;
        records one hit/miss telemetry sample unless ``record=False``."""
        with self._lock:
            val = self._od.get(key, _MISS)
            if val is not _MISS:
                self._od.move_to_end(key)
        if record:
            from .. import telemetry
            from ..telemetry import _state as _tstate

            if _tstate.enabled:
                telemetry.record_cache(self.site, hit=val is not _MISS)
        return val

    MISS = _MISS

    def insert(self, key, value) -> None:
        evicted = []
        with self._lock:
            self._od[key] = value
            self._od.move_to_end(key)
            if self.maxsize is not None:
                while len(self._od) > self.maxsize:
                    evicted.append(self._od.popitem(last=False))
        if evicted:
            from .. import telemetry
            from ..telemetry import _state as _tstate

            if _tstate.enabled:
                telemetry.record_cache_eviction(self.site, len(evicted))
            for k, _ in evicted:
                _log.debug("jit cache %r: evicted signature %r (capacity "
                           "%s)", self.site, k, self.maxsize)

    def __contains__(self, key) -> bool:
        with self._lock:
            return key in self._od

    def __len__(self) -> int:
        with self._lock:
            return len(self._od)

    def clear(self) -> None:
        with self._lock:
            self._od.clear()

    def keys(self) -> list:
        with self._lock:
            return list(self._od)

    def values(self) -> list:
        with self._lock:
            return list(self._od.values())


# the five sites' caches that are process-global (graph-level caches are
# per-object and construct their own SiteCache with site= the same family
# name, so telemetry aggregates per family regardless of instance)
_site_caches: Dict[str, SiteCache] = {}
_site_lock = threading.Lock()


def site_caches() -> Dict[str, SiteCache]:
    with _site_lock:
        return dict(_site_caches)


def shared_cache(site: str, maxsize: Optional[int] = None) -> SiteCache:
    """Process-global SiteCache for ``site`` (created on first use)."""
    with _site_lock:
        c = _site_caches.get(site)
        if c is None:
            c = _site_caches[site] = SiteCache(site, maxsize)
        return c


# ---------------------------------------------------------------------------
# ExecutableTable — single-flight in-process executable dedupe
# ---------------------------------------------------------------------------

class _Pending:
    __slots__ = ("event",)

    def __init__(self):
        self.event = threading.Event()


class ExecutableTable:
    """fingerprint -> compiled executable, with single-flight builds.

    ``get_or_build(fp, build)``: the first caller for a fingerprint runs
    ``build()`` (an XLA compile); concurrent callers for the same
    fingerprint block until it lands and share the result. A failed
    build releases the slot so a later caller can retry. LRU-bounded —
    eviction only drops the dedupe handle, never a live executable (site
    caches hold their own references).
    """

    def __init__(self, maxsize: int = 4096):
        self.maxsize = maxsize
        self._od: "collections.OrderedDict" = collections.OrderedDict()
        self._lock = threading.Lock()
        self.builds = 0          # build() calls that ran
        self.dedup_hits = 0      # calls served from the table
        self.waits = 0           # calls that blocked on another's build

    def get_or_build(self, fp: str, build: Callable):
        while True:
            wait_on = None
            with self._lock:
                entry = self._od.get(fp)
                if entry is None:
                    self._od[fp] = _Pending()
                elif isinstance(entry, _Pending):
                    wait_on = entry.event
                    self.waits += 1
                else:
                    self._od.move_to_end(fp)
                    self.dedup_hits += 1
                    return entry[0]
            if wait_on is not None:
                wait_on.wait()
                continue     # re-read: done (hit) or removed (retry)
            try:
                value = build()
            except BaseException:
                with self._lock:
                    entry = self._od.pop(fp, None)
                if isinstance(entry, _Pending):
                    entry.event.set()
                raise
            evicted = []
            with self._lock:
                pending = self._od.get(fp)
                self._od[fp] = (value,)
                self._od.move_to_end(fp)
                self.builds += 1
                while len(self._od) > self.maxsize:
                    k, v = self._od.popitem(last=False)
                    if isinstance(v, _Pending):   # never evict in-flight
                        self._od[k] = v
                        self._od.move_to_end(k, last=False)
                        break
                    evicted.append(k)
            if isinstance(pending, _Pending):
                pending.event.set()
            return value

    def stats(self) -> dict:
        with self._lock:
            return {"entries": len(self._od), "builds": self.builds,
                    "dedup_hits": self.dedup_hits, "waits": self.waits}

    def clear(self) -> None:
        with self._lock:
            pending = [v for v in self._od.values()
                       if isinstance(v, _Pending)]
            self._od.clear()
        for p in pending:
            p.event.set()


exec_table = ExecutableTable()


class GuardedExec:
    """An AOT-compiled executable with a traceable fallback.

    The compiled path serves the exact avals it was lowered for — the
    overwhelmingly common case after a warm start. Two escape hatches:

    * **tracer operands** (the call sits inside someone else's trace —
      ``jax.vjp`` over a hybridized block under ``autograd.record``): a
      ``Compiled`` cannot be transformed, so the call routes through the
      jit fallback for THAT call only; eager/serving calls keep the
      compiled executable.
    * **aval mismatch** (weak-typed scalar const, layout drift): fall
      back permanently — identical HLO, identical numerics, one retrace.
    """

    __slots__ = ("compiled", "_fallback_factory", "_fallback",
                 "_permanent")

    def __init__(self, compiled, fallback_factory: Callable):
        self.compiled = compiled
        self._fallback_factory = fallback_factory
        self._fallback = None
        self._permanent = False

    def _fb(self):
        if self._fallback is None:
            self._fallback = self._fallback_factory()
        return self._fallback

    def __call__(self, *args):
        if self._permanent:
            return self._fb()(*args)
        import jax

        if any(isinstance(leaf, jax.core.Tracer)
               for leaf in jax.tree_util.tree_leaves(args)):
            return self._fb()(*args)
        try:
            return self.compiled(*args)
        except (TypeError, ValueError) as e:
            _log.debug("AOT executable aval mismatch (%s); falling back "
                       "to jit retrace", e)
            self._permanent = True
            return self._fb()(*args)

    @property
    def __wrapped__(self):
        """The raw pure function, like ``jax.jit``'s ``__wrapped__`` —
        introspection (jaxpr probes in tests) keeps working on sealed
        entries."""
        return self._fb().__wrapped__


def fingerprint_lowered(lowered) -> str:
    """Stable fingerprint of a ``jax.stages.Lowered`` — the
    ExecutableTable key. Uses the lowered StableHLO text: two replicas of
    one architecture lower to byte-identical modules, different programs
    don't."""
    import hashlib

    text = lowered.as_text()
    return hashlib.sha256(text.encode()).hexdigest()[:32]


# ---------------------------------------------------------------------------
# Persistent exported executables: the traced program itself on disk.
#
# The jax persistent cache removes the XLA COMPILE from a warm start, but
# every process still pays the Python trace per signature. jax.export
# serializes the traced+lowered StableHLO module; a warm process
# deserializes it (milliseconds), wraps it in a thin jit, and compiles —
# which is then a persistent-cache disk hit. Net: warm start skips both
# the trace and the compile. Blobs live under
# ``<MXNET_XLA_CACHE_DIR>/exported/<signature-fp>.shlo``, keyed by the
# CANONICAL signature fingerprint (architecture + aval + routing +
# platform + jax version), never by Python object identity.
# ---------------------------------------------------------------------------

def _exported_path(sig_fp: str) -> Optional[str]:
    from . import persistent

    base = persistent.cache_dir()
    if not base:
        return None
    return os.path.join(os.path.dirname(base), "exported",
                        sig_fp + ".shlo")


def _avals_match(exported, args) -> bool:
    import jax

    leaves = jax.tree_util.tree_leaves(args)
    in_avals = exported.in_avals
    if len(leaves) != len(in_avals):
        return False
    return all(tuple(a.shape) == tuple(l.shape) and a.dtype == l.dtype
               for a, l in zip(in_avals, leaves))


def seal_executable(sig_fp: str, jitted, args, fallback: Callable):
    """AOT-compile ``jitted`` at ``args`` (ShapeDtypeStructs) through the
    full persistence stack: in-process executable table (single-flight,
    keyed by the canonical signature fingerprint), the on-disk exported
    StableHLO module (skips the trace on a warm start), and jax's
    persistent compile cache (skips the XLA compile). Returns a
    :class:`GuardedExec` (or the result of ``fallback()`` if AOT is not
    possible for this program — export unsupported for its features,
    donation active, ...).

    Callers must build ``sig_fp`` from everything that determines the
    traced program (graph identity incl. forward bytecode, every input
    aval, routing knobs, platform, jax version) — the blob store trusts
    it, with an aval cross-check on load as the backstop.
    """
    import jax

    def build():
        from jax import export as jexport

        exported = None
        path = _exported_path(sig_fp)
        if path and os.path.exists(path):
            try:
                with open(path, "rb") as f:
                    exported = jexport.deserialize(f.read())
                if not _avals_match(exported, args):
                    exported = None
            except Exception:
                exported = None
        if exported is None:
            exported = jexport.export(jitted)(*args)
            if path:
                try:
                    from ..checkpoint import atomic_write

                    os.makedirs(os.path.dirname(path), exist_ok=True)
                    atomic_write(path, exported.serialize())
                except Exception:
                    pass    # blob store is best-effort
        return jax.jit(exported.call).lower(*args).compile()

    try:
        compiled = exec_table.get_or_build(sig_fp, build)
    except Exception:
        _log.debug("seal_executable: AOT path failed for %s; using "
                   "fallback jit", sig_fp, exc_info=True)
        return fallback()
    return GuardedExec(compiled, fallback)


# ---------------------------------------------------------------------------
# warm_start
# ---------------------------------------------------------------------------

# Per-provider serialization, PROCESS-GLOBAL: two entries (or two whole
# warm_start calls — N replicas warming concurrently) targeting the SAME
# block or step must not race its parameter settle / state init; the
# interleaved initializer draws would even break bit-identity with a
# cold start. Weak-keyed so provider lifetimes stay the providers' own.
_provider_locks: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
_provider_locks_guard = threading.Lock()


def _provider_lock(provider) -> threading.Lock:
    with _provider_locks_guard:
        lock = _provider_locks.get(provider)
        if lock is None:
            lock = _provider_locks[provider] = threading.Lock()
        return lock


def _resolve_entries(manifest) -> List[dict]:
    if manifest is None:
        m = manifest_mod.recorder()
        if m is None:
            m = manifest_mod.Manifest()
        return m.entries()
    if isinstance(manifest, str):
        return manifest_mod.Manifest(manifest).entries()
    if isinstance(manifest, manifest_mod.Manifest):
        return manifest.entries()
    return list(manifest)


def _replay_entry(entry: dict, blocks_by_ident: dict,
                  steps_by_ident: dict) -> str:
    site, spec = entry["site"], entry["spec"]
    if site == "eager_op":
        from ..ops import registry

        return registry.warm_eager_spec(spec)
    if site == "fused_segment":
        from ..ops import registry

        return registry.warm_fused_spec(spec)
    if site == "cached_op":
        block = blocks_by_ident.get(spec.get("graph")) \
            if isinstance(spec, dict) else None
        if block is None:
            return "skipped"
        from ..gluon import block as block_mod

        return block_mod.warm_cached_op_spec(block, spec)
    if site == "train_step":
        step = steps_by_ident.get(spec.get("ident")) \
            if isinstance(spec, dict) else None
        if step is None:
            return "skipped"
        return step.warm_from_spec(spec)
    if site == "optimizer_sweep":
        # needs no provider: the spec fully determines the traced sweep
        # (family + hyperparams + bucket layout), so a fresh process
        # rebuilds and AOT-compiles it before the first Trainer.step
        from ..optimizer import multi_tensor

        return multi_tensor.warm_sweep_spec(spec)
    return "skipped"    # executor: replay needs a bound symbol graph


def warm_start(manifest=None, *, blocks: Sequence = (),
               train_steps: Sequence = (),
               max_workers: Optional[int] = None) -> dict:
    """Replay a signature manifest so this process starts hot.

    ``manifest``: a path, a :class:`~.manifest.Manifest`, a pre-loaded
    entry list, or None (= the active recorder's journal, else the
    default manifest under ``MXNET_XLA_CACHE_DIR``).

    ``blocks``: live HybridBlocks to warm ``cached_op`` entries against,
    matched by structural :func:`~.keys.graph_ident` — pass the model a
    serving replica is about to serve. ``train_steps``: live TrainSteps
    to warm ``train_step`` entries against (an elastic rejoiner's step).
    Op-level entries (``eager_op``, ``fused_segment``) replay with no
    provider.

    Compiles run on a thread pool; signatures another thread (or another
    replica of this process) already built are deduped through the
    in-process :class:`ExecutableTable` — replica N never re-compiles
    what replica 0 compiled. Returns a report dict:
    ``{"replayed", "deduped", "skipped", "failed", "entries", "seconds"}``.
    """
    from concurrent.futures import ThreadPoolExecutor

    t0 = time.perf_counter()
    entries = _resolve_entries(manifest)
    report = {"replayed": 0, "deduped": 0, "skipped": 0, "failed": 0,
              "entries": len(entries), "seconds": 0.0}
    if entries:
        blocks_by_ident = {keys.graph_ident(b): b for b in blocks}
        steps_by_ident = {s.warm_ident(): s for s in train_steps}

        def _provider(entry):
            spec = entry.get("spec")
            if not isinstance(spec, dict):
                return None
            if entry["site"] == "cached_op":
                return blocks_by_ident.get(spec.get("graph"))
            if entry["site"] == "train_step":
                return steps_by_ident.get(spec.get("ident"))
            return None

        def one(entry):
            try:
                prov = _provider(entry)
                if prov is None:
                    return _replay_entry(entry, blocks_by_ident,
                                         steps_by_ident)
                with _provider_lock(prov):
                    return _replay_entry(entry, blocks_by_ident,
                                         steps_by_ident)
            except Exception:
                _log.debug("warm_start: replay failed for site %s",
                           entry.get("site"), exc_info=True)
                return "failed"

        if max_workers is None:
            # auto: XLA:CPU compiles already fan out across every host
            # core, so warm THREADS only contend (measured 6x slower at
            # 4 workers); accelerator compiles are per-device-pipe and
            # overlap well
            import jax

            max_workers = 1 if jax.default_backend() == "cpu" else 4
        n_workers = max(1, min(max_workers, len(entries)))
        if n_workers == 1:
            outcomes = [one(e) for e in entries]
        else:
            with ThreadPoolExecutor(
                    max_workers=n_workers,
                    thread_name_prefix="mx-warm") as pool:
                outcomes = list(pool.map(one, entries))
        for oc in outcomes:
            report[oc if oc in report else "failed"] += 1
    report["seconds"] = time.perf_counter() - t0
    mark_event("warm_start_done")
    try:
        from .. import telemetry
        from ..telemetry import _state as _tstate

        if _tstate.enabled:
            for oc in ("replayed", "deduped", "skipped", "failed"):
                if report[oc]:
                    telemetry.record_warm_start(oc, report[oc])
    except Exception:
        pass
    return report
