"""Canonical signature keying for every jit-compile cache in the repo.

Before the compilation service, five caches keyed executables five ways
(eager per-op ``lru_cache`` args, the fused-segment node-sig tuple,
``_CachedGraph``'s shape key, ``TrainStep._cache``'s batch key, the symbol
``Executor``'s train flag). A signature here is ONE canonical shape::

    SigKey(site, ident, avals, attrs, shardings, platform, routing, extra)

* ``site``     — which cache family owns the entry (``eager_op``,
  ``fused_segment``, ``cached_op``, ``train_step``, ``executor``);
* ``ident``    — what is being compiled (op name, graph fingerprint, node
  signature tuple);
* ``avals``    — input ``(shape, dtype)`` descriptors, where the site keys
  on them (the eager per-op cache deliberately does not: jax.jit retraces
  per shape underneath one entry);
* ``attrs``    — static attributes baked into the trace;
* ``shardings``— input layout descriptors, where the site shards;
* ``platform`` — the execution platform the body was traced FOR (op impls
  dispatch on it at trace time — Pallas kernels, int8 MXU paths);
* ``routing``  — trace-time routing env knobs (``_routing_knobs``): a knob
  toggle selects a different op body for the same signature, so it must
  key every cache (round-9 review finding);
* ``extra``    — site-specific residue (training flag, has_rng, ...).

Every field is a hashable tree of primitives, so a SigKey is usable as a
dict key directly, and :func:`fingerprint` gives a stable hex digest for
the on-disk signature manifest.
"""
from __future__ import annotations

import hashlib
import json
import os
from typing import NamedTuple, Optional

__all__ = ["SigKey", "signature", "fingerprint", "routing_knobs",
           "graph_ident", "callable_ident", "encode", "decode"]


def routing_knobs() -> tuple:
    """Trace-time routing env knobs that select a DIFFERENT op body for
    the same (op, attrs, shapes) signature — they must key every
    executable cache or a knob toggle would keep replaying the
    previously-traced body."""
    return (os.environ.get("MXNET_PALLAS_FUSED", "0") == "1",
            os.environ.get("MXNET_TPU_HASH_DROPOUT", "0") == "1",
            os.environ.get("MXNET_FUSED_OPTIMIZER", "1") != "0")


class SigKey(NamedTuple):
    site: str
    ident: object
    avals: tuple = ()
    attrs: tuple = ()
    shardings: tuple = ()
    platform: Optional[str] = None
    routing: tuple = ()
    extra: tuple = ()


def signature(site: str, ident, avals=(), attrs=(), shardings=(),
              platform=None, routing=None, extra=()) -> SigKey:
    """Build the canonical key. ``routing=None`` means "read the live env
    knobs now" — pass an explicit tuple only when replaying a recorded
    signature."""
    return SigKey(site, ident, tuple(avals), tuple(attrs), tuple(shardings),
                  platform, routing_knobs() if routing is None
                  else tuple(routing), tuple(extra))


# ---------------------------------------------------------------------------
# Tagged JSON codec: SigKeys and replay specs are nested tuples of
# primitives; JSON has no tuple, so tuples are tagged and restored exactly
# (tuple-vs-list identity matters — cache keys compare by ==/hash).
# ---------------------------------------------------------------------------

def _enc(obj):
    if isinstance(obj, tuple):
        return {"t": [_enc(x) for x in obj]}
    if isinstance(obj, list):
        return {"l": [_enc(x) for x in obj]}
    if isinstance(obj, dict):
        return {"d": [[_enc(k), _enc(v)] for k, v in obj.items()]}
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    # dtype objects, np scalars, ... — degrade to their canonical string
    return {"s": str(obj)}


def _dec(obj):
    if isinstance(obj, dict):
        if "t" in obj:
            return tuple(_dec(x) for x in obj["t"])
        if "l" in obj:
            return [_dec(x) for x in obj["l"]]
        if "d" in obj:
            return {_dec(k): _dec(v) for k, v in obj["d"]}
        if "s" in obj:
            return obj["s"]
    return obj


def encode(obj) -> str:
    """Deterministic JSON text for a primitive tree (tuples tagged)."""
    return json.dumps(_enc(obj), sort_keys=True, separators=(",", ":"))


def decode(text: str):
    return _dec(json.loads(text))


def fingerprint(obj) -> str:
    """Stable hex digest of a key / replay spec — the manifest's dedupe
    and lookup handle. Accepts a SigKey, tuple tree, or encoded str."""
    if not isinstance(obj, str):
        obj = encode(tuple(obj) if isinstance(obj, SigKey) else obj)
    return hashlib.sha256(obj.encode()).hexdigest()[:24]


# ---------------------------------------------------------------------------
# Graph identity: a structural fingerprint of a Block (architecture, not
# weights) so manifest entries recorded against replica 0 match replica N
# built from the same factory, and a restarted process can match entries
# against a freshly built net.
# ---------------------------------------------------------------------------

def graph_ident(block) -> str:
    """Structural fingerprint of a gluon Block: class tree + registered
    parameter names/dtypes/grad modes + hybridize flags. Two blocks built
    by the same factory get the same ident; weights don't matter
    (executables take parameter values as runtime inputs), and parameter
    SHAPES are deliberately excluded — a warm target may still carry
    deferred shapes, and the ident is a routing hint for
    :func:`~mxnet_tpu.compiler.warm_start` (the replay always re-lowers
    against the live block, so a loose match costs a compile, never a
    wrong executable)."""
    parts = []

    def walk(b, path):
        cls = type(b)
        parts.append((path, f"{cls.__module__}.{cls.__qualname__}",
                      callable_ident(getattr(cls, "hybrid_forward", None)
                                     or getattr(cls, "forward", None))))
        for name, p in sorted(getattr(b, "_reg_params", {}).items()):
            parts.append((path, name, str(p.dtype),
                          getattr(p, "grad_req", "write"),
                          getattr(p, "grad_stype", "default")))
        for name, child in getattr(b, "_children", {}).items():
            walk(child, f"{path}/{name}")

    walk(block, "")
    # falsy flags are the defaults: a fresh block ({}) and a plain
    # hybridize() ({'static_alloc': False, ...}) must share an ident —
    # warm targets are matched BEFORE the warm path hybridizes them
    flags = tuple(sorted(
        (k, v) for k, v in (getattr(block, "_flags", None) or {}).items()
        if v))
    return fingerprint(encode((tuple(parts), flags)))


def callable_ident(fn) -> str:
    """Behavioral fingerprint of a callable: qualified name + bytecode
    hash (a subclass that overrode forward, or an edited loss lambda,
    must not share a persisted executable with the original)."""
    if fn is None:
        return "none"
    target = getattr(fn, "__func__", fn)
    code = getattr(target, "__code__", None)
    name = f"{getattr(target, '__module__', '')}." \
           f"{getattr(target, '__qualname__', type(fn).__qualname__)}"
    if code is None:
        # callable object: identify by its class's __call__ bytecode
        call = getattr(type(fn), "__call__", None)
        code = getattr(call, "__code__", None)
        if code is None:
            return name
    return name + ":" + hashlib.sha256(code.co_code).hexdigest()[:12]
