"""Signature manifest — the on-disk journal of everything this process
compiled, replayable by :func:`mxnet_tpu.compiler.warm_start`.

Format: append-only JSONL. One object per line::

    {"v": 1, "site": "train_step", "fp": "<hex>", "spec": <tagged tree>}

``spec`` is the site's replay recipe (op name + attrs + avals for
``eager_op``, the node program for ``fused_segment``, graph ident + input
signatures for ``cached_op``/``train_step``), encoded with the tagged
tuple codec in :mod:`.keys` so it round-trips to exactly the tuples the
live cache keys compare against.

Durability: the file is created through ``checkpoint.atomic_write``
(write-temp + fsync + rename); each further record appends ONE fsynced
line. A crash mid-append can tear at most that line, and reading
tolerates torn/corrupt lines (plus hand edits, unknown sites, and
version-mismatched entries) — each is skipped and counted, not fatal:
a stale manifest warms less, it never breaks startup.

Location: ``MXNET_COMPILE_MANIFEST`` names the file (``1`` = the default
``<MXNET_XLA_CACHE_DIR>/manifests/signatures.jsonl``, sharing the
persistent XLA cache's base layout; ``0``/unset = recording off).
"""
from __future__ import annotations

import json
import logging
import os
import threading
from typing import Dict, List, Optional

from . import keys

__all__ = ["Manifest", "default_path", "recorder", "enable_recording",
           "disable_recording", "record_signature", "KNOWN_SITES",
           "MANIFEST_VERSION"]

_log = logging.getLogger(__name__)

MANIFEST_VERSION = 1

# sites warm_start knows how to handle; an entry whose site is absent here
# is stale (written by a newer/older build) and is skipped on load
KNOWN_SITES = ("eager_op", "fused_segment", "cached_op", "train_step",
               "executor", "optimizer_sweep")


def cache_base_dir() -> str:
    return os.environ.get(
        "MXNET_XLA_CACHE_DIR",
        os.path.join(os.path.expanduser("~"), ".cache", "mxnet_tpu_xla"))


def default_path() -> str:
    return os.path.join(cache_base_dir(), "manifests", "signatures.jsonl")


class Manifest:
    """One signature journal file: load-tolerant reader + atomic recorder."""

    def __init__(self, path: Optional[str] = None):
        self.path = path or default_path()
        self._lock = threading.Lock()
        self._entries: Optional[List[Dict]] = None   # loaded lazily
        self._fps = set()
        self.n_skipped = 0          # corrupt/stale lines seen at load

    # -- read ----------------------------------------------------------
    def _load_locked(self) -> List[Dict]:
        if self._entries is not None:
            return self._entries
        entries: List[Dict] = []
        self.n_skipped = 0
        try:
            with open(self.path, encoding="utf-8") as f:
                lines = f.readlines()
        except OSError:
            lines = []
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                raw = json.loads(line)
                if (raw.get("v") != MANIFEST_VERSION
                        or raw.get("site") not in KNOWN_SITES
                        or not isinstance(raw.get("fp"), str)):
                    raise ValueError("stale or malformed entry")
                entry = {"v": raw["v"], "site": raw["site"],
                         "fp": raw["fp"],
                         "spec": keys._dec(raw.get("spec"))}
            except Exception:
                self.n_skipped += 1
                continue
            if entry["fp"] in self._fps:
                continue
            self._fps.add(entry["fp"])
            entries.append(entry)
        self._entries = entries
        if self.n_skipped:
            _log.debug("manifest %s: skipped %d corrupt/stale line(s)",
                       self.path, self.n_skipped)
        return entries

    def entries(self) -> List[Dict]:
        with self._lock:
            return list(self._load_locked())

    def __len__(self) -> int:
        return len(self.entries())

    # -- write ---------------------------------------------------------
    def record(self, site: str, spec) -> Optional[str]:
        """Journal one compiled signature; returns its fingerprint, or
        None when it was already journaled (dedupe by fingerprint).

        Durability model: the journal is created (and compacted) through
        ``checkpoint.atomic_write``; subsequent records APPEND one
        fsynced line — O(1) per compile miss, and a torn tail line is
        exactly what the tolerant reader skips. A full rewrite per
        record would re-serialize the whole journal on the compile-miss
        path (O(n²) over a run — round-10 review finding)."""
        fp = keys.fingerprint((site, keys.encode(spec)))
        with self._lock:
            self._load_locked()
            if fp in self._fps:
                return None
            self._fps.add(fp)
            entry = {"v": MANIFEST_VERSION, "site": site, "fp": fp,
                     "spec": spec}
            self._entries.append(entry)
            line = json.dumps(
                {"v": entry["v"], "site": entry["site"],
                 "fp": entry["fp"], "spec": keys._enc(entry["spec"])},
                sort_keys=True) + "\n"
            try:
                os.makedirs(os.path.dirname(self.path) or ".",
                            exist_ok=True)
                if not os.path.exists(self.path):
                    from ..checkpoint import atomic_write

                    atomic_write(self.path, line.encode())
                else:
                    with open(self.path, "a", encoding="utf-8") as f:
                        f.write(line)
                        f.flush()
                        os.fsync(f.fileno())
            except Exception:
                # journaling is best-effort: a read-only cache dir must
                # not break compiles (the entry stays recorded in-memory)
                _log.debug("manifest %s: record failed", self.path,
                           exc_info=True)
        return fp


# ---------------------------------------------------------------------------
# Process-wide recorder: sites call record_signature() on every compile
# miss; it no-ops unless recording was enabled (env or API).
# ---------------------------------------------------------------------------

class _Recorder:
    __slots__ = ("manifest",)

    def __init__(self):
        self.manifest: Optional[Manifest] = None


_recorder = _Recorder()
_recorder_lock = threading.Lock()
_env_checked = False


def _check_env() -> None:
    global _env_checked
    if _env_checked:
        return
    with _recorder_lock:
        if _env_checked:
            return
        spec = os.environ.get("MXNET_COMPILE_MANIFEST", "")
        if spec and spec != "0":
            path = default_path() if spec == "1" else spec
            _recorder.manifest = Manifest(path)
        _env_checked = True


def enable_recording(path: Optional[str] = None) -> Manifest:
    """Start journaling compiled signatures to ``path`` (default: the
    shared cache layout). Returns the live Manifest."""
    global _env_checked
    with _recorder_lock:
        _recorder.manifest = Manifest(path)
        _env_checked = True
        return _recorder.manifest


def disable_recording() -> None:
    global _env_checked
    with _recorder_lock:
        _recorder.manifest = None
        _env_checked = True


def recorder() -> Optional[Manifest]:
    """The active manifest recorder, or None when recording is off."""
    _check_env()
    return _recorder.manifest


def record_signature(site: str, spec) -> None:
    """Journal one compiled signature (no-op when recording is off).
    Called by every cache site on a compile miss."""
    m = recorder()
    if m is None:
        return
    try:
        m.record(site, spec)
    except Exception:
        _log.debug("signature journaling failed for site %s", site,
                   exc_info=True)
