"""``mxnet_tpu.compiler`` — the unified compilation service.

One subsystem owns everything that turns a signature into an executable:

* **signature keying** (:mod:`.keys`) — the canonical key every jit
  cache uses (op/graph id + avals + shardings + routing knobs +
  platform), replacing five ad-hoc schemes;
* **site caches** (:mod:`.service.SiteCache`) — shared LRU policy with
  hit/miss *and eviction* telemetry across the five cache sites;
* **executable table** (:mod:`.service.ExecutableTable`) — in-process,
  single-flight dedupe of XLA compiles keyed by lowered-HLO fingerprint
  (N serving replicas = 1 compile);
* **signature manifest** (:mod:`.manifest`) — append-only JSONL journal
  of every compiled signature, written atomically under the
  ``MXNET_XLA_CACHE_DIR`` layout;
* **AOT warm-start** (:func:`warm_start`) — replay a manifest through
  ``jax.jit(...).lower().compile()`` before first traffic;
* **persistent disk tier** (:mod:`.persistent`) — the managed jax
  compilation cache: ISA-namespaced, size-capped GC.

This module is import-light (the package ``__init__`` imports
``compiler.persistent`` before jax is configured); the service surface
loads lazily on first use.
"""
from __future__ import annotations

from . import keys
from .keys import SigKey, fingerprint, graph_ident, routing_knobs, signature

__all__ = [
    "SigKey", "signature", "fingerprint", "graph_ident", "routing_knobs",
    "Manifest", "enable_recording", "disable_recording", "recorder",
    "record_signature", "default_manifest_path",
    "SiteCache", "ExecutableTable", "GuardedExec", "exec_table",
    "warm_start", "mark_event", "events", "seconds_since_import",
    "cache_dir", "gc_cache", "keys",
]

_LAZY = {
    "Manifest": ("manifest", "Manifest"),
    "enable_recording": ("manifest", "enable_recording"),
    "disable_recording": ("manifest", "disable_recording"),
    "recorder": ("manifest", "recorder"),
    "record_signature": ("manifest", "record_signature"),
    "default_manifest_path": ("manifest", "default_path"),
    "SiteCache": ("service", "SiteCache"),
    "ExecutableTable": ("service", "ExecutableTable"),
    "GuardedExec": ("service", "GuardedExec"),
    "exec_table": ("service", "exec_table"),
    "warm_start": ("service", "warm_start"),
    "mark_event": ("service", "mark_event"),
    "events": ("service", "events"),
    "seconds_since_import": ("service", "seconds_since_import"),
    "cache_dir": ("persistent", "cache_dir"),
    "gc_cache": ("persistent", "gc_cache"),
}


def __getattr__(name):
    try:
        modname, attr = _LAZY[name]
    except KeyError:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}") from None
    import importlib

    mod = importlib.import_module(f".{modname}", __name__)
    value = getattr(mod, attr)
    globals()[name] = value
    return value
