"""``mx.tracing`` — end-to-end request tracing + flight recorder.

The third observability layer (after the profiler's per-op timelines and
telemetry's aggregate counters): per-request *causality* through the
serving stack. A trace is minted at the edge (``Ingress`` /
``Router.submit`` / ``Server.submit``), its context rides the
:mod:`.serving.wire` JSON frame header across the process boundary
(backward-compatible: an absent field is an untraced request), and every
stage a request crosses — ``ingress.decode``, ``router.queue``,
``router.attempt``, ``batch.wait``, ``dispatch``, ``wire.return`` —
contributes one span. A batch ``dispatch`` span is shared by the N
co-batched requests and linked to each of their ``batch.wait`` spans via
chrome-trace flow events (one dispatch serves many requests — the
linkage is the point). Worker-side spans ship back piggybacked on the
result frame, so the parent holds ONE connected trace for an
out-of-process request; a failover chain reads as one trace with one
``router.attempt`` span per replica tried, annotated by ``fault.py``
when the failure was injected.

Default-off with the telemetry/fault fast path: instrumented hot paths
cache a reference to ``_state`` and guard on ``_state.enabled`` — one
attribute load + branch, zero allocations per request while disabled.
Enable with ``MXNET_TRACING=1`` (inherited by serving worker processes)
or :func:`enable`.

On top rides the **flight recorder**: a bounded ring of recently
completed traces plus structured events (breaker transitions, shed
decisions, worker crashes/respawns, reloads). Routers and workers dump
it as JSONL — through ``checkpoint.atomic_write``, a crash mid-dump
must not tear the file — on breaker trip, worker crash/orphaning,
SIGTERM (worker processes), or interpreter exit when
``MXNET_TRACING_OUT=PATH`` is set (each process writes
``PATH.<pid>.jsonl``-style siblings so a fleet never clobbers one
file). ``tools/latency_report.py`` aggregates trace JSONL into the
per-stage p50/p99 decomposition serving_bench stage 8 hand-rolled.

Export paths: :func:`chrome_trace_events` (merged into
``profiler.dumps(format="chrome_trace")``), :func:`dump_jsonl` /
:func:`dump` (the flight-recorder ring), and OpenMetrics exemplars —
the serving latency histograms attach ``# {trace_id="..."}`` to the
bucket a traced request lands in, so a scraped p99 links to a concrete
trace (see ``telemetry.record_serving_request``).
"""
from __future__ import annotations

import collections
import contextlib
import itertools
import json
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

__all__ = [
    "enable", "disable", "enabled", "reset",
    "Trace", "Span", "new_trace", "adopt",
    "active", "ambient", "note",
    "begin_batch", "end_batch",
    "record_event", "events", "recorder", "FlightRecorder",
    "dump", "dump_jsonl", "maybe_dump", "dump_path",
    "chrome_trace_events", "set_process_name", "now_us",
]


class _State:
    __slots__ = ("enabled",)

    def __init__(self, enabled: bool):
        self.enabled = enabled


# THE fast-path guard — same contract as telemetry/fault: instrumented
# modules cache a reference to `_state` and branch on `.enabled`; the
# instance is never swapped.
_state = _State(os.environ.get("MXNET_TRACING", "0") == "1")


def enabled() -> bool:
    return _state.enabled


def enable() -> None:
    _state.enabled = True


def disable() -> None:
    _state.enabled = False


def now_us() -> int:
    """Wall-clock epoch microseconds — spans from different processes on
    one host align on this axis (the serving fleet is single-host)."""
    return time.time_ns() // 1000


# process role shown on every span this process creates ("router host",
# "worker:w0", ...); worker main() sets it from its --name
_proc_name = f"pid{os.getpid()}"


def set_process_name(name: str) -> None:
    global _proc_name
    _proc_name = str(name)


# trace/span ids: 64-bit hex; flow ids: process-unique ints salted with
# the pid so flows minted in a worker never collide with the parent's
_id_lock = threading.Lock()
_id_counter = itertools.count(1)


def _mint_id() -> str:
    with _id_lock:
        n = next(_id_counter)
    return f"{os.getpid():08x}{n:08x}"


_flow_counter = itertools.count(1)


def _mint_flow() -> int:
    return os.getpid() * 1_000_000 + next(_flow_counter)


class Span:
    """One timed stage of one trace. Created via :meth:`Trace.begin`,
    sealed with :meth:`end` (which appends its dict form to the owning
    trace). ``note``/``tag`` annotate the live span — ``fault.py`` uses
    them so injected faults and retries show up inside the stage they
    hit."""

    __slots__ = ("trace", "span_id", "parent_id", "name", "ts", "dur",
                 "tags", "notes", "flow_out", "flows_in", "_fanout",
                 "_done")

    def __init__(self, trace: "Trace", name: str,
                 parent_id: Optional[str], tags: Optional[dict]):
        self.trace = trace
        self.span_id = _mint_id()
        self.parent_id = parent_id
        self.name = name
        self.ts = now_us()
        self.dur = None
        self.tags = dict(tags) if tags else None
        self.notes: Optional[list] = None
        self.flow_out: Optional[int] = None   # this span starts a flow
        self.flows_in: Optional[list] = None  # flows ending at this span
        self._fanout = None   # batch spans: sibling traces to copy into
        self._done = False

    def tag(self, **kv) -> None:
        if self.tags is None:
            self.tags = {}
        self.tags.update(kv)

    def note(self, text: str) -> None:
        if self.notes is None:
            self.notes = []
        self.notes.append([now_us(), str(text)])

    def end(self, **tags) -> None:
        if self._done:
            return
        self._done = True
        if tags:
            self.tag(**tags)
        self.dur = max(now_us() - self.ts, 0)
        self.trace._add(self.as_dict())

    def as_dict(self) -> dict:
        d = {"trace_id": self.trace.trace_id, "span_id": self.span_id,
             "name": self.name, "ts": self.ts,
             "dur": self.dur if self.dur is not None else 0,
             "proc": _proc_name, "pid": os.getpid()}
        if self.parent_id:
            d["parent_id"] = self.parent_id
        if self.tags:
            d["tags"] = self.tags
        if self.notes:
            d["notes"] = self.notes
        if self.flow_out is not None:
            d["flow_out"] = self.flow_out
        if self.flows_in:
            d["flows_in"] = list(self.flows_in)
        return d


class Trace:
    """One request's spans, across threads and (merged) processes.
    Thread-safe: span ends, merges and ``finish`` may race between the
    submitting thread, scheduler threads and reader threads; the first
    ``finish`` wins and hands the sealed record to the flight
    recorder."""

    __slots__ = ("trace_id", "root", "remote_parent", "spans", "events",
                 "status", "_lock", "_finished")

    def __init__(self, trace_id: Optional[str] = None,
                 root_name: Optional[str] = None,
                 parent_id: Optional[str] = None,
                 tags: Optional[dict] = None):
        self.trace_id = trace_id or _mint_id()
        self.remote_parent = parent_id
        self.spans: List[dict] = []
        self.events: Optional[list] = None
        self.status: Optional[str] = None
        self._lock = threading.Lock()
        self._finished = False
        self.root = None     # set below; begin() reads it for defaults
        if root_name:
            self.root = self.begin(root_name, parent=parent_id,
                                   **(tags or {}))

    def begin(self, name: str, parent=None, **tags) -> Span:
        """Open a span. ``parent`` may be a :class:`Span`, a span-id
        string (the wire form), or None (defaults to the root span)."""
        if parent is None:
            parent = self.root
        pid = parent.span_id if isinstance(parent, Span) else parent
        return Span(self, name, pid, tags or None)

    def _add(self, span_dict: dict) -> None:
        with self._lock:
            self.spans.append(span_dict)

    def add_raw(self, name: str, ts: int, dur: int, parent=None,
                **tags) -> None:
        """Record an already-measured interval (e.g. ``wire.return``
        reconstructed from the worker's send timestamp) without opening
        a live span."""
        pid = parent.span_id if isinstance(parent, Span) else parent
        d = {"trace_id": self.trace_id, "span_id": _mint_id(),
             "name": name, "ts": int(ts), "dur": max(int(dur), 0),
             "proc": _proc_name, "pid": os.getpid()}
        if pid:
            d["parent_id"] = pid
        if tags:
            d["tags"] = tags
        self._add(d)

    def merge(self, span_dicts) -> None:
        """Adopt spans shipped back from another process (the result
        frame's piggyback). Non-list / non-dict payloads are ignored —
        the wire is not trusted to crash the reader thread."""
        if not isinstance(span_dicts, list):
            return
        with self._lock:
            for d in span_dicts:
                if isinstance(d, dict):
                    self.spans.append(d)

    def note(self, text: str) -> None:
        """Trace-level annotation (no live span to attach to — e.g. a
        worker crash observed by the supervisor thread)."""
        with self._lock:
            if self.events is None:
                self.events = []
            self.events.append([now_us(), str(text)])

    def wire(self, parent=None) -> dict:
        """The frame-header context: ``{"id": ..., "parent": ...}``.
        Absent field = untraced request (backward-compatible by
        construction — ``wire.recv_frame`` passes unknown header fields
        through)."""
        if parent is None:
            parent = self.root
        pid = parent.span_id if isinstance(parent, Span) else parent
        ctx = {"id": self.trace_id}
        if pid:
            ctx["parent"] = pid
        return ctx

    def export_spans(self) -> List[dict]:
        """JSON-safe copies of the finished spans (the worker-side
        result-frame piggyback)."""
        with self._lock:
            return list(self.spans)

    def finish(self, status: str = "ok") -> None:
        """Seal the trace (first call wins), ending the root span, and
        hand the record to the flight recorder."""
        with self._lock:
            if self._finished:
                return
            self._finished = True
        self.status = status
        if self.root is not None and not self.root._done:
            self.root.end(status=status)
        _recorder.record_trace(self.record())

    def record(self) -> dict:
        with self._lock:
            spans = list(self.spans)
        d = {"trace_id": self.trace_id, "status": self.status or "open",
             "spans": spans}
        if self.events:
            d["events"] = list(self.events)
        return d

    def finish_from_future(self, fut) -> None:
        """Done-callback form of :meth:`finish`: status from the
        future's resolution (the exception's type name, or ``ok``)."""
        try:
            exc = fut.exception()
        except BaseException as e:  # noqa: BLE001 - cancelled etc.
            exc = e
        self.finish("ok" if exc is None else type(exc).__name__)


def new_trace(name: str = "request", **tags) -> Trace:
    """Mint a fresh trace with a root span called ``name``."""
    return Trace(root_name=name, tags=tags or None)


def adopt(ctx, **tags) -> Optional[Trace]:
    """Continue a trace from its wire context (``Trace.wire`` form, as
    read from a frame header). Returns None on a malformed context —
    a bad peer must degrade to an untraced request, never an error."""
    if not isinstance(ctx, dict):
        return None
    tid = ctx.get("id")
    if not isinstance(tid, str):
        return None
    parent = ctx.get("parent")
    tr = Trace(trace_id=tid,
               parent_id=parent if isinstance(parent, str) else None)
    if tags:
        tr.note("adopted " + json.dumps(tags, sort_keys=True))
    return tr


# ---------------------------------------------------------------------------
# Ambient context: how trace context crosses the synchronous call seams
# that share a signature between traced and untraced callers
# (Router._route -> replica.submit works for Server AND RemoteReplica
# without changing the dispatch contract).
# ---------------------------------------------------------------------------

_tls = threading.local()


@contextlib.contextmanager
def active(trace: Trace, parent=None):
    """Make ``(trace, parent)`` the ambient context for calls made by
    this thread inside the block. ``parent`` is the Span (or span-id
    string) child spans should hang off."""
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    stack.append((trace, parent))
    try:
        yield
    finally:
        stack.pop()


def ambient() -> Optional[Tuple[Trace, object]]:
    """The innermost ``(trace, parent)`` set by :func:`active` on this
    thread, or None."""
    stack = getattr(_tls, "stack", None)
    if not stack:
        return None
    return stack[-1]


def note(text: str) -> None:
    """Annotate the innermost ambient span (no-op without one) — the
    ``fault.py`` hook: an injected fault or a retry lands inside the
    stage span that was live when it fired."""
    amb = ambient()
    if amb is None:
        return
    trace, parent = amb
    if isinstance(parent, Span):
        parent.note(text)
    else:
        trace.note(text)


# ---------------------------------------------------------------------------
# Batch spans: one dispatch serves N requests; link them.
# ---------------------------------------------------------------------------

def begin_batch(items, name: str = "dispatch", wait_tags: Optional[dict] = None,
                **tags) -> Optional[Span]:
    """Close the co-batched requests' wait spans and open the shared
    batch span. ``items`` is ``[(Trace, Span-or-None), ...]`` for the
    traced requests in the batch; each wait span ends NOW (dispatch
    start) carrying ``wait_tags`` and a chrome-trace flow id that
    terminates at the batch span. Returns the batch span (owned by the
    first trace; :func:`end_batch` copies it into the others so every
    trace is self-contained)."""
    items = [(tr, sp) for tr, sp in items if tr is not None]
    if not items:
        return None
    flows = []
    for _tr, sp in items:
        if sp is not None and not sp._done:
            fid = _mint_flow()
            sp.flow_out = fid
            flows.append(fid)
            if wait_tags:
                sp.end(**wait_tags)
            else:
                sp.end()
    tr0 = items[0][0]
    bsp = Span(tr0, name, None, tags or None)
    bsp.flows_in = flows
    bsp.tag(batch=len(items))
    bsp._fanout = [tr for tr, _sp in items[1:]]
    return bsp


def end_batch(bsp: Optional[Span], **tags) -> None:
    """Seal a :func:`begin_batch` span and copy its dict into every
    other participating trace (dedup'd at export by span_id)."""
    if bsp is None:
        return
    fanout = bsp._fanout or []
    bsp.end(**tags)
    d = bsp.as_dict()
    for tr in fanout:
        tr._add(d)


# ---------------------------------------------------------------------------
# Flight recorder: the bounded ring of completed traces + structured
# events, dumped as JSONL when something goes wrong.
# ---------------------------------------------------------------------------

class FlightRecorder:
    """Bounded ring of recently completed traces and structured events
    (breaker transitions, sheds, crashes, respawns, reloads, dumps).
    Everything is plain dicts so a dump is one ``json.dumps`` per line;
    thread-safe."""

    def __init__(self, trace_capacity: int = 256,
                 event_capacity: int = 1024):
        self._lock = threading.Lock()
        self._traces = collections.deque(maxlen=trace_capacity)
        self._events = collections.deque(maxlen=event_capacity)
        self.n_traces = 0
        self.n_events = 0

    def record_trace(self, record: dict) -> None:
        with self._lock:
            self._traces.append(record)
            self.n_traces += 1

    def record_event(self, kind: str, **fields) -> None:
        ev = {"event": str(kind), "ts": now_us(), "proc": _proc_name,
              "pid": os.getpid()}
        ev.update(fields)
        with self._lock:
            self._events.append(ev)
            self.n_events += 1

    def traces(self) -> List[dict]:
        with self._lock:
            return list(self._traces)

    def events(self) -> List[dict]:
        with self._lock:
            return list(self._events)

    def clear(self) -> None:
        with self._lock:
            self._traces.clear()
            self._events.clear()

    def dump_jsonl(self) -> str:
        """The ring as JSONL: events first (fleet weather), then one
        line per completed trace."""
        with self._lock:
            events = list(self._events)
            traces = list(self._traces)
        lines = [json.dumps(e, sort_keys=True) for e in events]
        lines.extend(json.dumps(t, sort_keys=True) for t in traces)
        return "\n".join(lines) + ("\n" if lines else "")

    def dump(self, path: str) -> None:
        """Atomic JSONL dump — dumps happen at the worst moments
        (crash, SIGTERM, breaker trip); a torn file would be a second
        incident. Routes through ``checkpoint.atomic_write``."""
        from . import checkpoint

        checkpoint.atomic_write(path, self.dump_jsonl().encode("utf-8"))


_recorder = FlightRecorder()


def recorder() -> FlightRecorder:
    return _recorder


def record_event(kind: str, **fields) -> None:
    """Record one structured event into the flight recorder. Callers on
    hot paths guard with ``_state.enabled`` themselves (the
    telemetry/fault pattern)."""
    if not _state.enabled:
        return
    _recorder.record_event(kind, **fields)


def events(kind: Optional[str] = None) -> List[dict]:
    """The flight recorder's event ring (optionally filtered to one
    ``kind``) — the in-process read side of :func:`record_event`, e.g.
    ``tracing.events("preempted")`` to find who preempted whom without
    round-tripping a JSONL dump."""
    evs = _recorder.events()
    if kind is None:
        return evs
    return [e for e in evs if e.get("event") == kind]


def dump_jsonl() -> str:
    return _recorder.dump_jsonl()


def dump(path: str) -> None:
    _recorder.dump(path)


def dump_path() -> Optional[str]:
    """Where :func:`maybe_dump` writes: ``MXNET_TRACING_OUT`` with the
    pid woven in (router and worker processes inherit the same env —
    per-pid siblings keep a fleet from clobbering one file)."""
    out = os.environ.get("MXNET_TRACING_OUT")
    if not out:
        return None
    base, ext = os.path.splitext(out)
    return f"{base}.{os.getpid()}{ext or '.jsonl'}"


def maybe_dump(reason: str) -> Optional[str]:
    """Dump the flight recorder if tracing is enabled and
    ``MXNET_TRACING_OUT`` is set; records the dump itself as an event.
    Returns the path written (or None). Never raises — this runs on
    crash/SIGTERM paths where a secondary failure must not mask the
    primary one."""
    if not _state.enabled:
        return None
    path = dump_path()
    if path is None:
        return None
    try:
        _recorder.record_event("dump", reason=str(reason), path=path)
        _recorder.dump(path)
        return path
    except Exception:   # noqa: BLE001 - best-effort by contract
        return None


def reset() -> None:
    """Disable tracing and clear the recorder ring (test isolation)."""
    _state.enabled = False
    _recorder.clear()


# MXNET_TRACING_OUT=PATH: dump the ring at interpreter exit too (the
# MXNET_TELEMETRY_OUT contract) — a clean run still leaves the evidence.
if os.environ.get("MXNET_TRACING_OUT"):
    import atexit

    _state.enabled = True
    atexit.register(maybe_dump, "atexit")


# ---------------------------------------------------------------------------
# Chrome-trace export: merged into profiler.dumps(format="chrome_trace").
# ---------------------------------------------------------------------------

def chrome_trace_events() -> List[Dict]:
    """The flight-recorder ring as chrome-trace events: one ``ph:"X"``
    per span (dedup'd by span_id — a batch span is copied into every
    participating trace), ``ph:"s"``/``ph:"f"`` flow-event pairs linking
    each request's ``batch.wait`` span to its batch ``dispatch`` span,
    and one instant event per recorder event. Timestamps are epoch
    microseconds (one host, one axis)."""
    events: List[Dict] = []
    seen = set()
    for rec in _recorder.traces():
        for d in rec.get("spans", []):
            sid = d.get("span_id")
            if sid in seen:
                continue
            seen.add(sid)
            pid = d.get("pid", 0)
            tid = d.get("proc", "")
            args = {"trace_id": d.get("trace_id")}
            if d.get("tags"):
                args.update(d["tags"])
            if d.get("notes"):
                args["notes"] = [n[1] for n in d["notes"]]
            events.append({"name": d.get("name", "span"), "ph": "X",
                           "cat": "serving", "pid": pid, "tid": tid,
                           "ts": d.get("ts", 0), "dur": d.get("dur", 0),
                           "args": args})
            end_ts = d.get("ts", 0) + d.get("dur", 0)
            if d.get("flow_out") is not None:
                events.append({"name": "batch", "ph": "s",
                               "cat": "serving", "id": d["flow_out"],
                               "pid": pid, "tid": tid, "ts": end_ts})
            for fid in d.get("flows_in", ()):
                events.append({"name": "batch", "ph": "f", "bp": "e",
                               "cat": "serving", "id": fid, "pid": pid,
                               "tid": tid, "ts": d.get("ts", 0)})
    for ev in _recorder.events():
        events.append({"name": ev.get("event", "event"), "ph": "i",
                       "cat": "serving", "s": "g",
                       "pid": ev.get("pid", 0),
                       "tid": ev.get("proc", ""),
                       "ts": ev.get("ts", 0),
                       "args": {k: v for k, v in ev.items()
                                if k not in ("event", "ts")}})
    return events
