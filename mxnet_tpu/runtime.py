"""``mx.runtime`` — build/runtime feature detection (reference:
``python/mxnet/runtime.py`` over ``src/libinfo.cc``).

The reference enumerates compile-time flags (CUDA, CUDNN, MKLDNN, OPENCV,
...). Here features are *runtime-probed*: what matters on a JAX/TPU stack
is which backends, kernels, and native components this process can
actually use.
"""
from __future__ import annotations

import collections

__all__ = ["Feature", "feature_list", "Features"]

Feature = collections.namedtuple("Feature", ["name", "enabled"])


def _probe():
    feats = {}

    def add(name, fn):
        try:
            feats[name] = bool(fn())
        except Exception:
            feats[name] = False

    import jax

    add("TPU", lambda: any(d.platform != "cpu" for d in jax.devices()))
    add("CPU", lambda: True)
    add("BF16", lambda: True)                      # native on XLA everywhere
    add("X64", lambda: jax.config.read("jax_enable_x64"))
    add("PALLAS", lambda: __import__(
        "jax.experimental.pallas", fromlist=["pallas"]) is not None)
    add("FLASH_ATTENTION", lambda: __import__(
        "mxnet_tpu.pallas_kernels", fromlist=["flash_attention"]
    ).flash_attention is not None)
    # build-level capability (like the reference's compile-time flag):
    # the coordination-service entry point exists in this jax build
    add("DIST_KVSTORE",
        lambda: callable(getattr(jax.distributed, "initialize", None)))
    add("NATIVE_RECORDIO", lambda: __import__(
        "mxnet_tpu._native", fromlist=["recordio_lib"]
    ).recordio_lib() is not None)

    def _pil():
        import PIL  # noqa: F401

        return True

    add("IMAGE_CODECS", _pil)                       # reference: OPENCV
    add("AMP", lambda: True)
    add("INT64_TENSOR_SIZE", lambda: True)
    # reference flags with no TPU meaning, reported disabled for parity
    for off in ("CUDA", "CUDNN", "NCCL", "TENSORRT", "MKLDNN", "OPENCV"):
        feats[off] = False
    return feats


class Features(dict):
    """Mapping name -> Feature (reference: runtime.Features)."""

    instance = None

    def __init__(self):
        super().__init__(
            {n: Feature(n, on) for n, on in _probe().items()})

    def __repr__(self):
        on = [n for n, f in sorted(self.items()) if f.enabled]
        off = [n for n, f in sorted(self.items()) if not f.enabled]
        return f"[✔ {', '.join(on)}] [✖ {', '.join(off)}]"

    def is_enabled(self, feature_name: str) -> bool:
        name = feature_name.upper()
        if name not in self:
            raise RuntimeError(f"unknown feature {feature_name!r}")
        return self[name].enabled


def feature_list():
    """List of Feature namedtuples (reference: runtime.feature_list)."""
    return list(Features().values())
