"""Functional-mutation capture for traced (hybridized) execution.

Reference problem: MXNet ops mutate state in place during forward —
BatchNorm moving stats (aux states), RNG state — and CachedOp simply
re-executes those mutations imperatively
(``src/imperative/cached_op.cc :: CachedOp::Forward``).

Under XLA everything inside a jit trace is pure, so in-place writes of
traced values must become *extra outputs* of the compiled function. While a
hybridize trace is active, ``NDArray._set_data`` routes tracer writes here;
the CachedGraph returns the logged values as additional outputs and writes
the concrete results back after execution. This is the TPU-native
re-design of MXNet's aux-state mutation contract.
"""
from __future__ import annotations

import contextlib
import threading
from typing import List

_state = threading.local()


class MutationLog:
    def __init__(self):
        self.arrays: List = []  # NDArray objects, in first-write order
        # (arr, payload-before-first-traced-write) pairs; parallel to arrays
        self.originals: List = []

    def log(self, arr) -> None:
        if not any(a is arr for a in self.arrays):
            self.arrays.append(arr)
            self.originals.append((arr, arr._data))


def active_log():
    return getattr(_state, "log", None)


def is_tracing() -> bool:
    return getattr(_state, "log", None) is not None


@contextlib.contextmanager
def mutation_scope():
    prev = getattr(_state, "log", None)
    _state.log = MutationLog()
    try:
        yield _state.log
    finally:
        _state.log = prev
