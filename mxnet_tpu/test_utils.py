"""``mx.test_utils`` — the public testing/oracle surface (reference:
``python/mxnet/test_utils.py``).

SURVEY.md §4 calls this the kernel oracle: numeric-gradient checks by
central difference, cross-device consistency runs, tolerance-aware
comparison with located mismatches. The TPU-native consistency check runs
a function on the CPU oracle device vs the accelerator, replacing the
reference's cpu-vs-gpu ctx list.
"""
from __future__ import annotations

import numpy as _np

from .base import MXNetError
from .context import Context, cpu, current_context, default_accelerator

__all__ = ["default_context", "set_default_context", "rand_ndarray",
           "assert_almost_equal", "almost_equal", "same",
           "check_numeric_gradient", "check_consistency", "rand_shape_2d",
           "rand_shape_3d", "rand_shape_nd", "effective_dtype",
           "default_rtols", "default_atols"]

_DEFAULT_RTOL = {
    _np.dtype(_np.float16): 1e-2, _np.dtype(_np.float32): 1e-4,
    _np.dtype(_np.float64): 1e-6,
}
_DEFAULT_ATOL = {
    _np.dtype(_np.float16): 1e-3, _np.dtype(_np.float32): 1e-5,
    _np.dtype(_np.float64): 1e-8,
}


def default_rtols():
    return dict(_DEFAULT_RTOL)


def default_atols():
    return dict(_DEFAULT_ATOL)


def default_context() -> Context:
    return current_context()


def set_default_context(ctx: Context):
    Context._default_ctx.value = ctx


def effective_dtype(arr):
    """The dtype tolerances should be judged at (bf16 counts as f16-ish)."""
    dt = getattr(arr, "dtype", None)
    if str(dt) == "bfloat16":
        return _np.dtype(_np.float16)
    try:
        return _np.dtype(dt)
    except TypeError:
        return _np.dtype(_np.float64)


def _as_np(a):
    if hasattr(a, "asnumpy"):
        return a.asnumpy()
    return _np.asarray(a)


def rand_shape_2d(dim0=10, dim1=10):
    return tuple(_np.random.randint(1, d + 1) for d in (dim0, dim1))


def rand_shape_3d(dim0=10, dim1=10, dim2=10):
    return tuple(_np.random.randint(1, d + 1) for d in (dim0, dim1, dim2))


def rand_shape_nd(num_dim, dim=10):
    return tuple(_np.random.randint(1, dim + 1, size=num_dim))


def rand_ndarray(shape, dtype="float32", ctx=None):
    from .ndarray import array

    return array(_np.random.randn(*shape).astype(dtype), ctx=ctx)


def same(a, b):
    return _np.array_equal(_as_np(a), _as_np(b))


def almost_equal(a, b, rtol=None, atol=None, equal_nan=False):
    a_np, b_np = _as_np(a), _as_np(b)
    dt = max(effective_dtype(a), effective_dtype(b),
             key=lambda d: _DEFAULT_RTOL.get(d, 1e-6))
    rtol = rtol if rtol is not None else _DEFAULT_RTOL.get(dt, 1e-5)
    atol = atol if atol is not None else _DEFAULT_ATOL.get(dt, 1e-6)
    return _np.allclose(a_np.astype(_np.float64), b_np.astype(_np.float64),
                        rtol=rtol, atol=atol, equal_nan=equal_nan)


def assert_almost_equal(a, b, rtol=None, atol=None, names=("a", "b"),
                        equal_nan=False):
    """Tolerance-aware comparison with located mismatch report (reference:
    test_utils.assert_almost_equal)."""
    a_np = _as_np(a).astype(_np.float64)
    b_np = _as_np(b).astype(_np.float64)
    dt = max(effective_dtype(a), effective_dtype(b),
             key=lambda d: _DEFAULT_RTOL.get(d, 1e-6))
    rtol = rtol if rtol is not None else _DEFAULT_RTOL.get(dt, 1e-5)
    atol = atol if atol is not None else _DEFAULT_ATOL.get(dt, 1e-6)
    if _np.allclose(a_np, b_np, rtol=rtol, atol=atol, equal_nan=equal_nan):
        return
    diff = _np.abs(a_np - b_np)
    denom = _np.abs(b_np) + atol / max(rtol, 1e-300)
    rel = diff / _np.maximum(denom, 1e-300)
    idx = _np.unravel_index(_np.argmax(rel), rel.shape) if rel.size else ()
    raise AssertionError(
        f"{names[0]} and {names[1]} differ beyond rtol={rtol} atol={atol}: "
        f"max rel err {rel.max():.3g} at {tuple(int(i) for i in idx)} "
        f"({names[0]}={a_np[idx]!r}, {names[1]}={b_np[idx]!r}); "
        f"max abs err {diff.max():.3g}")


def check_numeric_gradient(fn, inputs, eps=1e-3, rtol=1e-2, atol=1e-4):
    """Central-difference gradient oracle for a scalar-output function
    (reference: check_numeric_gradient; here fn is a python callable over
    NDArrays so it covers ops, blocks, and compositions alike)."""
    from . import autograd
    from .ndarray import array

    inputs = [array(_as_np(x).astype(_np.float64)) for x in inputs]
    for x in inputs:
        x.attach_grad()
    with autograd.record():
        out = fn(*inputs)
        if out.shape not in ((), (1,)):
            out = out.sum()
    out.backward()
    for k, x in enumerate(inputs):
        x_np = x.asnumpy()
        num = _np.zeros_like(x_np)
        flat = x_np.reshape(-1)
        for i in range(flat.size):
            for sgn in (+1, -1):
                pert = flat.copy()
                pert[i] += sgn * eps
                val = fn(*[array(pert.reshape(x_np.shape))
                           if j == k else inputs[j]
                           for j in range(len(inputs))])
                val = val.sum() if val.shape not in ((), (1,)) else val
                num.reshape(-1)[i] += sgn * float(val.asnumpy().reshape(()))
        num /= 2 * eps
        assert_almost_equal(x.grad, num, rtol=rtol, atol=atol,
                            names=(f"autograd[{k}]", f"numeric[{k}]"))


def check_consistency(fn, inputs, ctx_list=None, rtol=None, atol=None):
    """Run ``fn`` on each context and compare results against the first
    (reference: check_consistency over a cpu/gpu ctx_list; here the list
    defaults to [cpu oracle, local accelerator])."""
    from .ndarray import array

    ctx_list = ctx_list or [cpu(0), default_accelerator()]
    results = []
    for ctx in ctx_list:
        xs = [array(_as_np(x), ctx=ctx) for x in inputs]
        out = fn(*xs)
        outs = out if isinstance(out, (list, tuple)) else [out]
        results.append([_as_np(o) for o in outs])
    base = results[0]
    for ctx, res in zip(ctx_list[1:], results[1:]):
        for i, (a, b) in enumerate(zip(base, res)):
            assert_almost_equal(
                a, b, rtol=rtol, atol=atol,
                names=(f"{ctx_list[0]}[{i}]", f"{ctx}[{i}]"))
    return results
