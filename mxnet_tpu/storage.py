"""``mx.storage`` — device memory introspection & pool controls
(reference: ``src/storage/storage.cc`` :: ``StorageImpl`` /
``GPUPooledStorageManager``, python surface ``mx.context.gpu_memory_info``
and the ``MXNET_GPU_MEM_POOL_*`` env plane).

ADR — why there is no allocator here: the reference owns a caching device
allocator (round/naive pools, shared-memory segments for dataloader IPC)
because CUDA malloc is slow and workers share tensors over shm. On TPU,
PjRt owns HBM with its own BFC pool — re-implementing a pool UNDER it
would double-count memory and fight the XLA scheduler. What remains
framework-level, and lives here, is:

* observability — per-device pool stats (bytes in use, peak, limit),
  the data `mx.profiler`'s memory view and OOM messages need;
* the env-plane mapping (reference knob → XLA/PjRt knob), so ported
  run-scripts can be translated mechanically;
* host-side sharing — the dataloader's worker IPC uses OS shared memory
  on the host path (gluon.data), never device shm, because batches are
  device_put once per step anyway.

Env mapping (reference → here):
  MXNET_GPU_MEM_POOL_RESERVE  → XLA_PYTHON_CLIENT_MEM_FRACTION
  MXNET_GPU_MEM_POOL_TYPE     → (PjRt BFC; not selectable)
  MXNET_USE_FUSION            → (always on — XLA fusion)
"""
from __future__ import annotations

from typing import Dict, Optional

from .base import MXNetError
from .context import Context, current_context

__all__ = ["memory_info", "pool_stats", "empty_cache"]


def _dev(ctx: Optional[Context]):
    ctx = ctx or current_context()
    return ctx.jax_device()


def memory_info(ctx: Optional[Context] = None):
    """(free_bytes, total_bytes) for a device (reference:
    ``mx.context.gpu_memory_info``). Falls back to (0, 0) when the
    platform exposes no stats (CPU)."""
    stats = _dev(ctx).memory_stats() or {}
    total = stats.get("bytes_limit", 0)
    used = stats.get("bytes_in_use", 0)
    return (max(total - used, 0), total)


def pool_stats(ctx: Optional[Context] = None) -> Dict[str, int]:
    """Allocator statistics for one device — PjRt's BFC pool counters,
    the storage.cc pool observability equivalent."""
    stats = _dev(ctx).memory_stats() or {}
    return {
        "bytes_in_use": stats.get("bytes_in_use", 0),
        "peak_bytes_in_use": stats.get("peak_bytes_in_use", 0),
        "bytes_limit": stats.get("bytes_limit", 0),
        "num_allocs": stats.get("num_allocs", 0),
        "largest_alloc_size": stats.get("largest_alloc_size", 0),
    }


def empty_cache(ctx: Optional[Context] = None):
    """Best-effort pool release (reference: Context::empty_cache). PjRt
    frees buffers on GC; this forces a collection pass."""
    (ctx or current_context()).empty_cache()
