"""``mx.recordio`` — RecordIO container + MXNet record packing (reference:
``python/mxnet/recordio.py`` over dmlc-core's recordio.h).

Byte-compatible with upstream: files written here load in upstream MXNet
and vice versa. The container hot path (framing scan, multi-part
reassembly, index builds) runs in C++ (``_native/recordio.cpp``, the role
of dmlc-core's C++ reader inside ``iter_image_recordio_2.cc``) with a
pure-Python fallback when no toolchain is available.

Format: ``uint32 magic=0xced7230a; uint32 lrec = cflag<<29 | len;
payload; pad to 4``. IRHeader packs ``<IfQQ`` (flag, label, id, id2);
``flag > 0`` means `flag` extra float labels follow the header.
"""
from __future__ import annotations

import ctypes
import os
import struct
from collections import namedtuple

import numpy as np

from .base import MXNetError

__all__ = ["MXRecordIO", "MXIndexedRecordIO", "IRHeader", "pack", "unpack",
           "pack_img", "unpack_img"]

_MAGIC = 0xced7230a
_LEN_MASK = (1 << 29) - 1


class MXRecordIO:
    """Sequential record file (reference: recordio.py::MXRecordIO)."""

    def __init__(self, uri, flag):
        self.uri = str(uri)
        self.flag = flag
        self._h = None
        self._lib = None
        self._pyf = None
        self.is_open = False
        self.open()

    def open(self):
        from ._native import recordio_lib

        if self.flag not in ("r", "w"):
            raise MXNetError(f"invalid flag {self.flag!r} (use 'r' or 'w')")
        self._lib = recordio_lib()
        if self._lib is not None:
            fn = self._lib.rio_open if self.flag == "r" else \
                self._lib.rio_create
            self._h = fn(self.uri.encode())
            if not self._h:
                raise MXNetError(f"cannot open {self.uri}")
        else:
            self._pyf = open(self.uri, "rb" if self.flag == "r" else "wb")
        self.is_open = True

    def close(self):
        if not self.is_open:
            return
        if self._h:
            self._lib.rio_close(self._h)
            self._h = None
        if self._pyf:
            self._pyf.close()
            self._pyf = None
        self.is_open = False

    def reset(self):
        self.close()
        self.open()

    def __del__(self):
        self.close()

    def __getstate__(self):
        d = dict(self.__dict__)
        d["_h"] = None
        d["_lib"] = None
        d["_pyf"] = None
        is_open = d.pop("is_open")
        d["_reopen"] = is_open
        return d

    def __setstate__(self, d):
        reopen = d.pop("_reopen", False)
        self.__dict__.update(d)
        self.is_open = False
        if reopen:
            self.open()

    # -- write ---------------------------------------------------------
    def write(self, buf: bytes):
        if self.flag != "w":
            raise MXNetError("record file opened for reading")
        if self._h:
            pos = self._lib.rio_write(self._h, bytes(buf), len(buf))
            if pos == ctypes.c_uint64(-1).value:
                raise MXNetError("recordio write failed")
            return pos
        return self._py_write(buf)

    def _py_write(self, buf):
        f = self._pyf
        start = f.tell()
        data = bytes(buf)
        kmax = _LEN_MASK
        off, part = 0, 0
        while True:
            n = min(len(data) - off, kmax)
            remain_after = len(data) - off - n
            if part == 0 and remain_after == 0:
                flag = 0
            elif part == 0:
                flag = 1
            elif remain_after == 0:
                flag = 3
            else:
                flag = 2
            f.write(struct.pack("<II", _MAGIC, (flag << 29) | n))
            f.write(data[off:off + n])
            pad = (4 - (n & 3)) & 3
            if pad:
                f.write(b"\x00" * pad)
            off += n
            part += 1
            if off >= len(data):
                return start

    def tell(self):
        if self._h:
            return self._lib.rio_tell(self._h)
        return self._pyf.tell()

    # -- read ----------------------------------------------------------
    def read(self):
        """Next record's payload bytes, or None at EOF."""
        if self.flag != "r":
            raise MXNetError("record file opened for writing")
        if self._h:
            out = ctypes.POINTER(ctypes.c_uint8)()
            n = self._lib.rio_next(self._h, ctypes.byref(out))
            if n == 0:
                return None
            if n == ctypes.c_uint64(-1).value:
                raise MXNetError(f"corrupt recordio file {self.uri}")
            return ctypes.string_at(out, n)
        return self._py_read()

    def _py_read(self):
        f = self._pyf
        parts = []
        while True:
            head = f.read(8)
            if len(head) < 8:
                return None if not parts else _corrupt(self.uri)
            magic, lrec = struct.unpack("<II", head)
            if magic != _MAGIC:
                _corrupt(self.uri)
            flag, n = lrec >> 29, lrec & _LEN_MASK
            payload = f.read(n)
            if len(payload) < n:
                _corrupt(self.uri)
            f.seek((4 - (n & 3)) & 3, os.SEEK_CUR)
            parts.append(payload)
            if flag in (0, 3):
                return b"".join(parts)

    def seek(self, pos):
        if self.flag != "r":
            raise MXNetError("seek on write-mode record file")
        if self._h:
            self._lib.rio_seek(self._h, int(pos))
        else:
            self._pyf.seek(int(pos))


def _corrupt(uri):
    raise MXNetError(f"corrupt recordio file {uri}")


class MXIndexedRecordIO(MXRecordIO):
    """Random-access record file with a text .idx sidecar
    (reference: recordio.py::MXIndexedRecordIO; idx lines "key\\tpos")."""

    def __init__(self, idx_path, uri, flag, key_type=int):
        self.idx_path = str(idx_path)
        self.idx = {}
        self.keys = []
        self.key_type = key_type
        super().__init__(uri, flag)

    def open(self):
        super().open()
        self.idx = {}
        self.keys = []
        if self.flag == "r" and os.path.exists(self.idx_path):
            with open(self.idx_path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    k, pos = line.split("\t")
                    key = self.key_type(k)
                    self.idx[key] = int(pos)
                    self.keys.append(key)

    def close(self):
        if not self.is_open:
            return
        if self.flag == "w":
            with open(self.idx_path, "w") as f:
                for k in self.keys:
                    f.write(f"{k}\t{self.idx[k]}\n")
        super().close()

    def read_idx(self, idx):
        self.seek(self.idx[idx])
        return self.read()

    def write_idx(self, idx, buf):
        key = self.key_type(idx)
        pos = self.write(buf)
        self.idx[key] = int(pos)
        self.keys.append(key)


IRHeader = namedtuple("IRHeader", ["flag", "label", "id", "id2"])
_IR_FORMAT = "<IfQQ"
_IR_SIZE = struct.calcsize(_IR_FORMAT)


def pack(header: IRHeader, s: bytes) -> bytes:
    """Pack a header + payload into one record (reference: recordio.pack)."""
    header = IRHeader(*header)
    label = header.label
    if isinstance(label, (np.ndarray, list, tuple)):
        label = np.asarray(label, dtype=np.float32)
        header = header._replace(flag=label.size, label=0.0)
        extra = label.tobytes()
    else:
        extra = b""
    return struct.pack(_IR_FORMAT, header.flag, float(header.label),
                       header.id, header.id2) + extra + bytes(s)


def unpack(s: bytes):
    """Inverse of pack: (IRHeader, payload)."""
    header = IRHeader(*struct.unpack(_IR_FORMAT, s[:_IR_SIZE]))
    s = s[_IR_SIZE:]
    if header.flag > 0:
        label = np.frombuffer(s[:header.flag * 4], dtype=np.float32)
        header = header._replace(label=label)
        s = s[header.flag * 4:]
    return header, s


def pack_img(header, img, quality=95, img_fmt=".jpg"):
    """Encode an HWC uint8 image and pack it (reference: pack_img; PIL
    replaces cv2)."""
    import io as _io

    from PIL import Image

    img = np.asarray(img, dtype=np.uint8)
    buf = _io.BytesIO()
    fmt = img_fmt.lower().lstrip(".")
    fmt = {"jpg": "JPEG", "jpeg": "JPEG", "png": "PNG"}.get(fmt)
    if fmt is None:
        raise MXNetError(f"unsupported image format {img_fmt!r}")
    Image.fromarray(img).save(buf, format=fmt,
                              **({"quality": quality} if fmt == "JPEG" else {}))
    return pack(header, buf.getvalue())


def unpack_img(s, iscolor=1):
    """Inverse of pack_img: (IRHeader, HWC uint8 ndarray)."""
    import io as _io

    from PIL import Image

    header, payload = unpack(s)
    img = Image.open(_io.BytesIO(payload))
    img = img.convert("RGB" if iscolor else "L")
    return header, np.asarray(img)
