"""``mx.viz`` — network visualization (reference:
``python/mxnet/visualization.py`` :: ``print_summary``/``plot_network``)."""
from __future__ import annotations

import json

from .base import MXNetError

__all__ = ["print_summary", "plot_network"]


def print_summary(symbol, shape=None, line_length=120, positions=None):
    """Layer-by-layer table of a Symbol graph with parameter counts
    (reference: visualization.py::print_summary). Returns the text (and
    prints it, like the reference)."""
    from .symbol.symbol import Symbol

    if not isinstance(symbol, Symbol):
        raise MXNetError("print_summary expects a Symbol")
    arg_shapes = {}
    out_shapes = {}
    if shape is not None:
        try:
            args, _outs, auxs = symbol.infer_shape(**shape)
            names = symbol.list_arguments()
            arg_shapes = dict(zip(names, args))
            aux_names = symbol.list_auxiliary_states()
            arg_shapes.update(zip(aux_names, auxs))
        except Exception:
            pass
        try:
            internals = symbol.get_internals()
            _a, int_outs, _x = internals.infer_shape(**shape)
            for (node, _oi), s in zip(internals._entries, int_outs):
                out_shapes[node.name] = tuple(s)
        except Exception:
            pass
    positions = positions or [0.44, 0.64, 0.74, 1.0]
    cols = [int(line_length * p) for p in positions]
    header = ["Layer (type)", "Output Shape", "Param #", "Previous Layer"]

    def row(fields):
        line = ""
        for f, c in zip(fields, cols):
            line = (line + str(f))[:c - 1].ljust(c)
        return line.rstrip()

    lines = ["_" * line_length, row(header), "=" * line_length]
    graph = json.loads(symbol.tojson())
    nodes = graph["nodes"]
    total = 0
    for node in nodes:
        if node["op"] == "null":
            continue
        name = node["name"]
        inputs = [nodes[i[0]]["name"] for i in node["inputs"]]
        data_names = set(shape or ())
        nparams = 0
        for i in node["inputs"]:
            parent = nodes[i[0]]
            if parent["op"] == "null" and parent["name"] in arg_shapes \
                    and parent["name"] not in data_names:
                n = 1
                for d in arg_shapes[parent["name"]]:
                    n *= int(d)
                nparams += n
        total += nparams
        prev = [nodes[i[0]]["name"] for i in node["inputs"]
                if nodes[i[0]]["op"] != "null"]
        lines.append(row([f"{name} ({node['op']})",
                          out_shapes.get(name, ""), nparams,
                          ", ".join(prev[:2])]))
    lines += ["=" * line_length, f"Total params: {total}",
              "_" * line_length]
    text = "\n".join(lines)
    print(text)
    return text


def plot_network(symbol, title="plot", save_format="pdf", shape=None,
                 node_attrs=None, hide_weights=True):
    """Graphviz rendering of a Symbol graph (reference:
    visualization.py::plot_network). Requires the ``graphviz`` package;
    raises with guidance when absent (offline image)."""
    try:
        from graphviz import Digraph
    except ImportError as e:
        raise MXNetError(
            "plot_network requires the 'graphviz' python package, which "
            "is not installed in this environment; use print_summary for "
            "a text view") from e
    from .symbol.symbol import Symbol

    if not isinstance(symbol, Symbol):
        raise MXNetError("plot_network expects a Symbol")
    graph = json.loads(symbol.tojson())
    nodes = graph["nodes"]
    # hide PARAMETERS, not inputs: key off standard parameter suffixes
    # (an input named 'x' must still render), like the reference's
    # weight-like classification
    param_suffixes = ("weight", "bias", "gamma", "beta", "moving_mean",
                      "moving_var", "running_mean", "running_var",
                      "quant", "scale")

    def is_param(name):
        return name.endswith(param_suffixes)

    dot = Digraph(name=title, format=save_format)
    dot.attr("node", **(node_attrs or {"shape": "box"}))
    for i, node in enumerate(nodes):
        if node["op"] == "null":
            if hide_weights and is_param(node["name"]):
                continue
            dot.node(str(i), node["name"], shape="oval")
        else:
            dot.node(str(i), f"{node['name']}\n{node['op']}")
    for i, node in enumerate(nodes):
        if node["op"] == "null":
            continue
        for inp in node["inputs"]:
            parent = nodes[inp[0]]
            if parent["op"] == "null" and hide_weights and \
                    is_param(parent["name"]):
                continue
            dot.edge(str(inp[0]), str(i))
    return dot
