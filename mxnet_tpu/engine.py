"""Execution engine facade.

Reference: ``src/engine/threaded_engine.cc :: ThreadedEngine::PushAsync`` —
MXNet's dependency engine makes every op asynchronous: ops are pushed with
read/write variable lists and execute on worker threads; Python blocks only
at explicit sync points (``WaitToRead`` / ``asnumpy`` / ``WaitForAll``).

XLA/PjRt gives the same contract natively: every dispatched computation
returns a future-backed buffer immediately and ordering is guaranteed by
data dependence, so the heavy ThreadedEngine machinery (vars, dependency
counters, per-device worker pools — src/engine/threaded_engine_perdevice.cc)
collapses to a thin facade whose job is:

* the **Naive mode** switch (``MXNET_ENGINE_TYPE=NaiveEngine`` in the
  reference, ``set_engine_type('NaiveEngine')`` / env here): block after
  every op for debugging/de-flaking;
* ``wait_for_all`` / per-array ``wait_to_read`` sync points, which also
  re-raise any exception captured during async execution (reference:
  ThreadedVar ExceptionRef rethrow at WaitToRead);
* the ``bulk`` hint (reference: ``python/mxnet/engine.py :: bulk``) — a
  no-op here because XLA fuses, kept for API compat.
"""
from __future__ import annotations

import contextlib
import os
import threading
import time

from . import telemetry
from .telemetry import _state as _telemetry_state

__all__ = ["set_engine_type", "engine_type", "is_naive", "wait_for_all", "bulk"]

_state = threading.local()
_VALID = ("ThreadedEnginePerDevice", "ThreadedEngine", "NaiveEngine")


def _default_type() -> str:
    env = os.environ.get("MXNET_ENGINE_TYPE", "ThreadedEnginePerDevice")
    return env if env in _VALID else "ThreadedEnginePerDevice"


def engine_type() -> str:
    return getattr(_state, "engine_type", None) or _default_type()


def set_engine_type(name: str) -> None:
    if name not in _VALID:
        raise ValueError(f"unknown engine type {name!r}; one of {_VALID}")
    _state.engine_type = name


def is_naive() -> bool:
    return engine_type() == "NaiveEngine"


# Arrays whose async computation may still be in flight.  JAX tracks
# readiness itself; we only keep a registry so wait_for_all() can block on
# everything outstanding (reference: Engine::WaitForAll).
_live_arrays = []
_live_lock = threading.Lock()
_MAX_LIVE = 8192


def track(jax_array) -> None:
    # weak references only: the registry must never pin device buffers
    import weakref

    try:
        ref = weakref.ref(jax_array)
    except TypeError:  # non-weakrefable (plain scalar) — nothing async
        return
    n_evict = 0
    with _live_lock:
        _live_arrays.append(ref)
        if len(_live_arrays) > _MAX_LIVE:
            # compact collected (dead) entries first; halve only if still
            # over — those evictions drop STILL-LIVE refs out of
            # wait_for_all coverage, so they are counted (telemetry:
            # mxnet_engine_live_evictions_total) instead of silent
            _live_arrays[:] = [r for r in _live_arrays if r() is not None]
            if len(_live_arrays) > _MAX_LIVE:
                n_evict = len(_live_arrays) // 2
                del _live_arrays[:n_evict]
        n_live = len(_live_arrays)
    # record outside _live_lock: track() runs on every array creation and
    # telemetry takes its own lock — never nest the two
    if n_evict:
        telemetry.record_live_evictions(n_evict)
    if _telemetry_state.enabled:
        telemetry.set_live_arrays(n_live)


def wait_for_all() -> None:
    """Block until all outstanding async work is done; re-raises any
    exception captured during async execution (reference:
    ThreadedEngine::WaitForAll + exception rethrow)."""
    import jax

    # capture the flag ONCE: enable() from another thread mid-wait must
    # not pair an unset t0 with a recording exit (uptime-scale sample)
    rec = _telemetry_state.enabled
    t0 = time.perf_counter() if rec else 0.0
    with _live_lock:
        pending = [r() for r in _live_arrays]
        _live_arrays.clear()
    try:
        for arr in pending:
            if arr is not None:
                jax.block_until_ready(arr)
    finally:
        if rec:
            telemetry.record_engine_wait(time.perf_counter() - t0)
            # arrays may have been tracked concurrently while we blocked
            with _live_lock:
                n_live = len(_live_arrays)
            telemetry.set_live_arrays(n_live)


@contextlib.contextmanager
def bulk(size: int):
    """Bulked execution hint (reference: mx.engine.bulk). XLA fuses ops
    inside a jitted graph already, so this is semantics-only."""
    yield
