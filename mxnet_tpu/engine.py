"""Execution engine facade + bulked (lazy) imperative execution.

Reference: ``src/engine/threaded_engine.cc :: ThreadedEngine::PushAsync`` —
MXNet's dependency engine makes every op asynchronous: ops are pushed with
read/write variable lists and execute on worker threads; Python blocks only
at explicit sync points (``WaitToRead`` / ``asnumpy`` / ``WaitForAll``).

XLA/PjRt gives the same contract natively: every dispatched computation
returns a future-backed buffer immediately and ordering is guaranteed by
data dependence, so the heavy ThreadedEngine machinery (vars, dependency
counters, per-device worker pools — src/engine/threaded_engine_perdevice.cc)
collapses to a thin facade whose job is:

* the **Naive mode** switch (``MXNET_ENGINE_TYPE=NaiveEngine`` in the
  reference, ``set_engine_type('NaiveEngine')`` / env here): block after
  every op for debugging/de-flaking;
* ``wait_for_all`` / per-array ``wait_to_read`` sync points, which also
  re-raise any exception captured during async execution (reference:
  ThreadedVar ExceptionRef rethrow at WaitToRead);
* the ``bulk`` scope (reference: ``python/mxnet/engine.py :: bulk`` +
  ThreadedEngine op bulking): XLA only fuses *inside* one jit call, and the
  eager path dispatches one single-op ``jax.jit`` per NDArray op. Inside a
  ``bulk(size)`` scope ops are **recorded** into a per-thread segment
  instead of executing; the segment lowers into ONE fused XLA dispatch
  (compiled through a CachedOp-style signature-keyed cache in
  ``ops/registry.py``) when a sync point is hit, the segment reaches
  ``size`` ops, a non-recordable op arrives, or the scope exits.

This module owns the scope plumbing, the per-thread recorder state, the
pending-value placeholder (``PendingValue``) and the flush triggers; the
record-vs-execute fork and the fused-segment compile cache live in
``ops/registry.py``.
"""
from __future__ import annotations

import contextlib
import os
import threading
import time
import weakref

import jax

from . import telemetry
from .telemetry import _state as _telemetry_state

__all__ = ["set_engine_type", "engine_type", "is_naive", "wait_for_all",
           "bulk", "PendingValue", "Segment", "current_bulk_scope",
           "in_bulk_scope", "is_pending", "concretize"]

_state = threading.local()
_VALID = ("ThreadedEnginePerDevice", "ThreadedEngine", "NaiveEngine")


def _default_type() -> str:
    env = os.environ.get("MXNET_ENGINE_TYPE", "ThreadedEnginePerDevice")
    return env if env in _VALID else "ThreadedEnginePerDevice"


def engine_type() -> str:
    return getattr(_state, "engine_type", None) or _default_type()


def set_engine_type(name: str) -> None:
    if name not in _VALID:
        raise ValueError(f"unknown engine type {name!r}; one of {_VALID}")
    _state.engine_type = name


def is_naive() -> bool:
    return engine_type() == "NaiveEngine"


# Arrays whose async computation may still be in flight.  JAX tracks
# readiness itself; we only keep a registry so wait_for_all() can block on
# everything outstanding (reference: Engine::WaitForAll).
_live_arrays = []
_live_lock = threading.Lock()
_MAX_LIVE = 8192


def track(jax_array) -> None:
    if type(jax_array) is PendingValue:
        # recorded-but-not-executed payload: nothing async exists yet; the
        # concrete output is tracked when the owning segment flushes
        return
    # weak references only: the registry must never pin device buffers
    # (`weakref` import hoisted to module scope — it used to run on every
    # array creation; see PERF.md "engine hot-path imports")
    try:
        ref = weakref.ref(jax_array)
    except TypeError:  # non-weakrefable (plain scalar) — nothing async
        return
    n_evict = 0
    with _live_lock:
        _live_arrays.append(ref)
        if len(_live_arrays) > _MAX_LIVE:
            # compact collected (dead) entries first; halve only if still
            # over — those evictions drop STILL-LIVE refs out of
            # wait_for_all coverage, so they are counted (telemetry:
            # mxnet_engine_live_evictions_total) instead of silent
            _live_arrays[:] = [r for r in _live_arrays if r() is not None]
            if len(_live_arrays) > _MAX_LIVE:
                n_evict = len(_live_arrays) // 2
                del _live_arrays[:n_evict]
        n_live = len(_live_arrays)
    # record outside _live_lock: track() runs on every array creation and
    # telemetry takes its own lock — never nest the two
    if n_evict:
        telemetry.record_live_evictions(n_evict)
    if _telemetry_state.enabled:
        telemetry.set_live_arrays(n_live)


def wait_for_all() -> None:
    """Block until all outstanding async work is done; re-raises any
    exception captured during async execution (reference:
    ThreadedEngine::WaitForAll + exception rethrow). A sync point: flushes
    this thread's open bulk segment first."""
    scope = current_bulk_scope()
    if scope is not None:
        scope.flush("sync")
    # capture the flag ONCE: enable() from another thread mid-wait must
    # not pair an unset t0 with a recording exit (uptime-scale sample)
    rec = _telemetry_state.enabled
    t0 = time.perf_counter() if rec else 0.0
    with _live_lock:
        pending = [r() for r in _live_arrays]
        _live_arrays.clear()
    try:
        for arr in pending:
            if arr is not None:
                jax.block_until_ready(arr)
    finally:
        if rec:
            telemetry.record_engine_wait(time.perf_counter() - t0)
            # arrays may have been tracked concurrently while we blocked
            with _live_lock:
                n_live = len(_live_arrays)
            telemetry.set_live_arrays(n_live)


# ---------------------------------------------------------------------------
# Bulked execution: per-thread segment recorder (reference: ThreadedEngine
# op bulking / CachedOp forward_bulk_size; design: LazyTensor-style deferral)
# ---------------------------------------------------------------------------


class PendingValue:
    """Placeholder payload for an output of a recorded (not yet executed)
    bulk-segment op. Quacks enough like a jax.Array for NDArray metadata
    (shape/dtype/ndim); any real data access goes through :meth:`force`,
    which flushes the owning segment."""

    __slots__ = ("segment", "node_index", "out_index", "aval", "_concrete",
                 "__weakref__")

    def __init__(self, segment: "Segment", node_index: int, out_index: int,
                 aval):
        self.segment = segment
        self.node_index = node_index
        self.out_index = out_index
        self.aval = aval          # jax.ShapeDtypeStruct
        self._concrete = None     # set by Segment flush

    @property
    def shape(self):
        return self.aval.shape

    @property
    def dtype(self):
        return self.aval.dtype

    @property
    def ndim(self):
        return len(self.aval.shape)

    @property
    def size(self):
        n = 1
        for d in self.aval.shape:
            n *= d
        return n

    def force(self):
        """Materialize: flush the owning segment (sync-point trigger) and
        return the concrete jax.Array."""
        c = self._concrete
        if c is None:
            self.segment.flush("sync")
            c = self._concrete
            if c is None:
                from .base import MXNetError

                err = self.segment.error
                if err is not None:
                    # the segment already failed (possibly raised at an
                    # earlier sibling's sync point): re-raise for every
                    # pending output, reference ThreadedVar ExceptionRef
                    raise MXNetError(
                        f"bulk segment execution failed: {err}") from err
                raise MXNetError(  # pragma: no cover - lock-atomic
                    "bulk segment flushed without resolving a pending "
                    "output (engine bug)")
        return c


def is_pending(value) -> bool:
    """True for a PendingValue that has NOT been materialized yet (a
    resolved PendingValue may linger as an NDArray payload until the next
    read swaps it out — that array is no longer pending)."""
    return type(value) is PendingValue and value._concrete is None


def concretize(value):
    """PendingValue -> concrete jax.Array (flushing if needed); everything
    else passes through."""
    if type(value) is PendingValue:
        c = value._concrete
        return c if c is not None else value.force()
    return value


class _SegmentNode:
    """One recorded op: the pure fn, its attrs, and wiring into the segment.

    ``input_specs`` entries:
      ``("r", node_idx, out_idx)``  — output of an earlier node in the segment
      ``("a", const_idx)``          — runtime array argument (Segment.consts)
      ``("s", literal)``            — static python scalar / None
    ``sig`` additionally encodes const shapes/dtypes so it is a complete
    CachedOp-style signature element (op name, attrs, input shape/dtype seq).
    """

    __slots__ = ("name", "fn", "attr_items", "input_specs", "n_out",
                 "out_is_seq", "sig")

    def __init__(self, name, fn, attr_items, input_specs, n_out, out_is_seq,
                 sig):
        self.name = name
        self.fn = fn
        self.attr_items = attr_items
        self.input_specs = input_specs
        self.n_out = n_out
        self.out_is_seq = out_is_seq
        self.sig = sig


class Segment:
    """An open (recording) or flushed bulk segment.

    Thread-safety: the owning thread appends; any thread may force a
    PendingValue (e.g. an array handed across threads), so append and flush
    are serialized on ``_lock``. After flush the segment is immutable.
    """

    __slots__ = ("scope", "platform", "nodes", "consts", "_const_ids",
                 "out_refs", "flushed", "error", "_lock")

    def __init__(self, scope: "_BulkScope", platform: str):
        self.scope = scope
        self.platform = platform
        self.nodes = []         # List[_SegmentNode]
        self.consts = []        # runtime array args, in first-use order
        self._const_ids = {}    # id(value) -> const index (dedup)
        self.out_refs = []      # per node: list[weakref[PendingValue]]
        self.flushed = False
        self.error = None       # set if execution failed (rethrow at force)
        self._lock = threading.RLock()

    def __len__(self):
        return len(self.nodes)

    def add_const(self, value) -> int:
        # caller holds _lock (via record in ops/registry.py)
        idx = self._const_ids.get(id(value))
        if idx is None:
            idx = len(self.consts)
            self.consts.append(value)  # strong ref keeps id() valid
            self._const_ids[id(value)] = idx
        return idx

    def flush(self, reason: str) -> None:
        """Execute all recorded ops as one fused XLA dispatch and resolve
        every live PendingValue. Idempotent; safe from any thread."""
        with self._lock:
            if self.flushed:
                return
            self.flushed = True
            scope = self.scope
            if scope is not None and scope.segment is self:
                scope.segment = None
            if not self.nodes:
                return
            from .ops.registry import execute_segment

            try:
                execute_segment(self, reason)
            except BaseException as e:
                self.error = e
                raise
            finally:
                # resolved (or failed): drop the recorded graph and the
                # strong input refs — resolved PendingValues may outlive
                # the segment (as NDArray payloads until the next read)
                # and must not pin the input device buffers through it
                self.nodes = []
                self.consts = []
                self._const_ids.clear()
                self.out_refs = []


class _BulkScope:
    """Per-thread state for one ``engine.bulk(size)`` scope."""

    __slots__ = ("max_size", "segment")

    def __init__(self, max_size: int):
        self.max_size = max_size
        self.segment = None  # type: Segment | None

    def open_segment(self, platform: str) -> Segment:
        seg = self.segment
        if seg is None or seg.flushed:
            seg = Segment(self, platform)
            self.segment = seg
        return seg

    def flush(self, reason: str) -> None:
        seg = self.segment
        if seg is not None:
            seg.flush(reason)


_bulk_tls = threading.local()


def current_bulk_scope():
    """The innermost active ``bulk`` scope of THIS thread, or None. The
    recorder is strictly thread-local: ops on other threads execute
    eagerly regardless of this thread's scope."""
    return getattr(_bulk_tls, "scope", None)


def in_bulk_scope() -> bool:
    return current_bulk_scope() is not None


@contextlib.contextmanager
def bulk(size: int):
    """Bulked execution scope (reference: mx.engine.bulk / ThreadedEngine
    op bulking). Inside the scope, recordable imperative ops are deferred
    into a segment of at most ``size`` ops and executed as ONE fused XLA
    dispatch at the next flush trigger: a sync point (``asnumpy``,
    ``wait_to_read``, ``item``, printing, ``wait_for_all``), the ``size``
    cap, a non-recordable op (eager-only / unhashable attrs / sparse-grad
    / autograd recording), or scope exit.

    Results are semantically identical to eager execution; ``size`` bounds
    both deferral latency and compiled-segment size. Nesting flushes the
    outer scope's open segment at entry (clean segment boundaries) and the
    inner scope's at exit.
    """
    if isinstance(size, bool) or not isinstance(size, int):
        raise ValueError(
            f"bulk size must be an int >= 1, got {type(size).__name__} "
            f"{size!r}")
    if size < 1:
        raise ValueError(f"bulk size must be >= 1, got {size}")
    prev = current_bulk_scope()
    if prev is not None:
        prev.flush("nested_scope")
    scope = _BulkScope(size)
    _bulk_tls.scope = scope
    try:
        yield
    finally:
        _bulk_tls.scope = prev
        scope.flush("scope_exit")
