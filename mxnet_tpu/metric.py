"""Evaluation metrics.

Reference: ``python/mxnet/metric.py`` — `EvalMetric` base (host-side numpy
on synced outputs), Accuracy, TopKAccuracy, F1, MCC, MAE, MSE, RMSE,
CrossEntropy, NegativeLogLikelihood, Perplexity, PearsonCorrelation,
Loss, CompositeEvalMetric, CustomMetric, and `create`.
"""
from __future__ import annotations

import math
from typing import Optional

import numpy as _np

from .base import MXNetError

__all__ = ["EvalMetric", "Accuracy", "TopKAccuracy", "F1", "MCC", "MAE",
           "MSE", "RMSE", "CrossEntropy", "NegativeLogLikelihood",
           "Perplexity", "PearsonCorrelation", "Loss", "CompositeEvalMetric",
           "CustomMetric", "Torch", "Caffe", "PCC", "create", "np"]

_REGISTRY = {}


def register(klass):
    _REGISTRY[klass.__name__.lower()] = klass
    return klass


def create(metric, *args, **kwargs):
    if callable(metric):
        return CustomMetric(metric, *args, **kwargs)
    if isinstance(metric, EvalMetric):
        return metric
    if isinstance(metric, list):
        composite = CompositeEvalMetric()
        for m in metric:
            composite.add(create(m, *args, **kwargs))
        return composite
    key = str(metric).lower()
    if key == "acc":
        key = "accuracy"
    if key == "ce":
        key = "crossentropy"
    if key == "nll_loss":
        key = "negativeloglikelihood"
    if key not in _REGISTRY:
        raise MXNetError(f"unknown metric {metric!r}")
    return _REGISTRY[key](*args, **kwargs)


def _as_numpy(x):
    return x.asnumpy() if hasattr(x, "asnumpy") else _np.asarray(x)


def check_label_shapes(labels, preds, wrap=False, shape=False):
    if isinstance(labels, (list, tuple)) != isinstance(preds, (list, tuple)):
        pass
    if wrap:
        if not isinstance(labels, (list, tuple)):
            labels = [labels]
        if not isinstance(preds, (list, tuple)):
            preds = [preds]
    if isinstance(labels, (list, tuple)) and isinstance(preds, (list, tuple)) \
            and len(labels) != len(preds):
        raise MXNetError(
            f"label and prediction counts differ: {len(labels)} vs {len(preds)}")
    return labels, preds


class EvalMetric:
    def __init__(self, name, output_names=None, label_names=None, **kwargs):
        self.name = str(name)
        self.output_names = output_names
        self.label_names = label_names
        self._kwargs = kwargs
        self.reset()

    def reset(self):
        self.num_inst = 0
        self.sum_metric = 0.0
        self.global_num_inst = 0
        self.global_sum_metric = 0.0

    def reset_local(self):
        self.num_inst = 0
        self.sum_metric = 0.0

    def update(self, labels, preds):
        raise NotImplementedError

    def _accumulate(self, metric, count):
        self.sum_metric += metric
        self.num_inst += count
        self.global_sum_metric += metric
        self.global_num_inst += count

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, self.sum_metric / self.num_inst)

    def get_global(self):
        if self.global_num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, self.global_sum_metric / self.global_num_inst)

    def get_name_value(self):
        name, value = self.get()
        if not isinstance(name, list):
            name = [name]
        if not isinstance(value, list):
            value = [value]
        return list(zip(name, value))

    def update_dict(self, label, pred):
        if self.output_names is not None:
            pred = [pred[n] for n in self.output_names if n in pred]
        else:
            pred = list(pred.values())
        if self.label_names is not None:
            label = [label[n] for n in self.label_names if n in label]
        else:
            label = list(label.values())
        self.update(label, pred)

    def __str__(self):
        return f"EvalMetric: {dict([self.get_name_value()[0]])}"


@register
class Accuracy(EvalMetric):
    def __init__(self, axis=1, name="accuracy", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names)
        self.axis = axis

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, wrap=True)
        for label, pred in zip(labels, preds):
            label = _as_numpy(label)
            pred = _as_numpy(pred)
            if pred.ndim > label.ndim:
                pred = _np.argmax(pred, axis=self.axis)
            pred = pred.astype("int32").ravel()
            label = label.astype("int32").ravel()
            if len(pred) != len(label):
                raise MXNetError(
                    f"Accuracy: prediction count {len(pred)} != label count "
                    f"{len(label)}")
            correct = int((pred == label).sum())
            self._accumulate(correct, len(pred))


@register
class TopKAccuracy(EvalMetric):
    def __init__(self, top_k=1, name="top_k_accuracy", output_names=None,
                 label_names=None):
        super().__init__(f"{name}_{top_k}", output_names, label_names)
        self.top_k = top_k
        assert top_k > 1, "use Accuracy for top_k=1"

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, wrap=True)
        for label, pred in zip(labels, preds):
            label = _as_numpy(label).astype("int32")
            pred = _as_numpy(pred)
            topk = _np.argsort(pred, axis=-1)[..., -self.top_k:]
            hits = (topk == label.reshape(-1, 1)).any(axis=-1)
            self._accumulate(int(hits.sum()), hits.size)


@register
class F1(EvalMetric):
    def __init__(self, name="f1", output_names=None, label_names=None,
                 average="macro"):
        super().__init__(name, output_names, label_names)
        self.average = average
        self._tp = self._fp = self._fn = 0

    def reset(self):
        super().reset()
        self._tp = self._fp = self._fn = 0

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, wrap=True)
        for label, pred in zip(labels, preds):
            label = _as_numpy(label).ravel().astype("int32")
            pred = _as_numpy(pred)
            if pred.ndim > 1 and pred.shape[-1] > 1:
                pred = _np.argmax(pred, axis=-1)
            pred = (pred.ravel() > 0.5).astype("int32") if pred.dtype.kind == "f" and pred.max(initial=0) <= 1 else pred.ravel().astype("int32")
            self._tp += int(((pred == 1) & (label == 1)).sum())
            self._fp += int(((pred == 1) & (label == 0)).sum())
            self._fn += int(((pred == 0) & (label == 1)).sum())
            prec = self._tp / max(self._tp + self._fp, 1)
            rec = self._tp / max(self._tp + self._fn, 1)
            f1 = 2 * prec * rec / max(prec + rec, 1e-12)
            self.sum_metric = f1
            self.num_inst = 1
            self.global_sum_metric = f1
            self.global_num_inst = 1


@register
class MCC(EvalMetric):
    def __init__(self, name="mcc", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)
        self._tp = self._fp = self._fn = self._tn = 0

    def reset(self):
        super().reset()
        self._tp = self._fp = self._fn = self._tn = 0

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, wrap=True)
        for label, pred in zip(labels, preds):
            label = _as_numpy(label).ravel().astype("int32")
            pred = _as_numpy(pred)
            if pred.ndim > 1 and pred.shape[-1] > 1:
                pred = _np.argmax(pred, axis=-1)
            pred = pred.ravel().astype("int32")
            self._tp += int(((pred == 1) & (label == 1)).sum())
            self._fp += int(((pred == 1) & (label == 0)).sum())
            self._fn += int(((pred == 0) & (label == 1)).sum())
            self._tn += int(((pred == 0) & (label == 0)).sum())
            denom = math.sqrt(
                (self._tp + self._fp) * (self._tp + self._fn)
                * (self._tn + self._fp) * (self._tn + self._fn))
            mcc = ((self._tp * self._tn - self._fp * self._fn) / denom
                   if denom else 0.0)
            self.sum_metric = mcc
            self.num_inst = 1
            self.global_sum_metric = mcc
            self.global_num_inst = 1


@register
class MAE(EvalMetric):
    def __init__(self, name="mae", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, wrap=True)
        for label, pred in zip(labels, preds):
            label = _as_numpy(label)
            pred = _as_numpy(pred)
            self._accumulate(float(_np.abs(label.reshape(pred.shape) - pred).mean())
                             * label.shape[0], label.shape[0])


@register
class MSE(EvalMetric):
    def __init__(self, name="mse", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, wrap=True)
        for label, pred in zip(labels, preds):
            label = _as_numpy(label)
            pred = _as_numpy(pred)
            self._accumulate(float(((label.reshape(pred.shape) - pred) ** 2).mean())
                             * label.shape[0], label.shape[0])


@register
class RMSE(MSE):
    def __init__(self, name="rmse", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, math.sqrt(self.sum_metric / self.num_inst))


@register
class CrossEntropy(EvalMetric):
    def __init__(self, eps=1e-12, name="cross-entropy", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names)
        self.eps = eps

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, wrap=True)
        for label, pred in zip(labels, preds):
            label = _as_numpy(label).ravel().astype("int32")
            pred = _as_numpy(pred)
            prob = pred[_np.arange(label.shape[0]), label]
            self._accumulate(float((-_np.log(prob + self.eps)).sum()), label.shape[0])


@register
class NegativeLogLikelihood(CrossEntropy):
    def __init__(self, eps=1e-12, name="nll-loss", output_names=None,
                 label_names=None):
        super().__init__(eps, name, output_names, label_names)


@register
class Perplexity(EvalMetric):
    def __init__(self, ignore_label=None, axis=-1, name="perplexity",
                 output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)
        self.ignore_label = ignore_label
        self.axis = axis

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, wrap=True)
        loss = 0.0
        num = 0
        for label, pred in zip(labels, preds):
            label = _as_numpy(label).ravel().astype("int32")
            pred = _as_numpy(pred).reshape(-1, _as_numpy(pred).shape[-1])
            prob = pred[_np.arange(label.shape[0]), label]
            if self.ignore_label is not None:
                ignore = (label == self.ignore_label)
                prob = _np.where(ignore, 1.0, prob)
                num -= int(ignore.sum())
            loss += float(-_np.log(_np.maximum(prob, 1e-10)).sum())
            num += label.shape[0]
        self._accumulate(loss, num)

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, math.exp(self.sum_metric / self.num_inst))


@register
class PearsonCorrelation(EvalMetric):
    def __init__(self, name="pearsonr", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, wrap=True)
        for label, pred in zip(labels, preds):
            label = _as_numpy(label).ravel()
            pred = _as_numpy(pred).ravel()
            r = _np.corrcoef(label, pred)[0, 1]
            self._accumulate(float(r), 1)


@register
class Loss(EvalMetric):
    """Mean of a loss output (reference: metric.py::Loss)."""

    def __init__(self, name="loss", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, _, preds):
        if not isinstance(preds, (list, tuple)):
            preds = [preds]
        for pred in preds:
            loss = float(_as_numpy(pred).sum())
            self._accumulate(loss, _as_numpy(pred).size)


class CompositeEvalMetric(EvalMetric):
    def __init__(self, metrics=None, name="composite", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names)
        self.metrics = [create(m) for m in (metrics or [])]

    def add(self, metric):
        self.metrics.append(create(metric))

    def get_metric(self, index):
        return self.metrics[index]

    def update(self, labels, preds):
        for m in self.metrics:
            m.update(labels, preds)

    def reset(self):
        for m in getattr(self, "metrics", []):
            m.reset()

    def get(self):
        names, values = [], []
        for m in self.metrics:
            name, value = m.get()
            names.append(name)
            values.append(value)
        return (names, values)


class CustomMetric(EvalMetric):
    def __init__(self, feval, name="custom", allow_extra_outputs=False,
                 output_names=None, label_names=None):
        super().__init__(f"custom({getattr(feval, '__name__', name)})",
                         output_names, label_names)
        self._feval = feval
        self._allow_extra_outputs = allow_extra_outputs

    def update(self, labels, preds):
        if not isinstance(labels, (list, tuple)):
            labels = [labels]
        if not isinstance(preds, (list, tuple)):
            preds = [preds]
        for label, pred in zip(labels, preds):
            reval = self._feval(_as_numpy(label), _as_numpy(pred))
            if isinstance(reval, tuple):
                m, n = reval
                self._accumulate(m, n)
            else:
                self._accumulate(reval, 1)


def np(numpy_feval, name="custom", allow_extra_outputs=False):
    """Wrap a numpy feval as a metric (reference: metric.np)."""

    def feval(label, pred):
        return numpy_feval(label, pred)

    feval.__name__ = getattr(numpy_feval, "__name__", name)
    return CustomMetric(feval, name, allow_extra_outputs)


@register
class Torch(Loss):
    """Deprecated alias kept for API parity (reference: metric.py::Torch —
    mean of a torch-criterion output; identical to Loss here)."""

    def __init__(self, name="torch", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)


@register
class Caffe(Loss):
    """Deprecated alias kept for API parity (reference: metric.py::Caffe)."""

    def __init__(self, name="caffe", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)


@register
class PCC(EvalMetric):
    """Multiclass Pearson correlation of the confusion matrix (reference:
    metric.py::PCC — the k-category generalization of MCC)."""

    def __init__(self, name="pcc", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)
        self._conf = None
        self._gconf = None

    def reset(self):
        super().reset()
        self._conf = None
        self._gconf = None

    def reset_local(self):
        super().reset_local()
        self._conf = None

    @staticmethod
    def _pcc_of(c):
        n = c.sum()
        x = c.sum(axis=1)
        y = c.sum(axis=0)
        cov_xy = c.trace() * n - (x * y).sum()
        denom = ((n * n - (x * x).sum()) * (n * n - (y * y).sum())) ** 0.5
        return float(cov_xy / denom) if denom > 0 else 0.0

    def update(self, labels, preds):
        if not isinstance(labels, (list, tuple)):
            labels, preds = [labels], [preds]

        def grow(conf, k):
            if conf is None or conf.shape[0] < k:
                new = _np.zeros((k, k), _np.float64)
                if conf is not None:
                    new[:conf.shape[0], :conf.shape[1]] = conf
                return new
            return conf

        for label, pred in zip(labels, preds):
            lab = _as_numpy(label).astype(int).reshape(-1)
            p = _as_numpy(pred)
            cls = p.argmax(-1).reshape(-1) if p.ndim > 1 else \
                (p.reshape(-1) > 0.5).astype(int)
            k = int(max(lab.max(initial=0), cls.max(initial=0))) + 1
            self._conf = grow(self._conf, k)
            self._gconf = grow(self._gconf, k)
            _np.add.at(self._conf, (cls, lab), 1)
            _np.add.at(self._gconf, (cls, lab), 1)
            self.num_inst = 1
            self.global_num_inst = 1
        self.sum_metric = self._pcc_of(self._conf)
        self.global_sum_metric = self._pcc_of(self._gconf)
