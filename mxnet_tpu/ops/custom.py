"""The ``Custom`` operator — user Python code inside graphs.

Reference: ``src/operator/custom/custom.cc`` (Forward/Backward push a
callback onto the engine with CPU-copied NDArrays). TPU-native shape: the
user's ``CustomOp.forward`` runs under ``jax.pure_callback`` so the op is
usable eagerly AND inside jit/pjit-traced graphs (Symbol executor,
hybridized blocks); output shapes/dtypes come statically from the
registered ``CustomOpProp.infer_shape``/``infer_type``; a
``jax.custom_vjp`` routes cotangents through the user's ``backward``
(XLA cannot differentiate an opaque host call).
"""
from __future__ import annotations

import numpy as onp

from .registry import register


@register("Custom", variadic=True, pass_training_flag=True)
def custom(*inputs, op_type, _training=False, **kwargs):
    """Apply a registered user-defined operator (reference:
    ``mx.nd.Custom`` / ``custom.cc``).

    ``inputs`` = arguments then auxiliary states, per the prop's
    ``list_arguments()`` / ``list_auxiliary_states()``. Extra keyword
    attributes are forwarded to the ``CustomOpProp`` constructor.
    """
    import jax
    import jax.numpy as jnp

    from ..base import MXNetError
    from .. import operator as _op_mod

    prop = _op_mod.get_prop_cls(op_type)(**kwargs)
    n_args = len(prop.list_arguments())
    n_aux = len(prop.list_auxiliary_states())
    n_out = len(prop.list_outputs())
    if len(inputs) != n_args + n_aux:
        raise MXNetError(
            f"Custom[{op_type}]: got {len(inputs)} inputs, expected "
            f"{n_args} arguments + {n_aux} auxiliary states")

    in_shapes = [tuple(x.shape) for x in inputs[:n_args]]
    in_dtypes = [onp.dtype(x.dtype) for x in inputs[:n_args]]
    _, out_shapes, _ = prop.infer_shape([list(s) for s in in_shapes])
    _, out_dtypes, _ = prop.infer_type(list(in_dtypes))
    out_specs = tuple(
        jax.ShapeDtypeStruct(tuple(s), onp.dtype(d))
        for s, d in zip(out_shapes, out_dtypes))
    grad_specs = tuple(
        jax.ShapeDtypeStruct(tuple(x.shape), onp.dtype(x.dtype))
        for x in inputs[:n_args])
    is_train = bool(_training)

    def _to_nd(vals):
        # CPU NDArrays for the user's host code — custom.cc's CPU-copy
        # contract; keeps the single-client TPU tunnel out of callbacks
        from ..context import cpu
        from ..ndarray import array

        return [array(onp.asarray(v), ctx=cpu(0)) for v in vals]

    def _host_forward(*vals):
        nd_in = _to_nd(vals[:n_args])
        nd_aux = _to_nd(vals[n_args:])
        nd_out = _to_nd([onp.zeros(sp.shape, sp.dtype) for sp in out_specs])
        op = prop.create_operator(None, in_shapes, in_dtypes)
        op.forward(is_train=is_train, req=["write"] * n_out,
                   in_data=nd_in, out_data=nd_out, aux=nd_aux)
        return tuple(
            onp.asarray(o.asnumpy(), sp.dtype).reshape(sp.shape)
            for o, sp in zip(nd_out, out_specs))

    def _host_backward(*vals):
        og = _to_nd(vals[:n_out])
        nd_in = _to_nd(vals[n_out:n_out + n_args])
        nd_aux = _to_nd(vals[n_out + n_args:n_out + n_args + n_aux])
        nd_out = _to_nd(vals[n_out + n_args + n_aux:])
        nd_grad = _to_nd([onp.zeros(sp.shape, sp.dtype)
                          for sp in grad_specs])
        op = prop.create_operator(None, in_shapes, in_dtypes)
        op.backward(req=["write"] * n_args, out_grad=og, in_data=nd_in,
                    out_data=nd_out, in_grad=nd_grad, aux=nd_aux)
        return tuple(
            onp.asarray(g.asnumpy(), sp.dtype).reshape(sp.shape)
            for g, sp in zip(nd_grad, grad_specs))

    @jax.custom_vjp
    def f(*xs):
        return tuple(jax.pure_callback(_host_forward, out_specs, *xs))

    def f_fwd(*xs):
        outs = tuple(jax.pure_callback(_host_forward, out_specs, *xs))
        return outs, (xs, outs)

    def f_bwd(res, gouts):
        xs, outs = res
        gargs = jax.pure_callback(_host_backward, grad_specs,
                                  *gouts, *xs, *outs)
        # aux states are read-only: zero cotangents
        gaux = tuple(jnp.zeros(x.shape, x.dtype) for x in xs[n_args:])
        return tuple(gargs) + gaux

    f.defvjp(f_fwd, f_bwd)
    outs = f(*inputs)
    return outs if n_out > 1 else outs[0]
