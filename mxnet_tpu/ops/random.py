"""Random sampling operators.

Reference: ``src/operator/random/sample_op.cc`` (`_random_uniform`,
`_random_normal`, `_random_gamma`, ...), ``multisample_op.cc``,
``unique_sample_op.cc``.

MXNet keeps stateful per-device RNG resources (``ResourceRequest::kRandom``,
``src/resource.cc``). The TPU-native design is counter-based: a global
stateful key in ``mxnet_tpu.random_state`` is split per call in eager mode,
and hybridized graphs receive an explicit key input (threaded by the
CachedOp wrapper) so the same executable produces fresh randomness per call.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register


@register("_random_uniform", aliases=["uniform", "random_uniform"], needs_rng=True)
def random_uniform(rng, *, low=0.0, high=1.0, shape=(), dtype="float32"):
    return jax.random.uniform(rng, tuple(shape), minval=low, maxval=high,
                              dtype=jnp.dtype(dtype))


@register("_random_normal", aliases=["normal", "random_normal"], needs_rng=True)
def random_normal(rng, *, loc=0.0, scale=1.0, shape=(), dtype="float32"):
    return loc + scale * jax.random.normal(rng, tuple(shape), dtype=jnp.dtype(dtype))


@register("_random_gamma", aliases=["random_gamma"], needs_rng=True)
def random_gamma(rng, *, alpha=1.0, beta=1.0, shape=(), dtype="float32"):
    return beta * jax.random.gamma(rng, alpha, tuple(shape), dtype=jnp.dtype(dtype))


@register("_random_exponential", aliases=["random_exponential"], needs_rng=True)
def random_exponential(rng, *, lam=1.0, shape=(), dtype="float32"):
    return jax.random.exponential(rng, tuple(shape), dtype=jnp.dtype(dtype)) / lam


def _threefry(rng):
    """A threefry2x32 key derived from ``rng``.

    ``jax.random.poisson`` is only implemented for threefry, while this
    library defaults the global PRNG to rbg (hardware generator, ~2x
    cheaper for dropout — see mxnet_tpu/__init__.py). Deriving a
    threefry key from the incoming key's data keeps poisson-backed draws
    working under either default; traceable (pure bit reinterpretation).
    """
    data = jax.random.key_data(rng)
    if data.shape[-1] > 2:
        data = data[..., :2]
    return jax.random.wrap_key_data(data, impl="threefry2x32")


@register("_random_poisson", aliases=["random_poisson"], needs_rng=True)
def random_poisson(rng, *, lam=1.0, shape=(), dtype="float32"):
    return jax.random.poisson(_threefry(rng), lam,
                              tuple(shape)).astype(jnp.dtype(dtype))


@register("_random_negative_binomial", aliases=["random_negative_binomial"], needs_rng=True)
def random_negative_binomial(rng, *, k=1, p=1.0, shape=(), dtype="float32"):
    k1, k2 = jax.random.split(rng)
    lam = jax.random.gamma(k1, k, tuple(shape)) * ((1 - p) / p)
    return jax.random.poisson(_threefry(k2), lam,
                              tuple(shape)).astype(jnp.dtype(dtype))


@register("_random_randint", aliases=["random_randint"], needs_rng=True)
def random_randint(rng, *, low=0, high=1, shape=(), dtype="int32"):
    return jax.random.randint(rng, tuple(shape), low, high, dtype=jnp.dtype(dtype))


@register("_sample_uniform", aliases=["sample_uniform"], needs_rng=True)
def sample_uniform(rng, low, high, *, shape=(), dtype="float32"):
    s = tuple(low.shape) + tuple(shape)
    u = jax.random.uniform(rng, s, dtype=jnp.dtype(dtype))
    bshape = low.shape + (1,) * len(tuple(shape))
    return low.reshape(bshape) + u * (high - low).reshape(bshape)


@register("_sample_normal", aliases=["sample_normal"], needs_rng=True)
def sample_normal(rng, mu, sigma, *, shape=(), dtype="float32"):
    s = tuple(mu.shape) + tuple(shape)
    n = jax.random.normal(rng, s, dtype=jnp.dtype(dtype))
    bshape = mu.shape + (1,) * len(tuple(shape))
    return mu.reshape(bshape) + n * sigma.reshape(bshape)


@register("_sample_gamma", aliases=["sample_gamma"], needs_rng=True)
def sample_gamma(rng, alpha, beta, *, shape=(), dtype="float32"):
    s = tuple(alpha.shape) + tuple(shape)
    bshape = alpha.shape + (1,) * len(tuple(shape))
    g = jax.random.gamma(rng, jnp.broadcast_to(alpha.reshape(bshape), s), dtype=jnp.dtype(dtype))
    return g * beta.reshape(bshape)


@register("_sample_multinomial", aliases=["sample_multinomial"], needs_rng=True)
def sample_multinomial(rng, data, *, shape=(), get_prob=False, dtype="int32"):
    # data: (..., k) probabilities. Draw `shape` samples per distribution.
    n = 1
    for d in tuple(shape) or ():
        n *= d
    n = max(n, 1)
    logits = jnp.log(jnp.maximum(data, 1e-30))
    out = jax.random.categorical(rng, logits, axis=-1,
                                 shape=(n,) + data.shape[:-1])
    out = jnp.moveaxis(out, 0, -1)
    if tuple(shape) == ():
        out = out[..., 0]
    else:
        out = out.reshape(data.shape[:-1] + tuple(shape))
    out = out.astype(jnp.dtype(dtype))
    if get_prob:
        lp = jnp.take_along_axis(
            jax.nn.log_softmax(logits, axis=-1),
            out.astype(jnp.int32).reshape(data.shape[:-1] + (-1,)), axis=-1
        ).reshape(out.shape)
        return out, lp
    return out


@register("_random_bernoulli", aliases=["sample_bernoulli"], needs_rng=True)
def random_bernoulli(rng, *, p=0.5, shape=(), dtype="float32"):
    return jax.random.bernoulli(rng, p, tuple(shape)).astype(jnp.dtype(dtype))


def _per_dist(rng, param, shape, draw):
    """Broadcast helper for sample_*: one draw of ``shape`` per entry of
    the (leading) parameter tensor (reference multisample_op.cc)."""
    s = tuple(param.shape) + tuple(shape)
    bshape = param.shape + (1,) * len(tuple(shape))
    return draw(rng, s, bshape)


@register("_sample_exponential", aliases=["sample_exponential"],
          needs_rng=True)
def sample_exponential(rng, lam, *, shape=(), dtype="float32"):
    def draw(key, s, bshape):
        e = jax.random.exponential(key, s)
        return (e / lam.reshape(bshape)).astype(jnp.dtype(dtype))

    return _per_dist(rng, lam, shape, draw)


@register("_sample_poisson", aliases=["sample_poisson"], needs_rng=True)
def sample_poisson(rng, lam, *, shape=(), dtype="float32"):
    def draw(key, s, bshape):
        return jax.random.poisson(
            _threefry(key), jnp.broadcast_to(lam.reshape(bshape), s)).astype(
            jnp.dtype(dtype))

    return _per_dist(rng, lam, shape, draw)


@register("_sample_negative_binomial", aliases=["sample_negative_binomial"],
          needs_rng=True)
def sample_negative_binomial(rng, k, p, *, shape=(), dtype="float32"):
    def draw(key, s, bshape):
        k1, k2 = jax.random.split(key)
        rate = jax.random.gamma(
            k1, jnp.broadcast_to(k.reshape(bshape).astype(jnp.float32), s)) \
            * jnp.broadcast_to(((1 - p) / p).reshape(bshape), s)
        return jax.random.poisson(_threefry(k2), rate).astype(
            jnp.dtype(dtype))

    return _per_dist(rng, k, shape, draw)


@register("_sample_generalized_negative_binomial",
          aliases=["sample_generalized_negative_binomial"], needs_rng=True)
def sample_generalized_negative_binomial(rng, mu, alpha, *, shape=(),
                                         dtype="float32"):
    # reference sample_op.cc: Gamma(1/alpha, alpha*mu)-mixed Poisson
    def draw(key, s, bshape):
        k1, k2 = jax.random.split(key)
        a = jnp.broadcast_to(alpha.reshape(bshape).astype(jnp.float32), s)
        m = jnp.broadcast_to(mu.reshape(bshape).astype(jnp.float32), s)
        rate = jax.random.gamma(k1, 1.0 / a) * a * m
        return jax.random.poisson(_threefry(k2), rate).astype(
            jnp.dtype(dtype))

    return _per_dist(rng, mu, shape, draw)


@register("_random_generalized_negative_binomial",
          aliases=["random_generalized_negative_binomial"], needs_rng=True)
def random_generalized_negative_binomial(rng, *, mu=1.0, alpha=1.0, shape=(),
                                         dtype="float32"):
    k1, k2 = jax.random.split(rng)
    rate = jax.random.gamma(k1, 1.0 / alpha, tuple(shape)) * alpha * mu
    return jax.random.poisson(_threefry(k2), rate).astype(jnp.dtype(dtype))


# -- pdf ops (reference src/operator/random/pdf_op.cc): deterministic
# densities of samples under (broadcast) distribution parameters; the
# last axis of ``sample`` indexes draws per distribution --


def _pdf_wrap(logpdf, is_log):
    def fn(sample, *params):
        ps = [p.reshape(p.shape + (1,)) for p in params]
        lp = logpdf(sample.astype(jnp.float32),
                    *[p.astype(jnp.float32) for p in ps])
        return lp if is_log else jnp.exp(lp)

    return fn


def _register_pdf(name, logpdf):
    @register(f"_random_pdf_{name}", aliases=[f"random_pdf_{name}"])
    def pdf(sample, *params, is_log=False):
        return _pdf_wrap(logpdf, is_log)(sample, *params)

    return pdf


_register_pdf("uniform", lambda x, lo, hi: jnp.where(
    (x >= lo) & (x <= hi), -jnp.log(hi - lo), -jnp.inf))
_register_pdf("normal", lambda x, mu, sigma:
              -0.5 * jnp.square((x - mu) / sigma)
              - jnp.log(sigma) - 0.5 * jnp.log(2 * jnp.pi))
_register_pdf("exponential", lambda x, lam: jnp.log(lam) - lam * x)
_register_pdf("poisson", lambda x, lam:
              x * jnp.log(lam) - lam - jax.lax.lgamma(x + 1.0))
_register_pdf("gamma", lambda x, alpha, beta:
              alpha * jnp.log(beta) + (alpha - 1) * jnp.log(x) - beta * x
              - jax.lax.lgamma(alpha))
_register_pdf("negative_binomial", lambda x, k, p:
              jax.lax.lgamma(x + k) - jax.lax.lgamma(x + 1.0)
              - jax.lax.lgamma(k) + k * jnp.log(p) + x * jnp.log1p(-p))
_register_pdf("generalized_negative_binomial", lambda x, mu, alpha:
              jax.lax.lgamma(x + 1.0 / alpha) - jax.lax.lgamma(x + 1.0)
              - jax.lax.lgamma(1.0 / alpha)
              - (1.0 / alpha) * jnp.log1p(alpha * mu)
              + x * (jnp.log(alpha) + jnp.log(mu) - jnp.log1p(alpha * mu)))


@register("_random_pdf_dirichlet", aliases=["random_pdf_dirichlet"])
def random_pdf_dirichlet(sample, alpha, *, is_log=False):
    # sample: (..., draws, k); alpha: (..., k)
    a = alpha.astype(jnp.float32)[..., None, :]
    x = sample.astype(jnp.float32)
    lp = (jnp.sum((a - 1) * jnp.log(x), axis=-1)
          + jax.lax.lgamma(jnp.sum(a, axis=-1))
          - jnp.sum(jax.lax.lgamma(a), axis=-1))
    return lp if is_log else jnp.exp(lp)
