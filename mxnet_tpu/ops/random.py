"""Random sampling operators.

Reference: ``src/operator/random/sample_op.cc`` (`_random_uniform`,
`_random_normal`, `_random_gamma`, ...), ``multisample_op.cc``,
``unique_sample_op.cc``.

MXNet keeps stateful per-device RNG resources (``ResourceRequest::kRandom``,
``src/resource.cc``). The TPU-native design is counter-based: a global
stateful key in ``mxnet_tpu.random_state`` is split per call in eager mode,
and hybridized graphs receive an explicit key input (threaded by the
CachedOp wrapper) so the same executable produces fresh randomness per call.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register


@register("_random_uniform", aliases=["uniform", "random_uniform"], needs_rng=True)
def random_uniform(rng, *, low=0.0, high=1.0, shape=(), dtype="float32"):
    return jax.random.uniform(rng, tuple(shape), minval=low, maxval=high,
                              dtype=jnp.dtype(dtype))


@register("_random_normal", aliases=["normal", "random_normal"], needs_rng=True)
def random_normal(rng, *, loc=0.0, scale=1.0, shape=(), dtype="float32"):
    return loc + scale * jax.random.normal(rng, tuple(shape), dtype=jnp.dtype(dtype))


@register("_random_gamma", aliases=["random_gamma"], needs_rng=True)
def random_gamma(rng, *, alpha=1.0, beta=1.0, shape=(), dtype="float32"):
    return beta * jax.random.gamma(rng, alpha, tuple(shape), dtype=jnp.dtype(dtype))


@register("_random_exponential", aliases=["random_exponential"], needs_rng=True)
def random_exponential(rng, *, lam=1.0, shape=(), dtype="float32"):
    return jax.random.exponential(rng, tuple(shape), dtype=jnp.dtype(dtype)) / lam


@register("_random_poisson", aliases=["random_poisson"], needs_rng=True)
def random_poisson(rng, *, lam=1.0, shape=(), dtype="float32"):
    return jax.random.poisson(rng, lam, tuple(shape)).astype(jnp.dtype(dtype))


@register("_random_negative_binomial", aliases=["random_negative_binomial"], needs_rng=True)
def random_negative_binomial(rng, *, k=1, p=1.0, shape=(), dtype="float32"):
    k1, k2 = jax.random.split(rng)
    lam = jax.random.gamma(k1, k, tuple(shape)) * ((1 - p) / p)
    return jax.random.poisson(k2, lam, tuple(shape)).astype(jnp.dtype(dtype))


@register("_random_randint", aliases=["random_randint"], needs_rng=True)
def random_randint(rng, *, low=0, high=1, shape=(), dtype="int32"):
    return jax.random.randint(rng, tuple(shape), low, high, dtype=jnp.dtype(dtype))


@register("_sample_uniform", aliases=["sample_uniform"], needs_rng=True)
def sample_uniform(rng, low, high, *, shape=(), dtype="float32"):
    s = tuple(low.shape) + tuple(shape)
    u = jax.random.uniform(rng, s, dtype=jnp.dtype(dtype))
    bshape = low.shape + (1,) * len(tuple(shape))
    return low.reshape(bshape) + u * (high - low).reshape(bshape)


@register("_sample_normal", aliases=["sample_normal"], needs_rng=True)
def sample_normal(rng, mu, sigma, *, shape=(), dtype="float32"):
    s = tuple(mu.shape) + tuple(shape)
    n = jax.random.normal(rng, s, dtype=jnp.dtype(dtype))
    bshape = mu.shape + (1,) * len(tuple(shape))
    return mu.reshape(bshape) + n * sigma.reshape(bshape)


@register("_sample_gamma", aliases=["sample_gamma"], needs_rng=True)
def sample_gamma(rng, alpha, beta, *, shape=(), dtype="float32"):
    s = tuple(alpha.shape) + tuple(shape)
    bshape = alpha.shape + (1,) * len(tuple(shape))
    g = jax.random.gamma(rng, jnp.broadcast_to(alpha.reshape(bshape), s), dtype=jnp.dtype(dtype))
    return g * beta.reshape(bshape)


@register("_sample_multinomial", aliases=["sample_multinomial"], needs_rng=True)
def sample_multinomial(rng, data, *, shape=(), get_prob=False, dtype="int32"):
    # data: (..., k) probabilities. Draw `shape` samples per distribution.
    n = 1
    for d in tuple(shape) or ():
        n *= d
    n = max(n, 1)
    logits = jnp.log(jnp.maximum(data, 1e-30))
    out = jax.random.categorical(rng, logits, axis=-1,
                                 shape=(n,) + data.shape[:-1])
    out = jnp.moveaxis(out, 0, -1)
    if tuple(shape) == ():
        out = out[..., 0]
    else:
        out = out.reshape(data.shape[:-1] + tuple(shape))
    out = out.astype(jnp.dtype(dtype))
    if get_prob:
        lp = jnp.take_along_axis(
            jax.nn.log_softmax(logits, axis=-1),
            out.astype(jnp.int32).reshape(data.shape[:-1] + (-1,)), axis=-1
        ).reshape(out.shape)
        return out, lp
    return out


@register("_random_bernoulli", aliases=["sample_bernoulli"], needs_rng=True)
def random_bernoulli(rng, *, p=0.5, shape=(), dtype="float32"):
    return jax.random.bernoulli(rng, p, tuple(shape)).astype(jnp.dtype(dtype))
