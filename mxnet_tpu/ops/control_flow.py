"""Control-flow operators (reference: ``src/operator/control_flow.cc`` +
``python/mxnet/ndarray/contrib.py`` :: foreach / while_loop / cond).

Dual lowering, mirroring the reference's imperative/symbolic split:

* concrete (eager) inputs — plain Python loops/branches, exactly like the
  reference's imperative implementations; ops inside the body record on
  the autograd tape as usual, so gradients flow with no special casing.
* traced inputs (hybridize / TrainStep / jit) — ``lax.scan`` /
  ``lax.while_loop`` / ``lax.cond``, the XLA-native forms (SURVEY.md §2.1:
  data-dependent Python control flow cannot appear inside a jit trace).

Shape contract under tracing: ``while_loop`` requires ``max_iterations``
and emits fixed-length outputs (steps beyond the dynamic trip count hold
zeros), the same contract as the reference's symbolic while_loop.
"""
from __future__ import annotations

from ..base import MXNetError

__all__ = ["foreach", "while_loop", "cond"]


def _nd():
    from ..ndarray.ndarray import NDArray

    return NDArray


def _flatten(x):
    """Flatten an NDArray / (nested) list-tuple of NDArrays."""
    NDArray = _nd()
    if isinstance(x, NDArray):
        return [x], "leaf"
    if isinstance(x, (list, tuple)):
        flat, trees = [], []
        for item in x:
            f, t = _flatten(item)
            flat.extend(f)
            trees.append((t, len(f)))
        return flat, ("list", type(x) is tuple, trees)
    raise MXNetError(f"control flow expects NDArrays or lists, got {type(x)}")


def _unflatten(tree, flat, pos=0):
    if tree == "leaf":
        return flat[pos], pos + 1
    _, is_tuple, trees = tree
    items = []
    for sub, _ in trees:
        item, pos = _unflatten(sub, flat, pos)
        items.append(item)
    return (tuple(items) if is_tuple else items), pos


def _stack_steps(steps):
    """Stack per-step outputs (list of same-structure results) on axis 0,
    flattening each step once."""
    from ..ndarray import stack as nd_stack

    flats = [_flatten(s)[0] for s in steps]
    _, out_tree = _flatten(steps[0])
    stacked = []
    for k in range(len(flats[0])):
        cols = [f[k] for f in flats]
        stacked.append(nd_stack(*cols, axis=0) if len(cols) > 1
                       else cols[0].expand_dims(axis=0))
    out, _ = _unflatten(out_tree, stacked)
    return out


def _is_traced(arrs):
    import jax

    return any(isinstance(a.data, jax.core.Tracer) for a in arrs)


def _wrap(vals, ctx):
    NDArray = _nd()
    return [NDArray(data=v, ctx=ctx) for v in vals]


# ---------------------------------------------------------------------------


def foreach(body, data, init_states):
    """Scan ``body`` over axis 0 of ``data`` (reference: contrib.foreach).

    ``body(data_slice, states) -> (outputs, new_states)``. Returns
    ``(outputs stacked on axis 0, final_states)``.
    """
    import jax

    data_flat, data_tree = _flatten(data)
    states_flat, states_tree = _flatten(init_states)
    ctx = data_flat[0].context
    length = data_flat[0].shape[0]
    for d in data_flat:
        if d.shape[0] != length:
            raise MXNetError("foreach: all data inputs must share axis-0 "
                             f"length; got {d.shape[0]} != {length}")

    if length > 0 and not _is_traced(data_flat + states_flat):
        # imperative path: python loop; tape records body ops directly.
        # (length 0 falls through to lax.scan, which traces the body and
        # emits correctly-structured zero-length outputs.)
        states = init_states
        outs_steps = []
        for i in range(length):
            sl_flat = [d[i] for d in data_flat]
            sl, _ = _unflatten(data_tree, sl_flat)
            outs, states = body(sl, states)
            outs_steps.append(outs)
        return _stack_steps(outs_steps), states

    # traced path: one lax.scan
    cell = {}

    def step(carry, xs):
        st, _ = _unflatten(states_tree, _wrap(list(carry), ctx))
        sl, _ = _unflatten(data_tree, _wrap(list(xs), ctx))
        outs, new_states = body(sl, st)
        out_flat, out_tree = _flatten(outs)
        new_flat, _ = _flatten(new_states)
        cell["out_tree"] = out_tree
        return (tuple(a.data for a in new_flat),
                tuple(o.data for o in out_flat))

    final, stacked = jax.lax.scan(
        step, tuple(s.data for s in states_flat),
        tuple(d.data for d in data_flat))
    out, _ = _unflatten(cell["out_tree"], _wrap(list(stacked), ctx))
    states, _ = _unflatten(states_tree, _wrap(list(final), ctx))
    return out, states


def while_loop(cond, func, loop_vars, max_iterations=None):
    """Run ``func`` while ``cond`` holds (reference: contrib.while_loop).

    ``cond(*loop_vars) -> scalar``; ``func(*loop_vars) -> (step_output,
    new_loop_vars)``. Returns ``(stacked step outputs, final loop_vars)``.
    """
    import jax
    import jax.numpy as jnp

    vars_flat, vars_tree = _flatten(list(loop_vars))
    ctx = vars_flat[0].context

    if not _is_traced(vars_flat):
        if max_iterations is None:
            raise MXNetError("while_loop requires max_iterations")
        steps = []
        n = 0
        lv = list(loop_vars)
        while n < max_iterations and bool(cond(*lv).asnumpy().reshape(())):
            out, lv = func(*lv)
            lv = list(lv) if isinstance(lv, (list, tuple)) else [lv]
            steps.append(out)
            n += 1
        if not steps:
            return [], lv
        return _stack_steps(steps), lv

    if max_iterations is None:
        raise MXNetError(
            "while_loop under trace requires max_iterations (XLA needs "
            "static output shapes — the reference's symbolic contract)")
    cell = {}

    # scan over max_iterations with an active mask: differentiable (unlike
    # lax.while_loop) and keeps the fixed-shape output contract
    def step(carry, _):
        active, var_vals = carry
        lv, _ = _unflatten(vars_tree, _wrap(list(var_vals), ctx))
        lv = lv if isinstance(lv, list) else [lv]
        pred = cond(*lv).data.reshape(()).astype(bool)
        run = jnp.logical_and(active, pred)
        out, new_lv = func(*lv)
        new_lv = list(new_lv) if isinstance(new_lv, (list, tuple)) \
            else [new_lv]
        out_flat, out_tree = _flatten(out)
        new_flat, _ = _flatten(new_lv)
        cell["out_tree"] = out_tree
        kept = tuple(jnp.where(run, n.data, o)
                     for n, o in zip(new_flat, var_vals))
        outs = tuple(jnp.where(run, o.data, jnp.zeros_like(o.data))
                     for o in out_flat)
        return (run, kept), outs

    (_, final), stacked = jax.lax.scan(
        step, (jnp.bool_(True), tuple(v.data for v in vars_flat)),
        None, length=int(max_iterations))
    out, _ = _unflatten(cell["out_tree"], _wrap(list(stacked), ctx))
    fin, _ = _unflatten(vars_tree, _wrap(list(final), ctx))
    return out, (fin if isinstance(fin, list) else [fin])


def cond(pred, then_func, else_func):
    """Branch on a scalar predicate (reference: contrib.cond).

    ``then_func()``/``else_func()`` are nullary closures returning the same
    output structure."""
    import jax

    NDArray = _nd()
    if not isinstance(pred, NDArray):
        raise MXNetError("cond: pred must be an NDArray scalar")
    if not _is_traced([pred]):
        branch = then_func if bool(pred.asnumpy().reshape(())) else else_func
        return branch()

    ctx = pred.context
    cell = {}

    def run(branch, key):
        def inner(_):
            out = branch()
            flat, tree = _flatten(out)
            cell[key] = tree
            return tuple(o.data for o in flat)

        return inner

    vals = jax.lax.cond(pred.data.reshape(()).astype(bool),
                        run(then_func, "then"), run(else_func, "else"), None)
    if cell["then"] != cell["else"]:
        raise MXNetError(
            "cond: then_func and else_func must return the same structure "
            f"(got {cell['then']} vs {cell['else']})")
    # both branches trace; the output container follows the then branch
    out, _ = _unflatten(cell["then"], _wrap(list(vals), ctx))
    return out
