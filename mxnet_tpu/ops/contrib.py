"""Contrib operators.

Reference: ``src/operator/contrib/`` — ``transformer.cc`` (interleaved
attention matmuls used by GluonNLP BERT), ``gelu`` (via LeakyReLU gelu),
``adamw.cc`` (in optimizer_op.py here), ``index_copy.cc``, ``roi_align.cc``.

The fused attention ops are implemented as single jit-able compositions;
on TPU the flash-attention Pallas kernel in ``mxnet_tpu/ops/attention.py``
supersedes them for long sequences (SURVEY.md §5.7).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register


@register("_contrib_div_sqrt_dim", aliases=["div_sqrt_dim"])
def div_sqrt_dim(data):
    return data / jnp.sqrt(jnp.asarray(data.shape[-1], dtype=data.dtype))


@register("_contrib_gelu")
def gelu_op(data):
    return jax.nn.gelu(data, approximate=False)


@register("_contrib_interleaved_matmul_selfatt_qk")
def interleaved_matmul_selfatt_qk(queries_keys_values, *, heads=1):
    """reference: src/operator/contrib/transformer.cc ::
    InterleavedMatMulSelfAttQK — input (seq, batch, 3*proj) with q/k/v
    interleaved per head; output (batch*heads, seq, seq) of scaled q·kᵀ."""
    seq, batch, _ = queries_keys_values.shape
    x = queries_keys_values.reshape(seq, batch, heads, 3, -1)
    q = x[:, :, :, 0]  # (seq, batch, heads, head_dim)
    k = x[:, :, :, 1]
    head_dim = q.shape[-1]
    scale = 1.0 / jnp.sqrt(jnp.asarray(head_dim, dtype=q.dtype))
    qk = jnp.einsum("sbhd,tbhd->bhst", q * scale, k)
    return qk.reshape(batch * heads, seq, seq)


@register("_contrib_interleaved_matmul_selfatt_valatt")
def interleaved_matmul_selfatt_valatt(queries_keys_values, attention, *, heads=1):
    seq, batch, _ = queries_keys_values.shape
    x = queries_keys_values.reshape(seq, batch, heads, 3, -1)
    v = x[:, :, :, 2]  # (seq, batch, heads, head_dim)
    att = attention.reshape(batch, heads, seq, seq)
    out = jnp.einsum("bhst,tbhd->sbhd", att, v)
    return out.reshape(seq, batch, -1)


@register("_contrib_interleaved_matmul_encdec_qk")
def interleaved_matmul_encdec_qk(queries, keys_values, *, heads=1):
    qseq, batch, _ = queries.shape
    kseq = keys_values.shape[0]
    q = queries.reshape(qseq, batch, heads, -1)
    kv = keys_values.reshape(kseq, batch, heads, 2, -1)
    k = kv[:, :, :, 0]
    head_dim = q.shape[-1]
    scale = 1.0 / jnp.sqrt(jnp.asarray(head_dim, dtype=q.dtype))
    qk = jnp.einsum("sbhd,tbhd->bhst", q * scale, k)
    return qk.reshape(batch * heads, qseq, kseq)


@register("_contrib_interleaved_matmul_encdec_valatt")
def interleaved_matmul_encdec_valatt(keys_values, attention, *, heads=1):
    kseq, batch, _ = keys_values.shape
    kv = keys_values.reshape(kseq, batch, heads, 2, -1)
    v = kv[:, :, :, 1]
    qseq = attention.shape[1]
    att = attention.reshape(batch, heads, qseq, kseq)
    out = jnp.einsum("bhst,tbhd->sbhd", att, v)
    return out.reshape(qseq, batch, -1)


@register("_contrib_index_copy", aliases=["index_copy"])
def index_copy(old_tensor, index_vector, new_tensor):
    # reference: src/operator/contrib/index_copy.cc — rows of old_tensor
    # at index_vector replaced by rows of new_tensor
    return old_tensor.at[index_vector.astype(jnp.int32)].set(
        new_tensor.astype(old_tensor.dtype))


@register("_contrib_index_array", aliases=["index_array"])
def index_array(data, *, axes=None):
    shape = data.shape
    axes_ = tuple(axes) if axes else tuple(range(len(shape)))
    grids = jnp.meshgrid(*[jnp.arange(shape[a]) for a in range(len(shape))], indexing="ij")
    sel = jnp.stack([grids[a] for a in axes_], axis=-1)
    return sel.astype(jnp.int64)


@register("_contrib_ROIAlign", aliases=["ROIAlign"])
def roi_align(data, rois, *, pooled_size=(7, 7), spatial_scale=1.0,
              sample_ratio=-1, position_sensitive=False, aligned=False):
    """reference: src/operator/contrib/roi_align.cc — bilinear ROI pooling.
    Vectorized gather-based implementation (jit-friendly, static shapes)."""
    n, c, h, w = data.shape
    num_rois = rois.shape[0]
    ph, pw = pooled_size
    sratio = sample_ratio if sample_ratio > 0 else 2
    offset = 0.5 if aligned else 0.0
    batch_idx = rois[:, 0].astype(jnp.int32)
    x1 = rois[:, 1] * spatial_scale - offset
    y1 = rois[:, 2] * spatial_scale - offset
    x2 = rois[:, 3] * spatial_scale - offset
    y2 = rois[:, 4] * spatial_scale - offset
    roi_w = jnp.maximum(x2 - x1, 1.0 if not aligned else 1e-6)
    roi_h = jnp.maximum(y2 - y1, 1.0 if not aligned else 1e-6)
    bin_w = roi_w / pw
    bin_h = roi_h / ph
    # sample grid: (num_rois, ph, pw, sratio, sratio)
    iy = (jnp.arange(sratio) + 0.5) / sratio
    ix = (jnp.arange(sratio) + 0.5) / sratio
    py = jnp.arange(ph)
    px = jnp.arange(pw)
    ys = y1[:, None, None] + (py[None, :, None] + iy[None, None, :]) * bin_h[:, None, None]
    xs = x1[:, None, None] + (px[None, :, None] + ix[None, None, :]) * bin_w[:, None, None]

    def bilinear(img, yy, xx):
        # img: (c, h, w); yy/xx: (...,)
        y0 = jnp.clip(jnp.floor(yy), 0, h - 1)
        x0 = jnp.clip(jnp.floor(xx), 0, w - 1)
        y1_ = jnp.clip(y0 + 1, 0, h - 1)
        x1_ = jnp.clip(x0 + 1, 0, w - 1)
        wy1 = jnp.clip(yy - y0, 0.0, 1.0)
        wx1 = jnp.clip(xx - x0, 0.0, 1.0)
        y0i, x0i, y1i, x1i = (a.astype(jnp.int32) for a in (y0, x0, y1_, x1_))
        v00 = img[:, y0i, x0i]
        v01 = img[:, y0i, x1i]
        v10 = img[:, y1i, x0i]
        v11 = img[:, y1i, x1i]
        return (v00 * (1 - wy1) * (1 - wx1) + v01 * (1 - wy1) * wx1
                + v10 * wy1 * (1 - wx1) + v11 * wy1 * wx1)

    def per_roi(b, ys_r, xs_r):
        img = data[b]  # (c,h,w)
        yy = ys_r[:, None, :, None]  # (ph,1,sr,1)
        xx = xs_r[None, :, None, :]  # (1,pw,1,sr)
        yy = jnp.broadcast_to(yy, (ph, pw, sratio, sratio))
        xx = jnp.broadcast_to(xx, (ph, pw, sratio, sratio))
        vals = bilinear(img, yy, xx)  # (c, ph, pw, sr, sr)
        return jnp.mean(vals, axis=(-1, -2))

    out = jax.vmap(per_roi)(batch_idx, ys, xs)  # (num_rois, c, ph, pw)
    return out


@register("_contrib_quantize_v2")
def quantize_v2(data, *, out_type="int8", min_calib_range=None, max_calib_range=None):
    if min_calib_range is None:
        min_calib_range = float(-1.0)
        max_calib_range = float(1.0)
    scale = 127.0 / jnp.maximum(jnp.abs(min_calib_range), jnp.abs(max_calib_range))
    q = jnp.clip(jnp.round(data * scale), -127, 127).astype(jnp.int8)
    return q, jnp.asarray(min_calib_range, jnp.float32), jnp.asarray(max_calib_range, jnp.float32)


@register("_contrib_dequantize")
def dequantize(data, min_range, max_range, *, out_type="float32"):
    scale = jnp.maximum(jnp.abs(min_range), jnp.abs(max_range)) / 127.0
    return data.astype(jnp.float32) * scale


@register("_contrib_moe_dispatch_combine", aliases=["moe_dispatch_combine"])
def moe_dispatch_combine(tokens, probs, gate_up_weight, down_weight, *,
                         top_k=2, capacity=0):
    """GShard dense dispatch -> per-expert SwiGLU -> combine.

    tokens (N, U); probs (N, E) router softmax; gate_up (E, U, 2H);
    down (E, H, U). Top-k gates renormalized over the selected experts;
    per-expert capacity enforced by position-in-expert cumsum (overflow
    tokens get zero combine weight — GShard semantics). All dense einsums:
    under GSPMD with 'ep'-sharded weights these lower to token all-to-alls
    plus expert-local matmuls on the MXU.
    """
    if capacity < 1:
        raise ValueError(
            f"moe_dispatch_combine requires capacity >= 1, got {capacity} "
            "(capacity 0 would silently drop every token)")
    n, e = probs.shape
    # top-k selection per token
    gate_vals, gate_idx = jax.lax.top_k(probs, top_k)       # (N, K)
    gate_vals = gate_vals / jnp.maximum(
        gate_vals.sum(-1, keepdims=True), 1e-9)             # renormalize
    # queue counting in int32: a low-precision cumsum (bf16 tokens under
    # AMP) stops incrementing past 256 and collides capacity slots
    sel_i = jax.nn.one_hot(gate_idx, e, dtype=jnp.int32)    # (N, K, E)

    # position of each (token, k) within its expert queue, k-major so a
    # token's higher-priority assignment claims capacity first
    flat_i = sel_i.transpose(1, 0, 2).reshape(top_k * n, e)   # (K*N, E)
    pos = jnp.cumsum(flat_i, axis=0) - flat_i                 # pre-count
    keep = pos < capacity
    flat_i = flat_i * keep
    flat_sel = flat_i.astype(tokens.dtype)
    pos_idx = jnp.sum(pos * flat_i, axis=-1)                  # (K*N,)
    cap_oh = jax.nn.one_hot(pos_idx, capacity, dtype=tokens.dtype)
    # dispatch tensor (N, K, E, C) -> fold K: (N, E, C)
    disp = (flat_sel[:, :, None] * cap_oh[:, None, :]).reshape(
        top_k, n, e, capacity)
    gates = gate_vals.transpose(1, 0)[:, :, None, None]       # (K, N, 1, 1)
    dispatch = disp.sum(0)                                    # (N, E, C)
    combine = (disp * gates).sum(0)                           # (N, E, C)

    expert_in = jnp.einsum("nec,nu->ecu", dispatch, tokens)   # (E, C, U)
    gu = jnp.einsum("ecu,euh->ech", expert_in, gate_up_weight)
    h = gu.shape[-1] // 2
    act = jax.nn.silu(gu[..., :h]) * gu[..., h:]
    expert_out = jnp.einsum("ech,ehu->ecu", act, down_weight)
    return jnp.einsum("nec,ecu->nu", combine, expert_out)


def _fake_quant_act(data, min_calib_range, max_calib_range):
    """Snap activations onto the symmetric int8 grid — calibrated range
    when given, dynamic (per-batch max) otherwise. Values stay exactly on
    the grid, so downstream f32 math reproduces integer arithmetic.
    Derived from _quantize_act_s8 so the oracle and the s8 MXU path snap
    identically by construction."""
    codes, s = _quantize_act_s8(data, min_calib_range, max_calib_range)
    return codes.astype(jnp.float32) / s


def _quantize_act_s8(data, min_calib_range, max_calib_range):
    """Integer-domain counterpart of _fake_quant_act: (int8 codes, scale)
    with ``codes = round(clip(x * 127/t)) ; x ~ codes / scale``."""
    if min_calib_range is None:
        t = jnp.max(jnp.abs(data)).astype(jnp.float32) + 1e-12  # dynamic
    else:
        t = jnp.maximum(jnp.float32(abs(float(min_calib_range))),
                        jnp.float32(abs(float(max_calib_range)))) + 1e-12
    s = 127.0 / t
    codes = jnp.clip(jnp.round(data.astype(jnp.float32) * s),
                     -127, 127).astype(jnp.int8)
    return codes, s


def _int8_mxu_enabled():
    """True when quantized ops should run REAL s8 x s8 -> s32 MXU math.

    The v5e MXU's int8 rate is ~2x bf16 (measured 2.7x in the identical
    chained-matmul harness, PERF.md round 3); off-TPU the fake-quant f32
    path stays the oracle. MXNET_INT8_MXU=0 forces the oracle everywhere.
    """
    import os

    from ..base import current_execution_platform

    if os.environ.get("MXNET_INT8_MXU", "1") == "0":
        return False
    return current_execution_platform() == "tpu"


@register("_contrib_quantized_dense")
def quantized_dense(data, weight_q, w_scale, bias=None, *, num_hidden,
                    no_bias=False, flatten=True,
                    min_calib_range=None, max_calib_range=None):
    """Int8-weight dense (reference capability: quantization.py::
    quantize_model int8 inference).

    On TPU the GEMM is REAL s8 x s8 -> s32 on the MXU (int8 runs ~2x the
    bf16 rate), rescaled by ``w_scale / act_scale`` per output channel.
    Elsewhere the fake-quant f32 path computes numerically identical
    results (both operand sets sit exactly on the int8 grid, and the f32
    MXU matmul reproduces the integer arithmetic up to f32 summation,
    which the shared tolerance tests pin).
    """
    from .registry import get_op

    if _int8_mxu_enabled():
        xq, s_x = _quantize_act_s8(data, min_calib_range, max_calib_range)
        if flatten and xq.ndim > 2:
            xq = xq.reshape(xq.shape[0], -1)
        acc = jax.lax.dot_general(
            xq, weight_q, (((xq.ndim - 1,), (1,)), ((), ())),
            preferred_element_type=jnp.int32)          # (..., num_hidden)
        out = acc.astype(jnp.float32) * (w_scale / s_x)
        if not (no_bias or bias is None):
            out = out + bias.astype(jnp.float32)
        return out  # f32, matching the oracle path's output dtype

    xq = _fake_quant_act(data, min_calib_range, max_calib_range)
    w = weight_q.astype(jnp.float32) * w_scale[:, None]
    return get_op("FullyConnected").fn(
        xq, w, bias, num_hidden=num_hidden,
        no_bias=no_bias or bias is None, flatten=flatten)


def _calib_t(min_calib, max_calib, who):
    """Symmetric int8 threshold from python-float calib bounds; both
    bounds required, loud error naming the op otherwise."""
    if min_calib is None or max_calib is None:
        raise ValueError(
            f"{who}: min and max calibration bounds are both required "
            "for the int8 grid")
    return max(abs(float(min_calib)), abs(float(max_calib))) + 1e-12


def _requant_out(out_f32, out_min_calib, out_max_calib):
    """Fused requantize of a layer's f32-scaled result onto the int8 grid
    of its calibrated OUTPUT range — elementwise, so XLA folds it into
    the conv/dense epilogue and the inter-layer tensor in HBM is int8.
    Returns (codes, -t, t)."""
    t = jnp.float32(_calib_t(out_min_calib, out_max_calib,
                             "quantized out_type='int8'"))
    codes = jnp.clip(jnp.round(out_f32 * (127.0 / t)),
                     -127, 127).astype(jnp.int8)
    return codes, jnp.float32(-t), jnp.float32(t)


@register("_contrib_quantized_conv")
def quantized_conv(data, weight_q, w_scale, bias=None, *, kernel,
                   num_filter, stride=None, pad=None, dilate=None,
                   num_group=1, no_bias=False, layout=None,
                   min_calib_range=None, max_calib_range=None,
                   out_type="float32", out_min_calib=None,
                   out_max_calib=None):
    """Int8-weight convolution; on TPU the conv itself runs s8 x s8 ->
    s32 (see quantized_dense), elsewhere fake-quant f32.

    ``out_type='int8'`` (requires ``out_min_calib``/``out_max_calib``)
    fuses the requantize: returns (int8 codes, min, max) so the next
    quantized op consumes codes directly — the int8-end-to-end trunk
    path (reference: quantized conv + requantize fusion). ``data`` may
    then itself be int8 codes with ``min/max_calib_range`` as their
    range."""
    from .registry import get_op

    if _int8_mxu_enabled():
        from .nn import _conv_dnums, _channel_axis, _tuplize

        nd = len(kernel)
        if data.dtype == jnp.int8:
            # already codes (previous layer's int8 output)
            xq = data
            s_x = 127.0 / jnp.float32(_calib_t(
                min_calib_range, max_calib_range, "quantized_conv"))
        else:
            xq, s_x = _quantize_act_s8(data, min_calib_range,
                                       max_calib_range)
        acc = jax.lax.conv_general_dilated(
            xq, weight_q,
            window_strides=_tuplize(stride or 1, nd),
            padding=[(p, p) for p in _tuplize(pad or 0, nd)],
            rhs_dilation=_tuplize(dilate or 1, nd),
            dimension_numbers=_conv_dnums(nd, layout),
            feature_group_count=num_group,
            preferred_element_type=jnp.int32)
        c_ax = _channel_axis(layout, acc.ndim)
        sshape = [1] * acc.ndim
        sshape[c_ax] = w_scale.shape[0]
        out = acc.astype(jnp.float32) * (w_scale.reshape(sshape) / s_x)
        if not (no_bias or bias is None):
            out = out + bias.astype(jnp.float32).reshape(sshape)
        if out_type == "int8":
            return _requant_out(out, out_min_calib, out_max_calib)
        return out  # f32, matching the oracle path's output dtype

    if data.dtype == jnp.int8:
        t_in = jnp.float32(_calib_t(min_calib_range, max_calib_range,
                                    "quantized_conv"))
        xq = data.astype(jnp.float32) * (t_in / 127.0)
    else:
        xq = _fake_quant_act(data, min_calib_range, max_calib_range)
    scale = w_scale.reshape((-1,) + (1,) * (weight_q.ndim - 1))
    w = weight_q.astype(jnp.float32) * scale
    out = get_op("Convolution").fn(
        xq, w, bias, kernel=kernel, num_filter=num_filter, stride=stride,
        pad=pad, dilate=dilate, num_group=num_group, layout=layout,
        no_bias=no_bias or bias is None)
    if out_type == "int8":
        return _requant_out(out.astype(jnp.float32), out_min_calib,
                            out_max_calib)
    return out


@register("_contrib_requantize", num_outputs=3)
def requantize(data, min_range, max_range, *, out_type="int8",
               min_calib_range=None, max_calib_range=None):
    """int32 accumulator -> int8 codes (reference:
    src/operator/quantization/requantize-inl.h). ``min_range``/
    ``max_range`` describe the real-valued span of the s32 input; the
    output grid uses the calibrated range when given, else the input's.
    Pure elementwise rescale — XLA fuses it into the producing matmul's
    epilogue, so no f32 tensor ever materializes in HBM."""
    if out_type != "int8":
        raise ValueError("requantize: only int8 output is supported")
    in_t = _q8_range(min_range, max_range)
    if min_calib_range is not None or max_calib_range is not None:
        t = jnp.float32(_calib_t(min_calib_range, max_calib_range,
                                 "requantize"))
    else:
        t = in_t
    # s32 codes represent x = codes * in_t / (2^31 - 1)
    scale = (in_t / jnp.float32(2147483647.0)) * (127.0 / t)
    codes = jnp.clip(jnp.round(data.astype(jnp.float32) * scale),
                     -127, 127).astype(jnp.int8)
    return codes, -t, t


def _q8_range(min_r, max_r):
    t = jnp.maximum(jnp.abs(jnp.asarray(min_r, jnp.float32)),
                    jnp.abs(jnp.asarray(max_r, jnp.float32)))
    return t + 1e-12


@register("_contrib_quantized_pooling", num_outputs=3)
def quantized_pooling(data, min_data, max_data, *, kernel=None, pool_type="max",
                      global_pool=False, stride=None, pad=None,
                      pooling_convention="valid", layout=None, count_include_pad=True):
    """Pooling on int8 codes (reference: src/operator/quantization/
    quantized_pooling.cc). Max pooling is exact on codes (monotonic);
    avg pooling accumulates in s32 and rounds back onto the SAME grid, so
    the (min, max) range passes through unchanged and the trunk stays
    int8 — no dequantize between a quantized conv and its pool."""
    from .registry import get_op

    pool = get_op("Pooling").fn
    if pool_type == "max":
        out = pool(data.astype(jnp.int32), kernel=kernel, pool_type="max",
                   global_pool=global_pool, stride=stride, pad=pad,
                   pooling_convention=pooling_convention, layout=layout,
                   count_include_pad=count_include_pad).astype(jnp.int8)
    elif pool_type == "avg":
        # f32 mean of codes, rounded back to the code grid (the codes are
        # small ints, so f32 holds them exactly; XLA fuses the chain)
        out = jnp.clip(jnp.round(pool(
            data.astype(jnp.float32), kernel=kernel, pool_type="avg",
            global_pool=global_pool, stride=stride, pad=pad,
            pooling_convention=pooling_convention, layout=layout,
            count_include_pad=count_include_pad)), -127, 127).astype(jnp.int8)
    else:
        raise ValueError(
            f"quantized_pooling: pool_type {pool_type!r} not supported "
            "(reference supports max/avg)")
    return out, min_data, max_data


@register("_contrib_quantized_concat", variadic=True, num_outputs=3)
def quantized_concat(*args, dim=1, num_args=None):
    """Concat int8 tensors (reference: src/operator/quantization/
    quantized_concat.cc). Inputs arrive as ``x0..xn-1, min0, max0, ...``;
    inputs whose ranges differ are REQUANTIZED onto the widest range
    (codes scale by t_i / t_out) so one grid covers the result."""
    n = num_args if num_args is not None else len(args) // 3
    data = args[:n]
    mins = args[n::2][:n]
    maxs = args[n + 1::2][:n]
    ts = [_q8_range(mn, mx) for mn, mx in zip(mins, maxs)]
    t_out = ts[0]
    for t in ts[1:]:
        t_out = jnp.maximum(t_out, t)
    parts = []
    for x, t in zip(data, ts):
        scale = t / t_out
        parts.append(jnp.clip(jnp.round(x.astype(jnp.float32) * scale),
                              -127, 127).astype(jnp.int8))
    return jnp.concatenate(parts, axis=dim), -t_out, t_out


@register("_contrib_quantized_elemwise_add", num_outputs=3)
def quantized_elemwise_add(lhs, rhs, min_lhs, max_lhs, min_rhs, max_rhs, *,
                           min_calib_range=None, max_calib_range=None):
    """Residual add on int8 codes (reference: src/operator/quantization/
    quantized_elemwise_add.cc — the op that keeps ResNet skip
    connections int8). Each side rescales onto the OUTPUT grid (the
    calibrated range when given, else the sum of the input ranges so the
    result cannot clip), accumulating in f32 inside the fused epilogue;
    only int8 codes cross HBM."""
    t_l = _q8_range(min_lhs, max_lhs)
    t_r = _q8_range(min_rhs, max_rhs)
    if min_calib_range is not None or max_calib_range is not None:
        t = jnp.float32(_calib_t(min_calib_range, max_calib_range,
                                 "quantized_elemwise_add"))
    else:
        t = t_l + t_r
    acc = (lhs.astype(jnp.float32) * (t_l / 127.0)
           + rhs.astype(jnp.float32) * (t_r / 127.0))
    codes = jnp.clip(jnp.round(acc * (127.0 / t)),
                     -127, 127).astype(jnp.int8)
    return codes, -t, t


@register("_contrib_quantized_flatten", num_outputs=3)
def quantized_flatten(data, min_data, max_data):
    """Flatten int8 codes; range passes through (reference:
    src/operator/quantization/quantized_flatten.cc)."""
    return data.reshape(data.shape[0], -1), min_data, max_data


@register("_contrib_quadratic", aliases=["quadratic"])
def quadratic(data, *, a=0.0, b=0.0, c=0.0):
    # reference: src/operator/contrib/quadratic_op.cc (the tutorial op)
    return a * data * data + b * data + c


@register("_contrib_allclose", aliases=["allclose_op"])
def allclose_op(a, b, *, rtol=1e-5, atol=1e-8, equal_nan=True):
    # reference: src/operator/contrib/allclose_op.cc — 1 if all close
    return jnp.all(jnp.isclose(a, b, rtol=rtol, atol=atol,
                               equal_nan=equal_nan)).astype(jnp.float32)


@register("_contrib_fft", aliases=["fft"])
def fft(data, *, compute_size=128):
    """reference: src/operator/contrib/fft.cc — FFT along the last axis,
    real input, output interleaves (real, imag) doubling the last dim."""
    f = jnp.fft.fft(data.astype(jnp.float32), axis=-1)
    return jnp.stack([f.real, f.imag], axis=-1).reshape(
        data.shape[:-1] + (2 * data.shape[-1],)).astype(jnp.float32)


@register("_contrib_ifft", aliases=["ifft"])
def ifft(data, *, compute_size=128):
    # inverse of _contrib_fft's interleaved layout; output is the real part
    n = data.shape[-1] // 2
    ri = data.astype(jnp.float32).reshape(data.shape[:-1] + (n, 2))
    comp = ri[..., 0] + 1j * ri[..., 1]
    # reference scales by n on the inverse path (no 1/n normalization)
    return jnp.fft.ifft(comp, axis=-1).real.astype(jnp.float32) * n


@register("_contrib_count_sketch", aliases=["count_sketch"])
def count_sketch(data, h, s, *, out_dim, processing_batch_size=32):
    """reference: src/operator/contrib/count_sketch.cc — random feature
    hashing: out[j] += s[i] * data[i] for h[i] == j (per row)."""
    hi = h.reshape(-1).astype(jnp.int32)
    si = s.reshape(-1).astype(data.dtype)
    vals = data * si[None, :]
    out = jnp.zeros(data.shape[:-1] + (int(out_dim),), dtype=data.dtype)
    return out.at[..., hi].add(vals)


@register("_contrib_AdaptiveAvgPooling2D", aliases=["AdaptiveAvgPooling2D"])
def adaptive_avg_pooling2d(data, *, output_size=()):
    """reference: src/operator/contrib/adaptive_avg_pooling.cc — NCHW
    average pooling onto a fixed output grid with floor/ceil bin edges."""
    if not output_size:
        oh = ow = 1
    elif isinstance(output_size, int):
        oh = ow = int(output_size)
    else:
        out = tuple(output_size)
        oh, ow = (out[0], out[0]) if len(out) == 1 else (out[0], out[1])
    n, c, h, w = data.shape
    x = data.astype(jnp.float32)

    def pool_axis(arr, axis, n_in, n_out):
        # bin edges are static python ints (shapes are static under jit)
        starts = [(i * n_in) // n_out for i in range(n_out)]
        ends = [-(-(i + 1) * n_in // n_out) for i in range(n_out)]
        pieces = []
        for st, en in zip(starts, ends):
            sl = [slice(None)] * arr.ndim
            sl[axis] = slice(st, en)
            pieces.append(arr[tuple(sl)].mean(axis=axis, keepdims=True))
        return jnp.concatenate(pieces, axis=axis)

    x = pool_axis(x, 2, h, oh)
    x = pool_axis(x, 3, w, ow)
    return x.astype(data.dtype)


@register("_contrib_bipartite_matching", aliases=["bipartite_matching"],
          num_outputs=2)
def bipartite_matching(data, *, is_ascend=False, threshold=0.0, topk=-1):
    """reference: src/operator/contrib/bounding_box.cc ::
    BipartiteMatching — greedy bipartite matching on a (..., N, M) score
    matrix: repeatedly take the globally best remaining pair. Returns
    (row_match, col_match): for each row the matched col (or -1), and for
    each col the matched row (or -1). Static-shape lax.fori_loop over
    min(N, M) rounds — compiler-friendly."""
    import jax.lax as lax

    scores = data.astype(jnp.float32)
    lead = scores.shape[:-2]  # arbitrary batch dims, flattened for vmap
    n, m = scores.shape[-2:]
    scores = scores.reshape((-1, n, m))
    b = scores.shape[0]
    sgn = 1.0 if not is_ascend else -1.0
    s0 = scores * sgn
    thr = threshold * sgn
    rounds = min(n, m) if topk < 0 else min(topk, n, m)

    def one(sc):
        def body(_, state):
            s, rmatch, cmatch = state
            flat = s.reshape(-1)
            idx = jnp.argmax(flat)
            val = flat[idx]
            r, c_ = idx // m, idx % m
            ok = val >= thr
            rmatch = jnp.where(ok, rmatch.at[r].set(c_.astype(jnp.float32)),
                               rmatch)
            cmatch = jnp.where(ok, cmatch.at[c_].set(r.astype(jnp.float32)),
                               cmatch)
            neg = jnp.float32(-jnp.inf)
            s = jnp.where(ok, s.at[r, :].set(neg).at[:, c_].set(neg), s)
            return s, rmatch, cmatch

        init = (sc, jnp.full((n,), -1.0, jnp.float32),
                jnp.full((m,), -1.0, jnp.float32))
        _, rmatch, cmatch = lax.fori_loop(0, rounds, body, init)
        return rmatch, cmatch

    rms, cms = jax.vmap(one)(s0)
    return rms.reshape(lead + (n,)), cms.reshape(lead + (m,))
