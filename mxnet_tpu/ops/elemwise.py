"""Elementwise and broadcast operators.

Reference: ``src/operator/tensor/elemwise_binary_op_basic.cc``,
``elemwise_binary_broadcast_op_basic.cc``, ``elemwise_unary_op_basic.cc``,
``src/operator/tensor/elemwise_binary_scalar_op*.cc``.

MXNet distinguishes ``elemwise_*`` (strict same-shape) from ``broadcast_*``
(numpy broadcasting). XLA broadcasts natively, so both families share one
implementation; the ``elemwise_`` registrations keep the strictness check
for API parity.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register, alias

# ---------------------------------------------------------------------------
# binary arithmetic
# ---------------------------------------------------------------------------


def _binary(name, aliases, fn, strict_shape=False):
    def impl(lhs, rhs):
        if strict_shape and lhs.shape != rhs.shape:
            raise ValueError(
                f"{name}: shapes {lhs.shape} and {rhs.shape} must match "
                f"(use broadcast_{name.replace('elemwise_', '')} for broadcasting)"
            )
        return fn(lhs, rhs)

    impl.__name__ = name
    return register(name, aliases=aliases)(impl)


_binary("broadcast_add", ["broadcast_plus"], jnp.add)
_binary("broadcast_sub", ["broadcast_minus"], jnp.subtract)
_binary("broadcast_mul", [], jnp.multiply)
_binary("broadcast_div", [], jnp.divide)
_binary("broadcast_mod", [], jnp.mod)
_binary("broadcast_power", ["broadcast_pow"], jnp.power)
_binary("broadcast_maximum", [], jnp.maximum)
_binary("broadcast_minimum", [], jnp.minimum)
_binary("broadcast_hypot", [], jnp.hypot)
_binary("elemwise_add", ["_plus", "_add"], jnp.add, strict_shape=True)
_binary("elemwise_sub", ["_minus", "_sub"], jnp.subtract, strict_shape=True)
_binary("elemwise_mul", ["_mul"], jnp.multiply, strict_shape=True)
_binary("elemwise_div", ["_div"], jnp.divide, strict_shape=True)

# comparisons (outputs follow MXNet: same dtype as inputs, 0/1 values)


def _cmp(name, fn):
    def impl(lhs, rhs):
        return fn(lhs, rhs).astype(jnp.result_type(lhs))

    impl.__name__ = name
    register(name, aliases=[name.replace("broadcast_", "_")])(impl)


_cmp("broadcast_equal", jnp.equal)
_cmp("broadcast_not_equal", jnp.not_equal)
_cmp("broadcast_greater", jnp.greater)
_cmp("broadcast_greater_equal", jnp.greater_equal)
_cmp("broadcast_lesser", jnp.less)
_cmp("broadcast_lesser_equal", jnp.less_equal)


@register("broadcast_logical_and")
def broadcast_logical_and(lhs, rhs):
    return (jnp.logical_and(lhs != 0, rhs != 0)).astype(jnp.result_type(lhs))


@register("broadcast_logical_or")
def broadcast_logical_or(lhs, rhs):
    return (jnp.logical_or(lhs != 0, rhs != 0)).astype(jnp.result_type(lhs))


@register("broadcast_logical_xor")
def broadcast_logical_xor(lhs, rhs):
    return (jnp.logical_xor(lhs != 0, rhs != 0)).astype(jnp.result_type(lhs))


@register("logical_not")
def logical_not(data):
    return (data == 0).astype(jnp.result_type(data))


# scalar ops (reference: elemwise_binary_scalar_op — attrs carry the scalar)


def _scalar_op(name, fn):
    def impl(data, *, scalar=1.0):
        return fn(data, jnp.asarray(scalar, dtype=data.dtype))

    impl.__name__ = name
    register(name)(impl)


_scalar_op("_plus_scalar", jnp.add)
_scalar_op("_minus_scalar", jnp.subtract)
_scalar_op("_rminus_scalar", lambda d, s: s - d)
_scalar_op("_mul_scalar", jnp.multiply)
_scalar_op("_div_scalar", jnp.divide)
_scalar_op("_rdiv_scalar", lambda d, s: s / d)
_scalar_op("_mod_scalar", jnp.mod)
_scalar_op("_rmod_scalar", lambda d, s: jnp.mod(s, d))
_scalar_op("_power_scalar", jnp.power)
_scalar_op("_rpower_scalar", lambda d, s: jnp.power(s, d))
_scalar_op("_maximum_scalar", jnp.maximum)
_scalar_op("_minimum_scalar", jnp.minimum)
_scalar_op("_equal_scalar", lambda d, s: (d == s).astype(d.dtype))
_scalar_op("_not_equal_scalar", lambda d, s: (d != s).astype(d.dtype))
_scalar_op("_greater_scalar", lambda d, s: (d > s).astype(d.dtype))
_scalar_op("_greater_equal_scalar", lambda d, s: (d >= s).astype(d.dtype))
_scalar_op("_lesser_scalar", lambda d, s: (d < s).astype(d.dtype))
_scalar_op("_lesser_equal_scalar", lambda d, s: (d <= s).astype(d.dtype))


@register("_hypot_scalar")
def _hypot_scalar(data, *, scalar=1.0):
    return jnp.hypot(data, jnp.asarray(scalar, dtype=data.dtype))


# ---------------------------------------------------------------------------
# unary math (reference: elemwise_unary_op_basic.cc, *_trig.cc, *_pow.cc,
# *_logexp.cc)
# ---------------------------------------------------------------------------


def _unary(name, fn, aliases=()):
    def impl(data):
        return fn(data)

    impl.__name__ = name
    register(name, aliases=list(aliases))(impl)


_unary("abs", jnp.abs)
_unary("sign", jnp.sign)
_unary("round", jnp.round)
_unary("rint", jnp.rint)
_unary("ceil", jnp.ceil)
_unary("floor", jnp.floor)
_unary("trunc", jnp.trunc)
_unary("fix", jnp.trunc)
_unary("square", jnp.square)
_unary("sqrt", jnp.sqrt)
_unary("rsqrt", lambda x: jax.lax.rsqrt(x))
_unary("cbrt", jnp.cbrt)
_unary("rcbrt", lambda x: 1.0 / jnp.cbrt(x))
_unary("exp", jnp.exp)
_unary("log", jnp.log)
_unary("log10", jnp.log10)
_unary("log2", jnp.log2)
_unary("log1p", jnp.log1p)
_unary("expm1", jnp.expm1)
_unary("sin", jnp.sin)
_unary("cos", jnp.cos)
_unary("tan", jnp.tan)
_unary("arcsin", jnp.arcsin)
_unary("arccos", jnp.arccos)
_unary("arctan", jnp.arctan)
_unary("sinh", jnp.sinh)
_unary("cosh", jnp.cosh)
_unary("tanh", jnp.tanh)
_unary("arcsinh", jnp.arcsinh)
_unary("arccosh", jnp.arccosh)
_unary("arctanh", jnp.arctanh)
_unary("degrees", jnp.degrees)
_unary("radians", jnp.radians)
_unary("sigmoid", jax.nn.sigmoid)
_unary("softsign", jax.nn.soft_sign)
_unary("relu", jax.nn.relu)
_unary("erf", jax.lax.erf)
_unary("erfinv", jax.lax.erf_inv)
_unary("gamma", lambda x: jnp.exp(jax.lax.lgamma(x)))
_unary("gammaln", jax.lax.lgamma)
_unary("reciprocal", lambda x: 1.0 / x)
_unary("negative", jnp.negative, aliases=["_np_negative"])
_unary("identity", lambda x: x, aliases=["_copy"])


@register("clip")
def clip(data, *, a_min=None, a_max=None):
    return jnp.clip(data, a_min, a_max)


@register("Cast", aliases=["cast"])
def cast(data, *, dtype="float32"):
    from ..base import MXNetError  # noqa: F401  (kept for parity w/ checks)
    import ml_dtypes

    if dtype == "bfloat16":
        return data.astype(ml_dtypes.bfloat16)
    return data.astype(dtype)


@register("amp_cast")
def amp_cast(data, *, dtype="float32"):
    # reference: src/operator/tensor/amp_cast.cc — dtype cast that the AMP
    # pass inserts; identical to Cast at execution level.
    return cast.__wrapped__(data, dtype=dtype) if hasattr(cast, "__wrapped__") else cast(data, dtype=dtype)


@register("amp_multicast", variadic=True)
def amp_multicast(*data, num_outputs=1):
    # cast all inputs to the widest dtype among them
    wide = jnp.result_type(*[d.dtype for d in data])
    return tuple(d.astype(wide) for d in data)


@register("where")
def where(condition, x, y):
    return jnp.where(condition != 0, x, y)


@register("add_n", aliases=["ElementWiseSum", "_sum"], variadic=True)
def add_n(*args):
    out = args[0]
    for a in args[1:]:
        out = out + a
    return out


@register("isnan")
def isnan(data):
    return jnp.isnan(data).astype(jnp.float32)


@register("isinf")
def isinf(data):
    return jnp.isinf(data).astype(jnp.float32)


@register("isfinite")
def isfinite(data):
    return jnp.isfinite(data).astype(jnp.float32)
