"""Fused recurrent ops.

Reference: ``src/operator/rnn.cc`` — the fused RNN operator (cuDNN
`cudnnRNNForward` on GPU, hand-rolled CPU path) driving vanilla
RNN(relu/tanh), LSTM and GRU with multi-layer, bidirectional and dropout
support, with all parameters packed into one flat vector.

TPU-native design: time recursion via ``lax.scan`` (compiler-friendly
control flow; XLA pipelines the per-step matmuls onto the MXU). The flat
parameter layout matches the reference convention (per layer, per
direction: W_i2h, W_h2h; then all biases b_i2h, b_h2h) so gluon rnn_layer
weight splitting is layout-compatible. Gate orders: LSTM i,f,g,o; GRU r,z,n.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from .registry import register

_GATES = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}


def _cell_step(mode, W_ih, W_hh, b_ih, b_hh):
    """Returns step(carry, x_t) for one direction of one layer."""

    if mode == "lstm":
        def step(carry, x):
            h, c = carry
            gates = x @ W_ih.T + h @ W_hh.T + b_ih + b_hh
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
            g = jnp.tanh(g)
            c_new = f * c + i * g
            h_new = o * jnp.tanh(c_new)
            return (h_new, c_new), h_new
        return step
    if mode == "gru":
        def step(carry, x):
            (h,) = carry
            gi = x @ W_ih.T + b_ih
            gh = h @ W_hh.T + b_hh
            ir, iz, inn = jnp.split(gi, 3, axis=-1)
            hr, hz, hn = jnp.split(gh, 3, axis=-1)
            r = jax.nn.sigmoid(ir + hr)
            z = jax.nn.sigmoid(iz + hz)
            n = jnp.tanh(inn + r * hn)
            h_new = (1 - z) * n + z * h
            return (h_new,), h_new
        return step
    act = jax.nn.relu if mode == "rnn_relu" else jnp.tanh

    def step(carry, x):
        (h,) = carry
        h_new = act(x @ W_ih.T + h @ W_hh.T + b_ih + b_hh)
        return (h_new,), h_new

    return step


def _slice_params(params, mode, num_layers, input_size, hidden, bidirectional):
    """Unpack the flat vector into per-(layer, direction) weights."""
    gates = _GATES[mode]
    dirs = 2 if bidirectional else 1
    weights = []
    off = 0
    for layer in range(num_layers):
        in_size = input_size if layer == 0 else hidden * dirs
        layer_ws = []
        for _ in range(dirs):
            n = gates * hidden * in_size
            W_ih = params[off : off + n].reshape(gates * hidden, in_size)
            off += n
            n = gates * hidden * hidden
            W_hh = params[off : off + n].reshape(gates * hidden, hidden)
            off += n
            layer_ws.append([W_ih, W_hh, None, None])
        weights.append(layer_ws)
    for layer in range(num_layers):
        for d in range(dirs):
            n = gates * hidden
            weights[layer][d][2] = params[off : off + n]
            off += n
            weights[layer][d][3] = params[off : off + n]
            off += n
    return weights


def rnn_param_size(mode, num_layers, input_size, hidden, bidirectional):
    gates = _GATES[mode]
    dirs = 2 if bidirectional else 1
    size = 0
    for layer in range(num_layers):
        in_size = input_size if layer == 0 else hidden * dirs
        size += dirs * gates * hidden * (in_size + hidden + 2)
    return size


@register("RNN", needs_rng=True, pass_training_flag=True)
def rnn_op(rng, data, parameters, state, state_cell=None, *, state_size=0,
           num_layers=1, mode="lstm", bidirectional=False, p=0.0,
           state_outputs=True, projection_size=None, use_sequence_length=False,
           lstm_state_clip_min=None, lstm_state_clip_max=None,
           lstm_state_clip_nan=False, _training=False):
    """data: (seq, batch, input); state: (layers*dirs, batch, hidden).
    Returns (out, h_n[, c_n]) like the reference's RNN op."""
    seq, batch, input_size = data.shape
    hidden = state_size
    dirs = 2 if bidirectional else 1
    weights = _slice_params(parameters, mode, num_layers, input_size, hidden,
                            bidirectional)
    x = data
    h_states = []
    c_states = []
    key = rng
    for layer in range(num_layers):
        outs = []
        for d in range(dirs):
            W_ih, W_hh, b_ih, b_hh = weights[layer][d]
            step = _cell_step(mode, W_ih, W_hh, b_ih, b_hh)
            idx = layer * dirs + d
            h0 = state[idx]
            carry = (h0, state_cell[idx]) if mode == "lstm" else (h0,)
            seq_in = jnp.flip(x, axis=0) if d == 1 else x
            carry, ys = jax.lax.scan(step, carry, seq_in)
            if d == 1:
                ys = jnp.flip(ys, axis=0)
            outs.append(ys)
            h_states.append(carry[0])
            if mode == "lstm":
                c_states.append(carry[1])
        x = outs[0] if dirs == 1 else jnp.concatenate(outs, axis=-1)
        if p > 0.0 and _training and layer < num_layers - 1:
            key, sub = jax.random.split(key)
            mask = jax.random.bernoulli(sub, 1 - p, x.shape)
            x = jnp.where(mask, x / (1 - p), 0.0)
    h_n = jnp.stack(h_states, axis=0)
    if mode == "lstm":
        c_n = jnp.stack(c_states, axis=0)
        if lstm_state_clip_min is not None and lstm_state_clip_max is not None:
            c_n = jnp.clip(c_n, lstm_state_clip_min, lstm_state_clip_max)
        return x, h_n, c_n
    return x, h_n
